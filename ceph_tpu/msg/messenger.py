"""Asyncio messenger: the control-plane transport between daemons.

Reference parity: msg/Messenger.h (factory :164, send_message :466,
dispatcher chain, lossy-client vs lossless-peer policies) and the
AsyncMessenger event-loop transport (msg/async/AsyncMessenger.cc,
AsyncConnection.cc state machine).  Redesigned for asyncio instead of
epoll threads, with one deliberate simplification of the hardest part of
the reference (Pipe.cc's simultaneous-connect races): each DIRECTION of a
peer pair is its own TCP connection owned by its sender.  Lossless
delivery then needs no connection-takeover protocol — the sender replays
un-acked messages on its own reconnect, and the receiver dedupes by
(peer nonce, seq) learned from the banner.  Semantics preserved:
per-peer FIFO, at-most-once delivery to dispatchers, reset callbacks,
message-count fault injection (ms_inject_socket_failures).

Wire format: banner = [u32 len][EntityName][EntityAddr] once per
connection, then frames [u8 tag][u32 len][payload]:
  MSG  payload = [u64 seq][u16 type][u32 crc(body)][body]
  ACK  payload = [u64 seq]      (cumulative)

The data plane deliberately does NOT ride this path on co-located shards:
bulk chunk movement is JAX collectives over ICI/DCN
(ceph_tpu/parallel/layout.py); the messenger carries maps, consensus,
heartbeats and per-op control as in SURVEY §2.4's TPU-native mapping.
"""

from __future__ import annotations

import asyncio
import random
import struct
import threading
import time
import zlib
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.common.throttle import AsyncThrottle
from ceph_tpu.msg import payload as payload_mod
from ceph_tpu.msg.message import Message, message_class
from ceph_tpu.msg.types import EntityAddr, EntityName

TAG_MSG = 1
TAG_ACK = 2
TAG_KEEPALIVE = 3
TAG_AUTH_REPLY = 4

_FRAME_HDR = struct.Struct("<BI")       # tag, len
_MSG_HDR = struct.Struct("<QHI")        # seq, type, crc


class Policy:
    """Per-peer-type delivery policy (Messenger::Policy, msg/Messenger.h).

    lossy: on failure drop the queue and report a reset — the higher layer
    (Objecter, MonClient) owns resend.  lossless: reconnect forever and
    replay un-acked messages in order (daemon↔daemon)."""

    def __init__(self, lossy: bool):
        self.lossy = lossy

    @classmethod
    def lossy_client(cls) -> "Policy":
        return cls(lossy=True)

    @classmethod
    def lossless_peer(cls) -> "Policy":
        return cls(lossy=False)


class Dispatcher:
    """Receiver interface (msg/Dispatcher.h).  ms_dispatch returns True if
    the message was handled; the messenger tries each dispatcher in
    registration order (Messenger::ms_deliver_dispatch)."""

    def ms_dispatch(self, msg: Message) -> bool:
        return False

    def ms_handle_reset(self, addr: EntityAddr) -> None:
        """A lossy session to addr dropped its queue."""

    def ms_handle_remote_reset(self, addr: EntityAddr) -> None:
        """Peer at addr restarted (new nonce observed)."""


class Connection:
    """Outgoing logical channel to one peer address (sender-owned)."""

    def __init__(self, msgr: "Messenger", addr: EntityAddr, policy: Policy,
                 peer_type: Optional[str] = None):
        self.msgr = msgr
        self.addr = addr
        self.policy = policy
        self.peer_type = peer_type
        # cephx: authorizer presented in the banner; session key signs
        # every frame once the peer's AUTH_REPLY proof checks out
        self.session_key: Optional[bytes] = None
        self._auth_nonce: Optional[bytes] = None
        self._auth_verified = asyncio.Event()
        self._auth_error: Optional[str] = None
        # identifies THIS logical connection across its tcp reconnects;
        # a fresh Connection (e.g. after mark_down) gets a fresh seq space
        self.conn_id = random.getrandbits(63)
        self.out_q: Deque[Message] = deque()
        self.unacked: Deque[Tuple[int, bytes]] = deque()  # (seq, frame)
        self.out_seq = 0
        self.acked_seq = 0
        self._kick = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._broken = False   # peer hung up (ack stream EOF)
        self.closed = False

    def send(self, msg: Message) -> None:
        self.out_q.append(msg)
        self._kick.set()

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    # --- writer loop ---
    async def _run(self) -> None:
        backoff = self.msgr.cfg["ms_initial_backoff"]
        while not self.closed:
            try:
                reader, writer = await asyncio.open_connection(
                    self.addr.host, self.addr.port)
            except OSError:
                if self.policy.lossy:
                    self._fail_lossy()
                    return
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.msgr.cfg["ms_max_backoff"])
                continue
            backoff = self.msgr.cfg["ms_initial_backoff"]
            self._writer = writer
            self._broken = False
            ack_task = asyncio.get_running_loop().create_task(
                self._read_acks(reader))
            try:
                await self._send_banner(writer)
                self.msgr.log.debug(
                    f"link to {self.addr} up (replay "
                    f"{len(self.unacked)})")
                # replay everything not yet acked, oldest first (framed
                # at write time so replays re-sign with the CURRENT
                # session key, not the pre-reconnect one)
                for _, payload in list(self.unacked):
                    writer.write(self._wrap(payload))
                await writer.drain()
                await self._pump(writer)
            except (OSError, asyncio.IncompleteReadError,
                    ConnectionError) as e:
                self.msgr.log.debug(
                    f"link to {self.addr} dropped: {e!r}")
            finally:
                ack_task.cancel()
                self._writer = None
                writer.close()
            if self.closed:
                return
            if self.policy.lossy:
                self._fail_lossy()
                return

    def _fail_lossy(self) -> None:
        self.out_q.clear()
        self.unacked.clear()
        self.closed = True
        self.msgr._drop_connection(self)
        for d in self.msgr.dispatchers:
            d.ms_handle_reset(self.addr)

    async def _send_banner(self, writer: asyncio.StreamWriter) -> None:
        authorizer = b""
        self.session_key = None
        self._auth_verified = asyncio.Event()
        self._auth_error = None
        if self.msgr.get_authorizer_cb is not None:
            got = self.msgr.get_authorizer_cb(self.peer_type)
            if got is not None:
                authorizer, self.session_key, self._auth_nonce = got
        enc = Encoder()
        enc.struct(self.msgr.name).struct(self.msgr.addr)
        enc.u64(self.conn_id)
        enc.bytes_(authorizer)
        b = enc.getvalue()
        writer.write(struct.pack("<I", len(b)) + b)
        await writer.drain()
        if self.session_key is not None:
            # wait for the acceptor's mutual proof before trusting the
            # link with any frames (cephx authorizer reply); _read_acks
            # also sets the event on FAILURE (with _auth_error) so a
            # rejected handshake surfaces immediately with its real
            # reason instead of burning the full timeout
            try:
                await asyncio.wait_for(self._auth_verified.wait(), 10.0)
            except asyncio.TimeoutError:
                raise ConnectionError("authorizer reply timed out")
            if self._auth_error is not None:
                raise ConnectionError(self._auth_error)

    async def _pump(self, writer: asyncio.StreamWriter) -> None:
        while not self.closed:
            if self._broken:
                # peer hung up: writes to the dead socket would buffer
                # silently (half-open TCP), so force the reconnect path —
                # un-acked frames replay there
                raise ConnectionError("peer closed ack stream")
            if self.out_q:
                # cork: frame EVERY queued message into one buffer and
                # hand the transport a single write before the single
                # drain — per-message write() calls each cost a send
                # syscall (asyncio flushes an empty transport buffer
                # eagerly), which dominates small-message bursts like
                # repop ack storms.  Ordering is untouched: frames are
                # corked in queue order and unacked tracks each seq.
                buf = bytearray()
                inject = False
                n = 0
                while self.out_q:
                    msg = self.out_q.popleft()
                    self.out_seq += 1
                    msg.seq = self.out_seq
                    # lazy payload: the body materializes HERE, at the
                    # real socket boundary, exactly once per message
                    # (fan-out reuses the cache; replay reuses frames)
                    body = msg.wire_bytes()
                    payload = _MSG_HDR.pack(msg.seq, msg.TYPE,
                                            zlib.crc32(body)) + body
                    self.unacked.append((self.out_seq, payload))
                    if self.msgr._inject_failure():
                        inject = True   # this frame replays on reconnect
                        break
                    buf += self._wrap(payload)
                    n += 1
                if buf:
                    writer.write(bytes(buf))
                    self.msgr._sock_writes += 1
                    self.msgr._sock_write_msgs += n
                if inject:
                    writer.transport.abort()   # hard drop, like a RST
                    raise ConnectionError("injected socket failure")
            await writer.drain()
            self._kick.clear()
            if not self.out_q and not self._broken:
                await self._kick.wait()

    def _wrap(self, payload: bytes) -> bytes:
        if self.session_key is not None:
            from ceph_tpu.auth.cephx import sign_payload
            payload = payload + sign_payload(self.session_key, payload)
        return _FRAME_HDR.pack(TAG_MSG, len(payload)) + payload

    async def _read_acks(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                hdr = await reader.readexactly(_FRAME_HDR.size)
                tag, ln = _FRAME_HDR.unpack(hdr)
                payload = await reader.readexactly(ln)
                if tag == TAG_ACK:
                    (seq,) = struct.unpack("<Q", payload)
                    self.acked_seq = max(self.acked_seq, seq)
                    while self.unacked and self.unacked[0][0] <= seq:
                        self.unacked.popleft()
                elif tag == TAG_AUTH_REPLY:
                    from ceph_tpu.auth.cephx import (
                        authorizer_reply_proof, hmac_eq)
                    if payload == b"":
                        # acceptor claims no verifier armed.  With cephx
                        # mandated, downgrading would let an active MITM
                        # strip mutual auth + signing by forging this
                        # empty frame — fail closed.  The one legitimate
                        # window is a MON pushing to an OSD still inside
                        # its own boot handshake (its verifier arms only
                        # after MAuth completes, and the MAuthReply rides
                        # THIS link): allow that downgrade; the OSD kills
                        # unauthenticated inbound links once it arms
                        # require_authorizer (osd/daemon.py), so the mon
                        # re-handshakes signed right after boot.  The OSD
                        # is the ONLY daemon type the mon dials (mds/mgr
                        # talk through their own client stacks), so the
                        # window stays osd-scoped — for everyone else an
                        # empty reply can only be an attack or a bug.
                        boot_window = (self.msgr.name.type == "mon"
                                       and self.peer_type == "osd")
                        if (self.msgr.cfg["auth_supported"] == "cephx"
                                and not boot_window):
                            self._auth_error = ("empty authorizer reply "
                                                "(cephx required)")
                            self._auth_verified.set()
                            raise ConnectionError(self._auth_error)
                        self.msgr.log.info(
                            f"downgrading link to {self.addr} to "
                            f"unsigned (acceptor has no verifier yet)")
                        self.session_key = None
                        self._auth_verified.set()
                    elif (self.session_key is not None
                            and self._auth_nonce is not None
                            and hmac_eq(payload, authorizer_reply_proof(
                                self.session_key, self._auth_nonce))):
                        self._auth_verified.set()
                    else:
                        self.msgr.log.warning(
                            f"bad authorizer reply from {self.addr}")
                        self._auth_error = "bad authorizer reply"
                        self._auth_verified.set()
                        raise ConnectionError(self._auth_error)
        except asyncio.CancelledError:
            return
        except (OSError, asyncio.IncompleteReadError, ConnectionError):
            self._broken = True
            self._kick.set()   # wake _pump so it reconnects

    async def close(self) -> None:
        self.closed = True
        self._kick.set()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass


#: process-local endpoint registry: (host, port) -> bound Messenger.
#: Registration is unconditional (bind/shutdown); whether a sender USES
#: it is gated per-send by the ms_local_delivery config on both ends.
_LOCAL_ENDPOINTS: Dict[Tuple[str, int], "Messenger"] = {}


class LocalConnection:
    """Same-process fast path (AsyncMessenger local_connection /
    ms_fast_dispatch role, widened from self-delivery to any co-located
    messenger — the deployment the QA cluster and bench actually run).

    ZERO-ENCODE delivery (msg/payload.py): the receiver is handed the
    message's ``local_view()`` — the live object graph, frozen/copied
    per that type's discipline — in FIFO order; no body is serialized
    or parsed on this path, which is the counter-guarded invariant.
    Everything that exists to survive an unreliable byte stream —
    framing, crc, acks, replay, reconnect — is skipped: in-process
    delivery cannot drop or reorder.  Fault-injection and cephx configs
    fall back to TCP at routing time (_local_peer), so thrash/
    model-checker semantics and auth gating are untouched.

    Backpressure: the receiver's per-sender intake queue is bounded by
    a bytes budget (ms_dispatch_throttle_bytes — the role TCP's socket
    buffers play).  While the budget has room, send() hands the message
    over synchronously; once it fills, messages queue HERE and an async
    pump awaits the receiver's gate — so a co-located flood parks the
    sender's stream instead of growing intake RAM, without ever
    head-of-line blocking other senders' queues."""

    is_local = True

    def __init__(self, msgr: "Messenger", addr: EntityAddr,
                 peer: "Messenger"):
        self.msgr = msgr
        self.addr = addr
        self.peer = peer
        self.conn_id = random.getrandbits(63)
        self.out_q: Deque[Message] = deque()
        self.out_seq = 0
        self.closed = False
        self._kick = asyncio.Event()   # mark_down compatibility
        self._task: Optional[asyncio.Task] = None

    def _peer_alive(self) -> Optional["Messenger"]:
        peer = _LOCAL_ENDPOINTS.get(self.addr.without_nonce())
        return peer if peer is self.peer else None

    def _reset(self) -> None:
        # peer endpoint went away (daemon shutdown/restart): behave
        # like a torn-down TCP session — drop and let the caller's
        # resend machinery (objecter, peering) recover via whatever
        # endpoint rebinds
        self.closed = True
        self.out_q.clear()
        self.msgr._drop_connection(self)
        for d in self.msgr.dispatchers:
            d.ms_handle_reset(self.addr)

    def send(self, msg: Message) -> None:
        if self.closed:
            return
        if self._task is None and not self.out_q:
            peer = self._peer_alive()
            if peer is None:
                self._reset()
                return
            if self._try_shard_fast(peer, msg):
                return      # handed straight to the owning shard
            cost = msg.local_cost()
            if peer._local_intake_gate(self.conn_id).get_or_fail(cost):
                self._deliver(peer, msg, cost)   # uncongested fast path
                return
        # intake over budget (or a pump already draining a backlog):
        # preserve FIFO by parking behind the async producer gate
        self.out_q.append(msg)
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._pump_local())

    def _try_shard_fast(self, peer: "Messenger", msg: Message) -> bool:
        """Sharded-intake classify (osd/shards.py): when the peer runs
        a sharded data plane and this message class belongs to a PG,
        hand the local view STRAIGHT to the owning shard's ring — no
        per-sender intake queue, no worker task, no per-message
        wakeup.  Engages only while no legacy delivery from this
        connection is still in flight (``_local_pending``), so per-PG
        FIFO order can never be overtaken; op-class messages still
        pass the dispatch throttle (non-blocking probe — on a full
        budget the message takes the legacy path, which parks and
        preserves the backpressure contract)."""
        router = peer.shard_router
        if router is None or not router.wants(msg):
            return False
        if peer._local_pending.get(self.conn_id):
            return False
        throttled = 0
        if msg.THROTTLE_DISPATCH and not msg.THROTTLE_SPLIT \
                and peer.dispatch_throttle is not None:
            throttled = msg.local_cost()
            if not peer.dispatch_throttle.get_or_fail(throttled):
                return False
        self.out_seq += 1
        view = msg.local_view()
        view.seq = self.out_seq
        view.src_name = self.msgr.name
        view.src_addr = self.msgr.addr
        view.transport_id = -self.conn_id
        view.recv_stamp = time.monotonic()
        view.throttle_cost = throttled
        # stage cuts mirror the legacy intake worker exactly: only
        # throttled (client-op) classes consume chain stages here — a
        # sub-op shares the client's LIVE span and must not cut it
        if msg.THROTTLE_DISPATCH and peer.ctx.tracer.enabled \
                and view._span is not None:
            view._span.cut("deliver", peer.ctx.tracer.hist)
            view._span.cut("throttle_wait", peer.ctx.tracer.hist)
        self.msgr._local_msgs += 1
        payload_mod.note_local()
        peer._msgs_received += 1
        router.deliver(view)
        return True

    def _deliver(self, peer: "Messenger", msg: Message,
                 cost: int) -> None:
        self.out_seq += 1
        view = msg.local_view()
        view.seq = self.out_seq
        self.msgr._local_msgs += 1
        payload_mod.note_local()
        peer._local_enqueue(self.msgr.name, self.msgr.addr,
                            self.conn_id, view, cost)

    async def _pump_local(self) -> None:
        """Drains the backlog through the receiver's bytes-budget gate;
        exits once empty (send() resumes the synchronous fast path)."""
        try:
            while self.out_q and not self.closed:
                peer = self._peer_alive()
                if peer is None:
                    self._reset()
                    return
                msg = self.out_q[0]
                cost = msg.local_cost()
                gate = peer._local_intake_gate(self.conn_id)
                await gate.get(cost)
                if self.closed:
                    gate.put(cost)
                    return
                if self._peer_alive() is None:   # died across the await
                    self._reset()
                    return
                self.out_q.popleft()
                self._deliver(peer, msg, cost)
        except asyncio.CancelledError:
            pass
        finally:
            self._task = None

    async def close(self) -> None:
        self.closed = True
        self._kick.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass


class _AckBatcher:
    """Coalesces the receive side's cumulative acks: one ACK frame per
    drained burst of inbound frames (scheduled via call_soon, which runs
    only once the reader empties its buffer and yields), instead of one
    eager write syscall + sender wakeup per message.  Acks are
    cumulative, so acking only the newest seq is lossless."""

    __slots__ = ("writer", "_seq", "_scheduled")

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self._seq = 0
        self._scheduled = False

    def note(self, seq: int) -> None:
        if seq > self._seq:
            self._seq = seq
        if not self._scheduled:
            self._scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._scheduled = False
        if self.writer.is_closing():
            return
        ack = struct.pack("<Q", self._seq)
        self.writer.write(_FRAME_HDR.pack(TAG_ACK, len(ack)) + ack)


class Messenger:
    """One per process endpoint (daemons bind; clients stay unbound)."""

    def __init__(self, ctx, name: EntityName,
                 default_policy: Optional[Policy] = None):
        self.ctx = ctx
        self.cfg = ctx.config
        self.log = ctx.logger("ms")
        self.name = name
        self.nonce = random.getrandbits(48)
        self.addr = EntityAddr("", 0, self.nonce)
        self.dispatchers: List[Dispatcher] = []
        if default_policy is None:
            # clients default lossy (their stacks own resend); daemons
            # default lossless peer links (Messenger policy defaults)
            default_policy = (Policy.lossy_client() if name.is_client()
                              else Policy.lossless_peer())
        self.default_policy = default_policy
        self.policies: Dict[str, Policy] = {}   # peer entity type -> policy
        self.conns: Dict[Tuple[str, int], Connection] = {}
        # receive-side dedupe: (peer nonce, conn id) -> last delivered seq
        self._in_seq: Dict[Tuple[int, int], int] = {}
        self._peer_nonce: Dict[Tuple[str, int], int] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._in_tasks: set = set()
        self._next_transport_id = 1    # per-incoming-socket id counter
        self._msgs_sent = 0
        self._msgs_received = 0
        # corked-write accounting: messages coalesced per socket write
        # (msgs/write > 1 == the cork is earning its keep)
        self._sock_writes = 0
        self._sock_write_msgs = 0
        # same-process fast-path accounting + intake: one
        # queue+worker+bytes-gate PER SENDER CONNECTION, mirroring the
        # TCP path's per-peer reader tasks + socket buffers — a
        # throttled client op must only back-pressure its own sender,
        # never head-of-line block peer acks
        self._local_msgs = 0
        self._local_in: Dict[
            int, Tuple[asyncio.Queue, asyncio.Task, AsyncThrottle]] = {}
        # per-sender count of legacy local deliveries not yet fully
        # dispatched: the shard fast path stays OFF while any are in
        # flight so it can never overtake the queued stream (FIFO)
        self._local_pending: Dict[int, int] = {}
        # cephx hooks (msg/Messenger.h ms_get_authorizer /
        # ms_verify_authorizer dispatcher hooks, collapsed onto the
        # messenger since auth state lives with the owning stack):
        #   get_authorizer_cb(peer_type) -> (authorizer, session_key,
        #       nonce) | None — presented in the banner of OUTGOING
        #       connections
        #   verify_authorizer_cb(authorizer) -> (ticket, reply_proof) —
        #       validates INCOMING banners; raises AuthError to reject
        #   require_authorizer — drop incoming connections with no/bad
        #       authorizer (daemons with auth_supported=cephx)
        self.get_authorizer_cb = None
        self.verify_authorizer_cb = None
        self.require_authorizer = False
        # optional intake backpressure (Throttle.h role): frames whose
        # message class sets THROTTLE_DISPATCH block the reader while
        # over budget; the handling daemon releases at op completion
        self.dispatch_throttle = None
        # sharded data plane seam (osd/shards.py): when the owning OSD
        # runs >1 shard it installs a classifier here; intake then
        # hands op-class messages straight to the owning shard's ring
        # instead of dispatching on this loop (ms_fast_dispatch ->
        # ShardedOpWQ role).  None = classic dispatch, unchanged.
        self.shard_router = None
        # home event loop: the loop this messenger's asyncio state
        # (connections, throttles, intake queues) belongs to.  Sends
        # from a FOREIGN thread (a PG's shard loop) are marshalled
        # back here through a batched courier — one wakeup per burst
        # — so shard threads never touch loop-affine state directly.
        self._home_loop: Optional[asyncio.AbstractEventLoop] = None
        self._home_thread: Optional[int] = None
        self._out_courier = None
        self._xthread_msgs = 0
        self._xthread_flushes = 0
        try:
            self._capture_home_loop()
        except RuntimeError:
            pass        # bound later (bind/add_dispatcher re-capture)

    # --- setup ---
    def _capture_home_loop(self) -> None:
        self._home_loop = asyncio.get_running_loop()
        self._home_thread = threading.get_ident()

    def _on_home_thread(self) -> bool:
        """True when the caller may touch this messenger's asyncio
        state directly.  A messenger never bound to a loop yet behaves
        classically (single-threaded by construction)."""
        return self._home_thread is None \
            or self._home_thread == threading.get_ident()

    def add_dispatcher(self, d: Dispatcher) -> None:
        if self._home_loop is None:
            try:
                self._capture_home_loop()
            except RuntimeError:
                pass
        self.dispatchers.append(d)

    def set_policy(self, entity_type: str, policy: Policy) -> None:
        """Delivery policy for connections TO peers of entity_type
        (Messenger::set_policy); overwrites any earlier setting."""
        self.policies[entity_type] = policy

    def _policy_for(self, peer_type: Optional[str]) -> Policy:
        if peer_type is not None and peer_type in self.policies:
            return self.policies[peer_type]
        return self.default_policy

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> EntityAddr:
        self._capture_home_loop()
        self._server = await asyncio.start_server(
            self._handle_incoming, host, port)
        sock = self._server.sockets[0]
        bound_host, bound_port = sock.getsockname()[:2]
        self.addr = EntityAddr(bound_host, bound_port, self.nonce)
        _LOCAL_ENDPOINTS[self.addr.without_nonce()] = self
        self.log.debug(f"{self.name} bound at {self.addr}")
        return self.addr

    # --- send path ---
    def send_message(self, msg: Message, addr: EntityAddr,
                     peer_type: Optional[str] = None) -> None:
        """Queue msg for addr; never blocks (Messenger.h:466 contract).
        peer_type selects the delivery policy for a NEW connection (e.g.
        "client" when replying to a lossy client); existing connections
        keep the policy they were created with.

        Thread-safe: a call from a foreign thread (a PG's shard loop,
        osd/shards.py) is marshalled to the home loop through a
        batched courier — the send itself, and therefore every
        connection/queue touch, always runs on the home loop."""
        if not self._on_home_thread():
            self._post_home(self.send_message, msg, addr, peer_type)
            return
        key = addr.without_nonce()
        conn = self.conns.get(key)
        if conn is None or conn.closed:
            peer = self._local_peer(addr)
            if peer is not None:
                conn = LocalConnection(self, addr, peer)
            else:
                conn = Connection(self, addr,
                                  self._policy_for(peer_type), peer_type)
                conn.start()
            self.conns[key] = conn
        self._msgs_sent += 1
        conn.send(msg)

    def _post_home(self, fn, *args) -> None:
        """Batched cross-thread marshalling onto the home loop (one
        call_soon_threadsafe wakeup per burst, not per message)."""
        from ceph_tpu.osd.shards import Courier
        # gil-atomic:begin _out_courier,_xthread_msgs runs on the
        # POSTING shard thread by construction: the lazy courier init
        # races benignly (two shards can each build one; the second
        # store wins and the loser's courier drains its own posts —
        # both target the same home loop), and the counter is a
        # stats-only RMW whose drift under contention is accepted
        courier = self._out_courier
        if courier is None:
            # constructed lazily FROM a shard thread: the home thread
            # must be passed explicitly or the courier would treat the
            # constructing shard as "same thread" and skip the
            # cross-thread wakeup
            courier = self._out_courier = Courier(
                self._home_loop, f"{self.name}-out",
                thread_ident=self._home_thread)
            courier.on_flush = self._note_xthread_flush
        self._xthread_msgs += 1
        # gil-atomic:end
        courier.post(fn, *args)

    def _note_xthread_flush(self, n: int) -> None:
        self._xthread_flushes += 1

    def _local_peer(self, addr: EntityAddr) -> Optional["Messenger"]:
        """The co-located messenger at addr, when BOTH ends opted into
        ms_local_delivery and nothing requires real wire semantics
        (fault injection, cephx authorizers)."""
        if not self.cfg["ms_local_delivery"]:
            return None
        if self.cfg["ms_inject_socket_failures"] > 0:
            return None
        if self.get_authorizer_cb is not None:
            return None
        peer = _LOCAL_ENDPOINTS.get(addr.without_nonce())
        if peer is None or not peer.cfg["ms_local_delivery"] \
                or peer.cfg["ms_inject_socket_failures"] > 0 \
                or peer.require_authorizer or peer._server is None:
            return None
        return peer

    def get_connection(self, addr: EntityAddr) -> Optional[Connection]:
        return self.conns.get(addr.without_nonce())

    def mark_down(self, addr: EntityAddr) -> None:
        """Tear down the session to addr (Messenger::mark_down)."""
        conn = self.conns.pop(addr.without_nonce(), None)
        if conn is not None:
            conn.closed = True
            conn._kick.set()

    def _drop_connection(self, conn: Connection) -> None:
        cur = self.conns.get(conn.addr.without_nonce())
        if cur is conn:
            del self.conns[conn.addr.without_nonce()]

    def _inject_failure(self) -> bool:
        n = self.cfg["ms_inject_socket_failures"]
        return n > 0 and random.randrange(n) == 0

    # --- receive path (same-process fast path) ---
    def _local_entry(self, conn_id: int):
        ent = self._local_in.get(conn_id)
        if ent is None:
            q: asyncio.Queue = asyncio.Queue()
            # bytes-budget gate bounding THIS sender's intake queue
            # (the role TCP's socket buffer plays); 0/neg = unbounded
            gate = AsyncThrottle("ms_local_intake",
                                 self.cfg["ms_dispatch_throttle_bytes"])
            task = asyncio.get_running_loop().create_task(
                self._local_worker(q, gate, conn_id))
            ent = self._local_in[conn_id] = (q, task, gate)
        return ent

    def _local_intake_gate(self, conn_id: int) -> AsyncThrottle:
        """The producer gate senders must pass (sync get_or_fail on the
        uncongested path, async get from their pump once over budget)."""
        return self._local_entry(conn_id)[2]

    def _local_enqueue(self, peer_name: EntityName, peer_addr: EntityAddr,
                       conn_id: int, msg: Message, cost: int) -> None:
        """Zero-encode intake: `msg` is already the receiver-safe
        local_view; the caller holds `cost` of this queue's gate."""
        self._local_pending[conn_id] = \
            self._local_pending.get(conn_id, 0) + 1
        self._local_entry(conn_id)[0].put_nowait(
            (peer_name, peer_addr, msg, cost))

    async def _local_worker(self, q: asyncio.Queue, gate: AsyncThrottle,
                            conn_id: int) -> None:
        """Drains ONE co-located sender's messages in FIFO order — the
        local twin of a _serve_peer reader, minus everything that only
        exists to survive a real socket (no decode at all now: the view
        object IS the delivery).  Dispatch throttle still applies and,
        as on TCP, stalls only THIS sender's stream while the op budget
        is full — the intake-gate budget is held across that wait, so
        the backpressure reaches the sender.  An idle worker retires
        itself so sender reset/reconnect cycles (fresh conn_ids) can't
        accumulate parked tasks; retirement only happens with the gate
        fully released — a producer acquires the gate and enqueues in
        the same synchronous step, so gate.cur == 0 with an empty queue
        proves no message can slip into the popped entry."""
        while True:
            if not q.empty():
                # burst fast path: drain buffered messages without the
                # per-message wait_for Task/timer overhead (the same
                # no-yield drain a TCP reader gets from buffered frames;
                # throttle awaits below still yield under pressure)
                peer_name, peer_addr, msg, cost = q.get_nowait()
            else:
                try:
                    peer_name, peer_addr, msg, cost = \
                        await asyncio.wait_for(q.get(), 60.0)
                except asyncio.TimeoutError:
                    # retire only when provably drained: q.empty() must
                    # be re-checked here (an UNBOUNDED gate never bumps
                    # cur, so a sender may have enqueued between the
                    # timeout firing and this coroutine resuming); both
                    # checks and the pop are one synchronous step, so
                    # nothing can slip in after them
                    if gate.cur == 0 and q.empty():
                        self._local_in.pop(conn_id, None)
                        return
                    continue   # admitted-not-yet-enqueued producer races
            msg.src_name = peer_name
            msg.src_addr = peer_addr
            msg.transport_id = -conn_id   # local ids: distinct namespace
            msg.recv_stamp = time.monotonic()
            if (self.dispatch_throttle is not None
                    and msg.THROTTLE_DISPATCH
                    and not msg.THROTTLE_SPLIT):
                # op tracing: the live span rode local_view — attribute
                # transit-so-far as `deliver` and the budget wait as
                # `throttle_wait` into THIS daemon's stage histograms
                span = msg._span if self.ctx.tracer.enabled else None
                if span is not None:
                    span.cut("deliver", self.ctx.tracer.hist)
                await self.dispatch_throttle.get(cost)
                msg.throttle_cost = cost
                if span is not None:
                    span.cut("throttle_wait", self.ctx.tracer.hist)
            gate.put(cost)   # message left the intake queue
            try:
                self._dispatch(msg)
            finally:
                left = self._local_pending.get(conn_id, 1) - 1
                self._local_pending[conn_id] = max(0, left)

    # --- receive path ---
    async def _handle_incoming(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter) -> None:
        self._in_tasks.add(asyncio.current_task())
        try:
            await self._serve_peer(reader, writer)
        finally:
            self._in_tasks.discard(asyncio.current_task())

    async def _serve_peer(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        # receiver-assigned, unforgeable per-socket id: auth sessions bind
        # to this, never to the banner-claimed src address (which daemons
        # publish in the osdmap and anyone can claim)
        transport_id = self._next_transport_id
        self._next_transport_id += 1
        try:
            (blen,) = struct.unpack("<I",
                                    await reader.readexactly(4))
            dec = Decoder(await reader.readexactly(blen))
            peer_name = dec.struct(EntityName)
            peer_addr = dec.struct(EntityAddr)
            conn_id = dec.u64()
            authorizer = dec.bytes_() if dec.remaining() else b""
        except (OSError, asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        # cephx: validate the authorizer before ANY frame is accepted
        auth_ticket = None
        session_key = None
        if authorizer and self.verify_authorizer_cb is not None:
            try:
                auth_ticket, reply_proof = self.verify_authorizer_cb(
                    authorizer)
                session_key = auth_ticket.session_key
                writer.write(_FRAME_HDR.pack(TAG_AUTH_REPLY,
                                             len(reply_proof)) + reply_proof)
            except Exception as e:
                self.log.warning(
                    f"authorizer from {peer_name} {peer_addr} rejected: "
                    f"{e}")
                writer.close()
                return
        elif authorizer:
            # no verifier armed: tell the connector explicitly so it can
            # downgrade instead of waiting out its proof timeout
            writer.write(_FRAME_HDR.pack(TAG_AUTH_REPLY, 0))
        if self.require_authorizer and auth_ticket is None:
            self.log.warning(
                f"unauthenticated connection from {peer_name} "
                f"{peer_addr} refused (auth required)")
            writer.close()
            return
        # restart detection only applies to BOUND peers: distinct unbound
        # clients all advertise ("", 0) and must not alias each other
        if not peer_addr.is_blank():
            pkey = peer_addr.without_nonce()
            old_nonce = self._peer_nonce.get(pkey)
            if old_nonce is not None and old_nonce != peer_addr.nonce:
                # peer restarted: its seq spaces reset (remote reset event)
                for k in [k for k in self._in_seq if k[0] == old_nonce]:
                    del self._in_seq[k]
                for d in self.dispatchers:
                    d.ms_handle_remote_reset(peer_addr)
            if peer_addr.nonce:
                self._peer_nonce[pkey] = peer_addr.nonce
        # coalesced cumulative acks: frames already buffered in the
        # reader parse back-to-back without yielding, so the flush
        # scheduled via call_soon runs once per drained burst and acks
        # only the LATEST seq — one tiny write (and one peer wakeup)
        # per burst instead of one per message
        acker = _AckBatcher(writer)
        try:
            while True:
                hdr = await reader.readexactly(_FRAME_HDR.size)
                tag, ln = _FRAME_HDR.unpack(hdr)
                payload = await reader.readexactly(ln)
                if self.require_authorizer and auth_ticket is None:
                    # the bar was raised after this connection was
                    # accepted (daemon finished its auth boot): drop the
                    # unauthenticated link so the peer re-handshakes
                    # with a verifiable authorizer (unacked messages
                    # replay signed on its reconnect)
                    self.log.info(
                        f"dropping unauthenticated link from {peer_name} "
                        f"{peer_addr} (authorizer now required)")
                    raise ConnectionError(
                        "authorizer now required; re-handshake")
                if tag == TAG_MSG:
                    if session_key is not None:
                        from ceph_tpu.auth.cephx import (hmac_eq,
                                                         sign_payload)
                        payload, sig = payload[:-16], payload[-16:]
                        if not hmac_eq(sig, sign_payload(session_key,
                                                         payload)):
                            self.log.warning(
                                f"message signature mismatch from "
                                f"{peer_name}")
                            raise ConnectionError("bad message signature")
                    msg = self._parse_frame(payload, peer_name,
                                            peer_addr, conn_id, acker,
                                            auth_ticket, transport_id)
                    if msg is not None:
                        # dispatch throttle (Message.cc throttle hooks /
                        # Policy throttler): stop READING this peer's
                        # socket while the budget is full — TCP pushes
                        # the backpressure to the sender.  Only message
                        # types that opt in (client data ops) count.
                        if (self.dispatch_throttle is not None
                                and msg.THROTTLE_DISPATCH
                                and not msg.THROTTLE_SPLIT):
                            cost = len(payload)
                            span = msg._span
                            if span is not None:
                                span.cut("deliver", self.ctx.tracer.hist)
                            await self.dispatch_throttle.get(cost)
                            msg.throttle_cost = cost
                            if span is not None:
                                span.cut("throttle_wait",
                                         self.ctx.tracer.hist)
                        # sharded data plane: PG-bound wire messages
                        # enqueue onto the owning shard instead of
                        # dispatching on the reader (already
                        # throttled above)
                        if self.shard_router is not None \
                                and self.shard_router.wants(msg):
                            self.shard_router.deliver(msg)
                        else:
                            self._dispatch(msg)
                elif tag == TAG_KEEPALIVE:
                    pass
        except (OSError, asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    def _parse_frame(self, payload: bytes, peer_name: EntityName,
                     peer_addr: EntityAddr, conn_id: int,
                     acker: "_AckBatcher",
                     auth_ticket=None,
                     transport_id: Optional[int] = None
                     ) -> Optional[Message]:
        seq, mtype, crc = _MSG_HDR.unpack_from(payload, 0)
        body = payload[_MSG_HDR.size:]
        if zlib.crc32(body) != crc:
            self.log.warning(f"crc mismatch on {mtype} from {peer_name}")
            raise ConnectionError("bad crc")
        # ack first (cumulative, coalesced per burst), then dedupe replays
        acker.note(seq)
        skey = (peer_addr.nonce, conn_id)
        if seq <= self._in_seq.get(skey, 0):
            return None  # replayed duplicate after sender reconnect
        cls = message_class(mtype)
        if cls is None:
            # undecodable deterministically: consume the seq (replaying the
            # same bytes can never succeed) but keep the transport alive
            self.log.warning(f"unknown message type {mtype}")
            self._in_seq[skey] = seq
            return None
        try:
            msg = cls.from_bytes(body)
        except Exception as e:
            self.log.warning(f"decode of {cls.__name__} failed: {e!r}")
            self._in_seq[skey] = seq
            return None
        self._in_seq[skey] = seq   # delivered at-most-once from here on
        msg.seq = seq
        msg.src_name = peer_name
        msg.src_addr = peer_addr
        msg.transport_id = transport_id
        if auth_ticket is not None:
            # transport-authenticated identity (verified authorizer) —
            # dispatchers gate on this, never on the claimed src_name
            msg.auth_entity = auth_ticket.entity
            msg.auth_caps = auth_ticket.caps
        msg.recv_stamp = time.monotonic()
        # op tracing across a REAL wire: adopt the propagated span
        # context so downstream stage cuts attribute into THIS daemon's
        # histograms under the sender's trace (the transit itself stays
        # unattributed — different clocks cannot be differenced safely).
        # Only throttled client-op classes consume an adopted span —
        # replies resolve against the client's own op.span and replica
        # sub-ops record aux stages off the raw ids — so everything
        # else skips the per-message allocation
        if (msg.THROTTLE_DISPATCH and self.ctx.tracer.enabled
                and getattr(msg, "trace_id", 0)):
            msg._span = self.ctx.tracer.adopt(
                msg.trace_id, msg.span_id, t0=msg.recv_stamp)
        return msg

    def _dispatch(self, msg: Message) -> None:
        self._msgs_received += 1
        for d in self.dispatchers:
            try:
                if d.ms_dispatch(msg):
                    return
            except Exception:
                # a buggy dispatcher must not kill the peer transport —
                # but it must not leak the op's intake budget either, or
                # enough failures wedge the whole daemon's intake
                self.log.exception(f"dispatcher {d} failed on {msg}")
                self.put_dispatch_throttle(msg)
                return
        self.log.warning(f"unhandled message {msg}")
        self.put_dispatch_throttle(msg)

    def put_dispatch_throttle(self, msg: Message) -> None:
        """Release a throttled message's budget; owners (the OSD op
        path) call this when the op COMPLETES, unhandled messages
        release immediately.  Thread-safe: a release from a shard
        thread is marshalled to the home loop (the throttle's waiter
        futures belong there), batched one wakeup per burst."""
        cost = getattr(msg, "throttle_cost", 0)
        if cost and self.dispatch_throttle is not None:
            msg.throttle_cost = 0       # idempotent
            if self._on_home_thread():
                self.dispatch_throttle.put(cost)
            else:
                self._post_home(self.dispatch_throttle.put, cost)

    # --- teardown ---
    async def shutdown(self) -> None:
        key = self.addr.without_nonce()
        if _LOCAL_ENDPOINTS.get(key) is self:
            del _LOCAL_ENDPOINTS[key]
        for _, task, gate in list(self._local_in.values()):
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
            # admit any sender pump parked on our intake gate so it can
            # observe the deregistered endpoint and reset, instead of
            # hanging on a budget nobody will ever release
            gate.open_wide()
        self._local_in.clear()
        if self._server is not None:
            self._server.close()
        # cancel live peer handlers instead of wait_closed(): waiting would
        # deadlock two messengers shutting down in sequence (each handler
        # only exits when the OTHER side closes its sending socket)
        for t in list(self._in_tasks):
            t.cancel()
        for t in list(self._in_tasks):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        for conn in list(self.conns.values()):
            await conn.close()
        self.conns.clear()
