"""Lazy message payloads: serialization is a TRANSPORT detail.

Reference contrast: msg/Message.h treats a message as bytes-on-a-wire —
encode_payload runs before any send.  In a TPU-native rebuild the common
deployment co-locates OSD/mon/client daemons in one process (qa cluster,
bench, mesh mode), where PR 1 profiling showed the e2e write path is
CPU-bound on message/Transaction ENCODING, not on sockets or fsync.  So
here a message *body* is decoupled from its *wire form*:

  * ``LazyPayload`` carries a LIVE object (Transaction, LogEntry, ...)
    plus the implicit encoder thunk (``obj.to_bytes``); it materializes
    to bytes lazily, exactly once, and only when a frame actually hits a
    TCP socket (``Message.wire_bytes`` -> ``encode_payload`` ->
    ``LazyPayload.bytes``).
  * ``ms_local_delivery`` hands the receiver the object graph itself —
    zero encode, zero decode — under an enforced copy discipline:
    sealing a payload FREEZES the underlying object (freeze-and-assert),
    and receivers that need to mutate (a replica appending save_meta
    ops to a received txn) must take ``mutable()`` copies.

Module counters are the regression guard that keeps the encode round
trip removed: a pure-local hop must never bump ``msg_encode_calls``
(bench ec_e2e reports them; the perf-smoke suite fails on regression).
"""

from __future__ import annotations

from typing import Optional, Type


class _Counters:
    """Process-wide body-encode accounting (one process == one bench /
    qa cluster, so the aggregate is exactly the number the local-path
    guard cares about)."""

    __slots__ = ("encode_calls", "encode_bytes", "decode_calls",
                 "local_msgs")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        # gil-atomic:begin encode_calls,encode_bytes,decode_calls,local_msgs
        # test-scoped reset; plain stores are single GIL steps
        self.encode_calls = 0
        self.encode_bytes = 0
        self.decode_calls = 0
        self.local_msgs = 0
        # gil-atomic:end


_C = _Counters()


def note_encode(nbytes: int) -> None:
    """One full message body hit a real socket boundary."""
    # gil-atomic:begin encode_calls,encode_bytes,decode_calls,local_msgs
    # process-wide stats counters bumped from every loop and shard
    # thread: the RMW can drop increments under true parallelism —
    # accepted for stats, but the ZERO-encode guard is exact either
    # way (a counter that should be 0 gets no increments to lose)
    _C.encode_calls += 1
    _C.encode_bytes += nbytes
    # gil-atomic:end


def note_decode() -> None:
    # gil-atomic:begin decode_calls same stats-counter discipline
    _C.decode_calls += 1
    # gil-atomic:end


def note_local() -> None:
    # gil-atomic:begin local_msgs same stats-counter discipline
    _C.local_msgs += 1
    # gil-atomic:end


def counters() -> dict:
    return {"msg_encode_calls": _C.encode_calls,
            "msg_encode_bytes": _C.encode_bytes,
            "msg_decode_calls": _C.decode_calls,
            "msg_local_msgs": _C.local_msgs}


def reset_counters() -> None:
    _C.reset()


class LazyPayload:
    """A message body part: live object OR wire bytes, converted lazily.

    Exactly one of ``_obj`` / ``_raw`` is the source of truth at
    construction; ``bytes()`` materializes the wire form once and caches
    it, so a message fanned out to several TCP peers (repop to N
    replicas) still encodes its txn a single time.

    Copy discipline (receiver side):
      * ``peek(cls)``  — read-only view; when live, this is the SENDER'S
        object (frozen at seal time); mutating it is a bug the freeze
        turns into a loud failure.
      * ``mutable(cls)`` — receiver-owned copy, safe to mutate; cheap
        (``mutable_copy``, a shallow op-list copy for Transaction) when
        the type provides one, decode-from-bytes otherwise.
    """

    __slots__ = ("_obj", "_raw", "_ext")

    def __init__(self, obj=None, raw: Optional[bytes] = None, ext=None):
        self._obj = obj
        self._raw = raw
        #: shared-memory extent backing (osd/extents.ExtentRef) — the
        #: lane-transport zero-copy source: bytes materialize from it
        #: lazily, once, attributed to the extent_read stage
        self._ext = ext

    # ------------------------------------------------------ construction
    @classmethod
    def seal(cls, obj) -> "LazyPayload":
        """Wrap a live object and FREEZE it: once a payload is sealed
        into a message the sender must not mutate it (its bytes may
        already be cached / its graph already handed to a receiver)."""
        freeze = getattr(obj, "freeze", None)
        if callable(freeze):
            freeze()
        return cls(obj=obj)

    @classmethod
    def coerce(cls, v) -> "LazyPayload":
        """Constructor helper: accept bytes (wire/decode path), an
        already-built payload (fan-out sharing), or a live Encodable."""
        if isinstance(v, LazyPayload):
            return v
        if v is None:
            return cls(raw=b"")
        if getattr(v, "_is_extent_ref", False):
            # lane-transport zero-copy path: keep the shared-memory
            # handle, defer the one copy to first real use
            return cls(ext=v)
        if isinstance(v, (bytes, bytearray, memoryview)):
            return cls(raw=bytes(v))
        return cls.seal(v)

    # ------------------------------------------------------------ access
    def empty(self) -> bool:
        return self._obj is None and not self._raw and self._ext is None

    def bytes(self) -> bytes:
        """Wire form, materialized lazily and exactly once.  Objects
        that keep their own framed-encoding cache (LogEntry
        ``framed_bytes`` — pglog persistence already paid for it) are
        asked for that instead of re-encoding; extent-backed payloads
        pay their single copy out of shared memory here."""
        raw = self._raw
        if raw is None:
            if self._ext is not None:
                raw = self._raw = self._ext.materialize()
            else:
                fb = getattr(self._obj, "framed_bytes", None)
                raw = self._raw = (fb() if callable(fb)
                                   else self._obj.to_bytes())
        return raw

    def peek(self, kind: Type):
        """Read-only object view (zero-copy when live; decoded once and
        cached on the wire path, so repeated accessor calls cost one
        decode and share one object on BOTH transports)."""
        if self._obj is not None:
            return self._obj
        if not self._raw and self._ext is None:
            return None
        note_decode()
        self._obj = kind.from_bytes(self.bytes())
        return self._obj

    def mutable(self, kind: Type):
        """Receiver-owned object, safe to mutate (copy discipline)."""
        if self._obj is not None:
            mc = getattr(self._obj, "mutable_copy", None)
            if callable(mc):
                return mc()
            # no cheap copy on this type: isolate via the codec — and
            # COUNT the encode it forces, so a local-path round trip
            # sneaking back in can never hide from the zero-encode guard
            if self._raw is None:
                note_encode(len(self.bytes()))
            note_decode()
            return kind.from_bytes(self.bytes())
        if not self._raw and self._ext is None:
            return kind()
        note_decode()
        return kind.from_bytes(self.bytes())

    def cost(self) -> int:
        """Byte-budget estimate WITHOUT materializing (intake gates must
        never force the encode they exist to avoid)."""
        if self._raw is not None:
            return len(self._raw)
        if self._ext is not None:
            return self._ext.ln    # handle knows its length; no copy
        approx = getattr(self._obj, "approx_size", None)
        if callable(approx):
            return approx()
        return 256

    def __repr__(self):
        if self._raw is not None and self._obj is None:
            return f"LazyPayload(raw={len(self._raw)}B)"
        state = "materialized" if self._raw is not None else "lazy"
        return f"LazyPayload({type(self._obj).__name__}, {state})"
