"""Wire/communication layer (reference: src/msg/ + src/messages/).

Control plane: asyncio TCP messenger with typed messages and
lossy/lossless peer policies.  Data plane for co-located shards rides
JAX collectives instead (ceph_tpu/parallel/).
"""

from ceph_tpu.msg.message import (
    Message, MPing, PRIO_DEFAULT, PRIO_HIGH, PRIO_HIGHEST, PRIO_LOW,
    message_class, register_message,
)
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger, Policy
from ceph_tpu.msg.payload import LazyPayload
from ceph_tpu.msg.types import EntityAddr, EntityName

__all__ = [
    "Connection", "Dispatcher", "EntityAddr", "EntityName", "LazyPayload",
    "MPing", "Message", "Messenger", "PRIO_DEFAULT", "PRIO_HIGH",
    "PRIO_HIGHEST", "PRIO_LOW", "Policy", "message_class",
    "register_message",
]
