"""CrushCompiler: the textual crushmap dialect, both directions.

Reference parity: src/crush/CrushCompiler.cc + src/crush/grammar.h — the
`crushtool -d` / `crushtool -c` text form:

    # begin crush map
    tunable choose_total_tries 50
    device 0 osd.0
    type 0 osd
    type 1 host
    host host0 {
        id -1
        alg straw2
        hash 0  # rjenkins1
        item osd.0 weight 1.000000
    }
    rule replicated_rule {
        ruleset 0
        type replicated
        min_size 1
        max_size 10
        step take default
        step chooseleaf firstn 0 type host
        step emit
    }
    # end crush map

Redesigned without boost::spirit: a line-oriented tokenizer (comments
stripped, braces as block markers) feeding small per-section parsers.
Weights print with 6 decimals so the 16.16 fixed-point values survive
the text round-trip exactly (1/65536 ~ 1.5e-5 > 0.5e-6 print error);
buckets must be defined before they are referenced, like the reference.
"""

from __future__ import annotations

import re
from typing import Dict, List

from ceph_tpu.crush.builder import make_bucket
from ceph_tpu.crush.constants import (
    BUCKET_ALG_NAMES, HASH_RJENKINS1,
    RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP, RULE_EMIT, RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_TRIES, RULE_SET_CHOOSELEAF_VARY_R,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES, RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES, RULE_TAKE,
)
from ceph_tpu.crush.types import CrushMap, Rule, RuleStep

_ALG_IDS = {name: alg for alg, name in BUCKET_ALG_NAMES.items()}
_RULE_TYPE_NAMES = {1: "replicated", 3: "erasure"}
_RULE_TYPE_IDS = {v: k for k, v in _RULE_TYPE_NAMES.items()}
_SET_STEPS = {
    "set_choose_tries": RULE_SET_CHOOSE_TRIES,
    "set_chooseleaf_tries": RULE_SET_CHOOSELEAF_TRIES,
    "set_choose_local_tries": RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries": RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_vary_r": RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": RULE_SET_CHOOSELEAF_STABLE,
}
_SET_STEP_NAMES = {v: k for k, v in _SET_STEPS.items()}
_CHOOSE_STEPS = {
    ("choose", "firstn"): RULE_CHOOSE_FIRSTN,
    ("choose", "indep"): RULE_CHOOSE_INDEP,
    ("chooseleaf", "firstn"): RULE_CHOOSELEAF_FIRSTN,
    ("chooseleaf", "indep"): RULE_CHOOSELEAF_INDEP,
}
_CHOOSE_STEP_NAMES = {v: k for k, v in _CHOOSE_STEPS.items()}

_TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
             "choose_total_tries", "chooseleaf_descend_once",
             "chooseleaf_vary_r", "chooseleaf_stable",
             "straw_calc_version")


class CompileError(ValueError):
    pass


def _w2s(w: int) -> str:
    return f"{w / 0x10000:.6f}"


def _s2w(s: str) -> int:
    return int(round(float(s) * 0x10000))


# ---------------------------------------------------------------- decompile

def decompile(m: CrushMap) -> str:
    """CrushMap -> reference-dialect text (CrushCompiler::decompile)."""
    out: List[str] = ["# begin crush map"]
    for t in _TUNABLES:
        out.append(f"tunable {t} {getattr(m.tunables, t)}")
    out.append("")
    out.append("# devices")
    for dev in range(m.max_devices):
        name = m.name_map.get(dev)
        if name is not None:
            out.append(f"device {dev} {name}")
    out.append("")
    out.append("# types")
    for tid in sorted(m.type_map):
        out.append(f"type {tid} {m.type_map[tid]}")
    out.append("")
    out.append("# buckets")
    # definition must precede reference: emit leaf-most first (reverse
    # id order matches builder output; fall back to dependency sort)
    done: set = set()
    order: List[int] = []

    def visit(bid: int) -> None:
        if bid in done:
            return
        done.add(bid)
        b = m.bucket(bid)
        if b is None:
            return
        for it in b.items:
            if it < 0:
                visit(it)
        order.append(bid)

    for b in m.buckets:
        if b is not None:
            visit(b.id)
    for bid in order:
        b = m.bucket(bid)
        tname = m.type_map.get(b.type, str(b.type))
        out.append(f"{tname} {m.name_of(b.id)} {{")
        out.append(f"\tid {b.id}\t\t# do not change unnecessarily")
        out.append(f"\t# weight {_w2s(b.weight)}")
        out.append(f"\talg {BUCKET_ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for it, w in zip(b.items, b.item_weights):
            out.append(f"\titem {m.name_of(it)} weight {_w2s(w)}")
        out.append("}")
    out.append("")
    out.append("# rules")
    for rid, r in enumerate(m.rules):
        if r is None:
            continue
        out.append(f"rule {m.rule_name_map.get(rid, f'rule{rid}')} {{")
        out.append(f"\truleset {r.ruleset}")
        out.append(f"\ttype {_RULE_TYPE_NAMES.get(r.type, str(r.type))}")
        out.append(f"\tmin_size {r.min_size}")
        out.append(f"\tmax_size {r.max_size}")
        for s in r.steps:
            if s.op == RULE_TAKE:
                out.append(f"\tstep take {m.name_of(s.arg1)}")
            elif s.op == RULE_EMIT:
                out.append("\tstep emit")
            elif s.op in _CHOOSE_STEP_NAMES:
                kind, mode = _CHOOSE_STEP_NAMES[s.op]
                tname = m.type_map.get(s.arg2, str(s.arg2))
                out.append(f"\tstep {kind} {mode} {s.arg1} type {tname}")
            elif s.op in _SET_STEP_NAMES:
                out.append(f"\tstep {_SET_STEP_NAMES[s.op]} {s.arg1}")
            else:
                raise CompileError(f"cannot decompile step op {s.op}")
        out.append("}")
    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


# ------------------------------------------------------------------ compile

def compile_text(text: str) -> CrushMap:
    """Reference-dialect text -> CrushMap (CrushCompiler::compile).

    Token-stream parse (newlines are just whitespace, exactly like the
    reference's spirit grammar — `host h { id -1 ... }` on one line is
    valid).  Buckets must be defined before they are referenced (same
    constraint as the reference's single-pass grammar)."""
    m = CrushMap()
    m.type_map = {}
    names: Dict[str, int] = {}          # item name -> id

    toks: List[str] = []
    for raw in text.splitlines():
        line = re.sub(r"#.*", "", raw)
        toks += line.replace("{", " { ").replace("}", " } ").split()

    def expect(i: int, what: str) -> None:
        if i >= len(toks) or toks[i] != what:
            got = toks[i] if i < len(toks) else "<eof>"
            raise CompileError(f"expected {what!r}, got {got!r}")

    def block_body(i: int):
        """toks[i] must be '{'; -> (body tokens, index past '}')."""
        expect(i, "{")
        j = i + 1
        depth = 1
        while j < len(toks):
            if toks[j] == "{":
                depth += 1
            elif toks[j] == "}":
                depth -= 1
                if depth == 0:
                    return toks[i + 1:j], j + 1
            j += 1
        raise CompileError("unterminated block")

    i = 0
    try:
        while i < len(toks):
            t = toks[i]
            if t == "tunable":
                if i + 2 >= len(toks) or toks[i + 1] not in _TUNABLES:
                    raise CompileError(f"bad tunable at {toks[i:i + 3]}")
                setattr(m.tunables, toks[i + 1], int(toks[i + 2]))
                i += 3
            elif t == "device":
                dev = int(toks[i + 1])
                names[toks[i + 2]] = dev
                m.name_map[dev] = toks[i + 2]
                m.max_devices = max(m.max_devices, dev + 1)
                i += 3
            elif t == "type":
                m.type_map[int(toks[i + 1])] = toks[i + 2]
                i += 3
            elif t == "rule":
                name = toks[i + 1]
                body, i = block_body(i + 2)
                _parse_rule(m, name, body, names)
            elif t in m.type_map.values():
                name = toks[i + 1]
                body, i = block_body(i + 2)
                _parse_bucket(m, t, name, body, names)
            else:
                raise CompileError(f"cannot parse at {toks[i:i + 4]}")
    except (IndexError, ValueError) as e:
        # truncated/malformed statements must fail as compile errors,
        # never tracebacks (crushtool -c catches CompileError)
        raise CompileError(f"malformed map text near token {i}: {e}")
    return m


def _parse_bucket(m: CrushMap, type_name: str, name: str,
                  body: List[str], names: Dict[str, int]) -> None:
    type_id = next(t for t, n in m.type_map.items() if n == type_name)
    bucket_id = 0
    alg = "straw2"
    hash_ = HASH_RJENKINS1
    items: List[int] = []
    weights: List[int] = []
    i = 0
    while i < len(body):
        t = body[i]
        if t == "id":
            bucket_id = int(body[i + 1])
            i += 2
        elif t == "alg":
            alg = body[i + 1]
            i += 2
        elif t == "hash":
            hash_ = int(body[i + 1])
            i += 2
        elif t == "item":
            item_name = body[i + 1]
            if item_name not in names:
                raise CompileError(
                    f"bucket {name!r}: item {item_name!r} not defined "
                    f"yet")
            items.append(names[item_name])
            i += 2
            w = 0x10000
            if i + 1 < len(body) and body[i] == "weight":
                w = _s2w(body[i + 1])
                i += 2
            weights.append(w)
        else:
            raise CompileError(f"bucket {name!r}: bad token {t!r}")
    if alg not in _ALG_IDS:
        raise CompileError(f"bucket {name!r}: unknown alg {alg!r}")
    b = make_bucket(m, _ALG_IDS[alg], type_id, items, weights,
                    bucket_id=bucket_id, hash_=hash_)
    names[name] = b.id
    m.name_map[b.id] = name


def _parse_rule(m: CrushMap, name: str, body: List[str],
                names: Dict[str, int]) -> None:
    ruleset = len(m.rules)
    rtype, min_size, max_size = 1, 1, 10
    steps: List[RuleStep] = []
    i = 0
    while i < len(body):
        t = body[i]
        if t == "ruleset":
            ruleset = int(body[i + 1])
            i += 2
        elif t == "type":
            rtype = _RULE_TYPE_IDS.get(body[i + 1])
            if rtype is None:
                try:
                    rtype = int(body[i + 1])
                except ValueError:
                    raise CompileError(
                        f"rule {name!r}: bad type {body[i + 1]!r}")
            i += 2
        elif t == "min_size":
            min_size = int(body[i + 1])
            i += 2
        elif t == "max_size":
            max_size = int(body[i + 1])
            i += 2
        elif t == "step":
            step, i = _parse_step(m, name, body, i + 1, names)
            steps.append(step)
        else:
            raise CompileError(f"rule {name!r}: bad token {t!r}")
    rid = m.add_rule(Rule(ruleset=ruleset, type=rtype, min_size=min_size,
                          max_size=max_size, steps=steps))
    m.rule_name_map[rid] = name


def _parse_step(m: CrushMap, rule: str, body: List[str], i: int,
                names: Dict[str, int]):
    """Parse one step starting at body[i]; -> (RuleStep, next index)."""
    op = body[i]
    if op == "take":
        target = body[i + 1]
        if target not in names:
            raise CompileError(f"rule {rule!r}: take of undefined "
                               f"{target!r}")
        return RuleStep(RULE_TAKE, names[target]), i + 2
    if op == "emit":
        return RuleStep(RULE_EMIT), i + 1
    if op in ("choose", "chooseleaf"):
        # step choose[leaf] firstn|indep N type T
        code = _CHOOSE_STEPS.get((op, body[i + 1]))
        if code is None or i + 4 >= len(body) or body[i + 3] != "type":
            raise CompileError(
                f"rule {rule!r}: bad step {body[i:i + 5]}")
        tid = next((t for t, n in m.type_map.items()
                    if n == body[i + 4]), None)
        if tid is None:
            raise CompileError(
                f"rule {rule!r}: unknown type {body[i + 4]!r}")
        return RuleStep(code, int(body[i + 2]), tid), i + 5
    if op in _SET_STEPS:
        return RuleStep(_SET_STEPS[op], int(body[i + 1])), i + 2
    raise CompileError(f"rule {rule!r}: unknown step {op!r}")
