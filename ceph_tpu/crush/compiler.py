"""CrushCompiler: the textual crushmap dialect, both directions.

Reference parity: src/crush/CrushCompiler.cc + src/crush/grammar.h — the
`crushtool -d` / `crushtool -c` text form:

    # begin crush map
    tunable choose_total_tries 50
    device 0 osd.0
    type 0 osd
    type 1 host
    host host0 {
        id -1
        alg straw2
        hash 0  # rjenkins1
        item osd.0 weight 1.000000
    }
    rule replicated_rule {
        ruleset 0
        type replicated
        min_size 1
        max_size 10
        step take default
        step chooseleaf firstn 0 type host
        step emit
    }
    # end crush map

Redesigned without boost::spirit: a line-oriented tokenizer (comments
stripped, braces as block markers) feeding small per-section parsers.
Weights print with 6 decimals so the 16.16 fixed-point values survive
the text round-trip exactly (1/65536 ~ 1.5e-5 > 0.5e-6 print error);
buckets must be defined before they are referenced, like the reference.
"""

from __future__ import annotations

import re
from typing import Dict, List

from ceph_tpu.crush.builder import make_bucket
from ceph_tpu.crush.constants import (
    BUCKET_ALG_NAMES, HASH_RJENKINS1,
    RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP, RULE_EMIT, RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_TRIES, RULE_SET_CHOOSELEAF_VARY_R,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES, RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES, RULE_TAKE,
)
from ceph_tpu.crush.types import CrushMap, Rule, RuleStep

_ALG_IDS = {name: alg for alg, name in BUCKET_ALG_NAMES.items()}
_RULE_TYPE_NAMES = {1: "replicated", 3: "erasure"}
_RULE_TYPE_IDS = {v: k for k, v in _RULE_TYPE_NAMES.items()}
_SET_STEPS = {
    "set_choose_tries": RULE_SET_CHOOSE_TRIES,
    "set_chooseleaf_tries": RULE_SET_CHOOSELEAF_TRIES,
    "set_choose_local_tries": RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries": RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_vary_r": RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": RULE_SET_CHOOSELEAF_STABLE,
}
_SET_STEP_NAMES = {v: k for k, v in _SET_STEPS.items()}
_CHOOSE_STEPS = {
    ("choose", "firstn"): RULE_CHOOSE_FIRSTN,
    ("choose", "indep"): RULE_CHOOSE_INDEP,
    ("chooseleaf", "firstn"): RULE_CHOOSELEAF_FIRSTN,
    ("chooseleaf", "indep"): RULE_CHOOSELEAF_INDEP,
}
_CHOOSE_STEP_NAMES = {v: k for k, v in _CHOOSE_STEPS.items()}

_TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
             "choose_total_tries", "chooseleaf_descend_once",
             "chooseleaf_vary_r", "chooseleaf_stable",
             "straw_calc_version")


class CompileError(ValueError):
    pass


def _w2s(w: int) -> str:
    return f"{w / 0x10000:.6f}"


def _s2w(s: str) -> int:
    return int(round(float(s) * 0x10000))


# ---------------------------------------------------------------- decompile

def decompile(m: CrushMap) -> str:
    """CrushMap -> reference-dialect text (CrushCompiler::decompile)."""
    out: List[str] = ["# begin crush map"]
    for t in _TUNABLES:
        out.append(f"tunable {t} {getattr(m.tunables, t)}")
    out.append("")
    out.append("# devices")
    for dev in range(m.max_devices):
        name = m.name_map.get(dev)
        if name is not None:
            out.append(f"device {dev} {name}")
    out.append("")
    out.append("# types")
    for tid in sorted(m.type_map):
        out.append(f"type {tid} {m.type_map[tid]}")
    out.append("")
    out.append("# buckets")
    # definition must precede reference: emit leaf-most first (reverse
    # id order matches builder output; fall back to dependency sort)
    done: set = set()
    order: List[int] = []

    def visit(bid: int) -> None:
        if bid in done:
            return
        done.add(bid)
        b = m.bucket(bid)
        if b is None:
            return
        for it in b.items:
            if it < 0:
                visit(it)
        order.append(bid)

    for b in m.buckets:
        if b is not None:
            visit(b.id)
    for bid in order:
        b = m.bucket(bid)
        tname = m.type_map.get(b.type, str(b.type))
        out.append(f"{tname} {m.name_of(b.id)} {{")
        out.append(f"\tid {b.id}\t\t# do not change unnecessarily")
        out.append(f"\t# weight {_w2s(b.weight)}")
        out.append(f"\talg {BUCKET_ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for it, w in zip(b.items, b.item_weights):
            out.append(f"\titem {m.name_of(it)} weight {_w2s(w)}")
        out.append("}")
    out.append("")
    out.append("# rules")
    for rid, r in enumerate(m.rules):
        if r is None:
            continue
        out.append(f"rule {m.rule_name_map.get(rid, f'rule{rid}')} {{")
        out.append(f"\truleset {r.ruleset}")
        out.append(f"\ttype {_RULE_TYPE_NAMES.get(r.type, str(r.type))}")
        out.append(f"\tmin_size {r.min_size}")
        out.append(f"\tmax_size {r.max_size}")
        for s in r.steps:
            if s.op == RULE_TAKE:
                out.append(f"\tstep take {m.name_of(s.arg1)}")
            elif s.op == RULE_EMIT:
                out.append("\tstep emit")
            elif s.op in _CHOOSE_STEP_NAMES:
                kind, mode = _CHOOSE_STEP_NAMES[s.op]
                tname = m.type_map.get(s.arg2, str(s.arg2))
                out.append(f"\tstep {kind} {mode} {s.arg1} type {tname}")
            elif s.op in _SET_STEP_NAMES:
                out.append(f"\tstep {_SET_STEP_NAMES[s.op]} {s.arg1}")
            else:
                raise CompileError(f"cannot decompile step op {s.op}")
        out.append("}")
    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


# ------------------------------------------------------------------ compile

def compile_text(text: str) -> CrushMap:
    """Reference-dialect text -> CrushMap (CrushCompiler::compile).

    Buckets must be defined before they are referenced (same constraint
    as the reference's single-pass grammar).
    """
    m = CrushMap()
    m.type_map = {}
    names: Dict[str, int] = {}          # item name -> id

    # tokenize: strip comments, split into statements; `{...}` blocks
    # become (header_tokens, [line_tokens...])
    lines: List[List[str]] = []
    for raw in text.splitlines():
        line = re.sub(r"#.*", "", raw).strip()
        if line:
            lines.append(line.replace("{", " { ").replace("}", " } ")
                         .split())
    i = 0

    def parse_block(start: int):
        """-> (body_lines, next_index); start points at the header."""
        if lines[start][-1] != "{":
            raise CompileError(f"expected '{{' in {' '.join(lines[start])}")
        body = []
        j = start + 1
        while j < len(lines) and lines[j] != ["}"]:
            body.append(lines[j])
            j += 1
        if j >= len(lines):
            raise CompileError("unterminated block")
        return body, j + 1

    while i < len(lines):
        tok = lines[i]
        if tok[0] == "tunable" and len(tok) == 3:
            if tok[1] not in _TUNABLES:
                raise CompileError(f"unknown tunable {tok[1]!r}")
            setattr(m.tunables, tok[1], int(tok[2]))
            i += 1
        elif tok[0] == "device" and len(tok) >= 3:
            dev = int(tok[1])
            names[tok[2]] = dev
            m.name_map[dev] = tok[2]
            m.max_devices = max(m.max_devices, dev + 1)
            i += 1
        elif tok[0] == "type" and len(tok) == 3:
            m.type_map[int(tok[1])] = tok[2]
            i += 1
        elif tok[0] == "rule" and len(tok) >= 2:
            body, i = parse_block(i)
            _parse_rule(m, tok[1] if len(tok) > 2 else "rule",
                        body, names)
        elif tok[0] in m.type_map.values() and len(tok) >= 2:
            body, i = parse_block(i)
            _parse_bucket(m, tok[0], tok[1], body, names)
        else:
            raise CompileError(f"cannot parse: {' '.join(tok)}")
    return m


def _parse_bucket(m: CrushMap, type_name: str, name: str,
                  body: List[List[str]], names: Dict[str, int]) -> None:
    type_id = next(t for t, n in m.type_map.items() if n == type_name)
    bucket_id = 0
    alg = "straw2"
    hash_ = HASH_RJENKINS1
    items: List[int] = []
    weights: List[int] = []
    for tok in body:
        if tok[0] == "id":
            bucket_id = int(tok[1])
        elif tok[0] == "alg":
            alg = tok[1]
        elif tok[0] == "hash":
            hash_ = int(tok[1])
        elif tok[0] == "item":
            if tok[1] not in names:
                raise CompileError(
                    f"bucket {name!r}: item {tok[1]!r} not defined yet")
            items.append(names[tok[1]])
            w = 0x10000
            if len(tok) >= 4 and tok[2] == "weight":
                w = _s2w(tok[3])
            weights.append(w)
        elif tok[0] == "weight":
            pass                     # total is derived
        else:
            raise CompileError(f"bucket {name!r}: bad line {tok}")
    if alg not in _ALG_IDS:
        raise CompileError(f"bucket {name!r}: unknown alg {alg!r}")
    b = make_bucket(m, _ALG_IDS[alg], type_id, items, weights,
                    bucket_id=bucket_id, hash_=hash_)
    names[name] = b.id
    m.name_map[b.id] = name


def _parse_rule(m: CrushMap, name: str, body: List[List[str]],
                names: Dict[str, int]) -> None:
    ruleset = len(m.rules)
    rtype, min_size, max_size = 1, 1, 10
    steps: List[RuleStep] = []
    for tok in body:
        if tok[0] == "ruleset":
            ruleset = int(tok[1])
        elif tok[0] == "type":
            rtype = _RULE_TYPE_IDS.get(tok[1])
            if rtype is None:
                try:
                    rtype = int(tok[1])
                except ValueError:
                    raise CompileError(f"rule {name!r}: bad type {tok[1]!r}")
        elif tok[0] == "min_size":
            min_size = int(tok[1])
        elif tok[0] == "max_size":
            max_size = int(tok[1])
        elif tok[0] == "step":
            steps.append(_parse_step(m, name, tok[1:], names))
        else:
            raise CompileError(f"rule {name!r}: bad line {tok}")
    rid = m.add_rule(Rule(ruleset=ruleset, type=rtype, min_size=min_size,
                          max_size=max_size, steps=steps))
    m.rule_name_map[rid] = name


def _parse_step(m: CrushMap, rule: str, tok: List[str],
                names: Dict[str, int]) -> RuleStep:
    if tok[0] == "take":
        if tok[1] not in names:
            raise CompileError(f"rule {rule!r}: take of undefined "
                               f"{tok[1]!r}")
        return RuleStep(RULE_TAKE, names[tok[1]])
    if tok[0] == "emit":
        return RuleStep(RULE_EMIT)
    if tok[0] in ("choose", "chooseleaf"):
        # step choose[leaf] firstn|indep N type T
        op = _CHOOSE_STEPS.get((tok[0], tok[1]))
        if op is None or len(tok) != 5 or tok[3] != "type":
            raise CompileError(f"rule {rule!r}: bad step {tok}")
        tid = next((t for t, n in m.type_map.items() if n == tok[4]),
                   None)
        if tid is None:
            raise CompileError(f"rule {rule!r}: unknown type {tok[4]!r}")
        return RuleStep(op, int(tok[2]), tid)
    if tok[0] in _SET_STEPS:
        return RuleStep(_SET_STEPS[tok[0]], int(tok[1]))
    raise CompileError(f"rule {rule!r}: unknown step {tok[0]!r}")
