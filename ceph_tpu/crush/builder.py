"""CRUSH map construction: buckets with derived per-alg state.

Reference parity: crush/builder.c — crush_make_{uniform,list,tree,straw,
straw2}_bucket (:330-620) including straw length calculation
(crush_calc_straw :439, both straw_calc_version 0 and 1) and tree
node-weight propagation (:366-397).
"""

from __future__ import annotations

import math
from typing import List, Optional

from ceph_tpu.crush.constants import (BUCKET_LIST, BUCKET_STRAW,
                                      BUCKET_STRAW2, BUCKET_TREE,
                                      BUCKET_UNIFORM, HASH_RJENKINS1)
from ceph_tpu.crush.types import Bucket, CrushMap


def _calc_depth(size: int) -> int:
    if size == 0:
        return 0
    depth, t = 1, size - 1
    while t:
        t >>= 1
        depth += 1
    return depth


def _tree_node(i: int) -> int:
    return ((i + 1) << 1) - 1


def _height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def _parent(n: int) -> int:
    h = _height(n)
    if n & (1 << (h + 1)):
        return n - (1 << h)
    return n + (1 << h)


def calc_straws(item_weights: List[int], straw_calc_version: int) -> List[int]:
    """Straw lengths for the legacy straw bucket (builder.c:439-556)."""
    size = len(item_weights)
    straws = [0] * size
    # reverse = indices sorted ascending by weight, stable (insertion sort)
    reverse = sorted(range(size), key=lambda i: (item_weights[i], i))
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        w_i = item_weights[reverse[i]]
        if straw_calc_version == 0:
            if w_i == 0:
                straws[reverse[i]] = 0
                i += 1
                continue
            straws[reverse[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            if item_weights[reverse[i]] == item_weights[reverse[i - 1]]:
                continue
            wbelow += (float(item_weights[reverse[i - 1]]) - lastw) * numleft
            j = i
            while j < size:
                if item_weights[reverse[j]] == item_weights[reverse[i]]:
                    numleft -= 1
                else:
                    break
                j += 1
            wnext = numleft * (item_weights[reverse[i]]
                               - item_weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(item_weights[reverse[i - 1]])
        else:
            if w_i == 0:
                straws[reverse[i]] = 0
                i += 1
                numleft -= 1
                continue
            straws[reverse[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            wbelow += (float(item_weights[reverse[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (item_weights[reverse[i]]
                               - item_weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(item_weights[reverse[i - 1]])
    return straws


def make_bucket(map_: CrushMap, alg: int, type_: int, items: List[int],
                weights: Optional[List[int]] = None, bucket_id: int = 0,
                hash_: int = HASH_RJENKINS1) -> Bucket:
    """Build a bucket with all derived state and register it in the map.

    ``weights`` are 16.16 fixed; for uniform buckets all items share
    weights[0] (reference crush_make_uniform_bucket semantics).
    """
    size = len(items)
    weights = list(weights or [0x10000] * size)
    b = Bucket(id=bucket_id, alg=alg, hash=hash_, type=type_,
               items=list(items))
    if alg == BUCKET_UNIFORM:
        w = weights[0] if size else 0
        b.item_weights = [w] * size
        b.weight = w * size
    elif alg == BUCKET_LIST:
        b.item_weights = weights
        total = 0
        for w in weights:
            total += w
            b.sum_weights.append(total)
        b.weight = total
    elif alg == BUCKET_TREE:
        depth = _calc_depth(size)
        num_nodes = 1 << depth if size else 0
        b.node_weights = [0] * num_nodes
        total = 0
        for i, w in enumerate(weights):
            node = _tree_node(i)
            b.node_weights[node] = w
            total += w
            for _ in range(1, depth):
                node = _parent(node)
                b.node_weights[node] += w
        b.weight = total
        b.item_weights = weights
    elif alg == BUCKET_STRAW:
        b.item_weights = weights
        b.weight = sum(weights)
        b.straws = calc_straws(weights, map_.tunables.straw_calc_version)
    elif alg == BUCKET_STRAW2:
        b.item_weights = weights
        b.weight = sum(weights)
    else:
        raise ValueError(f"unknown bucket alg {alg}")
    map_.add_bucket(b)
    for it in items:
        if it >= 0:
            map_.max_devices = max(map_.max_devices, it + 1)
    return b


def reweight_item(map_: CrushMap, b: Bucket, item: int, weight: int) -> None:
    """Adjust one item's weight, recomputing derived state
    (reference: crush_bucket_adjust_item_weight, builder.c:830-1130)."""
    map_._invalidate_kernel_cache()
    pos = b.items.index(item)
    if b.alg == BUCKET_UNIFORM:
        b.item_weights = [weight] * b.size
        b.weight = weight * b.size
        return
    old = b.item_weights[pos]
    b.item_weights[pos] = weight
    b.weight += weight - old
    if b.alg == BUCKET_LIST:
        total = 0
        b.sum_weights = []
        for w in b.item_weights:
            total += w
            b.sum_weights.append(total)
    elif b.alg == BUCKET_TREE:
        depth = _calc_depth(b.size)
        node = _tree_node(pos)
        b.node_weights[node] = weight
        diff = weight - old
        for _ in range(1, depth):
            node = _parent(node)
            b.node_weights[node] += diff
    elif b.alg == BUCKET_STRAW:
        b.straws = calc_straws(b.item_weights,
                               map_.tunables.straw_calc_version)


def make_replicated_rule(map_: CrushMap, name: str, root_name: str = "default",
                         failure_domain: str = "host") -> int:
    """take root; chooseleaf_firstn 0 <domain>; emit — what
    CrushWrapper::add_simple_ruleset builds (CrushWrapper.cc)."""
    from ceph_tpu.crush.constants import (RULE_CHOOSELEAF_FIRSTN, RULE_EMIT,
                                          RULE_TAKE)
    from ceph_tpu.crush.types import Rule, RuleStep
    root_id = _find_name(map_, root_name)
    dom = _find_type(map_, failure_domain)
    rule = Rule(ruleset=len(map_.rules), type=1, min_size=1, max_size=10,
                steps=[RuleStep(RULE_TAKE, root_id),
                       RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, dom),
                       RuleStep(RULE_EMIT)])
    rid = map_.add_rule(rule)
    map_.rule_name_map[rid] = name
    return rid


def make_erasure_rule(map_: CrushMap, name: str, size: int,
                      failure_domain: str = "host",
                      root_name: str = "default") -> int:
    """take root; chooseleaf_indep <size> <domain>; emit — positionally
    stable placement for EC (ErasureCodeInterface create_ruleset role,
    /root/reference/src/erasure-code/ErasureCodeInterface.h:181)."""
    from ceph_tpu.crush.constants import (RULE_CHOOSELEAF_INDEP, RULE_EMIT,
                                          RULE_SET_CHOOSELEAF_TRIES,
                                          RULE_SET_CHOOSE_TRIES, RULE_TAKE)
    from ceph_tpu.crush.types import Rule, RuleStep
    root_id = _find_name(map_, root_name)
    dom = _find_type(map_, failure_domain)
    rule = Rule(ruleset=len(map_.rules), type=3, min_size=3,
                max_size=max(size, 3),
                steps=[RuleStep(RULE_SET_CHOOSELEAF_TRIES, 5),
                       RuleStep(RULE_SET_CHOOSE_TRIES, 100),
                       RuleStep(RULE_TAKE, root_id),
                       RuleStep(RULE_CHOOSELEAF_INDEP, size, dom),
                       RuleStep(RULE_EMIT)])
    rid = map_.add_rule(rule)
    map_.rule_name_map[rid] = name
    return rid


def _find_name(map_: CrushMap, name: str) -> int:
    for iid, n in map_.name_map.items():
        if n == name:
            return iid
    raise KeyError(f"no crush item named {name!r}")


def _find_type(map_: CrushMap, type_name: str) -> int:
    for tid, n in map_.type_map.items():
        if n == type_name:
            return tid
    raise KeyError(f"no crush type named {type_name!r}")


def build_hierarchy(map_: CrushMap, n_osds: int, osds_per_host: int,
                    alg: int = BUCKET_STRAW2, hosts_per_rack: int = 0,
                    osd_weight: int = 0x10000, root_name: str = "default"
                    ) -> Bucket:
    """Convenience: osds -> hosts (-> racks) -> root, registering names.

    Mirrors what CrushWrapper::build_simple_crush_map produces for tests.
    """
    hosts = []
    for h in range((n_osds + osds_per_host - 1) // osds_per_host):
        items = list(range(h * osds_per_host,
                           min((h + 1) * osds_per_host, n_osds)))
        hb = make_bucket(map_, alg, 1, items, [osd_weight] * len(items))
        map_.name_map[hb.id] = f"host{h}"
        hosts.append(hb)
        for o in items:
            map_.name_map[o] = f"osd.{o}"
    level = hosts
    if hosts_per_rack:
        racks = []
        for r in range((len(hosts) + hosts_per_rack - 1) // hosts_per_rack):
            group = hosts[r * hosts_per_rack:(r + 1) * hosts_per_rack]
            rb = make_bucket(map_, alg, 2, [g.id for g in group],
                             [g.weight for g in group])
            map_.name_map[rb.id] = f"rack{r}"
            racks.append(rb)
        level = racks
    root = make_bucket(map_, alg, 10, [b.id for b in level],
                       [b.weight for b in level])
    map_.name_map[root.id] = root_name
    return root
