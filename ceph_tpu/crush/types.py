"""CRUSH data model: buckets, rules, map, tunables.

Reference parity: crush/crush.h:129-232 (crush_map/crush_bucket structs) —
redesigned as plain dataclasses with derived per-alg fields computed by
builder.py.  Weights are 16.16 fixed-point u32 everywhere, device ids are
>= 0 and bucket ids are < 0 with index = -1-id, exactly like the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ceph_tpu.common.encoding import Decoder, Encodable, Encoder
from ceph_tpu.crush.constants import (BUCKET_ALG_NAMES, BUCKET_STRAW2,
                                      HASH_RJENKINS1, TUNABLE_PROFILES)


def weight_to_fixed(w: float) -> int:
    return int(w * 0x10000)


def fixed_to_weight(w: int) -> float:
    return w / 0x10000


@dataclass
class Bucket(Encodable):
    """One interior node of the hierarchy (crush.h:129-187)."""
    STRUCT_V = 1

    id: int                       # < 0
    alg: int = BUCKET_STRAW2
    hash: int = HASH_RJENKINS1
    type: int = 1                 # bucket type id (host/rack/root...)
    weight: int = 0               # 16.16 total
    items: List[int] = field(default_factory=list)
    # per-alg derived state:
    item_weights: List[int] = field(default_factory=list)  # list/straw/straw2
    sum_weights: List[int] = field(default_factory=list)   # list (cumulative)
    node_weights: List[int] = field(default_factory=list)  # tree (2^depth)
    straws: List[int] = field(default_factory=list)        # straw

    @property
    def size(self) -> int:
        return len(self.items)

    def encode_payload(self, enc: Encoder) -> None:
        enc.s32(self.id).u8(self.alg).u8(self.hash).u16(self.type)
        enc.u32(self.weight)
        enc.list_(self.items, lambda e, v: e.s32(v))
        enc.list_(self.item_weights, lambda e, v: e.u32(v))
        enc.list_(self.sum_weights, lambda e, v: e.u32(v))
        enc.list_(self.node_weights, lambda e, v: e.u32(v))
        enc.list_(self.straws, lambda e, v: e.u32(v))

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "Bucket":
        b = cls(id=dec.s32(), alg=dec.u8(), hash=dec.u8(), type=dec.u16(),
                weight=dec.u32())
        b.items = dec.list_(lambda d: d.s32())
        b.item_weights = dec.list_(lambda d: d.u32())
        b.sum_weights = dec.list_(lambda d: d.u32())
        b.node_weights = dec.list_(lambda d: d.u32())
        b.straws = dec.list_(lambda d: d.u32())
        return b


@dataclass
class RuleStep(Encodable):
    op: int
    arg1: int = 0
    arg2: int = 0

    def encode_payload(self, enc: Encoder) -> None:
        enc.u32(self.op).s32(self.arg1).s32(self.arg2)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "RuleStep":
        return cls(dec.u32(), dec.s32(), dec.s32())


@dataclass
class Rule(Encodable):
    """crush_rule + crush_rule_mask (crush.h:76-95)."""
    ruleset: int
    type: int                      # replicated / erasure
    min_size: int
    max_size: int
    steps: List[RuleStep] = field(default_factory=list)

    def encode_payload(self, enc: Encoder) -> None:
        enc.u8(self.ruleset).u8(self.type).u8(self.min_size).u8(self.max_size)
        enc.list_(self.steps, lambda e, s: e.struct(s))

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "Rule":
        r = cls(dec.u8(), dec.u8(), dec.u8(), dec.u8())
        r.steps = dec.list_(lambda d: RuleStep.decode(d))
        return r


@dataclass
class Tunables:
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1

    @classmethod
    def profile(cls, name: str) -> "Tunables":
        return cls(**TUNABLE_PROFILES[name])


class CrushMap(Encodable):
    """The full map (crush.h:191-232 + CrushWrapper name/type maps)."""
    STRUCT_V = 1

    def __init__(self):
        self.buckets: List[Optional[Bucket]] = []   # index = -1-id
        self.rules: List[Optional[Rule]] = []
        self.max_devices: int = 0
        self.tunables = Tunables()
        # CrushWrapper facade state (CrushWrapper.h): names and types
        self.type_map: Dict[int, str] = {0: "osd", 1: "host", 2: "rack",
                                         3: "row", 4: "room", 5: "datacenter",
                                         10: "root"}
        self.name_map: Dict[int, str] = {}          # item id -> name
        self.rule_name_map: Dict[int, str] = {}     # rule id -> name

    # -- topology accessors -------------------------------------------------
    @property
    def max_buckets(self) -> int:
        return len(self.buckets)

    def bucket(self, item_id: int) -> Optional[Bucket]:
        idx = -1 - item_id
        if 0 <= idx < len(self.buckets):
            return self.buckets[idx]
        return None

    def _invalidate_kernel_cache(self) -> None:
        """Drop the attached batched-kernel compile cache (see
        ops/crush_kernel.compile_rule) — in-place topology mutation
        invalidates compiled level tables."""
        self.__dict__.pop("_kernel_compile_cache", None)
        self.__dict__.pop("_kernel_compile_token", None)

    def add_bucket(self, b: Bucket) -> int:
        self._invalidate_kernel_cache()
        if b.id == 0:  # auto-assign
            b.id = -1 - len(self.buckets)
            self.buckets.append(b)
        else:
            idx = -1 - b.id
            while len(self.buckets) <= idx:
                self.buckets.append(None)
            assert self.buckets[idx] is None, f"bucket id {b.id} in use"
            self.buckets[idx] = b
        return b.id

    def add_rule(self, r: Rule, rule_id: int = -1) -> int:
        self._invalidate_kernel_cache()
        if rule_id < 0:
            rule_id = len(self.rules)
        while len(self.rules) <= rule_id:
            self.rules.append(None)
        self.rules[rule_id] = r
        return rule_id

    def find_rule(self, ruleset: int, type_: int, size: int) -> int:
        """reference: crush_find_rule (mapper.c top) / CrushWrapper."""
        for i, r in enumerate(self.rules):
            if (r is not None and r.ruleset == ruleset and r.type == type_
                    and r.min_size <= size <= r.max_size):
                return i
        return -1

    def name_of(self, item_id: int) -> str:
        return self.name_map.get(
            item_id, f"osd.{item_id}" if item_id >= 0 else f"bucket{item_id}")

    def set_tunables_profile(self, name: str) -> None:
        self._invalidate_kernel_cache()
        self.tunables = Tunables.profile(name)

    # -- encoding ------------------------------------------------------------
    def encode_payload(self, enc: Encoder) -> None:
        enc.s32(self.max_devices)
        t = self.tunables
        enc.u32(t.choose_local_tries).u32(t.choose_local_fallback_tries)
        enc.u32(t.choose_total_tries).u8(t.chooseleaf_descend_once)
        enc.u8(t.chooseleaf_vary_r).u8(t.chooseleaf_stable)
        enc.u8(t.straw_calc_version)
        enc.list_(self.buckets, lambda e, b: e.opt_struct(b))
        enc.list_(self.rules, lambda e, r: e.opt_struct(r))
        enc.map_(self.type_map, lambda e, k: e.s32(k), lambda e, v: e.string(v))
        enc.map_(self.name_map, lambda e, k: e.s32(k), lambda e, v: e.string(v))
        enc.map_(self.rule_name_map, lambda e, k: e.s32(k),
                 lambda e, v: e.string(v))

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "CrushMap":
        m = cls()
        m.max_devices = dec.s32()
        m.tunables = Tunables(
            choose_local_tries=dec.u32(),
            choose_local_fallback_tries=dec.u32(),
            choose_total_tries=dec.u32(),
            chooseleaf_descend_once=dec.u8(),
            chooseleaf_vary_r=dec.u8(),
            chooseleaf_stable=dec.u8(),
            straw_calc_version=dec.u8(),
        )
        m.buckets = dec.list_(lambda d: d.opt_struct(Bucket))
        m.rules = dec.list_(lambda d: d.opt_struct(Rule))
        m.type_map = dec.map_(lambda d: d.s32(), lambda d: d.string())
        m.name_map = dec.map_(lambda d: d.s32(), lambda d: d.string())
        m.rule_name_map = dec.map_(lambda d: d.s32(), lambda d: d.string())
        return m

    def __eq__(self, other):
        return isinstance(other, CrushMap) and self.to_bytes() == other.to_bytes()

    def summary(self) -> str:
        nb = sum(1 for b in self.buckets if b)
        nr = sum(1 for r in self.rules if r)
        return (f"CrushMap(devices<{self.max_devices}, buckets={nb}, "
                f"rules={nr}, algs={sorted({BUCKET_ALG_NAMES[b.alg] for b in self.buckets if b})})")
