"""CRUSH constants and tunable profiles.

Reference parity: crush/crush.h (bucket algs :111-117, rule ops :48-63,
CRUSH_ITEM_* :33-34) and CrushWrapper tunable profiles
(crush/CrushWrapper.h:105-151).
"""

CRUSH_MAX_DEPTH = 10
CRUSH_ITEM_UNDEF = 0x7FFFFFFE  # internal: undefined result
CRUSH_ITEM_NONE = 0x7FFFFFFF   # no result
CRUSH_MAX_DEVICE_WEIGHT = 100 * 0x10000
CRUSH_MAX_BUCKET_WEIGHT = 65535 * 0x10000

# bucket algorithms
BUCKET_UNIFORM = 1
BUCKET_LIST = 2
BUCKET_TREE = 3
BUCKET_STRAW = 4
BUCKET_STRAW2 = 5
BUCKET_ALG_NAMES = {
    BUCKET_UNIFORM: "uniform", BUCKET_LIST: "list", BUCKET_TREE: "tree",
    BUCKET_STRAW: "straw", BUCKET_STRAW2: "straw2",
}
BUCKET_ALG_BY_NAME = {v: k for k, v in BUCKET_ALG_NAMES.items()}

# hash functions
HASH_RJENKINS1 = 0

# rule step opcodes
RULE_NOOP = 0
RULE_TAKE = 1
RULE_CHOOSE_FIRSTN = 2
RULE_CHOOSE_INDEP = 3
RULE_EMIT = 4
RULE_CHOOSELEAF_FIRSTN = 6
RULE_CHOOSELEAF_INDEP = 7
RULE_SET_CHOOSE_TRIES = 8
RULE_SET_CHOOSELEAF_TRIES = 9
RULE_SET_CHOOSE_LOCAL_TRIES = 10
RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
RULE_SET_CHOOSELEAF_VARY_R = 12
RULE_SET_CHOOSELEAF_STABLE = 13

RULE_OP_NAMES = {
    RULE_NOOP: "noop", RULE_TAKE: "take",
    RULE_CHOOSE_FIRSTN: "choose firstn", RULE_CHOOSE_INDEP: "choose indep",
    RULE_EMIT: "emit",
    RULE_CHOOSELEAF_FIRSTN: "chooseleaf firstn",
    RULE_CHOOSELEAF_INDEP: "chooseleaf indep",
    RULE_SET_CHOOSE_TRIES: "set_choose_tries",
    RULE_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
    RULE_SET_CHOOSE_LOCAL_TRIES: "set_choose_local_tries",
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES: "set_choose_local_fallback_tries",
    RULE_SET_CHOOSELEAF_VARY_R: "set_chooseleaf_vary_r",
    RULE_SET_CHOOSELEAF_STABLE: "set_chooseleaf_stable",
}

# rule types (pool semantics)
RULE_TYPE_REPLICATED = 1
RULE_TYPE_ERASURE = 3

S64_MIN = -(1 << 63)

# Tunable profiles (reference: CrushWrapper.h:105-151).  Each maps to the
# crush_map tunable fields; "optimal" at this reference version == jewel.
TUNABLE_PROFILES = {
    "legacy": dict(choose_local_tries=2, choose_local_fallback_tries=5,
                   choose_total_tries=19, chooseleaf_descend_once=0,
                   chooseleaf_vary_r=0, chooseleaf_stable=0,
                   straw_calc_version=0),
    "argonaut": dict(choose_local_tries=2, choose_local_fallback_tries=5,
                     choose_total_tries=19, chooseleaf_descend_once=0,
                     chooseleaf_vary_r=0, chooseleaf_stable=0,
                     straw_calc_version=0),
    "bobtail": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                    choose_total_tries=50, chooseleaf_descend_once=1,
                    chooseleaf_vary_r=0, chooseleaf_stable=0,
                    straw_calc_version=0),
    "firefly": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                    choose_total_tries=50, chooseleaf_descend_once=1,
                    chooseleaf_vary_r=1, chooseleaf_stable=0,
                    straw_calc_version=0),
    "hammer": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                   choose_total_tries=50, chooseleaf_descend_once=1,
                   chooseleaf_vary_r=1, chooseleaf_stable=0,
                   straw_calc_version=1),
    "jewel": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                  choose_total_tries=50, chooseleaf_descend_once=1,
                  chooseleaf_vary_r=1, chooseleaf_stable=1,
                  straw_calc_version=1),
}
TUNABLE_PROFILES["optimal"] = TUNABLE_PROFILES["jewel"]
# reference set_tunables_default() = firefly + straw_calc_version=1
# (CrushWrapper.h:167-170) — note chooseleaf_stable stays 0
TUNABLE_PROFILES["default"] = dict(TUNABLE_PROFILES["firefly"],
                                   straw_calc_version=1)
