"""Fixed-point log2 used by straw2: crush_ln(x) = 2^44 * log2(x+1).

Reference parity: crush/mapper.c:246-288 (crush_ln) over the lookup tables in
crush/crush_ln_table.h, which document themselves as
    RH_LH_tbl[2k]   = 2^48 / (1.0 + k/128.0)
    RH_LH_tbl[2k+1] = 2^48 * log2(1.0 + k/128.0)
    LL_tbl[k]       = 2^48 * log2(1.0 + k/2^15)
The table CONSTANTS are behavioral ground truth: the reference's historical
generator deviates from the documented formulas in ways that matter for
bit-exactness (RH is ceil() not round; LH is floor(); LL matches
2^48*log2(1+k/2^15) only at the range endpoints and carries a generator
artifact in between).  We therefore carry the 514 constants as extracted
golden DATA (_ln_tables.json, produced by tests/golden/generate.py from the
reference header, pinned by the ln_fnv checksum in the golden corpus) and
keep the formula derivations below as validators for the rows that do obey
the documented math.
"""

from __future__ import annotations

import decimal
import json
import pathlib
from functools import lru_cache

import numpy as np

_SCALE48 = 1 << 48
_DATA = pathlib.Path(__file__).parent / "_ln_tables.json"


def _log2_fixed(num: int, den: int, scale: int = _SCALE48,
                rounding=decimal.ROUND_FLOOR) -> int:
    """floor/round(scale * log2(num/den)) via high-precision decimal."""
    assert num > 0 and den > 0
    with decimal.localcontext() as ctx:
        ctx.prec = 60
        v = (decimal.Decimal(num).ln() - decimal.Decimal(den).ln()) \
            / decimal.Decimal(2).ln() * scale
        return int(v.to_integral_value(rounding=rounding))


@lru_cache(maxsize=1)
def _tables():
    d = json.loads(_DATA.read_text())
    return (np.array(d["rh"], np.int64), np.array(d["lh"], np.int64),
            np.array(d["ll"], np.int64))


def rh_lh_tables():
    """RH[k] ~ ceil(2^48*128/(128+k)), LH[k] ~ floor(2^48*log2(1+k/128))."""
    rh, lh, _ = _tables()
    return rh, lh


def ll_table():
    """LL[k] ~ 2^48*log2(1+k/2^15) (exact only at endpoints; see module doc)."""
    return _tables()[2]


def derived_rh(k: int) -> int:
    """Documented-formula RH row (ceil), for validation tests."""
    num = _SCALE48 * 128
    den = 128 + k
    return -((-num) // den)


def crush_ln(xin: int) -> int:
    """Scalar bit-exact crush_ln (mapper.c:246-288)."""
    rh_tbl, lh_tbl = rh_lh_tables()
    ll_tbl = ll_table()
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        # count bits needed so bit 15 becomes the MSB of x&0x1ffff
        v = x & 0x1FFFF
        bits = 16 - v.bit_length()  # == __builtin_clz(v) - 16 for v < 2^17
        x = (x << bits) & 0xFFFFFFFF
        iexpon = 15 - bits
    idx = (x >> 8)            # in [0x80, 0x100]
    k = idx - 128
    rh = int(rh_tbl[k])
    lh = int(lh_tbl[k])
    xl64 = (x * rh) >> 48     # ~ 2^15 + xf, xf < 2^8
    result = iexpon << 44
    ll = int(ll_tbl[xl64 & 0xFF])
    result += (lh + ll) >> 4  # >> (48 - 12 - 32)
    return result


@lru_cache(maxsize=1)
def ln_u16_table() -> np.ndarray:
    """Precomputed crush_ln(u) for every 16-bit draw u in [0, 0xffff].

    straw2 only ever calls crush_ln on u & 0xffff, so the whole function
    collapses to one 64K-entry table — this is what the JAX kernel gathers
    from (ops/crush_kernel.py) and what the host mapper uses for speed.
    """
    return np.array([crush_ln(u) for u in range(0x10000)], np.int64)
