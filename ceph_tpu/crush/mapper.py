"""Bit-exact host CRUSH mapper: bucket chooses, descent loops, rule VM.

Reference parity: crush/mapper.c — bucket_perm_choose (:73), list (:140),
tree (:193), straw (:225), straw2 (:300), is_out (:378),
crush_choose_firstn (:414), crush_choose_indep (:600), crush_do_rule (:793).
This is the semantic ground truth the batched JAX kernel
(ceph_tpu/ops/crush_kernel.py) must match, and the fallback for tunable
combinations the TPU kernel does not support.  Golden-vector tests
(tests/golden/) pin it bit-for-bit to the reference C.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ceph_tpu.crush.constants import (BUCKET_LIST, BUCKET_STRAW,
                                      BUCKET_STRAW2, BUCKET_TREE,
                                      BUCKET_UNIFORM, CRUSH_ITEM_NONE,
                                      CRUSH_ITEM_UNDEF, RULE_CHOOSE_FIRSTN,
                                      RULE_CHOOSE_INDEP,
                                      RULE_CHOOSELEAF_FIRSTN,
                                      RULE_CHOOSELEAF_INDEP, RULE_EMIT,
                                      RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                                      RULE_SET_CHOOSE_LOCAL_TRIES,
                                      RULE_SET_CHOOSE_TRIES,
                                      RULE_SET_CHOOSELEAF_STABLE,
                                      RULE_SET_CHOOSELEAF_TRIES,
                                      RULE_SET_CHOOSELEAF_VARY_R, RULE_TAKE,
                                      S64_MIN)
from ceph_tpu.crush.hashfn import hash32_2, hash32_3, hash32_4
from ceph_tpu.crush.lntable import ln_u16_table
from ceph_tpu.crush.types import Bucket, CrushMap

_LN = None


def _ln16(u: int) -> int:
    global _LN
    if _LN is None:
        _LN = ln_u16_table()
    return int(_LN[u])


def _div64_trunc(a: int, b: int) -> int:
    """C div64_s64: truncation toward zero."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


# -- bucket chooses ----------------------------------------------------------

def bucket_perm_choose(b: Bucket, x: int, r: int) -> int:
    """Random-permutation choose (mapper.c:73-130).  The reference caches the
    partial permutation on the bucket; the result is a pure function of
    (bucket, x, r%size) so we compute it statelessly."""
    size = b.size
    pr = r % size
    if pr == 0:
        s = hash32_3(x, b.id & 0xFFFFFFFF, 0) % size
        return b.items[s]
    perm = list(range(size))
    for p in range(pr + 1):
        if p < size - 1:
            i = hash32_3(x, b.id & 0xFFFFFFFF, p) % (size - p)
            if i:
                perm[p + i], perm[p] = perm[p], perm[p + i]
    return b.items[perm[pr]]


def bucket_list_choose(b: Bucket, x: int, r: int) -> int:
    for i in range(b.size - 1, -1, -1):
        w = hash32_4(x, b.items[i] & 0xFFFFFFFF, r, b.id & 0xFFFFFFFF)
        w &= 0xFFFF
        w = (w * b.sum_weights[i]) >> 16
        if w < b.item_weights[i]:
            return b.items[i]
    return b.items[0]


def bucket_tree_choose(b: Bucket, x: int, r: int) -> int:
    n = len(b.node_weights) >> 1  # root
    while not (n & 1):
        w = b.node_weights[n]
        t = (hash32_4(x, n, r, b.id & 0xFFFFFFFF) * w) >> 32
        h = 0
        nn = n
        while (nn & 1) == 0:
            h += 1
            nn >>= 1
        left = n - (1 << (h - 1))
        if t < b.node_weights[left]:
            n = left
        else:
            n = n + (1 << (h - 1))
    return b.items[n >> 1]


def bucket_straw_choose(b: Bucket, x: int, r: int) -> int:
    high, high_draw = 0, 0
    for i in range(b.size):
        draw = hash32_3(x, b.items[i] & 0xFFFFFFFF, r)
        draw &= 0xFFFF
        draw *= b.straws[i]
        if i == 0 or draw > high_draw:
            high, high_draw = i, draw
    return b.items[high]


def bucket_straw2_choose(b: Bucket, x: int, r: int) -> int:
    """The hot loop (mapper.c:300-344): exponential-minimum sampling with
    fixed-point ln — this exact math is what the TPU kernel batches."""
    high, high_draw = 0, 0
    for i in range(b.size):
        w = b.item_weights[i]
        if w:
            u = hash32_3(x, b.items[i] & 0xFFFFFFFF, r) & 0xFFFF
            ln = _ln16(u) - 0x1000000000000
            draw = _div64_trunc(ln, w)
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high, high_draw = i, draw
    return b.items[high]


def crush_bucket_choose(b: Bucket, x: int, r: int) -> int:
    assert b.size > 0
    if b.alg == BUCKET_UNIFORM:
        return bucket_perm_choose(b, x, r)
    if b.alg == BUCKET_LIST:
        return bucket_list_choose(b, x, r)
    if b.alg == BUCKET_TREE:
        return bucket_tree_choose(b, x, r)
    if b.alg == BUCKET_STRAW:
        return bucket_straw_choose(b, x, r)
    if b.alg == BUCKET_STRAW2:
        return bucket_straw2_choose(b, x, r)
    return b.items[0]


def is_out(map_: CrushMap, weight: Sequence[int], item: int, x: int) -> bool:
    """Weight-fraction rejection (mapper.c:378-392)."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (hash32_2(x, item) & 0xFFFF) >= w


# -- descent loops -----------------------------------------------------------

def choose_firstn(map_: CrushMap, bucket: Bucket, weight: Sequence[int],
                  x: int, numrep: int, type_: int, out: List[int],
                  outpos: int, out_size: int, tries: int, recurse_tries: int,
                  local_retries: int, local_fallback_retries: int,
                  recurse_to_leaf: bool, vary_r: int, stable: int,
                  out2: Optional[List[int]], parent_r: int) -> int:
    """Depth-first descent with retries (mapper.c:414-593)."""
    count = out_size
    rep = 0 if stable else outpos
    item = 0
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_ = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                r = rep + parent_r + ftotal
                if in_.size == 0:
                    reject = True
                else:
                    if (local_fallback_retries > 0
                            and flocal >= (in_.size >> 1)
                            and flocal > local_fallback_retries):
                        item = bucket_perm_choose(in_, x, r)
                    else:
                        item = crush_bucket_choose(in_, x, r)
                    if item >= map_.max_devices:
                        skip_rep = True
                        break
                    if item < 0:
                        sub = map_.bucket(item)
                        itemtype = sub.type if sub else -1
                    else:
                        itemtype = 0
                    if itemtype != type_:
                        if item >= 0 or map_.bucket(item) is None:
                            skip_rep = True
                            break
                        in_ = map_.bucket(item)
                        retry_bucket = True
                        continue
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            got = choose_firstn(
                                map_, map_.bucket(item), weight, x,
                                1 if stable else outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0,
                                local_retries, local_fallback_retries,
                                False, vary_r, stable, None, sub_r)
                            if got <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject:
                        if itemtype == 0:
                            reject = is_out(map_, weight, item, x)
                        else:
                            reject = False
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
        if not skip_rep:
            out[outpos] = item
            outpos += 1
            count -= 1
        rep += 1
    return outpos


def choose_indep(map_: CrushMap, bucket: Bucket, weight: Sequence[int],
                 x: int, left: int, numrep: int, type_: int, out: List[int],
                 outpos: int, tries: int, recurse_tries: int,
                 recurse_to_leaf: bool, out2: Optional[List[int]],
                 parent_r: int) -> None:
    """Breadth-first positionally-stable descent for EC (mapper.c:600-780)."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_ = bucket
            while True:
                r = rep + parent_r
                if (in_.alg == BUCKET_UNIFORM
                        and in_.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_.size == 0:
                    break
                item = crush_bucket_choose(in_, x, r)
                if item >= map_.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                if item < 0:
                    sub = map_.bucket(item)
                    itemtype = sub.type if sub else -1
                else:
                    itemtype = 0
                if itemtype != type_:
                    if item >= 0 or map_.bucket(item) is None:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_ = map_.bucket(item)
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        choose_indep(map_, map_.bucket(item), weight, x, 1,
                                     numrep, 0, out2, rep, recurse_tries, 0,
                                     False, None, r)
                        if out2[rep] == CRUSH_ITEM_NONE:
                            break
                    else:
                        out2[rep] = item
                if itemtype == 0 and is_out(map_, weight, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


# -- rule VM -----------------------------------------------------------------

def do_rule(map_: CrushMap, ruleno: int, x: int, result_max: int,
            weight: Sequence[int]) -> List[int]:
    """Execute one placement rule (mapper.c:793-999); returns result vector."""
    # reference casts to __u32: negative ruleno is rejected, never indexed
    if not (0 <= ruleno < len(map_.rules)) or map_.rules[ruleno] is None:
        return []
    # reference callers always pass result_max >= 1; its scratch math would
    # overflow on 0, we just answer "no mapping"
    if result_max <= 0:
        return []
    rule = map_.rules[ruleno]
    t = map_.tunables
    choose_tries = t.choose_total_tries + 1
    choose_leaf_tries = 0
    local_retries = t.choose_local_tries
    local_fallback_retries = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    result: List[int] = []
    w: List[int] = [0] * result_max
    o: List[int] = [0] * result_max
    c: List[int] = [0] * result_max
    wsize = 0

    for step in rule.steps:
        firstn = False
        if step.op == RULE_TAKE:
            a1 = step.arg1
            if (0 <= a1 < map_.max_devices) or (
                    a1 < 0 and map_.bucket(a1) is not None):
                w[0] = a1
                wsize = 1
        elif step.op == RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif step.op == RULE_SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                local_retries = step.arg1
        elif step.op == RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                local_fallback_retries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif step.op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSE_FIRSTN,
                         RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_INDEP):
            if wsize == 0:
                continue
            firstn = step.op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSE_FIRSTN)
            recurse_to_leaf = step.op in (RULE_CHOOSELEAF_FIRSTN,
                                          RULE_CHOOSELEAF_INDEP)
            osize = 0
            for i in range(wsize):
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                bucket = map_.bucket(w[i]) if w[i] < 0 else None
                if bucket is None:
                    continue
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    # out/out2 views start at osize like the C pointer math
                    sub_out = [0] * (result_max - osize)
                    sub_out2 = [0] * (result_max - osize)
                    got = choose_firstn(
                        map_, bucket, weight, x, numrep, step.arg2,
                        sub_out, 0, result_max - osize,
                        choose_tries, recurse_tries,
                        local_retries, local_fallback_retries,
                        recurse_to_leaf, vary_r, stable, sub_out2, 0)
                    o[osize:osize + got] = sub_out[:got]
                    c[osize:osize + got] = sub_out2[:got]
                    osize += got
                else:
                    out_size = min(numrep, result_max - osize)
                    sub_out = [0] * out_size
                    sub_out2 = [0] * out_size
                    choose_indep(
                        map_, bucket, weight, x, out_size, numrep,
                        step.arg2, sub_out, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, sub_out2, 0)
                    o[osize:osize + out_size] = sub_out
                    c[osize:osize + out_size] = sub_out2
                    osize += out_size
            if recurse_to_leaf:
                o[:osize] = c[:osize]
            w, o = o, w
            wsize = osize
        elif step.op == RULE_EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
    return result
