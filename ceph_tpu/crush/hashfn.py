"""Robert Jenkins 32-bit integer mix hash, CRUSH flavor.

Reference parity: crush/hash.c:12-90 (crush_hashmix / crush_hash32_N,
seed 1315423911).  Two implementations share one algorithm description:
a scalar python-int version (host mapper) and a numpy-vectorized version
(batch verification + table generation); the batched JAX version
(ceph_tpu/ops/crush_kernel.py) is required to stay bit-equal to these.
"""

from __future__ import annotations

import numpy as np

M32 = 0xFFFFFFFF
HASH_SEED = 1315423911


def _mix(a: int, b: int, c: int):
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 13
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 8)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 13
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 12
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 16)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 5
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 3
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 10)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 15
    return a, b, c


def hash32(a: int) -> int:
    a &= M32
    h = HASH_SEED ^ a
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def hash32_2(a: int, b: int) -> int:
    a &= M32; b &= M32
    h = HASH_SEED ^ a ^ b
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash32_3(a: int, b: int, c: int) -> int:
    a &= M32; b &= M32; c &= M32
    h = HASH_SEED ^ a ^ b ^ c
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32
    h = HASH_SEED ^ a ^ b ^ c ^ d
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32; e &= M32
    h = HASH_SEED ^ a ^ b ^ c ^ d ^ e
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


# ---------------------------------------------------------------------------
# numpy-vectorized (arrays of uint32, broadcasting)

def _np_mix(a, b, c):
    a = (a - b); a = (a - c); a = a ^ (c >> np.uint32(13))
    b = (b - c); b = (b - a); b = b ^ (a << np.uint32(8))
    c = (c - a); c = (c - b); c = c ^ (b >> np.uint32(13))
    a = (a - b); a = (a - c); a = a ^ (c >> np.uint32(12))
    b = (b - c); b = (b - a); b = b ^ (a << np.uint32(16))
    c = (c - a); c = (c - b); c = c ^ (b >> np.uint32(5))
    a = (a - b); a = (a - c); a = a ^ (c >> np.uint32(3))
    b = (b - c); b = (b - a); b = b ^ (a << np.uint32(10))
    c = (c - a); c = (c - b); c = c ^ (b >> np.uint32(15))
    return a, b, c


def np_hash32_3(a, b, c):
    a = np.asarray(a, np.uint32); b = np.asarray(b, np.uint32)
    c = np.asarray(c, np.uint32)
    h = np.uint32(HASH_SEED) ^ a ^ b ^ c
    x = np.full_like(h, 231232); y = np.full_like(h, 1232)
    a, b, h = _np_mix(a, b, h)
    c, x, h = _np_mix(c, x, h)
    y, a, h = _np_mix(y, a, h)
    b, x, h = _np_mix(b, x, h)
    y, c, h = _np_mix(y, c, h)
    return h


def np_hash32_2(a, b):
    a = np.asarray(a, np.uint32); b = np.asarray(b, np.uint32)
    h = np.uint32(HASH_SEED) ^ a ^ b
    x = np.full_like(h, 231232); y = np.full_like(h, 1232)
    a, b, h = _np_mix(a, b, h)
    x, a, h = _np_mix(x, a, h)
    b, y, h = _np_mix(b, y, h)
    return h


def ceph_str_hash_rjenkins(data: bytes) -> int:
    """Jenkins string hash for object-name -> placement seed.

    Reference parity: common/ceph_hash.cc ceph_str_hash_rjenkins — golden
    ratio init, 12-byte mixing blocks, length folded into c.  Bit-exact.
    """
    length = len(data)
    a = b = 0x9E3779B9
    c = 0
    k = 0
    rem = length
    while rem >= 12:
        a = (a + (data[k] | data[k+1] << 8 | data[k+2] << 16
                  | data[k+3] << 24)) & M32
        b = (b + (data[k+4] | data[k+5] << 8 | data[k+6] << 16
                  | data[k+7] << 24)) & M32
        c = (c + (data[k+8] | data[k+9] << 8 | data[k+10] << 16
                  | data[k+11] << 24)) & M32
        a, b, c = _mix(a, b, c)
        k += 12
        rem -= 12
    c = (c + length) & M32
    # trailing bytes; first byte of c is reserved for the length
    for idx, sh in ((10, 24), (9, 16), (8, 8)):
        if rem >= idx + 1:
            c = (c + (data[k + idx] << sh)) & M32
    for idx, sh in ((7, 24), (6, 16), (5, 8), (4, 0)):
        if rem >= idx + 1:
            b = (b + (data[k + idx] << sh)) & M32
    for idx, sh in ((3, 24), (2, 16), (1, 8), (0, 0)):
        if rem >= idx + 1:
            a = (a + (data[k + idx] << sh)) & M32
    a, b, c = _mix(a, b, c)
    return c
