"""Object/collection identity types for the store layer.

Reference parity: hobject_t/ghobject_t and coll_t (osd/osd_types.h,
common/hobject.h) — objects are addressed by (pool, namespace, name, key,
snap, hash) and live in collections (PGs or meta).  Redesigned: plain
frozen dataclass-style Encodables; the 32-bit placement hash is computed
once from (key or name) with the same rjenkins string hash the placement
layer uses, so store-level ordering matches placement ordering.
"""

from __future__ import annotations

from ceph_tpu.common.encoding import Decoder, Encodable, Encoder
from ceph_tpu.crush.hashfn import ceph_str_hash_rjenkins

# snapid sentinels (include/rados.h)
SNAP_HEAD = 2**64 - 2      # CEPH_NOSNAP: the writable head object
SNAP_DIR = 2**64 - 1       # CEPH_SNAPDIR: virtual snapshot dir


class ObjectId(Encodable):
    """ghobject_t analog: fully-qualified object name.

    ``hash32`` drives PG placement and collection sort order (reference
    sorts objects bitwise-reversed by hash for split/backfill scans).
    """

    __slots__ = ("name", "key", "namespace", "pool", "snap", "hash32",
                 "shard", "generation")

    def __init__(self, name: str, key: str = "", namespace: str = "",
                 pool: int = -1, snap: int = SNAP_HEAD,
                 shard: int = -1, generation: int = 0):
        self.name = name
        self.key = key
        self.namespace = namespace
        self.pool = pool
        self.snap = snap
        self.shard = shard            # EC shard id, -1 = NO_SHARD
        self.generation = generation  # EC rollback generation
        self.hash32 = ceph_str_hash_rjenkins(
            (key or name).encode("utf-8")) & 0xFFFFFFFF

    # bitwise-reversed hash: reference's collection sort key
    # (hobject_t::get_bitwise_key, common/hobject.h)
    @property
    def reversed_hash(self) -> int:
        h, r = self.hash32, 0
        for _ in range(32):
            r = (r << 1) | (h & 1)
            h >>= 1
        return r

    def sort_key(self):
        # total order over ALL identity fields (ghobject_t comparison:
        # shard, pool, bitwise hash, nspace, key, name, snap, generation) —
        # two unequal ids must never compare equal, or listing pagination
        # with a start cursor would skip one of them.
        return (self.shard, self.pool, self.reversed_hash, self.namespace,
                self.key or self.name, self.name, self.snap,
                self.generation)

    def with_snap(self, snap: int) -> "ObjectId":
        return ObjectId(self.name, self.key, self.namespace, self.pool,
                        snap, self.shard, self.generation)

    def is_head(self) -> bool:
        return self.snap == SNAP_HEAD

    def encode_payload(self, enc: Encoder) -> None:
        enc.string(self.name).string(self.key).string(self.namespace)
        enc.s64(self.pool).u64(self.snap)
        enc.s32(self.shard).u64(self.generation)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "ObjectId":
        name, key, ns = dec.string(), dec.string(), dec.string()
        pool, snap = dec.s64(), dec.u64()
        shard, gen = dec.s32(), dec.u64()
        return cls(name, key, ns, pool, snap, shard, gen)

    def __hash__(self):
        return hash((self.name, self.key, self.namespace, self.pool,
                     self.snap, self.shard, self.generation))

    def __eq__(self, other):
        return (isinstance(other, ObjectId)
                and self.name == other.name and self.key == other.key
                and self.namespace == other.namespace
                and self.pool == other.pool and self.snap == other.snap
                and self.shard == other.shard
                and self.generation == other.generation)

    def __lt__(self, other):
        return self.sort_key() < other.sort_key()

    def __repr__(self):
        s = f"{self.pool}:{self.namespace}/{self.name}"
        if self.snap != SNAP_HEAD:
            s += f"@{self.snap}"
        if self.shard >= 0:
            s += f"(s{self.shard})"
        return s


class CollectionId(Encodable):
    """coll_t analog: either a PG collection ("<pool>.<pgid>s<shard>") or a
    named meta collection."""

    __slots__ = ("name",)

    TYPE_META = 0
    TYPE_PG = 1

    def __init__(self, name: str):
        self.name = name

    @classmethod
    def meta(cls) -> "CollectionId":
        return cls("meta")

    @classmethod
    def pg(cls, pool: int, seed: int, shard: int = -1) -> "CollectionId":
        s = f"{pool}.{seed:x}"
        if shard >= 0:
            s += f"s{shard}"
        return cls(s + "_head")

    def is_pg(self) -> bool:
        return self.name.endswith("_head")

    def encode_payload(self, enc: Encoder) -> None:
        enc.string(self.name)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "CollectionId":
        return cls(dec.string())

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        return isinstance(other, CollectionId) and self.name == other.name

    def __lt__(self, other):
        return self.name < other.name

    def __repr__(self):
        return f"coll({self.name})"
