"""KStore: everything-in-KV object store.

Reference parity: os/kstore/KStore.cc (the experimental store that puts
object data, attrs, and omap all in the key-value database — no
filesystem data path; durability and atomicity come entirely from the
KV WAL) and its stripe layout (kstore_default_stripe_size).

Redesign notes:
  * Rides KeyValueDB (store/kv.py): MemDB for tests, FileDB for a
    durable WAL + snapshot — one KVTransaction per ObjectStore
    Transaction keeps the reference's all-or-nothing commit rule
    without a separate journal.
  * Object data is striped into fixed-size chunk records so a small
    overwrite WALs only the touched chunks, not the whole object
    (KStore.cc _do_write stripe loop).
  * Keys are the Encodable byte forms of CollectionId/ObjectId (self-
    delimiting: the encoding starts with its own length, so no oid key
    can be a proper prefix of another); chunk numbers append big-endian
    so a data scan walks a stripe in order.
  * An in-memory (cid -> {oid bytes -> ObjectId}) registry, rebuilt at
    mount from the meta rows, serves collection_list in ghobject sort
    order — the KV itself has no need to sort by hobject like the
    reference's rocksdb comparator does.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.store.kv import FileDB, KeyValueDB, KVTransaction, MemDB
from ceph_tpu.store.objectstore import (NoSuchCollection, NoSuchObject,
                                        ObjectStore, StoreError,
                                        Transaction, TxOp,
                                        OP_NOP, OP_TOUCH, OP_WRITE,
                                        OP_ZERO, OP_TRUNCATE, OP_REMOVE,
                                        OP_SETATTR, OP_SETATTRS,
                                        OP_RMATTR, OP_CLONE,
                                        OP_CLONERANGE2, OP_MKCOLL,
                                        OP_RMCOLL, OP_OMAP_CLEAR,
                                        OP_OMAP_SETKEYS, OP_OMAP_RMKEYS,
                                        OP_OMAP_SETHEADER,
                                        OP_OMAP_RMKEYRANGE,
                                        OP_COLL_MOVE_RENAME,
                                        OP_TRY_RENAME)
from ceph_tpu.store.types import CollectionId, ObjectId

#: column prefixes (KStore.cc PREFIX_DATA/PREFIX_OMAP/...)
P_COLL = "C"       # cid -> b""
P_META = "M"       # cid+oid -> onode (size, xattrs, omap header)
P_DATA = "D"       # cid+oid+chunk#BE -> chunk bytes
P_OMAP = "O"       # cid+oid+okey -> value

STRIPE = 64 * 1024


class _Onode:
    """Per-object metadata row (KStore.cc kstore_onode_t)."""

    __slots__ = ("size", "xattrs", "omap_header")

    def __init__(self, size: int = 0,
                 xattrs: Optional[Dict[str, bytes]] = None,
                 omap_header: bytes = b""):
        self.size = size
        self.xattrs = xattrs if xattrs is not None else {}
        self.omap_header = omap_header

    def to_bytes(self) -> bytes:
        enc = Encoder()
        enc.u64(self.size).bytes_(self.omap_header)
        enc.map_(self.xattrs, lambda e, k: e.string(k),
                 lambda e, v: e.bytes_(v))
        return bytes(enc.buf)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "_Onode":
        dec = Decoder(raw)
        size, header = dec.u64(), dec.bytes_()
        xattrs = dec.map_(lambda d: d.string(), lambda d: d.bytes_())
        return cls(size, xattrs, header)


class _Txn:
    """A KVTransaction plus a dict overlay giving O(1) read-your-writes
    inside one ObjectStore transaction (clone-after-write must see the
    write; same pattern as blockstore's overlay)."""

    __slots__ = ("db", "kvt", "overlay")

    def __init__(self, db: KeyValueDB):
        self.db = db
        self.kvt = db.create_transaction()
        # (prefix, key) -> value | None (None = pending remove)
        self.overlay: Dict[Tuple[str, bytes], Optional[bytes]] = {}

    def set(self, prefix: str, key: bytes, value: bytes) -> None:
        self.kvt.set(prefix, key, value)
        self.overlay[(prefix, key)] = bytes(value)

    def rm(self, prefix: str, key: bytes) -> None:
        self.kvt.rmkey(prefix, key)
        self.overlay[(prefix, key)] = None

    def get(self, prefix: str, key: bytes) -> Optional[bytes]:
        if (prefix, key) in self.overlay:
            return self.overlay[(prefix, key)]
        return self.db.get(prefix, key)

    def scan(self, prefix: str, keyprefix: bytes) -> List[bytes]:
        """Keys under (prefix, keyprefix*) as visible inside the txn:
        an ordered range scan plus pending sets minus removes."""
        keys = set()
        for k, _ in self.db.iterate(prefix, start=keyprefix):
            if not k.startswith(keyprefix):
                break                   # ordered: past the prefix range
            keys.add(k)
        for (p, k), v in self.overlay.items():
            if p != prefix or not k.startswith(keyprefix):
                continue
            if v is None:
                keys.discard(k)
            else:
                keys.add(k)
        return sorted(keys)


class KStore(ObjectStore):
    def __init__(self, path: str = ""):
        super().__init__(path)
        self.db: Optional[KeyValueDB] = None
        # cid -> {oid key bytes -> ObjectId}
        self._objs: Dict[bytes, Dict[bytes, ObjectId]] = {}
        self._committer = None

    # ------------------------------------------------------------ keys
    @staticmethod
    def _ckey(cid: CollectionId) -> bytes:
        return cid.to_bytes()

    @staticmethod
    def _okey(cid: CollectionId, oid: ObjectId) -> bytes:
        return cid.to_bytes() + oid.to_bytes()

    @staticmethod
    def _dkey(okey: bytes, chunk: int) -> bytes:
        return okey + struct.pack(">Q", chunk)

    # ------------------------------------------------------- lifecycle
    def mkfs(self) -> None:
        if self.path:
            FileDB(self.path).close()

    def mount(self) -> None:
        self.db = FileDB(self.path) if self.path else MemDB()
        self._objs = {ck: {} for ck in self.db.keys(P_COLL)}
        for mk in self.db.keys(P_META):
            # cid.to_bytes() is self-delimiting: v u8, compat u8, then
            # a u32 payload length — so 6 + len delimits the cid record
            clen = 6 + struct.unpack("<I", mk[2:6])[0]
            ck, ok = mk[:clen], mk[clen:]
            oid = ObjectId.from_bytes(ok)
            self._objs.setdefault(ck, {})[ok] = oid
        # group commit: transactions apply to memory inline; the commit
        # thread makes the whole backlog durable with ONE WAL fsync
        # (a MemDB substrate has no deferral — log_deferred is a no-op
        # and the thread only groups/orders the commit callbacks)
        from ceph_tpu.store.commit import KVSyncThread
        # static gather base for the barrier-cost auto-tuner (see
        # BlockStore.mount): effective window = ewma(WAL fsync cost)
        # clamped to [0, 4x this]
        self._committer = KVSyncThread("kstore_commit",
                                       kv_sync=self.db.log_deferred,
                                       gather_window=0.001)
        self._committer.start()

    def umount(self) -> None:
        if self._committer is not None:
            self._committer.stop()
            self._committer = None
        if self.db is not None:
            self.db.close()
            self.db = None

    # ---------------------------------------------------------- writes
    def queue_transactions(self, txns: List[Transaction],
                           on_applied=None, on_commit=None) -> None:
        if self._committer is not None and self._committer.dead:
            # dead commit thread = WAL never syncs, acks never fire
            raise StoreError("kstore commit thread is dead")
        tx = _Txn(self.db)
        for txn in txns:
            for op in txn.ops:
                self._apply_op(tx, op)
        # memory-apply now (read-your-writes); WAL durability rides the
        # commit thread so concurrent batches share one fsync
        seq = self.db.submit_deferred(tx.kvt)
        self.applied_seq += 1
        if on_applied:
            on_applied()
        if self._committer is not None:
            self._committer.submit(seq=seq, on_commit=on_commit)
        elif on_commit:
            on_commit()

    def sync(self) -> None:
        if self._committer is not None:
            self._committer.flush()

    def commit_counters(self) -> Dict[str, float]:
        return self._committer.counters() if self._committer else {}

    def _onode(self, tx: _Txn, okey: bytes,
               create: bool) -> Optional[_Onode]:
        raw = tx.get(P_META, okey)
        if raw is not None:
            return _Onode.from_bytes(raw)
        return _Onode() if create else None

    def _put_onode(self, tx: _Txn, cid: CollectionId,
                   oid: ObjectId, on: _Onode) -> None:
        okey = self._okey(cid, oid)
        tx.set(P_META, okey, on.to_bytes())
        self._objs.setdefault(self._ckey(cid), {})[oid.to_bytes()] = oid

    def _read_chunks(self, tx: _Txn, okey: bytes, size: int,
                     off: int, length: int) -> bytes:
        if length < 0 or off + length > size:
            length = max(0, size - off)
        out = bytearray(length)
        pos = off
        while pos < off + length:
            cno, coff = divmod(pos, STRIPE)
            chunk = tx.get(P_DATA, self._dkey(okey, cno)) or b""
            take = min(STRIPE - coff, off + length - pos)
            piece = chunk[coff:coff + take]
            out[pos - off:pos - off + len(piece)] = piece
            pos += take
        return bytes(out)

    def _write_chunks(self, tx: _Txn, okey: bytes, off: int,
                      data: bytes) -> None:
        pos = 0
        while pos < len(data):
            cno, coff = divmod(off + pos, STRIPE)
            take = min(STRIPE - coff, len(data) - pos)
            if coff == 0 and take == STRIPE:
                chunk = data[pos:pos + STRIPE]
            else:
                chunk = bytearray(
                    tx.get(P_DATA, self._dkey(okey, cno))
                    or b"")
                if len(chunk) < coff + take:
                    chunk.extend(b"\x00" * (coff + take - len(chunk)))
                chunk[coff:coff + take] = data[pos:pos + take]
                chunk = bytes(chunk)
            tx.set(P_DATA, self._dkey(okey, cno), chunk)
            pos += take

    def _drop_object(self, tx: _Txn, cid: CollectionId,
                     oid: ObjectId, on: Optional[_Onode]) -> None:
        okey = self._okey(cid, oid)
        if on is not None:
            for cno in range((on.size + STRIPE - 1) // STRIPE):
                tx.rm(P_DATA, self._dkey(okey, cno))
        for k in tx.scan(P_OMAP, okey):
            tx.rm(P_OMAP, k)
        tx.rm(P_META, okey)
        c = self._objs.get(self._ckey(cid))
        if c is not None:
            c.pop(oid.to_bytes(), None)

    def _apply_op(self, tx: _Txn, op: TxOp) -> None:
        code = op.op
        if code == OP_NOP:
            return
        if code == OP_MKCOLL:
            tx.set(P_COLL, self._ckey(op.cid), b"")
            self._objs.setdefault(self._ckey(op.cid), {})
            return
        if code == OP_RMCOLL:
            ck = self._ckey(op.cid)
            for oid in list(self._objs.get(ck, {}).values()):
                self._drop_object(tx, op.cid, oid,
                                  self._onode(
                                      tx, self._okey(op.cid, oid),
                                      create=False))
            tx.rm(P_COLL, ck)
            self._objs.pop(ck, None)
            return
        okey = self._okey(op.cid, op.oid)
        if code == OP_TOUCH:
            self._put_onode(tx, op.cid, op.oid,
                            self._onode(tx, okey, create=True))
            return
        if code in (OP_WRITE, OP_ZERO):
            data = op.data if code == OP_WRITE else b"\x00" * op.length
            on = self._onode(tx, okey, create=True)
            self._write_chunks(tx, okey, op.off, data)
            on.size = max(on.size, op.off + len(data))
            self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_TRUNCATE:
            on = self._onode(tx, okey, create=True)
            size = op.off
            if size < on.size:
                lo = (size + STRIPE - 1) // STRIPE
                for cno in range(lo, (on.size + STRIPE - 1) // STRIPE):
                    tx.rm(P_DATA, self._dkey(okey, cno))
                if size % STRIPE:
                    cno = size // STRIPE
                    chunk = (tx.get(P_DATA,
                                    self._dkey(okey, cno)) or b"")
                    tx.set(P_DATA, self._dkey(okey, cno),
                           chunk[:size % STRIPE])
            on.size = size
            self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_REMOVE:
            self._drop_object(tx, op.cid, op.oid,
                              self._onode(tx, okey, create=False))
            return
        if code == OP_SETATTR:
            on = self._onode(tx, okey, create=True)
            on.xattrs[op.name] = op.data
            self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_SETATTRS:
            on = self._onode(tx, okey, create=True)
            for k, v in op.kv.items():
                on.xattrs[k.decode("utf-8")] = v
            self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_RMATTR:
            on = self._onode(tx, okey, create=False)
            if on is not None:
                on.xattrs.pop(op.name, None)
                self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_CLONE:
            on = self._onode(tx, okey, create=False)
            if on is None:
                return
            dst = self._okey(op.cid, op.oid2)
            self._drop_object(tx, op.cid, op.oid2,
                              self._onode(tx, dst, create=False))
            data = self._read_chunks(tx, okey, on.size, 0, -1)
            self._write_chunks(tx, dst, 0, data)
            for k in tx.scan(P_OMAP, okey):
                tx.set(P_OMAP, dst + k[len(okey):], tx.get(P_OMAP, k))
            self._put_onode(tx, op.cid, op.oid2,
                            _Onode(on.size, dict(on.xattrs),
                                   on.omap_header))
            return
        if code == OP_CLONERANGE2:
            on = self._onode(tx, okey, create=False)
            if on is None:
                return
            data = self._read_chunks(tx, okey, on.size, op.off,
                                     op.length)
            dst_oid = op.oid2
            dkey = self._okey(op.cid, dst_oid)
            don = self._onode(tx, dkey, create=True)
            self._write_chunks(tx, dkey, op.dest_off, data)
            don.size = max(don.size, op.dest_off + len(data))
            self._put_onode(tx, op.cid, dst_oid, don)
            return
        if code in (OP_COLL_MOVE_RENAME, OP_TRY_RENAME):
            on = self._onode(tx, okey, create=False)
            if on is None:
                return
            dst_cid = op.cid2 if code == OP_COLL_MOVE_RENAME else op.cid
            dkey0 = self._okey(dst_cid, op.oid2)
            self._drop_object(tx, dst_cid, op.oid2,
                              self._onode(tx, dkey0, create=False))
            data = self._read_chunks(tx, okey, on.size, 0, -1)
            omap = {k[len(okey):]: tx.get(P_OMAP, k)
                    for k in tx.scan(P_OMAP, okey)}
            self._drop_object(tx, op.cid, op.oid, on)
            dkey = self._okey(dst_cid, op.oid2)
            self._write_chunks(tx, dkey, 0, data)
            for k, v in omap.items():
                tx.set(P_OMAP, dkey + k, v)
            self._put_onode(tx, dst_cid, op.oid2, on)
            return
        if code == OP_OMAP_CLEAR:
            on = self._onode(tx, okey, create=False)
            if on is not None:
                for k in tx.scan(P_OMAP, okey):
                    tx.rm(P_OMAP, k)
                on.omap_header = b""
                self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_OMAP_SETKEYS:
            on = self._onode(tx, okey, create=True)
            for k, v in op.kv.items():
                tx.set(P_OMAP, okey + k, v)
            self._put_onode(tx, op.cid, op.oid, on)
            return
        if code == OP_OMAP_RMKEYS:
            for k in op.keys:
                tx.rm(P_OMAP, okey + k)
            return
        if code == OP_OMAP_RMKEYRANGE:
            first, last = op.keys
            for k in tx.scan(P_OMAP, okey):
                if first <= k[len(okey):] < last:
                    tx.rm(P_OMAP, k)
            return
        if code == OP_OMAP_SETHEADER:
            on = self._onode(tx, okey, create=True)
            on.omap_header = op.data
            self._put_onode(tx, op.cid, op.oid, on)
            return
        # unknown op code: skip (forward compat) — never poison replay

    # ----------------------------------------------------------- reads
    def _require(self, cid: CollectionId, oid: ObjectId) -> _Onode:
        ck = self._ckey(cid)
        if ck not in self._objs:
            raise NoSuchCollection(str(cid))
        raw = self.db.get(P_META, self._okey(cid, oid))
        if raw is None:
            raise NoSuchObject(str(oid))
        return _Onode.from_bytes(raw)

    def read(self, cid, oid, off: int = 0, length: int = -1) -> bytes:
        on = self._require(cid, oid)
        return self._read_chunks(_Txn(self.db), self._okey(cid, oid),
                                 on.size, off, length)

    def stat(self, cid, oid) -> Dict[str, int]:
        return {"size": self._require(cid, oid).size}

    def getattr(self, cid, oid, name: str) -> bytes:
        on = self._require(cid, oid)
        if name not in on.xattrs:
            raise NoSuchObject(f"{oid} xattr {name}")
        return on.xattrs[name]

    def getattrs(self, cid, oid) -> Dict[str, bytes]:
        return dict(self._require(cid, oid).xattrs)

    def omap_get(self, cid, oid) -> Tuple[bytes, Dict[bytes, bytes]]:
        on = self._require(cid, oid)
        okey = self._okey(cid, oid)
        omap = {}
        for k, v in self.db.iterate(P_OMAP, start=okey):
            if not k.startswith(okey):
                break
            omap[k[len(okey):]] = v
        return on.omap_header, omap

    def omap_get_values(self, cid, oid, keys) -> Dict[bytes, bytes]:
        okey = self._okey(cid, oid)
        self._require(cid, oid)
        out = {}
        for k in keys:
            v = self.db.get(P_OMAP, okey + k)
            if v is not None:
                out[k] = v
        return out

    def omap_get_header(self, cid, oid) -> bytes:
        return self._require(cid, oid).omap_header

    def list_collections(self) -> List[CollectionId]:
        return [CollectionId.from_bytes(ck) for ck in self._objs]

    def collection_exists(self, cid) -> bool:
        return self._ckey(cid) in self._objs

    def collection_list(self, cid, start: Optional[ObjectId] = None,
                        max_count: int = 2**31) -> List[ObjectId]:
        ck = self._ckey(cid)
        if ck not in self._objs:
            raise NoSuchCollection(str(cid))
        oids = sorted(self._objs[ck].values(),
                      key=lambda o: o.sort_key())
        if start is not None:
            oids = [o for o in oids if o.sort_key() > start.sort_key()]
        return oids[:max_count]
