"""Local persistence layer: ObjectStore backends + KeyValueDB.

Reference parity: src/os/ (ObjectStore/Transaction, MemStore, FileStore
journal) and src/kv/ (KeyValueDB over leveldb/rocksdb).
"""

from ceph_tpu.store.kv import FileDB, KeyValueDB, KVTransaction, MemDB
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.filestore import FileStore
from ceph_tpu.store.objectstore import (
    NoSuchCollection, NoSuchObject, ObjectStore, StoreError, Transaction,
)
from ceph_tpu.store.types import SNAP_DIR, SNAP_HEAD, CollectionId, ObjectId

__all__ = [
    "CollectionId", "FileDB", "FileStore", "KVTransaction", "KeyValueDB",
    "MemDB", "MemStore", "NoSuchCollection", "NoSuchObject", "ObjectId",
    "ObjectStore", "SNAP_DIR", "SNAP_HEAD", "StoreError", "Transaction",
]
