"""KeyValueDB: transactional ordered key-value store abstraction.

Reference parity: kv/KeyValueDB.h (abstract kv with batched transactions and
prefix iterators; backends LevelDBStore/RocksDBStore/MemDB).  Redesigned with
two backends, no external deps:

- MemDB      — sorted in-memory map (tests, MemStore omap).
- FileDB     — log-structured file backend: append-only WAL of committed
               batches + periodic compacted snapshot, replayed on open.
               This is the durability substrate for the monitor store and
               FileStore metadata, playing the role rocksdb plays in the
               reference (kv/RocksDBStore.cc) with a deliberately simple
               single-writer design.

Keys are namespaced by a string prefix like the reference
(``prefix`` + 0x00 + key ordering), values are bytes.
"""

from __future__ import annotations

import os
import struct
import threading
from bisect import bisect_left
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ceph_tpu.common.lockdep import make_thread_lock
from ceph_tpu.store.wal import WriteAheadLog, atomic_snapshot

_SEP = b"\x00"


def _full_key(prefix: str, key: bytes) -> bytes:
    return prefix.encode("utf-8") + _SEP + key


class KVTransaction:
    """Batched mutations applied atomically by ``KeyValueDB.submit``."""

    __slots__ = ("ops",)

    SET, RM, RM_PREFIX = 0, 1, 2

    def __init__(self):
        self.ops: List[Tuple[int, bytes, bytes]] = []

    def set(self, prefix: str, key, value: bytes) -> "KVTransaction":
        if isinstance(key, str):
            key = key.encode("utf-8")
        self.ops.append((self.SET, _full_key(prefix, key), bytes(value)))
        return self

    def rmkey(self, prefix: str, key) -> "KVTransaction":
        if isinstance(key, str):
            key = key.encode("utf-8")
        self.ops.append((self.RM, _full_key(prefix, key), b""))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append((self.RM_PREFIX, prefix.encode("utf-8") + _SEP, b""))
        return self

    def encode(self) -> bytes:
        out = bytearray(struct.pack("<I", len(self.ops)))
        for op, k, v in self.ops:
            out += struct.pack("<BI", op, len(k)) + k
            out += struct.pack("<I", len(v)) + v
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "KVTransaction":
        t = cls()
        off = 4
        (n,) = struct.unpack_from("<I", data, 0)
        for _ in range(n):
            op, klen = struct.unpack_from("<BI", data, off)
            off += 5
            k = data[off:off + klen]; off += klen
            (vlen,) = struct.unpack_from("<I", data, off)
            off += 4
            v = data[off:off + vlen]; off += vlen
            t.ops.append((op, k, v))
        return t


class KeyValueDB:
    """Abstract ordered kv store."""

    #: True when submit_deferred really defers durability (FileDB);
    #: backends without a durability cost just apply immediately
    supports_deferred = False

    def create_transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        raise NotImplementedError

    def submit_deferred(self, txn: KVTransaction) -> int:
        """Apply txn to the visible (in-memory) state NOW; its
        durability is deferred until ``log_deferred`` covers the
        returned seq.  Default: no durability substrate — plain
        submit."""
        self.submit(txn, sync=True)
        return 0

    def log_deferred(self, upto_seq: int) -> int:
        """Make every deferred record with seq <= upto_seq durable in
        one group (single WAL fsync).  Returns the record count."""
        return 0

    def get(self, prefix: str, key) -> Optional[bytes]:
        raise NotImplementedError

    def iterate(self, prefix: str, start=b"", end=None
                ) -> Iterator[Tuple[bytes, bytes]]:
        """Yield (key, value) within prefix, key >= start (< end if given),
        in key order."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    # conveniences
    def exists(self, prefix: str, key) -> bool:
        return self.get(prefix, key) is not None

    def keys(self, prefix: str) -> List[bytes]:
        return [k for k, _ in self.iterate(prefix)]

    def iterate_all(self) -> Iterator[Tuple[str, bytes, bytes]]:
        """Yield (prefix, key, value) over the whole keyspace — offline
        tooling surface (kvstore tool list/stats)."""
        raise NotImplementedError


class MemDB(KeyValueDB):
    """Sorted in-memory backend (reference kv/MemDB analog)."""

    def __init__(self):
        self._keys: List[bytes] = []          # sorted full keys
        self._map: Dict[bytes, bytes] = {}

    def _insert(self, k: bytes, v: bytes):
        if k not in self._map:
            self._keys.insert(bisect_left(self._keys, k), k)
        self._map[k] = v

    def _remove(self, k: bytes):
        if k in self._map:
            del self._map[k]
            i = bisect_left(self._keys, k)
            del self._keys[i]

    def _remove_prefix(self, p: bytes):
        lo = bisect_left(self._keys, p)
        hi = lo
        while hi < len(self._keys) and self._keys[hi].startswith(p):
            del self._map[self._keys[hi]]
            hi += 1
        del self._keys[lo:hi]

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        for op, k, v in txn.ops:
            if op == KVTransaction.SET:
                self._insert(k, v)
            elif op == KVTransaction.RM:
                self._remove(k)
            else:
                self._remove_prefix(k)

    def get(self, prefix: str, key) -> Optional[bytes]:
        if isinstance(key, str):
            key = key.encode("utf-8")
        return self._map.get(_full_key(prefix, key))

    def iterate(self, prefix: str, start=b"", end=None):
        if isinstance(start, str):
            start = start.encode("utf-8")
        if isinstance(end, str):
            end = end.encode("utf-8")
        p = prefix.encode("utf-8") + _SEP
        lo = bisect_left(self._keys, p + start)
        for i in range(lo, len(self._keys)):   # no tail copy
            k = self._keys[i]
            if not k.startswith(p):
                break
            short = k[len(p):]
            if end is not None and short >= end:
                break
            yield short, self._map[k]

    def iterate_all(self):
        for k in self._keys:
            p, _, short = k.partition(_SEP)
            yield p.decode("utf-8", errors="replace"), short, self._map[k]


class FileDB(MemDB):
    """Durable log-structured backend.

    Layout in ``path/``:
      - ``snapshot`` — compacted full state at some committed seq
                       (atomic-rename replaced).
      - ``wal``      — checksummed append log of KVTransactions since the
                       snapshot; replayed on open; truncated by compact().

    Crash semantics: submit(sync=True) returns only after the WAL record is
    fsync'd — the reference's journal-ahead rule (os/filestore/FileJournal).
    A torn tail record (bad crc / short read) is discarded and truncated on
    replay (wal.WriteAheadLog), exactly like the reference journal replay.

    Group commit: ``submit_deferred`` applies to memory immediately
    (read-your-writes for the event loop) and stages the encoded record;
    a commit thread later calls ``log_deferred(upto_seq)`` to append the
    whole backlog with ONE fsync (the BlueStore kv_sync_thread recipe).

    Two locks split memory from I/O so event-loop reads never stall for
    a barrier (the PR 1 known hazard: ``db.get``/``iterate`` blocked for
    the whole WAL group fsync / snapshot compaction):
      * ``_mu`` (RLock) — guards ONLY in-memory state (map/keys, seq,
        the deferred backlog); held for microseconds.
      * ``_io`` (Lock)  — serializes WAL appends, fsyncs and snapshot
        compaction so records hit the log in seq order; the group fsync
        and the data-device barrier run under ``_io`` alone, with the
        backlog STAGED under ``_mu`` and flushed outside it.
    Lock order is strictly ``_io`` -> ``_mu``; readers take ``_mu``
    only; ``iterate`` materializes its rows under the lock.
    """

    COMPACT_BYTES = 8 << 20

    supports_deferred = True

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.seq = 0
        # built through the lockdep factory: with the sanitizer enabled
        # (qa clusters) the documented _io -> _mu order is a CHECKED
        # edge in the runtime lock-order graph; disabled, these are
        # plain stdlib locks (zero overhead).  The static half of the
        # same invariant is devtools rule LOCK06.
        self._mu = make_thread_lock(f"filedb:{path}:_mu", rlock=True)
        self._io = make_thread_lock(f"filedb:{path}:_io")
        self._deferred: List[Tuple[int, bytes]] = []
        #: called under _io (NOT _mu — it must never block readers)
        #: right before a snapshot compaction / backlog flush persists;
        #: BlockStore points it at its data-device fsync so a snapshot
        #: can never persist metadata whose data blocks aren't durable
        self.pre_compact_hook: Optional[Callable[[], None]] = None
        #: set when a WAL append failed AFTER memory was applied: the
        #: in-memory state is ahead of the durable log and can never be
        #: reconciled, so the instance refuses further writes (the
        #: deferred path gets the same wedge from a dead KVSyncThread)
        self._broken: Optional[str] = None
        self._load_snapshot()
        self._wal = WriteAheadLog(self._wal_path())
        for seq, payload in self._wal.replay():
            if seq > self.seq:
                super().submit(KVTransaction.decode(payload))
                self.seq = seq

    def _check_broken(self) -> None:
        if self._broken is not None:
            raise RuntimeError(f"FileDB {self.path} is broken "
                               f"(memory ahead of WAL): {self._broken}")

    # --- persistence ---
    def _snap_path(self):
        return os.path.join(self.path, "snapshot")

    def _wal_path(self):
        return os.path.join(self.path, "wal")

    def _load_snapshot(self):
        try:
            with open(self._snap_path(), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        (self.seq, n) = struct.unpack_from("<QI", data, 0)
        off = 12
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", data, off); off += 4
            k = data[off:off + klen]; off += klen
            (vlen,) = struct.unpack_from("<I", data, off); off += 4
            v = data[off:off + vlen]; off += vlen
            self._insert(k, v)

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        with self._io:
            self._check_broken()
            payload = txn.encode()
            with self._mu:
                # reserve OUR seq first, applying memory in the same
                # critical section (memory order == seq/replay order
                # even against a racing submit_deferred on the same
                # key).  Any deferred record staged BEFORE this point
                # has a lower seq and is flushed below, strictly ahead
                # of our append; one staged AFTER has a higher seq and
                # stays deferred — so the WAL file order always equals
                # seq order and replay can never skip a durable record.
                self.seq += 1
                seq = self.seq
                super().submit(txn)
                backlog = bool(self._deferred)
            if backlog:
                # flush the lower-seq backlog before appending our
                # record — after the data barrier, since those records'
                # data blocks may be pwritten but not yet fsync'd
                # (data-before-metadata; their pwrites happened before
                # their submit_deferred returned, i.e. before the hook)
                if self.pre_compact_hook is not None:
                    self.pre_compact_hook()
                self._log_deferred_io(seq - 1)
            # memory was applied above: a failed append would leave it
            # ahead of the durable log forever — poison the instance so
            # LATER writes wedge loudly instead of persisting state a
            # crash would replay without this record
            try:
                self._wal.append(seq, payload, sync=sync)  # no _mu held
            except Exception as e:
                self._broken = f"append of seq {seq} failed: {e!r}"
                raise
            if self._wal.size() > self.COMPACT_BYTES:
                self._compact_io()

    def submit_deferred(self, txn: KVTransaction) -> int:
        """Memory-apply now, WAL later (group commit).  A crash before
        log_deferred loses the record — which is exactly the window the
        store's on_commit callback has not yet acknowledged."""
        with self._mu:
            self._check_broken()
            self.seq += 1
            self._deferred.append((self.seq, txn.encode()))
            super().submit(txn)
            return self.seq

    def log_deferred(self, upto_seq: int) -> int:
        """Append every deferred record with seq <= upto_seq in ONE
        group (single fsync).  Records staged after upto_seq stay
        deferred: their data-device barrier may not have happened yet
        (data-before-metadata)."""
        with self._io:
            return self._log_deferred_io(upto_seq)

    def _log_deferred_io(self, upto_seq: int) -> int:
        """Caller holds ``_io``.  The backlog is collected under ``_mu``
        but the group append/fsync runs outside it, so event-loop reads
        proceed for the whole barrier duration."""
        with self._mu:
            take = [r for r in self._deferred if r[0] <= upto_seq]
            if not take:
                return 0
            self._deferred = [r for r in self._deferred
                              if r[0] > upto_seq]
        try:
            self._wal.append_many(take, sync=True)  # fsync: no _mu held
        except Exception as e:
            # the taken records left the backlog but never reached the
            # log — memory is ahead of durable state for good
            self._broken = f"group append upto {upto_seq} failed: {e!r}"
            raise
        with self._mu:
            fully_logged = not self._deferred
        if self._wal.size() > self.COMPACT_BYTES and fully_logged:
            # compact only at a fully-logged boundary: the snapshot
            # covers live memory, which includes any still-deferred
            # records — never persist those before their barrier
            self._compact_io()
        return len(take)

    def compact(self) -> None:
        with self._io:
            self._compact_io()

    def _compact_io(self) -> None:
        """Caller holds ``_io``.  The snapshot image is built under
        ``_mu`` (consistent seq + state); the data-device barrier and
        the snapshot write/rename/rotate run outside it.  Ordering: any
        record in the image had its data pwritten before its
        submit_deferred returned (i.e. before the image was built), so
        the barrier AFTER building still covers every block the
        snapshot references (COW data-before-metadata)."""
        with self._mu:
            out = bytearray(struct.pack("<QI", self.seq, len(self._keys)))
            for k in self._keys:
                v = self._map[k]
                out += struct.pack("<I", len(k)) + k
                out += struct.pack("<I", len(v)) + v
        if self.pre_compact_hook is not None:
            self.pre_compact_hook()
        atomic_snapshot(self._snap_path(), bytes(out))
        self._wal.rotate()

    # --- thread-safe read/apply views (commit thread vs event loop) ---
    def get(self, prefix: str, key) -> Optional[bytes]:
        with self._mu:
            return super().get(prefix, key)

    def iterate(self, prefix: str, start=b"", end=None):
        with self._mu:
            rows = list(super().iterate(prefix, start=start, end=end))
        return iter(rows)

    def iterate_all(self):
        with self._mu:
            rows = list(super().iterate_all())
        return iter(rows)

    def close(self) -> None:
        with self._io:
            if self._wal.closed:
                return
            with self._mu:
                upto = self.seq
                backlog = bool(self._deferred)
            if backlog:
                # records can still be pending here when the commit
                # thread died: their data blocks may be pwritten but
                # never fsync'd — run the data barrier FIRST so the
                # WAL flush can't persist metadata ahead of its data
                # (data-before-metadata, same rule as compact)
                if self.pre_compact_hook is not None:
                    self.pre_compact_hook()
                self._log_deferred_io(upto)
            if self._wal.size() > 0:   # nothing new since snapshot?
                self._compact_io()
            self._wal.close()
