"""KeyValueDB: transactional ordered key-value store abstraction.

Reference parity: kv/KeyValueDB.h (abstract kv with batched transactions and
prefix iterators; backends LevelDBStore/RocksDBStore/MemDB).  Redesigned with
two backends, no external deps:

- MemDB      — sorted in-memory map (tests, MemStore omap).
- FileDB     — log-structured file backend: append-only WAL of committed
               batches + periodic compacted snapshot, replayed on open.
               This is the durability substrate for the monitor store and
               FileStore metadata, playing the role rocksdb plays in the
               reference (kv/RocksDBStore.cc) with a deliberately simple
               single-writer design.

Keys are namespaced by a string prefix like the reference
(``prefix`` + 0x00 + key ordering), values are bytes.
"""

from __future__ import annotations

import os
import struct
from bisect import bisect_left
from typing import Dict, Iterator, List, Optional, Tuple

from ceph_tpu.store.wal import WriteAheadLog, atomic_snapshot

_SEP = b"\x00"


def _full_key(prefix: str, key: bytes) -> bytes:
    return prefix.encode("utf-8") + _SEP + key


class KVTransaction:
    """Batched mutations applied atomically by ``KeyValueDB.submit``."""

    __slots__ = ("ops",)

    SET, RM, RM_PREFIX = 0, 1, 2

    def __init__(self):
        self.ops: List[Tuple[int, bytes, bytes]] = []

    def set(self, prefix: str, key, value: bytes) -> "KVTransaction":
        if isinstance(key, str):
            key = key.encode("utf-8")
        self.ops.append((self.SET, _full_key(prefix, key), bytes(value)))
        return self

    def rmkey(self, prefix: str, key) -> "KVTransaction":
        if isinstance(key, str):
            key = key.encode("utf-8")
        self.ops.append((self.RM, _full_key(prefix, key), b""))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append((self.RM_PREFIX, prefix.encode("utf-8") + _SEP, b""))
        return self

    def encode(self) -> bytes:
        out = bytearray(struct.pack("<I", len(self.ops)))
        for op, k, v in self.ops:
            out += struct.pack("<BI", op, len(k)) + k
            out += struct.pack("<I", len(v)) + v
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "KVTransaction":
        t = cls()
        off = 4
        (n,) = struct.unpack_from("<I", data, 0)
        for _ in range(n):
            op, klen = struct.unpack_from("<BI", data, off)
            off += 5
            k = data[off:off + klen]; off += klen
            (vlen,) = struct.unpack_from("<I", data, off)
            off += 4
            v = data[off:off + vlen]; off += vlen
            t.ops.append((op, k, v))
        return t


class KeyValueDB:
    """Abstract ordered kv store."""

    def create_transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        raise NotImplementedError

    def get(self, prefix: str, key) -> Optional[bytes]:
        raise NotImplementedError

    def iterate(self, prefix: str, start=b"", end=None
                ) -> Iterator[Tuple[bytes, bytes]]:
        """Yield (key, value) within prefix, key >= start (< end if given),
        in key order."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    # conveniences
    def exists(self, prefix: str, key) -> bool:
        return self.get(prefix, key) is not None

    def keys(self, prefix: str) -> List[bytes]:
        return [k for k, _ in self.iterate(prefix)]

    def iterate_all(self) -> Iterator[Tuple[str, bytes, bytes]]:
        """Yield (prefix, key, value) over the whole keyspace — offline
        tooling surface (kvstore tool list/stats)."""
        raise NotImplementedError


class MemDB(KeyValueDB):
    """Sorted in-memory backend (reference kv/MemDB analog)."""

    def __init__(self):
        self._keys: List[bytes] = []          # sorted full keys
        self._map: Dict[bytes, bytes] = {}

    def _insert(self, k: bytes, v: bytes):
        if k not in self._map:
            self._keys.insert(bisect_left(self._keys, k), k)
        self._map[k] = v

    def _remove(self, k: bytes):
        if k in self._map:
            del self._map[k]
            i = bisect_left(self._keys, k)
            del self._keys[i]

    def _remove_prefix(self, p: bytes):
        lo = bisect_left(self._keys, p)
        hi = lo
        while hi < len(self._keys) and self._keys[hi].startswith(p):
            del self._map[self._keys[hi]]
            hi += 1
        del self._keys[lo:hi]

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        for op, k, v in txn.ops:
            if op == KVTransaction.SET:
                self._insert(k, v)
            elif op == KVTransaction.RM:
                self._remove(k)
            else:
                self._remove_prefix(k)

    def get(self, prefix: str, key) -> Optional[bytes]:
        if isinstance(key, str):
            key = key.encode("utf-8")
        return self._map.get(_full_key(prefix, key))

    def iterate(self, prefix: str, start=b"", end=None):
        if isinstance(start, str):
            start = start.encode("utf-8")
        if isinstance(end, str):
            end = end.encode("utf-8")
        p = prefix.encode("utf-8") + _SEP
        lo = bisect_left(self._keys, p + start)
        for i in range(lo, len(self._keys)):   # no tail copy
            k = self._keys[i]
            if not k.startswith(p):
                break
            short = k[len(p):]
            if end is not None and short >= end:
                break
            yield short, self._map[k]

    def iterate_all(self):
        for k in self._keys:
            p, _, short = k.partition(_SEP)
            yield p.decode("utf-8", errors="replace"), short, self._map[k]


class FileDB(MemDB):
    """Durable log-structured backend.

    Layout in ``path/``:
      - ``snapshot`` — compacted full state at some committed seq
                       (atomic-rename replaced).
      - ``wal``      — checksummed append log of KVTransactions since the
                       snapshot; replayed on open; truncated by compact().

    Crash semantics: submit(sync=True) returns only after the WAL record is
    fsync'd — the reference's journal-ahead rule (os/filestore/FileJournal).
    A torn tail record (bad crc / short read) is discarded and truncated on
    replay (wal.WriteAheadLog), exactly like the reference journal replay.
    """

    COMPACT_BYTES = 8 << 20

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.seq = 0
        self._load_snapshot()
        self._wal = WriteAheadLog(self._wal_path())
        for seq, payload in self._wal.replay():
            if seq > self.seq:
                super().submit(KVTransaction.decode(payload))
                self.seq = seq

    # --- persistence ---
    def _snap_path(self):
        return os.path.join(self.path, "snapshot")

    def _wal_path(self):
        return os.path.join(self.path, "wal")

    def _load_snapshot(self):
        try:
            with open(self._snap_path(), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        (self.seq, n) = struct.unpack_from("<QI", data, 0)
        off = 12
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", data, off); off += 4
            k = data[off:off + klen]; off += klen
            (vlen,) = struct.unpack_from("<I", data, off); off += 4
            v = data[off:off + vlen]; off += vlen
            self._insert(k, v)

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        payload = txn.encode()
        self._wal.append(self.seq + 1, payload, sync=sync)
        self.seq += 1   # only after the record is durable
        super().submit(txn)
        if self._wal.size() > self.COMPACT_BYTES:
            self.compact()

    def compact(self) -> None:
        out = bytearray(struct.pack("<QI", self.seq, len(self._keys)))
        for k in self._keys:
            v = self._map[k]
            out += struct.pack("<I", len(k)) + k
            out += struct.pack("<I", len(v)) + v
        atomic_snapshot(self._snap_path(), bytes(out))
        self._wal.rotate()

    def close(self) -> None:
        if not self._wal.closed:
            if self._wal.size() > 0:   # nothing new since last snapshot?
                self.compact()
            self._wal.close()
