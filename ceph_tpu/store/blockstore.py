"""BlockStore: raw-block-file object store with extent allocation,
per-extent checksums and copy-on-write crash consistency.

Reference parity: os/bluestore/BlueStore.{h,cc} — objects live as extent
maps over a raw block device with metadata in a kv store, not as files
in a filesystem (/root/reference/src/os/bluestore/BlueStore.cc:1,
Allocator.h, bluestore_types.h onode/extent/blob).  The role split is
kept: ``block`` is the data device, FileDB (WAL + snapshot) plays
rocksdb, onodes carry the logical->disk extent map, and the allocator
hands out min_alloc-sized extents.

Redesign notes (vs the C++ original):
  * Crash consistency is pure COW ordering instead of BlueStore's
    deferred-write journal: new data always lands in FRESHLY allocated
    blocks, the block file is fsync'd, and only then does the metadata
    batch (onode updates) commit through the kv WAL.  A crash between
    the two leaks unreferenced blocks — which the mount-time allocator
    rebuild reclaims for free, playing FreelistManager without any
    persistent freelist to keep transactional.
  * Deferred small-write optimization is dropped: it exists to dodge
    HDD seek latency; the RMW a sub-block overwrite pays here is one
    pread + one pwrite into a fresh block.
  * Every extent stores a crc32c over its live bytes (bluestore csum);
    reads verify and raise on mismatch, which the scrub deep pass
    surfaces as a shard error instead of silently returning rot.
  * clone copies extents (no shared-blob refcounting); clone_range and
    zero/truncate trim or copy at extent granularity.
  * Commit is a group-committed pipeline (BlueStore kv_sync_thread):
    queue_transactions applies data (pwrite) and metadata (kv memory)
    inline — immediately readable — and a dedicated commit thread
    issues ONE data fsync + ONE atomic kv WAL submit for every batch in
    flight, preserving data-before-metadata and submission order, then
    fires on_commit callbacks back on the event loop.  Freed COW blocks
    return to the allocator only after their dereferencing metadata is
    durable.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional, Tuple

from ceph_tpu.common.crc import crc32c
from ceph_tpu.common.xxhash import xxh32, xxh64
from ceph_tpu.common.encoding import Decoder, Encodable, Encoder
from ceph_tpu.store.commit import KVSyncThread
from ceph_tpu.store.kv import FileDB, KVTransaction
from ceph_tpu.store.objectstore import (
    NoSuchCollection, NoSuchObject, ObjectStore, StoreError, Transaction,
    OP_NOP, OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE, OP_REMOVE,
    OP_SETATTR, OP_SETATTRS, OP_RMATTR, OP_CLONE, OP_CLONERANGE2,
    OP_MKCOLL, OP_RMCOLL, OP_OMAP_CLEAR, OP_OMAP_SETKEYS, OP_OMAP_RMKEYS,
    OP_OMAP_SETHEADER, OP_OMAP_RMKEYRANGE, OP_COLL_MOVE_RENAME,
    OP_TRY_RENAME,
)
from ceph_tpu.store.types import CollectionId, ObjectId

MIN_ALLOC = 4096          # bluestore min_alloc_size
_PREFIX_COLL = "C"        # cid -> b""
_PREFIX_ONODE = "O"       # cid + 0x00 + oidkey -> Onode
_PREFIX_OMAP = "M"        # cid + 0x00 + oidkey + 0x00 + key -> value


class Extent(Encodable):
    """One contiguous logical->disk mapping (bluestore_pextent_t +
    csum).  v2 adds blob compression (bluestore_blob_t compressed
    flag): `length` is always the LOGICAL byte count, `disk_len` the
    stored bytes, `alg` the compressor that produced them ("" = raw);
    crc covers the stored bytes."""

    STRUCT_V = 2

    __slots__ = ("logical", "disk", "length", "crc", "disk_len", "alg")

    def __init__(self, logical: int = 0, disk: int = 0, length: int = 0,
                 crc: int = 0, disk_len: int = -1, alg: str = ""):
        self.logical = logical
        self.disk = disk
        self.length = length
        self.crc = crc
        self.disk_len = disk_len if disk_len >= 0 else length
        self.alg = alg

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.logical).u64(self.disk).u32(self.length)
        enc.u32(self.crc)
        enc.u32(self.disk_len).string(self.alg)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "Extent":
        e = cls(dec.u64(), dec.u64(), dec.u32(), dec.u32())
        if struct_v >= 2:
            e.disk_len = dec.u32()
            e.alg = dec.string()
        return e

    def __repr__(self):
        z = f"~{self.alg}" if self.alg else ""
        return f"ext({self.logical}+{self.length}@{self.disk:#x}{z})"


class Onode(Encodable):
    """Object metadata record (bluestore_onode_t role)."""

    __slots__ = ("size", "extents", "attrs", "omap_header", "has_omap")

    def __init__(self):
        self.size = 0
        self.extents: List[Extent] = []
        self.attrs: Dict[str, bytes] = {}
        self.omap_header = b""
        self.has_omap = False

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.size)
        enc.list_(self.extents, lambda e, x: e.struct(x))
        enc.map_(self.attrs, lambda e, k: e.string(k),
                 lambda e, v: e.bytes_(v))
        enc.bytes_(self.omap_header).boolean(self.has_omap)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "Onode":
        o = cls()
        o.size = dec.u64()
        o.extents = dec.list_(lambda d: d.struct(Extent))
        o.attrs = dec.map_(lambda d: d.string(), lambda d: d.bytes_())
        o.omap_header = dec.bytes_()
        o.has_omap = dec.boolean()
        return o


class Allocator:
    """Free-extent manager over the block file (Allocator.h bitmap/stupid
    role, as a sorted free-range list).  Thread-safe: freed COW blocks
    are released from the commit thread once the metadata that stopped
    referencing them is durable, while the event loop allocates."""

    def __init__(self):
        from ceph_tpu.common.lockdep import make_thread_lock
        self._mu = make_thread_lock("blockstore:alloc:_mu")
        self.free: List[List[int]] = []   # sorted [off, len]
        self.device_size = 0

    def init_add_free(self, off: int, length: int) -> None:
        with self._mu:
            self.free.append([off, length])
            self.free.sort()
            self._coalesce()

    def init_rm_free(self, off: int, length: int) -> None:
        """Carve an allocated range out during mount rebuild."""
        with self._mu:
            out = []
            for f_off, f_len in self.free:
                f_end, end = f_off + f_len, off + length
                if f_end <= off or f_off >= end:
                    out.append([f_off, f_len])
                    continue
                if f_off < off:
                    out.append([f_off, off - f_off])
                if f_end > end:
                    out.append([end, f_end - end])
            self.free = sorted(out)

    def allocate(self, length: int) -> List[Tuple[int, int]]:
        """-> [(disk_off, len)] covering length (may fragment); extends
        the device when free space runs out (file-backed device grows)."""
        need = length
        got: List[Tuple[int, int]] = []
        with self._mu:
            while need > 0 and self.free:
                off, ln = self.free[0]
                take = min(ln, need)
                got.append((off, take))
                if take == ln:
                    self.free.pop(0)
                else:
                    self.free[0] = [off + take, ln - take]
                need -= take
            if need > 0:
                off = self.device_size
                grow = (need + MIN_ALLOC - 1) // MIN_ALLOC * MIN_ALLOC
                self.device_size += grow
                got.append((off, need))
                if grow > need:
                    self.free.append([off + need, grow - need])
                    self.free.sort()
                    self._coalesce()
        return got

    def release(self, off: int, length: int) -> None:
        self.init_add_free(off, length)

    def _coalesce(self) -> None:
        # caller holds _mu
        out: List[List[int]] = []
        for off, ln in self.free:
            if out and out[-1][0] + out[-1][1] == off:
                out[-1][1] += ln
            else:
                out.append([off, ln])
        self.free = out

    def free_bytes(self) -> int:
        with self._mu:
            return sum(ln for _, ln in self.free)


def _oid_key(oid: ObjectId) -> bytes:
    enc = Encoder()
    enc.struct(oid)
    return enc.getvalue()


def _onode_key(cid: CollectionId, oid: ObjectId) -> bytes:
    return cid.name.encode() + b"\x00" + _oid_key(oid)


def _omap_key(cid: CollectionId, oid: ObjectId, key: bytes) -> bytes:
    return _onode_key(cid, oid) + b"\x00" + key


class _Batch:
    """Call-local staging for ONE queue_transactions invocation.

    Previously the overlay / wrote-data flag were instance attributes
    mutated per call, so two interleaved callers corrupted each other's
    staged kv — and the async commit path makes interleaving the norm.
    """

    __slots__ = ("ov", "freed", "dirty", "wrote_data")

    def __init__(self):
        # staged kv mutations: (prefix, key) -> value | None(delete).
        # Reads during apply consult this overlay so ops see earlier
        # ops of the SAME batch, while the db commits in ONE atomic
        # KVTransaction at the end (anything less would tear the txn
        # on crash)
        self.ov: Dict[Tuple[str, bytes], Optional[bytes]] = {}
        self.freed: List[Tuple[int, int]] = []
        self.dirty: Dict[bytes, Optional[Onode]] = {}
        self.wrote_data = False


class BlockStore(ObjectStore):
    #: selectable per-extent checksum (bluestore csum_type: crc32c is
    #: the default; xxhash32/xxhash64 as in bluestore_types.h
    #: Checksummer).  Stored crcs are alg-agnostic 32-bit values, so
    #: the extent format doesn't change (xxh64 keeps its low 32 bits).
    CSUM_FNS = {
        "crc32c": crc32c,
        "xxhash32": xxh32,
        "xxhash64": lambda d: xxh64(d) & 0xFFFFFFFF,
    }

    def __init__(self, path: str, compression: str = "",
                 compression_min_blob: int = 4096,
                 csum_type: str = "crc32c"):
        super().__init__(path)
        self.db: Optional[FileDB] = None
        self._fd = -1
        self.alloc = Allocator()
        self._onodes: Dict[bytes, Onode] = {}    # write-through cache
        self.mounted = False
        self._committer: Optional[KVSyncThread] = None
        self._comp = None
        if csum_type not in self.CSUM_FNS:
            raise StoreError(
                f"unknown csum_type {csum_type!r} "
                f"(supported: {sorted(self.CSUM_FNS)})")
        self._csum_name = csum_type
        self._csum = self.CSUM_FNS[csum_type]
        self.set_compression(compression, compression_min_blob)

    def set_compression(self, algorithm: str,
                        min_blob: int = 4096) -> None:
        """Enable blob compression for future writes (per-extent alg tag
        means mixed/compressed data coexists and stays readable)."""
        from ceph_tpu.compressor import create
        self._comp = create(algorithm) if algorithm else None
        self.compression_min_blob = min_blob

    # ------------------------------------------------------------ lifecycle
    def _block_path(self) -> str:
        return os.path.join(self.path, "block")

    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(self._block_path(), "wb"):
            pass
        db = FileDB(os.path.join(self.path, "db"))
        db.close()

    def mount(self) -> None:
        if self.mounted:
            return
        if not os.path.exists(self._block_path()):
            self.mkfs()
        self.db = FileDB(os.path.join(self.path, "db"))
        # the csum alg is a STORE property (extents carry only the
        # 32-bit value): the pinned type wins over the constructor
        # argument, so reopening with a different default can't
        # misverify old extents.  A store WITH onodes but WITHOUT a
        # pin predates selectable csums — its extents are crc32c.
        pinned = self.db.get("meta", b"csum_type")
        if pinned is None and self.db.keys(_PREFIX_ONODE):
            pinned = b"crc32c"            # legacy store
        if pinned is not None:
            name = pinned.decode()
            if name not in self.CSUM_FNS:
                raise StoreError(
                    f"store pins unknown csum_type {name!r} "
                    f"(supported: {sorted(self.CSUM_FNS)})")
            self._csum_name = name
            self._csum = self.CSUM_FNS[name]
        txn = self.db.create_transaction()
        txn.set("meta", b"csum_type", self._csum_name.encode())
        self.db.submit(txn)
        self._fd = os.open(self._block_path(), os.O_RDWR)
        # allocator rebuild: everything is free except extents referenced
        # by some onode (FreelistManager role, derived not persisted)
        self.alloc = Allocator()
        # the file ends at the last written byte, which can be mid-block:
        # round up so rebuild carving stays block-aligned
        self.alloc.device_size = _align_up(os.fstat(self._fd).st_size)
        if self.alloc.device_size:
            self.alloc.init_add_free(0, self.alloc.device_size)
        for k in self.db.keys(_PREFIX_ONODE):
            on = Onode.from_bytes(self.db.get(_PREFIX_ONODE, k))
            for ext in on.extents:
                self.alloc.init_rm_free(ext.disk,
                                        _align_up(ext.disk_len))
        self._onodes = {}
        # group-commit pipeline (BlueStore kv_sync_thread role): the
        # event loop applies in memory; this thread batches the data
        # fsync + kv WAL sync for every transaction in flight
        self.db.pre_compact_hook = self._data_barrier
        # small static gather base: the auto-tuner tracks the MEASURED
        # barrier cost (EWMA) clamped to 4x this — on tmpfs the window
        # stays at the ~0.1ms a cheap fsync costs, on a real disk it
        # grows to the clamp so co-arriving txns share the 4ms+ fsync
        self._committer = KVSyncThread(
            "blockstore_commit",
            data_sync=self._data_barrier,
            kv_sync=self.db.log_deferred,
            gather_window=0.001)
        self._committer.start()
        self.mounted = True

    def _data_barrier(self) -> None:
        if self._fd >= 0:
            os.fsync(self._fd)

    def sync(self) -> None:
        """Block until every queued transaction is durable (flush)."""
        if self._committer is not None:
            self._committer.flush()

    def commit_counters(self) -> Dict[str, float]:
        return self._committer.counters() if self._committer else {}

    def umount(self) -> None:
        if not self.mounted:
            return
        self._committer.stop()
        self._committer = None
        # close the db BEFORE the block fd: close() may still flush
        # deferred kv records (dead commit thread) and its data barrier
        # (pre_compact_hook -> _data_barrier) needs the fd open
        self.db.close()
        self.db = None
        os.close(self._fd)
        self._fd = -1
        self._onodes = {}
        self.mounted = False

    # ------------------------------------------------------------- helpers
    def _coll_exists(self, cid: CollectionId,
                     b: Optional[_Batch] = None) -> bool:
        return self._kv_get(_PREFIX_COLL, cid.name.encode(),
                            b) is not None

    def _get_onode(self, cid: CollectionId, oid: ObjectId,
                   create: bool = False,
                   b: Optional[_Batch] = None) -> Onode:
        key = _onode_key(cid, oid)
        if b is not None and key in b.dirty and b.dirty[key] is None:
            # removed earlier in THIS batch: the committed row must not
            # resurrect (remove+write in one txn is apply_push's shape)
            if not create:
                raise NoSuchObject(f"{cid}/{oid}")
            if not self._coll_exists(cid, b):
                raise NoSuchCollection(str(cid))
            on = Onode()
            self._onodes[key] = on
            return on
        on = self._onodes.get(key)
        if on is None:
            raw = self._kv_get(_PREFIX_ONODE, key, b)
            if raw is not None:
                on = Onode.from_bytes(raw)
            elif create:
                if not self._coll_exists(cid, b):
                    raise NoSuchCollection(str(cid))
                on = Onode()
            else:
                raise NoSuchObject(f"{cid}/{oid}")
            self._onodes[key] = on
        return on

    # -------------------------------------------------------------- writes
    def queue_transactions(self, txns, on_applied=None,
                           on_commit=None) -> None:
        """Apply data + metadata in memory, then hand the staged kv
        batch to the commit thread: ONE data fsync + ONE atomic kv
        submit cover every batch in flight (group commit).  on_applied
        fires inline (state is readable); on_commit fires from the
        commit thread once the batch is durable, in submission order."""
        assert self.mounted, "blockstore not mounted"
        if self._committer is not None and self._committer.dead:
            # the commit thread died (fsync error / injected crash):
            # accepting more writes would apply them in memory with no
            # path to durability and no acks — fail loudly so the OSD
            # surfaces the wedge instead of serving phantom writes
            raise StoreError("blockstore commit thread is dead")
        b = _Batch()                     # call-local: reentrancy-safe
        try:
            for txn in txns:
                for op in txn.ops:
                    self._apply_op(op, b)
        except Exception:
            # roll back every trace of the failed batch: staged kv is
            # dropped, the onode cache may hold in-place mutations so it
            # is flushed wholesale (it is only a cache), and blocks
            # allocated for the doomed writes leak until the next mount
            # rebuild reclaims them
            self._onodes = {}
            raise
        for key, on in b.dirty.items():
            if on is None:
                self._stage(b, _PREFIX_ONODE, key, None)
                self._onodes.pop(key, None)
            else:
                self._stage(b, _PREFIX_ONODE, key, on.to_bytes())
                self._onodes[key] = on
        batch = KVTransaction()
        for (prefix, key), val in b.ov.items():
            if val is None:
                batch.rmkey(prefix, key)
            else:
                batch.set(prefix, key, val)
        # memory-apply now (read-your-writes for every later caller);
        # the WAL record becomes durable on the commit thread
        seq = self.db.submit_deferred(batch)
        self.applied_seq += 1
        if on_applied:
            on_applied()
        post = None
        if b.freed:
            freed = b.freed

            def post():
                # old blocks become reusable only after the metadata
                # that dereferenced them is DURABLE (COW ordering): a
                # reuse before that could overwrite blocks a replayed
                # old onode still references
                for off, ln in freed:
                    self.alloc.release(off, ln)
        self._committer.submit(seq=seq, wrote_data=b.wrote_data,
                               on_commit=on_commit, post=post)

    # --- staged kv views (overlay over the committed db) ---
    @staticmethod
    def _stage(b: _Batch, prefix: str, key: bytes,
               val: Optional[bytes]) -> None:
        b.ov[(prefix, key)] = val

    def _kv_get(self, prefix: str, key: bytes,
                b: Optional[_Batch] = None) -> Optional[bytes]:
        if b is not None and (prefix, key) in b.ov:
            return b.ov[(prefix, key)]
        return self.db.get(prefix, key)

    def _kv_keys(self, prefix: str, pre: bytes = b"",
                 b: Optional[_Batch] = None) -> List[bytes]:
        """Keys under `prefix` starting with `pre`, overlay-aware; the
        committed side is a bounded range scan, not a full-prefix walk."""
        end = _prefix_end(pre) if pre else None
        keys = {k for k, _ in self.db.iterate(prefix, start=pre,
                                              end=end)}
        if b is not None:
            for (p, k), v in b.ov.items():
                if p != prefix or not k.startswith(pre):
                    continue
                if v is None:
                    keys.discard(k)
                else:
                    keys.add(k)
        return sorted(keys)

    def _apply_op(self, op, b: _Batch) -> None:
        """Apply one op; any block-file write sets b.wrote_data."""
        c, o = op.cid, op.oid
        freed, dirty = b.freed, b.dirty
        if op.op == OP_NOP:
            return
        if op.op == OP_MKCOLL:
            self._stage(b, _PREFIX_COLL, c.name.encode(), b"")
            return
        if op.op == OP_RMCOLL:
            if not self._coll_exists(c, b):
                return       # removal of missing collection: no-op
            for oid in self.collection_list(c):
                self._remove_object(c, oid, b)
            self._stage(b, _PREFIX_COLL, c.name.encode(), None)
            return
        if op.op == OP_TOUCH:
            key = _onode_key(c, o)
            dirty[key] = self._get_onode(c, o, create=True, b=b)
            return
        if op.op == OP_WRITE:
            on = self._get_onode(c, o, create=True, b=b)
            self._write_range(on, op.off, op.data, b)
            dirty[_onode_key(c, o)] = on
            return
        if op.op == OP_ZERO:
            on = self._get_onode(c, o, create=True, b=b)
            self._punch(on, op.off, op.length, b)
            on.size = max(on.size, op.off + op.length)
            dirty[_onode_key(c, o)] = on
            return
        if op.op == OP_TRUNCATE:
            on = self._get_onode(c, o, create=True, b=b)
            size = op.off
            self._punch(on, size, max(on.size - size, 0), b)
            on.size = size
            dirty[_onode_key(c, o)] = on
            return
        if op.op == OP_REMOVE:
            self._remove_object(c, o, b)
            return
        if op.op == OP_SETATTR:
            on = self._get_onode(c, o, create=True, b=b)
            on.attrs[op.name] = op.data
            dirty[_onode_key(c, o)] = on
            return
        if op.op == OP_SETATTRS:
            on = self._get_onode(c, o, create=True, b=b)
            for k, v in op.kv.items():
                on.attrs[k.decode("utf-8")] = v
            dirty[_onode_key(c, o)] = on
            return
        if op.op == OP_RMATTR:
            try:
                on = self._get_onode(c, o, b=b)
            except StoreError:
                return       # destructive op on missing: no-op
            on.attrs.pop(op.name, None)
            dirty[_onode_key(c, o)] = on
            return
        if op.op == OP_CLONE:
            try:
                src = self._get_onode(c, o, b=b)
            except StoreError:
                return       # clone of missing: no-op
            # clone REPLACES the destination (memstore semantics): old
            # extents freed, old omap dropped
            try:
                old = self._get_onode(c, op.oid2, b=b)
                for ext in old.extents:
                    freed.append((ext.disk, _align_up(ext.disk_len)))
                pre_old = _omap_key(c, op.oid2, b"")
                for k in self._kv_keys(_PREFIX_OMAP, pre_old, b):
                    self._stage(b, _PREFIX_OMAP, k, None)
                self._onodes.pop(_onode_key(c, op.oid2), None)
            except StoreError:
                pass
            data = self._read_onode(src, 0, src.size)
            dst = Onode()
            dst.attrs = dict(src.attrs)
            dst.omap_header = src.omap_header
            self._write_range(dst, 0, data, b)
            dst.size = src.size
            # omap copies too (clone carries omap in the reference)
            if src.has_omap:
                dst.has_omap = True
                pre = _omap_key(c, o, b"")
                for k in self._kv_keys(_PREFIX_OMAP, pre, b):
                    self._stage(b, _PREFIX_OMAP,
                                _omap_key(c, op.oid2, k[len(pre):]),
                                self._kv_get(_PREFIX_OMAP, k, b))
            dirty[_onode_key(c, op.oid2)] = dst
            return
        if op.op == OP_CLONERANGE2:
            try:
                src = self._get_onode(c, o, b=b)
            except StoreError:
                return

            data = self._read_onode(src, op.off, op.length)
            try:
                dst = self._get_onode(c, op.oid2, create=True, b=b)
            except NoSuchObject:
                dst = Onode()
            self._write_range(dst, op.dest_off, data, b)
            dirty[_onode_key(c, op.oid2)] = dst
            return
        if op.op == OP_COLL_MOVE_RENAME or op.op == OP_TRY_RENAME:
            newcid = op.cid2 or c
            try:
                src = self._get_onode(c, o, b=b)
            except NoSuchObject:
                if op.op == OP_TRY_RENAME:
                    return
                raise
            # rename replaces any existing destination
            try:
                old = self._get_onode(newcid, op.oid2, b=b)
                if old is not src:
                    for ext in old.extents:
                        freed.append((ext.disk, _align_up(ext.disk_len)))
                    for k in self._kv_keys(_PREFIX_OMAP,
                                           _omap_key(newcid, op.oid2,
                                                     b""), b):
                        self._stage(b, _PREFIX_OMAP, k, None)
                    self._onodes.pop(_onode_key(newcid, op.oid2), None)
            except StoreError:
                pass
            dirty[_onode_key(c, o)] = None
            self._onodes.pop(_onode_key(c, o), None)
            dirty[_onode_key(newcid, op.oid2)] = src
            pre = _omap_key(c, o, b"")
            for k in self._kv_keys(_PREFIX_OMAP, pre, b):
                self._stage(b, _PREFIX_OMAP,
                            _omap_key(newcid, op.oid2, k[len(pre):]),
                            self._kv_get(_PREFIX_OMAP, k, b))
                self._stage(b, _PREFIX_OMAP, k, None)
            return
        if op.op == OP_OMAP_CLEAR:
            try:
                self._get_onode(c, o, b=b)
            except StoreError:
                return

            pre = _omap_key(c, o, b"")
            for k in self._kv_keys(_PREFIX_OMAP, pre, b):
                self._stage(b, _PREFIX_OMAP, k, None)
            return
        if op.op == OP_OMAP_SETKEYS:
            on = self._get_onode(c, o, create=True, b=b)
            on.has_omap = True
            dirty[_onode_key(c, o)] = on
            for k, v in op.kv.items():
                self._stage(b, _PREFIX_OMAP, _omap_key(c, o, k), v)
            return
        if op.op == OP_OMAP_RMKEYS:
            for k in op.keys:
                self._stage(b, _PREFIX_OMAP, _omap_key(c, o, k), None)
            return
        if op.op == OP_OMAP_RMKEYRANGE:
            first, last = op.keys
            pre = _omap_key(c, o, b"")
            for k in self._kv_keys(_PREFIX_OMAP, pre, b):
                if first <= k[len(pre):] < last:
                    self._stage(b, _PREFIX_OMAP, k, None)
            return
        if op.op == OP_OMAP_SETHEADER:
            on = self._get_onode(c, o, create=True, b=b)
            on.omap_header = op.data
            dirty[_onode_key(c, o)] = on
            return
        raise StoreError(f"blockstore: unsupported op {op.op}")

    def _remove_object(self, cid, oid, b: _Batch) -> None:
        try:
            on = self._get_onode(cid, oid, b=b)
        except NoSuchObject:
            return
        for ext in on.extents:
            b.freed.append((ext.disk, _align_up(ext.disk_len)))
        pre = _omap_key(cid, oid, b"")
        for k in self._kv_keys(_PREFIX_OMAP, pre, b):
            self._stage(b, _PREFIX_OMAP, k, None)
        b.dirty[_onode_key(cid, oid)] = None
        self._onodes.pop(_onode_key(cid, oid), None)

    # COW write: merge-affected old extents are read, the merged span is
    # written to fresh blocks, old blocks freed post-commit
    def _write_range(self, on: Onode, off: int, data: bytes,
                     b: _Batch) -> None:
        if not data:
            on.size = max(on.size, off)
            return
        end = off + len(data)
        # widen to existing extents overlapping the span so the rewrite
        # keeps their surviving bytes
        lo, hi = off, end
        keep: List[Extent] = []
        drop: List[Extent] = []
        for ext in on.extents:
            if ext.logical + ext.length <= off or ext.logical >= end:
                keep.append(ext)
            else:
                drop.append(ext)
                lo = min(lo, ext.logical)
                hi = max(hi, ext.logical + ext.length)
        span = bytearray(hi - lo)
        for ext in drop:
            span[ext.logical - lo:ext.logical - lo + ext.length] = \
                self._pread_checked(ext)
            b.freed.append((ext.disk, _align_up(ext.disk_len)))
        span[off - lo:end - lo] = data
        on.extents = sorted(keep + self._rewrite(lo, bytes(span), b),
                            key=lambda e: e.logical)
        on.size = max(on.size, end)

    def _punch(self, on: Onode, off: int, length: int,
               b: _Batch) -> None:
        if length <= 0:
            return
        end = off + length
        out: List[Extent] = []
        for ext in on.extents:
            e_end = ext.logical + ext.length
            if e_end <= off or ext.logical >= end:
                out.append(ext)
                continue
            data = self._pread_checked(ext)
            b.freed.append((ext.disk, _align_up(ext.disk_len)))
            if ext.logical < off:
                head = data[:off - ext.logical]
                out.extend(self._rewrite(ext.logical, head, b))
            if e_end > end:
                tail = data[end - ext.logical:]
                out.extend(self._rewrite(end, tail, b))
        on.extents = sorted(out, key=lambda e: e.logical)

    def _rewrite(self, logical: int, data: bytes,
                 b: _Batch) -> List[Extent]:
        exts = []
        pos = 0
        for d_off, d_len in self.alloc.allocate(_align_up(len(data))):
            take = min(d_len, len(data) - pos)
            if take <= 0:
                self.alloc.release(d_off, d_len)
                continue
            chunk = data[pos:pos + take]
            exts.append(self._store_piece(logical + pos, chunk, d_off,
                                          d_len, b))
            pos += take
        return exts

    def _store_piece(self, logical: int, chunk: bytes, d_off: int,
                     d_len: int, b: _Batch) -> Extent:
        """Write one contiguous piece, compressing when it pays
        (bluestore_compression_required_ratio role: stored bytes must
        save at least one alloc unit)."""
        stored, alg = chunk, ""
        if (self._comp is not None
                and len(chunk) >= self.compression_min_blob):
            cand = self._comp.compress(chunk)
            if _align_up(len(cand)) < _align_up(len(chunk)):
                stored, alg = cand, self._comp.name
        os.pwrite(self._fd, stored, d_off)
        b.wrote_data = True
        used = _align_up(len(stored))
        if used < d_len:
            self.alloc.release(d_off + used, d_len - used)
        return Extent(logical, d_off, len(chunk), self._csum(stored),
                      len(stored), alg)

    # --------------------------------------------------------------- reads
    def _pread_checked(self, ext: Extent) -> bytes:
        data = os.pread(self._fd, ext.disk_len, ext.disk)
        if len(data) != ext.disk_len or self._csum(data) != ext.crc:
            raise StoreError(
                f"blockstore: csum mismatch at {ext!r} "
                f"(stored {ext.crc:#x}, got {self._csum(data):#x})")
        if ext.alg:
            from ceph_tpu.compressor import CompressorError, cached
            try:
                data = cached(ext.alg).decompress(data)
            except CompressorError as e:
                # integrity failures must surface uniformly (scrub deep
                # pass catches StoreError as a shard error)
                raise StoreError(f"blockstore: {ext!r}: {e}")
            if len(data) != ext.length:
                raise StoreError(
                    f"blockstore: decompressed length mismatch at "
                    f"{ext!r}")
        return data

    def _read_onode(self, on: Onode, off: int, length: int) -> bytes:
        if length < 0:
            length = on.size - off
        length = max(0, min(length, on.size - off))
        out = bytearray(length)
        for ext in on.extents:
            e_end = ext.logical + ext.length
            if e_end <= off or ext.logical >= off + length:
                continue
            data = self._pread_checked(ext)
            s = max(off, ext.logical)
            e = min(off + length, e_end)
            out[s - off:e - off] = data[s - ext.logical:e - ext.logical]
        return bytes(out)

    def read(self, cid, oid, off: int = 0, length: int = -1) -> bytes:
        return self._read_onode(self._get_onode(cid, oid), off, length)

    def stat(self, cid, oid) -> Dict[str, int]:
        on = self._get_onode(cid, oid)
        return {"size": on.size}

    def getattr(self, cid, oid, name: str) -> bytes:
        on = self._get_onode(cid, oid)
        if name not in on.attrs:
            raise StoreError(f"no attr {name!r} on {oid}")
        return on.attrs[name]

    def getattrs(self, cid, oid) -> Dict[str, bytes]:
        return dict(self._get_onode(cid, oid).attrs)

    def omap_get(self, cid, oid) -> Tuple[bytes, Dict[bytes, bytes]]:
        on = self._get_onode(cid, oid)
        pre = _omap_key(cid, oid, b"")
        out = {}
        for k in self._kv_keys(_PREFIX_OMAP, pre):
            out[k[len(pre):]] = self._kv_get(_PREFIX_OMAP, k)
        return on.omap_header, out

    def omap_get_values(self, cid, oid, keys) -> Dict[bytes, bytes]:
        self._get_onode(cid, oid)          # existence check
        out = {}
        for k in keys:
            v = self._kv_get(_PREFIX_OMAP, _omap_key(cid, oid, k))
            if v is not None:
                out[k] = v
        return out

    def omap_get_header(self, cid, oid) -> bytes:
        return self._get_onode(cid, oid).omap_header

    def list_collections(self) -> List[CollectionId]:
        return [CollectionId(k.decode())
                for k in self._kv_keys(_PREFIX_COLL)]

    def collection_exists(self, cid) -> bool:
        return self._coll_exists(cid)

    def collection_list(self, cid, start: Optional[ObjectId] = None,
                        max_count: int = 2**31) -> List[ObjectId]:
        if not self._coll_exists(cid):
            raise NoSuchCollection(str(cid))
        pre = cid.name.encode() + b"\x00"
        oids = []
        for k in self._kv_keys(_PREFIX_ONODE, pre):
            oids.append(ObjectId.from_bytes(k[len(pre):]))
        oids.sort(key=lambda o: o.sort_key())
        if start is not None:
            oids = [o for o in oids if o.sort_key() > start.sort_key()]
        return oids[:max_count]

    # ---------------------------------------------------------- inspection
    def statfs(self) -> Dict[str, int]:
        """df-style usage (ObjectStore::statfs)."""
        total = self.alloc.device_size
        return {"total": total, "free": self.alloc.free_bytes(),
                "used": total - self.alloc.free_bytes()}


def _align_up(n: int) -> int:
    return (n + MIN_ALLOC - 1) // MIN_ALLOC * MIN_ALLOC


def _prefix_end(pre: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every string starting with pre."""
    b = bytearray(pre)
    while b and b[-1] == 0xFF:
        b.pop()
    if not b:
        return None
    b[-1] += 1
    return bytes(b)
