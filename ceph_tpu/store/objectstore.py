"""ObjectStore: transactional local object persistence API.

Reference parity: os/ObjectStore.h:68 (collections of objects carrying
byte data + xattrs + omap, mutated only through atomic ``Transaction``
batches with on_applied/on_commit callbacks; factory os/ObjectStore.cc:63).
Redesigned: Transactions are Encodable op-lists (so stores can WAL them
verbatim), apply is synchronous single-writer per store, and completion
callbacks fire in submission order.  Backends: MemStore (tests/OSD logic
without disks) and FileStore (WAL journal + checkpoint, filestore.py).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ceph_tpu.common.encoding import Decoder, Encodable, Encoder
from ceph_tpu.store.types import CollectionId, ObjectId

# op codes (subset of os/ObjectStore.h:345-388 that the data plane uses)
OP_NOP = 0
OP_TOUCH = 9
OP_WRITE = 10
OP_ZERO = 11
OP_TRUNCATE = 12
OP_REMOVE = 13
OP_SETATTR = 14
OP_SETATTRS = 15
OP_RMATTR = 16
OP_CLONE = 17
OP_CLONERANGE2 = 30
OP_MKCOLL = 20
OP_RMCOLL = 21
OP_OMAP_CLEAR = 31
OP_OMAP_SETKEYS = 32
OP_OMAP_RMKEYS = 33
OP_OMAP_SETHEADER = 34
OP_OMAP_RMKEYRANGE = 37
OP_COLL_MOVE_RENAME = 38
OP_TRY_RENAME = 41


class TxOp(Encodable):
    __slots__ = ("op", "cid", "oid", "oid2", "cid2", "off", "length",
                 "dest_off", "name", "data", "kv", "keys")

    def __init__(self, op: int, cid: CollectionId,
                 oid: Optional[ObjectId] = None,
                 oid2: Optional[ObjectId] = None,
                 cid2: Optional[CollectionId] = None,
                 off: int = 0, length: int = 0, dest_off: int = 0,
                 name: str = "", data: bytes = b"",
                 kv: Optional[Dict[bytes, bytes]] = None,
                 keys: Optional[List[bytes]] = None):
        self.op = op
        self.cid = cid
        self.oid = oid
        self.oid2 = oid2
        self.cid2 = cid2
        self.off = off
        self.length = length
        self.dest_off = dest_off
        self.name = name
        self.data = data
        self.kv = kv or {}
        self.keys = keys or []

    def encode_payload(self, enc: Encoder) -> None:
        enc.u8(self.op).struct(self.cid)
        enc.opt_struct(self.oid).opt_struct(self.oid2).opt_struct(self.cid2)
        enc.u64(self.off).u64(self.length).u64(self.dest_off)
        enc.string(self.name).bytes_(self.data)
        enc.map_(self.kv, lambda e, k: e.bytes_(k), lambda e, v: e.bytes_(v))
        enc.list_(self.keys, lambda e, k: e.bytes_(k))

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "TxOp":
        op = dec.u8()
        cid = dec.struct(CollectionId)
        oid = dec.opt_struct(ObjectId)
        oid2 = dec.opt_struct(ObjectId)
        cid2 = dec.opt_struct(CollectionId)
        off, length, dest_off = dec.u64(), dec.u64(), dec.u64()
        name, data = dec.string(), dec.bytes_()
        kv = dec.map_(lambda d: d.bytes_(), lambda d: d.bytes_())
        keys = dec.list_(lambda d: d.bytes_())
        return cls(op, cid, oid, oid2, cid2, off, length, dest_off,
                   name, data, kv, keys)


class Transaction(Encodable):
    """Atomic mutation batch (os/ObjectStore.h:209-239 builder methods).

    Lazy-payload copy discipline (msg/payload.py): a txn sealed into a
    message is shared between the sender's store apply, the wire
    encoder, and — under ms_local_delivery — the receivers themselves.
    ``freeze()`` seals it (builders then fail loudly); a receiver that
    must mutate (save_meta appends) takes ``mutable_copy()``, which is
    a shallow op-list copy: TxOps are immutable once built, so sharing
    them is safe and copies stay O(ops), never O(bytes)."""

    __slots__ = ("ops",)

    def __init__(self):
        self.ops: List[TxOp] = []

    def empty(self) -> bool:
        return not self.ops

    def freeze(self) -> "Transaction":
        """Seal against mutation: ops becomes a tuple, so any builder
        append raises AttributeError (freeze-and-assert)."""
        if isinstance(self.ops, list):
            self.ops = tuple(self.ops)
        return self

    @property
    def frozen(self) -> bool:
        return isinstance(self.ops, tuple)

    def mutable_copy(self) -> "Transaction":
        t = Transaction()
        t.ops = list(self.ops)
        return t

    def approx_size(self) -> int:
        """Byte-budget estimate without encoding (intake gates)."""
        n = 32
        for op in self.ops:
            n += 64 + len(op.data) + len(op.name)
            for k, v in op.kv.items():
                n += len(k) + len(v)
            for k in op.keys:
                n += len(k)
        return n

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    # --- builders ---
    def nop(self):
        self.ops.append(TxOp(OP_NOP, CollectionId.meta())); return self

    def touch(self, cid, oid):
        self.ops.append(TxOp(OP_TOUCH, cid, oid)); return self

    def write(self, cid, oid, off: int, data: bytes):
        self.ops.append(TxOp(OP_WRITE, cid, oid, off=off,
                             length=len(data), data=bytes(data)))
        return self

    def zero(self, cid, oid, off: int, length: int):
        self.ops.append(TxOp(OP_ZERO, cid, oid, off=off, length=length))
        return self

    def truncate(self, cid, oid, size: int):
        self.ops.append(TxOp(OP_TRUNCATE, cid, oid, off=size)); return self

    def remove(self, cid, oid):
        self.ops.append(TxOp(OP_REMOVE, cid, oid)); return self

    def setattr(self, cid, oid, name: str, value: bytes):
        self.ops.append(TxOp(OP_SETATTR, cid, oid, name=name,
                             data=bytes(value)))
        return self

    def setattrs(self, cid, oid, attrs: Dict[str, bytes]):
        kv = {k.encode("utf-8"): bytes(v) for k, v in attrs.items()}
        self.ops.append(TxOp(OP_SETATTRS, cid, oid, kv=kv)); return self

    def rmattr(self, cid, oid, name: str):
        self.ops.append(TxOp(OP_RMATTR, cid, oid, name=name)); return self

    def clone(self, cid, oid, newoid):
        self.ops.append(TxOp(OP_CLONE, cid, oid, oid2=newoid)); return self

    def clone_range(self, cid, oid, newoid, srcoff, length, dstoff):
        self.ops.append(TxOp(OP_CLONERANGE2, cid, oid, oid2=newoid,
                             off=srcoff, length=length, dest_off=dstoff))
        return self

    def create_collection(self, cid):
        self.ops.append(TxOp(OP_MKCOLL, cid)); return self

    def remove_collection(self, cid):
        self.ops.append(TxOp(OP_RMCOLL, cid)); return self

    def collection_move_rename(self, oldcid, oldoid, newcid, newoid):
        self.ops.append(TxOp(OP_COLL_MOVE_RENAME, oldcid, oldoid,
                             oid2=newoid, cid2=newcid))
        return self

    def try_rename(self, cid, oldoid, newoid):
        self.ops.append(TxOp(OP_TRY_RENAME, cid, oldoid, oid2=newoid))
        return self

    def omap_clear(self, cid, oid):
        self.ops.append(TxOp(OP_OMAP_CLEAR, cid, oid)); return self

    def omap_setkeys(self, cid, oid, kv: Dict[bytes, bytes]):
        self.ops.append(TxOp(OP_OMAP_SETKEYS, cid, oid,
                             kv={bytes(k): bytes(v) for k, v in kv.items()}))
        return self

    def omap_rmkeys(self, cid, oid, keys):
        self.ops.append(TxOp(OP_OMAP_RMKEYS, cid, oid,
                             keys=[bytes(k) for k in keys]))
        return self

    def omap_rmkeyrange(self, cid, oid, first: bytes, last: bytes):
        self.ops.append(TxOp(OP_OMAP_RMKEYRANGE, cid, oid,
                             keys=[bytes(first), bytes(last)]))
        return self

    def omap_setheader(self, cid, oid, header: bytes):
        self.ops.append(TxOp(OP_OMAP_SETHEADER, cid, oid,
                             data=bytes(header)))
        return self

    def encode_payload(self, enc: Encoder) -> None:
        enc.list_(self.ops, lambda e, op: e.struct(op))

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "Transaction":
        t = cls()
        t.ops = dec.list_(lambda d: d.struct(TxOp))
        return t


class StoreError(Exception):
    pass


class NoSuchCollection(StoreError):
    pass


class NoSuchObject(StoreError):
    pass


class ObjectStore:
    """Abstract store (factory: create())."""

    def __init__(self, path: str = ""):
        self.path = path
        self.applied_seq = 0

    @staticmethod
    def create(kind: str, path: str = "") -> "ObjectStore":
        # reference factory os/ObjectStore.cc:63-87
        from ceph_tpu.store.memstore import MemStore
        from ceph_tpu.store.filestore import FileStore
        if kind == "memstore":
            return MemStore(path)
        if kind == "filestore":
            return FileStore(path)
        if kind == "blockstore":
            from ceph_tpu.store.blockstore import BlockStore
            return BlockStore(path)
        if kind == "kstore":
            from ceph_tpu.store.kstore import KStore
            return KStore(path)
        raise ValueError(f"unknown objectstore kind {kind!r}")

    # lifecycle
    def mkfs(self) -> None: ...
    def mount(self) -> None: ...
    def umount(self) -> None: ...

    # writes
    def queue_transactions(
            self, txns: List[Transaction],
            on_applied: Optional[Callable[[], None]] = None,
            on_commit: Optional[Callable[[], None]] = None) -> None:
        raise NotImplementedError

    def apply_transaction(self, txn: Transaction) -> None:
        """Apply txn and return once it is DURABLE: queue + drain the
        commit pipeline.  Callers that can tolerate deferred durability
        (the OSD's hot write path) use queue_transactions with an
        on_commit callback instead and keep working while the group
        commits."""
        self.queue_transactions([txn])
        self.sync()

    def sync(self) -> None:
        """Block until every queued transaction is durable (the
        reference ObjectStore::sync / flush_commit role).  Stores with
        synchronous commit have nothing to wait for."""

    def commit_counters(self) -> Dict[str, float]:
        """Group-commit pipeline counters (commit_batches, txns,
        fsyncs, txns_per_batch, ...); empty for synchronous stores."""
        return {}

    # reads
    def read(self, cid, oid, off: int = 0, length: int = -1) -> bytes:
        raise NotImplementedError

    def stat(self, cid, oid) -> Dict[str, int]:
        raise NotImplementedError

    def exists(self, cid, oid) -> bool:
        try:
            self.stat(cid, oid)
            return True
        except StoreError:
            return False

    def getattr(self, cid, oid, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid, oid) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid, oid) -> Tuple[bytes, Dict[bytes, bytes]]:
        raise NotImplementedError

    def omap_get_values(self, cid, oid, keys) -> Dict[bytes, bytes]:
        omap = self.omap_get(cid, oid)[1]
        return {k: omap[k] for k in keys if k in omap}

    def omap_get_header(self, cid, oid) -> bytes:
        """Header-only read; backends override so hot per-object cls
        methods don't materialize the whole omap for it."""
        return self.omap_get(cid, oid)[0]

    def list_collections(self) -> List[CollectionId]:
        raise NotImplementedError

    def collection_exists(self, cid) -> bool:
        return cid in self.list_collections()

    def collection_list(self, cid, start: Optional[ObjectId] = None,
                        max_count: int = 2**31) -> List[ObjectId]:
        raise NotImplementedError
