"""FileStore: durable ObjectStore with write-ahead journal + checkpoints.

Reference parity: os/filestore/FileStore.cc + FileJournal (journal-ahead
writes, replay on mount) and BlueStore's WAL idea distilled.  Redesigned:
state lives in memory (MemStore apply semantics), durability comes from a
checksummed WAL of encoded Transactions plus an atomically-replaced
checkpoint of the full store — the same snapshot+log recipe as kv.FileDB.
``queue_transactions`` returns after the WAL record is fsync'd, so
on_commit == journal-durable exactly like the reference's journaled mode
(JournalingObjectStore).  A torn WAL tail is discarded on replay.
"""

from __future__ import annotations

import os
from typing import Dict, List

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.store.memstore import MemStore, Obj
from ceph_tpu.store.objectstore import StoreError, Transaction
from ceph_tpu.store.types import CollectionId, ObjectId
from ceph_tpu.store.wal import WriteAheadLog, atomic_snapshot

_MAGIC = b"CTFS\x01"


class KilledAt(StoreError):
    """Injected crash (filestore_kill_at role, config_opts.h:1171):
    the store dies mid-write-path; the test re-mounts and checks the
    recovered state is an exact transaction-boundary prefix."""


class FileStore(MemStore):
    COMPACT_BYTES = 64 << 20

    def __init__(self, path: str):
        if not path:
            raise StoreError("filestore requires a path")
        super().__init__(path)
        self.committed_seq = 0
        self._wal = None
        #: crash injection countdown (0 = off).  N > 0: die AFTER the
        #: Nth batch's WAL records are durable but BEFORE the in-memory
        #: apply (journal replay must recover it).  N < 0: die BEFORE
        #: the |N|th batch touches the WAL (the txn must vanish).
        self.kill_at = 0

    # --- paths ---
    def _ckpt_path(self):
        return os.path.join(self.path, "checkpoint")

    def _wal_path(self):
        return os.path.join(self.path, "wal")

    # --- lifecycle ---
    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, "fsid"), "wb") as f:
            f.write(_MAGIC)

    def mount(self) -> None:
        if not os.path.exists(os.path.join(self.path, "fsid")):
            raise StoreError(f"{self.path}: not a filestore (run mkfs)")
        self._load_checkpoint()
        self._wal = WriteAheadLog(self._wal_path())
        for seq, payload in self._wal.replay():
            if seq > self.committed_seq:
                self._apply(Transaction.from_bytes(payload))
                self.committed_seq = seq
        self.applied_seq = self.committed_seq
        self.mounted = True

    def umount(self) -> None:
        if self._wal is not None and not self._wal.closed:
            if self._wal.size() > 0:   # snapshot already current otherwise
                self.checkpoint()
            self._wal.close()
        self.mounted = False

    # --- write path ---
    def queue_transactions(self, txns: List[Transaction],
                           on_applied=None, on_commit=None):
        if not self.mounted:
            raise StoreError("not mounted")
        if self.kill_at < 0:
            self.kill_at += 1
            if self.kill_at == 0:
                self._die("before journal")
        # journal-ahead: encode + fsync all records, then apply in memory
        recs = [(self.committed_seq + 1 + i, t.to_bytes())
                for i, t in enumerate(txns)]
        self._wal.append_many(recs)
        if self.kill_at > 0:
            self.kill_at -= 1
            if self.kill_at == 0:
                self._die("after journal, before apply")
        self.committed_seq += len(txns)   # only after records are durable
        for t in txns:
            self._apply(t)
        self.applied_seq = self.committed_seq
        if on_applied:
            on_applied()
        if on_commit:
            on_commit()
        if self._wal.size() > self.COMPACT_BYTES:
            self.checkpoint()

    def _die(self, where: str) -> None:
        """Injected crash: the store must look DEAD — in particular the
        WAL handle closes WITHOUT checkpoint/rotate, or a well-meaning
        try/finally umount() would snapshot the stale pre-apply state
        and truncate the very record the injection proved durable."""
        self.mounted = False
        if self._wal is not None and not self._wal.closed:
            self._wal.close()
        raise KilledAt(where)

    # --- checkpoint / replay ---
    def checkpoint(self) -> None:
        enc = Encoder()
        enc.u64(self.committed_seq)
        enc.u32(len(self.colls))
        for cid in sorted(self.colls):
            enc.struct(cid)
            objs = self.colls[cid]
            enc.u32(len(objs))
            for oid, o in objs.items():
                enc.struct(oid)
                enc.bytes_(bytes(o.data))
                enc.map_({k.encode("utf-8"): v for k, v in o.xattrs.items()},
                         lambda e, k: e.bytes_(k), lambda e, v: e.bytes_(v))
                enc.map_(o.omap, lambda e, k: e.bytes_(k),
                         lambda e, v: e.bytes_(v))
                enc.bytes_(o.omap_header)
        atomic_snapshot(self._ckpt_path(), enc.getvalue())
        if self._wal is None:
            self._wal = WriteAheadLog(self._wal_path())
            self._wal.open()
        self._wal.rotate()

    def _load_checkpoint(self) -> None:
        self.colls = {}
        try:
            with open(self._ckpt_path(), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        dec = Decoder(data)
        self.committed_seq = dec.u64()
        ncoll = dec.u32()
        for _ in range(ncoll):
            cid = dec.struct(CollectionId)
            nobj = dec.u32()
            objs: Dict[ObjectId, Obj] = {}
            for _ in range(nobj):
                oid = dec.struct(ObjectId)
                o = Obj()
                o.data = bytearray(dec.bytes_())
                o.xattrs = {k.decode("utf-8"): v for k, v in dec.map_(
                    lambda d: d.bytes_(), lambda d: d.bytes_()).items()}
                o.omap = dec.map_(lambda d: d.bytes_(), lambda d: d.bytes_())
                o.omap_header = dec.bytes_()
                objs[oid] = o
            self.colls[cid] = objs
        self.applied_seq = self.committed_seq

