"""Shared write-ahead log: checksummed append records + torn-tail recovery.

Reference parity: os/filestore/FileJournal (journal-ahead rule: a record is
durable once fsync'd; replay discards a torn tail).  One helper serves both
the kv backend (kv.FileDB) and the object store (filestore.FileStore) so the
record framing, replay, truncation and rotation logic exist exactly once.

Recovery contract: ``replay()`` returns the valid (seq, payload) records AND
truncates the file to the last valid byte, so records appended after a
recovered crash are reachable by the next replay (appending after garbage
would orphan them).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import List, Tuple

_REC_HDR = struct.Struct("<IIQ")   # crc32, payload_len, seq


def fsync_dir(path: str) -> None:
    """Durably persist a directory entry (after os.replace/creat)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class WriteAheadLog:
    def __init__(self, path: str):
        self.path = path
        self._f = None

    def replay(self) -> List[Tuple[int, bytes]]:
        """Read valid records, truncate any torn tail, open for append."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            data = b""
        records: List[Tuple[int, bytes]] = []
        off = valid_end = 0
        while off + _REC_HDR.size <= len(data):
            crc, ln, seq = _REC_HDR.unpack_from(data, off)
            payload = data[off + _REC_HDR.size: off + _REC_HDR.size + ln]
            if len(payload) != ln or zlib.crc32(payload) != crc:
                break  # torn tail: discard the rest
            records.append((seq, payload))
            off += _REC_HDR.size + ln
            valid_end = off
        if valid_end < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(valid_end)
                f.flush()
                os.fsync(f.fileno())
        self._f = open(self.path, "ab")
        return records

    def open(self) -> None:
        if self._f is None or self._f.closed:
            self._f = open(self.path, "ab")

    def append(self, seq: int, payload: bytes, sync: bool = True) -> None:
        self.append_many([(seq, payload)], sync=sync)

    def append_many(self, recs: List[Tuple[int, bytes]],
                    sync: bool = True) -> None:
        buf = bytearray()
        for seq, payload in recs:
            buf += _REC_HDR.pack(zlib.crc32(payload), len(payload), seq)
            buf += payload
        good = self._f.tell()
        try:
            self._f.write(buf)
            self._f.flush()
            if sync:
                os.fsync(self._f.fileno())
        except OSError:
            # a partial record mid-log would orphan every later fsync'd
            # record at the next replay (CRC scan stops at the tear) —
            # roll the file back to the last good byte before re-raising
            try:
                self._f.truncate(good)
                self._f.seek(good)
            except OSError:
                pass
            raise

    def size(self) -> int:
        return self._f.tell() if self._f else 0

    def rotate(self) -> None:
        """Empty the log (after the caller persisted a snapshot)."""
        self._f.close()
        self._f = open(self.path, "wb")
        self._f.flush()
        os.fsync(self._f.fileno())

    @property
    def closed(self) -> bool:
        return self._f is None or self._f.closed

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.close()


def atomic_snapshot(path: str, data: bytes) -> None:
    """Atomically replace ``path`` with data, durably: write sidecar tmp,
    fsync it, rename over, fsync the directory (rename must hit disk
    before the caller empties its WAL — the snapshot+log crash rule)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")
