"""Group-commit thread: many in-flight transactions share one fsync.

Reference parity: os/bluestore/BlueStore.cc ``_kv_sync_thread`` — the
event loop (or op threads) stage transactions cheaply in memory and a
dedicated thread drains the backlog, issuing ONE data-device barrier and
ONE atomic kv submit for the whole group, then completes the commit
callbacks in submission order.  The store's ``queue_transactions``
becomes "apply + enqueue"; durability (and therefore repop acks, client
acks, pglog last_complete) rides the callback.

Invariants the thread preserves:
  * data before metadata — the group's data fsync happens strictly
    before its kv records are made durable (COW crash rule);
  * submission order — kv records are logged in seq order and commit
    callbacks fire in the exact order transactions were submitted;
  * bounded backlog — the queue is bounded; a producer outrunning the
    disk blocks on enqueue (Throttle role) instead of ballooning RAM.

Fault injection for crash-ordering tests: ``crash_at`` kills the thread
at a named point ("before_data_sync" | "before_kv") leaving the store
exactly as a power cut at that instant would; ``trace`` observes the
stage sequence without perturbing it.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional

from ceph_tpu.common.lockdep import make_thread_lock
from ceph_tpu.common.perf_counters import PerfCounters

_log = logging.getLogger("ceph-tpu.store.commit")

_STOP = object()

#: Deterministic-simulation switch (devtools/schedule.py): when True,
#: threads STARTED from then on run INLINE — no kv-sync thread is
#: spawned; corked groups commit synchronously at the loop-side flush
#: point.  The commit code path (_commit, fault injection, counters,
#: callback posting) is byte-identical; only the thread handoff — the
#: one nondeterministic interleaving the schedule explorer cannot
#: control — is removed.  Never set outside a sim run.
SIM_INLINE = False

#: Observer hook for the schedule explorer's commit-order invariant
#: ("no ack before durability"): called as OBSERVER(store_name, event,
#: item_indices) with event in {"committed", "callbacks", "crashed"}.
#: None (the default) costs one attribute load per group.
OBSERVER: Optional[Callable[[str, str, List[int]], None]] = None


class _Item:
    """One staged transaction's PORTABLE commit record: plain scalars
    only.  The loop-bound on_commit/post closures never ride the
    kv-sync queue — they stay in the submitter-side ``_cbs`` table
    keyed by ``idx``, and completion crosses back as an idx-keyed
    record the owning lane resolves (the process-lane form the seam
    inventory prescribed)."""

    __slots__ = ("seq", "wrote_data", "t0", "idx")

    def __init__(self, seq, wrote_data, idx=0):
        self.seq = seq
        self.wrote_data = wrote_data
        self.t0 = time.perf_counter()
        #: process-unique submission index (the seq field is
        #: store-assigned and 0 for RAM stores): the explorer's
        #: phantom-ack check keys on this, and the callback table
        #: (_cbs) is keyed by it
        self.idx = idx


class InjectedCrash(Exception):
    """Raised on the commit thread by the crash_at fault hook."""


class KVSyncThread:
    """One per mounted store.

    data_sync() -- durability barrier for the data device (optional).
    kv_sync(upto_seq) -- make every staged kv record with seq <=
    upto_seq durable in ONE atomic submit (optional).
    """

    QUEUE_MAX = 1024        # backlog bound (bluestore throttle role)
    _instances = 0          # name-uniquifier (see __init__)

    def __init__(self, name: str,
                 data_sync: Optional[Callable[[], None]] = None,
                 kv_sync: Optional[Callable[[int], None]] = None,
                 queue_max: int = QUEUE_MAX,
                 gather_window: float = 0.0,
                 auto_tune: bool = True,
                 ack_on_apply: bool = False):
        # unique per instance: co-located stores of the same backend
        # (a 4-OSD in-process cluster = four "memstore_commit"s) must
        # be distinguishable in the schedule explorer's commit-order
        # observations; mount order is deterministic under sim
        KVSyncThread._instances += 1
        self.name = f"{name}#{KVSyncThread._instances}"
        self.data_sync = data_sync
        self.kv_sync = kv_sync
        #: seconds to linger after the first item of a group so bursts
        #: coalesce.  Stores whose commit has real cost (fsync) batch
        #: naturally and leave this 0; RAM-backed stores set a tiny
        #: window so group commit still engages under concurrency.
        #: This is the STATIC base; with auto_tune the effective window
        #: tracks the observed barrier cost instead (see
        #: _effective_window) — lingering longer than a barrier costs
        #: buys nothing, and a static guess on a device whose fsync is
        #: 4x slower under-batches by the same factor.
        self.gather_window = gather_window
        #: sharded-data-plane opt-in (the OSD sets it for stores it
        #: mounts while the plane is enabled): a store with NO
        #: durability hooks may then commit groups inline at the
        #: cork-flush point instead of paying the thread handoff —
        #: see start().  Off = today's threaded behavior, bit-for-bit
        #: (osd_op_num_shards=1 and standalone stores keep it off).
        self.ack_on_apply = ack_on_apply
        #: adapt the window to the measured barrier latency (EWMA),
        #: clamped to [0, 4x the static value].  Only engages on stores
        #: with a REAL barrier hook — a RAM store has no fsync signal
        #: to tune from and keeps its static window.
        self.auto_tune = auto_tune
        self._barrier_ewma: Optional[float] = None
        self.perf = PerfCounters(name)
        for key in ("commit_batches", "txns", "data_fsyncs", "kv_syncs",
                    "fsyncs_saved"):
            self.perf.add_u64(key)
        self.perf.add_avg("txns_per_batch")
        self.perf.add_avg("commit_inflight")
        self.perf.add_time("commit_lat")
        # full latency distribution (perf_histogram role): the mean
        # above hides the p99 the op tracer's commit-group-wait stage
        # needs to be checked against
        self.perf.add_hist("commit_lat_hist")
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_max)
        #: idx -> (on_commit, post, loop): the submitter-side half of
        #: the idx-keyed completion records.  Closures never cross the
        #: kv-sync seam — _complete ships idx lists back to each loop
        #: and _run_completion_records resolves them HERE, under the
        #: same lock every side already takes for _submitted
        self._cbs: dict = {}
        self._thread: Optional[threading.Thread] = None
        # lockdep-wrapped when the sanitizer is on: the commit thread
        # holds this while the event loop submits, so an ordering slip
        # against the store's own locks is a real deadlock class
        self._lock = make_thread_lock(f"kvsync:{name}:_lock")
        self._cv = threading.Condition(self._lock)
        self._submitted = 0
        self._completed = 0
        # event-loop-side cork: submissions staged within one loop pass
        # ship to the thread as ONE queue put (one lock round + one GIL
        # handoff per pass instead of per transaction — the handoffs,
        # not the queue, are what tax a busy event loop).  Staging is
        # keyed PER LOOP: under the sharded data plane (osd/shards.py)
        # several shard loops submit to one store concurrently, and a
        # shared list would lose wakeups across threads.  Per-loop FIFO
        # is the order that matters (a PG lives on exactly one shard).
        self._staged: dict = {}          # id(loop) -> List[_Item]
        self._flush_scheduled: dict = {}  # id(loop) -> bool
        self.dead = False           # crashed (fault injection) or error
        # --- test hooks ---
        self.trace: Optional[Callable[[str, int], None]] = None
        self.crash_at: Optional[str] = None
        #: occurrence-indexed crash injection: skip this many hits of
        #: crash_at's point before raising — the schedule explorer
        #: enumerates (point, occurrence) pairs, not just first-hit
        self.crash_skip = 0
        self.gate: Optional[threading.Event] = None   # holds the thread
        #     before it takes its next group (deterministic batching)
        #: captured at start(): inline (sim) vs threaded commit
        self._inline = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if SIM_INLINE:
            self._inline = True
            return
        if self.ack_on_apply and self.data_sync is None \
                and self.kv_sync is None:
            # ack-on-apply (ROADMAP: "tighter gather window or
            # ack-on-apply semantics where safe"): a RAM-backed store
            # has NO durability point beyond the apply — no data
            # barrier, no kv submit — so the commit thread would add
            # only a GIL handoff (5-15ms p50 on a busy event loop,
            # the tracer's repl_commit cost) between apply and ack.
            # Commit groups run inline at the cork-flush point
            # instead: the exact SIM_INLINE code path, so ordering,
            # observer hooks and crash injection are unchanged.
            self._inline = True
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kv_sync_thread")
        self._thread.start()

    def submit(self, seq: int = 0, wrote_data: bool = False,
               on_commit: Optional[Callable[[], None]] = None,
               post: Optional[Callable[[], None]] = None) -> None:
        """Enqueue one staged transaction batch.  Blocks (backpressure)
        when the commit backlog is full.  Captures the running event
        loop, if any, so callbacks are posted back to it; without a
        loop they run on the commit thread itself, still in order.

        With a loop, items cork on the loop side and ship to the thread
        once per loop pass (call_soon flush) — submission order within
        and across passes is preserved."""
        loop = None
        try:
            import asyncio
            loop = asyncio.get_running_loop()
        except RuntimeError:
            pass
        with self._lock:
            self._submitted += 1
            idx = self._submitted
            if on_commit is not None or post is not None:
                self._cbs[idx] = (on_commit, post, loop)
        rec = _Item(seq, wrote_data, idx=idx)
        if loop is None:
            if self._inline:
                self._run_group([rec])
            else:
                # the record is plain scalars (seq/wrote_data/idx/t0):
                # the loop-bound callbacks stayed in _cbs on this side
                self._q.put([rec])
            return
        key = id(loop)
        # gil-atomic:begin _staged,_flush_scheduled per-loop staging
        # keyed by id(loop): each loop only ever touches ITS OWN key
        # from its own thread; the dict inserts themselves are single
        # GIL steps, so foreign-key traffic (teardown's _flush_staged
        # sweep) can race only per-key pops, never corrupt the dict
        self._staged.setdefault(key, []).append(rec)
        if not self._flush_scheduled.get(key):
            self._flush_scheduled[key] = True
            loop.call_soon(self._flush_one, key)
        # gil-atomic:end

    def _flush_one(self, key: int) -> None:
        """Ship one loop's corked items (runs ON that loop)."""
        # gil-atomic:begin _staged,_flush_scheduled the per-key pop is
        # one GIL step: racing the owning loop's own flush is safe —
        # exactly one side ships each staged list
        self._flush_scheduled[key] = False
        recs = self._staged.pop(key, None)
        # gil-atomic:end
        if not recs:
            return
        if self._inline:
            # sim mode: the loop-pass cork IS the commit group; no
            # thread handoff, no gather linger — deterministic
            self._run_group(recs)
        else:
            self._q.put(recs)

    def _flush_staged(self) -> None:
        """Ship the CALLING loop's corked items now (flush()/stop()
        path).  With no running loop — tools, teardown — OR in inline
        (ack-on-apply / sim) mode, ship EVERY loop's residue: inline
        groups commit synchronously wherever they run, and a
        teardown-time flush from the intake thread must not leave a
        shard loop's staged group behind (its scheduled cork flush
        may never run once the daemon stops).  The per-key pop is
        GIL-atomic, so racing the owning loop's own flush is safe —
        exactly one side ships each list."""
        try:
            import asyncio
            key = id(asyncio.get_running_loop())
        except RuntimeError:
            key = None
        if key is not None and not self._inline:
            self._flush_one(key)
            return
        for k in list(self._staged):
            self._flush_one(k)

    def _run_group(self, group: List[_Item]) -> None:
        """One group through the commit path, on the calling thread
        (inline sim mode).  Identical failure semantics to _run: an
        injected crash or commit error kills the store 'thread'."""
        if self.dead:
            self._finish(group)
            return
        try:
            self._commit(group)
        except InjectedCrash:
            self.dead = True
            self._finish(group)
        except Exception:
            _log.exception("inline commit failed; store is dead")
            self.dead = True
            self._finish(group)

    def flush(self, timeout: float = 60.0) -> None:
        """Wait until every submitted batch is durable (callbacks may
        still be pending on their event loop).  Ships any corked items
        first.  Call from the submitting (event-loop) thread or from
        loop-less code — a foreign thread racing the loop's scheduled
        cork flush could put groups out of submission order.  Raises
        when the thread is dead: returning quietly would let
        sync()/apply_transaction report durability that never
        happened."""
        self._flush_staged()
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._completed < self._submitted and not self.dead \
                    and not self._inline:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("commit flush timed out")
                self._cv.wait(left)
        if self.dead:
            from ceph_tpu.store.objectstore import StoreError
            raise StoreError("commit thread is dead; queued "
                             "transactions were never made durable")

    def stop(self) -> None:
        if self._inline:
            if not self.dead:
                try:
                    self.flush()
                except Exception:
                    pass
            return
        if self._thread is None:
            return
        if not self.dead:
            try:
                self.flush()
            except Exception:
                pass   # teardown is best-effort; dead is handled below
        self._q.put(_STOP)
        self._thread.join(timeout=30.0)
        self._thread = None

    # ------------------------------------------------------------- internal
    def _run(self) -> None:
        while True:
            got = self._q.get()
            if got is _STOP:
                return
            if self.gate is not None:
                self.gate.wait()
            win = self._effective_window()
            if win > 0.0:
                # linger ONLY when more submissions are actually in
                # flight beyond what this group already holds: a lone
                # closed-loop writer (iodepth 1) is blocked on THIS
                # commit, so sleeping would add pure latency with zero
                # batching gain — the exact p50 floor the bench
                # measures.  Concurrent writers have submitted (or
                # corked) before blocking, so the backlog check sees
                # them.
                with self._lock:
                    backlog = self._submitted - self._completed
                if backlog > len(got):
                    time.sleep(win)
            group: List[_Item] = list(got)
            stop_after = False
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                group.extend(nxt)
            if self.dead:
                self._finish(group)     # crashed: account, do nothing
            else:
                try:
                    self._commit(group)
                except InjectedCrash:
                    self.dead = True
                    self._finish(group)
                except Exception:
                    _log.exception("commit thread failed; store is dead")
                    self.dead = True
                    self._finish(group)
            if stop_after:
                return

    def _inject(self, point: str, group: List[_Item]) -> None:
        if self.trace is not None:
            self.trace(point, len(group))
        if self.crash_at == point:
            if self.crash_skip > 0:
                # fault-injection hook: the schedule explorer arms it
                # on exactly one commit context at a time
                # lint: allow[ESC12] test hook, single armed commit context by construction
                self.crash_skip -= 1
            else:
                raise InjectedCrash(point)

    def _notify(self, event: str, group: List[_Item]) -> None:
        obs = OBSERVER
        if obs is not None:
            obs(self.name, event, [it.idx for it in group])

    def _effective_window(self) -> float:
        """The gather window actually slept: the EWMA of observed
        barrier cost, clamped to [0, 4x] of the static value — linger
        about as long as one barrier costs (that is exactly the span
        co-arriving transactions can share), never more than 4x the
        configured base.  Falls back to the static window while there
        is no auto-tune signal (disabled, no real barrier hooks, or no
        sample yet)."""
        base = self.gather_window
        if not self.auto_tune or self._barrier_ewma is None \
                or base <= 0.0:
            return base
        return min(max(self._barrier_ewma, 0.0), 4.0 * base)

    def _commit(self, group: List[_Item]) -> None:
        with self._lock:
            # backlog depth at group start (submitted-not-yet-durable):
            # the write-path pipelining evidence `perf dump` reports
            self.perf.tinc("commit_inflight",
                           self._submitted - self._completed)
        self._inject("before_data_sync", group)
        n_data = sum(1 for it in group if it.wrote_data)
        t_barrier0 = time.perf_counter()
        ran_barrier = False
        if n_data and self.data_sync is not None:
            self.data_sync()            # ONE barrier for the whole group
            self.perf.inc("data_fsyncs")
            ran_barrier = True
        self._inject("before_kv", group)
        if self.kv_sync is not None:
            # ONE atomic kv submit covering every record of the group,
            # strictly after the data barrier (data-before-metadata)
            self.kv_sync(max(it.seq for it in group))
            self.perf.inc("kv_syncs")
            ran_barrier = True
        if ran_barrier:
            dt = time.perf_counter() - t_barrier0
            self._barrier_ewma = dt if self._barrier_ewma is None \
                else 0.8 * self._barrier_ewma + 0.2 * dt
        self._inject("committed", group)
        self._notify("committed", group)
        now = time.perf_counter()
        self.perf.inc("commit_batches")
        self.perf.inc("txns", len(group))
        self.perf.tinc("txns_per_batch", len(group))
        # the synchronous path would have paid one data fsync per
        # data-writing txn plus one kv sync per txn; the group paid at
        # most one of each.  Only barriers this store ACTUALLY has
        # count — a RAM-backed store (no hooks) saves nothing.
        would_have = (n_data if self.data_sync is not None else 0) \
            + (len(group) if self.kv_sync is not None else 0)
        actual = (1 if n_data and self.data_sync is not None else 0) \
            + (1 if self.kv_sync is not None else 0)
        self.perf.inc("fsyncs_saved", max(0, would_have - actual))
        for it in group:
            self.perf.tinc("commit_lat", now - it.t0)
            self.perf.hinc("commit_lat_hist", now - it.t0)
        self._complete(group)
        with self._cv:
            self._completed += len(group)
            self._cv.notify_all()

    def _finish(self, group: List[_Item]) -> None:
        """Crashed path: account the items so flush() can't hang, but
        run NO callbacks — these transactions never committed.  Their
        completion records are PURGED (not delivered): a dead commit
        thread must never phantom-ack."""
        self._notify("crashed", group)
        with self._cv:
            for it in group:
                self._cbs.pop(it.idx, None)
            self._completed += len(group)
            self._cv.notify_all()

    def _complete(self, group: List[_Item]) -> None:
        self._notify("callbacks", group)
        # completions post PER SHARD LOOP, batched: one
        # call_soon_threadsafe wakeup per (loop, group) carrying the
        # idx-keyed completion RECORDS for that loop in submission
        # order — plain ints; the owning lane resolves them against
        # its _cbs half (the process-portable form of the old
        # closure-list handoff).  One wakeup per (loop, group), never
        # one per transaction.
        by_loop: dict = {}
        direct: List[int] = []
        with self._lock:
            metas = [(it.idx, self._cbs.get(it.idx)) for it in group]
        for idx, meta in metas:
            if meta is None:
                continue
            loop = meta[2]
            if loop is not None and not loop.is_closed():
                by_loop.setdefault(id(loop), (loop, []))[1].append(idx)
            else:
                direct.append(idx)
        if direct:
            # no submitting loop (tools, teardown): resolve on the
            # commit thread itself, still in order
            self._run_completion_records(direct)
        for loop, records in by_loop.values():
            try:
                loop.call_soon_threadsafe(
                    self._run_completion_records, records)
            except RuntimeError:
                self._run_completion_records(records)  # loop closed

    def _run_completion_records(self, records: List[int]) -> None:
        """Resolve idx-keyed completion records on the owning lane:
        pop each idx's callbacks from the submitter-side table and run
        them in record (== submission) order."""
        for idx in records:
            with self._lock:
                meta = self._cbs.pop(idx, None)
            if meta is None:
                continue
            for f in meta[:2]:
                if f is not None:
                    self._guard(f)

    @staticmethod
    def _guard(fn: Callable[[], None]) -> None:
        try:
            fn()
        except Exception:
            _log.exception("commit callback failed")

    # ---------------------------------------------------------- inspection
    def counters(self) -> dict:
        d = self.perf.dump()
        tpb = d.get("txns_per_batch", {})
        lat = d.get("commit_lat", {})
        inf = d.get("commit_inflight", {})
        hist = d.get("commit_lat_hist", {})
        n_b = tpb.get("avgcount", 0) or 0
        n_l = lat.get("avgcount", 0) or 0
        n_i = inf.get("avgcount", 0) or 0
        return {
            "commit_batches": d.get("commit_batches", 0),
            "txns": d.get("txns", 0),
            "data_fsyncs": d.get("data_fsyncs", 0),
            "kv_syncs": d.get("kv_syncs", 0),
            "fsyncs": d.get("data_fsyncs", 0) + d.get("kv_syncs", 0),
            "fsyncs_saved": d.get("fsyncs_saved", 0),
            "txns_per_batch": (tpb.get("sum", 0.0) / n_b) if n_b else 0.0,
            "commit_lat_ms": (lat.get("sum", 0.0) / n_l * 1e3)
            if n_l else 0.0,
            "commit_lat_p50_ms": hist.get("p50_ms", 0.0),
            "commit_lat_p99_ms": hist.get("p99_ms", 0.0),
            # auto-tune evidence: the window actually slept (EWMA of
            # barrier cost clamped to 4x static) + mean backlog depth
            "gather_window_ms": round(self._effective_window() * 1e3, 4),
            "gather_window_static_ms": round(self.gather_window * 1e3, 4),
            "commit_inflight": (inf.get("sum", 0.0) / n_i)
            if n_i else 0.0,
        }
