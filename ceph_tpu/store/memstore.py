"""MemStore: in-memory ObjectStore backend.

Reference parity: os/memstore/MemStore.cc (RAM-backed fake store used to run
OSD logic without disks).  Holds the canonical Transaction apply semantics
that FileStore reuses.

Apply is TOTAL: mutation ops never raise — destructive ops on missing
targets are no-ops, constructive ops create their collection/object, and
unknown op codes are skipped (forward compat, mirroring encoding's
skip-unknown rule).  This guarantees (a) transactions are atomic in the
only failure mode left (process crash, handled by the WAL), and (b) journal
replay can never poison a mount.  Validity checking (ENOENT for clients
etc.) is the PG/OSD layer's job, as in the reference where FileStore replay
tolerates what the op layer already vetted.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ceph_tpu.store.objectstore import (
    OP_CLONE, OP_CLONERANGE2, OP_COLL_MOVE_RENAME, OP_MKCOLL, OP_NOP,
    OP_OMAP_CLEAR, OP_OMAP_RMKEYRANGE, OP_OMAP_RMKEYS, OP_OMAP_SETHEADER,
    OP_OMAP_SETKEYS, OP_REMOVE, OP_RMATTR, OP_RMCOLL, OP_SETATTR,
    OP_SETATTRS, OP_TOUCH, OP_TRUNCATE, OP_TRY_RENAME, OP_WRITE, OP_ZERO,
    NoSuchCollection, NoSuchObject, ObjectStore, StoreError, Transaction,
    TxOp,
)
from ceph_tpu.store.types import CollectionId, ObjectId


class Obj:
    __slots__ = ("data", "xattrs", "omap", "omap_header")

    def __init__(self):
        self.data = bytearray()
        self.xattrs: Dict[str, bytes] = {}
        self.omap: Dict[bytes, bytes] = {}
        self.omap_header = b""

    def clone(self) -> "Obj":
        o = Obj()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        o.omap_header = self.omap_header
        return o


class MemStore(ObjectStore):
    #: gather window for the commit thread: RAM has no fsync cost to
    #: batch behind, so a tiny linger is what lets concurrent writers
    #: share one commit batch (and keeps callback ordering pipelined)
    GATHER_WINDOW = 0.0003

    def __init__(self, path: str = ""):
        super().__init__(path)
        self.colls: Dict[CollectionId, Dict[ObjectId, Obj]] = {}
        self.mounted = False
        self._committer = None

    # --- lifecycle ---
    def mkfs(self) -> None:
        self.colls = {}

    def mount(self) -> None:
        from ceph_tpu.store.commit import KVSyncThread
        self._committer = KVSyncThread(
            "memstore_commit", gather_window=self.GATHER_WINDOW,
            # set by the mounting OSD when its sharded data plane is
            # enabled: RAM stores then ack-on-apply (inline commit
            # groups — no barrier exists to wait for); default off =
            # today's threaded handoff, bit-for-bit
            ack_on_apply=getattr(self, "ack_on_apply", False))
        self._committer.start()
        self.mounted = True

    def umount(self) -> None:
        if self._committer is not None:
            self._committer.stop()
            self._committer = None
        self.mounted = False

    # --- write path ---
    def queue_transactions(self, txns, on_applied=None, on_commit=None):
        if self._committer is not None and self._committer.dead:
            # dead commit thread = acks would never fire: fail loudly
            raise StoreError("memstore commit thread is dead")
        for t in txns:
            self._apply(t)
        self.applied_seq += len(txns)
        if on_applied:
            on_applied()
        if on_commit is None:
            return            # memory state IS the committed state
        if self._committer is not None:
            # ride the group-commit thread: callbacks fire in
            # submission order and concurrent batches share one pass,
            # so the OSD's ack pipeline behaves like the durable stores
            self._committer.submit(on_commit=on_commit)
        else:
            on_commit()

    def sync(self) -> None:
        if self._committer is not None:
            self._committer.flush()

    def commit_counters(self) -> Dict[str, float]:
        return self._committer.counters() if self._committer else {}

    # read-path lookups (raise) -----------------------------------------
    def _coll(self, cid) -> Dict[ObjectId, Obj]:
        c = self.colls.get(cid)
        if c is None:
            raise NoSuchCollection(str(cid))
        return c

    def _obj(self, cid, oid) -> Obj:
        o = self._coll(cid).get(oid)
        if o is None:
            raise NoSuchObject(f"{cid}/{oid}")
        return o

    # write-path lookups (total) ----------------------------------------
    def _obj_w(self, cid, oid) -> Obj:
        c = self.colls.setdefault(cid, {})
        o = c.get(oid)
        if o is None:
            o = c[oid] = Obj()
        return o

    def _obj_opt(self, cid, oid) -> Optional[Obj]:
        c = self.colls.get(cid)
        return None if c is None else c.get(oid)

    def _apply(self, txn: Transaction) -> None:
        for op in txn.ops:
            self._apply_op(op)

    @staticmethod
    def _splice(o: Obj, off: int, data: bytes) -> None:
        end = off + len(data)
        if len(o.data) < end:
            o.data.extend(b"\x00" * (end - len(o.data)))
        o.data[off:end] = data

    def _apply_op(self, op: TxOp) -> None:
        code = op.op
        if code == OP_NOP:
            return
        if code == OP_MKCOLL:
            self.colls.setdefault(op.cid, {})
            return
        if code == OP_RMCOLL:
            self.colls.pop(op.cid, None)
            return
        if code == OP_TOUCH:
            self._obj_w(op.cid, op.oid)
            return
        if code == OP_WRITE:
            self._splice(self._obj_w(op.cid, op.oid), op.off, op.data)
            return
        if code == OP_ZERO:
            self._splice(self._obj_w(op.cid, op.oid), op.off,
                         b"\x00" * op.length)
            return
        if code == OP_TRUNCATE:
            o = self._obj_w(op.cid, op.oid)
            size = op.off
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\x00" * (size - len(o.data)))
            return
        if code == OP_REMOVE:
            c = self.colls.get(op.cid)
            if c is not None:
                c.pop(op.oid, None)
            return
        if code == OP_SETATTR:
            self._obj_w(op.cid, op.oid).xattrs[op.name] = op.data
            return
        if code == OP_SETATTRS:
            o = self._obj_w(op.cid, op.oid)
            for k, v in op.kv.items():
                o.xattrs[k.decode("utf-8")] = v
            return
        if code == OP_RMATTR:
            o = self._obj_opt(op.cid, op.oid)
            if o is not None:
                o.xattrs.pop(op.name, None)
            return
        if code == OP_CLONE:
            src = self._obj_opt(op.cid, op.oid)
            if src is not None:
                self.colls[op.cid][op.oid2] = src.clone()
            return
        if code == OP_CLONERANGE2:
            src = self._obj_opt(op.cid, op.oid)
            if src is not None:
                chunk = bytes(src.data[op.off:op.off + op.length])
                self._splice(self._obj_w(op.cid, op.oid2), op.dest_off,
                             chunk)
            return
        if code == OP_COLL_MOVE_RENAME:
            c = self.colls.get(op.cid)
            src = c.pop(op.oid, None) if c is not None else None
            if src is not None:
                self.colls.setdefault(op.cid2, {})[op.oid2] = src
            return
        if code == OP_TRY_RENAME:
            c = self.colls.get(op.cid)
            src = c.pop(op.oid, None) if c is not None else None
            if src is not None:
                c[op.oid2] = src
            return
        if code == OP_OMAP_CLEAR:
            o = self._obj_opt(op.cid, op.oid)
            if o is not None:
                o.omap.clear()
                o.omap_header = b""
            return
        if code == OP_OMAP_SETKEYS:
            self._obj_w(op.cid, op.oid).omap.update(op.kv)
            return
        if code == OP_OMAP_RMKEYS:
            o = self._obj_opt(op.cid, op.oid)
            if o is not None:
                for k in op.keys:
                    o.omap.pop(k, None)
            return
        if code == OP_OMAP_RMKEYRANGE:
            o = self._obj_opt(op.cid, op.oid)
            if o is not None:
                first, last = op.keys
                for k in [k for k in o.omap if first <= k < last]:
                    del o.omap[k]
            return
        if code == OP_OMAP_SETHEADER:
            self._obj_w(op.cid, op.oid).omap_header = op.data
            return
        # unknown op code: skip (forward compat, like encoding's
        # skip-unknown-trailing rule) — never poison WAL replay.

    # --- read path (raises NoSuchCollection/NoSuchObject) ---
    def read(self, cid, oid, off: int = 0, length: int = -1) -> bytes:
        o = self._obj(cid, oid)
        if length < 0:
            return bytes(o.data[off:])
        return bytes(o.data[off:off + length])

    def stat(self, cid, oid) -> Dict[str, int]:
        o = self._obj(cid, oid)
        return {"size": len(o.data), "omap_keys": len(o.omap)}

    def getattr(self, cid, oid, name: str) -> bytes:
        o = self._obj(cid, oid)
        if name not in o.xattrs:
            raise NoSuchObject(f"xattr {name} on {oid}")
        return o.xattrs[name]

    def getattrs(self, cid, oid) -> Dict[str, bytes]:
        return dict(self._obj(cid, oid).xattrs)

    _STATFS_TTL = 5.0

    def statfs(self) -> Dict[str, int]:
        """df-style usage (ObjectStore::statfs): RAM-backed stores
        have no fixed device — total/free report 0 = unknown.  The
        object walk is TTL-cached: the stats reporter calls this every
        tick and deliberately avoids per-tick store walks."""
        import time
        cached = getattr(self, "_statfs_cache", None)
        now = time.monotonic()
        if cached is not None and now - cached[0] < self._STATFS_TTL:
            return cached[1]
        used = sum(len(o.data)
                   for objs in self.colls.values()
                   for o in objs.values())
        out = {"total": 0, "free": 0, "used": used}
        self._statfs_cache = (now, out)
        return out

    def omap_get(self, cid, oid) -> Tuple[bytes, Dict[bytes, bytes]]:
        o = self._obj(cid, oid)
        return o.omap_header, dict(o.omap)

    def omap_get_values(self, cid, oid, keys) -> Dict[bytes, bytes]:
        o = self._obj(cid, oid)
        return {k: o.omap[k] for k in keys if k in o.omap}

    def omap_get_header(self, cid, oid) -> bytes:
        return self._obj(cid, oid).omap_header

    def list_collections(self) -> List[CollectionId]:
        return sorted(self.colls)

    def collection_exists(self, cid) -> bool:
        return cid in self.colls

    def collection_list(self, cid, start: Optional[ObjectId] = None,
                        max_count: int = 2**31) -> List[ObjectId]:
        objs = sorted(self._coll(cid), key=lambda o: o.sort_key())
        if start is not None:
            sk = start.sort_key()
            objs = [o for o in objs if o.sort_key() > sk]
        return objs[:max_count]
