"""Operator CLIs (reference: src/ceph.in, src/tools/).

- daemons:      ceph-mon / ceph-osd process mains
- vstart:       dev-cluster launcher (vstart.sh / ceph-helpers.sh)
- ceph:         mon command CLI
- rados:        object I/O + bench (obj_bencher)
- crushtool:    build/inspect/test crush maps
- osdmaptool:   --test-map-pgs bulk placement harness
- ec_benchmark: ceph_erasure_code_benchmark contract
"""
