"""psim: placement simulator (src/tools/psim.cc).

Builds a synthetic cluster map and simulates object placement to show
the distribution quality CRUSH achieves before any hardware exists:

    python -m ceph_tpu.tools.psim --osds 32 --pgs 1024 --size 3 \
        [--objects 100000] [--hosts 8] [--engine auto|host|jax]
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.crush.builder import build_hierarchy, make_replicated_rule
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import (OSD_EXISTS, OSD_IN_WEIGHT, OSD_UP, PGPool,
                                POOL_TYPE_REPLICATED)


def build_map(n_osds: int, hosts: int, pg_num: int, size: int) -> OSDMap:
    m = OSDMap()
    m.epoch = 1
    m.set_max_osd(n_osds)
    crush = CrushMap()
    per_host = max(1, n_osds // hosts)
    build_hierarchy(crush, n_osds, per_host)
    domain = "host" if hosts >= size else "osd"
    ruleset = make_replicated_rule(crush, "psim",
                                   failure_domain=domain)
    m.crush = crush
    for o in range(n_osds):
        m.osd_state[o] = OSD_EXISTS | OSD_UP
        m.osd_weight[o] = OSD_IN_WEIGHT
    m.pools[1] = PGPool(POOL_TYPE_REPLICATED, size=size, pg_num=pg_num,
                        crush_ruleset=ruleset)
    m.pool_names[1] = "psim"
    return m


def simulate(m: OSDMap, objects: int, engine: str) -> dict:
    per_osd = [0] * m.max_osd
    primaries = [0] * m.max_osd
    pool = m.pools[1]
    for pg, up, upp, acting, actp in m.map_pgs_batch(1, engine=engine):
        for rank, o in enumerate(acting):
            if o < 0:
                continue
            per_osd[o] += 1
        if actp >= 0:
            primaries[actp] += 1
    # objects spread over pgs by hash; distribution per osd follows the
    # pg distribution scaled by objects/pg_num
    scale = objects / pool.pg_num
    obj_per_osd = [int(c * scale) for c in per_osd]
    nz = [c for c in per_osd if c] or [0]
    return {
        "osds": m.max_osd, "pgs": pool.pg_num, "size": pool.size,
        "objects": objects,
        "pg_per_osd": {"min": min(nz), "max": max(nz),
                       "avg": sum(per_osd) / max(1, m.max_osd)},
        "spread_ratio": (max(nz) / (sum(per_osd) / max(1, m.max_osd))
                         if per_osd else 0),
        "primary_balance": {"min": min(primaries),
                            "max": max(primaries)},
        "objects_per_osd": {"min": min(obj_per_osd),
                            "max": max(obj_per_osd)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="psim")
    ap.add_argument("--osds", type=int, default=32)
    ap.add_argument("--hosts", type=int, default=8)
    ap.add_argument("--pgs", type=int, default=1024)
    ap.add_argument("--size", type=int, default=3)
    ap.add_argument("--objects", type=int, default=100000)
    ap.add_argument("--engine", default="auto",
                    choices=("auto", "host", "jax"))
    args = ap.parse_args(argv)
    m = build_map(args.osds, args.hosts, args.pgs, args.size)
    out = simulate(m, args.objects, args.engine)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
