"""rbd CLI: block-image management + bench.

Reference parity: src/tools/rbd (create/ls/info/rm/resize/bench-write,
import/export) over the librbd-analog (ceph_tpu/services/rbd.py).

    python -m ceph_tpu.tools.rbd --dir DIR -p pool create NAME --size 64M
    ... ls | info NAME | rm NAME | resize NAME --size N
    ... import FILE NAME | export NAME FILE
    ... bench NAME --io-size 4096 --io-total 4M [--io-pattern rand]
        [--workload write|read]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time

from ceph_tpu.tools.daemons import load_monmap


def parse_size(s: str) -> int:
    s = str(s).strip().upper()
    mult = 1
    for suf, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30),
                   ("T", 1 << 40)):
        if s.endswith(suf):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


async def bench(img, io_size: int, io_total: int, pattern: str,
                workload: str, concurrency: int = 8) -> dict:
    """rbd bench: closed-loop striped IO (reference rbd bench-write)."""
    n_ops = max(1, io_total // io_size)
    payload = bytes((i * 131 + 17) & 0xFF for i in range(io_size))
    rng = random.Random(42)
    max_off = max(img.size - io_size, 0)
    offsets = [(rng.randrange(0, max_off + 1) if pattern == "rand"
                else (i * io_size) % (max_off + 1))
               for i in range(n_ops)]
    stats = {"ops": 0, "lat_sum": 0.0, "lat_max": 0.0}
    queue = asyncio.Queue()
    for off in offsets:
        queue.put_nowait(off)

    async def worker():
        while not queue.empty():
            try:
                off = queue.get_nowait()
            except asyncio.QueueEmpty:
                return
            t0 = time.monotonic()
            if workload == "write":
                await img.write(off, payload)
            else:
                await img.read(off, io_size)
            dt = time.monotonic() - t0
            stats["ops"] += 1
            stats["lat_sum"] += dt
            stats["lat_max"] = max(stats["lat_max"], dt)

    t0 = time.monotonic()
    await asyncio.gather(*[worker() for _ in range(concurrency)])
    wall = time.monotonic() - t0
    ops = stats["ops"] or 1
    return {
        "workload": workload, "pattern": pattern,
        "io_size": io_size, "ops": stats["ops"],
        "seconds": round(wall, 3),
        "mb_per_sec": round(stats["ops"] * io_size / wall / 1e6, 3),
        "iops": round(stats["ops"] / wall, 1),
        "avg_lat_ms": round(1000 * stats["lat_sum"] / ops, 3),
        "max_lat_ms": round(1000 * stats["lat_max"], 3),
    }


async def run(args) -> int:
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.common.context import Context
    from ceph_tpu.services.rbd import RBD, Image, RBDError
    ctx = Context("client.admin")
    from ceph_tpu.tools.daemons import apply_conf
    apply_conf(ctx, args.dir)
    r = Rados(ctx, load_monmap(args.dir))
    await r.connect()
    try:
        io = r.open_ioctx(args.pool)
        rbd = RBD(io)
        if args.op == "create":
            await rbd.create(args.args[0], parse_size(args.size),
                             order=args.order,
                             stripe_unit=parse_size(args.stripe_unit)
                             if args.stripe_unit else 0,
                             stripe_count=args.stripe_count)
        elif args.op == "ls":
            for name in await rbd.list():
                print(name)
        elif args.op == "info":
            img = await Image.open(io, args.args[0])
            st = img.stat()
            print(f"rbd image '{img.name}':")
            print(f"\tsize {st['size']} bytes in {st['num_objs']} objects")
            print(f"\torder {st['order']} ({st['object_size']} B objects)")
            print(f"\tstripe unit {st['stripe_unit']}, "
                  f"count {st['stripe_count']}")
        elif args.op == "rm":
            await rbd.remove(args.args[0])
        elif args.op == "resize":
            img = await Image.open(io, args.args[0])
            await img.resize(parse_size(args.size))
        elif args.op == "import":
            with open(args.args[0], "rb") as f:
                data = f.read()
            await rbd.create(args.args[1], len(data), order=args.order)
            img = await Image.open(io, args.args[1])
            step = 4 << 20
            for off in range(0, len(data), step):
                await img.write(off, data[off:off + step])
        elif args.op == "export":
            img = await Image.open(io, args.args[0])
            step = 4 << 20
            with open(args.args[1], "wb") as f:
                for off in range(0, img.size, step):
                    f.write(await img.read(off, min(step,
                                                    img.size - off)))
        elif args.op == "mirror":
            # rbd mirror IMAGE DST_POOL: bootstrap + replay once (the
            # rbd-mirror daemon loop, one-shot form)
            from ceph_tpu.services.rbd_mirror import ImageReplayer
            dst_io = r.open_ioctx(args.args[1])
            rep = ImageReplayer(io, dst_io, args.args[0])
            await rep.bootstrap()
            n = await rep.replay_once()
            print(f"mirrored {args.args[0]!r} -> pool "
                  f"{args.args[1]!r} ({n} events replayed)")
        elif args.op == "snap":
            # snap create|ls|rm|rollback|protect|unprotect IMAGE@SNAP
            verb = args.args[0]
            spec = args.args[1]
            name, _, snap = spec.partition("@")
            img = await Image.open(io, name)
            try:
                if verb == "create":
                    await img.snap_create(snap)
                elif verb == "ls":
                    for s in img.snap_list():
                        flag = " (protected)" if s.get("protected") \
                            else ""
                        print(f"{s['id']}\t{s['name']}\t"
                              f"{s['size']}{flag}")
                elif verb == "rm":
                    await img.snap_remove(snap)
                elif verb == "rollback":
                    await img.snap_rollback(snap)
                elif verb == "protect":
                    await img.snap_protect(snap)
                elif verb == "unprotect":
                    await img.snap_unprotect(snap)
                else:
                    print(f"unknown snap verb {verb}", file=sys.stderr)
                    return 2
            finally:
                await img.close()
        elif args.op == "clone":
            # clone PARENT@SNAP CHILD [--dest-pool POOL]
            pspec, child = args.args[0], args.args[1]
            pname, _, snap = pspec.partition("@")
            c_io = r.open_ioctx(args.dest_pool) if args.dest_pool \
                else None
            await rbd.clone(pname, snap, child, clone_ioctx=c_io)
        elif args.op == "object-map":
            # object-map rebuild IMAGE (librbd rebuild_object_map)
            verb, name = args.args[0], args.args[1]
            if verb != "rebuild":
                print(f"unknown object-map verb {verb}",
                      file=sys.stderr)
                return 2
            from ceph_tpu.services.rbd import ObjectMap
            img = await Image.open(io, name)
            try:
                om = ObjectMap(img.io, img.id, img._n_objs())
                await om.rebuild(img)
                await om.save(clean=True)
                n = sum(om.exists(i) for i in range(om.n_objs))
                print(f"object map rebuilt: {n}/{om.n_objs} objects "
                      f"present")
            finally:
                await img.close()
        elif args.op == "flatten":
            img = await Image.open(io, args.args[0])
            try:
                await img.flatten()
            finally:
                await img.close()
        elif args.op == "children":
            pname, _, snap = args.args[0].partition("@")
            for c in await rbd.children(pname, snap):
                print(c)
        elif args.op == "bench":
            img = await Image.open(io, args.args[0], cached=args.cached)
            try:
                out = await bench(img, parse_size(args.io_size),
                                  parse_size(args.io_total),
                                  args.io_pattern, args.workload)
            finally:
                await img.close()    # drain the write-back cache
            out["cached"] = args.cached
            print(json.dumps(out))
        else:
            print(f"unknown op {args.op}", file=sys.stderr)
            return 2
        return 0
    except RBDError as e:
        print(f"rbd: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    finally:
        await r.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rbd")
    ap.add_argument("--dir", default="./vcluster")
    ap.add_argument("-p", "--pool", default="rbd")
    ap.add_argument("--size", default="64M")
    ap.add_argument("--order", type=int, default=22)
    ap.add_argument("--stripe-unit", default=None)
    ap.add_argument("--stripe-count", type=int, default=1)
    ap.add_argument("--io-size", default="4096")
    ap.add_argument("--io-total", default="4M")
    ap.add_argument("--io-pattern", choices=("seq", "rand"),
                    default="seq")
    ap.add_argument("--cached", action="store_true",
                    help="use the client ObjectCacher (rbd_cache=true)")
    ap.add_argument("--workload", choices=("write", "read"),
                    default="write")
    ap.add_argument("--dest-pool", default=None,
                    help="clone: pool for the child image")
    ap.add_argument("op",
                    help="create|ls|info|rm|resize|import|export|bench|"
                         "snap|clone|flatten|children")
    ap.add_argument("args", nargs="*")
    args = ap.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
