"""Erasure-code benchmark CLI.

Reference parity: src/test/erasure-code/ceph_erasure_code_benchmark.cc
(:40-63 options, :150-187 encode/decode loops) — same contract:
--plugin/--size/--iterations/--workload encode|decode/--erasures/
--parameter k=v; prints "<seconds>\t<KiB>" like the reference, plus an
optional json summary line.

    python -m ceph_tpu.tools.ec_benchmark --plugin rs --workload encode \
        --size $((1<<24)) --iterations 10 -P k=8 -P m=4 [-P backend=tpu]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ec_benchmark")
    ap.add_argument("--plugin", default="rs")
    ap.add_argument("--workload", choices=["encode", "decode"],
                    default="encode")
    ap.add_argument("--size", type=int, default=1 << 20,
                    help="total bytes per iteration")
    ap.add_argument("--iterations", type=int, default=1)
    ap.add_argument("--erasures", type=int, default=1)
    ap.add_argument("-P", "--parameter", action="append", default=[],
                    help="profile k=v (k, m, technique, backend, ...)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from ceph_tpu.ec.registry import factory
    profile = dict(kv.split("=", 1) for kv in args.parameter)
    codec = factory(args.plugin, profile)
    k, m = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
    n = k + m

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
    want_all = set(range(n))

    # warm once (jit compile on the tpu backend is one-time cost)
    chunks = codec.encode(want_all, data)

    t0 = time.perf_counter()
    if args.workload == "encode":
        for _ in range(args.iterations):
            chunks = codec.encode(want_all, data)
    else:
        erased = list(range(args.erasures))
        have = {i: c for i, c in chunks.items() if i not in erased}
        for _ in range(args.iterations):
            out = codec.decode(set(erased), have)
        # verify the reconstruction (reference --verify flavor)
        for e in erased:
            assert np.array_equal(out[e], chunks[e]), "bad decode"
    dt = time.perf_counter() - t0

    total_kib = args.size * args.iterations / 1024
    print(f"{dt:.6f}\t{int(total_kib)}")
    if args.json:
        print(json.dumps({
            "plugin": args.plugin, "workload": args.workload,
            "k": k, "m": m, "iterations": args.iterations,
            "bytes_per_iter": args.size,
            "seconds": round(dt, 6),
            "mb_per_sec": round(args.size * args.iterations / dt / 1e6, 2),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
