"""rados CLI: object I/O + the bench harness.

Reference parity: src/tools/rados/rados.cc (put/get/rm/ls/stat
:102 usage) and src/common/obj_bencher.h:62 (bench write|seq|rand with
throughput/latency stats — the cluster-level BASELINE harness).

    python -m ceph_tpu.tools.rados --dir DIR -p pool put NAME FILE
    ... get NAME FILE | rm NAME | ls | stat NAME
    ... bench SECONDS write|seq|rand [-b SIZE] [-t CONCURRENCY]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

from ceph_tpu.tools.daemons import load_monmap


async def bench(io, seconds: int, mode: str, block: int,
                concurrency: int) -> dict:
    """obj_bencher distilled: timed closed-loop with N writers."""
    payload = bytes(range(256)) * (block // 256 + 1)
    payload = payload[:block]
    stats = {"ops": 0, "bytes": 0, "lat_sum": 0.0, "lat_max": 0.0}
    stop_at = time.monotonic() + seconds
    written: list = []

    async def worker(wid: int):
        n = 0
        while time.monotonic() < stop_at:
            name = f"bench_{wid}_{n}"
            t0 = time.monotonic()
            if mode == "write":
                await io.write_full(name, payload)
                written.append(name)
            else:
                if not written:
                    return
                target = written[(wid * 7919 + n) % len(written)]
                await io.read(target)
            dt = time.monotonic() - t0
            stats["ops"] += 1
            stats["bytes"] += block
            stats["lat_sum"] += dt
            stats["lat_max"] = max(stats["lat_max"], dt)
            n += 1

    if mode in ("seq", "rand"):
        # seed objects to read back
        for i in range(concurrency * 4):
            name = f"bench_seed_{i}"
            await io.write_full(name, payload)
            written.append(name)
        stop_at = time.monotonic() + seconds
    t0 = time.monotonic()
    await asyncio.gather(*(worker(i) for i in range(concurrency)))
    wall = time.monotonic() - t0
    ops = stats["ops"] or 1
    return {
        "mode": mode,
        "seconds": round(wall, 3),
        "ops": stats["ops"],
        "bytes": stats["bytes"],
        # client iodepth (closed-loop writers): must exceed 1 for the
        # OSD-side per-PG op window to fill (obj_bencher concurrentios)
        "iodepth": concurrency,
        # achieved concurrency: ops * mean latency / wall — how much of
        # the requested iodepth the cluster actually sustained
        "achieved_iodepth": round(stats["lat_sum"] / wall, 2)
        if wall else 0.0,
        "mb_per_sec": round(stats["bytes"] / wall / 1e6, 3),
        "iops": round(stats["ops"] / wall, 1),
        "avg_lat_ms": round(1000 * stats["lat_sum"] / ops, 3),
        "max_lat_ms": round(1000 * stats["lat_max"], 3),
    }


def _client_stage_quantiles(ctx) -> dict:
    """Per-stage p50/p99 from THIS client's op tracer (op_tracing=true
    in the cluster conf).  Against an in-process cluster the stages
    cover the whole path; over TCP the client sees its own side
    (client_submit / ack_delivery / op_total) and each daemon's share
    is served by its admin socket (`dump_op_stages`)."""
    from ceph_tpu.common import tracer as tracer_mod
    merged = tracer_mod.merge_stage_histograms([ctx])
    if not merged:
        return {}
    return {"stages": {
        name: {"p50_ms": d["p50_ms"], "p99_ms": d["p99_ms"],
               "count": d["count"]}
        for name, d in ((n, h.dump()) for n, h in sorted(merged.items()))
        if d["count"]}}


async def run(args) -> int:
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.common.context import Context
    ctx = Context("client.admin")
    from ceph_tpu.tools.daemons import apply_conf
    apply_conf(ctx, args.dir)
    r = Rados(ctx, load_monmap(args.dir))
    await r.connect()
    try:
        if args.op == "lspools":
            print("\n".join(r.pool_list()))
            return 0
        if args.op == "df":
            # per-pool usage (rados df role, PGMap dump_pool_stats)
            ack = await r.mon_command({"prefix": "df"})
            d = json.loads(ack.outs)
            for p in d["pools"]:
                print(f"{p['name']:<20} objects {p['objects']:<8} "
                      f"used {p['bytes_used']:<12} "
                      f"raw {p['raw_bytes_used']}")
            s = d["stats"]
            print(f"total: objects {s['total_objects']} "
                  f"used {s['total_bytes_used']} "
                  f"raw {s['total_raw_used']}")
            return 0
        io = r.open_ioctx(args.pool)
        if args.snap:
            io.set_snap_read(io.snap_lookup(args.snap))
        if args.op == "mksnap":
            await io.snap_create(args.args[0])
        elif args.op == "rmsnap":
            await io.snap_remove(args.args[0])
        elif args.op == "lssnap":
            for sid, name in sorted(io.snap_list().items()):
                print(f"{sid}\t{name}")
        elif args.op == "rollback":
            await io.rollback(args.args[0], args.args[1])
        elif args.op == "listsnaps":
            print(json.dumps(await io.list_snaps(args.args[0])))
        elif args.op == "put":
            with open(args.args[1], "rb") as f:
                await io.write_full(args.args[0], f.read())
        elif args.op == "get":
            data = await io.read(args.args[0])
            if len(args.args) > 1 and args.args[1] != "-":
                with open(args.args[1], "wb") as f:
                    f.write(data)
            else:
                sys.stdout.buffer.write(data)
        elif args.op == "rm":
            await io.remove(args.args[0])
        elif args.op == "stat":
            size = await io.stat(args.args[0])
            print(f"{args.pool}/{args.args[0]} size {size}")
        elif args.op == "ls":
            for name in await io.list_objects():
                print(name)
        elif args.op == "bench":
            seconds = int(args.args[0])
            mode = args.args[1] if len(args.args) > 1 else "write"
            out = await bench(io, seconds, mode, args.block_size,
                              args.concurrent)
            out.update(_client_stage_quantiles(ctx))
            print(json.dumps(out))
        else:
            print(f"unknown op {args.op}", file=sys.stderr)
            return 2
        return 0
    finally:
        await r.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("--dir", default="./vcluster")
    ap.add_argument("-p", "--pool", default="rbd")
    ap.add_argument("-b", "--block-size", type=int, default=4 << 20)
    ap.add_argument("-t", "--concurrent", "--iodepth", type=int,
                    default=16,
                    help="closed-loop writer count (bench iodepth; the "
                         "per-PG op window only fills when this > 1)")
    ap.add_argument("-s", "--snap", default="",
                    help="read from this pool snapshot")
    ap.add_argument("op", help="put|get|rm|ls|stat|bench|lspools|df|"
                               "mksnap|rmsnap|lssnap|rollback|listsnaps")
    ap.add_argument("args", nargs="*")
    args = ap.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
