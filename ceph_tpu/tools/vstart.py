"""vstart: boot a dev cluster of real mon/osd subprocesses.

Reference parity: src/vstart.sh (:111-120 — N mons/osds as local
processes) and qa/workunits/ceph-helpers.sh (setup/run_mon/run_osd/
kill_daemon/wait_for_clean) — the multi-node-without-a-cluster test
strategy (SURVEY §4).  Usable as a CLI and as a library (fault tests
import VCluster to kill/restart daemons).

    python -m ceph_tpu.tools.vstart --dir /tmp/cl -n 3 --mons 1 \
        [--osds-per-host 1] [--conf k=v ...] [--keep-running]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ceph_tpu.common.context import Context
from ceph_tpu.mon.monmap import MonMap
from ceph_tpu.msg.types import EntityAddr


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class VCluster:
    """Launcher handle: daemon subprocess management + admin client."""

    def __init__(self, directory: str, n_osds: int = 3, n_mons: int = 1,
                 osds_per_host: int = 1,
                 conf: Optional[Dict[str, str]] = None,
                 cephx: bool = False, mds: int = 0):
        self.dir = os.path.abspath(directory)
        self.n_osds = n_osds
        self.n_mons = n_mons
        self.osds_per_host = osds_per_host
        self.conf = conf or {}
        self.cephx = cephx
        self.mds = int(mds)          # number of MDS ranks (0 = none)
        self.procs: Dict[str, subprocess.Popen] = {}
        self.monmap = MonMap()

    # ------------------------------------------------------------ lifecycle
    def write_configs(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self.monmap.fsid = f"vstart-{os.path.basename(self.dir)}"
        for i in range(self.n_mons):
            name = chr(ord("a") + i)
            self.monmap.add(name,
                            EntityAddr("127.0.0.1", _free_port(), 0))
        with open(os.path.join(self.dir, "monmap.bin"), "wb") as f:
            f.write(self.monmap.to_bytes())
        conf = dict(self.conf)
        # every daemon gets an admin socket under the cluster dir
        # ($name expands per daemon: mon.a.asok, osd.0.asok, ...)
        conf.setdefault("admin_socket",
                        os.path.join(self.dir, "$name.asok"))
        conf.setdefault("mon_cluster_log_file",
                        os.path.join(self.dir, "cluster.log"))
        if self.cephx:
            # one shared keyring (vstart.sh writes keyring + caps the
            # same way: mon. master, client.admin allow *, per-osd keys)
            from ceph_tpu.auth.keyring import Keyring
            kr = Keyring()
            kr.add("mon.")
            kr.add("client.admin",
                   caps={"mon": "allow *", "osd": "allow *"})
            for i in range(self.n_osds):
                kr.add(f"osd.{i}", caps={"mon": "allow profile osd",
                                         "osd": "allow *"})
            for i in range(max(1, self.mds)):
                kr.add(f"mds.{chr(ord('a') + i)}",
                       caps={"mon": "allow *", "osd": "allow *"})
            kr.save(os.path.join(self.dir, "keyring"))
            conf["auth_supported"] = "cephx"
            conf["keyring"] = os.path.join(self.dir, "keyring")
        with open(os.path.join(self.dir, "ceph.conf"), "w") as f:
            for k, v in conf.items():
                f.write(f"{k} = {v}\n")

    def _spawn(self, kind: str, id_: str, extra=()) -> None:
        # Daemons run jax on the CPU backend (device work rides the
        # primary's batch queue; tests are hermetic).  cpu_child_env
        # strips the TPU plugin's site dir: its sitecustomize imports
        # jax at INTERPRETER STARTUP in every child (seconds of source
        # compile each with bytecode caching off) — N daemons spawning
        # concurrently wedged whole vstart clusters on busy machines.
        from ceph_tpu.common.envutil import cpu_child_env
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        with open(os.path.join(self.dir, f"{kind}.{id_}.log"), "ab") as logf:
            p = subprocess.Popen(
                [sys.executable, "-m", "ceph_tpu.tools.daemons", kind,
                 "--id", id_, "--dir", self.dir, *extra],
                stdout=logf, stderr=subprocess.STDOUT,
                env=cpu_child_env(pythonpath_first=repo_root))
        self.procs[f"{kind}.{id_}"] = p

    def start_daemons(self) -> None:
        for i in range(self.n_mons):
            self._spawn("mon", chr(ord("a") + i))
        for i in range(self.n_osds):
            self._spawn("osd", str(i))

    def start_mds(self) -> None:
        """After bootstrap (the mds needs pools + a served osdmap).
        Multi-rank: rank i = mds.<a+i>, each told the rank count so
        dirfrag ownership (services/mds.py owner_rank) agrees."""
        n = max(1, self.mds)
        for i in range(n):
            self._spawn("mds", chr(ord("a") + i),
                        extra=["--rank", str(i), "--nranks", str(n)])

    def kill_daemon(self, name: str, sig=signal.SIGKILL) -> None:
        """qa/ceph-helpers.sh kill_daemon."""
        p = self.procs.pop(name, None)
        if p is not None:
            p.send_signal(sig)
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                # daemon wedged (e.g. stuck device runtime init): escalate
                p.kill()
                p.wait(timeout=10)

    def restart_daemon(self, name: str) -> None:
        kind, id_ = name.split(".", 1)
        self._spawn(kind, id_)

    def stop(self) -> None:
        for name in list(self.procs):
            self.kill_daemon(name, signal.SIGTERM)

    # ------------------------------------------------------------ admin ops
    async def admin(self):
        from ceph_tpu.client.rados import Rados
        ctx = Context("client.admin")
        for k, v in self.conf.items():
            try:
                ctx.config.set(k, v)
            except KeyError:
                pass
        if self.cephx:
            ctx.config.set("auth_supported", "cephx")
            ctx.config.set("keyring", os.path.join(self.dir, "keyring"))
        r = Rados(ctx, self.monmap)
        await r.connect()
        return r

    async def wait_healthy(self, timeout: float = 120.0) -> None:
        """Wait until every osd is up/in (wait_for_clean role)."""
        admin = await self.admin()
        try:
            deadline = time.monotonic() + timeout
            while True:
                m = admin.monc.osdmap
                if m is not None and m.count_up() == self.n_osds:
                    return
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"cluster not healthy: {m and m.summary()}")
                await asyncio.sleep(0.2)
        finally:
            await admin.shutdown()

    async def bootstrap(self) -> None:
        """Full bring-up: crush map + wait for osds."""
        admin = await self.admin()
        try:
            await admin.mon_command(
                {"prefix": "osd crush build-simple",
                 "num_osds": self.n_osds,
                 "osds_per_host": self.osds_per_host}, timeout=60)
        finally:
            await admin.shutdown()
        await self.wait_healthy()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="vstart")
    ap.add_argument("--dir", default="./vcluster")
    ap.add_argument("-n", "--osds", type=int, default=3)
    ap.add_argument("--mons", type=int, default=1)
    ap.add_argument("--osds-per-host", type=int, default=1)
    ap.add_argument("--conf", nargs="*", default=[],
                    help="extra k=v config entries")
    ap.add_argument("--new", action="store_true",
                    help="wipe the cluster dir first (vstart -n)")
    ap.add_argument("--cephx", action="store_true",
                    help="enable cephx auth (generates a keyring)")
    ap.add_argument("--mds", nargs="?", const=1, default=0, type=int,
                    help="start N mds ranks (CephFS) after bootstrap "
                         "(bare --mds = 1)")
    ap.add_argument("--keep-running", action="store_true",
                    help="stay attached until ^C")
    args = ap.parse_args(argv)

    if args.new and os.path.exists(args.dir):
        shutil.rmtree(args.dir)
    conf = dict(kv.split("=", 1) for kv in args.conf)
    cl = VCluster(args.dir, args.osds, args.mons, args.osds_per_host,
                  conf, cephx=args.cephx, mds=args.mds)
    cl.write_configs()
    cl.start_daemons()
    asyncio.run(cl.bootstrap())
    if args.mds:
        cl.start_mds()
    print(f"cluster up: dir={cl.dir} mons={args.mons} osds={args.osds}"
          + (" +mds" if args.mds else ""))
    print(f"  use: python -m ceph_tpu.tools.ceph --dir {cl.dir} status")
    if args.keep_running:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
        cl.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
