"""radosgw-admin analog: RGW user management + gateway runner.

Reference parity: src/rgw/rgw_admin.cc (user create/rm/list) and the
radosgw daemon entry (rgw_main.cc) — here one tool does both:

    python -m ceph_tpu.tools.rgw_admin --dir DIR user create \
        --access AK --secret SK [--display NAME]
    python -m ceph_tpu.tools.rgw_admin --dir DIR user ls
    python -m ceph_tpu.tools.rgw_admin --dir DIR serve --port 8080
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.tools.daemons import apply_conf, load_monmap


async def _connect(args):
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.common.context import Context
    ctx = Context("client.admin")
    apply_conf(ctx, args.dir)
    r = Rados(ctx, load_monmap(args.dir))
    await r.connect()
    # the gateway's backing pool (rgw_main.cc default .rgw.* pools)
    if r.monc.osdmap.lookup_pool(args.pool) < 0:
        await r.pool_create(args.pool, pg_num=8)
    return r


async def run(args) -> int:
    from ceph_tpu.services.rgw import S3Gateway, UserDB
    r = await _connect(args)
    try:
        io = r.open_ioctx(args.pool)
        if args.cmd == "user":
            db = UserDB(io)
            if args.op == "create":
                await db.create(args.access, args.secret, args.display)
                print(json.dumps({"user": args.access, "created": True}))
            elif args.op == "rm":
                await db.remove(args.access)
                print(json.dumps({"user": args.access, "removed": True}))
            elif args.op == "ls":
                print(json.dumps(await db.list()))
            return 0
        if args.cmd == "gc":
            from ceph_tpu.services.rgw_gc import GarbageCollector
            gc = GarbageCollector(io)
            if args.op == "list":
                print(json.dumps([
                    {"tag": t, "ready": ready, "objs": soids}
                    for t, ready, soids in await gc.entries()]))
            else:                                  # process
                print(json.dumps({"removed": await gc.process()}))
            return 0
        if args.cmd == "lc":
            gw = S3Gateway(r, pool=args.pool, require_auth=False)
            print(json.dumps(await gw.lc_process()))
            return 0
        if args.cmd == "quota":
            if args.bucket:
                gw = S3Gateway(r, pool=args.pool, require_auth=False)
                ok = await gw.set_bucket_quota(args.bucket,
                                               args.max_size,
                                               args.max_objects)
            else:
                ok = await UserDB(io).set_quota(args.access,
                                                args.max_size,
                                                args.max_objects)
            print(json.dumps({"set": ok}))
            return 0 if ok else 1
        if args.cmd == "usage":
            from ceph_tpu.services.rgw_usage import UsageLog
            ul = UsageLog(io)
            if args.op == "show":
                print(json.dumps(await ul.show(
                    args.uid, args.start_epoch,
                    args.end_epoch if args.end_epoch >= 0 else None)))
            else:                                  # trim
                n = await ul.trim(args.uid, args.before_epoch)
                print(json.dumps({"trimmed": n}))
            return 0
        if args.cmd == "bucket":
            # shard-layout aware ops ride the gateway's routing (the
            # bucket rec decides legacy vs N-shard generation oids)
            gw = S3Gateway(r, pool=args.pool, require_auth=False)
            if args.op == "stats":
                rep = await gw.bucket_shard_stats(args.bucket)
                if rep is None:
                    print(json.dumps({"error": "NoSuchBucket"}))
                    return 1
                print(json.dumps({"entries": rep["entries"],
                                  "bytes": rep["bytes"],
                                  "shards": rep["shards"]}))
                return 0
            if args.op == "shard-stats":
                rep = await gw.bucket_shard_stats(args.bucket)
                if rep is None:
                    print(json.dumps({"error": "NoSuchBucket"}))
                    return 1
                print(json.dumps(rep))
                return 0
            if args.op == "reshard":
                out = await gw.reshard_bucket(args.bucket,
                                              args.num_shards)
                if out is None:
                    print(json.dumps(
                        {"error": "NoSuchBucket or reshard in "
                                  "progress"}))
                    return 1
                print(json.dumps(out))
                return 0
            # check [--fix]: header-vs-actual + stale pending markers
            # aggregated across every shard (rgw_admin.cc bucket
            # check / cls_rgw bucket_check role).  --min-age guards
            # young markers: one may belong to an op in flight RIGHT
            # NOW, and expiring it defeats crash reconciliation.
            rep = await gw.bucket_check(args.bucket, fix=args.fix,
                                        min_age=args.min_age)
            if rep is None:
                print(json.dumps({"error": "NoSuchBucket"}))
                return 1
            print(json.dumps(rep))
            return 0
        if args.cmd == "serve":
            gw = S3Gateway(r, pool=args.pool,
                           require_auth=not args.no_auth)
            port = await gw.start(port=args.port)
            print(f"rgw listening on 127.0.0.1:{port}", flush=True)
            try:
                while True:
                    await asyncio.sleep(3600)
            except (KeyboardInterrupt, asyncio.CancelledError):
                pass
            await gw.stop()
            return 0
        return 2
    finally:
        await r.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="radosgw-admin")
    ap.add_argument("--dir", default="./vcluster")
    ap.add_argument("--pool", default=".rgw")
    sub = ap.add_subparsers(dest="cmd", required=True)
    u = sub.add_parser("user")
    u.add_argument("op", choices=("create", "rm", "ls"))
    u.add_argument("--access", default="")
    u.add_argument("--secret", default="")
    u.add_argument("--display", default="")
    g = sub.add_parser("gc")
    g.add_argument("op", choices=("list", "process"))
    sub.add_parser("lc")
    q = sub.add_parser("quota")
    q.add_argument("--access", default="")
    q.add_argument("--bucket", default="")
    q.add_argument("--max-size", type=int, default=-1)
    q.add_argument("--max-objects", type=int, default=-1)
    us = sub.add_parser("usage")
    us.add_argument("op", choices=("show", "trim"))
    us.add_argument("--uid", required=True)
    us.add_argument("--start-epoch", type=int, default=0)
    us.add_argument("--end-epoch", type=int, default=-1)
    us.add_argument("--before-epoch", type=int, default=0)
    b = sub.add_parser("bucket")
    b.add_argument("op", choices=("stats", "check", "reshard",
                                  "shard-stats"))
    b.add_argument("--bucket", required=True)
    b.add_argument("--fix", action="store_true")
    b.add_argument("--min-age", type=float, default=3600.0,
                   help="only expire pending markers older than this")
    b.add_argument("--num-shards", type=int, default=4,
                   help="target shard count for `bucket reshard`")
    s = sub.add_parser("serve")
    s.add_argument("--port", type=int, default=7480)
    s.add_argument("--no-auth", action="store_true")
    args = ap.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
