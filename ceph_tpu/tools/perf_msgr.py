"""Messenger throughput harness.

Reference parity: src/test/msgr/perf_msgr_server.cc /
perf_msgr_client.cc — a server messenger echoes typed payload
messages while clients blast N in-flight requests and report msg/s +
MB/s + latency percentiles.  One process, two messengers over real
TCP, because the number that matters is the full encode -> frame ->
socket -> decode -> dispatch path.

    python -m ceph_tpu.tools.perf_msgr [--count 2000] [--size 4096]
        [--inflight 32]
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from typing import Dict

from ceph_tpu.common.context import Context
from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.msg import (Dispatcher, EntityName, Message, Messenger,
                          Policy)
from ceph_tpu.msg.message import register_message


@register_message
class MPerf(Message):
    """Echo payload (perf_msgr's MOSDOp stand-in)."""

    TYPE = 4090

    def __init__(self, tid: int = 0, data: bytes = b""):
        super().__init__()
        self.tid = tid
        self.data = data

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid).bytes_(self.data)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "MPerf":
        return cls(dec.u64(), dec.bytes_())


class _Echo(Dispatcher):
    def __init__(self, msgr: Messenger):
        self.msgr = msgr

    def ms_dispatch(self, msg: Message) -> bool:
        if msg.TYPE != MPerf.TYPE:
            return False
        self.msgr.send_message(MPerf(msg.tid, b""), msg.src_addr)
        return True


class _Client(Dispatcher):
    def __init__(self):
        self.waiters: Dict[int, asyncio.Future] = {}

    def ms_dispatch(self, msg: Message) -> bool:
        if msg.TYPE != MPerf.TYPE:
            return False
        fut = self.waiters.pop(msg.tid, None)
        if fut is not None and not fut.done():
            fut.set_result(None)
        return True


async def run(count: int, size: int, inflight: int) -> dict:
    ctx_s = Context("osd.0")
    ctx_c = Context("client.perf")
    server = Messenger(ctx_s, EntityName.parse("osd.0"))
    server.set_policy("client", Policy(lossy=True))
    server.add_dispatcher(_Echo(server))
    addr = await server.bind()

    client = Messenger(ctx_c, EntityName.parse("client.perf"))
    client.set_policy("osd", Policy(lossy=True))
    disp = _Client()
    client.add_dispatcher(disp)
    await client.bind()          # replies dial back to this addr

    payload = b"\x5a" * size
    loop = asyncio.get_running_loop()
    lats = []
    sem = asyncio.Semaphore(inflight)

    async def one(tid: int) -> None:
        async with sem:
            fut = loop.create_future()
            disp.waiters[tid] = fut
            t0 = time.perf_counter()
            client.send_message(MPerf(tid, payload), addr)
            await fut
            lats.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    await asyncio.gather(*[one(i) for i in range(count)])
    wall = time.perf_counter() - t0
    await client.shutdown()
    await server.shutdown()
    lats.sort()
    return {
        "count": count, "size": size, "inflight": inflight,
        "msgs_per_sec": round(count / wall, 1),
        "mb_per_sec": round(count * size / wall / 1e6, 2),
        "p50_us": round(lats[len(lats) // 2] * 1e6, 1),
        "p99_us": round(lats[int(len(lats) * 0.99) - 1] * 1e6, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="perf_msgr")
    ap.add_argument("--count", type=int, default=2000)
    ap.add_argument("--size", type=int, default=4096)
    ap.add_argument("--inflight", type=int, default=32)
    args = ap.parse_args(argv)
    import json
    out = asyncio.run(run(args.count, args.size, args.inflight))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
