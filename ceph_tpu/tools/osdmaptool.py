"""osdmaptool: offline OSDMap inspection + bulk placement benchmark.

Reference parity: src/tools/osdmaptool.cc (--print, --test-map-pgs :328
— the bulk pg→osd mapping harness in BASELINE.md).

    python -m ceph_tpu.tools.ceph --dir DIR osd getmap --out map.bin
    python -m ceph_tpu.tools.osdmaptool map.bin --print
    python -m ceph_tpu.tools.osdmaptool map.bin --test-map-pgs [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import Counter

from ceph_tpu.crush.constants import CRUSH_ITEM_NONE
from ceph_tpu.osd.osdmap import OSDMap


def cmd_print(m: OSDMap) -> int:
    print(m.summary())
    for pid in sorted(m.pools):
        p = m.pools[pid]
        print(f"pool {pid} '{m.pool_names.get(pid)}' type {p.type} "
              f"size {p.size} min_size {p.min_size} pg_num {p.pg_num} "
              f"crush_ruleset {p.crush_ruleset}")
    for o in range(m.max_osd):
        if m.exists(o):
            state = ("up" if m.is_up(o) else "down") + \
                ("/in" if m.is_in(o) else "/out")
            print(f"osd.{o} {state} weight "
                  f"{m.osd_weight[o] / 0x10000:.3f} addr {m.get_addr(o)}")
    return 0


def cmd_test_map_pgs(m: OSDMap, as_json: bool,
                     engine: str = "auto") -> int:
    per_osd = Counter()
    primaries = Counter()
    total = 0
    sizes = Counter()
    if engine == "jax":
        # pay the jit compile before the timed region, like the OSD does
        for pid in sorted(m.pools):
            m.warmup_placement(pid)
    t0 = time.perf_counter()
    for pid in sorted(m.pools):
        for pg, up, upp, acting, actp in m.map_pgs_batch(pid, engine):
            total += 1
            sizes[len([o for o in up if o != CRUSH_ITEM_NONE])] += 1
            for o in up:
                if o != CRUSH_ITEM_NONE:
                    per_osd[o] += 1
            if upp >= 0:
                primaries[upp] += 1
    dt = time.perf_counter() - t0
    vals = sorted(per_osd.values())
    report = {
        "total_pgs": total,
        "seconds": round(dt, 4),
        "mappings_per_sec": round(total / dt, 1) if dt else 0,
        "size_histogram": dict(sizes),
        "pg_per_osd": {
            "min": vals[0] if vals else 0,
            "max": vals[-1] if vals else 0,
            "avg": round(sum(vals) / len(vals), 1) if vals else 0,
        },
        "primaries_per_osd": dict(sorted(primaries.items())),
    }
    if as_json:
        print(json.dumps(report))
    else:
        print(f"mapped {total} pgs in {dt:.4f}s "
              f"({report['mappings_per_sec']} pg/s)")
        print(f"size histogram: {dict(sizes)}")
        print(f"pgs per osd: min {report['pg_per_osd']['min']} "
              f"max {report['pg_per_osd']['max']} "
              f"avg {report['pg_per_osd']['avg']}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="osdmaptool")
    ap.add_argument("mapfile")
    ap.add_argument("--print", dest="do_print", action="store_true")
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--engine", choices=("auto", "host", "jax"),
                    default="auto",
                    help="placement engine (jax = TPU descent, compiles "
                         "up front; auto = host unless already warm)")
    args = ap.parse_args(argv)
    with open(args.mapfile, "rb") as f:
        m = OSDMap.from_bytes(f.read())
    if args.do_print:
        return cmd_print(m)
    if args.test_map_pgs:
        return cmd_test_map_pgs(m, args.json, args.engine)
    return cmd_print(m)


if __name__ == "__main__":
    sys.exit(main())
