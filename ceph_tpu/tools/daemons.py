"""Daemon entrypoints: ceph-mon / ceph-osd process mains.

Reference parity: src/ceph_mon.cc, src/ceph_osd.cc — global_init, store
open/mkfs, daemon construction, run forever.  Launched by vstart.py as
real subprocesses (multi-node-without-a-cluster, qa/ceph-helpers.sh
run_mon/run_osd role).

    python -m ceph_tpu.tools.daemons mon --id a --dir DIR
    python -m ceph_tpu.tools.daemons osd --id 0 --dir DIR

DIR must contain monmap.bin (written by vstart/`ceph-tpu mon mkmap`).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys

from ceph_tpu.common.context import Context
from ceph_tpu.mon.monmap import MonMap
from ceph_tpu.msg.messenger import Messenger
from ceph_tpu.msg.types import EntityName


def load_monmap(cluster_dir: str) -> MonMap:
    with open(os.path.join(cluster_dir, "monmap.bin"), "rb") as f:
        return MonMap.from_bytes(f.read())


def apply_conf(ctx: Context, cluster_dir: str) -> None:
    conf = os.path.join(cluster_dir, "ceph.conf")
    if os.path.exists(conf):
        ctx.config.parse_file(conf)


async def run_mon(args) -> None:
    from ceph_tpu.mon.monitor import Monitor
    from ceph_tpu.store.kv import FileDB
    ctx = Context(f"mon.{args.id}")
    apply_conf(ctx, args.dir)
    monmap = load_monmap(args.dir)
    store = FileDB(os.path.join(args.dir, f"mon.{args.id}"))
    msgr = Messenger(ctx, EntityName("mon", args.id))
    mon = Monitor(ctx, args.id, monmap, store, msgr)
    await mon.start()
    await _run_until_signal()
    await mon.shutdown()


async def run_osd(args) -> None:
    from ceph_tpu.osd.daemon import OSD
    from ceph_tpu.store.objectstore import ObjectStore
    ctx = Context(f"osd.{args.id}")
    apply_conf(ctx, args.dir)
    monmap = load_monmap(args.dir)
    path = os.path.join(args.dir, f"osd.{args.id}")
    kind = ctx.config["objectstore"]
    if kind == "memstore":        # memstore can't back a daemon restart
        kind = "filestore"
    store = ObjectStore.create(kind, path)
    if kind == "blockstore" and ctx.config["blockstore_compression"]:
        store.set_compression(
            ctx.config["blockstore_compression"],
            ctx.config["blockstore_compression_min_blob"])
    if kind == "filestore" and ctx.config["filestore_kill_at"]:
        # crash injection countdown (config_opts.h filestore_kill_at)
        store.kill_at = int(ctx.config["filestore_kill_at"])
    fresh_marker = os.path.join(
        path, "fsid" if kind == "filestore" else "block")
    if not os.path.exists(fresh_marker):
        store.mkfs()
    msgr = Messenger(ctx, EntityName("osd", args.id))
    osd = OSD(ctx, int(args.id), store, msgr, monmap)
    await osd.start()
    await _run_until_signal()
    await osd.shutdown()


async def run_mds(args) -> None:
    """MDS daemon: metadata service over the cephfs metadata pool
    (creates both cephfs pools if absent)."""
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.services.mds import MDS
    ctx = Context(f"mds.{args.id}")
    apply_conf(ctx, args.dir)
    monmap = load_monmap(args.dir)
    r = Rados(ctx, monmap)
    await r.connect()
    for pool in ("cephfs_metadata", "cephfs_data"):
        if r.monc.osdmap.lookup_pool(pool) < 0:
            await r.pool_create(pool, pg_num=8)
    msgr = Messenger(ctx, EntityName("mds", args.id))
    addr = await msgr.bind()
    rank, nranks = getattr(args, "rank", 0), getattr(args, "nranks", 1)
    mds = MDS(ctx, msgr, r, "cephfs_metadata",
              rank=rank, nranks=nranks)
    if rank == 0:
        await mds.create_fs()
    await mds.start()          # MDLog recovery + write-back flusher
    # register with the mon (FSMonitor beacon) + a file fallback for
    # offline inspection; a transient registration failure must not
    # kill the daemon — clients fall back to the file
    with open(os.path.join(args.dir, f"mds.{args.id}.addr"), "w") as f:
        f.write(f"{addr.host}:{addr.port}:{addr.nonce}")
    try:
        await r.mon_command(
            {"prefix": "mds boot", "name": f"mds.{args.id}",
             "addr": f"{addr.host}:{addr.port}:{addr.nonce}",
             "rank": rank})
    except Exception as e:
        ctx.logger("mds").warning(f"mds boot registration failed: {e}")
    if nranks > 1:
        # resolve peer ranks from the committed fsmap (poll: the other
        # daemons register on their own schedule)
        import json as _json
        from ceph_tpu.msg.types import EntityAddr
        deadline = asyncio.get_running_loop().time() + 60.0
        while len(mds.peers) < nranks:
            try:
                ack = await r.mon_command({"prefix": "mds dump"})
                fsmap = _json.loads(ack.outs)
            except Exception:
                fsmap = {}
            peers = {}
            for rec in fsmap.values():
                h, p, n = rec["addr"].rsplit(":", 2)
                peers[rec.get("rank", 0)] = EntityAddr(
                    h, int(p), int(n))
            mds.peers = peers          # partial map beats none: local
            #                            ops keep working meanwhile
            if len(peers) >= nranks:
                break
            if asyncio.get_running_loop().time() > deadline:
                ctx.logger("mds").warning(
                    f"only {sorted(peers)} of {nranks} ranks "
                    "registered after 60s; cross-rank ops to missing "
                    "ranks will fail until they boot")
                break
            await asyncio.sleep(0.5)
    await _run_until_signal()
    await msgr.shutdown()
    await r.shutdown()


async def _run_until_signal() -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()


def daemonize(pidfile: str, logfile: str) -> None:
    """Classic double-fork daemonization (global/global_init.cc
    global_init_daemonize role): detach from the controlling terminal,
    write a pidfile, point stdio at the log."""
    # resolve BEFORE the chdir below — relative --dir/--pid-file would
    # silently resolve against / in the detached child
    pidfile = os.path.abspath(pidfile)
    logfile = os.path.abspath(logfile)
    if os.fork() > 0:
        os._exit(0)                      # parent returns to the shell
    os.setsid()
    if os.fork() > 0:
        os._exit(0)                      # session leader exits
    os.chdir("/")
    fd = os.open(logfile, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                 0o644)
    null = os.open(os.devnull, os.O_RDONLY)
    os.dup2(null, 0)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    os.close(null)
    if fd > 2:
        os.close(fd)
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    import atexit
    atexit.register(lambda: os.path.exists(pidfile)
                    and os.unlink(pidfile))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-tpu-daemon")
    ap.add_argument("kind", choices=["mon", "osd", "mds"])
    ap.add_argument("--id", required=True)
    ap.add_argument("--dir", required=True, help="cluster directory")
    ap.add_argument("-d", "--daemonize", action="store_true",
                    help="double-fork into the background with a "
                         "pidfile + log redirect (global_init role)")
    ap.add_argument("--pid-file", default="",
                    help="pidfile path (default: "
                         "<dir>/<kind>.<id>.pid)")
    ap.add_argument("--rank", type=int, default=0,
                    help="mds only: this daemon's rank")
    ap.add_argument("--nranks", type=int, default=1,
                    help="mds only: total active ranks")
    args = ap.parse_args(argv)
    if args.daemonize:
        pidfile = args.pid_file or os.path.join(
            args.dir, f"{args.kind}.{args.id}.pid")
        logfile = os.path.join(args.dir,
                               f"{args.kind}.{args.id}.daemon.log")
        daemonize(pidfile, logfile)
    runner = {"mon": run_mon, "osd": run_osd,
              "mds": run_mds}[args.kind]
    asyncio.run(runner(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
