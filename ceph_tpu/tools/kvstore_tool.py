"""ceph-kvstore-tool / ceph-monstore-tool analog: offline kv surgery.

Operates on a FileDB directory (a mon's data dir, a blockstore's db/):

    python -m ceph_tpu.tools.kvstore_tool PATH list [PREFIX]
    ... get PREFIX KEY [--out FILE]
    ... rm PREFIX KEY
    ... stats
    ... compact
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.store.kv import FileDB


def _key(s: str) -> bytes:
    return bytes.fromhex(s[2:]) if s.startswith("0x") else s.encode()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-kvstore-tool")
    ap.add_argument("path")
    ap.add_argument("op", choices=("list", "get", "rm", "stats",
                                   "compact"))
    ap.add_argument("args", nargs="*")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    db = FileDB(args.path)
    try:
        if args.op == "list":
            want = args.args[0] if args.args else None
            if want:
                for k in db.keys(want):
                    print(f"{want}\t{k!r}")
            else:
                for p, k, _ in db.iterate_all():
                    print(f"{p}\t{k!r}")
            return 0
        if args.op == "get":
            prefix, key = args.args[0], _key(args.args[1])
            v = db.get(prefix, key)
            if v is None:
                print("(no such key)", file=sys.stderr)
                return 1
            if args.out:
                with open(args.out, "wb") as f:
                    f.write(v)
                print(f"wrote {len(v)} bytes to {args.out}")
            else:
                print(v.hex())
            return 0
        if args.op == "rm":
            prefix, key = args.args[0], _key(args.args[1])
            db.submit(db.create_transaction().rmkey(prefix, key))
            print("removed")
            return 0
        if args.op == "stats":
            n, total = 0, 0
            for p, k, v in db.iterate_all():
                n += 1
                total += len(p) + len(k) + len(v)
            print(json.dumps({"keys": n, "bytes": total,
                              "seq": db.seq}))
            return 0
        if args.op == "compact":
            db.compact()
            print("compacted")
            return 0
        return 2
    finally:
        db.close()


if __name__ == "__main__":
    sys.exit(main())
