"""ceph CLI: mon command front-end.

Reference parity: src/ceph.in (python CLI driving mon commands,
ceph.in:98-145).  Commands map 1:1 onto the monitor's command table:

    python -m ceph_tpu.tools.ceph --dir DIR status
    ... osd dump | osd tree | osd stat | osd pool ls | quorum_status
    ... osd pool create <name> [pg_num] [--type erasure --k 4 --m 2]
    ... osd pool delete <name>
    ... osd out|in|down <id>
    ... osd getmap [epoch] --out FILE

Observability (admin-socket plane, no mon round trip):

    ... --admin-daemon DIR/osd.0.asok dump_op_stages
    ... perf dump --cluster [--prom]    # merged metrics snapshot of
                                        # every daemon + lane worker
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from ceph_tpu.tools.daemons import apply_conf, load_monmap


async def run(args, extra) -> int:
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.common.context import Context
    ctx = Context("client.admin")
    apply_conf(ctx, args.dir)   # picks up auth_supported/keyring etc.
    monmap = load_monmap(args.dir)
    r = Rados(ctx, monmap)
    await r.connect()
    try:
        cmd = build_command(args, extra)
        ack = await r.mon_command(cmd, timeout=args.timeout)
        if args.out and ack.outbl:
            with open(args.out, "wb") as f:
                f.write(ack.outbl)
            print(f"wrote {len(ack.outbl)} bytes to {args.out}")
        if ack.outs:
            print(ack.outs)
        return 0
    finally:
        await r.shutdown()


def build_command(args, extra) -> dict:
    # peel k=v arguments off the command words (reference ceph.in style:
    # `ceph osd tier add pool=cold tierpool=hot`) so the prefix is only
    # the verb phrase
    words = [w for w in args.command
             if "=" not in w or w.startswith("-")]
    extra = [w for w in args.command
             if "=" in w and not w.startswith("-")] + list(extra)
    cmd = {"prefix": " ".join(words)}
    if words[0] in ("status", "health", "df", "quorum_status", "mon"):
        return cmd
    if words[0] == "pg" and len(words) > 2 \
            and words[1] in ("scrub", "deep-scrub"):
        return {"prefix": f"pg {words[1]}", "pgid": words[2]}
    if words[0] == "osd" and len(words) > 1:
        if words[1] == "pool" and len(words) > 3:
            cmd = {"prefix": f"osd pool {words[2]}", "pool": words[3]}
            if words[2] == "set-quota" and len(words) > 5:
                # `osd pool set-quota data max_objects 100` sugar over
                # pool set quota_max_*
                cmd = {"prefix": "osd pool set", "pool": words[3],
                       "var": f"quota_{words[4]}", "val": words[5]}
            elif words[2] == "set" and len(words) > 5:
                cmd["var"], cmd["val"] = words[4], words[5]
            elif len(words) > 4 and words[4].isdigit():
                cmd["pg_num"] = int(words[4])
            if args.type:
                cmd["pool_type"] = args.type
            if args.k:
                cmd["k"] = args.k
            if args.m:
                cmd["m"] = args.m
            if args.size:
                cmd["size"] = args.size
        elif words[1] == "erasure-code-profile" and len(words) > 2:
            cmd = {"prefix": f"osd erasure-code-profile {words[2]}"}
            if len(words) > 3:
                cmd["name"] = words[3]
            if words[2] == "set":
                prof = {}
                if args.k:
                    prof["k"] = str(args.k)
                if args.m:
                    prof["m"] = str(args.m)
                for kv in list(extra):
                    k, eq, v = kv.partition("=")
                    if eq:
                        prof[k.lstrip("-")] = v
                        extra.remove(kv)
                cmd["profile"] = prof
        elif words[1] in ("out", "in", "down", "lost") and len(words) > 2:
            confirmed = False
            for bag in (extra, words):
                if "--yes-i-really-mean-it" in bag:
                    bag.remove("--yes-i-really-mean-it")
                    confirmed = True
            cmd = {"prefix": f"osd {words[1]}", "id": int(words[2])}
            if words[1] == "lost" and confirmed:
                cmd["yes_i_really_mean_it"] = True
        elif words[1] in ("set", "unset") and len(words) > 2:
            # cluster flags: ceph osd set noout / unset noout
            cmd = {"prefix": f"osd {words[1]}", "key": words[2]}
        elif words[1] == "getmap":
            cmd = {"prefix": "osd getmap"}
            if len(words) > 2:
                cmd["epoch"] = int(words[2])
        elif words[1] == "setmaxosd" and len(words) > 2:
            cmd = {"prefix": "osd setmaxosd", "num": int(words[2])}
        elif words[1] == "crush" and len(words) > 3 \
                and words[2] == "build-simple":
            cmd = {"prefix": "osd crush build-simple",
                   "num_osds": int(words[3]),
                   "osds_per_host": int(words[4]) if len(words) > 4 else 1}
        else:
            cmd = {"prefix": " ".join(words)}
    for kv in extra:
        k, _, v = kv.partition("=")
        cmd[k.lstrip("-")] = v
    return cmd


def _cluster_perf_dump(cluster_dir: str, prom: bool) -> int:
    """`ceph perf dump --cluster`: the mgr-style cluster-wide scrape.
    Every daemon under the cluster dir exposes `perf dump full` on its
    admin socket — one mergeable metrics-plane snapshot per process
    PLUS one per live lane worker (the daemon fans the request over
    FRAME_RPC itself).  The merged view sums counters, merges
    histogram buckets, and recomputes quantiles + the live
    device_byte_fraction; dead lanes are carried loudly in
    ``lane_dead``, never dropped.  ``--prom`` renders a
    Prometheus-style text exposition instead of JSON."""
    import glob
    import json as _json

    from ceph_tpu.common import metrics
    from ceph_tpu.common.admin_socket import admin_command
    socks = sorted(glob.glob(os.path.join(cluster_dir, "*.asok")))
    if not socks:
        print(f"no admin sockets under {cluster_dir!r} — is the "
              f"cluster running (vstart) with admin_socket set?",
              file=sys.stderr)
        return 1
    snaps, lane_dead, errors = [], [], []
    for path in socks:
        who = os.path.basename(path)[:-len(".asok")]
        try:
            out = admin_command(path, "perf dump full")
        except OSError:
            # a dead daemon leaves a stale socket behind; the scrape
            # exists precisely for degraded windows, so the survivors'
            # metrics must come through with the dead source carried
            # loudly — an operator mid-outage gets data, not a
            # traceback
            errors.append(who)
            continue
        if not isinstance(out, dict) or "snapshots" not in out:
            errors.append(who)
            continue
        snaps.extend(out["snapshots"])
        lane_dead.extend(out.get("lane_dead", []))
    merged = metrics.merge(snaps, lane_dead=lane_dead)
    if errors:
        merged["scrape_errors"] = errors
        print(f"WARNING: no snapshot from: {', '.join(errors)}",
              file=sys.stderr)
    if lane_dead:
        print(f"WARNING: DEAD lane(s), metrics missing: "
              f"{', '.join(map(str, lane_dead))}", file=sys.stderr)
    if prom:
        sys.stdout.write(metrics.prometheus_text(merged))
    else:
        print(_json.dumps(merged, indent=2, default=str))
    return 0


def _render_stage_table(stages: dict) -> str:
    """Aligned per-stage latency table (dump_op_stages sugar)."""
    rows = [f"{'stage':<16} {'count':>8} {'avg_ms':>10} {'p50_ms':>10} "
            f"{'p99_ms':>10} {'p999_ms':>10}"]
    for name, d in stages.items():
        if not isinstance(d, dict) or "p50_ms" not in d:
            continue
        tag = "*" if d.get("aux") else " "
        rows.append(
            f"{name:<15}{tag} {d.get('count', 0):>8} "
            f"{d.get('avg_ms', 0.0):>10.3f} {d.get('p50_ms', 0.0):>10.3f} "
            f"{d.get('p99_ms', 0.0):>10.3f} {d.get('p999_ms', 0.0):>10.3f}")
    rows.append("(* = auxiliary stage, overlaps the chain — not part "
                "of the attributed sum)")
    return "\n".join(rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("--dir", default="./vcluster", help="cluster dir")
    ap.add_argument("--out", default="", help="write outbl to file")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--type", default="", help="pool type for create")
    ap.add_argument("--k", type=int, default=0)
    ap.add_argument("--m", type=int, default=0)
    ap.add_argument("--size", type=int, default=0)
    ap.add_argument("--admin-daemon", default="",
                    help="talk to a daemon's admin socket instead of "
                         "the cluster (reference ceph.in)")
    ap.add_argument("--cluster", action="store_true",
                    help="with `perf dump`: scrape EVERY daemon's "
                         "admin socket under --dir (and, through "
                         "each daemon, every process-lane worker) "
                         "and print one merged metrics snapshot")
    ap.add_argument("--prom", action="store_true",
                    help="with `perf dump --cluster`: Prometheus-"
                         "style text exposition instead of JSON")
    ap.add_argument("command", nargs="+")
    args, extra = ap.parse_known_args(argv)
    if args.command[:2] == ["perf", "dump"] and args.cluster:
        return _cluster_perf_dump(args.dir, args.prom)
    if args.admin_daemon:
        import json as _json
        from ceph_tpu.common.admin_socket import admin_command
        out = admin_command(args.admin_daemon, " ".join(args.command))
        if isinstance(out, dict) and isinstance(out.get("stages"), dict) \
                and out["stages"]:
            # op-stage breakdown (dump_op_stages): render the table a
            # human actually wants next to the raw JSON consumers parse
            print(_render_stage_table(out["stages"]), file=sys.stderr)
        print(_json.dumps(out, indent=2, default=str))
        return 1 if isinstance(out, dict) and "error" in out else 0
    return asyncio.run(run(args, extra))


if __name__ == "__main__":
    sys.exit(main())
