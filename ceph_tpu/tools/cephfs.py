"""cephfs CLI: drive a CephFS tree on a live cluster (cephfs-shell role).

    python -m ceph_tpu.tools.cephfs --dir DIR ls /path
    ... mkdir /path | put LOCAL /path | get /path LOCAL | rm /path
    ... mv /src /dst | stat /path

Talks to the mds daemon started via `python -m ceph_tpu.tools.daemons
mds --id a --dir DIR` (its address is published in DIR/mds.<id>.addr).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from ceph_tpu.tools.daemons import apply_conf, load_monmap


def _parse_addr(s: str):
    from ceph_tpu.msg.types import EntityAddr
    host, port, nonce = s.strip().rsplit(":", 2)
    return EntityAddr(host, int(port), int(nonce))


async def _mds_addrs(r, cluster_dir: str, mds_id: str):
    """Resolve the rank-ordered MDS address list via the mon's fsmap
    (mds dump); file fallback (single mds) for dirs whose mds predates
    registration."""
    try:
        ack = await r.mon_command({"prefix": "mds dump"})
        fsmap = json.loads(ack.outs)
        by_rank = {rec.get("rank", 0): _parse_addr(rec["addr"])
                   for rec in fsmap.values()}
        if by_rank and sorted(by_rank) == list(range(len(by_rank))):
            return [by_rank[i] for i in range(len(by_rank))]
    except Exception:
        pass
    path = os.path.join(cluster_dir, f"mds.{mds_id}.addr")
    return [_parse_addr(open(path).read())]


async def run(args) -> int:
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.common.context import Context
    from ceph_tpu.services.cephfs import CephFS, CephFSError
    ctx = Context("client.admin")
    apply_conf(ctx, args.dir)
    r = Rados(ctx, load_monmap(args.dir))
    await r.connect()
    try:
        fs = CephFS(r, await _mds_addrs(r, args.dir, args.mds),
                    "cephfs_data")
        if args.op == "ls":
            for name in await fs.listdir(args.args[0]):
                print(name)
        elif args.op == "mkdir":
            await fs.makedirs(args.args[0])
        elif args.op == "put":
            with open(args.args[0], "rb") as f:
                await fs.write_file(args.args[1], f.read())
        elif args.op == "get":
            data = await fs.read_file(args.args[0])
            if args.args[1] == "-":
                sys.stdout.buffer.write(data)
            else:
                with open(args.args[1], "wb") as f:
                    f.write(data)
        elif args.op == "rm":
            await fs.unlink(args.args[0])
        elif args.op == "rmdir":
            await fs.rmdir(args.args[0])
        elif args.op == "mv":
            await fs.rename(args.args[0], args.args[1])
        elif args.op == "stat":
            print(json.dumps(await fs.stat(args.args[0])))
        else:
            return 2
        return 0
    except CephFSError as e:
        print(f"cephfs: {e}", file=sys.stderr)
        return 1
    finally:
        await r.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cephfs")
    ap.add_argument("--dir", default="./vcluster")
    ap.add_argument("--mds", default="a")
    ap.add_argument("op", choices=("ls", "mkdir", "put", "get", "rm",
                                   "rmdir", "mv", "stat"))
    ap.add_argument("args", nargs="*")
    args = ap.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
