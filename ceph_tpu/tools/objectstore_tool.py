"""ceph-objectstore-tool analog: offline store surgery.

Reference parity: src/tools/ceph_objectstore_tool.cc — operate directly
on a daemon's (un-mounted) object store: list pgs/objects, dump object
info, export a whole PG to a portable file, import it into another
store, remove objects or PGs.  The export container is simply an encoded
ObjectStore Transaction (plus a magic header), so import replays it
through the normal apply path of ANY backend — memstore dumps can be
imported into a blockstore and vice versa.

    python -m ceph_tpu.tools.objectstore_tool --data-path DIR \
        [--type blockstore|filestore] --op list|list-pgs|info|export|...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.store.objectstore import ObjectStore, Transaction
from ceph_tpu.store.types import CollectionId, ObjectId

MAGIC = b"ceph-tpu-pg-export-v1"


def detect_type(path: str) -> str:
    if os.path.exists(os.path.join(path, "block")):
        return "blockstore"
    return "filestore"


def open_store(args) -> ObjectStore:
    kind = args.type or detect_type(args.data_path)
    s = ObjectStore.create(kind, args.data_path)
    s.mount()
    return s


def _cid(args) -> CollectionId:
    if not args.pgid:
        raise SystemExit("--pgid required for this op")
    return CollectionId(args.pgid if args.pgid.endswith("_head")
                        else args.pgid + "_head")


def op_list_pgs(s, args) -> int:
    for cid in sorted(s.list_collections(), key=lambda c: c.name):
        if cid.is_pg():
            print(cid.name[:-len("_head")])
    return 0


def op_list(s, args) -> int:
    cids = ([_cid(args)] if args.pgid else
            [c for c in s.list_collections() if c.is_pg()])
    for cid in cids:
        for oid in s.collection_list(cid):
            print(json.dumps([cid.name, {
                "name": oid.name, "snap": oid.snap, "pool": oid.pool}]))
    return 0


def _find(s, cid: CollectionId, name: str) -> Optional[ObjectId]:
    for oid in s.collection_list(cid):
        if oid.name == name:
            return oid
    return None


def op_info(s, args) -> int:
    cid = _cid(args)
    oid = _find(s, cid, args.object)
    if oid is None:
        print(f"object {args.object!r} not found", file=sys.stderr)
        return 1
    hdr, omap = s.omap_get(cid, oid)
    print(json.dumps({
        "oid": {"name": oid.name, "snap": oid.snap, "pool": oid.pool},
        "size": s.stat(cid, oid)["size"],
        "attrs": sorted(s.getattrs(cid, oid)),
        "omap_keys": len(omap),
    }, indent=2))
    return 0


def op_get_bytes(s, args) -> int:
    cid = _cid(args)
    oid = _find(s, cid, args.object)
    if oid is None:
        return 1
    data = s.read(cid, oid)
    if args.file == "-":
        sys.stdout.buffer.write(data)
    else:
        with open(args.file, "wb") as f:
            f.write(data)
    return 0


def op_remove(s, args) -> int:
    cid = _cid(args)
    if args.object:
        oid = _find(s, cid, args.object)
        if oid is None:
            return 1
        s.apply_transaction(Transaction().remove(cid, oid))
        print(f"removed {args.object}")
    else:
        s.apply_transaction(Transaction().remove_collection(cid))
        print(f"removed pg {args.pgid}")
    return 0


def export_pg(s, cid: CollectionId) -> bytes:
    """The whole PG as one replayable Transaction."""
    t = Transaction().create_collection(cid)
    for oid in s.collection_list(cid):
        data = s.read(cid, oid)
        t.touch(cid, oid)
        if data:
            t.write(cid, oid, 0, data)
        attrs = s.getattrs(cid, oid)
        if attrs:
            t.setattrs(cid, oid, attrs)
        hdr, omap = s.omap_get(cid, oid)
        if hdr:
            t.omap_setheader(cid, oid, hdr)
        if omap:
            t.omap_setkeys(cid, oid, omap)
    enc = Encoder()
    enc.bytes_(MAGIC).string(cid.name).struct(t)
    return enc.getvalue()


def op_export(s, args) -> int:
    cid = _cid(args)
    blob = export_pg(s, cid)
    with open(args.file, "wb") as f:
        f.write(blob)
    print(f"exported {args.pgid} ({len(blob)} bytes) to {args.file}")
    return 0


def op_import(s, args) -> int:
    with open(args.file, "rb") as f:
        dec = Decoder(f.read())
    if dec.bytes_() != MAGIC:
        print("not a pg export file", file=sys.stderr)
        return 1
    name = dec.string()
    txn = dec.struct(Transaction)
    if s.collection_exists(CollectionId(name)):
        print(f"pg {name} already exists in target; remove it first",
              file=sys.stderr)
        return 1
    s.apply_transaction(txn)
    print(f"imported pg {name[:-len('_head')]}")
    return 0


def op_statfs(s, args) -> int:
    if hasattr(s, "statfs"):
        print(json.dumps(s.statfs()))
        return 0
    print("{}")
    return 0


OPS = {
    "list": op_list,
    "list-pgs": op_list_pgs,
    "info": op_info,
    "get-bytes": op_get_bytes,
    "remove": op_remove,
    "export": op_export,
    "import": op_import,
    "statfs": op_statfs,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-objectstore-tool")
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--type", default="",
                    help="blockstore|filestore (default: detect)")
    ap.add_argument("--op", required=True, choices=sorted(OPS))
    ap.add_argument("--pgid", default="", help="e.g. 1.4  (pg collection)")
    ap.add_argument("--object", default="", help="object name")
    ap.add_argument("--file", default="-", help="export/import/get file")
    args = ap.parse_args(argv)
    s = open_store(args)
    try:
        return OPS[args.op](s, args)
    except BrokenPipeError:
        return 0   # output piped into head etc.
    finally:
        s.umount()


if __name__ == "__main__":
    sys.exit(main())
