"""crushtool: build / inspect / test crush maps offline.

Reference parity: src/tools/crushtool.cc (--build/--test/-d) and
src/crush/CrushTester.h (mapping distribution + timing).

    python -m ceph_tpu.tools.crushtool --build N [--osds-per-host H] -o F
    python -m ceph_tpu.tools.crushtool -d F
    python -m ceph_tpu.tools.crushtool --test F --num-rep 3 \
        [--min-x 0 --max-x 1023] [--rule 0] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import Counter

from ceph_tpu.crush.builder import (build_hierarchy, make_erasure_rule,
                                    make_replicated_rule)
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.ops.crush_kernel import batch_do_rule


def cmd_build(args) -> int:
    m = CrushMap()
    m.max_devices = args.build
    build_hierarchy(m, args.build, args.osds_per_host)
    make_replicated_rule(m, "replicated_rule")
    make_erasure_rule(m, "erasure_rule", size=args.ec_size)
    data = m.to_bytes()
    out = args.output or "crushmap.bin"
    with open(out, "wb") as f:
        f.write(data)
    print(f"built crush map: {args.build} osds, "
          f"{args.osds_per_host}/host, {len(data)} bytes -> {out}")
    return 0


def cmd_decompile(args) -> int:
    """Emit the reference text dialect (crushtool -d, CrushCompiler)."""
    from ceph_tpu.crush.compiler import decompile
    with open(args.decompile, "rb") as f:
        m = CrushMap.from_bytes(f.read())
    text = decompile(m)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def cmd_compile(args) -> int:
    """Compile the text dialect to a binary map (crushtool -c)."""
    from ceph_tpu.crush.compiler import CompileError, compile_text
    with open(args.compile) as f:
        text = f.read()
    try:
        m = compile_text(text)
    except CompileError as e:
        print(f"crushtool: {e}", file=sys.stderr)
        return 1
    data = m.to_bytes()
    out = args.output or "crushmap.bin"
    with open(out, "wb") as f:
        f.write(data)
    print(f"compiled {args.compile}: {m.summary()} "
          f"({len(data)} bytes) -> {out}")
    return 0


def cmd_test(args) -> int:
    with open(args.test, "rb") as f:
        m = CrushMap.from_bytes(f.read())
    weights = [0x10000] * m.max_devices
    ruleno = args.rule
    n = args.max_x - args.min_x + 1
    per_osd = Counter()
    sizes = Counter()
    t0 = time.perf_counter()
    results = batch_do_rule(m, ruleno,
                            list(range(args.min_x, args.max_x + 1)),
                            args.num_rep, weights)
    dt = time.perf_counter() - t0
    for out in results:
        sizes[len(out)] += 1
        for o in out:
            per_osd[o] += 1
    expected = n * args.num_rep / max(1, m.max_devices)
    report = {
        "inputs": n,
        "num_rep": args.num_rep,
        "rule": ruleno,
        "result_size_histogram": dict(sizes),
        "mappings_per_sec": round(n / dt, 1),
        "seconds": round(dt, 4),
        "device_utilization": {
            "expected_per_osd": round(expected, 1),
            "min": min(per_osd.values()) if per_osd else 0,
            "max": max(per_osd.values()) if per_osd else 0,
        },
    }
    if args.json:
        print(json.dumps(report))
    else:
        print(f"rule {ruleno}, x = {args.min_x}..{args.max_x}, "
              f"numrep {args.num_rep}")
        for sz, cnt in sorted(sizes.items()):
            print(f"rule {ruleno} num_rep {args.num_rep} "
                  f"result size == {sz}:\t{cnt}/{n}")
        print(f"timing: {dt:.4f}s ({n / dt:.0f} mappings/s)")
        print(f"device utilization: expected {expected:.1f} "
              f"min {report['device_utilization']['min']} "
              f"max {report['device_utilization']['max']}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crushtool")
    ap.add_argument("--build", type=int, help="build simple map: N osds")
    ap.add_argument("--osds-per-host", type=int, default=1)
    ap.add_argument("--ec-size", type=int, default=6)
    ap.add_argument("-o", "--output", default=None,
                    help="output file (compile default: crushmap.bin; "
                         "decompile default: stdout)")
    ap.add_argument("-d", "--decompile", help="print a map as text")
    ap.add_argument("-c", "--compile", help="compile a text map")
    ap.add_argument("--test", help="map inputs through a rule")
    ap.add_argument("--rule", type=int, default=0)
    ap.add_argument("--num-rep", type=int, default=3)
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.build:
        return cmd_build(args)
    if args.decompile:
        return cmd_decompile(args)
    if args.compile:
        return cmd_compile(args)
    if args.test:
        return cmd_test(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
