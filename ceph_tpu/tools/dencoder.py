"""ceph-dencoder analog: inspect/round-trip versioned encodings.

Reference parity: src/tools/ceph-dencoder (src/test/encoding/
readable.sh harness) — `list_types`, `type T encode export`,
`type T import F decode dump_json`.  The committed corpus under
tests/corpus/ is generated/validated by tests/corpus_gen.py +
tests/test_encoding_corpus.py; this CLI is the operator-facing probe.

    python -m ceph_tpu.tools.dencoder list_types
    python -m ceph_tpu.tools.dencoder type ceph_tpu.osd.types.PGPool \
        encode --out /tmp/pool.bin
    python -m ceph_tpu.tools.dencoder type ceph_tpu.osd.types.PGPool \
        decode /tmp/pool.bin
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys


def _load_type(dotted: str):
    mod, _, cls = dotted.rpartition(".")
    return getattr(importlib.import_module(mod), cls)


def _samples():
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve()
                           .parents[2] / "tests"))
    import corpus_gen
    return corpus_gen.samples()


def _dump(obj) -> dict:
    out = {"_type": type(obj).__name__,
           "_struct_v": obj.STRUCT_V}
    slots = getattr(obj, "__slots__", None)
    names = slots if slots else [a for a in vars(obj)
                                 if not a.startswith("_")]
    for a in names:
        try:
            v = getattr(obj, a)
        except AttributeError:
            continue
        if isinstance(v, bytes):
            v = f"<{len(v)} bytes>"
        elif not isinstance(v, (str, int, float, bool, type(None),
                                list, dict)):
            v = repr(v)
        out[a] = v
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dencoder")
    ap.add_argument("verb", choices=["list_types", "type"])
    ap.add_argument("args", nargs="*")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    if args.verb == "list_types":
        for name in sorted(_samples()):
            print(name)
        return 0
    if len(args.args) < 2:
        print("usage: type <dotted.Type> encode|decode [file]",
              file=sys.stderr)
        return 2
    tname, op = args.args[0], args.args[1]
    if op == "encode":
        obj = _samples().get(tname)
        if obj is None:
            print(f"no sample for {tname}", file=sys.stderr)
            return 1
        blob = obj.to_bytes()
        if args.out:
            with open(args.out, "wb") as f:
                f.write(blob)
            print(f"wrote {len(blob)} bytes (v{obj.STRUCT_V})")
        else:
            sys.stdout.buffer.write(blob)
        return 0
    if op == "decode":
        cls = _load_type(tname)
        path = args.args[2] if len(args.args) > 2 else "-"
        blob = (sys.stdin.buffer.read() if path == "-"
                else open(path, "rb").read())
        obj = cls.from_bytes(blob)
        print(json.dumps(_dump(obj), indent=2, default=str))
        return 0
    print(f"unknown op {op!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # `| head` closed the pipe: not an error
        sys.exit(0)
