"""Deterministic schedule explorer (ISSUE 9).

Coverage map:
  * determinism — same seed => byte-identical interleaving trace hash
    across two runs (the replay contract every pinned-seed regression
    test depends on);
  * bounded exploration — >= 64 seeded schedules PLUS every enumerated
    commit-thread crash point on the ec mini-workload, zero invariant
    findings on the live tree;
  * seeded-bug fixtures — the two reintroduced historical hazards
    (pre-PR-5 out-of-order version assignment; commit callbacks before
    the durability barrier) are each caught within a bounded schedule
    budget;
  * the sequencer EAGAIN path under a forced adversarial schedule —
    a windowed op that observes a mid-flight interval change releases
    its slot, dispatch-throttle and OpTracker accounting exactly once;
  * the LoopStallMonitor wired to the deterministic loop (virtual
    attach): exhaustive per-callback stall attribution in sim mode.
"""

import asyncio
import errno
import time
from collections import Counter

from ceph_tpu.common import lockdep
from ceph_tpu.devtools.schedule import (
    CRASH_POINTS, AdversarialScheduler, ScheduleController,
    explore, run_deterministic, run_ec_mini,
)

# ------------------------------------------------------------ determinism


def test_same_seed_identical_trace_hash():
    kw = dict(pool_type="replicated", n_osds=2, n_objects=4, iodepth=4)
    r1 = run_ec_mini(seed=3, **kw)
    r2 = run_ec_mini(seed=3, **kw)
    assert r1.ok, r1.render()
    assert r2.ok, r2.render()
    assert r1.steps == r2.steps
    assert r1.trace_hash == r2.trace_hash
    # and the hash actually covers the schedule: a different seed's
    # walk through the same workload takes different decisions
    r3 = run_ec_mini(seed=4, **kw)
    assert r3.ok, r3.render()
    assert (r3.trace_hash != r1.trace_hash) or (r3.steps != r1.steps)


def test_virtual_time_no_wall_clock_sleeps():
    """A FAST_CFG cluster boot + write burst sleeps for many seconds of
    cluster time (election, heartbeats, boot retry loops); under the
    deterministic loop that is all VIRTUAL — the run must finish in a
    fraction of the simulated time."""
    t0 = time.monotonic()
    rep = run_ec_mini(seed=0, controller=ScheduleController(),
                      pool_type="replicated", n_osds=2,
                      n_objects=4, iodepth=4)
    wall = time.monotonic() - t0
    assert rep.ok, rep.render()
    # generous bound: simulated boot alone waits multiple seconds of
    # timer time; the wall bound only fails if sleeps became real
    assert wall < 30.0, wall


# --------------------------------------------------- bounded exploration


def test_bounded_exploration_ec_mini_is_clean():
    """>= 64 seeded schedules + every enumerated crash point (all three
    PR-1 fault-injection hooks, occurrence-indexed) on the ec_e2e
    mini-workload: the live tree must hold every machine-checked
    invariant under every explored interleaving."""
    rep = explore(64, max_crash_occurrences=2)
    assert len(rep.schedules) >= 64
    assert {p for _osd, p, _occ in rep.crash_points} == set(CRASH_POINTS), \
        rep.crash_points
    assert rep.crash_runs
    assert not rep.failures, rep.render_failures()


def test_two_shard_sim_exploration_is_clean():
    """ISSUE 10 satellite: the EC mini-workload at osd_op_num_shards=2
    under SIM — shard pumps are ordinary tasks on the seeded
    deterministic loop, so every explored schedule is a different
    interleaving of the two shard threads' work.  The full PR-9
    checklist (dense pglog, durability-before-ack, balanced
    slots/throttle/rings, zero local-path encodes, no acked write
    lost) must hold across >= 64 schedules + every enumerated
    commit-thread crash point."""
    rep = explore(64, max_crash_occurrences=2, num_shards=2)
    assert len(rep.schedules) >= 64
    assert {p for _osd, p, _occ in rep.crash_points} == \
        set(CRASH_POINTS), rep.crash_points
    assert rep.crash_runs
    assert not rep.failures, rep.render_failures()
    # the sharded plane actually engaged: same seed replays identically
    r1 = run_ec_mini(seed=5, num_shards=2)
    r2 = run_ec_mini(seed=5, num_shards=2)
    assert r1.ok and r2.ok, r1.render() + r2.render()
    assert r1.trace_hash == r2.trace_hash


def test_kill_restart_exploration_cursor_invariants():
    """ISSUE 17 tentpole: an osd kill+restart event landing at
    seed-permuted points in >= 64 explored schedules (32 seeds x two
    kill depths), under the backfill-cursor canaries — no shard serves
    a read past its own durable cursor, no cursor regresses within an
    interval, and no acked write is lost across the kill + rebuild
    (the restarted OSD must CONVERGE before acked reads re-verify)."""
    rep = explore(32, with_crashes=False, with_kills=True)
    assert len(rep.kill_runs) >= 64, len(rep.kill_runs)
    assert all(r.kill is not None for r in rep.kill_runs)
    assert not rep.failures, rep.render_failures()


# ----------------------------------------------------- seeded-bug fixtures


def test_explorer_catches_out_of_order_version_assignment():
    from schedule_fixtures import out_of_order_version_assignment
    kw = dict(pool_type="replicated", n_osds=3, n_objects=8, iodepth=8)
    with out_of_order_version_assignment():
        caught = None
        for seed in range(16):          # bounded schedule budget
            rep = run_ec_mini(seed=seed, **kw)
            if any("dense" in f for f in rep.findings):
                caught = rep
                break
        assert caught is not None, \
            "explorer missed the out-of-order version hazard in 16 schedules"
    # and the fix holds: same workload, same seed, bug removed => clean
    rep = run_ec_mini(seed=caught.seed, **kw)
    assert rep.ok, rep.render()


def test_explorer_catches_commit_callbacks_before_durability():
    from schedule_fixtures import commit_callbacks_before_durability
    kw = dict(pool_type="replicated", n_osds=2, n_objects=4, iodepth=4)
    with commit_callbacks_before_durability():
        rep = run_ec_mini(seed=0, controller=ScheduleController(), **kw)
        assert any("ack before durability" in f for f in rep.findings), \
            rep.findings
        # with a crash armed at the first post-warm group the escaped
        # acks vouch for state the crash threw away
        rep2 = run_ec_mini(seed=0, controller=ScheduleController(),
                           crash=(0, "before_data_sync", 0), **kw)
        assert any("ack before durability" in f
                   for f in rep2.findings), rep2.findings
    rep3 = run_ec_mini(seed=0, controller=ScheduleController(), **kw)
    assert rep3.ok, rep3.render()


def test_explorer_catches_boolean_backfill_marker():
    """ISSUE 18 regression fixture: reintroduce the pre-cursor
    boolean backfill marker (a mid-copy EC shard claims authority over
    its whole namespace — absent names answer ENOENT, half-copies
    serve) and assert the backfill-cursor canaries catch it within a
    bounded kill-schedule budget.  A checker that never caught its
    target bug is a no-op with good marketing."""
    from schedule_fixtures import boolean_backfill_marker
    # recovery throttle keeps the backfill-cursor window open long
    # enough for degraded reads to race it
    kw = dict(n_objects=8, iodepth=8,
              cfg={"osd_recovery_max_active": 1,
                   "osd_recovery_sleep": 0.05})
    caught = None
    with boolean_backfill_marker():
        for seed in range(16):          # bounded schedule budget
            # fresh-store restart: full resync, so reads race a live
            # backfill-cursor window (a surviving store does log-based
            # recovery and never opens the window)
            rep = run_ec_mini(seed=seed, kill=(1, 1, True), **kw)
            if any("cursor hole served as ENOENT" in f
                   or "cursor read leak" in f
                   or "served as deletion" in f
                   for f in rep.findings):
                caught = rep
                break
        assert caught is not None, \
            "canaries missed the boolean-marker bug in 16 kill schedules"
    # and the fix holds: same schedule, bug removed => cursor-clean
    rep2 = run_ec_mini(seed=caught.seed, kill=(1, 1, True), **kw)
    assert not any("cursor" in f or "served as deletion" in f
                   for f in rep2.findings), rep2.render()


# ------------------------------------- sequencer EAGAIN path (satellite)


def test_windowed_eagain_releases_accounting_exactly_once():
    """Forced adversarial schedule: admitted windowed ops are starved
    until a mid-flight interval change (replica marked down) flips the
    PG out of ACTIVE; every such op must abort EAGAIN and release its
    window slot, dispatch-throttle budget and OpTracker entry exactly
    once — then the resent ops complete against the new interval."""
    from ceph_tpu.qa.cluster import Cluster, make_sim_ctx

    box = {"pg": None, "armed": False}

    def starving() -> bool:
        pg = box["pg"]
        return bool(box["armed"] and pg is not None
                    and pg.state == "active")

    controller = AdversarialScheduler("PG._run_windowed",
                                      active=starving)

    async def main():
        cl = Cluster(ctx_factory=make_sim_ctx)
        admin = await cl.start(3)
        await admin.pool_create("ea", pg_num=1)
        io = admin.open_ioctx("ea")
        await io.write_full("warm", b"w")
        posd = next(o for o in cl.osds.values()
                    for pg in o.pgs.values()
                    if pg.pool_id == io.pool_id and pg.is_primary())
        pg = next(p for p in posd.pgs.values()
                  if p.pool_id == io.pool_id)
        box["pg"] = pg

        eagain_windowed = []
        orig_reply = posd.reply_to

        def counting_reply(req, msg):
            if getattr(msg, "result", 0) == -errno.EAGAIN \
                    and getattr(req, "_windowed", False):
                eagain_windowed.append(req.tid)
            orig_reply(req, msg)

        posd.reply_to = counting_reply
        finishes = Counter()
        orig_finish = posd.op_tracker.finish

        def counting_finish(op, event="done"):
            finishes[op.seq] += 1
            orig_finish(op, event)

        posd.op_tracker.finish = counting_finish

        box["armed"] = True

        async def noise():
            # keeps the ready queue non-empty while armed so the
            # starved victims are never the sole runnable candidate
            # (the scheduler's no-livelock fallback would run them);
            # sleep(0) reschedules via call_soon — no timer, so the
            # virtual clock stays frozen during the adversarial phase
            while box["armed"]:
                await asyncio.sleep(0)

        noise_task = asyncio.ensure_future(noise())
        blobs = {f"e{i:03d}": bytes([i]) * 1024 for i in range(24)}
        burst = asyncio.ensure_future(
            cl.write_burst(io, blobs, iodepth=24))
        # let admissions fill the window (the victims stay starved);
        # timer-free polling — time is frozen while noise runs
        for _ in range(5000):
            await asyncio.sleep(0)
            if pg.op_window.active >= 4:
                break
        assert pg.op_window.active >= 1, "window never filled"
        victim_osd = next(o for o in pg.acting if o != posd.whoami)
        cmd = asyncio.ensure_future(admin.mon_command(
            {"prefix": "osd down", "id": victim_osd}))
        # wait for the interval change to reach the primary: from here
        # the scheduler releases the starved windowed ops into a
        # not-active PG — the EAGAIN path under test
        for _ in range(20000):
            await asyncio.sleep(0)
            if pg.state != "active":
                break
        assert pg.state != "active", "interval change never landed"
        box["armed"] = False
        await noise_task
        await asyncio.wait_for(cmd, 60.0)
        await asyncio.wait_for(burst, 300.0)
        for name, data in blobs.items():
            assert await io.read(name) == data
        # quiesce, then the exactly-once accounting must balance
        for _ in range(200):
            if all(p.op_window.active == 0
                   for o in cl.osds.values() for p in o.pgs.values()) \
                    and not posd.op_tracker._inflight:
                break
            await asyncio.sleep(0.1)
        assert eagain_windowed, \
            "no windowed op ever observed the interval change"
        assert all(n == 1 for n in finishes.values()), finishes
        assert pg.op_window.balanced()
        for osd in cl.osds.values():
            thr = osd.messenger.dispatch_throttle
            assert thr is None or thr.cur == 0, \
                (osd.whoami, thr.cur)
        await cl.stop()
        return len(eagain_windowed)

    hits, _loop = run_deterministic(main, seed=0,
                                    controller=controller)
    assert hits >= 1


# -------------------------------------------- virtual stall monitor


def test_stall_monitor_virtual_attach_is_deterministic():
    """Under the deterministic loop the stall monitor times EVERY
    callback (no probe thread, no sampling luck): a synchronous 0.2s
    section with a 50ms budget is flagged with the owning tracer stage
    and the callback label, on every run."""
    from ceph_tpu.common.tracer import Span

    lockdep.reset()
    lockdep.enable()
    try:
        async def main():
            loop = asyncio.get_running_loop()
            mon = lockdep.LoopStallMonitor(loop, budget=0.05)
            mon.attach_virtual(loop)
            await asyncio.sleep(0.1)

            async def stall_task():
                span = Span(1, 1)
                span.cut("prepare")
                time.sleep(0.2)     # deliberate synchronous stall

            # a real task, so the finding names the offending coroutine
            await asyncio.get_running_loop().create_task(stall_task())
            await asyncio.sleep(0.1)
            mon.stop()
            return mon.stalls

        stalls, _loop = run_deterministic(main, seed=0)
        assert stalls >= 1
        rep = [e for e in lockdep.report() if e["kind"] == "loop_stall"]
        assert rep, lockdep.report()
        assert rep[0]["seconds"] >= 0.15
        assert rep[0]["stage"] == "prepare"
        assert "stall_task" in rep[0].get("callback", "")
    finally:
        lockdep.disable()
        lockdep.reset()
