"""Weighted priority op queue (common/WeightedPriorityQueue.h role)."""

import asyncio

from ceph_tpu.common.wpq import WeightedPriorityQueue


def test_fifo_within_class():
    async def run():
        q = WeightedPriorityQueue()
        for i in range(5):
            q.put_nowait(("c", i), "client")
        got = [await q.get() for _ in range(5)]
        assert got == [("c", i) for i in range(5)]
    asyncio.run(run())


def test_get_nowait_drains_like_asyncio_queue():
    async def run():
        q = WeightedPriorityQueue()
        q.put_nowait("a", "client")
        q.put_nowait("b", "scrub")
        drained = []
        try:
            while True:
                drained.append(q.get_nowait())
        except asyncio.QueueEmpty:
            pass
        assert sorted(drained) == ["a", "b"] and q.empty()
    asyncio.run(run())


def test_no_starvation_under_client_flood():
    """A scrub item enqueued behind a flood of client ops must be
    served within ~one client-weight cycle, not after the flood."""
    async def run():
        q = WeightedPriorityQueue({"client": 10, "recovery": 3,
                                   "scrub": 2, "agent": 2})
        for i in range(1000):
            q.put_nowait(("c", i), "client")
        q.put_nowait(("s", 0), "scrub")
        q.put_nowait(("a", 0), "agent")
        drained = []
        for _ in range(40):
            drained.append(await q.get())
        assert ("s", 0) in drained, "scrub starved by client flood"
        assert ("a", 0) in drained, "agent starved by client flood"
        # clients still dominate throughput by ~their weight share
        n_client = sum(1 for x in drained if x[0] == "c")
        assert n_client >= 25
    asyncio.run(run())


def test_weight_shares_between_busy_classes():
    async def run():
        q = WeightedPriorityQueue({"client": 6, "recovery": 2,
                                   "scrub": 1, "agent": 1})
        for i in range(300):
            q.put_nowait(("c", i), "client")
            q.put_nowait(("r", i), "recovery")
        drained = [await q.get() for _ in range(200)]
        n_c = sum(1 for x in drained if x[0] == "c")
        n_r = sum(1 for x in drained if x[0] == "r")
        assert 2.0 < n_c / n_r < 4.0, (n_c, n_r)   # ~6:2
    asyncio.run(run())


def test_credit_rotation_is_deterministic():
    """Exact credit-rotation order: spend weight[k] credits on class k,
    then rotate; empty classes forfeit their turn.  This trace is part
    of the qos=off contract — FAST_CFG determinism (and the seeded
    schedule explorer) ride on wpq serving bit-for-bit this order."""
    q = WeightedPriorityQueue({"client": 2, "scrub": 1})
    for i in range(4):
        q.put_nowait(("c", i), "client")
    for i in range(2):
        q.put_nowait(("s", i), "scrub")
    got = [q.get_nowait() for _ in range(6)]
    assert got == [("c", 0), ("c", 1), ("s", 0), ("c", 2), ("c", 3),
                   ("s", 1)]


def test_unknown_class_auto_registers_weight_one():
    """A class outside the configured weights (e.g. 'recovery' on the
    default map) joins the rotation at weight 1 instead of being
    dropped or starving."""
    q = WeightedPriorityQueue({"client": 4})
    q.put_nowait("r", "recovery")          # not pre-registered
    assert q.weights["recovery"] == 1
    for i in range(8):
        q.put_nowait(("c", i), "client")
    got = [q.get_nowait() for _ in range(6)]
    assert "r" in got                      # one credit per cycle
    assert q.qsize() == 3


def test_qos_seam_flag_is_off():
    """queue_op keys class-tag rewrites off the queue's QOS attr: wpq
    must never see envelope classes (an unknown class would register
    at weight 1 and change the deterministic rotation above)."""
    assert WeightedPriorityQueue.QOS is False


def test_async_consumer_wakes_on_put():
    async def run():
        q = WeightedPriorityQueue()

        async def producer():
            await asyncio.sleep(0.05)
            q.put_nowait("x", "client")

        asyncio.get_running_loop().create_task(producer())
        assert await asyncio.wait_for(q.get(), 2.0) == "x"
    asyncio.run(run())
