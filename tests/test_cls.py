"""Object classes (cls): server-side methods, cls_lock, cls_rbd, and the
RBD exclusive lock built on them.

Mirrors the reference's src/test/cls_lock / cls_rbd unit tests plus the
librbd ExclusiveLock behavior: racing clients serialize through the PG
instead of losing read-modify-writes (osd/ClassHandler.cc,
objclass/objclass.h:28-60, src/cls/lock/cls_lock.cc).
"""

import asyncio
import errno
import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.client.objecter import ObjectOperationError  # noqa: E402
from ceph_tpu.services.rbd import RBD, Image, ImageBusy  # noqa: E402


def test_cls_lock_and_dir_replicated():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=8)
        io = admin.open_ioctx("p")

        # exclusive lock: second holder busy, unlock releases
        req = {"name": "l1", "type": "exclusive", "entity": "a",
               "cookie": "c1"}
        await io.exec("obj", "lock", "lock", json.dumps(req).encode())
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("obj", "lock", "lock", json.dumps(
                {**req, "entity": "b", "cookie": "c2"}).encode())
        assert ei.value.retcode == -errno.EBUSY
        info = json.loads(await io.exec(
            "obj", "lock", "get_info", json.dumps({"name": "l1"}).encode()))
        assert list(info["lockers"]) == ["a/c1"]
        await io.exec("obj", "lock", "unlock", json.dumps(
            {"name": "l1", "entity": "a", "cookie": "c1"}).encode())
        await io.exec("obj", "lock", "lock", json.dumps(
            {**req, "entity": "b", "cookie": "c2"}).encode())

        # break_lock evicts a dead holder
        await io.exec("obj", "lock", "break_lock", json.dumps(
            {"name": "l1", "entity": "b", "cookie": "c2"}).encode())
        info = json.loads(await io.exec(
            "obj", "lock", "get_info", json.dumps({"name": "l1"}).encode()))
        assert not info["lockers"]

        # rbd directory methods (omap-backed, replicated pool)
        await io.exec("dir", "rbd", "dir_add",
                      json.dumps({"name": "img1"}).encode())
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("dir", "rbd", "dir_add",
                          json.dumps({"name": "img1"}).encode())
        assert ei.value.retcode == -errno.EEXIST
        names = json.loads(await io.exec("dir", "rbd", "dir_list"))
        assert names == ["img1"]

        # unknown method fails loudly
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("obj", "nope", "method")
        assert ei.value.retcode == -errno.EOPNOTSUPP
        await cl.stop()
    asyncio.run(run())


def test_cls_lock_on_ec_pool():
    """xattr-based cls methods must work on EC pools (staged logical
    ops translate through the EC per-shard write path)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(5)
        await admin.pool_create("ecp", pg_num=8, pool_type="erasure",
                                k=2, m=2)
        io = admin.open_ioctx("ecp")
        req = {"name": "l", "type": "exclusive", "entity": "a",
               "cookie": "c"}
        await io.exec("eobj", "lock", "lock", json.dumps(req).encode())
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("eobj", "lock", "lock", json.dumps(
                {**req, "entity": "b"}).encode())
        assert ei.value.retcode == -errno.EBUSY
        info = json.loads(await io.exec(
            "eobj", "lock", "get_info",
            json.dumps({"name": "l"}).encode()))
        assert list(info["lockers"]) == ["a/c"]
        # a method staging omap gets the EC pool's EOPNOTSUPP
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("edir", "rbd", "dir_add",
                          json.dumps({"name": "x"}).encode())
        assert ei.value.retcode == -errno.EOPNOTSUPP
        await cl.stop()
    asyncio.run(run())


def test_rbd_exclusive_lock_no_lost_updates():
    """VERDICT r3 ask #6 done-criterion: two clients writing one image
    concurrently must not lose updates.  With the exclusive lock, the
    second writer can't even open until the first closes; its RMW then
    sees the first writer's bytes."""
    async def run():
        cl = Cluster()
        admin = await cl.start(5)
        await admin.pool_create("rbd", pg_num=8, pool_type="erasure",
                                k=2, m=2)
        io = admin.open_ioctx("rbd")
        await RBD(io).create("disk", size=1 << 20, order=16)

        img_a = await Image.open(io, "disk", exclusive=True)
        with pytest.raises(ImageBusy):
            await Image.open(io, "disk", exclusive=True)

        # A writes the first half of an object, closes (releases lock)
        await img_a.write(0, b"A" * 1000)
        await img_a.close()

        # B can now take the lock; its RMW of the SAME object must
        # preserve A's bytes
        img_b = await Image.open(io, "disk", exclusive=True)
        await img_b.write(1000, b"B" * 1000)
        got = await img_b.read(0, 2000)
        assert got == b"A" * 1000 + b"B" * 1000, "lost update"
        await img_b.close()

        # lock is free again after close
        img_c = await Image.open(io, "disk", exclusive=True)
        await img_c.close()
        await cl.stop()
    asyncio.run(run())


def test_rbd_header_via_cls():
    """Header create/get/set_size ride cls_rbd; double-create is
    EEXIST server-side (no read-check-write race window)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("img", size=4 << 20, order=16)
        from ceph_tpu.services.rbd import ImageExists
        with pytest.raises(ImageExists):
            await rbd.create("img", size=1 << 20, order=16)
        img = await Image.open(io, "img")
        assert img.size == 4 << 20 and img.order == 16
        await img.resize(2 << 20)
        img2 = await Image.open(io, "img")
        assert img2.size == 2 << 20
        assert await rbd.list() == ["img"]
        await rbd.remove("img")
        assert await rbd.list() == []
        await cl.stop()
    asyncio.run(run())


def test_cls_refcount_get_put_delete():
    """cls_refcount (src/cls/refcount/cls_refcount.cc role): the object
    survives while any tag holds a ref; the last put deletes it."""
    async def run():
        import json
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=4)
        io = admin.open_ioctx("p")
        await io.write_full("shared", b"tail bytes")
        await io.exec("shared", "refcount", "get",
                      json.dumps({"tag": "copyA"}).encode())
        await io.exec("shared", "refcount", "get",
                      json.dumps({"tag": "copyB"}).encode())
        refs = json.loads((await io.exec("shared", "refcount", "read",
                                         b"")).decode())
        assert set(refs) == {"#implicit", "copyA", "copyB"}
        # drop implicit + A: object stays
        for tag in ("#implicit", "copyA"):
            out = json.loads((await io.exec(
                "shared", "refcount", "put",
                json.dumps({"tag": tag}).encode())).decode())
            assert out["deleted"] is False
        assert await io.read("shared") == b"tail bytes"
        # last ref: object goes
        out = json.loads((await io.exec(
            "shared", "refcount", "put",
            json.dumps({"tag": "copyB"}).encode())).decode())
        assert out["deleted"] is True
        from ceph_tpu.client.objecter import ObjectOperationError
        with pytest.raises(ObjectOperationError):
            await io.read("shared")
        await cl.stop()
    asyncio.run(run())


def test_cls_journal_commit_monotonic_and_cas():
    """cls_journal guards: commits never rewind; active-object rotation
    is CAS (src/cls/journal/cls_journal.cc role)."""
    async def run():
        import errno
        import json
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=4)
        io = admin.open_ioctx("p")
        from ceph_tpu.journal import Journaler
        jr = Journaler(io, "img")
        await jr.create()
        await jr.register_client("m1")
        await jr.commit("m1", 7)
        await jr.commit("m1", 3)          # stale: must not rewind
        assert await jr.get_commit("m1") == 7
        # unknown client refuses
        from ceph_tpu.client.objecter import ObjectOperationError
        with pytest.raises(ObjectOperationError):
            await io.exec("journal.img", "journal", "client_commit",
                          json.dumps({"id": "ghost", "seq": 1}).encode())
        # CAS rotation: stale expect -> ESTALE
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("journal.img", "journal", "advance_active",
                          json.dumps({"expect": 5, "to": 6}).encode())
        assert ei.value.retcode == -errno.ESTALE
        await cl.stop()
    asyncio.run(run())
