"""Invariant sanitizer: static checker (devtools lint) + runtime
lockdep/loop-stall sanitizer (common/lockdep.py).

Three layers of coverage:

  1. The live package must lint CLEAN — any write-path invariant
     regression (an await sneaking into a submit section, a wall clock
     in an op path, a slot release escaping its finally) is a tier-1
     test failure right here, not a review comment.
  2. Fixture snippets per rule: each must trip EXACTLY its rule, so a
     rule that rots into a no-op (or starts over-matching) fails too.
  3. Runtime injection: a real ``_mu -> _io`` lock-order inversion, a
     cross-loop asyncio-lock misuse and an over-budget synchronous
     loop section must each land in the lockdep report with the
     offending acquisition stacks / owning stage attached.
"""

import asyncio
import json
import subprocess
import sys
import threading
import time

import pytest

from ceph_tpu.common import lockdep
from ceph_tpu.devtools.lint import (lint_paths, lint_project_sources,
                                    lint_source)

# ===================================================== 1. live tree clean


def test_live_package_lints_clean():
    violations, errors = lint_paths()
    assert not errors, errors
    assert not violations, \
        "invariant lint violations on the live tree:\n" + \
        "\n".join(v.render() for v in violations)


def test_cli_entry_point_runs_standalone():
    # the console entry the CI/tooling satellite promises: standalone
    # module invocation, exit 0 on the clean tree
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.devtools.lint",
         "--list-rules"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    for rid in ("AF01", "FP02", "SEND03", "BLK04", "MONO05",
                "LOCK06", "FIN07", "PROTO08", "REPLY09", "EPOCH10",
                "SHARD11", "ESC12", "PORT13", "ATOM14", "SYNC15",
                "JIT16", "XFER17", "STAGE18", "RETRY19", "QOS20"):
        assert rid in out.stdout


def test_cli_json_smoke_schema_roundtrips():
    """The CI satellite: `python -m ceph_tpu.devtools.lint --json` on
    the live tree exits 0 with a schema-versioned document whose
    per-rule summary is complete and which round-trips through json."""
    from ceph_tpu.devtools.lint import JSON_SCHEMA
    from ceph_tpu.devtools.rules import RULE_IDS
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.devtools.lint", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["schema"] == JSON_SCHEMA
    assert doc["clean"] is True and doc["exit"] == 0
    assert doc["violations"] == [] and doc["errors"] == []
    assert doc["files"] > 100
    assert set(doc["rules"]) == set(RULE_IDS)
    for rid, summary in doc["rules"].items():
        assert summary["violations"] == 0, (rid, summary)
        assert summary["waived"] >= 0
        assert summary["description"]
    # the documented waivers exist (MONO05 persisted stamps etc)
    assert doc["rules"]["MONO05"]["waived"] >= 1
    # schema v2: per-rule analysis wall time rides the summary
    for rid, summary in doc["rules"].items():
        assert "ms" in summary and summary["ms"] >= 0.0, rid
    # schema v2: the unused-waiver audit ran and every in-source
    # waiver (the four documented MONO05/EPOCH10 ones included) still
    # suppresses something — a stale allow is at least a warning
    assert doc["unused_waivers"] == [], doc["unused_waivers"]
    assert doc["strict_waivers"] is False
    # schema v2: the full-package run carries the seam inventory
    assert doc["seam"]["seam_schema"] >= 1
    assert doc["seam"]["summary"]["unprotected_structures"] == 0
    # schema v3: ... and the device inventory, clean on the live tree
    assert doc["device"]["device_schema"] >= 1
    assert doc["device"]["summary"]["unclassified_kernel_sites"] == 0
    assert doc["device"]["summary"]["unsanctioned_syncs"] == 0
    assert doc["device"]["summary"]["per_call_jit"] == 0
    assert "device_analysis_ms" in doc
    # byte-true JSON round trip (CI stores and diffs these)
    assert json.loads(json.dumps(doc)) == doc


def test_cli_exit_code_is_stable_on_violations():
    """Exit contract: 1 = violations (not a crash), stderr carries the
    per-rule summary; the JSON document mirrors the code in 'exit'."""
    import os
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        # explicit file target keeps this hermetic; its rel path won't
        # start with osd/, so use a rule that is not module-scoped
        path = os.path.join(td, "fixture.py")
        with open(path, "w") as f:
            f.write("async def run(self, m, slot):\n"
                    "    await self.do_op(m)\n"
                    "    self.op_window.release(slot)\n")
        out = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.devtools.lint", "--json",
             path],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 1, out.stdout + out.stderr
        doc = json.loads(out.stdout)
        assert doc["exit"] == 1 and doc["clean"] is False
        assert doc["rules"]["FIN07"]["violations"] == 1


# ================================================ 2. one fixture per rule


def _rules_of(src: str, rel: str):
    return sorted({v.rule for v in lint_source(src, rel)})


def test_af01_await_inside_submit_section():
    src = (
        "async def submit(pg):\n"
        "    # awaitfree:begin fixture-submit\n"
        "    version = pg.next_version()\n"
        "    await pg.flush()\n"
        "    # awaitfree:end fixture-submit\n"
        "    return version\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["AF01"]


def test_af01_async_with_and_unbalanced_sentinel():
    src = (
        "async def submit(pg, lock):\n"
        "    # awaitfree:begin fixture\n"
        "    async with lock:\n"
        "        pg.append_log()\n"
        "    # awaitfree:end fixture\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["AF01"]
    src2 = (
        "async def submit(pg):\n"
        "    # awaitfree:begin never-closed\n"
        "    pg.append_log()\n"
    )
    assert _rules_of(src2, "osd/fixture.py") == ["AF01"]


def test_af01_clean_region_passes():
    src = (
        "async def submit(pg):\n"
        "    chunks = await pg.encode()\n"
        "    # awaitfree:begin fixture\n"
        "    version = pg.next_version()\n"
        "    pg.append_log(version, chunks)\n"
        "    # awaitfree:end fixture\n"
        "    await pg.gather_acks()\n"
    )
    assert _rules_of(src, "osd/fixture.py") == []


def test_fp02_mutating_a_local_view():
    src = (
        "def deliver(msg):\n"
        "    view = msg.local_view()\n"
        "    view.ops = []\n"
    )
    assert _rules_of(src, "msg/fixture.py") == ["FP02"]


def test_fp02_mutator_call_on_peeked_payload():
    src = (
        "def apply(m, pg):\n"
        "    entry = m.log_entry()\n"
        "    entry.xattrs.update({'a': 1})\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["FP02"]


def test_fp02_mutation_through_subscript_chain():
    # mutating an op INSIDE the frozen view's list — the most
    # realistic receiver-side slip (result fields belong on the
    # receiver's own result_copy op shells, not the sender's)
    src = (
        "def fill(msg):\n"
        "    view = msg.local_view()\n"
        "    view.ops[0].rval = 0\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["FP02"]
    src2 = (
        "def fill(msg, data):\n"
        "    view = msg.local_view()\n"
        "    view.ops[0].outdata.append(data)\n"
    )
    assert _rules_of(src2, "osd/fixture.py") == ["FP02"]


def test_fp02_envelope_stamp_and_mutable_copy_pass():
    src = (
        "def deliver(msg, seq):\n"
        "    view = msg.local_view()\n"
        "    view.seq = seq\n"            # receiver-owned envelope
        "    txn = view.payload.mutable(Transaction)\n"
        "    txn.ops = []\n"              # sanctioned mutable copy
    )
    assert _rules_of(src, "msg/fixture.py") == []


def test_send03_mutation_after_first_send():
    src = (
        "def fan_out(osd, peer, rep):\n"
        "    osd.send_osd(peer, rep)\n"
        "    rep.version = 3\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["SEND03"]


def test_send03_reply_to_request_stays_mutable():
    # reply_to(request, reply) SENDS the reply; stamping tracker state
    # onto the request afterwards is the normal intake path
    src = (
        "def intake(osd, m, tracker):\n"
        "    osd.reply_to(m, make_reply(m))\n"
        "    m.oid = normalize(m.oid)\n"
    )
    assert _rules_of(src, "osd/fixture.py") == []


def test_blk04_blocking_call_in_async_def():
    src = (
        "import time as _time\n"
        "async def tick(self):\n"
        "    _time.sleep(0.1)\n"          # alias must not hide it
    )
    assert _rules_of(src, "osd/fixture.py") == ["BLK04"]
    src2 = (
        "async def load(path):\n"
        "    with open(path) as f:\n"
        "        return f.read()\n"
    )
    assert _rules_of(src2, "mon/fixture.py") == ["BLK04"]


def test_blk04_commit_thread_module_exempt():
    src = (
        "import time\n"
        "async def gather(self):\n"
        "    time.sleep(0.001)\n"
    )
    assert _rules_of(src, "store/commit.py") == []


def test_mono05_wall_clock_in_op_path():
    src = (
        "import time\n"
        "def age(op):\n"
        "    return time.time() - op.start\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["MONO05"]
    # same code outside the op-path module set is fine (mon leases,
    # rgw mtimes and friends are wall-clock protocol data)
    assert _rules_of(src, "mon/fixture.py") == []


def test_mono05_waiver_comment_is_honored():
    src = (
        "import time\n"
        "def stamp(info):\n"
        "    # lint: allow[MONO05] persisted cross-restart stamp\n"
        "    info.last_scrub_stamp = time.time()\n"
    )
    assert _rules_of(src, "osd/fixture.py") == []


def test_lock06_io_acquired_under_mu():
    src = (
        "def bad(self, txn):\n"
        "    with self._mu:\n"
        "        with self._io:\n"
        "            self.apply(txn)\n"
    )
    assert _rules_of(src, "store/fixture.py") == ["LOCK06"]
    good = (
        "def good(self, txn):\n"
        "    with self._io:\n"
        "        with self._mu:\n"
        "            self.apply(txn)\n"
    )
    assert _rules_of(good, "store/fixture.py") == []


def test_fin07_slot_release_outside_finally():
    src = (
        "async def run(self, m, slot):\n"
        "    await self.do_op(m)\n"
        "    self.op_window.release(slot)\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["FIN07"]
    good = (
        "async def run(self, m, slot):\n"
        "    try:\n"
        "        await self.do_op(m)\n"
        "    finally:\n"
        "        self.op_window.release(slot)\n"
    )
    assert _rules_of(good, "osd/fixture.py") == []


def test_reply09_early_return_without_discharge():
    src = (
        "def handle(self, m):\n"
        "    if m.stale:\n"
        "        return\n"                     # consumed, never answered
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["REPLY09"]
    # same code outside osd/ is out of scope (mon handlers use their
    # own reply helper and are not dispatch-throttled consumers)
    assert _rules_of(src, "mon/fixture.py") == []


def test_reply09_branch_discharge_does_not_leak_to_fallthrough():
    """A reply inside ONE branch must not discharge the fall-through
    path: the not-cached+stopping path below consumes the op and never
    answers — exactly the client-timeout bug the rule exists for."""
    src = (
        "def handle(self, m):\n"
        "    if m.cached:\n"
        "        self.osd.reply_to(m, cached(m))\n"
        "    if self.stopping:\n"
        "        return\n"
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["REPLY09"]
    # discharged in BOTH arms => the fall-through really is discharged
    both = (
        "def handle(self, m, pg):\n"
        "    if m.cached:\n"
        "        self.osd.reply_to(m, cached(m))\n"
        "    else:\n"
        "        pg.queue_op(m)\n"
        "    if self.stopping:\n"
        "        return\n"
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(both, "osd/fixture.py") == []
    # an arm that RETURNS does not fall through: the state after the
    # if comes from the discharging straight-line path alone
    returns = (
        "def handle(self, m, pg):\n"
        "    if m.bad:\n"
        "        self.osd.reply_to(m, err(m))\n"
        "        return\n"
        "    pg.queue_op(m)\n"
        "    return\n"
    )
    assert _rules_of(returns, "osd/fixture.py") == []


def test_reply09_reply_requeue_handoff_and_waiver_pass():
    replied = (
        "def handle(self, m):\n"
        "    if m.stale:\n"
        "        self.osd.reply_to(m, eagain(m))\n"
        "        return\n"
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(replied, "osd/fixture.py") == []
    requeued = (
        "def handle(self, m, pg):\n"
        "    if not pg.ready:\n"
        "        pg.queue_op(m)\n"
        "        return\n"
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(requeued, "osd/fixture.py") == []
    handoff = (
        "def handle(self, m, loop):\n"
        "    if m.slow:\n"
        "        loop.create_task(self.slow_path(m))\n"
        "        return\n"
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(handoff, "osd/fixture.py") == []
    waived = (
        "def handle(self, m):\n"
        "    if m.stale:\n"
        "        # lint: allow[REPLY09] stale dup: sender already acked\n"
        "        return\n"
        "    self.osd.reply_to(m, make_reply(m))\n"
    )
    assert _rules_of(waived, "osd/fixture.py") == []


def test_epoch10_unguarded_pg_mutation():
    src = (
        "def on_pg_log(self, m):\n"
        "    self.log = m.adopt()\n"
        "    self.save_meta(txn)\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["EPOCH10"]
    # out of osd/ scope
    assert _rules_of(src, "mon/fixture.py") == []


def test_epoch10_guard_before_mutation_passes():
    good = (
        "def on_pg_log(self, m):\n"
        "    if m.epoch < self.info.same_interval_since:\n"
        "        return\n"
        "    self.log = m.adopt()\n"
        "    self.save_meta(txn)\n"
    )
    assert _rules_of(good, "osd/fixture.py") == []
    waived = (
        "# lint: allow[EPOCH10] staleness arbitrated per object\n"
        "def on_push(self, m):\n"
        "    self.backend.apply_push(m)\n"
    )
    assert _rules_of(waived, "osd/fixture.py") == []


def test_shard11_pg_mutation_from_intake_path():
    """ISSUE 10: PG-state mutation from an intake/heartbeat-path
    function must go through the shard handoff seam."""
    src = (
        "def ms_dispatch(self, m):\n"
        "    pg = self._pg_for(m.pgid)\n"
        "    pg.queue_op(m)\n"
    )
    assert _rules_of(src, "osd/fixture.py") == ["SHARD11"]
    # PG-field assignment from a heartbeat-path function trips too
    src2 = (
        "def _scrub_scheduler(self, m):\n"
        "    pg = self._load_stray_pg(m.pgid)\n"
        "    pg.info.last_scrub_stamp = 0\n"
    )
    assert _rules_of(src2, "osd/fixture.py") == ["SHARD11"]
    # out of intake-module scope (a PG method itself is fine)
    assert _rules_of(src, "common/fixture.py") == []


def test_shard11_seam_routing_and_waiver_pass():
    good = (
        "def ms_dispatch(self, m):\n"
        "    pg = self._pg_for(m.pgid)\n"
        "    self.shards.route(m.pgid, pg.queue_op, m)\n"
    )
    assert _rules_of(good, "osd/fixture.py") == []
    # reads stay legal from intake (status/describe/is_primary)
    good2 = (
        "def _report_stats(self):\n"
        "    pg = self._pg_for(self.pgid)\n"
        "    if pg.is_primary():\n"
        "        x = pg.describe()\n"
    )
    assert _rules_of(good2, "osd/fixture.py") == []
    waived = (
        "def ms_dispatch(self, m):\n"
        "    pg = self._pg_for(m.pgid)\n"
        "    # lint: allow[SHARD11] single-loop teardown sweep\n"
        "    pg.stop()\n"
    )
    assert _rules_of(waived, "osd/fixture.py") == []


def test_proto08_unhandled_wire_type_trips_and_handled_passes():
    messages = (
        "from ceph_tpu.msg.message import Message, register_message\n"
        "@register_message\n"
        "class MFixtureProbe(Message):\n"
        "    TYPE = 9999\n"
    )
    sender = (
        "class OSD:\n"
        "    def kick(self, mon_addr):\n"
        "        self.messenger.send_message(MFixtureProbe(), mon_addr,\n"
        "                                    peer_type=\"mon\")\n"
    )
    mon_missing = (
        "class Monitor:\n"
        "    def ms_dispatch(self, m):\n"
        "        if isinstance(m, MPing):\n"
        "            return True\n"
        "        return False\n"
    )
    vio = lint_project_sources([
        ("osd/fixture_messages.py", messages),
        ("osd/fixture_daemon.py", sender),
        ("mon/monitor.py", mon_missing),
    ])
    assert [v.rule for v in vio] == ["PROTO08"], vio
    assert "MFixtureProbe" in vio[0].msg and "'mon'" in vio[0].msg
    mon_handles = mon_missing.replace("MPing", "MFixtureProbe")
    assert lint_project_sources([
        ("osd/fixture_messages.py", messages),
        ("osd/fixture_daemon.py", sender),
        ("mon/monitor.py", mon_handles),
    ]) == []
    # an edge into a role with NO module in the linted set is skipped
    # (single-file lint must not fabricate missing-handler noise)
    assert lint_project_sources([
        ("osd/fixture_messages.py", messages),
        ("osd/fixture_daemon.py", sender),
    ]) == []


def test_proto08_send_osd_and_local_variable_resolution():
    messages = (
        "from ceph_tpu.msg.message import Message, register_message\n"
        "@register_message\n"
        "class MFixtureSub(Message):\n"
        "    TYPE = 9998\n"
    )
    sender = (
        "class PG:\n"
        "    def fan_out(self, peer):\n"
        "        rep = MFixtureSub()\n"
        "        self.osd.send_osd(peer, rep)\n"
    )
    osd_missing = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        return False\n"
    )
    vio = lint_project_sources([
        ("osd/fixture_messages.py", messages),
        ("osd/fixture_pg.py", sender),
        ("osd/daemon.py", osd_missing),
    ])
    assert [v.rule for v in vio] == ["PROTO08"], vio


def test_proto08_container_frame_contributes_inner_edges():
    """The MOSDOpBatch satellite: a THROTTLE_SPLIT envelope's send
    contributes its INNER (type, role) edges — a receiver that handles
    only the envelope but not the unpacked inner type is still a
    silent drop."""
    messages = (
        "from ceph_tpu.msg.message import Message, register_message\n"
        "@register_message\n"
        "class MFixInner(Message):\n"
        "    TYPE = 9996\n"
        "@register_message\n"
        "class MFixBatch(Message):\n"
        "    TYPE = 9997\n"
        "    THROTTLE_SPLIT = True\n"
        "    @classmethod\n"
        "    def decode_payload(cls, dec, struct_v):\n"
        "        return cls([MFixInner.from_bytes(b) "
        "for b in dec.list_(lambda d: d.bytes_())])\n"
    )
    sender = (
        "class PG:\n"
        "    def fan_out(self, peer):\n"
        "        self.osd.send_osd(peer, MFixBatch())\n"
    )
    envelope_only = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        if isinstance(m, MFixBatch):\n"
        "            return True\n"
        "        return False\n"
    )
    vio = lint_project_sources([
        ("osd/fixture_messages.py", messages),
        ("osd/fixture_pg.py", sender),
        ("osd/daemon.py", envelope_only),
    ])
    assert [v.rule for v in vio] == ["PROTO08"], vio
    assert "MFixInner" in vio[0].msg
    assert "container frame MFixBatch" in vio[0].msg
    both = envelope_only.replace("isinstance(m, MFixBatch)",
                                 "isinstance(m, (MFixBatch, MFixInner))")
    assert lint_project_sources([
        ("osd/fixture_messages.py", messages),
        ("osd/fixture_pg.py", sender),
        ("osd/daemon.py", both),
    ]) == []


# ===================================== 2b. seam rules (ESC12/PORT13/ATOM14)


def test_esc12_cross_side_mutation_without_declaration():
    """ISSUE 12 tentpole: a structure written from a shard-lane
    function while the intake side reads it — with no lock, region or
    waiver — escapes the seam."""
    src = (
        "class OSD:\n"
        "    def __init__(self):\n"
        "        self.pgs = {}\n"
        "    def ms_dispatch(self, m):\n"          # intake side reads
        "        return self.pgs.get(m.pgid)\n"
        "    def _run_pg(self, m):\n"              # shard side writes
        "        self.pgs.pop(m.pgid, None)\n"
        "    def kick(self, m):\n"
        "        self.shards.route(m.pgid, self._run_pg, m)\n"
    )
    vio = lint_project_sources([("osd/daemon.py", src)])
    assert [v.rule for v in vio] == ["ESC12"], vio
    assert "pgs" in vio[0].msg


def test_esc12_gil_atomic_region_and_lock_pass():
    declared = (
        "class OSD:\n"
        "    def __init__(self):\n"
        "        self.pgs = {}\n"
        "    def ms_dispatch(self, m):\n"
        "        return self.pgs.get(m.pgid)\n"
        "    def _run_pg(self, m):\n"
        "        # gil-atomic:begin pgs single GIL-step pop\n"
        "        self.pgs.pop(m.pgid, None)\n"
        "        # gil-atomic:end\n"
        "    def kick(self, m):\n"
        "        self.shards.route(m.pgid, self._run_pg, m)\n"
    )
    assert lint_project_sources([("osd/daemon.py", declared)]) == []
    locked = declared.replace(
        "        # gil-atomic:begin pgs single GIL-step pop\n"
        "        self.pgs.pop(m.pgid, None)\n"
        "        # gil-atomic:end\n",
        "        with self._pg_lock:\n"
        "            self.pgs.pop(m.pgid, None)\n")
    assert lint_project_sources([("osd/daemon.py", locked)]) == []


def test_esc12_rmw_scalar_counter():
    """An augassign is never atomic whatever the type: a counter
    bumped from a seam-crossing function is flagged too (the live-tree
    catch: OSD.next_tid could mint duplicate tids across shards)."""
    src = (
        "class OSD:\n"
        "    def _mint(self, m):\n"
        "        self._tid += 1\n"
        "    def ms_dispatch(self, m):\n"
        "        self.shards.route(m.pgid, self._mint, m)\n"
    )
    vio = lint_project_sources([("osd/daemon.py", src)])
    assert [v.rule for v in vio] == ["ESC12"], vio
    assert "_tid" in vio[0].msg


def test_port13_live_object_reference_crossing_the_seam():
    """The live-tree catch: a PG object passed as DATA through
    shards.route cannot exist in the sending process once lanes
    split — pass the routing key and re-resolve."""
    src = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        pg = self._pg_for(m.pgid)\n"
        "        self.shards.route(m.pgid, self._run_pg, pg)\n"
        "    def _run_pg(self, pg):\n"
        "        pass\n"
    )
    vio = lint_project_sources([("osd/daemon.py", src)])
    assert [v.rule for v in vio] == ["PORT13"], vio
    assert "live shared-object reference" in vio[0].msg


def test_port13_closure_and_clean_handoff():
    closure = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        self.shards.route(m.pgid, lambda: self.apply(m))\n"
    )
    vio = lint_project_sources([("osd/daemon.py", closure)])
    assert [v.rule for v in vio] == ["PORT13"], vio
    assert "lambda/closure" in vio[0].msg
    # the sanctioned shapes: bound method + wire message + routing key
    clean = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        self.shards.route(m.pgid, self._run_pg, m)\n"
        "    def _run_pg(self, m):\n"
        "        pass\n"
    )
    assert lint_project_sources([("osd/daemon.py", clean)]) == []
    waived = closure.replace(
        "        self.shards.route",
        "        # lint: allow[PORT13] fixture waiver\n"
        "        self.shards.route")
    assert lint_project_sources([("osd/daemon.py", waived)]) == []


def test_port13_keyword_arguments_cannot_evade():
    """A kwarg-passed live ref or closure crosses the seam exactly
    like a positional one and must classify the same way."""
    live_kw = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        pg = self._pg_for(m.pgid)\n"
        "        self.shards.route(m.pgid, self._run_pg, pg=pg)\n"
        "    def _run_pg(self, pg=None):\n"
        "        pass\n"
    )
    vio = lint_project_sources([("osd/daemon.py", live_kw)])
    assert [v.rule for v in vio] == ["PORT13"], vio
    closure_kw = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        self.shards.route(m.pgid, fn=lambda: self.apply(m))\n"
    )
    vio = lint_project_sources([("osd/daemon.py", closure_kw)])
    assert [v.rule for v in vio] == ["PORT13"], vio
    assert "lambda/closure" in vio[0].msg


def test_port13_raw_bytes_over_threshold_escape():
    """ISSUE 20: bulk payload bytes crossing the seam INLINE are the
    escape the shared-memory extent pool exists to close — one ring
    copy in, one out, per hop.  A conventional payload name handed
    through shards.route is flagged with the extent-pool remedy; the
    sanctioned shape (publish once, pass the (pool, gen, off, len)
    handle) is clean."""
    raw = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        payload = m.data\n"
        "        self.shards.route(m.pgid, self._apply, payload)\n"
        "    def _apply(self, payload):\n"
        "        pass\n"
    )
    vio = lint_project_sources([("osd/daemon.py", raw)])
    assert [v.rule for v in vio] == ["PORT13"], vio
    assert "extent pool" in vio[0].msg and "handle" in vio[0].msg
    # the zero-copy shape: the handle is a named segment + scalars
    clean = (
        "class OSD:\n"
        "    def ms_dispatch(self, m):\n"
        "        handle = self.ext_pool.put(m.data)\n"
        "        self.shards.route(m.pgid, self._apply, handle)\n"
        "    def _apply(self, handle):\n"
        "        pass\n"
    )
    assert lint_project_sources([("osd/daemon.py", clean)]) == []


def test_atom14_write_outside_declared_region():
    """Once a structure is declared gil-atomic, EVERY write in the
    module must sit inside a region — the region set stays exhaustive,
    so the seam inventory it compiles into can be trusted."""
    src = (
        "class Shard:\n"
        "    def __init__(self):\n"          # construction is exempt
        "        self.ring = []\n"
        "    def post(self, item):\n"
        "        # gil-atomic:begin ring single-producer append\n"
        "        self.ring.append(item)\n"
        "        # gil-atomic:end\n"
        "    def sneak(self, item):\n"
        "        self.ring.append(item)\n"   # outside any region
    )
    vio = lint_project_sources([("osd/shards.py", src)])
    assert [v.rule for v in vio] == ["ATOM14"], vio
    assert "'ring'" in vio[0].msg


def test_atom14_region_hygiene():
    unbalanced = (
        "class Shard:\n"
        "    def post(self, item):\n"
        "        # gil-atomic:begin ring never closed\n"
        "        self.ring.append(item)\n"
    )
    vio = lint_project_sources([("osd/shards.py", unbalanced)])
    assert [v.rule for v in vio] == ["ATOM14"], vio
    missing_reason = (
        "class Shard:\n"
        "    def post(self, item):\n"
        "        # gil-atomic:begin ring\n"
        "        self.ring.append(item)\n"
        "        # gil-atomic:end\n"
    )
    vio = lint_project_sources([("osd/shards.py", missing_reason)])
    assert [v.rule for v in vio] == ["ATOM14"], vio
    assert "reason" in vio[0].msg


def test_seam_report_fixture_inventory():
    """The seam inventory classifies every crossing value and every
    declared region with source locations (fixture-scale check; the
    live-tree inventory is covered by the subprocess smoke)."""
    from ceph_tpu.devtools.rules import FileInfo
    from ceph_tpu.devtools.seam import SeamAnalysis
    src = (
        "class OSD:\n"
        "    def __init__(self):\n"
        "        self.pgs = {}\n"
        "    def ms_dispatch(self, m):\n"
        "        self.shards.route(m.pgid, self._run_pg, m)\n"
        "    def _run_pg(self, m):\n"
        "        # gil-atomic:begin pgs one-GIL-step insert\n"
        "        self.pgs[m.pgid] = m\n"
        "        # gil-atomic:end\n"
    )
    an = SeamAnalysis([FileInfo("osd/daemon.py", src)])
    assert an.violations == []
    rep = an.report()
    assert rep["seam_schema"] >= 1
    assert rep["summary"]["sites"] == 1
    site = rep["sites"][0]
    assert site["kind"] == "shard-route" and site["line"] == 5
    classes = {v["class"] for v in site["values"]}
    assert classes == {"primitive", "home-bound", "wire"}
    assert rep["gil_atomic_regions"][0]["attrs"] == ["pgs"]
    (entry,) = rep["shared_state"]
    assert entry["attr"] == "pgs"
    assert entry["classification"] == "gil-atomic"
    assert json.loads(json.dumps(rep)) == rep


# ============================ 2c. device rules (SYNC15/JIT16/XFER17)


def test_sync15_device_sync_in_async_op_path():
    """ISSUE 14 tentpole: an implicit device->host sync inside an
    async op-path function stalls the shard loop — violation."""
    src = (
        "class ECBackend:\n"
        "    async def _encode_object(self, data):\n"
        "        y = self.kernel.device_call(data)\n"
        "        return float(y)\n"
    )
    vio = lint_project_sources([("osd/fixture.py", src)])
    assert [v.rule for v in vio] == ["SYNC15"], vio
    assert "device->host sync" in vio[0].msg
    # the sanctioned shape: await the executor, fetch nothing inline
    clean = (
        "class ECBackend:\n"
        "    async def _encode_object(self, data):\n"
        "        parity = await self.ec_queue.apply(self.gen, data)\n"
        "        return parity\n"
    )
    assert lint_project_sources([("osd/fixture.py", clean)]) == []


def test_sync15_declared_region_in_sync_fn_passes():
    """A declared device-sync region sanctions the fetch — but only in
    a SYNC function (the executor shape); the same region inside an
    async def is itself a violation."""
    import textwrap
    region = textwrap.dedent("""\
        def _run_group(self, chunks):
            out = self.kernel.device_call(chunks)
            # device-sync:begin executor-thread group fetch
            res = np.asarray(out)
            # device-sync:end
            return res
        """)
    assert lint_project_sources([("ec/kernel.py", region)]) == []
    bare = region.replace(
        "    # device-sync:begin executor-thread group fetch\n", "") \
        .replace("    # device-sync:end\n", "")
    vio = lint_project_sources([("ec/kernel.py", bare)])
    assert [v.rule for v in vio] == ["SYNC15"], vio
    async_region = "async " + region
    vio = lint_project_sources([("ec/kernel.py", async_region)])
    assert vio and all(v.rule == "SYNC15" for v in vio), vio
    assert any("async" in v.msg for v in vio)
    waived = bare.replace(
        "    res = np.asarray(out)\n",
        "    # lint: allow[SYNC15] fixture: measured fetch\n"
        "    res = np.asarray(out)\n")
    assert lint_project_sources([("ec/kernel.py", waived)]) == []


def test_sync15_region_hygiene():
    no_reason = (
        "def fetch(self, out):\n"
        "    # device-sync:begin\n"
        "    return np.asarray(out)\n"
        "    # device-sync:end\n"
    )
    vio = lint_project_sources([("ec/kernel.py", no_reason)])
    assert [v.rule for v in vio] == ["SYNC15"], vio
    assert "reason" in vio[0].msg
    unclosed = (
        "def fetch(self, out):\n"
        "    # device-sync:begin fixture fetch\n"
        "    return out\n"
    )
    vio = lint_project_sources([("ec/kernel.py", unclosed)])
    assert [v.rule for v in vio] == ["SYNC15"], vio


def test_jit16_per_call_jit_lambda():
    """The live-tree catch: the ec/kernel.py autotuner built a
    jax.jit(lambda ...) per variant per sweep — a fresh compile cache
    every call."""
    src = (
        "def _tune(self, d):\n"
        "    import jax\n"
        "    fetch = jax.jit(lambda x: x + 1)\n"
        "    return fetch(d)\n"
    )
    vio = lint_project_sources([("ec/fixture.py", src)])
    assert vio and {v.rule for v in vio} == {"JIT16"}, vio
    assert any("lambda" in v.msg for v in vio)


def test_jit16_builder_return_and_guarded_cache_pass():
    builder = (
        "def make_step(step):\n"
        "    import jax\n"
        "    return jax.jit(step)\n"
    )
    assert lint_project_sources([("ops/fixture.py", builder)]) == []
    guarded = (
        "_fn_cache = {}\n"
        "def get_step(self, key, step):\n"
        "    import jax\n"
        "    if key not in _fn_cache:\n"
        "        _fn_cache[key] = jax.jit(step)\n"
        "    return _fn_cache[key]\n"
    )
    assert lint_project_sources([("ops/fixture.py", guarded)]) == []
    # the guarded-GLOBAL shape (crush_kernel._get_winners_fn):
    # construct once behind `x is None`, invoke the cached object
    global_cache = (
        "_fn = None\n"
        "def step_fn(self, step, x):\n"
        "    import jax\n"
        "    global _fn\n"
        "    if _fn is None:\n"
        "        _fn = jax.jit(step)\n"
        "    return _fn(x)\n"
    )
    assert lint_project_sources([("ops/fixture.py", global_cache)]) == []
    # construct-and-invoke with NO cache guard: every call retraces
    unguarded = (
        "def run_step(self, step, x):\n"
        "    import jax\n"
        "    fn = jax.jit(step)\n"
        "    return fn(x)\n"
    )
    vio = lint_project_sources([("ops/fixture.py", unguarded)])
    assert vio and {v.rule for v in vio} == {"JIT16"}, vio
    # an UNRELATED is/in comparison in the body must not silence the
    # rule: only a guard on the jit binding itself sanctions it
    decoy_guard = (
        "def run_step(self, step, x, mode=None):\n"
        "    import jax\n"
        "    if mode is None:\n"
        "        mode = 'a'\n"
        "    fn = jax.jit(step)\n"
        "    return fn(x)\n"
    )
    vio = lint_project_sources([("ops/fixture.py", decoy_guard)])
    assert vio and {v.rule for v in vio} == {"JIT16"}, vio


def test_xfer17_opaque_transfer_trips_staged_and_wire_pass():
    opaque = (
        "def _stage(self, blob):\n"
        "    import jax.numpy as jnp\n"
        "    return jnp.asarray(blob)\n"
    )
    vio = lint_project_sources([("osd/fixture.py", opaque)])
    assert [v.rule for v in vio] == ["XFER17"], vio
    assert "stage it" in vio[0].msg
    clean = (
        "def _stage(self, chunks, table):\n"
        "    import jax\n"
        "    import jax.numpy as jnp\n"
        "    a = jnp.asarray(chunks)\n"          # wire-classified buffer
        "    b = jax.device_put(table)\n"        # declared staging
        "    return a, b\n"
    )
    assert lint_project_sources([("osd/fixture.py", clean)]) == []
    waived = opaque.replace(
        "    return jnp.asarray(blob)\n",
        "    # lint: allow[XFER17] fixture: blob layout pinned upstream\n"
        "    return jnp.asarray(blob)\n")
    assert lint_project_sources([("osd/fixture.py", waived)]) == []


def test_device_report_fixture_inventory():
    """The device inventory classifies candidate kernel sites with
    sync/retrace/transfer verdicts (fixture-scale; the live tree is
    covered by the subprocess smoke)."""
    from ceph_tpu.devtools.device import DeviceAnalysis
    from ceph_tpu.devtools.rules import FileInfo
    src = (
        "class Objecter:\n"
        "    def _flush_cork(self):\n"
        "        pend, self._cork = self._cork, []\n"
        "        # device-candidate:crush-placement@landed one batched\n"
        "        # kernel call per cork (CHUNK_SIZES-bucketed)\n"
        "        self.messenger.send_message(pend)\n"
    )
    an = DeviceAnalysis([FileInfo("client/fixture.py", src)])
    assert an.violations == []
    rep = an.report()
    assert rep["device_schema"] >= 1
    (site,) = rep["kernel_sites"]
    assert site["kind"] == "crush-placement"
    assert site["fn"].endswith("_flush_cork")
    assert site["sync"] == "clean"
    assert site["retrace"] == "CHUNK_SIZES"
    assert site["landed"] is True
    assert rep["summary"]["landed_kernel_sites"] == 1
    assert rep["summary"]["unclassified_kernel_sites"] == 0
    assert json.loads(json.dumps(rep)) == rep


# ===================================== 2d. STAGE18 (stage coverage)


def test_stage18_undeclared_stage_name_trips():
    """ISSUE 15 CI satellite: a span cut naming a stage that is not
    declared in CHAIN_STAGES/AUX_STAGES silently falls out of the
    attributed chain sum — violation; declared names pass."""
    src = (
        "def _admit(self, m):\n"
        "    m._span.cut(\"que_wait\", self.tracer.hist)\n"
    )
    vio = lint_project_sources([("osd/fixture.py", src)])
    assert [v.rule for v in vio] == ["STAGE18"], vio
    assert "undeclared stage" in vio[0].msg
    clean = src.replace("que_wait", "queue_wait_pump")
    assert lint_project_sources([("osd/fixture.py", clean)]) == []
    # explicit-duration attribution sites (Span.attribute) are held to
    # the same declaration discipline as cut()
    attr = (
        "def _hop(self, span, dwell):\n"
        "    span.attribute(\"ringe_wait\", dwell)\n"
    )
    vio = lint_project_sources([("osd/fixture.py", attr)])
    assert [v.rule for v in vio] == ["STAGE18"], vio
    ok = attr.replace("ringe_wait", "ring_wait")
    assert lint_project_sources([("osd/fixture.py", ok)]) == []
    # waiver escape hatch
    waived = src.replace(
        "    m._span.cut(",
        "    # lint: allow[STAGE18] fixture: exotic local stage\n"
        "    m._span.cut(")
    assert lint_project_sources([("osd/fixture.py", waived)]) == []


def test_stage18_coverage_half_needs_whole_tree():
    """The every-declared-stage-has-a-cut-site half only runs on a
    whole-op-path file set (all anchors present): a partial lint must
    not report every stage as uncovered.  The live tree IS whole and
    lints clean (test_live_package_lints_clean), which proves every
    CHAIN stage currently has a site."""
    from ceph_tpu.devtools.rules import (_STAGE_COVERAGE_ANCHORS,
                                         check_stage18, FileInfo)
    # partial set: one file with one legal cut, no anchors -> clean
    fi = FileInfo("osd/fixture.py",
                  "def f(s):\n    s.cut(\"prepare\")\n")
    assert list(check_stage18([fi])) == []
    # the anchors the gate keys on must all exist in the live package
    import os
    pkg = os.path.dirname(os.path.dirname(
        os.path.abspath(__import__("ceph_tpu").__file__)))
    for rel in _STAGE_COVERAGE_ANCHORS:
        assert os.path.exists(os.path.join(pkg, "ceph_tpu", rel)), rel


def test_lint_json_carries_stage_coverage_block():
    """lint --json schema 4: whole-package runs expose the per-stage
    cut-site inventory (diffable, like the seam/device blocks)."""
    from ceph_tpu.common.tracer import CHAIN_STAGES
    from ceph_tpu.devtools.lint import JSON_SCHEMA, lint_report
    assert JSON_SCHEMA >= 4
    doc = lint_report()
    assert doc["stages"]["declared_chain"] == list(CHAIN_STAGES)
    sites = doc["stages"]["sites"]
    for name in ("ring_wait", "lane_codec", "queue_wait_ring",
                 "queue_wait_pump"):
        assert sites.get(name, 0) >= 1, (name, sites)
    assert json.loads(json.dumps(doc["stages"])) == doc["stages"]


# ================================ 2d2. RETRY19 (retry-backoff policy)


def test_retry19_fixed_sleep_retry_loop_trips():
    """ISSUE 18: a constant-interval sleep inside a retry/poll while
    loop of an async op-path function hammers a degraded cluster in
    lockstep — violation; the same loop riding the shared Backoff
    passes."""
    src = (
        "import asyncio\n"
        "async def wait_primary(self):\n"
        "    while self.primary < 0:\n"
        "        await asyncio.sleep(0.05)\n"
    )
    vio = lint_source(src, "osd/fixture.py", rule="RETRY19")
    assert [v.rule for v in vio] == ["RETRY19"], vio
    assert "shared jittered backoff" in vio[0].msg
    backed = (
        "import asyncio\n"
        "from ceph_tpu.common.backoff import Backoff\n"
        "async def wait_primary(self):\n"
        "    bo = Backoff(\"primary_wait\", base=0.05)\n"
        "    while self.primary < 0:\n"
        "        await bo.sleep()\n"
    )
    assert lint_source(backed, "osd/fixture.py", rule="RETRY19") == []


def test_retry19_same_loop_backoff_covers_aux_sleep():
    """A loop already riding the policy may carry an extra literal
    sleep (e.g. a post-resend settle) — the Backoff await in the SAME
    loop is the discipline, so it passes."""
    src = (
        "import asyncio\n"
        "from ceph_tpu.common.backoff import Backoff\n"
        "async def resend(self):\n"
        "    bo = Backoff(\"resend\")\n"
        "    while True:\n"
        "        await bo.wait_for(self.fut)\n"
        "        await asyncio.sleep(0.1)\n"
    )
    assert lint_source(src, "osd/fixture.py", rule="RETRY19") == []


def test_retry19_exemptions_yield_config_scope():
    """sleep(0) yield-to-loop, config-driven delays, sync functions and
    files outside osd//client/ are all out of scope."""
    yield_idiom = (
        "import asyncio\n"
        "async def drain(self):\n"
        "    while self.q:\n"
        "        await asyncio.sleep(0)\n"
    )
    assert lint_source(yield_idiom, "osd/fixture.py", rule="RETRY19") == []
    config_driven = (
        "import asyncio\n"
        "async def throttle(self):\n"
        "    d = float(self.cfg[\"osd_recovery_sleep\"])\n"
        "    while self.more():\n"
        "        await asyncio.sleep(d)\n"
    )
    assert lint_source(config_driven, "osd/fixture.py", rule="RETRY19") == []
    fixed = (
        "import asyncio\n"
        "async def wait(self):\n"
        "    while self.primary < 0:\n"
        "        await asyncio.sleep(0.05)\n"
    )
    # common/ (the policy's own home) is not held to the rule
    assert lint_source(fixed, "common/fixture.py", rule="RETRY19") == []


def test_retry19_swallowed_timeout_trips():
    """`except TimeoutError: pass` (either flavour — 3.10 still splits
    asyncio.TimeoutError from TimeoutError) silently drops a deadline
    with no counter or give-up tag — violation; a waiver stating why
    the silence is safe passes."""
    src = (
        "import asyncio\n"
        "async def notify(self, fut):\n"
        "    try:\n"
        "        await asyncio.wait_for(fut, 5.0)\n"
        "    except asyncio.TimeoutError:\n"
        "        pass\n"
    )
    vio = lint_source(src, "osd/fixture.py", rule="RETRY19")
    assert [v.rule for v in vio] == ["RETRY19"], vio
    assert "swallows" in vio[0].msg
    bare = src.replace("asyncio.TimeoutError", "TimeoutError")
    vio = lint_source(bare, "client/fixture.py", rule="RETRY19")
    assert [v.rule for v in vio] == ["RETRY19"], vio
    waived = src.replace(
        "    except asyncio.TimeoutError:",
        "    # lint: allow[RETRY19] fixture: timeout is the protocol\n"
        "    except asyncio.TimeoutError:")
    assert lint_source(waived, "osd/fixture.py", rule="RETRY19") == []
    # a handler that DOES something with the timeout is fine
    handled = src.replace("        pass\n",
                          "        self.perf.inc(\"notify_timeout\")\n")
    assert lint_source(handled, "osd/fixture.py", rule="RETRY19") == []


def test_retry19_waiver_on_sleep_line():
    """Waiver escape hatch for legitimate fixed cadences (pump belts,
    heartbeat-scale polls) — on the sleep line or the line above."""
    src = (
        "import asyncio\n"
        "async def pump(self):\n"
        "    while not self._stopping:\n"
        "        # lint: allow[RETRY19] fixture: pump belt cadence\n"
        "        await asyncio.sleep(0.2)\n"
    )
    assert lint_source(src, "osd/fixture.py", rule="RETRY19") == []


def test_qos20_untagged_op_queue_put_trips():
    """ISSUE 19: an op enqueued to a PG op queue without an explicit
    class rides the 'client' default — under dmClock that bills
    foreign work against the client reservation; violation.  The
    tagged put (positional or klass=) passes."""
    src = (
        "def requeue(self, m):\n"
        "    self._op_queue.put_nowait(m)\n"
    )
    vio = lint_source(src, "osd/fixture.py", rule="QOS20")
    assert [v.rule for v in vio] == ["QOS20"], vio
    assert "QoS class" in vio[0].msg
    tagged = (
        "def requeue(self, m):\n"
        "    self._op_queue.put_nowait(m, \"background\")\n"
    )
    assert lint_source(tagged, "osd/fixture.py", rule="QOS20") == []
    kw = (
        "def requeue(self, m):\n"
        "    self.pg._op_queue.put_nowait(m, klass=\"scrub\")\n"
    )
    assert lint_source(kw, "osd/fixture.py", rule="QOS20") == []


def test_qos20_scope_and_waiver():
    """Only op-queue receivers in osd/ are in scope: plain asyncio
    queues and non-osd modules pass untagged; a documented
    default-class put passes with the waiver."""
    plain_queue = (
        "def hand_off(self, m):\n"
        "    self._ring.put_nowait(m)\n"
    )
    assert lint_source(plain_queue, "osd/fixture.py", rule="QOS20") == []
    outside = (
        "def requeue(self, m):\n"
        "    self._op_queue.put_nowait(m)\n"
    )
    assert lint_source(outside, "client/fixture.py", rule="QOS20") == []
    waived = (
        "def requeue(self, m):\n"
        "    # lint: allow[QOS20] fixture: deliberate default class\n"
        "    self._op_queue.put_nowait(m)\n"
    )
    assert lint_source(waived, "osd/fixture.py", rule="QOS20") == []


# ================================ 2e. waiver audit + lint performance


def test_unused_waiver_detection_and_strict_promotion():
    import os
    import tempfile
    from ceph_tpu.devtools.lint import lint_report
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "fixture.py")
        with open(path, "w") as f:
            f.write("def f():\n"
                    "    # lint: allow[MONO05] stale: nothing here\n"
                    "    return 1\n")
        doc = lint_report([path])
        assert doc["exit"] == 0      # a stale waiver alone is a warning
        (uw,) = doc["unused_waivers"]
        assert uw["rel"].endswith("fixture.py")
        assert uw["line"] == 2 and uw["rule"] == "MONO05"
        strict = lint_report([path], strict_waivers=True)
        assert strict["exit"] == 1 and strict["clean"] is False
        (vio,) = strict["violations"]
        assert vio["rule"] == "WAIVER" and "MONO05" in vio["msg"]


def test_waiver_usage_is_per_run_despite_parse_cache():
    """FileInfo objects persist in the parse cache across lint runs;
    usage recorded by an EARLIER run (or injected) must not mask a
    waiver that suppresses nothing THIS run."""
    import os
    import tempfile
    from ceph_tpu.devtools import lint as lint_mod
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "fixture.py")
        with open(path, "w") as f:
            f.write("def f():\n"
                    "    # lint: allow[MONO05] stale\n"
                    "    return 1\n")
        doc = lint_mod.lint_report([path], strict_waivers=True)
        assert doc["exit"] == 1          # stale, flagged
        # simulate a prior run having consumed the waiver: the cached
        # FileInfo carries stale usage into the next run
        ap = os.path.abspath(path)
        fi = lint_mod._FILE_CACHE[ap][2]
        fi.waiver_used.add(("MONO05", 2))
        doc = lint_mod.lint_report([path], strict_waivers=True)
        assert doc["exit"] == 1, \
            "stale waiver masked by usage leaked from a previous run"


def test_live_waiver_is_counted_used_not_stale():
    import os
    import tempfile
    from ceph_tpu.devtools.lint import lint_report
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "fixture.py")
        with open(path, "w") as f:
            # FIN07 is not module-scoped, so it fires on any rel path
            f.write("async def run(self, m, slot):\n"
                    "    await self.do_op(m)\n"
                    "    # lint: allow[FIN07] fixture: failure handled upstream\n"
                    "    self.op_window.release(slot)\n")
        doc = lint_report([path], strict_waivers=True)
        assert doc["exit"] == 0, doc["violations"]
        assert doc["unused_waivers"] == []
        assert doc["rules"]["FIN07"]["waived"] == 1


def test_cli_strict_waivers_live_tree_clean():
    """The audit satellite's acceptance: every in-source waiver in the
    live package — the documented MONO05/EPOCH10 set included — still
    suppresses a real would-be violation even under --strict-waivers."""
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.devtools.lint",
         "--strict-waivers", "--json"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    doc = json.loads(out.stdout)
    assert doc["strict_waivers"] is True
    assert doc["unused_waivers"] == []
    # the documented wall-clock/epoch waivers are all live (the
    # fourth MONO05: the fastpath forward envelope's wire recv_stamp)
    assert doc["rules"]["MONO05"]["waived"] == 4
    assert doc["rules"]["EPOCH10"]["waived"] == 1


def test_lint_parse_cache_cuts_full_tree_wall_time():
    """The performance satellite: each module parses ONCE into a
    shared FileInfo cache used by all rules; a second full-tree lint
    in the same process re-parses nothing and must be faster."""
    from ceph_tpu.devtools import lint as lint_mod
    lint_mod._FILE_CACHE.clear()
    lint_mod.CACHE_STATS.update(hits=0, misses=0)
    t0 = time.perf_counter()
    lint_paths()
    cold = time.perf_counter() - t0
    misses = lint_mod.CACHE_STATS["misses"]
    assert misses > 100          # the whole package really parsed
    # best-of-two warm runs: the drop is structural (no parse, no
    # seam re-analysis), but a single run can eat a CI scheduler
    # stall — requiring BOTH to stall before flaking
    warms = []
    for _ in range(2):
        t0 = time.perf_counter()
        lint_paths()
        warms.append(time.perf_counter() - t0)
    warm = min(warms)
    assert lint_mod.CACHE_STATS["misses"] == misses, \
        "warm lints re-parsed files the cache should have served"
    assert lint_mod.CACHE_STATS["hits"] >= misses
    assert warm < cold, (warm, cold)


def test_cli_changed_mode_smoke():
    """--changed reports only git-touched package files (pre-commit
    mode) but ANALYZES the whole package — a subset call graph can't
    see the callers that prove a function single-sided, so the seam
    rules would flag phantom cross-side escapes in untouched
    architecture whenever a seam-adjacent file is in the diff.  Exit
    must be clean whether the worktree is dirty (touched files are
    part of the clean live tree) or pristine."""
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.devtools.lint", "--changed"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr


# ============================= 2e. committed inventories (seam+device)


def test_cli_seam_report_roundtrips_and_matches_committed():
    """Acceptance: `ceph-tpu-lint --seam-report` emits a
    schema-versioned JSON inventory of every seam-crossing value,
    region and shared structure; the committed SEAM_INVENTORY.json is
    the same inventory structurally (line numbers aside), so the
    GIL-escape work-list cannot silently rot."""
    import pathlib
    from ceph_tpu.devtools.seam import SEAM_SCHEMA
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.devtools.lint",
         "--seam-report"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["seam_schema"] == SEAM_SCHEMA
    assert doc["partial"] is False   # whole-package work-list
    assert json.loads(json.dumps(doc)) == doc
    # the structures the ISSUE names are inventoried
    shared = {(e["module"], e["attr"]): e["classification"]
              for e in doc["shared_state"]}
    assert shared[("osd/shards.py", "ring")] == "gil-atomic"
    assert shared[("osd/shards.py", "_ring")] == "gil-atomic"
    assert shared[("store/commit.py", "_staged")] == "gil-atomic"
    assert shared[("osd/daemon.py", "pgs")] == "gil-atomic"
    assert shared[("msg/payload.py", "encode_calls")] == "gil-atomic"
    assert shared[("osd/daemon.py", "_waiting_maps")] == "lock"
    assert doc["summary"]["unprotected_structures"] == 0
    assert doc["summary"]["sites"] >= 20
    # every value at every site is classified
    for site in doc["sites"]:
        for v in site["values"]:
            assert v["class"] and v["role"]
    # committed work-list stays structurally in sync (regenerate with
    # `python -m ceph_tpu.devtools.lint --seam-report` when it drifts)
    committed_path = pathlib.Path(__file__).parent.parent \
        / "SEAM_INVENTORY.json"
    committed = json.loads(committed_path.read_text())
    assert committed["seam_schema"] == doc["seam_schema"]
    assert committed["partial"] is False, \
        "a partial (--changed / explicit-path) inventory was " \
        "committed over the whole-package work-list"

    def shape(d):
        return {
            "shared": sorted((e["module"], e["class"] or "", e["attr"],
                              e["classification"])
                             for e in d["shared_state"]),
            "regions": sorted((r["rel"], ",".join(r["attrs"]))
                              for r in d["gil_atomic_regions"]),
            "sites": sorted((s["rel"], s["kind"],
                             tuple(sorted(v["class"]
                                          for v in s["values"])))
                            for s in d["sites"]),
        }
    assert shape(committed) == shape(doc), \
        "SEAM_INVENTORY.json drifted from the live tree — regenerate " \
        "with: python -m ceph_tpu.devtools.lint --seam-report > " \
        "SEAM_INVENTORY.json"


def test_cli_device_report_roundtrips_and_matches_committed():
    """Acceptance (ISSUE 14): `ceph-tpu-lint --device-report` emits a
    schema-versioned inventory with every candidate kernel call site
    classified (sync/retrace/transfer), zero unsanctioned syncs, zero
    unportable transfers, zero per-call jit — and the committed
    DEVICE_INVENTORY.json stays structurally in sync, so the
    batched-CRUSH-in-the-data-path work-list cannot silently rot."""
    import pathlib
    from ceph_tpu.devtools.device import DEVICE_SCHEMA
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.devtools.lint",
         "--device-report"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["device_schema"] == DEVICE_SCHEMA
    assert doc["partial"] is False    # whole-package work-list
    assert json.loads(json.dumps(doc)) == doc
    s = doc["summary"]
    assert s["unclassified_kernel_sites"] == 0
    assert s["unsanctioned_syncs"] == 0
    assert s["unportable_transfers"] == 0
    assert s["per_call_jit"] == 0
    # the ISSUE-named candidate sites are all inventoried + classified
    kinds = {k["kind"]: k for k in doc["kernel_sites"]}
    assert "crush-placement" in kinds       # Objecter corked batch
    assert "ec-encode" in kinds             # ECBackend via ec_queue
    assert "ec-decode" in kinds             # degraded-read rebuild
    assert "decode-rebuild" in kinds        # recovery rebuild
    assert "ec-dispatch" in kinds           # the live executor launch
    assert kinds["crush-placement"]["retrace"] == "CHUNK_SIZES"
    assert kinds["ec-encode"]["sync"] == "clean"
    assert kinds["ec-dispatch"]["side"] == "executor"
    assert kinds["ec-dispatch"]["sync"] == "declared-region"
    assert kinds["ec-dispatch"]["transfer"] == "staged"
    # ISSUE 16: the batched-placement PR consumed the work-list — every
    # inventoried site is marked landed in-source
    assert all(k["landed"] for k in kinds.values()), kinds
    assert s["landed_kernel_sites"] == s["kernel_sites"]
    # every jit entry carries a cache kind; none are per-call
    for j in doc["jit_entries"]:
        assert j["cache"] in ("module", "builder-return",
                              "guarded-cache"), j
    # the fixed live-tree findings stay fixed: the autotuner probe is
    # a module-level jit entry, the winners kernel a guarded cache
    names = {(j["rel"], j["name"]): j["cache"]
             for j in doc["jit_entries"]}
    assert names[("ec/kernel.py", "_pallas_probe_sum")] == "module"
    assert names[("ops/crush_kernel.py",
                  "_get_winners_fn")] == "guarded-cache"
    # committed work-list stays structurally in sync (regenerate with
    # `python -m ceph_tpu.devtools.lint --device-report` on drift)
    committed_path = pathlib.Path(__file__).parent.parent \
        / "DEVICE_INVENTORY.json"
    committed = json.loads(committed_path.read_text())
    assert committed["device_schema"] == doc["device_schema"]
    assert committed["partial"] is False

    def shape(d):
        return {
            "sites": sorted((s["rel"], s["kind"], s["side"], s["sync"],
                             s["retrace"], s["transfer"], s["landed"])
                            for s in d["kernel_sites"]),
            "regions": sorted(r["rel"] for r in d["sync_regions"]),
            "jits": sorted((j["rel"], j["name"], j["cache"])
                           for j in d["jit_entries"]),
            "syncs": sorted((s["rel"], s["api"], s["sanction"])
                            for s in d["sync_sites"]),
        }
    assert shape(committed) == shape(doc), \
        "DEVICE_INVENTORY.json drifted from the live tree — " \
        "regenerate with: python -m ceph_tpu.devtools.lint " \
        "--device-report > DEVICE_INVENTORY.json"


# ============================================= 3. runtime lockdep layer


@pytest.fixture
def clean_lockdep():
    lockdep.reset()
    lockdep.enable()
    yield
    lockdep.disable()
    lockdep.reset()


def test_injected_mu_io_inversion_is_reported(clean_lockdep):
    """The FileDB invariant as a CHECKED edge: establish the legal
    _io -> _mu order, then take the locks inverted from another thread
    — the report must carry both acquisition stacks."""
    mu = lockdep.DepThreadLock("filedb:/x:_mu", rlock=True)
    io = lockdep.DepThreadLock("filedb:/x:_io")
    with io:
        with mu:                       # legal order: _io -> _mu
            pass

    def inverted():
        with mu:
            with io:                   # inversion
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join(5.0)
    rep = [e for e in lockdep.report() if e["kind"] == "lock_order"]
    assert len(rep) == 1, lockdep.report()
    e = rep[0]
    assert e["acquiring"] == "filedb:/x:_io"
    assert e["holding"] == "filedb:/x:_mu"
    # both backtraces: where the legal order was established, and the
    # offending acquisition
    assert "in inverted" in e["stack"]
    assert e["prior_stack"].strip()


def test_lockdep_cycle_reports_dedupe_per_edge_pair(clean_lockdep):
    """The same lock-order inversion hit from two different acquisition
    sites renders as ONE finding carrying both stacks (satellite: the
    report used to repeat once per site)."""
    a = lockdep.DepThreadLock("dd:a")
    b = lockdep.DepThreadLock("dd:b")
    with a:
        with b:                        # legal order: a -> b
            pass

    def inversion_site_one():
        with b:
            with a:
                pass

    def inversion_site_two():
        with b:
            with a:
                pass

    inversion_site_one()
    inversion_site_two()
    rep = [e for e in lockdep.report() if e["kind"] == "lock_order"]
    assert len(rep) == 1, rep
    e = rep[0]
    assert e["count"] == 2
    assert e["acquiring"] == "dd:a" and e["holding"] == "dd:b"
    stacks = e["stacks"]
    assert len(stacks) == 2
    assert "inversion_site_one" in stacks[0]
    assert "inversion_site_two" in stacks[1]
    # the rendered report names the extra site
    assert "also observed" in lockdep.render_report([e])


def test_rlock_reentrancy_is_not_a_cycle(clean_lockdep):
    mu = lockdep.DepThreadLock("r:_mu", rlock=True)
    with mu:
        with mu:                       # reentrant, legal
            pass
    assert lockdep.report() == []


def test_cross_loop_asyncio_misuse_is_reported(clean_lockdep):
    """An asyncio lock bound to one event loop, then acquired from a
    second loop on another thread: the release callbacks of loop A can
    never wake a waiter on loop B — report it at the acquisition."""
    lock = lockdep.DepLock("mds.mutex")

    async def use():
        async with lock:
            pass

    asyncio.run(use())                 # binds the lock to loop 1

    result = {}

    def second_loop():
        try:
            asyncio.run(use())         # fresh loop: misuse
        except lockdep.LockOrderViolation as e:
            result["err"] = e

    t = threading.Thread(target=second_loop)
    t.start()
    t.join(5.0)
    assert "err" in result
    rep = [e for e in lockdep.report() if e["kind"] == "cross_loop"]
    assert len(rep) == 1
    assert rep[0]["name"] == "mds.mutex"
    assert rep[0]["prior_stack"].strip() and rep[0]["stack"].strip()


def test_asyncio_lock_order_cycle_still_raises(clean_lockdep):
    """The original DepLock contract (test_mgr_tools covers it too):
    recorded AND raised."""
    async def run():
        a, b = lockdep.DepLock("a"), lockdep.DepLock("b")
        async with a:
            async with b:
                pass
        with pytest.raises(lockdep.LockOrderViolation):
            async with b:
                async with a:
                    pass

    asyncio.run(run())
    assert any(e["kind"] == "lock_order" for e in lockdep.report())


def test_loop_stall_monitor_detects_and_attributes(clean_lockdep):
    """A synchronous 0.3s section on the loop with a 50ms budget must
    be flagged, attributed to the last tracer stage cut on the loop
    thread."""
    from ceph_tpu.common.tracer import Span

    async def main():
        mon = lockdep.LoopStallMonitor(
            asyncio.get_running_loop(), budget=0.05).start()
        await asyncio.sleep(0.1)       # monitor sees a healthy loop
        span = Span(1, 1)
        span.cut("prepare")            # names the owning stage
        time.sleep(0.3)                # the stall (deliberate, BLK04-
        #   exempt here: tests are not linted)
        await asyncio.sleep(0.1)       # heartbeat lands, stall closes
        mon.stop()
        return mon.stalls

    stalls = asyncio.run(main())
    assert stalls >= 1
    rep = [e for e in lockdep.report() if e["kind"] == "loop_stall"]
    assert rep, lockdep.report()
    assert rep[0]["seconds"] >= 0.2
    assert rep[0]["stage"] == "prepare"


def test_factories_are_off_path_when_disabled():
    """The zero-overhead-when-off contract: disabled factories hand
    back PLAIN stdlib locks — no wrapper, no graph participation."""
    lockdep.disable()
    lockdep.reset()
    assert type(lockdep.make_thread_lock("x")) is type(threading.Lock())
    assert type(lockdep.make_thread_lock("x", rlock=True)) \
        is type(threading.RLock())
    assert isinstance(lockdep.make_async_lock("x"), asyncio.Lock)
    assert not isinstance(lockdep.make_async_lock("x"),
                          lockdep.DepLock)
    # and nothing records
    lk = lockdep.make_thread_lock("y")
    with lk:
        pass
    assert lockdep.GRAPH.edges == {}
    assert lockdep.report() == []


def test_filedb_locks_follow_the_gate(tmp_path):
    from ceph_tpu.store.kv import FileDB
    lockdep.disable()
    plain = FileDB(str(tmp_path / "plain"))
    assert not isinstance(plain._mu, lockdep.DepThreadLock)
    plain.close()
    lockdep.enable()
    try:
        checked = FileDB(str(tmp_path / "checked"))
        assert isinstance(checked._mu, lockdep.DepThreadLock)
        assert isinstance(checked._io, lockdep.DepThreadLock)
        # exercise the real write path: the _io -> _mu edge lands in
        # the graph and no violation is recorded (clean order)
        t = checked.create_transaction()
        t.set("p", b"k", b"v")
        checked.submit(t, sync=True)
        checked.close()
        assert [e for e in lockdep.report()
                if e["kind"] == "lock_order"] == []
        assert any("_mu" in str(dsts)
                   for dsts in lockdep.GRAPH.edges.values()) or \
            lockdep.GRAPH.edges, "expected _io -> _mu edges recorded"
    finally:
        lockdep.disable()
        lockdep.reset()


def test_cluster_teardown_fails_loudly_on_findings():
    """The qa satellite: an e2e test that leaks a sanitizer finding
    must fail at Cluster.stop() with the report attached — and the
    process-wide state must still be reset for the next test."""
    from ceph_tpu.qa.cluster import Cluster

    async def run():
        cl = Cluster()
        admin = await cl.start(1)
        assert lockdep.is_enabled()
        lockdep.record("lock_order", domain="thread",
                       order="a -> b -> a", acquiring="a", holding="b",
                       prior_stack="prior", stack="now")
        with pytest.raises(AssertionError,
                           match="invariant sanitizer"):
            await cl.stop()
        assert admin is not None

    asyncio.run(run())
    assert not lockdep.is_enabled()
    assert lockdep.report() == []


def test_cluster_teardown_clean_when_no_findings():
    from ceph_tpu.qa.cluster import Cluster

    async def run():
        cl = Cluster()
        admin = await cl.start(1)
        await admin.mon_command({"prefix": "status"})
        await cl.stop()

    asyncio.run(run())
    assert not lockdep.is_enabled()
