"""Lazy message-payload subsystem (msg/payload.py): copy discipline,
lazy<->wire equivalence, and the zero-encode local-path guard.

Covers the ISSUE 4 acceptance points:
- a replica mutating a received Transaction is never observable by the
  sender or by a second replica (freeze-and-assert + mutable copies);
- the same message delivered locally and over TCP (fault injection
  forces the TCP path) produces byte-identical wire frames and equal
  receiver state;
- a pure-local repop round performs ZERO body encodes
  (counter-asserted on a real replicated mini-cluster).
"""

import asyncio
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.msg import LazyPayload, Message, register_message
from ceph_tpu.msg import payload as payload_mod
from ceph_tpu.osd.messages import (
    EVersion, MOSDOp, MOSDRepOp, OP_WRITE, OSDOp,
)
from ceph_tpu.osd.pglog import LogEntry
from ceph_tpu.osd.types import PGId
from ceph_tpu.store.objectstore import Transaction
from ceph_tpu.store.types import CollectionId, ObjectId


def _sample_txn() -> Transaction:
    t = Transaction()
    cid = CollectionId.pg(1, 3, -1)
    t.write(cid, ObjectId("obj"), 0, b"payload-bytes" * 32)
    t.setattr(cid, ObjectId("obj"), "_ver", b"1'7")
    t.omap_setkeys(cid, ObjectId("_pgmeta_"), {b"k": b"v"})
    return t


def _sample_entry() -> LogEntry:
    return LogEntry(1, "obj", EVersion(1, 7), EVersion(1, 6), "c.1")


# ------------------------------------------------------- unit: LazyPayload

def test_payload_materializes_once_and_wire_matches_eager():
    txn = _sample_txn()
    eager = txn.to_bytes()
    p = LazyPayload.seal(txn)
    assert p.bytes() == eager
    assert p.bytes() is p.bytes()          # cached, not re-encoded
    # raw payloads pass through untouched (decode path)
    assert LazyPayload.coerce(eager).bytes() == eager


def test_seal_freezes_sender_txn():
    txn = _sample_txn()
    LazyPayload.seal(txn)
    assert txn.frozen
    with pytest.raises(AttributeError):
        txn.touch(CollectionId.pg(1, 3, -1), ObjectId("x"))
    # a mutable copy is open for business and isolated
    cp = txn.mutable_copy()
    cp.touch(CollectionId.pg(1, 3, -1), ObjectId("x"))
    assert len(cp.ops) == len(txn.ops) + 1


def test_repop_receiver_mutation_is_not_observable():
    """Two replicas mutate their received txns; the sender's txn and the
    sibling replica's copy never see it (the save_meta scenario)."""
    txn, entry = _sample_txn(), _sample_entry()
    n_ops = len(txn.ops)
    tp, lp = LazyPayload.seal(txn), LazyPayload.seal(entry)
    m1 = MOSDRepOp(PGId(1, 3), 7, tp, lp, EVersion(1, 7), 5)
    m2 = MOSDRepOp(PGId(1, 3), 7, tp, lp, EVersion(1, 7), 5)
    r1, r2 = m1.txn(), m2.txn()
    r1.omap_setkeys(CollectionId.pg(1, 3, -1), ObjectId("_pgmeta_"),
                    {b"info": b"replica1-meta"})
    r2.remove(CollectionId.pg(1, 3, -1), ObjectId("obj"))
    assert len(txn.ops) == n_ops            # sender untouched
    assert len(r1.ops) == n_ops + 1
    assert len(r2.ops) == n_ops + 1
    assert r1.ops[-1].op != r2.ops[-1].op   # replicas isolated
    # the shared immutable entry is the same object on both sides
    assert m1.log_entry() is entry


def test_save_meta_asserts_on_frozen_txn():
    """The exact ISSUE hazard: save_meta on the sender's sealed txn must
    fail loudly, not silently leak meta ops across daemons."""
    txn = _sample_txn()
    LazyPayload.seal(txn)

    class _FakePG:
        pass

    from ceph_tpu.osd.pg import PG
    with pytest.raises(ValueError):
        PG.save_meta(_FakePG(), txn)


def test_local_view_isolates_transport_envelope():
    """A multicast send (one message object to N co-located receivers,
    e.g. MWatchNotify to every watcher) must give each receiver its own
    envelope: per-delivery transport stamps can never collide."""
    from ceph_tpu.osd.messages import MWatchNotify
    m = MWatchNotify(PGId(1, 0), "o", 7, b"notify-payload", 0)
    v1, v2 = m.local_view(), m.local_view()
    assert v1 is not m and v1 is not v2
    v1.seq, v2.seq = 5, 9
    v1.transport_id, v2.transport_id = -1, -2
    assert (v1.seq, v1.transport_id) == (5, -1)
    assert m.seq == 0 and m.transport_id is None
    # the payload itself is shared, not copied
    assert v1.payload is m.payload


def test_mpglog_mpgnotify_lazy_wire_identity_and_roundtrip():
    """ISSUE 5 satellite: MPGLog/MPGNotify no longer pre-encode their
    info/log at construction — wire bytes must stay byte-identical to
    the old eager encoding, the decode round trip must reproduce the
    sender's state, and the sender's live info/log mutating AFTER the
    send must not leak into the payload (snapshot-at-construction)."""
    from ceph_tpu.osd.messages import MPGLog, MPGNotify
    from ceph_tpu.osd.pglog import PGInfo, PGLog

    info = PGInfo(PGId(2, 5))
    info.last_update = EVersion(3, 41)
    info.last_complete = EVersion(3, 40)
    info.last_epoch_started = 3
    log = PGLog()
    for v in (40, 41):
        log.append(LogEntry(1, f"obj{v}", EVersion(3, v),
                            EVersion(3, v - 1), f"c.{v}"))
    # byte-identity vs the old eager construction (bytes passed in)
    lazy = MPGLog(PGId(2, 5), 9, info, log, 1, activate=True)
    lazy.backfill_from = "bf"
    eager = MPGLog(PGId(2, 5), 9, info.to_bytes(), log.to_bytes(), 1,
                   activate=True)
    eager.backfill_from = "bf"
    assert lazy.to_bytes() == eager.to_bytes()
    nlazy = MPGNotify(PGId(2, 5), 9, info, 1)
    neager = MPGNotify(PGId(2, 5), 9, info.to_bytes(), 1)
    assert nlazy.to_bytes() == neager.to_bytes()
    # round trip: receiver state equals sender state at send time
    rt = MPGLog.from_bytes(lazy.to_bytes())
    ri, rl = rt.info(), rt.log()
    assert ri.last_update == info.last_update
    assert ri.last_epoch_started == info.last_epoch_started
    assert [e.version for e in rl.entries] \
        == [e.version for e in log.entries]
    assert MPGNotify.from_bytes(nlazy.to_bytes()).info().last_update \
        == info.last_update
    # snapshot discipline: sender keeps appending after construction
    log.append(LogEntry(1, "obj42", EVersion(3, 42), EVersion(3, 41)))
    info.last_update = EVersion(3, 42)
    assert len(lazy.log().entries) == 2
    assert lazy.info().last_update == EVersion(3, 41)
    # receiver copies are isolated from each other (adopt-and-append)
    l1, l2 = lazy.log(), lazy.log()
    l1.append(LogEntry(1, "x", EVersion(3, 42), EVersion(3, 41)))
    assert len(l2.entries) == 2


def test_mpglog_local_delivery_zero_encode():
    """The info/log payloads hand a co-located receiver mutable copies
    without ever serializing (msg_encode_calls stays 0)."""
    from ceph_tpu.osd.messages import MPGLog
    from ceph_tpu.osd.pglog import PGInfo, PGLog

    info = PGInfo(PGId(1, 1))
    info.last_update = EVersion(2, 7)
    log = PGLog()
    log.append(LogEntry(1, "o", EVersion(2, 7), EVersion(2, 6)))
    payload_mod.reset_counters()
    m = MPGLog(PGId(1, 1), 4, info, log, 0, activate=True)
    view = m.local_view()
    ri, rl = view.info(), view.log()
    assert ri.last_update == EVersion(2, 7)
    assert rl.entries[0] is log.entries[0]   # immutable entries shared
    c = payload_mod.counters()
    assert c["msg_encode_calls"] == 0, c
    assert c["msg_encode_bytes"] == 0, c


def test_mosdop_local_view_isolates_result_fields():
    ops = [OSDOp(OP_WRITE, 0, 5, data=b"hello")]
    m = MOSDOp(PGId(1, 0), "o", None, ops, tid=9)
    view = m.local_view()
    assert view.ops[0] is not ops[0]
    assert view.ops[0].data is ops[0].data      # bytes shared, not copied
    view.ops[0].rval = -5
    view.ops[0].outdata = b"result"
    assert ops[0].rval == 0 and ops[0].outdata == b""


def test_wire_bytes_counted_and_cached():
    payload_mod.reset_counters()
    m = MOSDRepOp(PGId(1, 3), 7, LazyPayload.seal(_sample_txn()),
                  LazyPayload.seal(_sample_entry()), EVersion(1, 7), 5)
    w1 = m.wire_bytes()
    w2 = m.wire_bytes()
    assert w1 is w2
    c = payload_mod.counters()
    assert c["msg_encode_calls"] == 1
    assert c["msg_encode_bytes"] == len(w1)
    # the wire form equals an eagerly-built bytes-carrying message
    eager = MOSDRepOp(PGId(1, 3), 7, _sample_txn().to_bytes(),
                      _sample_entry().to_bytes(), EVersion(1, 7), 5)
    assert w1 == eager.to_bytes()
    # and decodes back to equal receiver state
    rt = MOSDRepOp.from_bytes(w1)
    assert rt.txn().to_bytes() == _sample_txn().to_bytes()
    assert rt.log_entry() == _sample_entry()


def test_fanout_shares_one_encode():
    """N peers' messages share the payload: TCP fan-out pays ONE txn
    encode (payload cache), local fan-out pays zero."""
    txn = _sample_txn()
    tp = LazyPayload.seal(txn)
    lp = LazyPayload.seal(_sample_entry())
    msgs = [MOSDRepOp(PGId(1, 3), 7, tp, lp, EVersion(1, 7), 5)
            for _ in range(3)]
    bodies = [m.wire_bytes() for m in msgs]
    assert bodies[0] == bodies[1] == bodies[2]
    # the txn payload materialized once; each message envelope is its
    # own (seq-independent) encode on top of the shared cache
    assert tp.bytes() is tp.bytes()


# --------------------------------------------- e2e: local vs TCP delivery

@register_message
class MPayloadProbe(Message):
    """Test-only payload-carrying message (registered at a high type
    code so the corpus never sees it)."""
    TYPE = 9100

    def __init__(self, txn=b"", log=b""):
        super().__init__()
        self.txn_payload = LazyPayload.coerce(txn)
        self.log_payload = LazyPayload.coerce(log)

    def txn(self):
        return self.txn_payload.mutable(Transaction)

    def log_entry(self):
        return self.log_payload.peek(LogEntry)

    def encode_payload(self, enc: Encoder) -> None:
        enc.bytes_(self.txn_payload.bytes())
        enc.bytes_(self.log_payload.bytes())

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int):
        return cls(dec.bytes_(), dec.bytes_())


def _probe_pair_run(coro):
    return asyncio.run(coro)


def test_local_and_tcp_paths_agree():
    """The same message content delivered locally (zero-encode) and over
    TCP (fault injection forces the wire) yields equal receiver state,
    and the TCP frame is byte-identical to eager encoding."""
    import test_msg as tm

    async def run():
        # --- local pair: zero encodes, live graph delivery
        a, b, _, cb = await tm._pair(ms_local_delivery=True)
        payload_mod.reset_counters()
        a.send_message(MPayloadProbe(LazyPayload.seal(_sample_txn()),
                                     LazyPayload.seal(_sample_entry())),
                       b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        local_msg = cb.msgs[0]
        assert payload_mod.counters()["msg_encode_calls"] == 0
        local_txn = local_msg.txn()
        local_entry = local_msg.log_entry()
        await a.shutdown()
        await b.shutdown()

        # --- TCP pair: huge 1-in-N injection arms wire semantics
        # without ever actually firing, forcing the fallback path
        c_, d, _, cd = await tm._pair(ms_local_delivery=True,
                                      ms_inject_socket_failures=10**9)
        payload_mod.reset_counters()
        msg = MPayloadProbe(LazyPayload.seal(_sample_txn()),
                            LazyPayload.seal(_sample_entry()))
        c_.send_message(msg, d.addr)
        await cd.wait_for(lambda col: len(col.msgs) >= 1)
        tcp_msg = cd.msgs[0]
        cnt = payload_mod.counters()
        assert cnt["msg_encode_calls"] >= 1    # the wire hop encoded
        assert c_._local_msgs == 0
        # wire frame byte-identical to eager encoding
        assert msg.wire_bytes() == MPayloadProbe(
            _sample_txn().to_bytes(),
            _sample_entry().to_bytes()).to_bytes()
        # equal receiver state across the two transports
        assert tcp_msg.txn().to_bytes() == local_txn.to_bytes()
        assert tcp_msg.log_entry() == local_entry
        await c_.shutdown()
        await d.shutdown()

    _probe_pair_run(run())


def test_zero_encode_pure_local_repop_round():
    """Counter-asserted acceptance: a replicated write (repop fan-out +
    acks + client reply, every daemon co-located with
    ms_local_delivery) performs ZERO message body encodes."""
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("ms_local_delivery", True)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(3)
        await admin.pool_create("lzp", pg_num=4)
        io = admin.open_ioctx("lzp")
        await io.write_full("warm", b"w" * 512)   # settle peering/maps
        payload_mod.reset_counters()
        blobs = {f"lz{i:02d}": bytes([i]) * 2048 for i in range(8)}
        await asyncio.gather(*[io.write_full(k, v)
                               for k, v in blobs.items()])
        for k, v in blobs.items():
            assert await io.read(k) == v
        cnt = payload_mod.counters()
        local = sum(o.messenger._local_msgs for o in cl.osds.values())
        await cl.stop()
        assert local > 0, "local fast path never engaged"
        assert cnt["msg_encode_calls"] == 0, cnt
        assert cnt["msg_encode_bytes"] == 0, cnt

    asyncio.run(run())
