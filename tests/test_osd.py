"""End-to-end data-plane tests: mon + OSDs + rados client in-process.

Models the reference's vstart.sh + qa/workunits rados suites
(SURVEY §4): replicated and EC pool I/O, osd failure → re-peer →
recovery, degraded writes, restart-with-data.
"""

import asyncio

import pytest

from ceph_tpu.client import ObjectOperationError, Rados
from ceph_tpu.common.context import Context
from ceph_tpu.mon import Monitor
from ceph_tpu.mon.monmap import MonMap
from ceph_tpu.msg.messenger import Messenger
from ceph_tpu.msg.types import EntityName
from ceph_tpu.osd import OSD
from ceph_tpu.store.kv import MemDB
from ceph_tpu.store.memstore import MemStore

from ceph_tpu.qa.cluster import FAST_CFG, Cluster, make_ctx  # noqa: F401,E402


def test_replicated_put_get_cycle():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=8)
        io = admin.open_ioctx("data")
        await io.write_full("hello", b"world" * 100)
        assert await io.read("hello") == b"world" * 100
        assert await io.read("hello", length=5, offset=5) == b"world"
        assert await io.stat("hello") == 500
        await io.setxattr("hello", "user.k", b"v")
        assert await io.getxattr("hello", "user.k") == b"v"
        await io.omap_set("hello", {b"a": b"1"})
        assert await io.omap_get("hello") == {b"a": b"1"}
        # partial overwrite
        await io.write("hello", b"WORLD", offset=0)
        assert (await io.read("hello"))[:5] == b"WORLD"
        # many objects spread over pgs + listing
        for i in range(20):
            await io.write_full(f"obj-{i}", bytes([i]) * 64)
        names = await io.list_objects()
        assert set(names) >= {f"obj-{i}" for i in range(20)}
        # delete
        await io.remove("hello")
        with pytest.raises(ObjectOperationError):
            await io.read("hello")
        # data is actually replicated 3x on the osd stores
        found = 0
        for osd in cl.osds.values():
            for cid in osd.store.list_collections():
                for soid in osd.store.collection_list(cid):
                    if soid.name == "obj-3":
                        found += 1
        assert found == 3
        await cl.stop()
    asyncio.run(run())


def test_ec_pool_io():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ecpool", pg_num=8, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ecpool")
        payload = bytes(range(256)) * 64    # 16 KiB
        await io.write_full("big", payload)
        assert await io.read("big") == payload
        assert await io.stat("big") == len(payload)
        assert await io.read("big", length=100, offset=1000) == \
            payload[1000:1100]
        await io.setxattr("big", "tag", b"ec")
        assert await io.getxattr("big", "tag") == b"ec"
        # every live shard holds 1/4-size chunks (k=4 of 16KiB)
        chunk_sizes = []
        for osd in cl.osds.values():
            for cid in osd.store.list_collections():
                for soid in osd.store.collection_list(cid):
                    if soid.name == "big":
                        chunk_sizes.append(
                            osd.store.stat(cid, soid)["size"])
        assert len(chunk_sizes) == 6
        assert all(s == 4096 for s in chunk_sizes)
        # omap rejected on EC pools
        with pytest.raises(ObjectOperationError):
            await io.omap_set("big", {b"x": b"y"})
        await io.remove("big")
        with pytest.raises(ObjectOperationError):
            await io.read("big")
        await cl.stop()
    asyncio.run(run())


def test_replicated_osd_failure_and_recovery():
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("rep", pg_num=8, size=3)
        io = admin.open_ioctx("rep")
        for i in range(10):
            await io.write_full(f"o{i}", f"payload-{i}".encode() * 20)
        # kill an osd; mark down via mon command (heartbeat path tested
        # separately); out-aging then remaps pgs
        victim = 1
        await cl.kill_osd(victim)
        await cl.mark_down_and_wait(admin, victim)
        # cluster still serves reads and writes (degraded)
        for i in range(10):
            assert (await io.read(f"o{i}")) == \
                f"payload-{i}".encode() * 20
        await io.write_full("during-degraded", b"x" * 100)
        # after down-out interval the osd goes out; data re-replicates
        deadline = asyncio.get_event_loop().time() + 30
        while admin.monc.osdmap.is_in(victim):
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.1)
        await asyncio.sleep(1.0)   # let recovery run
        # every object has 3 live replicas again
        for name in [f"o{i}" for i in range(10)] + ["during-degraded"]:
            copies = 0
            for osd in cl.osds.values():
                for cid in osd.store.list_collections():
                    for soid in osd.store.collection_list(cid):
                        if soid.name == name:
                            copies += 1
            assert copies == 3, (name, copies)
        await cl.stop()
    asyncio.run(run())


def test_ec_shard_failure_reconstruction():
    async def run():
        cl = Cluster()
        admin = await cl.start(7)
        await admin.pool_create("ec", pg_num=4, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ec")
        payload = b"erasure-coded-payload" * 300
        for i in range(5):
            await io.write_full(f"e{i}", payload + bytes([i]))
        victim = 2
        await cl.kill_osd(victim)
        await cl.mark_down_and_wait(admin, victim)
        # degraded reads still work (decode from surviving shards)
        for i in range(5):
            assert (await io.read(f"e{i}")) == \
                payload + bytes([i])
        # osd goes out; crush repositions; recovery reconstructs shards
        deadline = asyncio.get_event_loop().time() + 30
        while admin.monc.osdmap.is_in(victim):
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.1)
        await asyncio.sleep(2.0)
        for i in range(5):
            copies = 0
            for osd in cl.osds.values():
                for cid in osd.store.list_collections():
                    for soid in osd.store.collection_list(cid):
                        if soid.name == f"e{i}":
                            copies += 1
            assert copies == 6, (i, copies)
            assert (await io.read(f"e{i}")) == \
                payload + bytes([i])
        # recovery observability (ISSUE 18): the rebuild left
        # first-class counters in the osd.recovery perf group that
        # `perf dump --cluster` scrapes per daemon and merges
        rec = {}
        for osd in cl.osds.values():
            assert "recovery" in osd.ctx.perf.dump()
            for k, v in osd.perf_recovery.dump().items():
                rec[k] = rec.get(k, 0) + int(v)
        assert rec["objects_pushed"] > 0, rec
        assert rec["objects_pulled"] > 0, rec
        assert rec["push_bytes"] > 0 and rec["pull_bytes"] > 0, rec
        # converged: every backfill cursor back at LB_MAX, no lag left
        assert rec["cursor_lag"] == 0, rec
        await cl.stop()
    asyncio.run(run())


def test_osd_restart_rejoins_with_data():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rep", pg_num=4, size=3)
        io = admin.open_ioctx("rep")
        await io.write_full("keep", b"original")
        store = await cl.kill_osd(0)
        await cl.mark_down_and_wait(admin, 0)
        # write while it's gone: osd.0 misses this
        await io.write_full("keep", b"updated!!")
        await io.write_full("new-obj", b"fresh")
        # restart with its old store
        await cl.start_osd(0, store=store)
        await cl.osds[0].wait_for_boot()
        await asyncio.sleep(1.5)   # peering + log-based catch-up
        # osd.0's copy caught up to the authoritative version
        osd0 = cl.osds[0]
        data = None
        for cid in osd0.store.list_collections():
            for soid in osd0.store.collection_list(cid):
                if soid.name == "keep":
                    data = osd0.store.read(cid, soid)
        assert data == b"updated!!"
        await cl.stop()
    asyncio.run(run())


def test_heartbeat_failure_reporting():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=4, size=3)
        io = admin.open_ioctx("p")
        await io.write_full("x", b"1")   # PGs exist → osds are hb peers
        # hard-kill osd.2 (no mon command): peers must report it
        await cl.kill_osd(2)
        deadline = asyncio.get_event_loop().time() + 20
        while admin.monc.osdmap.is_up(2):
            assert asyncio.get_event_loop().time() < deadline, \
                "peers never reported the dead osd"
            await asyncio.sleep(0.1)
        await cl.stop()
    asyncio.run(run())


def test_ec_profile_persisted_and_honored():
    """ADVICE r1: a profile with m=3 must actually run 3 parity shards —
    the k/m live in the osdmap's ec_profiles, never derived from size."""
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.mon_command({
            "prefix": "osd erasure-code-profile set", "name": "p33",
            "profile": {"k": "3", "m": "3"}})
        ack = await admin.mon_command(
            {"prefix": "osd erasure-code-profile get", "name": "p33"})
        assert ack.retcode == 0 and '"m": "3"' in ack.outs
        # contradicting k/m at pool create is rejected
        from ceph_tpu.mon.client import CommandError
        with pytest.raises(CommandError):
            await admin.mon_command({
                "prefix": "osd pool create", "pool": "bad", "pg_num": 4,
                "pool_type": "erasure", "erasure_code_profile": "p33",
                "k": 4, "m": 2})
        await admin.pool_create("ec33", pg_num=4, pool_type="erasure",
                                erasure_code_profile="p33")
        pid = admin.monc.osdmap.lookup_pool("ec33")
        pool = admin.monc.osdmap.pools[pid]
        assert pool.size == 6 and \
            admin.monc.osdmap.ec_profiles["p33"]["m"] == "3"
        io = admin.open_ioctx("ec33")
        payload = bytes(range(256)) * 48   # 12 KiB -> 4 KiB chunks (k=3)
        await io.write_full("obj", payload)
        assert await io.read("obj") == payload
        # 3 data + 3 parity shards on distinct osds
        chunks = 0
        for osd in cl.osds.values():
            for cid in osd.store.list_collections():
                for soid in osd.store.collection_list(cid):
                    if soid.name == "obj":
                        chunks += 1
        assert chunks == 6
        # in-use profile can't be removed
        with pytest.raises(CommandError):
            await admin.mon_command(
                {"prefix": "osd erasure-code-profile rm", "name": "p33"})
        await cl.stop()
    asyncio.run(run())


def test_full_resync_removes_peer_only_objects():
    """ADVICE r1: an object deleted beyond the log window must not
    survive on a peer that was down across the deletion (backfill scans
    both sides in the reference)."""
    from ceph_tpu.osd.pglog import PGLog

    async def run():
        old_max = PGLog.MAX_ENTRIES
        PGLog.MAX_ENTRIES = 8    # force the catch-up window shut fast
        try:
            cl = Cluster()
            admin = await cl.start(2)
            await admin.pool_create("rep", pg_num=1, size=2)
            io = admin.open_ioctx("rep")
            await io.write_full("doomed", b"zombie" * 10)
            await io.write_full("keep", b"alive")
            store1 = await cl.kill_osd(1)
            await cl.mark_down_and_wait(admin, 1)
            await io.remove("doomed")
            # push the delete out of the log window
            for i in range(12):
                await io.write_full(f"fill-{i}", bytes([i]) * 16)
            # osd.1 comes back with its stale store -> full resync
            await cl.start_osd(1, store=store1)
            await cl.osds[1].wait_for_boot()
            await asyncio.sleep(2.0)
            osd1 = cl.osds[1]
            names = set()
            for cid in osd1.store.list_collections():
                for soid in osd1.store.collection_list(cid):
                    names.add(soid.name)
            assert "doomed" not in names, "deleted object resurrected"
            assert "keep" in names and "fill-5" in names
            await cl.stop()
        finally:
            PGLog.MAX_ENTRIES = old_max
    asyncio.run(run())


def test_pool_quota_full_flag_blocks_writes():
    """Pool quotas (OSDMonitor set-quota + PGMap check_full role): the
    mon flips FLAG_FULL_QUOTA when usage crosses the quota; writes
    fail EDQUOT, deletes still pass (dig-out), and clearing the quota
    or deleting objects unblocks."""
    import errno as _errno

    async def run():
        import time as _time
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("q", pg_num=4)
        io = admin.open_ioctx("q")
        await admin.mon_command({"prefix": "osd pool set", "pool": "q",
                                 "var": "quota_max_objects", "val": "2"})
        await io.write_full("a", b"x" * 100)
        await io.write_full("b", b"y" * 100)

        # stats propagate -> mon flags the pool full -> writes EDQUOT
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            try:
                await io.write_full("c", b"z")
                await io.remove("c")          # not yet flagged: undo
                await asyncio.sleep(0.3)
            except ObjectOperationError as e:
                assert e.retcode == -_errno.EDQUOT, e
                break
        else:
            raise AssertionError("pool never went quota-full")

        # deletes pass while full (dig-out), then usage drops below
        # the quota and the mon clears the flag
        await io.remove("b")
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            try:
                await io.write_full("d", b"w")
                break
            except ObjectOperationError:
                await asyncio.sleep(0.3)
        else:
            raise AssertionError("pool never un-flagged after delete")
        # raise the quota entirely: a third object fits now
        await admin.mon_command({"prefix": "osd pool set", "pool": "q",
                                 "var": "quota_max_objects", "val": "0"})
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            try:
                await io.write_full("e", b"v")
                break
            except ObjectOperationError:
                await asyncio.sleep(0.3)
        else:
            raise AssertionError("quota=0 never unblocked")
        await cl.stop()
    asyncio.run(run())


def test_cluster_flag_noout_holds_down_osd_in():
    """`osd set noout` (OSDMap cluster flags): a down osd is NOT aged
    out while the flag is set; unset resumes the down-out clock; the
    flag shows in the osdmap summary."""
    async def run():
        import time as _time
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("nf", pg_num=4)   # heartbeat peers
        io = admin.open_ioctx("nf")
        await io.write_full("x", b"y")
        ack = await admin.mon_command({"prefix": "osd set",
                                       "key": "noout"})
        assert "noout" in ack.outs
        ack = await admin.mon_command({"prefix": "status"})
        assert "noout" in ack.outs

        await cl.kill_osd(2)
        grace = FAST_CFG["mon_osd_down_out_interval"]
        # wait until it's seen DOWN, then well past the out-grace
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline and \
                admin.monc.osdmap.is_up(2):
            await asyncio.sleep(0.2)
        await asyncio.sleep(grace + 2.0)
        m = admin.monc.osdmap
        assert not m.is_up(2) and m.is_in(2), "noout must hold it in"

        await admin.mon_command({"prefix": "osd unset", "key": "noout"})
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            if not admin.monc.osdmap.is_in(2):
                break
            await asyncio.sleep(0.3)
        assert not admin.monc.osdmap.is_in(2), \
            "unset noout must resume down-out"
        # unknown flag is rejected loudly
        with pytest.raises(Exception) as ei:
            await admin.mon_command({"prefix": "osd set",
                                     "key": "nosuchflag"})
        assert "nosuchflag" in str(ei.value)
        await cl.stop()
    asyncio.run(run())
