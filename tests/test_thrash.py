"""Stochastic model checking under thrashing (RadosModel analog).

Runs ceph_tpu/qa/rados_model.py seeds in-process — randomized
write/delete/read workloads raced against osd kills, restarts, out/in
flaps and false down marks, with object-level verification against an
in-memory model — plus a targeted crash-mid-backfill case proving the
backfill_complete marker forces a resync retry (VERDICT r2 ask #8).
"""

import asyncio
import os
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.qa.rados_model import run_model  # noqa: E402

# the standalone runner covers many more: python -m ceph_tpu.qa.rados_model
SEEDS = range(1, 1 + int(os.environ.get("THRASH_SEEDS", "6")))

# seed 5's kill pattern replays ~48 s of recovery wall time and pins
# no named regression (1-4, 6 keep the default-tier churn coverage);
# it runs in the slow tier with the EC role-change seed below
_REP_SLOW = {5}
SEEDS = [pytest.param(s, marks=pytest.mark.slow) if s in _REP_SLOW
         else s for s in SEEDS]

# EC churn seeds.  101 drove six earlier fixes; 105 is the regression
# seed for the role-change wedge (an EC shard moving osd slots, e.g.
# s2 -> s0 on one osd, left a newborn primary starved of peering
# replies behind its own old-shard stray) and for the backfill-cursor
# read gate (a mid-backfill replica must serve versioned objects it
# holds and answer EAGAIN — never ENOENT — for names past its cursor).
# Widen locally with EC_SEEDS=10; the standalone runner covers more:
# python -m ceph_tpu.qa.rados_model --ec --seeds 10
_N_EC = int(os.environ.get("EC_SEEDS", "2"))
EC_SEEDS = [101, 105] if _N_EC <= 2 else list(range(101, 101 + _N_EC))

# Seed 105 replays the role-change wedge end to end (~150 s wall); it
# stays required coverage but runs in the slow tier so the default
# sweep fits its time budget.  python -m ceph_tpu.qa.rados_model --ec
# still covers it, as does pytest without `-m 'not slow'`.
_EC_SLOW = {105}
EC_SEEDS = [
    pytest.param(s, marks=pytest.mark.slow) if s in _EC_SLOW else s
    for s in EC_SEEDS
]


@pytest.mark.parametrize("seed", SEEDS)
def test_model_checker_replicated(seed):
    res = asyncio.run(run_model(seed, rounds=60))
    assert res["ok"], res["failures"]


@pytest.mark.parametrize("seed", EC_SEEDS)
def test_model_checker_ec_pool(seed):
    # required (no xfail) since the per-object backfill-cursor +
    # shard-aware primariness work: the historical ~1/6-seed ENOENT
    # window came from cursor-blind replicas serving holes as
    # deletions and from role-changed primaries wedging mid-recovery
    res = asyncio.run(run_model(
        seed, rounds=50, n_osds=5,
        pool_kw={"pool_type": "erasure", "k": 2, "m": 2}))
    assert res["ok"], res["failures"]


def test_crash_mid_backfill_forces_retry():
    """Kill the backfill TARGET mid-resync: on restart its
    backfill_complete=False marker must force a fresh full resync
    instead of trusting the half-copied object set."""
    from ceph_tpu.osd.pglog import PGLog

    async def run():
        old_max = PGLog.MAX_ENTRIES
        PGLog.MAX_ENTRIES = 8     # shut the log window fast
        try:
            cl = Cluster()
            admin = await cl.start(3)
            await admin.pool_create("p", pg_num=1, size=3)
            io = admin.open_ioctx("p")
            for i in range(10):
                await io.write_full(f"a{i}", bytes([i]) * 512)
            # take osd.2 down; write far past the log window so catch-up
            # requires a FULL resync, with many objects to copy
            store2 = await cl.kill_osd(2)
            await cl.mark_down_and_wait(admin, 2)
            for i in range(40):
                await io.write_full(f"b{i}", bytes([i]) * 2048)
            # restart the stale osd; let backfill BEGIN and stamp a
            # partial cursor, then crash it before it can finish
            from ceph_tpu.osd.pglog import LB_MAX
            osd2 = await cl.start_osd(2, store=store2)
            deadline = asyncio.get_running_loop().time() + 20
            started = False
            while not started:
                for pg in osd2.pgs.values():
                    if not pg.info.backfill_complete \
                            and pg.info.last_backfill \
                            and pg.info.last_backfill != LB_MAX:
                        started = True
                assert asyncio.get_running_loop().time() < deadline, \
                    "backfill never started"
                await asyncio.sleep(0.002)
            store2 = await cl.kill_osd(2)
            await cl.mark_down_and_wait(admin, 2)
            # the crashed copy must have persisted the incomplete marker
            # (that is the crash-safety claim under test) — and its
            # DURABLE last_backfill cursor, which the retry must resume
            # FROM rather than restarting the copy from scratch
            from ceph_tpu.osd.pg import PGInfo
            killed_cursor = ""
            # scan every collection's meta object for a pg info row
            for cid in store2.list_collections():
                for o in store2.collection_list(cid):
                    try:
                        _, omap = store2.omap_get(cid, o)
                    except Exception:
                        continue
                    if b"info" in omap:
                        info = PGInfo.from_bytes(omap[b"info"])
                        killed_cursor = max(killed_cursor,
                                            info.last_backfill)
            assert killed_cursor and killed_cursor != LB_MAX, \
                "no durable partial cursor found on the killed store"
            # restart again: the marker forces a retry; eventually every
            # object lands and the copy is trusted — and the cursor
            # NEVER regresses below its killed-time durable value
            osd2 = await cl.start_osd(2, store=store2)
            deadline = asyncio.get_running_loop().time() + 40
            while True:
                for pg in osd2.pgs.values():
                    if not pg.info.backfill_complete:
                        lb = pg.info.last_backfill
                        assert lb >= killed_cursor, \
                            (f"resume regressed below the durable "
                             f"cursor: {lb!r} < {killed_cursor!r}")
                pgs = list(osd2.pgs.values())
                if pgs and all(p.info.backfill_complete for p in pgs):
                    names = {o.name
                             for pg in pgs
                             for o in osd2.store.collection_list(pg.cid)
                             if o.name != pg.meta_oid.name}
                    want = ({f"a{i}" for i in range(10)}
                            | {f"b{i}" for i in range(40)})
                    if want <= names:
                        break
                assert asyncio.get_running_loop().time() < deadline, \
                    "resync never completed after mid-backfill crash"
                await asyncio.sleep(0.2)
            # and the data is right everywhere
            for i in range(40):
                assert await io.read(f"b{i}") == bytes([i]) * 2048
            await cl.stop()
        finally:
            PGLog.MAX_ENTRIES = old_max
    asyncio.run(run())


def test_backfill_windowed_listing_and_cursor_resume():
    """Large-PG backfill with a tiny scan window (osd_backfill_scan_max)
    must page the listing in bounded messages, and a target killed
    mid-backfill must RESUME from its persisted last_backfill cursor
    rather than restarting from scratch (PG.h:1911)."""
    from ceph_tpu.osd.pglog import LB_MAX, PGLog

    async def run():
        old_max = PGLog.MAX_ENTRIES
        PGLog.MAX_ENTRIES = 8
        try:
            from ceph_tpu.qa.cluster import make_ctx

            def ctx_f(name):
                c = make_ctx(name)
                c.config.set("osd_backfill_scan_max", 7)
                return c
            cl = Cluster(ctx_factory=ctx_f)
            admin = await cl.start(3)
            await admin.pool_create("p", pg_num=1, size=3)
            io = admin.open_ioctx("p")
            store2 = await cl.kill_osd(2)
            await cl.mark_down_and_wait(admin, 2)
            # 60 objects, far beyond the log window -> full backfill
            # paged across ~9 windows of 7
            for i in range(60):
                await io.write_full(f"obj{i:03d}", bytes([i]) * 1024)
            osd2 = await cl.start_osd(2, store=store2)
            # catch it mid-backfill with a partial cursor, then kill
            deadline = asyncio.get_running_loop().time() + 30
            cursor = None
            while cursor is None:
                for pg in osd2.pgs.values():
                    lb = pg.info.last_backfill
                    if lb and lb != LB_MAX:
                        cursor = lb
                assert asyncio.get_running_loop().time() < deadline, \
                    "no partial cursor observed"
                await asyncio.sleep(0.002)
            store2 = await cl.kill_osd(2)
            await cl.mark_down_and_wait(admin, 2)
            osd2 = await cl.start_osd(2, store=store2)
            deadline = asyncio.get_running_loop().time() + 60
            while True:
                pgs = list(osd2.pgs.values())
                if pgs and all(p.info.backfill_complete for p in pgs):
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "backfill never completed after resume"
                await asyncio.sleep(0.05)
            # every object must be present and correct on the resumed
            # copy (read each back through the cluster)
            for i in range(60):
                got = await io.read(f"obj{i:03d}")
                assert got == bytes([i]) * 1024, f"obj{i:03d} corrupt"
            await cl.stop()
        finally:
            PGLog.MAX_ENTRIES = old_max
    asyncio.run(run())


def test_op_intake_throttle_bounds_memory():
    """Flood one OSD with more write bytes than the intake cap: the
    dispatch throttle must bound in-flight bytes (clients block on TCP
    backpressure, ops still all complete) — VERDICT r3 weak #6."""
    async def run():
        from ceph_tpu.qa.cluster import make_ctx

        def ctx_f(name):
            c = make_ctx(name)
            c.config.set("osd_client_message_size_cap", 262144)
            return c
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(1)
        await admin.pool_create("p", pg_num=1, size=1)
        io = admin.open_ioctx("p")
        osd = next(iter(cl.osds.values()))
        thr = osd.messenger.dispatch_throttle
        assert thr is not None and thr.max == 262144
        peak = 0

        async def watch():
            nonlocal peak
            while True:
                peak = max(peak, thr.cur)
                await asyncio.sleep(0.001)
        w = asyncio.get_running_loop().create_task(watch())
        # 8 MiB of writes vs a 256 KiB budget
        writes = [io.write_full(f"o{i}", bytes([i % 256]) * 65536)
                  for i in range(128)]
        await asyncio.gather(*writes)
        w.cancel()
        assert peak <= 262144, f"throttle exceeded: {peak}"
        assert thr.waited > 0, "flood never hit the throttle"
        # drained: nothing leaked budget
        for _ in range(100):
            if thr.cur == 0:
                break
            await asyncio.sleep(0.01)
        assert thr.cur == 0, f"leaked {thr.cur} bytes of intake budget"
        for i in range(0, 128, 17):
            assert await io.read(f"o{i}") == bytes([i % 256]) * 65536
        await cl.stop()
    asyncio.run(run())
