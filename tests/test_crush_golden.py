"""Bit-exactness of the host CRUSH implementation against golden vectors
generated from the reference C (tests/golden/generate.py).

Scenario construction here mirrors tests/golden/gen_golden.c exactly,
including the LCG weight streams, so mapping outputs must match verbatim.
"""

import json
import pathlib

import pytest

from ceph_tpu.crush import builder
from ceph_tpu.crush.constants import (BUCKET_LIST, BUCKET_STRAW,
                                      BUCKET_STRAW2, BUCKET_TREE,
                                      BUCKET_UNIFORM, RULE_CHOOSE_FIRSTN,
                                      RULE_CHOOSELEAF_FIRSTN,
                                      RULE_CHOOSELEAF_INDEP, RULE_EMIT,
                                      RULE_TAKE)
from ceph_tpu.crush import hashfn
from ceph_tpu.crush.lntable import crush_ln, ln_u16_table
from ceph_tpu.crush.mapper import do_rule
from ceph_tpu.crush.types import CrushMap, Rule, RuleStep

GOLDEN = json.loads(
    (pathlib.Path(__file__).parent / "golden/crush_golden.json").read_text())


class LCG:
    """Mirror of gen_golden.c's lcg()."""

    def __init__(self, seed=12345):
        self.state = seed

    def __call__(self):
        self.state = (self.state * 1103515245 + 12345) & 0xFFFFFFFF
        return (self.state >> 16) & 0x7FFF


def test_hash_vectors():
    for i, row in enumerate(GOLDEN["hash"]):
        a = (i * 2654435761) & 0xFFFFFFFF
        b = (i * 40503 + 7) & 0xFFFFFFFF
        c = (i + 0xDEADBEEF) & 0xFFFFFFFF
        d = (i * 97) & 0xFFFFFFFF
        e = (i * 1000003) & 0xFFFFFFFF
        assert hashfn.hash32(a) == row[0]
        assert hashfn.hash32_2(a, b) == row[1]
        assert hashfn.hash32_3(a, b, c) == row[2]
        assert hashfn.hash32_4(a, b, c, d) == row[3]
        assert hashfn.hash32_5(a, b, c, d, e) == row[4]


def test_np_hash_matches_scalar():
    import numpy as np
    a = np.arange(100, dtype=np.uint32) * np.uint32(2654435761)
    b = np.arange(100, dtype=np.uint32)
    c = np.full(100, 7, np.uint32)
    got = hashfn.np_hash32_3(a, b, c)
    for i in range(100):
        assert int(got[i]) == hashfn.hash32_3(int(a[i]), int(b[i]), int(c[i]))
    got2 = hashfn.np_hash32_2(a, b)
    for i in range(100):
        assert int(got2[i]) == hashfn.hash32_2(int(a[i]), int(b[i]))


def test_crush_ln_sparse_samples():
    samples = GOLDEN["ln_samples"]
    for j, val in enumerate(samples):
        u = j * 509
        assert crush_ln(u) == val, f"crush_ln({u})"


def test_crush_ln_full_range_checksum():
    tbl = ln_u16_table()
    fnv = 1469598103934665603
    for u in range(0x10000):
        fnv = ((fnv ^ int(tbl[u])) * 1099511628211) & (2**64 - 1)
    assert fnv == GOLDEN["ln_fnv"]


# -- scenario builders (mirror gen_golden.c) ---------------------------------

def scen_a():
    m = CrushMap()
    m.set_tunables_profile("jewel")
    items = list(range(12))
    w = [(i + 1) * 0x8000 for i in range(12)]
    root = builder.make_bucket(m, BUCKET_STRAW2, 10, items, w)
    r = Rule(0, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                           RuleStep(RULE_CHOOSE_FIRSTN, 0, 0),
                           RuleStep(RULE_EMIT)])
    m.add_rule(r)
    weight = [0x10000] * 12
    weight[3] = 0
    weight[5] = 0x8000
    return m, [(0, 3, weight, 256)]


def _two_level(lcg):
    m = CrushMap()
    m.set_tunables_profile("jewel")
    hosts = []
    osd = 0
    for h in range(5):
        n = 2 + (h % 3)
        items = list(range(osd, osd + n))
        osd += n
        w = [0x10000 + (lcg() % 0x10000) for _ in range(n)]
        hosts.append(builder.make_bucket(m, BUCKET_STRAW2, 1, items, w))
    root = builder.make_bucket(m, BUCKET_STRAW2, 10,
                               [h.id for h in hosts],
                               [h.weight for h in hosts])
    return m, root


def scen_bc():
    lcg = LCG()
    m, root = _two_level(lcg)
    m.add_rule(Rule(0, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    m.add_rule(Rule(1, 3, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_INDEP, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    weight = [0x10000] * 14
    weight[2] = 0
    weight[7] = 0xC000
    return m, [(0, 3, weight, 256), (1, 4, weight, 256)], lcg


def scen_d(lcg):
    m = CrushMap()
    m.set_tunables_profile("jewel")
    algs = [BUCKET_UNIFORM, BUCKET_LIST, BUCKET_TREE, BUCKET_STRAW,
            BUCKET_STRAW2]
    hosts = []
    osd = 0
    for h in range(5):
        n = 3 + (h % 2)
        items = list(range(osd, osd + n))
        osd += n
        if algs[h] == BUCKET_UNIFORM:
            w = [0x10000] * n
        else:
            w = [0x8000 + (lcg() % 0x18000) for _ in range(n)]
        hosts.append(builder.make_bucket(m, algs[h], 1, items, w))
    root = builder.make_bucket(m, BUCKET_STRAW2, 10,
                               [h.id for h in hosts],
                               [h.weight for h in hosts])
    m.add_rule(Rule(0, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSE_FIRSTN, 0, 1),
                                  RuleStep(RULE_CHOOSE_FIRSTN, 1, 0),
                                  RuleStep(RULE_EMIT)]))
    weight = [0x10000] * osd
    weight[1] = 0x4000
    return m, [(0, 4, weight, 256)]


def scen_e(lcg):
    m = CrushMap()
    m.set_tunables_profile("legacy")
    hosts = []
    osd = 0
    for h in range(4):
        items = list(range(osd, osd + 3))
        osd += 3
        w = [0x10000 + (lcg() % 0x20000) for _ in range(3)]
        hosts.append(builder.make_bucket(m, BUCKET_STRAW, 1, items, w))
    root = builder.make_bucket(m, BUCKET_STRAW, 10,
                               [h.id for h in hosts],
                               [h.weight for h in hosts])
    m.add_rule(Rule(0, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    weight = [0x10000] * 12
    weight[4] = 0
    return m, [(0, 3, weight, 256)]


def scen_f():
    m = CrushMap()
    m.set_tunables_profile("jewel")
    hosts = []
    osd = 0
    for h in range(32):
        items = list(range(osd, osd + 4))
        osd += 4
        hosts.append(builder.make_bucket(m, BUCKET_STRAW2, 1, items,
                                         [0x10000] * 4))
    root = builder.make_bucket(m, BUCKET_STRAW2, 10,
                               [h.id for h in hosts],
                               [h.weight for h in hosts])
    m.add_rule(Rule(0, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    m.add_rule(Rule(1, 3, 1, 16, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_INDEP, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    weight = [0x10000] * osd
    weight[10] = 0
    weight[50] = 0
    weight[77] = 0x8000
    return m, [(0, 3, weight, 512), (1, 12, weight, 512)]


def scen_g(lcg):
    """THREE-level straw2: root -> 4 racks -> 3 hosts -> 2 osds."""
    m = CrushMap()
    m.set_tunables_profile("jewel")
    racks = []
    osd = 0
    for _rk in range(4):
        hosts = []
        for _h in range(3):
            items = list(range(osd, osd + 2))
            osd += 2
            w = [0x10000 + (lcg() % 0x10000) for _ in range(2)]
            hosts.append(builder.make_bucket(m, BUCKET_STRAW2, 1,
                                             items, w))
        racks.append(builder.make_bucket(m, BUCKET_STRAW2, 2,
                                         [h.id for h in hosts],
                                         [h.weight for h in hosts]))
    root = builder.make_bucket(m, BUCKET_STRAW2, 10,
                               [r.id for r in racks],
                               [r.weight for r in racks])
    m.add_rule(Rule(0, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    m.add_rule(Rule(1, 3, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_INDEP, 0, 1),
                                  RuleStep(RULE_EMIT)]))
    m.add_rule(Rule(2, 1, 1, 10, [RuleStep(RULE_TAKE, root.id),
                                  RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, 2),
                                  RuleStep(RULE_EMIT)]))
    weight = [0x10000] * osd
    weight[3] = 0
    weight[11] = 0x9000
    weight[17] = 0
    return m, [(0, 3, weight, 512), (1, 5, weight, 512),
               (2, 3, weight, 512)]


def scen_h(lcg):
    """Multi-take: two independent 2-level roots, emit from each."""
    m = CrushMap()
    m.set_tunables_profile("jewel")
    roots = []
    osd = 0
    for _rt in range(2):
        hosts = []
        for _h in range(3):
            items = list(range(osd, osd + 3))
            osd += 3
            w = [0x10000 + (lcg() % 0x8000) for _ in range(3)]
            hosts.append(builder.make_bucket(m, BUCKET_STRAW2, 1,
                                             items, w))
        roots.append(builder.make_bucket(m, BUCKET_STRAW2, 10,
                                         [h.id for h in hosts],
                                         [h.weight for h in hosts]))
    m.add_rule(Rule(0, 1, 1, 10, [
        RuleStep(RULE_TAKE, roots[0].id),
        RuleStep(RULE_CHOOSELEAF_FIRSTN, 2, 1),
        RuleStep(RULE_EMIT),
        RuleStep(RULE_TAKE, roots[1].id),
        RuleStep(RULE_CHOOSELEAF_FIRSTN, 2, 1),
        RuleStep(RULE_EMIT)]))
    m.add_rule(Rule(1, 3, 1, 10, [
        RuleStep(RULE_TAKE, roots[0].id),
        RuleStep(RULE_CHOOSELEAF_INDEP, 2, 1),
        RuleStep(RULE_EMIT),
        RuleStep(RULE_TAKE, roots[1].id),
        RuleStep(RULE_CHOOSELEAF_INDEP, 2, 1),
        RuleStep(RULE_EMIT)]))
    weight = [0x10000] * osd
    weight[2] = 0
    weight[12] = 0xA000
    return m, [(0, 4, weight, 512), (1, 4, weight, 512)]


def all_runs():
    """Yield (scenario_index, map, ruleno, result_max, weight, nx)."""
    runs = []
    m, rr = scen_a()
    for r in rr:
        runs.append((m, *r))
    m, rr, lcg = scen_bc()
    for r in rr:
        runs.append((m, *r))
    m, rr = scen_d(lcg)
    for r in rr:
        runs.append((m, *r))
    m, rr = scen_e(lcg)
    for r in rr:
        runs.append((m, *r))
    m, rr = scen_f()
    for r in rr:
        runs.append((m, *r))
    m, rr = scen_g(lcg)
    for r in rr:
        runs.append((m, *r))
    m, rr = scen_h(lcg)
    for r in rr:
        runs.append((m, *r))
    return runs


NAMES = ["A:flat-straw2", "B:chooseleaf-firstn", "C:chooseleaf-indep",
         "D:all-algs", "E:legacy-straw", "F:32x4-repl", "F:32x4-ec-indep",
         "G:3level-firstn", "G:3level-indep", "G:3level-rackleaf",
         "H:multitake-firstn", "H:multitake-indep"]

#: scenarios the BATCHED kernel must accept (no scalar fallback): the
#: generalized depth/multi-take coverage (VERDICT r4 ask #3)
BATCHABLE = {1, 2, 5, 6, 7, 8, 9, 10, 11}


@pytest.mark.parametrize("idx", range(12), ids=NAMES)
def test_do_rule_matches_reference(idx):
    runs = all_runs()
    m, ruleno, result_max, weight, nx = runs[idx]
    expect = GOLDEN["scenarios"][idx]
    assert len(expect) == nx
    for x in range(nx):
        got = do_rule(m, ruleno, x, result_max, weight)
        assert got == expect[x], (
            f"scenario {NAMES[idx]} x={x}: got {got} want {expect[x]}")


@pytest.mark.parametrize("idx", sorted(BATCHABLE), ids=[
    NAMES[i] for i in sorted(BATCHABLE)])
def test_batched_kernel_matches_reference(idx):
    """The vectorized kernel (not just the scalar mapper) reproduces the
    reference C outputs verbatim, and compile_rule must NOT fall back
    for these production shapes."""
    from ceph_tpu.ops.crush_kernel import batch_do_rule, compile_rule
    runs = all_runs()
    m, ruleno, result_max, weight, nx = runs[idx]
    assert compile_rule(m, ruleno) is not None, \
        f"scenario {NAMES[idx]} lost the batched path"
    expect = GOLDEN["scenarios"][idx]
    got = batch_do_rule(m, ruleno, list(range(nx)), result_max, weight,
                        engine="host")
    assert got == expect, f"scenario {NAMES[idx]} batched != reference"
