"""OSDMap tests: placement pipeline, incrementals, overrides.

Models reference test/osd/TestOSDMap.cc: build a map, map pgs, kill osds,
check up/acting behavior for replicated (shifting) and EC (positional)
pools, pg_temp overrides, primary affinity, encode round-trips.
"""

import pytest

from ceph_tpu.crush.builder import (build_hierarchy, make_erasure_rule,
                                    make_replicated_rule)
from ceph_tpu.crush.constants import CRUSH_ITEM_NONE
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.msg.types import EntityAddr
from ceph_tpu.osd.osdmap import Incremental, OSDMap
from ceph_tpu.osd.types import (
    OSD_IN_WEIGHT, OSD_UP, ObjectLocator, PGId, PGPool,
    POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED, ceph_stable_mod,
)

N_OSDS = 12
OSDS_PER_HOST = 2


def build_map(n_osds=N_OSDS) -> OSDMap:
    m = OSDMap()
    m.fsid = "test-fsid"
    crush = CrushMap()
    crush.max_devices = n_osds
    build_hierarchy(crush, n_osds, OSDS_PER_HOST)
    rep_rule = make_replicated_rule(crush, "replicated_rule")
    ec_rule = make_erasure_rule(crush, "ec_rule", size=6)
    m.crush = crush
    m.set_max_osd(n_osds)
    inc = Incremental(1)
    for o in range(n_osds):
        inc.new_up[o] = EntityAddr("127.0.0.1", 6800 + o, o + 1)
        inc.new_weight[o] = OSD_IN_WEIGHT
    m.apply_incremental(inc)
    m.pools[1] = PGPool(POOL_TYPE_REPLICATED, size=3,
                        crush_ruleset=rep_rule, pg_num=32)
    m.pool_names[1] = "rbd"
    m.pools[2] = PGPool(POOL_TYPE_ERASURE, size=6, min_size=5,
                        crush_ruleset=ec_rule, pg_num=32,
                        ec_profile="k4m2")
    m.pool_names[2] = "ecpool"
    return m


def mark_down(m: OSDMap, osd: int) -> None:
    inc = Incremental(m.epoch + 1)
    inc.new_state[osd] = OSD_UP
    m.apply_incremental(inc)


def host_of(osd: int) -> int:
    return osd // OSDS_PER_HOST


def test_stable_mod():
    # include/rados.h:84 semantics
    assert ceph_stable_mod(11, 12, 15) == 11
    assert ceph_stable_mod(13, 12, 15) == 5
    for x in range(200):
        v = ceph_stable_mod(x, 12, 15)
        assert 0 <= v < 12


def test_basic_state():
    m = build_map()
    assert m.epoch == 1
    assert m.count_up() == N_OSDS
    assert all(m.is_in(o) for o in range(N_OSDS))
    assert m.get_addr(3).port == 6803
    mark_down(m, 3)
    assert not m.is_up(3)
    assert m.is_in(3)       # down but still in
    assert m.exists(3)
    assert m.osd_info[3].down_at == m.epoch


def test_replicated_placement_properties():
    m = build_map()
    seen = set()
    for pg in m.pg_ids(1):
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
        assert len(up) == 3
        assert len(set(up)) == 3
        # chooseleaf: one osd per host
        assert len({host_of(o) for o in up}) == 3
        assert upp == up[0] and actp == acting[0]
        assert acting == up      # no overrides yet
        seen.update(up)
    assert len(seen) > N_OSDS // 2   # spread across the cluster


def test_placement_deterministic_and_stable():
    m = build_map()
    a = [m.pg_to_up_acting_osds(pg) for pg in m.pg_ids(1)]
    b = [m.pg_to_up_acting_osds(pg) for pg in m.pg_ids(1)]
    assert a == b
    m2 = OSDMap.from_bytes(build_map().to_bytes())
    c = [m2.pg_to_up_acting_osds(pg) for pg in m2.pg_ids(1)]
    assert a == c


def test_object_to_pg_mapping():
    m = build_map()
    loc = ObjectLocator(pool=1)
    pg, acting, primary = m.object_to_acting("myobject", loc)
    assert pg.pool == 1 and 0 <= pg.seed < 32
    assert primary == acting[0]
    # locator key overrides object name
    loc_k = ObjectLocator(pool=1, key="myobject")
    pg2, _, _ = m.object_to_acting("othername", loc_k)
    assert pg2 == pg
    # namespace changes the hash
    loc_ns = ObjectLocator(pool=1, namespace="ns1")
    pg3, _, _ = m.object_to_acting("myobject", loc_ns)
    assert (pg3.seed != pg.seed) or True  # may collide, but computed path


def test_replicated_osd_down_then_out():
    m = build_map()
    target = m.pg_ids(1)[0]
    up0, _, _, _ = m.pg_to_up_acting_osds(target)
    victim = up0[1]
    # down-but-in: crush still maps to it; the up set just shrinks
    # (reference: _raw_to_up_osds filters down osds; remap waits for OUT)
    mark_down(m, victim)
    up1, _, _, _ = m.pg_to_up_acting_osds(target)
    assert victim not in up1
    assert up1 == [o for o in up0 if o != victim]
    # marking it OUT makes crush reject it and find a replacement
    inc = Incremental(m.epoch + 1)
    inc.new_weight[victim] = 0
    m.apply_incremental(inc)
    up2, _, _, _ = m.pg_to_up_acting_osds(target)
    assert victim not in up2
    assert len(up2) == 3
    assert set(up0) - {victim} <= set(up2)   # survivors keep membership


def test_ec_down_is_positional():
    m = build_map()
    for pg in m.pg_ids(2)[:8]:
        up0, _, _, _ = m.pg_to_up_acting_osds(pg)
        assert len(up0) == 6 and CRUSH_ITEM_NONE not in up0
        victim_pos = 2
        victim = up0[victim_pos]
        mark_down(m, victim)
        up1, _, _, _ = m.pg_to_up_acting_osds(pg)
        assert len(up1) == 6
        # indep: non-failed positions unchanged
        for i in range(6):
            if i != victim_pos:
                assert up1[i] == up0[i], (pg, i, up0, up1)
        assert up1[victim_pos] != victim
        # bring back for next iteration
        inc = Incremental(m.epoch + 1)
        inc.new_up[victim] = EntityAddr("127.0.0.1", 6800 + victim,
                                        victim + 100)
        m.apply_incremental(inc)


def test_out_osd_gets_nothing():
    m = build_map()
    inc = Incremental(m.epoch + 1)
    inc.new_weight[5] = 0    # reweight out
    m.apply_incremental(inc)
    assert m.is_out(5)
    for pool in (1, 2):
        for pg in m.pg_ids(pool):
            up, _, _, _ = m.pg_to_up_acting_osds(pg)
            assert 5 not in up


def test_pg_temp_override():
    m = build_map()
    pg = m.pg_ids(1)[3]
    up, upp, acting0, _ = m.pg_to_up_acting_osds(pg)
    override = [o for o in range(N_OSDS) if o not in up][:3]
    inc = Incremental(m.epoch + 1)
    inc.new_pg_temp[pg] = override
    m.apply_incremental(inc)
    up1, _, acting1, actp1 = m.pg_to_up_acting_osds(pg)
    assert up1 == up            # up unchanged
    assert acting1 == override  # acting overridden
    assert actp1 == override[0]
    # removal restores crush mapping
    inc2 = Incremental(m.epoch + 1)
    inc2.new_pg_temp[pg] = []
    m.apply_incremental(inc2)
    _, _, acting2, _ = m.pg_to_up_acting_osds(pg)
    assert acting2 == acting0


def test_primary_temp_override():
    m = build_map()
    pg = m.pg_ids(1)[4]
    _, _, acting, _ = m.pg_to_up_acting_osds(pg)
    inc = Incremental(m.epoch + 1)
    inc.new_primary_temp[pg] = acting[2]
    m.apply_incremental(inc)
    _, _, _, actp = m.pg_to_up_acting_osds(pg)
    assert actp == acting[2]


def test_primary_affinity_zero_demotes():
    m = build_map()
    # find a pg where osd 0 is primary
    pgs = [pg for pg in m.pg_ids(1)
           if m.pg_to_up_acting_osds(pg)[1] == 0]
    assert pgs, "osd 0 should be primary somewhere in 32 pgs"
    inc = Incremental(m.epoch + 1)
    inc.new_primary_affinity[0] = 0
    m.apply_incremental(inc)
    for pg in pgs:
        up, upp, _, _ = m.pg_to_up_acting_osds(pg)
        assert upp != 0          # fully demoted
        assert 0 in up           # still serves as replica
        assert upp == up[0]      # replicated pools shift primary to front


def test_pool_delete():
    m = build_map()
    inc = Incremental(m.epoch + 1)
    inc.old_pools.append(1)
    m.apply_incremental(inc)
    assert m.get_pool(1) is None
    assert m.lookup_pool("rbd") == -1
    assert m.pg_to_up_acting_osds(PGId(1, 0)) == ([], -1, [], -1)


def test_osdmap_roundtrip():
    m = build_map()
    mark_down(m, 7)
    inc = Incremental(m.epoch + 1)
    inc.new_pg_temp[PGId(1, 5)] = [0, 2, 4]
    inc.new_primary_affinity[1] = 0x8000
    m.apply_incremental(inc)
    m2 = OSDMap.from_bytes(m.to_bytes())
    assert m2.epoch == m.epoch
    assert m2.summary() == m.summary()
    for pool in (1, 2):
        for pg in m.pg_ids(pool):
            assert (m2.pg_to_up_acting_osds(pg)
                    == m.pg_to_up_acting_osds(pg))


def test_incremental_roundtrip():
    inc = Incremental(5)
    inc.new_pools[9] = PGPool(POOL_TYPE_ERASURE, size=6, pg_num=64,
                              ec_profile="p")
    inc.new_pool_names[9] = "x"
    inc.new_up[3] = EntityAddr("10.0.0.1", 6801, 44)
    inc.new_state[2] = OSD_UP
    inc.new_weight[2] = 1234
    inc.new_pg_temp[PGId(9, 1)] = [1, 2, 3]
    inc.new_primary_temp[PGId(9, 2)] = 7
    inc.new_up_thru[3] = 4
    inc2 = Incremental.from_bytes(inc.to_bytes())
    assert inc2.epoch == 5
    assert inc2.new_pools[9].pg_num == 64
    assert inc2.new_up[3].port == 6801
    assert inc2.new_pg_temp[PGId(9, 1)] == [1, 2, 3]
    assert inc2.new_primary_temp[PGId(9, 2)] == 7
    assert inc2.new_up_thru[3] == 4


def test_epoch_ordering_enforced():
    m = build_map()
    with pytest.raises(AssertionError):
        m.apply_incremental(Incremental(m.epoch + 2))
