"""RGW-lite S3 gateway: bucket/object REST surface + v2 auth.

Mirrors the reference's s3tests role (qa s3-tests subset): bucket CRUD,
object round-trips with ETag, listing with prefix, range reads, auth
rejection — all against a live in-process cluster and a real HTTP
socket.
"""

import asyncio
import hashlib
import sys
from email.utils import formatdate

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.services.rgw import S3Gateway, UserDB, sign_v2  # noqa: E402


class S3Client:
    """Tiny raw-socket S3 client speaking signature v2."""

    def __init__(self, port, access="", secret=""):
        self.port = port
        self.access = access
        self.secret = secret

    async def request(self, method, path, body=b"", headers=None,
                      sign=True):
        headers = dict(headers or {})
        headers.setdefault("Date", formatdate(usegmt=True))
        if sign and self.access:
            sig = sign_v2(self.secret, method,
                          headers.get("Content-MD5", ""),
                          headers.get("Content-Type", ""),
                          headers["Date"], path.split("?")[0])
            headers["Authorization"] = f"AWS {self.access}:{sig}"
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       self.port)
        try:
            lines = [f"{method} {path} HTTP/1.1", "Host: localhost",
                     f"Content-Length: {len(body)}",
                     "Connection: close"]
            lines += [f"{k}: {v}" for k, v in headers.items()]
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
            await writer.drain()
            status_line = await reader.readline()
            status = int(status_line.split()[1])
            rhdrs = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode().partition(":")
                rhdrs[k.strip().lower()] = v.strip()
            n = int(rhdrs.get("content-length", "0"))
            payload = await reader.readexactly(n) if n else b""
            return status, rhdrs, payload
        finally:
            writer.close()


def test_s3_gateway_end_to_end():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        await UserDB(admin.open_ioctx(".rgw")).create("AKID", "sekrit")
        port = await gw.start()
        c = S3Client(port, "AKID", "sekrit")

        # unauthenticated / bad-signature requests are refused
        st, _, _ = await S3Client(port).request("GET", "/", sign=False)
        assert st == 403
        st, _, _ = await S3Client(port, "AKID", "wrong").request("GET", "/")
        assert st == 403

        # bucket lifecycle
        st, _, _ = await c.request("PUT", "/photos")
        assert st == 200
        st, _, _ = await c.request("PUT", "/photos")
        assert st == 409                        # exists
        st, _, body = await c.request("GET", "/")
        assert st == 200 and b"<Name>photos</Name>" in body

        # object round-trip with etag
        payload = b"s3 object payload " * 5000       # ~90 KiB, striped
        st, h, _ = await c.request("PUT", "/photos/album/pic1.jpg",
                                   payload)
        assert st == 200
        assert h["etag"].strip('"') == hashlib.md5(payload).hexdigest()
        st, h, got = await c.request("GET", "/photos/album/pic1.jpg")
        assert st == 200 and got == payload

        # range read
        st, h, got = await c.request("GET", "/photos/album/pic1.jpg",
                                     headers={"Range": "bytes=10-29"})
        assert st == 206 and got == payload[10:30]
        assert h["content-range"] == f"bytes 10-29/{len(payload)}"

        # listing + prefix filter
        await c.request("PUT", "/photos/album/pic2.jpg", b"x")
        await c.request("PUT", "/photos/other.txt", b"y")
        st, _, body = await c.request("GET", "/photos?prefix=album/")
        assert st == 200
        assert b"pic1.jpg" in body and b"pic2.jpg" in body
        assert b"other.txt" not in body

        # head / delete
        st, _, _ = await c.request("HEAD", "/photos/other.txt")
        assert st == 200
        st, _, _ = await c.request("DELETE", "/photos/other.txt")
        assert st == 204
        st, _, _ = await c.request("HEAD", "/photos/other.txt")
        assert st == 404

        # bucket with content refuses delete; empty deletes
        st, _, _ = await c.request("DELETE", "/photos")
        assert st == 409
        for k in ("album/pic1.jpg", "album/pic2.jpg"):
            await c.request("DELETE", f"/photos/{k}")
        st, _, _ = await c.request("DELETE", "/photos")
        assert st == 204

        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_s3_overwrite_and_missing():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)
        await c.request("PUT", "/b", sign=False)
        # overwrite shrinks: no stale tail from the previous version
        await c.request("PUT", "/b/k", b"A" * 50000, sign=False)
        await c.request("PUT", "/b/k", b"B" * 100, sign=False)
        st, _, got = await c.request("GET", "/b/k", sign=False)
        assert st == 200 and got == b"B" * 100
        st, _, _ = await c.request("GET", "/b/missing", sign=False)
        assert st == 404
        st, _, _ = await c.request("GET", "/nobucket?list", sign=False)
        assert st == 404
        await gw.stop()
        await cl.stop()
    asyncio.run(run())
