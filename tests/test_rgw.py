"""RGW-lite S3 gateway: bucket/object REST surface + v2 auth.

Mirrors the reference's s3tests role (qa s3-tests subset): bucket CRUD,
object round-trips with ETag, listing with prefix, range reads, auth
rejection — all against a live in-process cluster and a real HTTP
socket.
"""

import asyncio
import hashlib
import sys
from email.utils import formatdate

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.services.rgw import S3Gateway, UserDB, sign_v2  # noqa: E402


class S3Client:
    """Tiny raw-socket S3 client speaking signature v2."""

    def __init__(self, port, access="", secret=""):
        self.port = port
        self.access = access
        self.secret = secret

    async def request(self, method, path, body=b"", headers=None,
                      sign=True):
        headers = dict(headers or {})
        headers.setdefault("Date", formatdate(usegmt=True))
        if sign and self.access:
            from ceph_tpu.services.rgw import v2_canonical_resource
            p, _, q = path.partition("?")
            sig = sign_v2(self.secret, method,
                          headers.get("Content-MD5", ""),
                          headers.get("Content-Type", ""),
                          headers["Date"],
                          v2_canonical_resource(p, q))
            headers["Authorization"] = f"AWS {self.access}:{sig}"
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       self.port)
        try:
            lines = [f"{method} {path} HTTP/1.1", "Host: localhost",
                     f"Content-Length: {len(body)}",
                     "Connection: close"]
            lines += [f"{k}: {v}" for k, v in headers.items()]
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
            await writer.drain()
            status_line = await reader.readline()
            status = int(status_line.split()[1])
            rhdrs = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode().partition(":")
                rhdrs[k.strip().lower()] = v.strip()
            n = int(rhdrs.get("content-length", "0"))
            payload = await reader.readexactly(n) if n else b""
            return status, rhdrs, payload
        finally:
            writer.close()


def test_s3_gateway_end_to_end():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        await UserDB(admin.open_ioctx(".rgw")).create("AKID", "sekrit")
        port = await gw.start()
        c = S3Client(port, "AKID", "sekrit")

        # unauthenticated / bad-signature requests are refused
        st, _, _ = await S3Client(port).request("GET", "/", sign=False)
        assert st == 403
        st, _, _ = await S3Client(port, "AKID", "wrong").request("GET", "/")
        assert st == 403

        # bucket lifecycle
        st, _, _ = await c.request("PUT", "/photos")
        assert st == 200
        st, _, _ = await c.request("PUT", "/photos")
        assert st == 409                        # exists
        st, _, body = await c.request("GET", "/")
        assert st == 200 and b"<Name>photos</Name>" in body

        # object round-trip with etag
        payload = b"s3 object payload " * 5000       # ~90 KiB, striped
        st, h, _ = await c.request("PUT", "/photos/album/pic1.jpg",
                                   payload)
        assert st == 200
        assert h["etag"].strip('"') == hashlib.md5(payload).hexdigest()
        st, h, got = await c.request("GET", "/photos/album/pic1.jpg")
        assert st == 200 and got == payload

        # range read
        st, h, got = await c.request("GET", "/photos/album/pic1.jpg",
                                     headers={"Range": "bytes=10-29"})
        assert st == 206 and got == payload[10:30]
        assert h["content-range"] == f"bytes 10-29/{len(payload)}"

        # listing + prefix filter
        await c.request("PUT", "/photos/album/pic2.jpg", b"x")
        await c.request("PUT", "/photos/other.txt", b"y")
        st, _, body = await c.request("GET", "/photos?prefix=album/")
        assert st == 200
        assert b"pic1.jpg" in body and b"pic2.jpg" in body
        assert b"other.txt" not in body

        # head / delete
        st, _, _ = await c.request("HEAD", "/photos/other.txt")
        assert st == 200
        st, _, _ = await c.request("DELETE", "/photos/other.txt")
        assert st == 204
        st, _, _ = await c.request("HEAD", "/photos/other.txt")
        assert st == 404

        # bucket with content refuses delete; empty deletes
        st, _, _ = await c.request("DELETE", "/photos")
        assert st == 409
        for k in ("album/pic1.jpg", "album/pic2.jpg"):
            await c.request("DELETE", f"/photos/{k}")
        st, _, _ = await c.request("DELETE", "/photos")
        assert st == 204

        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_s3_overwrite_and_missing():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)
        await c.request("PUT", "/b", sign=False)
        # overwrite shrinks: no stale tail from the previous version
        await c.request("PUT", "/b/k", b"A" * 50000, sign=False)
        await c.request("PUT", "/b/k", b"B" * 100, sign=False)
        st, _, got = await c.request("GET", "/b/k", sign=False)
        assert st == 200 and got == b"B" * 100
        st, _, _ = await c.request("GET", "/b/missing", sign=False)
        assert st == 404
        st, _, _ = await c.request("GET", "/nobucket?list", sign=False)
        assert st == 404
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_multipart_upload_round_trip():
    """rgw_multi.cc role: init -> 6 parts -> ListParts -> Complete
    (manifest, no copy) -> GET whole + ranges across part seams ->
    overwrite cleans old parts; plus abort and error paths."""
    import re

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        await UserDB(admin.open_ioctx(".rgw")).create("AKID", "sekrit")
        port = await gw.start()
        c = S3Client(port, "AKID", "sekrit")

        st, _, _ = await c.request("PUT", "/mp")
        assert st == 200

        # init
        st, _, body = await c.request("POST", "/mp/big?uploads")
        assert st == 200, body
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()

        # upload 6 parts of distinct content/pattern sizes
        parts = [bytes([i]) * (1000 + 137 * i) for i in range(1, 7)]
        etags = []
        for i, data in enumerate(parts, 1):
            st, h, _ = await c.request(
                "PUT", f"/mp/big?partNumber={i}&uploadId={upload_id}",
                body=data)
            assert st == 200
            etags.append(h["etag"].strip('"'))
            assert etags[-1] == hashlib.md5(data).hexdigest()

        # re-upload part 3 with different bytes (replace semantics)
        parts[2] = b"\xAB" * 1999
        st, h, _ = await c.request(
            "PUT", f"/mp/big?partNumber=3&uploadId={upload_id}",
            body=parts[2])
        assert st == 200
        etags[2] = h["etag"].strip('"')

        # ListParts shows all six with sizes
        st, _, body = await c.request(
            "GET", f"/mp/big?uploadId={upload_id}")
        assert st == 200
        assert body.count(b"<Part>") == 6
        assert f"<Size>{len(parts[2])}</Size>".encode() in body

        # complete (client lists all 6 in order)
        comp = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{i}</PartNumber>"
            f'<ETag>"{etags[i - 1]}"</ETag></Part>'
            for i in range(1, 7)) + "</CompleteMultipartUpload>"
        st, _, body = await c.request(
            "POST", f"/mp/big?uploadId={upload_id}", body=comp.encode())
        assert st == 200, body
        md5s = b"".join(bytes.fromhex(e) for e in etags)
        want_etag = f"{hashlib.md5(md5s).hexdigest()}-6"
        assert want_etag.encode() in body

        # the upload is gone (complete is terminal)
        st, _, _ = await c.request("GET", f"/mp/big?uploadId={upload_id}")
        assert st == 404

        # whole-object GET equals the concatenation
        full = b"".join(parts)
        st, h, got = await c.request("GET", "/mp/big")
        assert st == 200 and got == full
        assert h["etag"].strip('"') == want_etag

        # range read across the part-1/part-2 seam and a suffix range
        lo, hi = len(parts[0]) - 10, len(parts[0]) + 9
        st, _, got = await c.request(
            "GET", "/mp/big", headers={"Range": f"bytes={lo}-{hi}"})
        assert st == 206 and got == full[lo:hi + 1]
        st, _, got = await c.request(
            "GET", "/mp/big", headers={"Range": "bytes=-25"})
        assert st == 206 and got == full[-25:]

        # listing shows the completed object with the multipart size
        st, _, body = await c.request("GET", "/mp")
        assert f"<Size>{len(full)}</Size>".encode() in body

        # overwrite with a simple PUT removes manifest parts, reads back
        st, _, _ = await c.request("PUT", "/mp/big", body=b"tiny")
        assert st == 200
        st, _, got = await c.request("GET", "/mp/big")
        assert got == b"tiny"

        # abort path: init + one part + abort -> NoSuchUpload afterwards
        st, _, body = await c.request("POST", "/mp/die?uploads")
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()
        await c.request("PUT", f"/mp/die?partNumber=1&uploadId={upload_id}",
                        body=b"x" * 100)
        st, _, _ = await c.request(
            "DELETE", f"/mp/die?uploadId={upload_id}")
        assert st == 204
        st, _, _ = await c.request("GET", f"/mp/die?uploadId={upload_id}")
        assert st == 404

        # completing with a wrong etag is InvalidPart
        st, _, body = await c.request("POST", "/mp/bad?uploads")
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()
        await c.request("PUT", f"/mp/bad?partNumber=1&uploadId={upload_id}",
                        body=b"data")
        comp = ("<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
                f'<ETag>"{"0" * 32}"</ETag></Part>'
                "</CompleteMultipartUpload>")
        st, _, body = await c.request(
            "POST", f"/mp/bad?uploadId={upload_id}", body=comp.encode())
        assert st == 400 and b"InvalidPart" in body

        await gw.stop()
        await cl.stop()
    asyncio.run(run())


# --------------------------------------------------------------- SigV4

def test_sigv4_matches_aws_documented_vector():
    """The worked example from the AWS docs ('Authenticating Requests:
    Using the Authorization Header' — GET /test.txt on examplebucket,
    20130524): our signer must reproduce the documented signature
    byte-for-byte."""
    from ceph_tpu.services.rgw import sign_v4
    secret = "wJalrXUtnFEMI/K7MDENG/bPxRfiCYEXAMPLEKEY"
    headers = {
        "host": "examplebucket.s3.amazonaws.com",
        "range": "bytes=0-9",
        "x-amz-content-sha256": "e3b0c44298fc1c149afbf4c8996fb92427ae41"
                                "e4649b934ca495991b7852b855",
        "x-amz-date": "20130524T000000Z",
    }
    sig = sign_v4(
        secret, "GET", "/test.txt", "", headers,
        ["host", "range", "x-amz-content-sha256", "x-amz-date"],
        "20130524T000000Z", "20130524/us-east-1/s3/aws4_request",
        headers["x-amz-content-sha256"])
    assert sig == ("f0e8bdb87c964420e857bd35b5d6ed310bd44f0170aba48dd9"
                   "1039c6036bdb41")


def test_sigv4_chunk_signature_matches_aws_documented_vector():
    """Chunked-upload seed + first-chunk signatures from the AWS docs
    ('Example: PUT with chunked transfer' — 65536 bytes of 'a')."""
    from ceph_tpu.services.rgw import sign_v4, v4_chunk_signature
    secret = "wJalrXUtnFEMI/K7MDENG/bPxRfiCYEXAMPLEKEY"
    amz_date = "20130524T000000Z"
    scope = "20130524/us-east-1/s3/aws4_request"
    headers = {
        "content-encoding": "aws-chunked",
        "content-length": "66824",
        "host": "s3.amazonaws.com",
        "x-amz-content-sha256": "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
        "x-amz-date": amz_date,
        "x-amz-decoded-content-length": "66560",
        "x-amz-storage-class": "REDUCED_REDUNDANCY",
    }
    seed = sign_v4(
        secret, "PUT", "/examplebucket/chunkObject.txt", "", headers,
        ["content-encoding", "content-length", "host",
         "x-amz-content-sha256", "x-amz-date",
         "x-amz-decoded-content-length", "x-amz-storage-class"],
        amz_date, scope, "STREAMING-AWS4-HMAC-SHA256-PAYLOAD")
    assert seed == ("4f232c4386841ef735655705268965c44a0e4690baa4adea1"
                    "53f7db9fa80a0a9")
    c1 = v4_chunk_signature(secret, scope, amz_date, seed, b"a" * 65536)
    assert c1 == ("ad80c730a21e5b8d04586a2213dd63b9a0e99e0e2307b0ade3"
                  "5a65485a288648")


class _V4Client(S3Client):
    """Test client signing with SigV4 headers (optionally chunked)."""

    REGION = "us-east-1"

    async def request(self, method, path, body=b"", headers=None,
                      sign=True, chunked=0):
        import time as _time
        from ceph_tpu.services.rgw import (_sha256_hex, sign_v4,
                                           v4_chunk_signature)
        headers = dict(headers or {})
        if not (sign and self.access):
            return await super().request(method, path, body, headers,
                                         sign=False)
        amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
        date = amz_date[:8]
        scope = f"{date}/{self.REGION}/s3/aws4_request"
        p, _, q = path.partition("?")
        headers["host"] = "localhost"
        headers["x-amz-date"] = amz_date
        if chunked:
            headers["x-amz-content-sha256"] = \
                "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
            headers["x-amz-decoded-content-length"] = str(len(body))
        else:
            headers["x-amz-content-sha256"] = _sha256_hex(body)
        signed = sorted(h.lower() for h in headers)
        sig = sign_v4(self.secret, method, p, q, {
            k.lower(): v for k, v in headers.items()}, signed,
            amz_date, scope, headers["x-amz-content-sha256"])
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        if chunked:
            framed = bytearray()
            prev = sig
            pieces = [body[off:off + chunked]
                      for off in range(0, len(body), chunked)]
            pieces.append(b"")          # signed terminal 0-byte chunk
            for piece in pieces:
                csig = v4_chunk_signature(self.secret, scope, amz_date,
                                          prev, piece)
                framed += (f"{len(piece):x};chunk-signature={csig}"
                           "\r\n").encode() + piece + b"\r\n"
                prev = csig
            body = bytes(framed)
        return await super().request(method, path, body, headers,
                                     sign=False)


def test_sigv4_end_to_end_put_get_multipart():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        port = await gw.start()
        await UserDB(gw.io).create("AK4", "SK4SECRET")
        c = _V4Client(port, "AK4", "SK4SECRET")
        assert (await c.request("PUT", "/b4"))[0] == 200
        payload = bytes(range(256)) * 40
        st, _, _ = await c.request("PUT", "/b4/obj", payload)
        assert st == 200
        st, _, got = await c.request("GET", "/b4/obj")
        assert st == 200 and got == payload
        # tampered payload (signed hash covers different bytes) refuses
        st2, _, _ = await _tampered_put(c, "/b4/evil2", payload)
        assert st2 == 403
        # multipart through v4
        st, _, out = await c.request("POST", "/b4/big?uploads", b"")
        assert st == 200
        upload_id = out.decode().split("<UploadId>")[1] \
                       .split("</UploadId>")[0]
        st, h, _ = await c.request(
            "PUT", f"/b4/big?uploadId={upload_id}&partNumber=1",
            b"A" * 5000)
        assert st == 200
        comp = ("<CompleteMultipartUpload><Part><PartNumber>1"
                "</PartNumber><ETag>" + h["etag"].strip('"')
                + "</ETag></Part></CompleteMultipartUpload>")
        st, _, _ = await c.request(
            "POST", f"/b4/big?uploadId={upload_id}", comp.encode())
        assert st == 200
        st, _, got = await c.request("GET", "/b4/big")
        assert st == 200 and got == b"A" * 5000
        # v2 still works against the same gateway
        c2 = S3Client(port, "AK4", "SK4SECRET")
        st, _, got = await c2.request("GET", "/b4/obj")
        assert st == 200 and got == payload
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


async def _tampered_put(c, path, payload):
    """Sign a v4 PUT whose x-amz-content-sha256 covers different bytes
    than the body actually sent."""
    import time as _time
    from ceph_tpu.services.rgw import _sha256_hex, sign_v4
    amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
    scope = f"{amz_date[:8]}/us-east-1/s3/aws4_request"
    headers = {"host": "localhost", "x-amz-date": amz_date,
               "x-amz-content-sha256": _sha256_hex(b"not the payload")}
    signed = sorted(headers)
    sig = sign_v4(c.secret, "PUT", path, "", headers, signed, amz_date,
                  scope, headers["x-amz-content-sha256"])
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={c.access}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return await S3Client.request(c, "PUT", path, payload, headers,
                                 sign=False)


def test_sigv4_chunked_upload_end_to_end():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        port = await gw.start()
        await UserDB(gw.io).create("AKC", "SKCSECRET")
        c = _V4Client(port, "AKC", "SKCSECRET")
        assert (await c.request("PUT", "/bc"))[0] == 200
        payload = bytes((i * 37) & 0xFF for i in range(50000))
        st, _, _ = await c.request("PUT", "/bc/obj", payload,
                                   chunked=16384)
        assert st == 200
        st, _, got = await c.request("GET", "/bc/obj")
        assert st == 200 and got == payload, "chunked body mis-decoded"
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_v2_signature_covers_subresources():
    """ADVICE r4: a v2 signature over /bucket/key must not replay as a
    different subresource op on the same path."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        port = await gw.start()
        await UserDB(gw.io).create("AKR", "SKRSECRET")
        c = S3Client(port, "AKR", "SKRSECRET")
        assert (await c.request("PUT", "/br"))[0] == 200
        # sign a plain POST /br/key, replay it as ?uploads
        from email.utils import formatdate as _fd
        date = _fd(usegmt=True)
        sig = sign_v2("SKRSECRET", "POST", "", "", date, "/br/key")
        headers = {"Date": date,
                   "Authorization": f"AWS AKR:{sig}"}
        st, _, _ = await c.request("POST", "/br/key?uploads", b"",
                                   headers=headers, sign=False)
        assert st == 403, "v2 replay across subresources was accepted"
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_chunked_truncation_at_boundary_rejected():
    """A stream ending at a chunk boundary WITHOUT the signed terminal
    0-chunk must be refused (truncation attack)."""
    from ceph_tpu.services.rgw import (decode_aws_chunked, sign_v4,
                                       v4_chunk_signature)
    secret, scope, amz = "s", "20130524/us-east-1/s3/aws4_request", \
        "20130524T000000Z"
    seed = "0" * 64
    data = b"x" * 100
    sig = v4_chunk_signature(secret, scope, amz, seed, data)
    framed = (f"64;chunk-signature={sig}\r\n").encode() + data + b"\r\n"
    # no terminal chunk: refused
    assert decode_aws_chunked(framed, secret, scope, amz, seed) is None
    # with the terminal chunk: accepted
    fin = v4_chunk_signature(secret, scope, amz, sig, b"")
    full = framed + (f"0;chunk-signature={fin}\r\n\r\n").encode()
    assert decode_aws_chunked(full, secret, scope, amz, seed) == data


def test_swift_dialect_end_to_end():
    """Swift REST personality over the same store (rgw_rest_swift.cc /
    tempauth): token auth, containers, objects, json listings."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        port = await gw.start()
        await UserDB(gw.io).create("swiftop", "swsecret")
        c = S3Client(port)

        # bad creds refused; good creds issue a token
        st, _, _ = await c.request(
            "GET", "/auth/v1.0", sign=False,
            headers={"X-Auth-User": "swiftop", "X-Auth-Key": "wrong"})
        assert st == 401
        st, h, _ = await c.request(
            "GET", "/auth/v1.0", sign=False,
            headers={"X-Auth-User": "swiftop", "X-Auth-Key": "swsecret"})
        assert st == 204 and h["x-auth-token"].startswith("AUTH_tk")
        tok = {"X-Auth-Token": h["x-auth-token"]}

        # tokenless access refused
        st, _, _ = await c.request("GET", "/swift/v1", sign=False)
        assert st == 401

        # container lifecycle
        st, _, _ = await c.request("PUT", "/swift/v1/media", sign=False,
                                   headers=tok)
        assert st == 201
        st, _, _ = await c.request("PUT", "/swift/v1/media", sign=False,
                                   headers=tok)
        assert st == 202                      # exists: Accepted
        st, _, body = await c.request("GET", "/swift/v1?format=json",
                                      sign=False, headers=tok)
        import json as _json
        assert st == 200 and {"name": "media"} in _json.loads(body)

        # object round-trip
        payload = b"swift bytes " * 3000
        st, h, _ = await c.request("PUT", "/swift/v1/media/a/b.bin",
                                   payload, sign=False, headers=tok)
        assert st == 201
        assert h["etag"] == hashlib.md5(payload).hexdigest()
        st, _, got = await c.request("GET", "/swift/v1/media/a/b.bin",
                                     sign=False, headers=tok)
        assert st == 200 and got == payload
        # listing with prefix, json format
        st, _, body = await c.request(
            "GET", "/swift/v1/media?format=json&prefix=a/",
            sign=False, headers=tok)
        rows = _json.loads(body)
        assert rows and rows[0]["name"] == "a/b.bin" \
            and rows[0]["bytes"] == len(payload)
        # the S3 personality sees the same object (same user, same
        # credentials — ownership spans both dialects)
        s3 = S3Client(port, "swiftop", "swsecret")
        st, _, got = await s3.request("GET", "/media/a/b.bin")
        assert st == 200 and got == payload
        # ...and a DIFFERENT s3 user is refused by the same ACLs
        await UserDB(gw.io).create("AKS", "SKS")
        st, _, _ = await S3Client(port, "AKS", "SKS").request(
            "GET", "/media/a/b.bin")
        assert st == 403
        # delete object then container
        st, _, _ = await c.request("DELETE", "/swift/v1/media/a/b.bin",
                                   sign=False, headers=tok)
        assert st == 204
        st, _, _ = await c.request("DELETE", "/swift/v1/media",
                                   sign=False, headers=tok)
        assert st == 204
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_multisite_zone_sync():
    """rgw_data_sync.cc role: zone A's datalog replicates buckets and
    objects (incl. multipart manifests) to zone B; deletes follow."""
    import re

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw.a", pg_num=8)
        await admin.pool_create(".rgw.b", pg_num=8)
        gw_a = S3Gateway(admin, pool=".rgw.a", require_auth=False,
                         datalog=True)
        gw_b = S3Gateway(admin, pool=".rgw.b", require_auth=False)
        pa = await gw_a.start()
        await gw_b.start()
        ca = S3Client(pa)

        # pre-bootstrap content (full-sync path)
        await ca.request("PUT", "/zone", sign=False)
        await ca.request("PUT", "/zone/pre.bin", b"P" * 20000,
                         sign=False)
        from ceph_tpu.services.rgw_sync import ZoneSyncAgent
        agent = ZoneSyncAgent(gw_a, gw_b)
        await agent.bootstrap()
        st, _, got = await gw_b._get_object("zone", "pre.bin", {})
        assert st == 200 and got == b"P" * 20000

        # incremental: put (overwrites collapse), multipart, delete
        await ca.request("PUT", "/zone/inc.bin", b"v1" * 500,
                         sign=False)
        await ca.request("PUT", "/zone/inc.bin", b"v2" * 500,
                         sign=False)
        st, _, body = await ca.request("POST", "/zone/big?uploads", b"",
                                       sign=False)
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()
        st, h, _ = await ca.request(
            "PUT", f"/zone/big?partNumber=1&uploadId={upload_id}",
            b"M" * 7000, sign=False)
        comp = ("<CompleteMultipartUpload><Part><PartNumber>1"
                "</PartNumber><ETag>" + h["etag"].strip('"')
                + "</ETag></Part></CompleteMultipartUpload>")
        await ca.request("POST", f"/zone/big?uploadId={upload_id}",
                         comp.encode(), sign=False)
        await ca.request("DELETE", "/zone/pre.bin", sign=False)
        n = await agent.replay_once()
        assert n >= 4
        st, _, got = await gw_b._get_object("zone", "inc.bin", {})
        assert st == 200 and got == b"v2" * 500
        st, _, got = await gw_b._get_object("zone", "big", {})
        assert st == 200 and got == b"M" * 7000
        st, _, _ = await gw_b._get_object("zone", "pre.bin", {})
        assert st == 404
        # idempotent: nothing new replays twice
        assert await agent.replay_once() == 0
        await gw_a.stop()
        await gw_b.stop()
        await cl.stop()
    asyncio.run(run())


def test_gc_deferred_chain_collection():
    """Deletes/overwrites queue their data chains in .rgw.gc; the bytes
    survive until gc.process() collects ready chains (rgw_gc.cc)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)

        await c.request("PUT", "/b", sign=False)
        await c.request("PUT", "/b/one.bin", b"A" * 9000, sign=False)
        await c.request("PUT", "/b/one.bin", b"B" * 9000, sign=False)
        await c.request("PUT", "/b/dead.bin", b"C" * 9000, sign=False)
        await c.request("DELETE", "/b/dead.bin", sign=False)

        ents = await gw.gc.entries()
        assert len(ents) == 2          # overwritten chain + deleted chain
        before = len(await gw.io.list_objects())
        removed = await gw.gc.process()
        assert removed >= 2
        assert len(await gw.io.list_objects()) < before
        assert not await gw.gc.entries()
        # live object unaffected by collection
        st, _, got = await c.request("GET", "/b/one.bin", sign=False)
        assert st == 200 and got == b"B" * 9000

        # min_wait holds chains back until their time comes
        gw.gc.min_wait = 3600.0
        await c.request("DELETE", "/b/one.bin", sign=False)
        assert await gw.gc.process() == 0
        assert len(await gw.gc.entries()) == 1
        assert await gw.gc.process(now=__import__("time").time()
                                   + 7200) >= 1
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_lifecycle_config_and_expiration():
    """?lifecycle config round-trip + the lc worker expiring objects by
    prefix/age and aborting stale multipart uploads (rgw_lc.cc)."""
    import time as _time

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)
        await c.request("PUT", "/lc", sign=False)

        # no config yet
        st, _, body = await c.request("GET", "/lc?lifecycle", sign=False)
        assert st == 404 and b"NoSuchLifecycleConfiguration" in body

        cfg = (b'<LifecycleConfiguration><Rule><ID>exp</ID>'
               b'<Prefix>logs/</Prefix><Status>Enabled</Status>'
               b'<Expiration><Days>7</Days></Expiration></Rule>'
               b'<Rule><Prefix></Prefix><Status>Enabled</Status>'
               b'<AbortIncompleteMultipartUpload>'
               b'<DaysAfterInitiation>2</DaysAfterInitiation>'
               b'</AbortIncompleteMultipartUpload></Rule>'
               b'</LifecycleConfiguration>')
        st, _, _ = await c.request("PUT", "/lc?lifecycle", cfg,
                                   sign=False)
        assert st == 200
        st, _, body = await c.request("GET", "/lc?lifecycle", sign=False)
        assert st == 200 and b"<Days>7</Days>" in body \
            and b"<DaysAfterInitiation>2</DaysAfterInitiation>" in body
        # malformed config refused
        st, _, _ = await c.request("PUT", "/lc?lifecycle",
                                   b"<LifecycleConfiguration/>",
                                   sign=False)
        assert st == 400

        await c.request("PUT", "/lc/logs/a.log", b"x" * 4000,
                        sign=False)
        await c.request("PUT", "/lc/keep.dat", b"y" * 4000, sign=False)
        st, _, _ = await c.request("POST", "/lc/stale?uploads", b"",
                                   sign=False)
        # nothing expires at now
        res = await gw.lc_process()
        assert res == {"expired": 0, "aborted": 0}
        # 8 days later: logs/ expired, keep.dat alive, upload aborted
        res = await gw.lc_process(now=_time.time() + 8 * 86400)
        assert res["expired"] == 1 and res["aborted"] == 1
        st, _, _ = await c.request("GET", "/lc/logs/a.log", sign=False)
        assert st == 404
        st, _, _ = await c.request("GET", "/lc/keep.dat", sign=False)
        assert st == 200

        # DELETE ?lifecycle removes the config
        st, _, _ = await c.request("DELETE", "/lc?lifecycle", sign=False)
        assert st == 204
        st, _, _ = await c.request("GET", "/lc?lifecycle", sign=False)
        assert st == 404
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_quota_enforcement_and_usage_accounting():
    """Bucket + user quota (max_size/max_objects) refuse writes that
    would exceed the caps; usage counters track put/delete/multipart
    (rgw_quota.cc check_quota)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        io = admin.open_ioctx(".rgw")
        db = UserDB(io)
        await db.create("AKID", "sekrit")
        port = await gw.start()
        c = S3Client(port, "AKID", "sekrit")

        await c.request("PUT", "/q")               # owner = AKID
        assert await gw.set_bucket_quota("q", max_size=10000,
                                         max_objects=3)
        st, _, _ = await c.request("PUT", "/q/a", b"x" * 6000)
        assert st == 200
        st, _, body = await c.request("PUT", "/q/b", b"x" * 6000)
        assert st == 403 and b"QuotaExceeded" in body
        # overwrite that shrinks is fine; growth past cap is not
        st, _, _ = await c.request("PUT", "/q/a", b"x" * 2000)
        assert st == 200
        st, _, _ = await c.request("PUT", "/q/b", b"x" * 6000)
        assert st == 200
        # usage lives in the cls-maintained index header (atomic with
        # every entry change), not a gateway-side counter
        import json as _json
        from ceph_tpu.services.rgw import _index_oid
        hdr = _json.loads(await io.exec(_index_oid("q"), "rgw",
                                        "bucket_read_header"))
        assert hdr == {"entries": 2, "bytes": 8000}
        assert await gw._bucket_usage("q") == (8000, 2)
        # object-count cap
        await c.request("PUT", "/q/c", b"z")
        st, _, body = await c.request("PUT", "/q/d", b"z")
        assert st == 403 and b"QuotaExceeded" in body
        # delete releases quota
        await c.request("DELETE", "/q/c")
        st, _, _ = await c.request("PUT", "/q/d", b"z")
        assert st == 200
        # multipart parts are checked too
        import re as _re
        st, _, body = await c.request("POST", "/q/mp?uploads", b"")
        uid = _re.search(rb"<UploadId>([^<]+)</UploadId>",
                         body).group(1).decode()
        st, _, body = await c.request(
            "PUT", f"/q/mp?partNumber=1&uploadId={uid}", b"x" * 9000)
        assert st == 403 and b"QuotaExceeded" in body

        # user quota caps the SUM across the owner's buckets
        assert await db.set_quota("AKID", max_size=12000)
        await c.request("PUT", "/q2")
        st, _, body = await c.request("PUT", "/q2/big", b"x" * 6000)
        assert st == 403 and b"QuotaExceeded" in body
        st, _, _ = await c.request("PUT", "/q2/ok", b"x" * 3000)
        assert st == 200
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_list_multipart_uploads():
    """GET /bucket?uploads lists in-progress uploads; completed/aborted
    ones disappear (rgw RGWListBucketMultiparts)."""
    import re as _re

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)
        await c.request("PUT", "/mu", sign=False)
        st, _, body = await c.request("GET", "/mu?uploads", sign=False)
        assert st == 200 and b"<Upload>" not in body
        ids = []
        for key in ("k1", "k2"):
            _, _, body = await c.request("POST", f"/mu/{key}?uploads",
                                         b"", sign=False)
            ids.append(_re.search(rb"<UploadId>([^<]+)</UploadId>",
                                  body).group(1).decode())
        st, _, body = await c.request("GET", "/mu?uploads", sign=False)
        assert body.count(b"<Upload>") == 2
        assert ids[0].encode() in body and ids[1].encode() in body
        await c.request("DELETE", f"/mu/k1?uploadId={ids[0]}",
                        sign=False)
        st, _, body = await c.request("GET", "/mu?uploads", sign=False)
        assert body.count(b"<Upload>") == 1 \
            and ids[0].encode() not in body
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_canned_acls_and_anonymous_access():
    """Canned ACL matrix (rgw_acl.cc / s3tests anonymous access):
    private refuses anonymous; public-read opens GET but not PUT;
    public-read-write opens both; object acl overrides bucket acl;
    the ?acl subresource stays owner-only."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        db = UserDB(admin.open_ioctx(".rgw"))
        await db.create("OWNER", "sk1")
        await db.create("OTHER", "sk2")
        port = await gw.start()
        owner = S3Client(port, "OWNER", "sk1")
        other = S3Client(port, "OTHER", "sk2")
        anon = S3Client(port)

        await owner.request("PUT", "/b")
        await owner.request("PUT", "/b/secret", b"s3cret")

        # private (default): anonymous and other users are refused
        st, _, _ = await anon.request("GET", "/b/secret", sign=False)
        assert st == 403
        st, _, _ = await other.request("GET", "/b/secret")
        assert st == 403
        st, _, _ = await anon.request("GET", "/b", sign=False)
        assert st == 403

        # object-level public-read via ?acl (owner-only subresource)
        st, _, _ = await other.request("PUT", "/b/secret?acl",
                                       headers={"x-amz-acl":
                                                "public-read"})
        assert st == 403
        st, _, _ = await owner.request("PUT", "/b/secret?acl",
                                       headers={"x-amz-acl":
                                                "public-read"})
        assert st == 200
        st, _, got = await anon.request("GET", "/b/secret", sign=False)
        assert st == 200 and got == b"s3cret"
        # read is open; write is not
        st, _, _ = await anon.request("PUT", "/b/secret", b"x",
                                      sign=False)
        assert st == 403
        st, _, body = await owner.request("GET", "/b/secret?acl")
        assert st == 200 and b"AllUsers" in body \
            and b"FULL_CONTROL" in body

        # bucket-level public-read-write: anonymous can PUT new keys
        # and list
        st, _, _ = await owner.request("PUT", "/b?acl",
                                       headers={"x-amz-acl":
                                                "public-read-write"})
        assert st == 200
        st, _, _ = await anon.request("PUT", "/b/dropbox", b"hi",
                                      sign=False)
        assert st == 200
        st, _, body = await anon.request("GET", "/b", sign=False)
        assert st == 200 and b"dropbox" in body

        # authenticated-read: other signed users read, anonymous not
        st, _, _ = await owner.request("PUT", "/b?acl",
                                       headers={"x-amz-acl":
                                                "authenticated-read"})
        assert st == 200
        st, _, _ = await other.request("GET", "/b/dropbox")
        assert st == 200
        st, _, _ = await anon.request("GET", "/b/dropbox", sign=False)
        assert st == 403

        # x-amz-acl at PUT time
        st, _, _ = await owner.request("PUT", "/b/open", b"o",
                                       headers={"x-amz-acl":
                                                "public-read"})
        assert st == 200
        st, _, got = await anon.request("GET", "/b/open", sign=False)
        assert st == 200 and got == b"o"
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_server_side_copy():
    """x-amz-copy-source (rgw_op.cc RGWCopyObj): same- and cross-
    bucket copies move bytes without the client round-trip; source
    ACLs gate the read; ETag survives."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        db = UserDB(admin.open_ioctx(".rgw"))
        await db.create("OWNER", "sk1")
        await db.create("OTHER", "sk2")
        port = await gw.start()
        owner = S3Client(port, "OWNER", "sk1")
        other = S3Client(port, "OTHER", "sk2")

        payload = b"copy me " * 9000              # striped size
        await owner.request("PUT", "/src")
        await owner.request("PUT", "/dst")
        await owner.request("PUT", "/src/orig", payload)

        st, _, body = await owner.request(
            "PUT", "/dst/copied", b"",
            headers={"x-amz-copy-source": "/src/orig"})
        assert st == 200 and b"CopyObjectResult" in body
        assert hashlib.md5(payload).hexdigest().encode() in body
        st, _, got = await owner.request("GET", "/dst/copied")
        assert st == 200 and got == payload

        # same-bucket copy
        st, _, _ = await owner.request(
            "PUT", "/src/orig2", b"",
            headers={"x-amz-copy-source": "/src/orig"})
        assert st == 200

        # a different user can't copy from a private source even into
        # their own bucket
        await other.request("PUT", "/theirs")
        st, _, _ = await other.request(
            "PUT", "/theirs/stolen", b"",
            headers={"x-amz-copy-source": "/src/orig"})
        assert st == 403

        # missing source
        st, _, _ = await owner.request(
            "PUT", "/dst/nope", b"",
            headers={"x-amz-copy-source": "/src/missing"})
        assert st == 404
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_usage_log_accounting():
    """rgw_usage.cc role: REST ops are billed to the bucket owner per
    (bucket, category, hour); flush merges idempotently into the
    owner's usage object; show filters by epoch; trim reclaims."""
    async def run():
        from ceph_tpu.services.rgw_usage import UsageLog

        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        db = UserDB(admin.open_ioctx(".rgw"))
        await db.create("OWNER", "sk1")
        port = await gw.start()
        c = S3Client(port, "OWNER", "sk1")

        await c.request("PUT", "/b")
        await c.request("PUT", "/b/k1", b"x" * 1000)
        await c.request("PUT", "/b/k2", b"y" * 500)
        st, _, _ = await c.request("GET", "/b/k1")
        assert st == 200
        st, _, _ = await c.request("GET", "/b/missing")
        assert st == 404                       # counted, unsuccessful

        assert await gw.usage_flush() > 0
        rows = await UsageLog(gw.io).show("OWNER")
        by_cat = {r["category"]: r for r in rows if r["bucket"] == "b"}
        assert by_cat["put_obj"]["ops"] == 2
        assert by_cat["put_obj"]["successful_ops"] == 2
        assert by_cat["put_obj"]["bytes_received"] == 1500
        assert by_cat["get_obj"]["ops"] == 2
        assert by_cat["get_obj"]["successful_ops"] == 1
        assert by_cat["get_obj"]["bytes_sent"] >= 1000
        assert by_cat["create_bucket"]["ops"] == 1

        # second flush merges (not overwrites)
        await c.request("PUT", "/b/k3", b"z" * 100)
        await gw.usage_flush()
        rows = await UsageLog(gw.io).show("OWNER")
        by_cat = {r["category"]: r for r in rows if r["bucket"] == "b"}
        assert by_cat["put_obj"]["ops"] == 3
        assert by_cat["put_obj"]["bytes_received"] == 1600

        # epoch filters + trim
        cur = rows[0]["epoch"]
        assert await UsageLog(gw.io).show("OWNER",
                                          start_epoch=cur + 1) == []
        n = await UsageLog(gw.io).trim("OWNER", before_epoch=cur + 1)
        assert n == len(rows)
        assert await UsageLog(gw.io).show("OWNER") == []
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_list_objects_delimiter_and_pagination():
    """ListObjects v1+v2 (rgw_rest_s3.cc RGWListBucket): delimiter
    folds keys into CommonPrefixes, max-keys truncates with
    NextMarker / NextContinuationToken resume."""
    import re as _re

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)
        await c.request("PUT", "/b", sign=False)
        for k in ("a/1.txt", "a/2.txt", "b/3.txt", "top1", "top2"):
            await c.request("PUT", f"/b/{k}", b"x", sign=False)

        # delimiter folds a/ and b/ into CommonPrefixes
        st, _, body = await c.request("GET", "/b?delimiter=/",
                                      sign=False)
        assert st == 200
        assert body.count(b"<CommonPrefixes>") == 2
        assert b"<Prefix>a/</Prefix>" in body
        assert b"<Prefix>b/</Prefix>" in body
        assert b"top1" in body and b"top2" in body
        assert b"a/1.txt" not in body              # folded away
        assert b"<IsTruncated>false</IsTruncated>" in body

        # prefix + delimiter descends one level
        st, _, body = await c.request(
            "GET", "/b?prefix=a/&delimiter=/", sign=False)
        assert b"a/1.txt" in body and b"a/2.txt" in body
        assert b"CommonPrefixes" not in body

        # v1 pagination: max-keys=2 -> NextMarker resume walks all 5
        got, marker = [], ""
        while True:
            qs = f"/b?max-keys=2" + (f"&marker={marker}" if marker
                                     else "")
            st, _, body = await c.request("GET", qs, sign=False)
            got += [m.decode() for m in
                    _re.findall(rb"<Key>([^<]+)</Key>", body)]
            if b"<IsTruncated>true</IsTruncated>" not in body:
                break
            marker = _re.search(rb"<NextMarker>([^<]+)</NextMarker>",
                                body).group(1).decode()
        assert got == ["a/1.txt", "a/2.txt", "b/3.txt", "top1", "top2"]

        # delimiter + tiny pages: marker-following must TERMINATE and
        # never repeat a CommonPrefix (resume marker = folded prefix)
        got, marker, pages = [], "", 0
        while True:
            qs = "/b?delimiter=/&max-keys=1" + (
                f"&marker={marker}" if marker else "")
            st, _, body = await c.request("GET", qs, sign=False)
            got += [m.decode() for m in _re.findall(
                rb"<Prefix>([^<]+)</Prefix>", body)]
            got += [m.decode() for m in _re.findall(
                rb"<Key>([^<]+)</Key>", body)]
            pages += 1
            assert pages < 20, got     # livelock guard
            if b"<IsTruncated>true</IsTruncated>" not in body:
                break
            marker = _re.search(rb"<NextMarker>([^<]+)</NextMarker>",
                                body).group(1).decode()
        assert got == ["a/", "b/", "top1", "top2"]

        # max-keys=0: complete empty listing, never a resume loop
        st, _, body = await c.request("GET", "/b?max-keys=0",
                                      sign=False)
        assert b"<IsTruncated>false</IsTruncated>" in body
        assert b"<Key>" not in body

        # v2: continuation-token + KeyCount
        st, _, body = await c.request(
            "GET", "/b?list-type=2&max-keys=3", sign=False)
        assert b"<KeyCount>3</KeyCount>" in body
        tok = _re.search(
            rb"<NextContinuationToken>([^<]+)</NextContinuationToken>",
            body).group(1).decode()
        st, _, body = await c.request(
            "GET", f"/b?list-type=2&continuation-token={tok}",
            sign=False)
        assert b"top1" in body and b"top2" in body
        await gw.stop()
        await cl.stop()
    asyncio.run(run())
