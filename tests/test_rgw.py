"""RGW-lite S3 gateway: bucket/object REST surface + v2 auth.

Mirrors the reference's s3tests role (qa s3-tests subset): bucket CRUD,
object round-trips with ETag, listing with prefix, range reads, auth
rejection — all against a live in-process cluster and a real HTTP
socket.
"""

import asyncio
import hashlib
import sys
from email.utils import formatdate

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.services.rgw import S3Gateway, UserDB, sign_v2  # noqa: E402


class S3Client:
    """Tiny raw-socket S3 client speaking signature v2."""

    def __init__(self, port, access="", secret=""):
        self.port = port
        self.access = access
        self.secret = secret

    async def request(self, method, path, body=b"", headers=None,
                      sign=True):
        headers = dict(headers or {})
        headers.setdefault("Date", formatdate(usegmt=True))
        if sign and self.access:
            sig = sign_v2(self.secret, method,
                          headers.get("Content-MD5", ""),
                          headers.get("Content-Type", ""),
                          headers["Date"], path.split("?")[0])
            headers["Authorization"] = f"AWS {self.access}:{sig}"
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       self.port)
        try:
            lines = [f"{method} {path} HTTP/1.1", "Host: localhost",
                     f"Content-Length: {len(body)}",
                     "Connection: close"]
            lines += [f"{k}: {v}" for k, v in headers.items()]
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
            await writer.drain()
            status_line = await reader.readline()
            status = int(status_line.split()[1])
            rhdrs = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode().partition(":")
                rhdrs[k.strip().lower()] = v.strip()
            n = int(rhdrs.get("content-length", "0"))
            payload = await reader.readexactly(n) if n else b""
            return status, rhdrs, payload
        finally:
            writer.close()


def test_s3_gateway_end_to_end():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        await UserDB(admin.open_ioctx(".rgw")).create("AKID", "sekrit")
        port = await gw.start()
        c = S3Client(port, "AKID", "sekrit")

        # unauthenticated / bad-signature requests are refused
        st, _, _ = await S3Client(port).request("GET", "/", sign=False)
        assert st == 403
        st, _, _ = await S3Client(port, "AKID", "wrong").request("GET", "/")
        assert st == 403

        # bucket lifecycle
        st, _, _ = await c.request("PUT", "/photos")
        assert st == 200
        st, _, _ = await c.request("PUT", "/photos")
        assert st == 409                        # exists
        st, _, body = await c.request("GET", "/")
        assert st == 200 and b"<Name>photos</Name>" in body

        # object round-trip with etag
        payload = b"s3 object payload " * 5000       # ~90 KiB, striped
        st, h, _ = await c.request("PUT", "/photos/album/pic1.jpg",
                                   payload)
        assert st == 200
        assert h["etag"].strip('"') == hashlib.md5(payload).hexdigest()
        st, h, got = await c.request("GET", "/photos/album/pic1.jpg")
        assert st == 200 and got == payload

        # range read
        st, h, got = await c.request("GET", "/photos/album/pic1.jpg",
                                     headers={"Range": "bytes=10-29"})
        assert st == 206 and got == payload[10:30]
        assert h["content-range"] == f"bytes 10-29/{len(payload)}"

        # listing + prefix filter
        await c.request("PUT", "/photos/album/pic2.jpg", b"x")
        await c.request("PUT", "/photos/other.txt", b"y")
        st, _, body = await c.request("GET", "/photos?prefix=album/")
        assert st == 200
        assert b"pic1.jpg" in body and b"pic2.jpg" in body
        assert b"other.txt" not in body

        # head / delete
        st, _, _ = await c.request("HEAD", "/photos/other.txt")
        assert st == 200
        st, _, _ = await c.request("DELETE", "/photos/other.txt")
        assert st == 204
        st, _, _ = await c.request("HEAD", "/photos/other.txt")
        assert st == 404

        # bucket with content refuses delete; empty deletes
        st, _, _ = await c.request("DELETE", "/photos")
        assert st == 409
        for k in ("album/pic1.jpg", "album/pic2.jpg"):
            await c.request("DELETE", f"/photos/{k}")
        st, _, _ = await c.request("DELETE", "/photos")
        assert st == 204

        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_s3_overwrite_and_missing():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin, require_auth=False)
        port = await gw.start()
        c = S3Client(port)
        await c.request("PUT", "/b", sign=False)
        # overwrite shrinks: no stale tail from the previous version
        await c.request("PUT", "/b/k", b"A" * 50000, sign=False)
        await c.request("PUT", "/b/k", b"B" * 100, sign=False)
        st, _, got = await c.request("GET", "/b/k", sign=False)
        assert st == 200 and got == b"B" * 100
        st, _, _ = await c.request("GET", "/b/missing", sign=False)
        assert st == 404
        st, _, _ = await c.request("GET", "/nobucket?list", sign=False)
        assert st == 404
        await gw.stop()
        await cl.stop()
    asyncio.run(run())


def test_multipart_upload_round_trip():
    """rgw_multi.cc role: init -> 6 parts -> ListParts -> Complete
    (manifest, no copy) -> GET whole + ranges across part seams ->
    overwrite cleans old parts; plus abort and error paths."""
    import re

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        gw = S3Gateway(admin)
        await UserDB(admin.open_ioctx(".rgw")).create("AKID", "sekrit")
        port = await gw.start()
        c = S3Client(port, "AKID", "sekrit")

        st, _, _ = await c.request("PUT", "/mp")
        assert st == 200

        # init
        st, _, body = await c.request("POST", "/mp/big?uploads")
        assert st == 200, body
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()

        # upload 6 parts of distinct content/pattern sizes
        parts = [bytes([i]) * (1000 + 137 * i) for i in range(1, 7)]
        etags = []
        for i, data in enumerate(parts, 1):
            st, h, _ = await c.request(
                "PUT", f"/mp/big?partNumber={i}&uploadId={upload_id}",
                body=data)
            assert st == 200
            etags.append(h["etag"].strip('"'))
            assert etags[-1] == hashlib.md5(data).hexdigest()

        # re-upload part 3 with different bytes (replace semantics)
        parts[2] = b"\xAB" * 1999
        st, h, _ = await c.request(
            "PUT", f"/mp/big?partNumber=3&uploadId={upload_id}",
            body=parts[2])
        assert st == 200
        etags[2] = h["etag"].strip('"')

        # ListParts shows all six with sizes
        st, _, body = await c.request(
            "GET", f"/mp/big?uploadId={upload_id}")
        assert st == 200
        assert body.count(b"<Part>") == 6
        assert f"<Size>{len(parts[2])}</Size>".encode() in body

        # complete (client lists all 6 in order)
        comp = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{i}</PartNumber>"
            f'<ETag>"{etags[i - 1]}"</ETag></Part>'
            for i in range(1, 7)) + "</CompleteMultipartUpload>"
        st, _, body = await c.request(
            "POST", f"/mp/big?uploadId={upload_id}", body=comp.encode())
        assert st == 200, body
        md5s = b"".join(bytes.fromhex(e) for e in etags)
        want_etag = f"{hashlib.md5(md5s).hexdigest()}-6"
        assert want_etag.encode() in body

        # the upload is gone (complete is terminal)
        st, _, _ = await c.request("GET", f"/mp/big?uploadId={upload_id}")
        assert st == 404

        # whole-object GET equals the concatenation
        full = b"".join(parts)
        st, h, got = await c.request("GET", "/mp/big")
        assert st == 200 and got == full
        assert h["etag"].strip('"') == want_etag

        # range read across the part-1/part-2 seam and a suffix range
        lo, hi = len(parts[0]) - 10, len(parts[0]) + 9
        st, _, got = await c.request(
            "GET", "/mp/big", headers={"Range": f"bytes={lo}-{hi}"})
        assert st == 206 and got == full[lo:hi + 1]
        st, _, got = await c.request(
            "GET", "/mp/big", headers={"Range": "bytes=-25"})
        assert st == 206 and got == full[-25:]

        # listing shows the completed object with the multipart size
        st, _, body = await c.request("GET", "/mp")
        assert f"<Size>{len(full)}</Size>".encode() in body

        # overwrite with a simple PUT removes manifest parts, reads back
        st, _, _ = await c.request("PUT", "/mp/big", body=b"tiny")
        assert st == 200
        st, _, got = await c.request("GET", "/mp/big")
        assert got == b"tiny"

        # abort path: init + one part + abort -> NoSuchUpload afterwards
        st, _, body = await c.request("POST", "/mp/die?uploads")
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()
        await c.request("PUT", f"/mp/die?partNumber=1&uploadId={upload_id}",
                        body=b"x" * 100)
        st, _, _ = await c.request(
            "DELETE", f"/mp/die?uploadId={upload_id}")
        assert st == 204
        st, _, _ = await c.request("GET", f"/mp/die?uploadId={upload_id}")
        assert st == 404

        # completing with a wrong etag is InvalidPart
        st, _, body = await c.request("POST", "/mp/bad?uploads")
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()
        await c.request("PUT", f"/mp/bad?partNumber=1&uploadId={upload_id}",
                        body=b"data")
        comp = ("<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
                f'<ETag>"{"0" * 32}"</ETag></Part>'
                "</CompleteMultipartUpload>")
        st, _, body = await c.request(
            "POST", f"/mp/bad?uploadId={upload_id}", body=comp.encode())
        assert st == 400 and b"InvalidPart" in body

        await gw.stop()
        await cl.stop()
    asyncio.run(run())
