"""Device-mesh data-plane tests on the virtual 8-device CPU mesh
(conftest.py sets xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ceph_tpu.ec import gf256
from ceph_tpu.ec.kernel import matrix_apply
from ceph_tpu.parallel.layout import ec_cluster_step, make_mesh


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_cluster_step_matches_single_device():
    k, m = 4, 2
    mesh = make_mesh(8)
    assert mesh.shape["host"] * mesh.shape["shard"] == 8
    gen = gf256.rs_vandermonde_matrix(k, m)
    bitmat = jnp.asarray(gf256.expand_to_bitmatrix(gen[k:]), jnp.int8)
    n_host, n_shard = mesh.shape["host"], mesh.shape["shard"]
    B, L = 2 * n_host, 128 * n_shard
    data = np.random.default_rng(0).integers(
        0, 256, (B, k, L), dtype=np.uint8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    ddata = jax.device_put(
        jnp.asarray(data), NamedSharding(mesh, P("host", None, "shard")))
    parity, scrub = ec_cluster_step(mesh, bitmat)(ddata)
    got = np.asarray(parity)
    want = np.stack([matrix_apply(gen[k:])(d) for d in data])
    Lloc = L // n_shard
    for s in range(n_shard):
        src = (s - 1) % n_shard
        assert np.array_equal(got[:, :, s * Lloc:(s + 1) * Lloc],
                              want[:, :, src * Lloc:(src + 1) * Lloc])
    assert np.asarray(scrub).tolist() == \
        np.sum(want.astype(np.uint64), axis=(0, 2)).astype(int).tolist()


def test_make_mesh_shapes():
    for n in (1, 2, 4, 8):
        mesh = make_mesh(n)
        assert mesh.shape["host"] * mesh.shape["shard"] == n


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_recover_step_rebuilds_lost_chunks_across_mesh():
    """Distributed recovery (ECBackend continue_recovery_op analog):
    survivor chunks live on DIFFERENT shard devices; all_gather along
    'shard' + local decode matmul rebuilds the lost chunks bit-exactly
    on every device."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ceph_tpu.parallel.layout import ec_recover_step

    k, m = 8, 2
    mesh = make_mesh(8)
    n_host, n_shard = mesh.shape["host"], mesh.shape["shard"]
    gen = gf256.rs_vandermonde_matrix(k, m)
    rng = np.random.default_rng(5)
    B, L = 2 * n_host, 256
    data = rng.integers(0, 256, (B, k, L), dtype=np.uint8)
    parity = np.stack([matrix_apply(gen[k:])(d) for d in data])
    full = np.concatenate([data, parity], axis=1)   # [B, k+m, L]

    # lose data chunks 1 and 4; the 8 survivors land one per shard
    # device — the OSD placement itself
    lost, present = [1, 4], [0, 2, 3, 5, 6, 7, 8, 9]
    n_surv = len(present)
    assert n_surv % n_shard == 0
    dec = gf256.decode_matrix(gen, present, lost)
    dec_bm = jnp.asarray(gf256.expand_to_bitmatrix(dec), jnp.int8)
    surv = np.ascontiguousarray(full[:, present, :])
    dsurv = jax.device_put(
        jnp.asarray(surv), NamedSharding(mesh, P("host", "shard", None)))

    rebuilt, scrub = ec_recover_step(mesh, dec_bm, n_surv)(dsurv)
    got = np.asarray(rebuilt)
    want = data[:, lost, :]
    assert np.array_equal(got, want)
    assert np.asarray(scrub).tolist() == \
        np.sum(want.astype(np.uint64), axis=(0, 2)).astype(int).tolist()
