"""Batched CRUSH kernel vs scalar host mapper: bit-exact equivalence.

The masked fixed-trip reformulation (ops/crush_kernel.py) must return
EXACTLY what crush/mapper.py's sequential loops return for every input —
including degraded weight vectors (outed osds, fractional reweights)
where the retry/collision paths actually fire.
"""

import numpy as np
import pytest

from ceph_tpu.crush.builder import (build_hierarchy, make_erasure_rule,
                                    make_replicated_rule)
from ceph_tpu.crush.mapper import do_rule
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.ops.crush_kernel import batch_do_rule, compile_rule

N_X = 512


def build(n_osds, per_host, ec_size=6):
    m = CrushMap()
    m.max_devices = n_osds
    build_hierarchy(m, n_osds, per_host)
    rep = make_replicated_rule(m, "rep")
    ec = make_erasure_rule(m, "ec", size=ec_size)
    return m, rep, ec


def assert_match(m, rule, numrep, weights, xs=None):
    xs = xs if xs is not None else list(range(N_X))
    got = batch_do_rule(m, rule, xs, numrep, weights)
    want = [do_rule(m, rule, x, numrep, weights) for x in xs]
    mism = [(x, w, g) for x, w, g in zip(xs, want, got) if w != g]
    assert not mism, f"{len(mism)} mismatches, first: {mism[:3]}"


WEIGHT_CASES = [
    ("all-in", lambda n: [0x10000] * n),
    ("one-out", lambda n: [0] + [0x10000] * (n - 1)),
    ("three-out", lambda n: [0, 0x10000, 0, 0x10000, 0] +
        [0x10000] * (n - 5)),
    ("fractional", lambda n: [(0x4000 + 0x2000 * (i % 7)) & 0xFFFF or
                              0x10000 for i in range(n)]),
    ("mixed", lambda n: [0 if i % 5 == 0 else
                         (0x8000 if i % 3 == 0 else 0x10000)
                         for i in range(n)]),
]


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
@pytest.mark.parametrize("n_osds,per_host", [(12, 2), (8, 1), (15, 3)])
def test_firstn_bit_exact(n_osds, per_host, wname, wfn):
    m, rep, _ = build(n_osds, per_host)
    assert compile_rule(m, rep) is not None
    for numrep in (1, 2, 3):
        assert_match(m, rep, numrep, wfn(n_osds))


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
@pytest.mark.parametrize("n_osds,per_host,size", [(12, 2, 6), (8, 1, 6),
                                                  (9, 1, 4)])
def test_indep_bit_exact(n_osds, per_host, size, wname, wfn):
    m, _, ec = build(n_osds, per_host, ec_size=size)
    assert compile_rule(m, ec) is not None
    assert_match(m, ec, size, wfn(n_osds))


def test_uneven_host_sizes():
    # hosts of different sizes exercise the padded-items masking
    m = CrushMap()
    m.max_devices = 11
    from ceph_tpu.crush.builder import make_bucket
    from ceph_tpu.crush.constants import BUCKET_STRAW2
    sizes = [1, 2, 3, 5]
    start = 0
    hosts = []
    for h, sz in enumerate(sizes):
        items = list(range(start, start + sz))
        start += sz
        hb = make_bucket(m, BUCKET_STRAW2, 1, items, [0x10000] * sz)
        m.name_map[hb.id] = f"host{h}"
        hosts.append(hb)
    root = make_bucket(m, BUCKET_STRAW2, 10, [b.id for b in hosts],
                       [b.weight for b in hosts])
    m.name_map[root.id] = "default"
    rep = make_replicated_rule(m, "rep")
    ec = make_erasure_rule(m, "ec", size=4)
    for numrep in (2, 3, 4):
        assert_match(m, rep, numrep, [0x10000] * 11)
    assert_match(m, ec, 4, [0x10000] * 11)
    assert_match(m, ec, 4, [0x10000] * 8 + [0, 0, 0])


def test_more_reps_than_hosts():
    # impossible placements: firstn returns short sets, indep holes
    m, rep, ec = build(6, 2, ec_size=6)   # only 3 hosts
    assert_match(m, rep, 5, [0x10000] * 6)
    assert_match(m, ec, 6, [0x10000] * 6)


def test_random_weight_fuzz():
    rng = np.random.default_rng(7)
    m, rep, ec = build(16, 2, ec_size=6)
    for _ in range(5):
        w = rng.choice([0, 0x3000, 0x8000, 0xC000, 0x10000],
                       size=16).tolist()
        xs = rng.integers(0, 2**31, 128).tolist()
        assert_match(m, rep, 3, w, xs)
        assert_match(m, ec, 6, w, xs)


def test_fallback_for_unsupported_shapes():
    # non-default tunables -> compile refuses, batch falls back to host
    m, rep, _ = build(8, 2)
    m.tunables.chooseleaf_stable = 0
    assert compile_rule(m, rep) is None
    assert_match(m, rep, 3, [0x10000] * 8)   # still correct via fallback


def test_batch_speedup_sanity():
    import time
    m, rep, _ = build(32, 4)
    w = [0x10000] * 32
    xs = list(range(4096))
    t0 = time.perf_counter()
    batch = batch_do_rule(m, rep, xs, 3, w)
    t_batch = time.perf_counter() - t0
    t0 = time.perf_counter()
    scalar = [do_rule(m, rep, x, 3, w) for x in xs[:256]]
    t_scalar = (time.perf_counter() - t0) * (len(xs) / 256)
    assert batch[:256] == scalar
    # vectorization must buy at least an order of magnitude
    assert t_batch < t_scalar / 10, (t_batch, t_scalar)


def test_jax_engine_matches_numpy():
    import numpy as np
    from ceph_tpu.ops.crush_kernel import (_straw2_draw,
                                           jax_straw2_winners)
    rng = np.random.default_rng(3)
    items = np.array([-2, -5, -9, -12, -13], np.int64)
    weights = rng.choice([0, 0x8000, 0x10000, 0x28000], 5).astype(np.int64)
    weights[0] = 0x10000
    xs = rng.integers(0, 2**31, 257)
    rs = np.arange(11, dtype=np.int64)
    got = jax_straw2_winners(items, weights, xs, rs)
    want = np.empty((257, 11), np.int64)
    for j, r in enumerate(rs):
        idx = _straw2_draw(items[None, :], weights[None, :], xs,
                           np.full(len(xs), r))
        want[:, j] = items[idx]
    assert np.array_equal(got, want)


def test_osdmap_batch_matches_scalar():
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from test_osdmap import build_map, mark_down
    m = build_map()
    mark_down(m, 3)
    from ceph_tpu.osd.osdmap import Incremental
    inc = Incremental(m.epoch + 1)
    inc.new_weight[7] = 0
    inc.new_primary_affinity[1] = 0x4000
    m.apply_incremental(inc)
    for pool in (1, 2):
        batch = m.map_pgs_batch(pool)
        for pg, up, upp, acting, actp in batch:
            assert (up, upp, acting, actp) == m.pg_to_up_acting_osds(pg)


def test_indep_numrep_exceeds_result_max_keeps_r_stride():
    """crush_do_rule splits out_size (slots: min(numrep, result_max))
    from numrep (the r stride: r = rep + numrep*ftotal, mapper.c:668).
    A 'chooseleaf indep 6' rule queried with result_max=4 must keep the
    6-stride retry sequence — conflating the two diverges from the
    scalar mapper whenever any retry fires."""
    m, _, ec = build(12, 2, ec_size=6)      # rule arg numrep = 6
    # degraded weights force retries so the stride actually matters
    for wname, wfn in WEIGHT_CASES:
        assert_match(m, ec, 4, wfn(12))
        assert_match(m, ec, 2, wfn(12))


# ================================================ ISSUE 16: widened scope
# uniform buckets (perm-choose), mixed bucket algs within one map, mixed
# firstn+indep rule programs, and the per-map-object compile cache.


def build_uniform(n_osds, per_host, ec_size=6):
    from ceph_tpu.crush.constants import BUCKET_UNIFORM
    m = CrushMap()
    m.max_devices = n_osds
    build_hierarchy(m, n_osds, per_host, alg=BUCKET_UNIFORM)
    rep = make_replicated_rule(m, "rep")
    ec = make_erasure_rule(m, "ec", size=ec_size)
    return m, rep, ec


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
@pytest.mark.parametrize("n_osds,per_host", [(12, 2), (12, 3), (8, 4)])
def test_uniform_firstn_bit_exact(n_osds, per_host, wname, wfn):
    m, rep, _ = build_uniform(n_osds, per_host)
    assert compile_rule(m, rep) is not None
    for numrep in (1, 2, 3):
        assert_match(m, rep, numrep, wfn(n_osds))


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
@pytest.mark.parametrize("size", [3, 4, 6])
def test_uniform_indep_bit_exact(size, wname, wfn):
    # 12 osds / 2 per host = 6 hosts: sizes 3 and 6 divide the root
    # bucket evenly (the uniform (numrep+1)*ftotal r-bump of
    # choose_indep fires); size 4 does not (plain numrep*ftotal)
    m, _, ec = build_uniform(12, 2, ec_size=size)
    assert compile_rule(m, ec) is not None
    assert_match(m, ec, size, wfn(12))


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
def test_uniform_leaf_bump_bit_exact(wname, wfn):
    # host size 6 with numrep 3/6: the r-bump fires on the LEAF level
    # of the chooseleaf recursion too (host.size % numrep == 0)
    m, _, _ = build_uniform(30, 6, ec_size=3)
    ec6 = make_erasure_rule(m, "ec6", size=6)
    ec3 = m.find_rule(1, 3, 3)
    assert compile_rule(m, ec3) is not None
    assert_match(m, ec3, 3, wfn(30))
    assert_match(m, ec6, 6, wfn(30))


def test_mixed_alg_levels_bit_exact():
    """straw2 root over UNIFORM hosts (and the reverse): alg is static
    PER LEVEL, so one map may mix draw kinds across levels."""
    from ceph_tpu.crush.builder import make_bucket
    from ceph_tpu.crush.constants import BUCKET_STRAW2, BUCKET_UNIFORM
    for root_alg, host_alg in ((BUCKET_STRAW2, BUCKET_UNIFORM),
                               (BUCKET_UNIFORM, BUCKET_STRAW2)):
        m = CrushMap()
        m.max_devices = 30
        hosts = []
        for h in range(5):
            items = list(range(h * 6, h * 6 + 6))
            hb = make_bucket(m, host_alg, 1, items, [0x10000] * 6)
            m.name_map[hb.id] = f"host{h}"
            hosts.append(hb)
        root = make_bucket(m, root_alg, 10, [b.id for b in hosts],
                           [b.weight for b in hosts])
        m.name_map[root.id] = "default"
        rep = make_replicated_rule(m, "rep")
        ec = make_erasure_rule(m, "ec", size=4)
        assert compile_rule(m, rep) is not None
        assert compile_rule(m, ec) is not None
        for wname, wfn in WEIGHT_CASES:
            assert_match(m, rep, 3, wfn(30))
            assert_match(m, ec, 4, wfn(30))


def test_mixed_firstn_indep_rule_bit_exact():
    """One rule program mixing a firstn segment and an indep segment
    (TAKE;CHOOSELEAF_FIRSTN;EMIT;TAKE;CHOOSELEAF_INDEP;EMIT) compiles
    and matches the scalar mapper — including the cumulative
    result_max cap landing mid-segment (indep holes included)."""
    from ceph_tpu.crush.constants import (RULE_CHOOSELEAF_FIRSTN,
                                          RULE_CHOOSELEAF_INDEP,
                                          RULE_EMIT, RULE_TAKE)
    from ceph_tpu.crush.types import Rule, RuleStep
    m, _, _ = build(24, 2)
    root = next(i for i, n in m.name_map.items() if n == "default")
    rule = Rule(ruleset=9, type=1, min_size=1, max_size=10,
                steps=[RuleStep(RULE_TAKE, root),
                       RuleStep(RULE_CHOOSELEAF_FIRSTN, 2, 1),
                       RuleStep(RULE_EMIT),
                       RuleStep(RULE_TAKE, root),
                       RuleStep(RULE_CHOOSELEAF_INDEP, 4, 1),
                       RuleStep(RULE_EMIT)])
    ruleno = m.add_rule(rule)
    assert compile_rule(m, ruleno) is not None
    for wname, wfn in WEIGHT_CASES:
        assert_match(m, ruleno, 8, wfn(24))   # both segments in full
        assert_match(m, ruleno, 5, wfn(24))   # cap lands mid-indep


def test_uniform_osdmap_every_pg_every_rule():
    """OSDMap-level parity on a uniform-alg map: EVERY pgid of every
    pool through map_pgs_batch == the scalar pg_to_up_acting_osds."""
    from ceph_tpu.crush.constants import BUCKET_UNIFORM
    from ceph_tpu.msg.types import EntityAddr
    from ceph_tpu.osd.osdmap import Incremental, OSDMap
    from ceph_tpu.osd.types import (OSD_IN_WEIGHT, PGPool,
                                    POOL_TYPE_ERASURE,
                                    POOL_TYPE_REPLICATED)
    m = OSDMap()
    m.fsid = "uniform-fsid"
    crush = CrushMap()
    crush.max_devices = 12
    build_hierarchy(crush, 12, 2, alg=BUCKET_UNIFORM)
    rep_rule = make_replicated_rule(crush, "replicated_rule")
    ec_rule = make_erasure_rule(crush, "ec_rule", size=6)
    m.crush = crush
    m.set_max_osd(12)
    inc = Incremental(1)
    for o in range(12):
        inc.new_up[o] = EntityAddr("127.0.0.1", 6800 + o, o + 1)
        inc.new_weight[o] = OSD_IN_WEIGHT
    m.apply_incremental(inc)
    m.pools[1] = PGPool(POOL_TYPE_REPLICATED, size=3,
                        crush_ruleset=rep_rule, pg_num=32)
    m.pool_names[1] = "rbd"
    m.pools[2] = PGPool(POOL_TYPE_ERASURE, size=6, min_size=5,
                        crush_ruleset=ec_rule, pg_num=32,
                        ec_profile="k4m2")
    m.pool_names[2] = "ecpool"
    inc = Incremental(m.epoch + 1)
    inc.new_weight[7] = 0x8000          # degraded: retries fire
    m.apply_incremental(inc)
    for pool in (1, 2):
        batch = m.map_pgs_batch(pool)
        assert len(batch) == 32
        for pg, up, upp, acting, actp in batch:
            assert (up, upp, acting, actp) == m.pg_to_up_acting_osds(pg)


def test_compile_cache_per_map_object():
    """Guarded compile cache: steady-state compile_rule calls against
    the SAME map object note exactly one real compile per rule; a new
    map object (epoch churn via from_bytes) recompiles once; in-place
    mutation drops the attached cache."""
    from ceph_tpu.common import devstats
    m, rep, ec = build(12, 2)

    def compiles():
        return devstats.counters()["compiles"].get("crush_compile", 0)

    base = compiles()
    assert compile_rule(m, rep) is not None
    after_first = compiles()
    assert after_first == base + 1
    for _ in range(5):                  # steady state: pure cache hits
        assert compile_rule(m, rep) is not None
    assert compiles() == after_first
    assert compile_rule(m, ec) is not None   # second rule: one more
    assert compiles() == after_first + 1

    m2 = CrushMap.from_bytes(m.to_bytes())   # epoch churn: new object
    assert compile_rule(m2, rep) is not None
    assert compiles() == after_first + 2
    assert compile_rule(m2, rep) is not None
    assert compiles() == after_first + 2

    # in-place mutation invalidates: the next call REALLY recompiles
    from ceph_tpu.crush.builder import reweight_item
    host0 = m2.bucket(next(i for i, n in m2.name_map.items()
                           if n == "host0"))
    reweight_item(m2, host0, 0, 0x8000)
    assert not hasattr(m2, "_kernel_compile_cache")
    assert compile_rule(m2, rep) is not None
    assert compiles() == after_first + 3
