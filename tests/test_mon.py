"""Monitor tests: election, paxos commit/recovery, commands, subscriptions.

Models the reference's mon test strategy (test/mon/*.sh: single and
multi-mon clusters, leader kill, command behavior) in-process with
asyncio + MemDB-backed stores.
"""

import asyncio
import errno

import pytest

from ceph_tpu.common.context import Context
from ceph_tpu.mon import CommandError, MonClient, Monitor
from ceph_tpu.mon.messages import MOSDBoot, MOSDFailure
from ceph_tpu.mon.monmap import MonMap
from ceph_tpu.msg.messenger import Messenger
from ceph_tpu.msg.types import EntityAddr, EntityName
from ceph_tpu.store.kv import MemDB

FAST_CFG = {
    "mon_election_timeout": 0.3,
    "mon_lease": 1.0,
    "mon_tick_interval": 0.5,
    "ms_initial_backoff": 0.02,
}


async def start_mons(n, stores=None):
    """Boot an n-mon cluster on ephemeral ports; returns (monmap, mons)."""
    monmap = MonMap()
    monmap.fsid = "fsid-test"
    msgrs = []
    for i in range(n):
        name = chr(ord("a") + i)
        ctx = Context(f"mon.{name}")
        for k, v in FAST_CFG.items():
            ctx.config.set(k, v)
        msgr = Messenger(ctx, EntityName("mon", name))
        addr = await msgr.bind()
        monmap.add(name, addr)
        msgrs.append((ctx, name, msgr))
    mons = []
    for i, (ctx, name, msgr) in enumerate(msgrs):
        store = stores[i] if stores else MemDB()
        mon = Monitor(ctx, name, monmap, store, msgr)
        await mon.start()
        mons.append(mon)
    return monmap, mons


async def wait_quorum(mons, timeout=15.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        leaders = [m for m in mons if m.is_leader()
                   and m.paxos.state == "active"]
        if leaders and len(leaders) == 1:
            return leaders[0]
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(
                f"no quorum: {[(m.name, m.state, m.paxos.state) for m in mons]}")
        await asyncio.sleep(0.05)


async def make_client(monmap):
    ctx = Context("client.admin")
    for k, v in FAST_CFG.items():
        ctx.config.set(k, v)
    msgr = Messenger(ctx, EntityName("client", "admin"))
    await msgr.bind()   # bound so the mon can push maps back
    return MonClient(ctx, msgr, monmap), msgr


async def stop_all(mons, extra_msgrs=()):
    for m in mons:
        await m.shutdown()
    for ms in extra_msgrs:
        await ms.shutdown()


def test_single_mon_bootstrap_and_commands():
    async def run():
        monmap, mons = await start_mons(1)
        leader = await wait_quorum(mons)
        assert leader.osdmon.osdmap.epoch >= 1   # create_initial committed
        client, cmsgr = await make_client(monmap)
        ack = await client.command({"prefix": "status"})
        assert "fsid-test" in ack.outs
        ack = await client.command({"prefix": "osd crush build-simple",
                                    "num_osds": 4, "osds_per_host": 2})
        ack = await client.command({"prefix": "osd pool create",
                                    "pool": "data", "pg_num": 8})
        assert "created" in ack.outs
        ack = await client.command({"prefix": "osd pool ls"})
        assert "data" in ack.outs
        ack = await client.command({"prefix": "osd dump"})
        from ceph_tpu.osd.osdmap import OSDMap
        m = OSDMap.from_bytes(ack.outbl)
        assert m.lookup_pool("data") >= 0
        assert m.max_osd == 4
        with pytest.raises(CommandError):
            await client.command({"prefix": "bogus"})
        await stop_all(mons, [cmsgr])
    asyncio.run(run())


def test_osd_boot_failure_and_subscription():
    async def run():
        monmap, mons = await start_mons(1)
        leader = await wait_quorum(mons)
        client, cmsgr = await make_client(monmap)
        await client.command({"prefix": "osd crush build-simple",
                              "num_osds": 3, "osds_per_host": 1})
        # osd.0..2 boot (as osd entities)
        osd_msgrs = []
        for i in range(3):
            ctx = Context(f"osd.{i}")
            for k, v in FAST_CFG.items():
                ctx.config.set(k, v)
            om = Messenger(ctx, EntityName("osd", str(i)))
            addr = await om.bind()
            om.send_message(MOSDBoot(i, addr), monmap.addr_of_rank(0),
                            peer_type="mon")
            osd_msgrs.append(om)
        # client learns the new map via subscription
        deadline = asyncio.get_event_loop().time() + 10
        while True:
            m = await client.wait_for_osdmap()
            if m.count_up() == 3:
                break
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.05)
        assert m.is_up(0) and m.is_up(1) and m.is_up(2)
        # failure report from osd.1 against osd.2
        osd_msgrs[1].send_message(
            MOSDFailure(target_osd=2, epoch=m.epoch),
            monmap.addr_of_rank(0), peer_type="mon")
        deadline = asyncio.get_event_loop().time() + 10
        while client.osdmap.is_up(2):
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.05)
        assert client.osdmap.is_in(2)   # down but not yet out
        await stop_all(mons, osd_msgrs + [cmsgr])
    asyncio.run(run())


def test_three_mon_election_and_commit():
    async def run():
        monmap, mons = await start_mons(3)
        leader = await wait_quorum(mons)
        assert leader.rank == 0     # lowest rank wins
        peons = [m for m in mons if m is not leader]
        assert all(m.state == "peon" for m in peons)
        client, cmsgr = await make_client(monmap)
        await client.command({"prefix": "osd crush build-simple",
                              "num_osds": 2, "osds_per_host": 1})
        await client.command({"prefix": "osd pool create", "pool": "p3",
                              "pg_num": 4})
        # peons replicate the committed state
        deadline = asyncio.get_event_loop().time() + 10
        while True:
            if all(m.osdmon.osdmap.lookup_pool("p3") >= 0 for m in peons):
                break
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.05)
        await stop_all(mons, [cmsgr])
    asyncio.run(run())


def test_command_to_peon_redirects():
    async def run():
        monmap, mons = await start_mons(3)
        await wait_quorum(mons)
        client, cmsgr = await make_client(monmap)
        client.cur_mon = 2          # deliberately talk to a peon first
        ack = await client.command({"prefix": "status"})
        assert client.cur_mon == 0  # followed the leader hint
        await stop_all(mons, [cmsgr])
    asyncio.run(run())


def test_leader_failover():
    async def run():
        monmap, mons = await start_mons(3)
        leader = await wait_quorum(mons)
        client, cmsgr = await make_client(monmap)
        await client.command({"prefix": "osd crush build-simple",
                              "num_osds": 2, "osds_per_host": 1})
        await client.command(
            {"prefix": "osd pool create", "pool": "before", "pg_num": 4})
        # kill the leader
        await leader.shutdown()
        rest = [m for m in mons if m is not leader]
        # surviving mons elect rank 1; wait for an active new leader
        new_leader = await wait_quorum(rest, timeout=30)
        assert new_leader.rank == 1
        assert new_leader.osdmon.osdmap.lookup_pool("before") >= 0
        # cluster still serves writes
        ack = await client.command(
            {"prefix": "osd pool create", "pool": "after", "pg_num": 4},
            timeout=30)
        # a retry racing the failover may find the pool already committed
        # by the dead leader — both outcomes are correct
        assert ack.retcode == 0 and ("created" in ack.outs
                                     or "exists" in ack.outs)
        await stop_all(rest, [cmsgr])
    asyncio.run(run())


def test_mon_restart_preserves_state():
    async def run():
        stores = [MemDB()]
        monmap, mons = await start_mons(1, stores=stores)
        await wait_quorum(mons)
        client, cmsgr = await make_client(monmap)
        await client.command({"prefix": "osd crush build-simple",
                              "num_osds": 2, "osds_per_host": 1})
        await client.command({"prefix": "osd pool create",
                              "pool": "persist", "pg_num": 4})
        epoch_before = mons[0].osdmon.osdmap.epoch
        await mons[0].shutdown()

        # restart with same store + same monmap address
        ctx = Context("mon.a")
        for k, v in FAST_CFG.items():
            ctx.config.set(k, v)
        msgr = Messenger(ctx, EntityName("mon", "a"))
        mon2 = Monitor(ctx, "a", monmap, stores[0], msgr)
        await mon2.start()
        leader = await wait_quorum([mon2])
        assert leader.osdmon.osdmap.epoch >= epoch_before
        assert leader.osdmon.osdmap.lookup_pool("persist") >= 0
        await stop_all([mon2], [cmsgr])
    asyncio.run(run())


def test_subscription_before_first_commit_bootstraps():
    """A subscriber that arrives before the mon's first osdmap commit
    must still bootstrap: the mon must not serve an epoch-0 push and
    advance the cursor past the full map (vstart race: early osds
    stayed mapless forever on incrementals they couldn't chain)."""
    async def run():
        monmap, mons = await start_mons(1)
        mon = mons[0]
        monc, msgr = await make_client(monmap)
        # simulate the race: cursor at 0 while the mon has no map yet
        sub = {"_addr": msgr.addr, "_type": "client", "osdmap": 0}
        saved_epoch = mon.osdmon.osdmap.epoch
        mon.osdmon.osdmap.epoch = 0
        mon._push_maps_to(sub)
        assert sub["osdmap"] == 0, \
            "cursor must not advance past an unserved epoch-0 push"
        mon.osdmon.osdmap.epoch = saved_epoch
        # normal path still works end to end
        await wait_quorum(mons)
        monc.sub_want("osdmap", 0)
        got = await monc.wait_for_osdmap(timeout=10)
        assert got.epoch >= 1
        await stop_all(mons, [msgr])
    asyncio.run(run())


def test_monclient_rerequests_full_on_unbridgeable_incrementals():
    """Incrementals with no base map (or a gap) must trigger a full-map
    re-request instead of being skipped silently."""
    from ceph_tpu.mon.messages import MOSDMap

    class _Rec:
        def __init__(self):
            self.sent = []

        def send_message(self, msg, addr, peer_type=None):
            self.sent.append(msg)

    async def run():
        monmap, mons = await start_mons(1)
        await wait_quorum(mons)
        monc, msgr = await make_client(monmap)
        monc._subs["osdmap"] = 5
        rec = _Rec()
        monc.messenger = rec            # capture the re-subscription
        m = MOSDMap()
        m.incrementals[7] = b"\x00"     # no base: cannot chain onto None
        monc._handle_osdmap(m)
        assert monc._subs["osdmap"] == 0, "must reset to request full map"
        assert rec.sent, "must re-send the subscription"
        await stop_all(mons, [msgr])
    asyncio.run(run())


import sys as _sys
_sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402


def test_fsmonitor_is_a_paxos_service():
    """mds boot commits through the FSMap PaxosService (epoch-versioned
    store state), and dump resolves it — mon/MDSMonitor.cc role."""
    async def run():
        import json
        cl = Cluster()
        admin = await cl.start(3)
        await admin.mon_command({"prefix": "mds boot", "name": "mds.x",
                                 "addr": "127.0.0.1:7777/1"})
        ack = await admin.mon_command({"prefix": "mds dump"})
        out = json.loads(ack.outs)
        assert out["mds.x"]["addr"] == "127.0.0.1:7777/1"
        # committed as an epoch-versioned map under the fsmap prefix
        mon = cl.mons[0]
        assert mon.fsmon.epoch >= 1
        blob = mon.store_get("fsmap", f"full_{mon.fsmon.epoch}")
        assert blob and "mds.x" in blob.decode()
        await cl.stop()
    asyncio.run(run())


def test_object_names_with_cursor_sentinel_rejected():
    async def run():
        from ceph_tpu.client.objecter import ObjectOperationError
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=4)
        io = admin.open_ioctx("p")
        with pytest.raises(ObjectOperationError):
            await io.write_full("bad\U0010ffffname", b"x")
        await cl.stop()
    asyncio.run(run())


def test_osdmap_msg_shared_across_subscribers():
    """ISSUE 5 satellite: the mon builds ONE MOSDMap message per epoch
    range and shares it across subscriber sessions — the message's
    lazy wire cache then means ONE body encode per epoch range no
    matter how many daemons subscribe (previously each push re-built
    and re-encoded its own copy)."""
    from ceph_tpu.msg import payload as payload_mod

    async def run():
        monmap, mons = await start_mons(1)
        leader = await wait_quorum(mons)
        client, cmsgr = await make_client(monmap)
        # commit a couple of epochs so there is a real range to ship
        await client.command({"prefix": "osd crush build-simple",
                              "num_osds": 4})
        await client.command({"prefix": "osd setmaxosd", "num": 8})
        e = leader.osdmon.osdmap.epoch
        assert e >= 2
        m1 = leader.osdmon.build_osdmap_msg(1, e)
        m2 = leader.osdmon.build_osdmap_msg(1, e)
        assert m1 is m2                      # one message per range
        assert leader.osdmon.osdmap_msgs_shared >= 1
        payload_mod.reset_counters()
        w1, w2 = m1.wire_bytes(), m2.wire_bytes()
        assert w1 is w2                      # one ENCODE per range
        assert payload_mod.counters()["msg_encode_calls"] == 1
        # a different range is its own (cached) message
        m3 = leader.osdmon.build_osdmap_msg(e, e)
        assert m3 is not m1
        assert leader.osdmon.build_osdmap_msg(e, e) is m3
        await stop_all(mons, [cmsgr])

    asyncio.run(run())


def test_osdmap_encode_shared_in_multi_osd_cluster():
    """5 subscribing OSDs (plus the admin client) ride shared MOSDMap
    messages: the mon re-uses cached messages across sessions, so
    builds stay bounded by distinct epoch RANGES (not sessions) and
    sharing actually happens during boot."""
    async def run():
        cl = Cluster()
        admin = await cl.start(5)
        await admin.pool_create("shr", pg_num=4)
        io = admin.open_ioctx("shr")
        await io.write_full("o", b"x")
        osdmon = cl.mons[0].osdmon
        built, shared = osdmon.osdmap_msgs_built, osdmon.osdmap_msgs_shared
        # with 6+ subscribers tracking the same epochs, pushes must hit
        # the cache: encodes scale with distinct ranges, not sessions
        assert shared > 0, (built, shared)
        await cl.stop()

    asyncio.run(run())
