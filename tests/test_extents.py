"""Shared-memory payload extents (ISSUE 20 tentpole, osd/extents.py).

Coverage map:
  * refcount balance on the three op outcomes that matter — commit
    (materialize + release), abort (release without materialize) and
    EAGAIN requeue (materialize twice, release once): every alloc gets
    exactly one free, late/stale frees are refused and counted;
  * lane-death reclaim is LOUD — sweep_all force-frees every live slot
    with a warning and an ``ext_swept`` count, and post-sweep frees /
    fetches hit the ABA generation guard instead of a new tenant;
  * threshold routing byte-identity — a data_bytes_ round trip through
    an extent sink diverts only at-or-over-threshold payloads, and the
    materialized bytes are identical to the inline path's on both
    sides of the threshold (pool-full falls back inline, also
    byte-identical);
  * the schedule-explorer invariant via ``extents.OBSERVER`` — across
    seeded adversarial interleavings of producer/consumer tasks, no
    extent outlives its last reference: refs never dip below zero,
    ``free`` fires exactly at refs==0, nothing stays live at the end.
"""

import logging
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from ceph_tpu.common.encoding import Decoder, Encoder  # noqa: E402
from ceph_tpu.osd import extents  # noqa: E402
from ceph_tpu.osd.extents import ExtentPool, ExtentSink  # noqa: E402


@pytest.fixture()
def pool():
    extents.reset_counters()
    p = ExtentPool(capacity=1 << 20, threshold=4096, create=True).register()
    try:
        yield p
    finally:
        assert extents.OBSERVER is None  # tests must restore the hook
        p.sweep_all("test teardown")
        p.close()
        p.unlink()
        extents.detach_all()


# -------------------------------------------------------- refcount balance


def test_refcount_commit_path_balances(pool):
    data = b"x" * 8192
    h = pool.put(data)
    assert h is not None and pool.live == 1
    ref = extents.make_ref(*h)
    assert len(ref) == len(data)
    assert ref.materialize() == data
    # the EAGAIN shape: a requeued op touches its payload again — the
    # cached copy serves it, and the slot is still held
    assert ref.materialize() == data
    assert pool.live == 1
    ref.release()
    assert pool.live == 0
    ref.release()  # idempotent: the commit callback may race a drop
    c = extents.counters()
    assert c["ext_allocs"] == 1 and c["ext_frees"] == 1
    assert c["ext_stale_free"] == 0
    assert c["ext_reads"] == 1  # one copy out, not one per touch


def test_refcount_abort_path_releases_without_read(pool):
    h = pool.put(b"y" * 5000)
    ref = extents.make_ref(*h)
    ref.release()  # op errored out before ever touching the payload
    c = extents.counters()
    assert c["ext_allocs"] == 1 and c["ext_frees"] == 1
    assert c["ext_reads"] == 0
    assert pool.live == 0


def test_fanout_refs_free_on_last_release_only(pool):
    # replica fan-out: one slot, refcount preset to the consumer count
    h = pool.put(b"z" * 6000, refs=3)
    for i in range(3):
        assert pool.live == 1, f"freed after {i} of 3 releases"
        extents.release(h)
    assert pool.live == 0
    c = extents.counters()
    assert c["ext_allocs"] == 1 and c["ext_frees"] == 1


def test_pool_full_returns_none_and_counts(pool):
    h1 = pool.put(b"a" * (1 << 20))  # fills the arena exactly
    assert h1 is not None
    assert pool.put(b"b" * 4096) is None
    c = extents.counters()
    assert c["ext_alloc_full"] == 1
    extents.release(h1)
    assert pool.put(b"b" * 4096) is not None  # space came back


# ---------------------------------------------------- lane-death reclaim


def test_lane_death_sweep_is_loud_and_aba_safe(pool, caplog):
    handles = [pool.put(bytes([i]) * 4096) for i in range(4)]
    assert pool.live == 4
    with caplog.at_level(logging.WARNING, "ceph-tpu.osd.extents"):
        swept = pool.sweep_all("lane 0 worker died")
    assert swept == 4 and pool.live == 0
    assert any("swept 4 live slot" in r.getMessage()
               for r in caplog.records)
    c = extents.counters()
    assert c["ext_swept"] == 4
    # a straggler free arriving after the sweep is refused (ABA guard),
    # not applied to whatever reuses the offset next
    extents.release(handles[0])
    assert extents.counters()["ext_stale_free"] == 1
    # and a late read of a swept generation fails loudly
    with pytest.raises(KeyError):
        pool.read(handles[1][2], handles[1][3], handles[1][1])
    # the arena is whole again: a full-size alloc fits
    h = pool.put(b"c" * (1 << 20))
    assert h is not None
    extents.release(h)


# ------------------------------------------- threshold routing + identity


def _roundtrip(payload: bytes, sink):
    enc = Encoder()
    enc.extent_sink = sink
    enc.data_bytes_(payload)
    out = Decoder(bytes(enc.buf)).data_bytes_()
    return bytes(enc.buf), extents.materialize(out), out


def test_threshold_routing_byte_identity(pool):
    sink = ExtentSink(pool)
    small = bytes(range(256)) * 15            # 3840 < threshold 4096
    big = bytes(reversed(range(256))) * 17    # 4352 >= threshold

    wire_small, got_small, raw_small = _roundtrip(small, sink)
    assert got_small == small
    assert not getattr(raw_small, "_is_extent_ref", False)
    assert extents.counters()["ext_allocs"] == 0
    # below threshold the sink must not change the wire at all
    plain = Encoder()
    plain.data_bytes_(small)
    assert wire_small == bytes(plain.buf)

    wire_big, got_big, raw_big = _roundtrip(big, sink)
    assert got_big == big
    assert getattr(raw_big, "_is_extent_ref", False)
    assert extents.counters()["ext_allocs"] == 1
    # the handle really is tiny: the payload bytes stayed off the wire
    assert len(wire_big) < 64
    raw_big.release()
    assert pool.live == 0

    # pool-full fallback: inline, still byte-identical
    filler = pool.put(b"f" * (1 << 20))
    _wire, got_fb, raw_fb = _roundtrip(big, sink)
    assert got_fb == big
    assert not getattr(raw_fb, "_is_extent_ref", False)
    extents.release(filler)


def test_reencode_of_ref_materializes_never_leaks_handle(pool):
    # a lane-received message re-encoded for a REAL wire (no sink) must
    # carry bytes, not a shared-memory handle another host can't see
    h = pool.put(b"w" * 8192)
    ref = extents.make_ref(*h)
    enc = Encoder()
    enc.data_bytes_(ref)
    assert Decoder(bytes(enc.buf)).data_bytes_() == b"w" * 8192
    ref.release()


# ---------------------------------------- schedule-explorer invariant


class _LifetimeObserver:
    """Per-offset lifetime checker for extents.OBSERVER: alloc opens a
    segment, incref/decref move within it (never below zero), free
    closes it exactly at refs==0; any event outside an open segment —
    an extent outliving its last reference, or dying before it — is a
    finding."""

    def __init__(self):
        self.open = {}      # (pool, off) -> refs
        self.findings = []
        self.allocs = 0
        self.closes = 0

    def __call__(self, pool, event, off, refs_after):
        key = (pool, off)
        if event == "alloc":
            if key in self.open:
                self.findings.append(f"alloc over live slot {key}")
            self.open[key] = refs_after
            self.allocs += 1
            return
        if key not in self.open:
            self.findings.append(f"{event} on dead slot {key}")
            return
        if event in ("incref", "decref"):
            self.open[key] = refs_after
            if refs_after < 0:
                self.findings.append(f"refs below zero on {key}")
        elif event in ("free", "sweep"):
            if event == "free" and self.open[key] != 0:
                self.findings.append(
                    f"free at refs={self.open[key]} on {key}")
            del self.open[key]
            self.closes += 1


def test_schedule_explorer_no_extent_outlives_last_ref():
    """Seeded adversarial interleavings of producer/consumer tasks over
    one pool: whatever order the scheduler wakes them in, every slot's
    observed lifetime is alloc -> refs -> free-at-zero, and nothing is
    live once the schedule drains."""
    import asyncio

    from ceph_tpu.devtools.schedule import (
        RandomScheduler, run_deterministic)

    async def churn(pool, idx):
        payloads = [bytes([idx * 16 + j]) * (4096 + 512 * j)
                    for j in range(4)]
        refs = []
        for p in payloads:
            h = pool.put(p, refs=2)
            assert h is not None
            await asyncio.sleep(0)
            # consumer one: materializes, then commits
            r = extents.make_ref(*h)
            assert r.materialize() == p
            refs.append(r)
            await asyncio.sleep(0)
            # consumer two: aborts without touching the bytes
            extents.release(h)
        await asyncio.sleep(0)
        for r in refs:
            r.release()
            await asyncio.sleep(0)

    for seed in range(8):
        extents.reset_counters()
        obs = _LifetimeObserver()
        pool = ExtentPool(capacity=1 << 20, threshold=4096,
                          create=True).register()
        extents.OBSERVER = obs
        try:
            async def main():
                await asyncio.gather(*(churn(pool, i) for i in range(4)))

            run_deterministic(main, seed=seed,
                              controller=RandomScheduler(seed))
        finally:
            extents.OBSERVER = None
            pool.close()
            pool.unlink()
        assert not obs.findings, f"seed {seed}: {obs.findings}"
        assert obs.open == {}, f"seed {seed}: live at drain: {obs.open}"
        assert obs.allocs == 16 and obs.closes == 16, (seed, obs.allocs,
                                                       obs.closes)
        c = extents.counters()
        assert c["ext_allocs"] == c["ext_frees"] == 16, (seed, c)
        assert c["ext_stale_free"] == 0 and c["ext_ref_gc"] == 0, (seed, c)
