"""Encoding-compatibility corpus (ceph-dencoder + ceph-object-corpus
role, reference src/test/encoding/readable.sh): every Encodable type
has a committed sample encoding under tests/corpus/; this suite fails
if a change silently breaks an on-disk or wire format.

Contract:
- committed bytes must always DECODE (backward compat — old stores and
  peers speak old versions);
- if the type's STRUCT_V still equals the corpus version, re-encoding
  the decoded object must reproduce the bytes EXACTLY (no silent format
  drift within a version);
- if STRUCT_V advanced, decode-then-reencode must survive a second
  decode (the new encoder still frames correctly) — and the corpus
  should be regenerated (python tests/corpus_gen.py) in the same
  change.
- every Encodable subclass in the package is covered or explicitly
  excluded with a reason (corpus_gen.EXCLUDED).
"""

import importlib
import pathlib
import pkgutil
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
import corpus_gen  # noqa: E402

CORPUS = sorted(corpus_gen.CORPUS_DIR.glob("*.bin"))


def _load_type(dotted: str):
    mod, _, cls = dotted.rpartition(".")
    return getattr(importlib.import_module(mod), cls)


def test_corpus_exists_and_covers_every_encodable():
    import ceph_tpu
    from ceph_tpu.common.encoding import Encodable
    for m in pkgutil.walk_packages(ceph_tpu.__path__, "ceph_tpu."):
        try:
            importlib.import_module(m.name)
        except Exception:
            pass
    seen = set()

    def walk(cls):
        for c in cls.__subclasses__():
            if c not in seen:
                seen.add(c)
                walk(c)
    walk(Encodable)
    have = {p.stem for p in CORPUS}
    missing = []
    for c in seen:
        name = f"{c.__module__}.{c.__name__}"
        if name in corpus_gen.EXCLUDED or name.startswith("tests."):
            continue
        if c.__module__.startswith("test") or "conftest" in c.__module__:
            continue
        if name not in have:
            missing.append(name)
    assert not missing, (
        f"Encodable types without corpus coverage: {sorted(missing)} — "
        f"add samples to tests/corpus_gen.py and regenerate")


def test_codec_registry_types_all_have_corpus_files():
    """The corpus-coverage satellite: every wire type registered with
    the message codec (msg.message._REGISTRY — what the messenger can
    actually put on a socket) has a committed tests/corpus/*.bin
    round-trip file, and corpus_gen.registry_samples() can emit a
    sample for each, so a new @register_message type cannot ship
    uncovered (MOSDOpBatch got its sample by hand in PR 10 — this
    makes forgetting impossible)."""
    corpus_gen._import_package()
    from ceph_tpu.msg.message import _REGISTRY
    have = {p.stem for p in CORPUS}
    missing = []
    for code, cls in sorted(_REGISTRY.items()):
        name = f"{cls.__module__}.{cls.__name__}"
        if name in corpus_gen.EXCLUDED \
                or cls.__module__.split(".")[-1].startswith(("test", "conftest")):
            continue
        if name not in have:
            missing.append(f"{name} (type {code})")
    assert not missing, (
        f"registered wire types without corpus coverage: {missing} — "
        f"run `python tests/corpus_gen.py` (registry_samples() emits "
        f"default-constructed samples; hand-write one if construction "
        f"needs arguments)")
    # and the generator covers the whole registry, so regenerating
    # emits every registered type
    emitted = set(corpus_gen.registry_samples())
    for code, cls in sorted(_REGISTRY.items()):
        name = f"{cls.__module__}.{cls.__name__}"
        if name in corpus_gen.EXCLUDED \
                or cls.__module__.split(".")[-1].startswith(("test", "conftest")):
            continue
        assert name in emitted, name


@pytest.mark.parametrize("path", CORPUS, ids=[p.stem for p in CORPUS])
def test_committed_corpus_round_trips(path):
    cls = _load_type(path.stem)
    blob = path.read_bytes()
    corpus_v = blob[0]
    obj = cls.from_bytes(blob)          # backward compat: MUST decode
    re1 = obj.to_bytes()
    if cls.STRUCT_V == corpus_v:
        assert re1 == blob, (
            f"{path.stem}: same STRUCT_V ({corpus_v}) but different "
            f"bytes — the format changed without a version bump")
    # whatever the version, the re-encoding must survive another cycle
    obj2 = cls.from_bytes(re1)
    assert obj2.to_bytes() == re1, f"{path.stem}: unstable re-encode"


def test_fresh_samples_round_trip():
    for name, obj in corpus_gen.samples().items():
        cls = type(obj)
        blob = obj.to_bytes()
        again = cls.from_bytes(blob).to_bytes()
        assert again == blob, f"{name}: encode/decode not stable"
