"""Core runtime tests: config layering/observers, encoding framing, counters,
throttle (reference test analog: src/test/common/)."""

import threading

import pytest

from ceph_tpu.common.config import Config, Option
from ceph_tpu.common.context import Context, global_init
from ceph_tpu.common.encoding import Decoder, Encodable, Encoder
from ceph_tpu.common.perf_counters import PerfCounters
from ceph_tpu.common.throttle import Throttle


class TestConfig:
    def test_defaults_and_types(self):
        cfg = Config()
        assert cfg["osd_pool_default_size"] == 3
        assert isinstance(cfg["ms_dispatch_throttle_bytes"], int)
        assert cfg["ms_dispatch_throttle_bytes"] == 100 << 20

    def test_set_coerces(self):
        cfg = Config()
        cfg.set("osd_pool_default_size", "5")
        assert cfg["osd_pool_default_size"] == 5
        cfg.set("ms_tcp_nodelay", "false")
        assert cfg["ms_tcp_nodelay"] is False
        cfg.set("filestore_journal_size", "1g")
        assert cfg["filestore_journal_size"] == 1 << 30

    def test_unknown_raises(self):
        with pytest.raises(KeyError):
            Config().set("no_such_option", 1)

    def test_observer_fires_once_per_change(self):
        cfg = Config()
        seen = []
        cfg.add_observer(["mon_lease"], lambda ch: seen.append(set(ch)))
        cfg.set("mon_lease", 7.5)
        cfg.set("mon_lease", 7.5)  # no-op: same value
        assert seen == [{"mon_lease"}]

    def test_argv_and_injectargs(self):
        cfg = Config()
        rest = cfg.parse_argv(["--mon-lease", "9", "positional",
                               "--ms-type=simple"])
        assert rest == ["positional"]
        assert cfg["mon_lease"] == 9.0
        assert cfg["ms_type"] == "simple"
        cfg.injectargs("--mon-lease 11")
        assert cfg["mon_lease"] == 11.0

    def test_meta_expansion(self):
        cfg = Config()
        cfg.set_daemon_name("osd", "3")
        cfg.set("log_file", "/tmp/$name.log")
        assert cfg["log_file"] == "/tmp/osd.3.log"

    def test_conf_file_sections(self, tmp_path):
        p = tmp_path / "ceph.conf"
        p.write_text("""
[global]
mon lease = 2.5
[osd]
osd heartbeat grace = 99
[mon]
mon tick interval = 42
""")
        cfg = Config()
        cfg.set_daemon_name("osd", "0")
        cfg.parse_file(str(p))
        assert cfg["mon_lease"] == 2.5
        assert cfg["osd_heartbeat_grace"] == 99.0
        assert cfg["mon_tick_interval"] == 5.0  # [mon] section skipped


class Point(Encodable):
    STRUCT_V = 2
    STRUCT_COMPAT = 1

    def __init__(self, x=0, y=0, label=""):
        self.x, self.y, self.label = x, y, label

    def encode_payload(self, enc):
        enc.s32(self.x).s32(self.y).string(self.label)

    @classmethod
    def decode_payload(cls, dec, struct_v):
        x, y = dec.s32(), dec.s32()
        label = dec.string() if struct_v >= 2 else ""
        return cls(x, y, label)


class TestEncoding:
    def test_roundtrip(self):
        p = Point(-3, 7, "hello")
        assert Point.from_bytes(p.to_bytes()) == p

    def test_forward_compat_skips_trailing(self):
        # a v3 encoder appends a field; v2 decoder must skip it cleanly
        enc = Encoder()
        enc.u8(3).u8(1)
        lenpos = len(enc.buf)
        enc.u32(0)
        start = len(enc.buf)
        enc.s32(1).s32(2).string("x").u64(999)  # extra trailing field
        import struct as _s
        _s.pack_into("<I", enc.buf, lenpos, len(enc.buf) - start)
        enc.string("after")  # data following the struct
        dec = Decoder(enc.getvalue())
        p = Point.decode(dec)
        assert (p.x, p.y, p.label) == (1, 2, "x")
        assert dec.string() == "after"

    def test_incompat_rejected(self):
        enc = Encoder()
        enc.u8(9).u8(9).u32(0)
        with pytest.raises(ValueError):
            Point.decode(Decoder(enc.getvalue()))

    def test_containers(self):
        enc = Encoder()
        enc.map_({"b": 2, "a": 1}, lambda e, k: e.string(k),
                 lambda e, v: e.u32(v))
        enc.list_([Point(1, 1), Point(2, 2)], lambda e, p: e.struct(p))
        dec = Decoder(enc.getvalue())
        assert dec.map_(lambda d: d.string(), lambda d: d.u32()) == {"a": 1, "b": 2}
        pts = dec.list_(lambda d: Point.decode(d))
        assert pts[1].x == 2


class TestPerfThrottle:
    def test_counters(self):
        pc = PerfCounters("osd")
        pc.add_u64("ops")
        pc.add_time("op_lat")
        pc.inc("ops", 3)
        pc.tinc("op_lat", 0.5)
        d = pc.dump()
        assert d["ops"] == 3
        assert d["op_lat"]["avgcount"] == 1

    def test_throttle_blocks_and_releases(self):
        t = Throttle("b", 2)
        t.get(2)
        got = []

        def worker():
            t.get(1)
            got.append(1)

        th = threading.Thread(target=worker)
        th.start()
        assert not t.get_or_fail(1)
        t.put(2)
        th.join(timeout=5)
        assert got == [1]

    def test_oversized_grant_allowed_when_idle(self):
        # reference semantics: a request larger than max succeeds if count==0
        t = Throttle("b", 4)
        assert t.get_or_fail(10)
        t.put(10)


def test_context_and_global_init():
    ctx = global_init("osd.7", argv=["--log-level", "3"])
    assert ctx.config["log_level"] == 3
    log = ctx.logger("osd")
    log.info("boot")
    assert any("boot" in line for line in ctx.log.dump_recent())


def test_xxhash_canonical_vectors():
    """XXH32/XXH64 against the algorithm's published vectors (the
    reference bundles xxhash for BlueStore csum_type xxhash32/64)."""
    from ceph_tpu.common.xxhash import xxh32, xxh64
    assert xxh32(b"") == 0x02CC5D05
    assert xxh32(b"a") == 0x550D7456
    assert xxh32(b"abc") == 0x32D153FF
    assert xxh32(b"Nobody inspects the spammish repetition") \
        == 0xE2293B2F
    assert xxh32(b"x" * 1000, seed=7) == xxh32(b"x" * 1000, seed=7)
    assert xxh32(b"x" * 1000) != xxh32(b"x" * 999)
    assert xxh64(b"") == 0xEF46DB3751D8E999
    assert xxh64(b"a") == 0xD24EC4F1A98C6E5B
    assert xxh64(b"abc") == 0x44BC2CF5AD770999


def test_blockstore_xxhash_csum_pinned(tmp_path):
    """BlockStore csum_type=xxhash32 verifies reads, detects rot, and
    the type is PINNED at first mount — reopening with the default
    crc32c still verifies correctly."""
    from ceph_tpu.store.blockstore import BlockStore
    from ceph_tpu.store.objectstore import StoreError, Transaction
    from ceph_tpu.store.types import CollectionId, ObjectId
    cid, oid = CollectionId.pg(1, 0), ObjectId("o", pool=1)
    p = str(tmp_path / "bs")
    s = BlockStore(p, csum_type="xxhash32")
    s.mkfs(); s.mount()
    t = Transaction()
    t.create_collection(cid)
    t.write(cid, oid, 0, b"payload" * 1000)
    s.apply_transaction(t)
    assert s.read(cid, oid) == b"payload" * 1000
    s.umount()
    # reopen with the DEFAULT csum type: pinned xxhash32 must win
    s2 = BlockStore(p)
    s2.mount()
    assert s2.read(cid, oid) == b"payload" * 1000
    # bit rot detected under the pinned alg
    import os as _os
    blk = _os.path.join(p, "block")
    with open(blk, "r+b") as f:
        f.seek(100); b = f.read(1); f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(StoreError):
        s2.read(cid, oid)
    s2.umount()
