"""Incremental pglog persistence (ISSUE 13): PG.save_meta_log.

The write path no longer re-encodes the whole PGLog/missing blobs per
op (osd/PGLog.cc incremental omap writes): appends land as per-entry
``loge.*`` keys + an O(1) info/loghead head, compacted back into the
``log`` blob snapshot every META_COMPACT_EVERY appends.  Coverage:

  * layout — a served write burst leaves per-entry keys + the head
    record; the base blob only changes on full saves;
  * restart round-trip — an OSD restarted on the surviving store
    reloads the merged (blob + appends) log and serves reads;
  * legacy upgrade — a store written in the pre-incremental full-blob
    layout (no loge./loghead keys) loads byte-for-byte the same;
  * trim honoring — loghead's tail bound drops entries the in-memory
    log trimmed even when only incremental heads were written.
"""

import asyncio

from ceph_tpu.qa.cluster import Cluster


def _primary_pg(cl, pool_name="mp"):
    for osd in cl.osds.values():
        for pg in osd.pgs.values():
            if pg.is_primary() and pg.log.entries:
                return osd, pg
    raise AssertionError("no primary pg with log entries")


def test_write_path_leaves_incremental_keys_and_survives_restart():
    async def run():
        cl = Cluster()
        admin = await cl.start(2)
        await admin.pool_create("mp", pg_num=1, size=2)
        io = admin.open_ioctx("mp")
        blobs = {f"m{i:02d}": bytes([i]) * 512 for i in range(8)}
        for k, v in blobs.items():
            await io.write_full(k, v)
        osd, pg = _primary_pg(cl)
        _, omap = osd.store.omap_get(pg.cid, pg.meta_oid)
        incr = [k for k in omap if k.startswith(b"loge.")]
        # every client write appended ONE per-entry key; the blob
        # snapshot still reflects the pre-burst (activation) state
        assert len(incr) >= len(blobs), sorted(omap)
        assert b"loghead" in omap and b"info" in omap
        from ceph_tpu.osd.pglog import PGLog
        base = PGLog.from_bytes(omap[b"log"])
        assert base.head < pg.log.head
        head_before = pg.log.head
        n_entries = len(pg.log.entries)

        # restart on the surviving store: load_meta merges blob +
        # incremental keys and the data serves
        store = await cl.kill_osd(0)
        await cl.start_osd(0, store=store)
        await cl.osds[0].wait_for_boot()
        for k, v in blobs.items():
            assert await io.read(k) == v
        osd2, pg2 = _primary_pg(cl)
        assert pg2.log.head >= head_before
        assert len(pg2.log.entries) >= n_entries
        # reqid dup-detection index rebuilt over the merged log
        assert len(pg2.reqids) >= len(blobs)
        await cl.stop()

    asyncio.run(run())


def test_legacy_full_blob_layout_still_loads():
    """Upgrade path: a store written by the pre-incremental layout
    (full log/missing blobs, no loge./loghead keys) must load
    identically."""
    async def run():
        from ceph_tpu.common.encoding import Encoder
        from ceph_tpu.store.objectstore import Transaction
        cl = Cluster()
        admin = await cl.start(2)
        await admin.pool_create("lg", pg_num=1, size=2)
        io = admin.open_ioctx("lg")
        for i in range(6):
            await io.write_full(f"l{i}", bytes([i]) * 256)
        osd, pg = _primary_pg(cl, "lg")
        # rewrite the meta object exactly as the OLD code would have:
        # the four legacy keys, nothing else
        legacy = {
            b"info": pg.info.to_bytes(),
            b"log": pg.log.to_bytes(),
            b"past_intervals": Encoder().list_(
                pg.past_intervals, lambda e, v: e.struct(v)).getvalue(),
            b"missing": Encoder().map_(
                dict(pg.missing.items),
                lambda e, k: e.string(k),
                lambda e, v: e.struct(v)).getvalue(),
        }
        txn = Transaction()
        txn.omap_clear(pg.cid, pg.meta_oid)
        txn.omap_setkeys(pg.cid, pg.meta_oid, legacy)
        osd.store.apply_transaction(txn)
        head, n = pg.log.head, len(pg.log.entries)

        store = await cl.kill_osd(0)
        await cl.start_osd(0, store=store)
        await cl.osds[0].wait_for_boot()
        osd2, pg2 = _primary_pg(cl, "lg")
        assert pg2.log.head == head
        assert len(pg2.log.entries) == n
        # legacy layouts predate per-target backfill cursors: the
        # missing b"peer_cursors" key must load as "no records"
        assert pg2.peer_backfill_cursors == {}
        for i in range(6):
            assert await io.read(f"l{i}") == bytes([i]) * 256
        await cl.stop()

    asyncio.run(run())


def test_peer_backfill_cursors_roundtrip_across_restart():
    """ISSUE 17: the primary-side per-target backfill cursor record
    (b"peer_cursors" in PG meta) must survive a primary restart via
    the incremental layout's full-save path — a primary crash
    mid-backfill must not forget how far each target actually got."""
    async def run():
        from ceph_tpu.store.objectstore import Transaction
        cl = Cluster()
        admin = await cl.start(2)
        await admin.pool_create("pc", pg_num=1, size=2)
        io = admin.open_ioctx("pc")
        for i in range(4):
            await io.write_full(f"c{i}", bytes([i]) * 128)
        osd, pg = _primary_pg(cl, "pc")
        pg.peer_backfill_cursors = {1: "c0002", 3: "c0040"}
        txn = Transaction()
        pg.save_meta(txn)
        osd.store.apply_transaction(txn)
        _, omap = osd.store.omap_get(pg.cid, pg.meta_oid)
        assert b"peer_cursors" in omap

        store = await cl.kill_osd(0)
        await cl.start_osd(0, store=store)
        await cl.osds[0].wait_for_boot()
        osd2, pg2 = _primary_pg(cl, "pc")
        assert pg2.peer_backfill_cursors == {1: "c0002", 3: "c0040"}
        await cl.stop()

    asyncio.run(run())


def test_loghead_tail_bound_trims_on_load():
    """A log that trimmed in memory while only incremental heads were
    written: load_meta must honor loghead's tail and drop the
    superseded entries instead of resurrecting them."""
    from ceph_tpu.osd.pg import PG
    from ceph_tpu.osd.pglog import LogEntry, PGLog
    from ceph_tpu.osd.messages import EVersion

    class _FakePG:
        _loghead_bytes = PG._loghead_bytes

    fake = _FakePG()
    fake.log = PGLog()
    for v in range(1, 8):
        fake.log.append(LogEntry(oid=f"o{v}",
                                 version=EVersion(1, v)))
    # simulate MAX_ENTRIES trim: drop the first 3
    fake.log.tail = EVersion(1, 3)
    fake.log.entries = fake.log.entries[3:]
    head_blob = fake._loghead_bytes()

    # a loader that only has the pre-trim blob + the head record
    full = PGLog()
    for v in range(1, 8):
        full.append(LogEntry(oid=f"o{v}", version=EVersion(1, v)))
    from ceph_tpu.common.encoding import Decoder
    d = Decoder(head_blob)
    tail = d.struct(EVersion)
    assert tail == EVersion(1, 3)
    if full.tail < tail:
        full.entries = [e for e in full.entries if tail < e.version]
        full.tail = tail
    assert full.tail == EVersion(1, 3)
    assert [e.version.version for e in full.entries] == [4, 5, 6, 7]
