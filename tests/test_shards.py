"""Sharded OSD data plane (ISSUE 10): osd/shards.py.

Coverage map:
  * shard_index — stable pgid->shard hash (process-stable, shard-less
    identity, full coverage of the shard range);
  * Courier — FIFO order, batched wakeups (one drain per burst), and
    cross-thread posting;
  * e2e inline lanes — a 4-shard EC cluster serves writes+reads with
    zero local-path encodes, PG work pinned to home shards, handoff
    wakeups batched (wakeups < ops), and sub-op inline applies
    engaged;
  * e2e threaded — the same cluster with real per-shard event-loop
    threads (the msgr-worker split) stays correct through teardown;
  * objecter corked batching — N concurrent submits to one OSD ride
    one MOSDOpBatch (one frame / one local handoff), each earning its
    own reply; single submits stay unbatched on the wire;
  * backward compat — osd_op_num_shards=1 leaves the plane disabled:
    no shard router on the messenger, route() is an inline call
    (today's dispatch, bit-for-bit — the pin the rest of tier-1 runs
    under via FAST_CFG).
"""

import asyncio

import pytest
import threading

from ceph_tpu.osd.shards import Courier, shard_index
from ceph_tpu.osd.types import PGId
from ceph_tpu.qa.cluster import Cluster, make_ctx


# ------------------------------------------------------------- unit: hash

def test_shard_index_stable_and_covering():
    n = 4
    seen = set()
    for pool in range(4):
        for seed in range(64):
            pgid = PGId(pool, seed)
            i = shard_index(pgid, n)
            assert 0 <= i < n
            seen.add(i)
            # stable across calls and shard-qualified ids (EC shard
            # members of one PG share the home shard)
            assert shard_index(pgid, n) == i
            assert shard_index(pgid.with_shard(2), n) == i
    assert seen == set(range(n))        # every shard gets PGs
    assert shard_index(PGId(1, 2), 1) == 0


# ---------------------------------------------------------- unit: courier

def test_courier_fifo_and_batched_wakeups():
    async def run():
        loop = asyncio.get_running_loop()
        c = Courier(loop, "t")
        flushes = []
        c.on_flush = flushes.append
        got = []
        for i in range(10):
            c.post(got.append, i)
        assert got == []                # nothing ran synchronously
        await asyncio.sleep(0)
        assert got == list(range(10))   # FIFO
        assert flushes == [10]          # ONE drain for the burst
    asyncio.run(run())


def test_courier_cross_thread_post():
    async def run():
        loop = asyncio.get_running_loop()
        c = Courier(loop, "x")
        got = []
        done = threading.Event()

        def producer():
            for i in range(50):
                c.post(got.append, i)
            done.set()

        t = threading.Thread(target=producer)
        t.start()
        for _ in range(2000):
            await asyncio.sleep(0.001)
            if done.is_set() and len(got) == 50:
                break
        t.join()
        assert got == list(range(50))
    asyncio.run(run())


# ------------------------------------------------------------ e2e helpers

def _ctx_factory(shards, threads=False, tracing=False):
    def f(name):
        c = make_ctx(name)
        c.config.set("osd_op_num_shards", shards)
        c.config.set("osd_shard_threads", threads)
        c.config.set("ms_local_delivery", True)
        if tracing:
            c.config.set("op_tracing", True)
        return c
    return f


def _sum_shard_counters(cl):
    out = {}
    for osd in cl.osds.values():
        for k, v in osd.shards.counters().items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
    return out


async def _rw_burst(cl, admin, pool="shpool", n=24, ec=True):
    if ec:
        await admin.pool_create(pool, pg_num=4, pool_type="erasure",
                                k=2, m=2)
    else:
        await admin.pool_create(pool, pg_num=4)
    io = admin.open_ioctx(pool)
    blobs = {f"s{i:03d}": bytes([i]) * (4096 + i) for i in range(n)}
    await cl.write_burst(io, blobs, iodepth=12)
    for k, v in blobs.items():
        assert await io.read(k) == v
    return io


# ------------------------------------------------------- e2e inline lanes

def test_sharded_inline_cluster_rw_and_home_shard_pinning():
    from ceph_tpu.msg import payload as payload_mod
    from ceph_tpu.osd.shards import shard_index as sidx

    async def run():
        cl = Cluster(ctx_factory=_ctx_factory(4))
        admin = await cl.start(4)
        payload_mod.reset_counters()
        await _rw_burst(cl, admin)
        enc = payload_mod.counters()
        # zero-encode invariant holds through the classify seam
        assert enc["msg_encode_calls"] == 0, enc
        sc = _sum_shard_counters(cl)
        assert sc["handoff_ops"] > 0
        # batched wakeups: strictly fewer pump wakeups than items
        assert sc["handoff_wakeups"] < sc["handoff_ops"], sc
        # replica write sub-ops applied inline off the ring
        assert sc["subop_inline"] > 0, sc
        # home-shard pinning: every PG's worker task lives on the loop
        # of shard_index(pgid) — the SHARD11 property, checked live
        for osd in cl.osds.values():
            assert osd.shards.enabled and osd.messenger.shard_router
            for pgid, pg in osd.pgs.items():
                home = osd.shards.shards[sidx(pgid, 4)]
                if pg._worker_task is not None:
                    assert pg._worker_task.get_loop() is home.loop
        await cl.stop()

    asyncio.run(run())


# ---------------------------------------------------------- e2e threaded

def test_sharded_threaded_cluster_rw_and_teardown():
    """The msgr-worker split for real: per-shard event-loop THREADS.
    Writes+reads land correctly (cross-thread handoffs both ways:
    intake->shard ring, shard->messenger courier), PG workers run on
    their shard threads, and teardown joins every thread cleanly."""
    async def run():
        cl = Cluster(ctx_factory=_ctx_factory(2, threads=True))
        admin = await cl.start(3)
        await _rw_burst(cl, admin, n=16)
        threads = []
        for osd in cl.osds.values():
            assert osd.shards.threaded
            for s in osd.shards.shards:
                assert s._thread is not None and s._thread.is_alive()
                assert s.loop is not asyncio.get_running_loop()
                threads.append(s._thread)
            # shard->intake marshalling engaged (sends from shard
            # threads ride the batched courier)
            assert osd.messenger._xthread_msgs > 0
        await cl.stop()
        return threads

    threads = asyncio.run(run())
    for t in threads:
        assert not t.is_alive()         # joined at shutdown


# ------------------------------------------------- objecter corked batching

def test_objecter_corked_batching_one_handoff_many_replies():
    async def run():
        cl = Cluster(ctx_factory=_ctx_factory(4))
        admin = await cl.start(3)
        await admin.pool_create("bat", pg_num=1)   # one PG = one OSD
        io = admin.open_ioctx("bat")
        obj = admin.objecter
        base_b, base_o = obj.batches_sent, obj.ops_batched
        # same loop pass: all submits cork into one frame per target
        blobs = {f"b{i:02d}": bytes([i]) * 512 for i in range(8)}
        await asyncio.gather(*[io.write_full(k, v)
                               for k, v in blobs.items()])
        assert obj.batches_sent > base_b
        assert obj.ops_batched - base_o >= 4
        for k, v in blobs.items():
            assert await io.read(k) == v
        await cl.stop()

    asyncio.run(run())


def test_objecter_batching_off_is_unbatched():
    def ctx(name):
        c = _ctx_factory(1)(name)
        c.config.set("objecter_op_batching", False)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx)
        admin = await cl.start(3)
        await admin.pool_create("nb", pg_num=1)
        io = admin.open_ioctx("nb")
        blobs = {f"n{i:02d}": bytes([i]) * 512 for i in range(6)}
        await asyncio.gather(*[io.write_full(k, v)
                               for k, v in blobs.items()])
        assert admin.objecter.batches_sent == 0
        for k, v in blobs.items():
            assert await io.read(k) == v
        await cl.stop()

    asyncio.run(run())


# ------------------------------------------------------ process lanes

def _proc_ctx_factory(shards):
    def f(name):
        c = make_ctx(name)
        c.config.set("osd_op_num_shards", shards)
        c.config.set("osd_shard_lanes", "process")
        c.config.set("ms_local_delivery", True)
        return c
    return f


def test_process_lanes_forced_inline_under_sim_loop():
    """The schedule explorer still covers the plane: under a
    deterministic loop, osd_shard_lanes=process degrades to inline
    pumps the seeded scheduler permutes — a worker process would be
    the one wakeup source the explorer cannot replay."""
    from ceph_tpu.common.context import Context
    from ceph_tpu.osd.shards import ShardedDataPlane

    class _OSD:
        def __init__(self):
            self.ctx = Context("osd.9")
            self.cfg = self.ctx.config
            self.cfg.set("osd_op_num_shards", 2)
            self.cfg.set("osd_shard_lanes", "process")
            self.whoami = 9

    async def run():
        loop = asyncio.get_running_loop()
        loop.deterministic = True       # what DeterministicLoop sets
        try:
            plane = ShardedDataPlane(_OSD())
            assert plane.lane_backend == "process"
            plane.start()
            assert plane.active_backend == "inline"
            assert plane.process_lanes is None
            assert not plane.threaded
            await plane.stop()
        finally:
            del loop.deterministic

    asyncio.run(run())


def test_lane_backend_auto_resolves_from_thread_knob():
    from ceph_tpu.common.context import Context
    from ceph_tpu.osd.shards import ShardedDataPlane

    class _OSD:
        def __init__(self, threads):
            self.ctx = Context("osd.8")
            self.cfg = self.ctx.config
            self.cfg.set("osd_op_num_shards", 2)
            self.cfg.set("osd_shard_threads", threads)
            self.whoami = 8

    assert ShardedDataPlane(_OSD(True)).lane_backend == "thread"
    assert ShardedDataPlane(_OSD(False)).lane_backend == "inline"


@pytest.mark.slow
def test_process_lane_minicluster_replicated_rw():
    """Real parallelism: 2 worker processes per OSD, every PG hosted
    lane-side, all traffic crossing the shared-memory rings as wire
    frames.  Writes + reads land correctly; per-lane courier counters
    show the frames; teardown joins every worker."""
    async def run():
        cl = Cluster(ctx_factory=_proc_ctx_factory(2))
        admin = await cl.start(3)
        for osd in cl.osds.values():
            assert osd.shards.active_backend == "process"
            assert osd.shards.process_lanes is not None
            assert not osd.pgs       # the parent hosts NO PGs
        await _rw_burst(cl, admin, n=12, ec=False)
        procs = []
        for osd in cl.osds.values():
            lanes = osd.shards.counters()["lanes"]
            assert sum(c["to_lane_frames"]
                       for c in lanes.values()) > 0
            assert not any(c["dead"] for c in lanes.values())
            for lane in osd.shards.process_lanes:
                procs.append(lane.proc)
        await cl.stop()
        return procs

    procs = asyncio.run(run())
    for p in procs:
        assert not p.is_alive()       # workers joined at shutdown


@pytest.mark.slow
def test_process_lane_observability_attribution_and_cluster_scrape():
    """ISSUE 15 acceptance: a PROCESS-lane cluster run attributes
    >=90% of measured e2e wall time to named chain stages — including
    the new lane-hop cuts (ring_wait / lane_codec) and the cause-split
    queue-wait stages — because each lane worker's stage histograms
    ship to the parent over the metrics plane and merge bit-for-bit.
    The same run proves the cluster scrape: one merged perf snapshot
    covering parent + all lanes with devstats and device_byte_fraction
    included, lane-merged dump_op_stages, and a LOUD lane_dead marker
    once a worker is killed."""
    import time as _time

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("osd_op_num_shards", 2)
        c.config.set("osd_shard_lanes", "process")
        c.config.set("ms_local_delivery", True)
        c.config.set("op_tracing", True)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(3)
        await admin.pool_create("obspool", pg_num=4)
        io = admin.open_ioctx("obspool")
        lats = []
        sem = asyncio.Semaphore(8)

        async def one(name, data):
            async with sem:
                t0 = _time.perf_counter()
                await io.write_full(name, data)
                lats.append(_time.perf_counter() - t0)

        blobs = {f"ob{i:03d}": bytes([i]) * 8192 for i in range(24)}
        await asyncio.gather(*[one(n, d) for n, d in blobs.items()])
        # fresh lane scrape (FRAME_RPC), then the merged views
        dead = await cl.refresh_lane_metrics()
        assert dead == [], dead
        bd = cl.stage_breakdown(measured_e2e_s=sum(lats))
        merged = cl.stage_histograms()
        scrape = cl.cluster_perf_dump()
        # lane-merged admin dump straight off one OSD
        osd = next(iter(cl.osds.values()))
        table = await osd._dump_op_stages()
        slow = await osd._dump_historic_slow_ops()
        # kill one worker: the dump must MARK the lane dead, not
        # silently omit it
        victim = osd.shards.process_lanes[0]
        victim.proc.terminate()
        victim.proc.join(timeout=10.0)
        for _ in range(100):
            if victim.dead:
                break
            await asyncio.sleep(0.05)
        table_dead = await osd._dump_op_stages()
        scrape_dead = await osd._perf_dump_full()
        await cl.stop()
        return (bd, merged, scrape, table, slow, victim.idx,
                table_dead, scrape_dead)

    (bd, merged, scrape, table, slow, victim_idx, table_dead,
     scrape_dead) = asyncio.run(run())
    # (a) the acceptance bar: >=90% attribution WITH process lanes
    assert bd["measured_s"] > 0
    assert bd["attributed_s"] >= 0.9 * bd["measured_s"], bd
    assert bd["unattributed_frac"] < 0.10, bd
    # (b) the lane-hop chain stages recorded real samples
    for stage in ("ring_wait", "lane_codec", "queue_wait_pump",
                  "prepare", "store_apply", "replica_rtt",
                  "ack_delivery"):
        assert stage in merged and merged[stage].count > 0, stage
    # (c) lane-merged dump_op_stages saw the lane-side pipeline
    assert table["lanes_merged"] >= 1 and table["lane_dead"] == []
    assert "ring_wait" in table["stages"], table["stages"].keys()
    assert "prepare" in table["stages"]
    assert slow["lane_dead"] == []
    # (d) one merged cluster snapshot covers parent + lanes + devstats
    assert any("/lane" in s for s in scrape["sources"]), scrape["sources"]
    assert "devstats" in scrape and "device_byte_fraction" in scrape
    assert "op_stages" in scrape["groups"]
    # (e) a dead lane is LOUD, never silence
    assert victim_idx in table_dead["lane_dead"], table_dead
    assert any(str(victim_idx) in d for d in scrape_dead["lane_dead"])


@pytest.mark.slow
def test_process_lane_minicluster_ec_write_burst():
    """The tier-1 smoke the ISSUE names: a 2-lane process plane
    serving one EC (k=2,m=2) write burst end to end — sub-op fan-out,
    shard applies, acks and client replies all crossing process
    boundaries.  slow-marked: the seed tier-1 run already saturates
    the suite budget on this container."""
    async def run():
        cl = Cluster(ctx_factory=_proc_ctx_factory(2))
        admin = await cl.start(4)
        await _rw_burst(cl, admin, n=12, ec=True)
        await cl.stop()

    asyncio.run(run())


# ------------------------------------------------------- backward compat

def test_single_shard_plane_is_disabled_legacy_dispatch():
    async def run():
        cl = Cluster()          # FAST_CFG pins osd_op_num_shards=1
        admin = await cl.start(3)
        await _rw_burst(cl, admin, n=8, ec=False)
        for osd in cl.osds.values():
            assert not osd.shards.enabled
            assert osd.messenger.shard_router is None
            assert osd.shards.num_shards == 1
            # ack-on-apply is plane-gated: shards=1 keeps the commit
            # thread (today's behavior, bit-for-bit)
            assert not osd.store._committer._inline
        await cl.stop()

    asyncio.run(run())
