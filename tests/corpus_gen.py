"""Encoding-corpus sample builders (ceph-dencoder / ceph-object-corpus
role, reference src/test/encoding/readable.sh): one representative,
deterministic instance per Encodable type.

`samples()` returns {dotted_type_name: instance}.  tests/corpus/ holds
the committed encodings; test_encoding_corpus.py round-trips both ways
so a later round cannot silently break an on-disk or wire format —
changing a format requires BUMPING STRUCT_V (old bytes must still
decode) and regenerating the corpus with `python tests/corpus_gen.py`.
"""

from __future__ import annotations

import pathlib
import sys

CORPUS_DIR = pathlib.Path(__file__).parent / "corpus"

#: Encodable subclasses deliberately NOT in the corpus, with reasons
EXCLUDED = {
    "ceph_tpu.msg.message.Message": "abstract base",
    "ceph_tpu.common.encoding.Encodable": "abstract base",
}


def _crush_map():
    from ceph_tpu.crush.builder import (build_hierarchy,
                                        make_erasure_rule,
                                        make_replicated_rule)
    from ceph_tpu.crush.types import CrushMap
    m = CrushMap()
    m.max_devices = 12
    build_hierarchy(m, 12, 2, hosts_per_rack=3)
    make_replicated_rule(m, "rep")
    make_erasure_rule(m, "ec", size=4)
    return m


def _osdmap():
    from ceph_tpu.osd.osdmap import OSDMap
    from ceph_tpu.osd.types import PGPool
    from ceph_tpu.msg.types import EntityAddr
    m = OSDMap()
    m.epoch = 7
    m.crush = _crush_map()
    m.set_max_osd(12)
    pool = PGPool(pg_num=8, size=3)
    pool.snap_seq = 3
    pool.snaps = {2: "snapA"}
    pool.removed_snaps = [1]
    pool.tiers = [2]
    pool.read_tier = 2
    pool.write_tier = 2
    m.pools[1] = pool
    cache = PGPool(pg_num=8, size=2)
    cache.tier_of = 1
    cache.cache_mode = "writeback"
    cache.target_max_objects = 1000
    m.pools[2] = cache
    m.pool_names = {1: "data", 2: "hot"}
    m.osd_addrs[0] = EntityAddr("127.0.0.1", 6800, 1)
    return m


def _mperf():
    from ceph_tpu.tools.perf_msgr import MPerf
    return MPerf(7, b"perf-payload")


def samples():
    """Deterministic instances, keyed by dotted type name."""
    from ceph_tpu.crush.types import Bucket, Rule, RuleStep
    from ceph_tpu.crush.constants import (BUCKET_STRAW2, RULE_TAKE,
                                          RULE_CHOOSELEAF_FIRSTN,
                                          RULE_EMIT)
    from ceph_tpu.msg.types import EntityAddr, EntityName
    from ceph_tpu.msg.message import MPing
    from ceph_tpu.mon import messages as monm
    from ceph_tpu.mon.monmap import MonMap
    from ceph_tpu.osd import messages as osdm
    from ceph_tpu.osd.hitset import BloomHitSet
    from ceph_tpu.osd.messages import EVersion, OSDOp, ScrubEntry
    from ceph_tpu.osd.osdmap import Incremental
    from ceph_tpu.osd.pglog import (LogEntry, PGInfo, PGLog,
                                    PastInterval)
    from ceph_tpu.osd.snaps import SnapSet
    from ceph_tpu.osd.types import (ObjectLocator, OSDInfo, PGId,
                                    PGPool)
    from ceph_tpu.services.mds import MClientReply, MClientRequest
    from ceph_tpu.store.blockstore import Extent, Onode
    from ceph_tpu.store.objectstore import Transaction, TxOp
    from ceph_tpu.store.types import CollectionId, ObjectId

    pgid = PGId(1, 3, 2)
    ev = EVersion(5, 42)
    oloc = ObjectLocator(1, "lockey", "ns", -1)
    osd_op = OSDOp(1, offset=4096, length=512, name="xa",
                   data=b"payload", kv={b"k": b"v"}, keys=[b"k1"])
    oid = ObjectId("obj-α", pool=1, snap=4)
    cid = CollectionId("1.3s2")

    txn = Transaction()
    txn.create_collection(cid)
    txn.touch(cid, oid)
    txn.write(cid, oid, 0, b"bytes")
    txn.setattr(cid, oid, "name", b"val")
    txn.omap_setkeys(cid, oid, {b"ok": b"ov"})
    txn.clone(cid, oid, oid.with_snap(9))

    log_entry = LogEntry(1, "obj1", ev, EVersion(5, 41),
                         "client.4121:7")
    pginfo = PGInfo(pgid)
    pginfo.last_update = ev
    pginfo.last_epoch_started = 4
    pglog = PGLog()
    pglog.entries.append(log_entry)

    snapset = SnapSet()
    snapset.seq = 4
    snapset.clones = [2, 4]
    snapset.clone_snaps = {2: [1, 2], 4: [3, 4]}

    hs = BloomHitSet(target_size=64, fpp=0.05)
    hs.insert_many(["a", "b", "c"])

    bucket = Bucket(id=-2, alg=BUCKET_STRAW2, hash=0, type=1,
                    items=[0, 1], item_weights=[65536, 65536])

    inc = Incremental()
    inc.epoch = 8
    inc.new_up[3] = EntityAddr("127.0.0.1", 6801, 2)
    inc.new_weights = getattr(inc, "new_weights", {})

    mosdop = osdm.MOSDOp(pgid, "obj1", oloc, [osd_op], tid=9,
                         map_epoch=7, reqid="abc.9", snap_seq=4,
                         snaps=[4, 2], snapid=0)
    mosdop2 = osdm.MOSDOp(pgid, "obj2", oloc, [osd_op], tid=10,
                          map_epoch=7, reqid="abc.10")
    op_batch = osdm.MOSDOpBatch([mosdop, mosdop2])

    out = {
        "ceph_tpu.crush.types.Bucket": bucket,
        "ceph_tpu.crush.types.CrushMap": _crush_map(),
        "ceph_tpu.crush.types.Rule": Rule(0, 1, 1, 10, [
            RuleStep(RULE_TAKE, -1),
            RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, 1),
            RuleStep(RULE_EMIT)]),
        "ceph_tpu.crush.types.RuleStep": RuleStep(RULE_TAKE, -1),
        "ceph_tpu.mon.messages.MAuth": monm.MAuth(),
        "ceph_tpu.mon.messages.MAuthReply": monm.MAuthReply(),
        "ceph_tpu.mon.messages.MLog": monm.MLog(),
        "ceph_tpu.mon.messages.MMonCommand": monm.MMonCommand(
            {"prefix": "osd tree"}, 3),
        "ceph_tpu.mon.messages.MMonCommandAck": monm.MMonCommandAck(
            3, 0, "ok", b"blob"),
        "ceph_tpu.mon.messages.MMonElection": monm.MMonElection(),
        "ceph_tpu.mon.messages.MMonGetMap": monm.MMonGetMap(),
        "ceph_tpu.mon.messages.MMonMap": monm.MMonMap(),
        "ceph_tpu.mon.messages.MMonPaxos": monm.MMonPaxos(),
        "ceph_tpu.mon.messages.MMonSubscribe": monm.MMonSubscribe(
            {"osdmap": 3}),
        "ceph_tpu.mon.messages.MMonSubscribeAck": monm.MMonSubscribeAck(),
        "ceph_tpu.mon.messages.MOSDAlive": monm.MOSDAlive(),
        "ceph_tpu.mon.messages.MOSDBoot": monm.MOSDBoot(),
        "ceph_tpu.mon.messages.MOSDFailure": monm.MOSDFailure(),
        "ceph_tpu.mon.messages.MOSDMap": monm.MOSDMap(),
        "ceph_tpu.mon.messages.MPGStats": monm.MPGStats(),
        "ceph_tpu.mon.messages.MPGTemp": monm.MPGTemp(),
        "ceph_tpu.mon.monmap.MonMap": MonMap(),
        "ceph_tpu.msg.message.MPing": MPing(),
        "ceph_tpu.tools.perf_msgr.MPerf": _mperf(),
        "ceph_tpu.msg.types.EntityAddr": EntityAddr("10.0.0.1", 6789,
                                                    77),
        "ceph_tpu.msg.types.EntityName": EntityName("osd", "3"),
        "ceph_tpu.osd.hitset.BloomHitSet": hs,
        "ceph_tpu.osd.messages.EVersion": ev,
        "ceph_tpu.osd.messages.MOSDECSubOpRead":
            osdm.MOSDECSubOpRead(),
        "ceph_tpu.osd.messages.MOSDECSubOpReadReply":
            osdm.MOSDECSubOpReadReply(),
        "ceph_tpu.osd.messages.MOSDECSubOpWrite":
            osdm.MOSDECSubOpWrite(),
        "ceph_tpu.osd.messages.MOSDECSubOpWriteReply":
            osdm.MOSDECSubOpWriteReply(),
        "ceph_tpu.osd.messages.MOSDOp": mosdop,
        "ceph_tpu.osd.messages.MOSDOpBatch": op_batch,
        "ceph_tpu.osd.messages.MOSDOpReply": osdm.MOSDOpReply(
            9, 0, [osd_op], 7),
        "ceph_tpu.osd.messages.MOSDPing": osdm.MOSDPing(),
        "ceph_tpu.osd.messages.MOSDRepOp": osdm.MOSDRepOp(),
        "ceph_tpu.osd.messages.MOSDRepOpReply": osdm.MOSDRepOpReply(),
        "ceph_tpu.osd.messages.MPGLog": osdm.MPGLog(),
        "ceph_tpu.osd.messages.MPGLogRequest": osdm.MPGLogRequest(),
        "ceph_tpu.osd.messages.MPGNotify": osdm.MPGNotify(),
        "ceph_tpu.osd.messages.MPGObjectList": osdm.MPGObjectList(),
        "ceph_tpu.osd.messages.MPGPush": osdm.MPGPush(),
        "ceph_tpu.osd.messages.MPGPushReply": osdm.MPGPushReply(),
        "ceph_tpu.osd.messages.MPGQuery": osdm.MPGQuery(),
        "ceph_tpu.osd.messages.MPGRemove": osdm.MPGRemove(),
        "ceph_tpu.osd.messages.MPGScrub": osdm.MPGScrub(),
        "ceph_tpu.osd.messages.MPGScrubMap": osdm.MPGScrubMap(),
        "ceph_tpu.osd.messages.MPGScrubScan": osdm.MPGScrubScan(),
        "ceph_tpu.osd.messages.MWatchNotify": osdm.MWatchNotify(),
        "ceph_tpu.osd.messages.MWatchNotifyAck":
            osdm.MWatchNotifyAck(),
        "ceph_tpu.osd.messages.OSDOp": osd_op,
        "ceph_tpu.osd.messages.ScrubEntry": ScrubEntry(),
        "ceph_tpu.osd.osdmap.Incremental": inc,
        "ceph_tpu.osd.osdmap.OSDMap": _osdmap(),
        "ceph_tpu.osd.pglog.LogEntry": log_entry,
        "ceph_tpu.osd.pglog.PGInfo": pginfo,
        "ceph_tpu.osd.pglog.PGLog": pglog,
        "ceph_tpu.osd.pglog.PastInterval": PastInterval(
            3, 6, [0, 1], [1, 0], 1, True),
        "ceph_tpu.osd.snaps.SnapSet": snapset,
        "ceph_tpu.osd.types.OSDInfo": OSDInfo(1, 2, 3, 4, 5, 6),
        "ceph_tpu.osd.types.ObjectLocator": oloc,
        "ceph_tpu.osd.types.PGId": pgid,
        "ceph_tpu.osd.types.PGPool": _osdmap().pools[1],
        "ceph_tpu.services.mds.MClientLease": __import__(
            "ceph_tpu.services.mds", fromlist=["MClientLease"]
        ).MClientLease(["/a/b", "/c"]),
        "ceph_tpu.services.mds.MClientReply": MClientReply(),
        "ceph_tpu.services.mds.MClientRequest": MClientRequest(),
        "ceph_tpu.store.blockstore.Extent": Extent(0, 4096),
        "ceph_tpu.store.blockstore.Onode": Onode(),
        "ceph_tpu.store.objectstore.Transaction": txn,
        "ceph_tpu.store.objectstore.TxOp": txn.ops[0],
        "ceph_tpu.store.types.CollectionId": cid,
        "ceph_tpu.store.types.ObjectId": oid,
    }
    return out


def _import_package():
    """Import every ceph_tpu module so @register_message side effects
    populate the codec registry."""
    import importlib
    import pkgutil
    import ceph_tpu
    for m in pkgutil.walk_packages(ceph_tpu.__path__, "ceph_tpu."):
        try:
            importlib.import_module(m.name)
        except Exception:
            pass


def registry_samples():
    """samples() plus a default-constructed instance for every wire
    type registered with the message codec that samples() forgot —
    MOSDOpBatch needed a hand-written sample in PR 10; this makes
    forgetting impossible: a new @register_message type either
    default-constructs into the corpus here or regenerate() fails
    loudly asking for a hand sample."""
    _import_package()
    from ceph_tpu.msg.message import _REGISTRY
    out = samples()
    for code in sorted(_REGISTRY):
        cls = _REGISTRY[code]
        name = f"{cls.__module__}.{cls.__name__}"
        if name in out or name in EXCLUDED \
                or cls.__module__.split(".")[-1].startswith(("test", "conftest")):
            continue
        try:
            out[name] = cls()
        except Exception as e:
            raise RuntimeError(
                f"registered wire type {name} (code {code}) has no "
                f"corpus sample and is not default-constructible "
                f"({e!r}): add a hand-written sample to "
                f"tests/corpus_gen.py samples()") from None
    return out


def regenerate():
    CORPUS_DIR.mkdir(exist_ok=True)
    for name, obj in sorted(registry_samples().items()):
        blob = obj.to_bytes()
        (CORPUS_DIR / f"{name}.bin").write_bytes(blob)
        print(f"{name}: {len(blob)} bytes (v{obj.STRUCT_V})")


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
    regenerate()
