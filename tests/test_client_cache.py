"""ObjectCacher + RadosStriper client layers.

Mirrors the reference test strategy: ObjectCacher unit behavior
(test/osdc/object_cacher-stress role — hit/miss/flush/trim invariants)
and libradosstriper integration against a live cluster
(test/libradosstriper/*.cc), plus cached-RBD write-back semantics.
"""

import asyncio
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.client.object_cacher import ObjectCacher  # noqa: E402
from ceph_tpu.client.rados_striper import (RadosStriper,  # noqa: E402
                                           StripedObjectNotFound)
from ceph_tpu.services.striper import Layout  # noqa: E402


# ------------------------------------------------------------ object cacher

class FakeBackend:
    def __init__(self):
        self.objects = {}
        self.reads = 0
        self.writes = 0

    async def read(self, oid, off, length):
        self.reads += 1
        data = self.objects.get(oid, b"")
        return data[off:off + length]

    async def write(self, oid, off, data):
        self.writes += 1
        cur = bytearray(self.objects.get(oid, b""))
        if len(cur) < off + len(data):
            cur.extend(b"\x00" * (off + len(data) - len(cur)))
        cur[off:off + len(data)] = data
        self.objects[oid] = bytes(cur)


def test_cacher_writeback_and_hits():
    async def run():
        be = FakeBackend()
        c = ObjectCacher(be.read, be.write, max_dirty_age=30.0)
        c.start()
        await c.write("o", 0, b"hello world")
        assert be.writes == 0                 # write-back: not yet flushed
        assert await c.read("o", 0, 11) == b"hello world"
        assert be.reads == 0                  # served from dirty buffer
        await c.flush("o")
        assert be.objects["o"] == b"hello world"
        # read-through caches clean data
        be.objects["x"] = b"0123456789"
        assert await c.read("x", 2, 4) == b"2345"
        r = be.reads
        assert await c.read("x", 2, 4) == b"2345"
        assert be.reads == r                  # hit
        assert c.stats["hit_bytes"] > 0
        await c.stop()
    asyncio.run(run())


def test_cacher_flusher_ages_out_dirty():
    async def run():
        be = FakeBackend()
        c = ObjectCacher(be.read, be.write, max_dirty_age=0.05)
        c.start()
        await c.write("o", 0, b"aged")
        for _ in range(80):
            if be.objects.get("o") == b"aged":
                break
            await asyncio.sleep(0.05)
        assert be.objects.get("o") == b"aged"
        await c.stop()
    asyncio.run(run())


def test_cacher_dirty_limit_throttles_and_overwrite_composes():
    async def run():
        be = FakeBackend()
        c = ObjectCacher(be.read, be.write, max_dirty=4096,
                         max_dirty_age=30.0)
        c.start()
        for i in range(8):
            await c.write("o", i * 1024, bytes([i]) * 1024)
        # dirty limit forced flushes along the way
        assert be.writes > 0
        await c.write("o", 512, b"Z" * 1024)  # overlap across buffers
        await c.flush_all()
        want = bytearray()
        for i in range(8):
            want += bytes([i]) * 1024
        want[512:1536] = b"Z" * 1024
        assert be.objects["o"] == bytes(want)
        await c.stop()
    asyncio.run(run())


def test_cacher_trims_clean_lru():
    async def run():
        be = FakeBackend()
        for i in range(8):
            be.objects[f"o{i}"] = bytes([i]) * 4096
        c = ObjectCacher(be.read, be.write, max_bytes=8192,
                         max_dirty_age=30.0)
        c.start()
        for i in range(8):
            await c.read(f"o{i}", 0, 4096)
        assert c._total_bytes <= 8192
        assert c.stats["evictions"] >= 6
        await c.stop()
    asyncio.run(run())


# ------------------------------------------------------------ radosstriper

def test_striper_over_cluster():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("sp", pg_num=8)
        io = admin.open_ioctx("sp")
        st = RadosStriper(io, Layout(4096, 2, 16384))
        payload = bytes(range(256)) * 300          # 75 KiB over objects
        await st.write("bigfile", payload)
        assert (await st.stat("bigfile"))["size"] == len(payload)
        assert await st.read("bigfile") == payload
        assert await st.read("bigfile", length=1000,
                             offset=30000) == payload[30000:31000]
        # sub-objects really exist (striped, not one blob)
        names = await io.list_objects()
        subs = [n for n in names if n.startswith("bigfile.")]
        assert len(subs) > 1
        # overwrite window + extend
        await st.write("bigfile", b"X" * 5000, offset=70000)
        want = bytearray(payload)
        if len(want) < 75000:
            want.extend(b"\x00" * (75000 - len(want)))
        want[70000:75000] = b"X" * 5000
        assert await st.read("bigfile") == bytes(want)

        # xattrs ride the head object
        await st.setxattr("bigfile", "owner", b"me")
        assert await st.getxattr("bigfile", "owner") == b"me"

        # truncate drops tail sub-objects
        await st.truncate("bigfile", 10000)
        assert (await st.stat("bigfile"))["size"] == 10000
        assert await st.read("bigfile") == bytes(want[:10000])
        # remove cleans every sub-object
        await st.remove("bigfile")
        with pytest.raises(StripedObjectNotFound):
            await st.stat("bigfile")
        names = await io.list_objects()
        assert not [n for n in names if n.startswith("bigfile.")]
        await cl.stop()
    asyncio.run(run())


# ------------------------------------------------------------- cached rbd

def test_rbd_cached_image_writeback():
    from ceph_tpu.services.rbd import RBD, Image

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("disk", 4 << 20, order=16)
        img = await Image.open(io, "disk", cached=True)
        data = bytes(range(256)) * 1024            # 256 KiB
        await img.write(8192, data)
        # cache serves the read even before flush
        assert await img.read(8192, len(data)) == data
        await img.flush()
        # a second, uncached handle sees the flushed bytes
        img2 = await Image.open(io, "disk")
        assert await img2.read(8192, len(data)) == data
        # overwrite through cache composes with flushed state
        await img.write(10000, b"Y" * 40000)
        await img.close()                          # flushes
        want = bytearray(data)
        want[10000 - 8192:10000 - 8192 + 40000] = b"Y" * 40000
        assert await img2.read(8192, len(data)) == bytes(want)
        await cl.stop()
    asyncio.run(run())
