"""Store layer tests: KeyValueDB, Transaction codec, MemStore, FileStore.

Models the reference's store test strategy (test/objectstore/store_test.cc —
value-parameterized over backends) plus journal replay/crash tests
(DeterministicOpSequence / run_seed_to.sh analog).
"""

import os

import numpy as np

import pytest

from ceph_tpu.store import (
    CollectionId, FileDB, FileStore, MemDB, MemStore, NoSuchCollection,
    NoSuchObject, ObjectId, ObjectStore, Transaction,
)
from ceph_tpu.crush.hashfn import ceph_str_hash_rjenkins


# ---------------------------------------------------------------- kv layer

@pytest.fixture(params=["mem", "file"])
def kvdb(request, tmp_path):
    if request.param == "mem":
        db = MemDB()
    else:
        db = FileDB(str(tmp_path / "kv"))
    yield db
    db.close()


def test_kv_basic(kvdb):
    t = kvdb.create_transaction()
    t.set("p", "a", b"1").set("p", "b", b"2").set("q", "a", b"3")
    kvdb.submit(t)
    assert kvdb.get("p", "a") == b"1"
    assert kvdb.get("q", "a") == b"3"
    assert kvdb.get("p", "zzz") is None
    assert [k for k, _ in kvdb.iterate("p")] == [b"a", b"b"]

    t2 = kvdb.create_transaction().rmkey("p", "a")
    kvdb.submit(t2)
    assert kvdb.get("p", "a") is None

    kvdb.submit(kvdb.create_transaction().rmkeys_by_prefix("p"))
    assert kvdb.keys("p") == []
    assert kvdb.get("q", "a") == b"3"


def test_kv_iterate_range(kvdb):
    t = kvdb.create_transaction()
    for i in range(10):
        t.set("x", f"k{i}", str(i).encode())
    kvdb.submit(t)
    got = [k for k, _ in kvdb.iterate("x", start=b"k3", end=b"k7")]
    assert got == [b"k3", b"k4", b"k5", b"k6"]


def test_filedb_replay(tmp_path):
    path = str(tmp_path / "kv")
    db = FileDB(path)
    db.submit(db.create_transaction().set("p", "a", b"1"))
    db.submit(db.create_transaction().set("p", "b", b"2"))
    # simulate crash: do NOT close/compact
    db._wal.close()
    db2 = FileDB(path)
    assert db2.get("p", "a") == b"1"
    assert db2.get("p", "b") == b"2"
    db2.close()
    # clean reopen after compact
    db3 = FileDB(path)
    assert db3.get("p", "b") == b"2"
    db3.close()


def test_filedb_torn_tail(tmp_path):
    path = str(tmp_path / "kv")
    db = FileDB(path)
    db.submit(db.create_transaction().set("p", "a", b"1"))
    db._wal.close()
    with open(os.path.join(path, "wal"), "ab") as f:
        f.write(b"\x01\x02garbage-torn-record")
    db2 = FileDB(path)
    assert db2.get("p", "a") == b"1"
    # regression: commits made AFTER torn-tail recovery must survive the
    # next replay (the tail must be truncated, not appended past)
    db2.submit(db2.create_transaction().set("p", "b", b"2"))
    db2._wal.close()
    db3 = FileDB(path)
    assert db3.get("p", "a") == b"1"
    assert db3.get("p", "b") == b"2"
    db3.close()


def test_filedb_reads_dont_block_on_group_fsync(tmp_path):
    """ISSUE 4 satellite (ROADMAP known hazard): the WAL group fsync on
    the commit thread must NOT hold the memory lock — event-loop reads
    (get/iterate) proceed for the whole barrier duration."""
    import threading
    import time as _time

    db = FileDB(str(tmp_path / "kv"))
    db.submit(db.create_transaction().set("p", "seed", b"v"))
    for i in range(8):
        db.submit_deferred(
            db.create_transaction().set("p", f"d{i}", str(i).encode()))

    entered, release = threading.Event(), threading.Event()
    orig = db._wal.append_many

    def slow_append(recs, sync=True):
        entered.set()
        assert release.wait(10), "test wedged: releaser never ran"
        orig(recs, sync=sync)

    db._wal.append_many = slow_append
    flusher = threading.Thread(target=db.log_deferred, args=(db.seq,))
    flusher.start()
    assert entered.wait(10)

    # the "fsync" is in flight and will stay stuck until `release`:
    # reads must complete NOW (they only need the memory lock)
    done = threading.Event()

    def reader():
        for _ in range(50):
            assert db.get("p", "seed") == b"v"
            assert db.get("p", "d0") == b"0"       # deferred: visible
            assert [k for k, _ in db.iterate("p", start=b"d")][0] == b"d0"
        done.set()

    r = threading.Thread(target=reader)
    r.start()
    assert done.wait(5.0), \
        "db.get/iterate stalled behind the WAL group fsync"
    release.set()
    flusher.join(10)
    r.join(5)
    db._wal.append_many = orig
    # durability unaffected: reopen sees every record
    db.close()
    db2 = FileDB(str(tmp_path / "kv"))
    assert db2.get("p", "d7") == b"7"
    db2.close()


def test_filedb_concurrent_submit_and_log_deferred(tmp_path):
    """Seq order on the WAL survives submit() racing log_deferred()
    across threads (the _io lock serializes appenders; _mu only guards
    memory)."""
    import threading

    db = FileDB(str(tmp_path / "kv"))
    stop = threading.Event()

    def committer():
        while not stop.is_set():
            db.log_deferred(db.seq)

    t = threading.Thread(target=committer)
    t.start()
    try:
        for i in range(200):
            if i % 3 == 0:
                db.submit(db.create_transaction()
                          .set("s", f"k{i:03d}", b"sync"))
            else:
                db.submit_deferred(db.create_transaction()
                                   .set("s", f"k{i:03d}", b"def"))
    finally:
        stop.set()
        t.join(10)
    db.close()
    db2 = FileDB(str(tmp_path / "kv"))
    assert len(db2.keys("s")) == 200
    db2.close()


def test_memdb_remove_prefix_high_bytes():
    # regression: keys whose suffix starts with many 0xff bytes must be
    # removed by rmkeys_by_prefix and must not desync the sorted index
    db = MemDB()
    hot = b"\xff" * 12
    db.submit(db.create_transaction().set("p", hot, b"v")
              .set("p", b"normal", b"n").set("q", b"other", b"o"))
    db.submit(db.create_transaction().rmkeys_by_prefix("p"))
    assert db.get("p", hot) is None
    assert db.keys("p") == []
    assert db.get("q", b"other") == b"o"
    assert [k for k, _ in db.iterate("q")] == [b"other"]


# ------------------------------------------------------------- object ids

def test_object_id_hash_matches_reference_rjenkins():
    # golden values from compiling /root/reference/src/common/ceph_hash.cc
    golden = {
        b"": 0xBD49D10D, b"foo": 0x7FC1F406, b"object_12345": 0x1632FBC1,
        b"aaaaaaaaaaa": 0x17A6E6E2, b"bbbbbbbbbbbb": 0xB15A9932,
        b"ccccccccccccccccccccccc": 0x39658A70,
        b"dddddddddddddddddddddddd": 0x11360A09,
        b"hello world this is long": 0xA83AA0EE,
    }
    for s, want in golden.items():
        assert ceph_str_hash_rjenkins(s) == want


def test_object_id_roundtrip_and_order():
    a = ObjectId("obj1", pool=3)
    b = ObjectId.from_bytes(a.to_bytes())
    assert a == b and hash(a) == hash(b)
    # locator key overrides name for placement
    c = ObjectId("other", key="obj1")
    assert c.hash32 == a.hash32
    ids = sorted([ObjectId(f"o{i}") for i in range(20)])
    assert ids == sorted(ids, key=lambda o: o.sort_key())


def test_collection_id():
    c = CollectionId.pg(3, 0x1A, shard=2)
    assert c.is_pg()
    assert CollectionId.from_bytes(c.to_bytes()) == c
    assert not CollectionId.meta().is_pg()


# ------------------------------------------------------------ transaction

def test_transaction_roundtrip():
    cid = CollectionId.pg(1, 0)
    oid = ObjectId("a", pool=1)
    t = Transaction()
    t.create_collection(cid)
    t.write(cid, oid, 0, b"hello")
    t.setattr(cid, oid, "_", b"oi")
    t.omap_setkeys(cid, oid, {b"k": b"v"})
    t.clone(cid, oid, oid.with_snap(4))
    t2 = Transaction.from_bytes(t.to_bytes())
    assert len(t2.ops) == 5
    assert [o.op for o in t2.ops] == [o.op for o in t.ops]
    assert t2.ops[1].data == b"hello"
    assert t2.ops[3].kv == {b"k": b"v"}
    assert t2.ops[4].oid2.snap == 4


# ------------------------------------------------------------- stores

@pytest.fixture(params=["memstore", "filestore", "blockstore",
                        "kstore"])
def store(request, tmp_path):
    s = ObjectStore.create(request.param, str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    yield s
    s.umount()


CID = CollectionId.pg(1, 0)
OID = ObjectId("obj", pool=1)


def _mkcoll(s):
    t = Transaction().create_collection(CID)
    s.apply_transaction(t)


def test_store_write_read(store):
    _mkcoll(store)
    store.apply_transaction(Transaction().write(CID, OID, 0, b"hello world"))
    assert store.read(CID, OID) == b"hello world"
    assert store.read(CID, OID, 6, 5) == b"world"
    store.apply_transaction(Transaction().write(CID, OID, 6, b"there"))
    assert store.read(CID, OID) == b"hello there"
    # sparse write past EOF zero-fills
    store.apply_transaction(Transaction().write(CID, OID, 20, b"x"))
    assert store.read(CID, OID, 11, 9) == b"\x00" * 9
    assert store.stat(CID, OID)["size"] == 21


def test_store_zero_truncate_remove(store):
    _mkcoll(store)
    store.apply_transaction(Transaction().write(CID, OID, 0, b"abcdef"))
    store.apply_transaction(Transaction().zero(CID, OID, 1, 3))
    assert store.read(CID, OID) == b"a\x00\x00\x00ef"
    store.apply_transaction(Transaction().truncate(CID, OID, 2))
    assert store.read(CID, OID) == b"a\x00"
    store.apply_transaction(Transaction().remove(CID, OID))
    assert not store.exists(CID, OID)
    with pytest.raises(NoSuchObject):
        store.read(CID, OID)


def test_store_xattr_omap(store):
    _mkcoll(store)
    store.apply_transaction(
        Transaction().touch(CID, OID)
        .setattrs(CID, OID, {"_": b"meta", "snapset": b"ss"})
        .omap_setheader(CID, OID, b"hdr")
        .omap_setkeys(CID, OID, {b"a": b"1", b"b": b"2"}))
    assert store.getattr(CID, OID, "_") == b"meta"
    assert store.getattrs(CID, OID) == {"_": b"meta", "snapset": b"ss"}
    hdr, omap = store.omap_get(CID, OID)
    assert hdr == b"hdr" and omap == {b"a": b"1", b"b": b"2"}
    store.apply_transaction(Transaction().rmattr(CID, OID, "snapset")
                            .omap_rmkeys(CID, OID, [b"a"]))
    assert store.getattrs(CID, OID) == {"_": b"meta"}
    assert store.omap_get(CID, OID)[1] == {b"b": b"2"}
    assert store.omap_get_values(CID, OID, [b"b", b"zz"]) == {b"b": b"2"}


def test_store_clone_and_rename(store):
    _mkcoll(store)
    snap = OID.with_snap(5)
    store.apply_transaction(Transaction().write(CID, OID, 0, b"v1")
                            .clone(CID, OID, snap))
    store.apply_transaction(Transaction().write(CID, OID, 0, b"v2"))
    assert store.read(CID, snap) == b"v1"
    assert store.read(CID, OID) == b"v2"
    cid2 = CollectionId.pg(1, 1)
    store.apply_transaction(Transaction().create_collection(cid2)
                            .collection_move_rename(CID, OID, cid2, OID))
    assert store.read(cid2, OID) == b"v2"
    assert not store.exists(CID, OID)


def test_store_collections_and_listing(store):
    _mkcoll(store)
    oids = [ObjectId(f"o{i}", pool=1) for i in range(10)]
    t = Transaction()
    for o in oids:
        t.touch(CID, o)
    store.apply_transaction(t)
    listed = store.collection_list(CID)
    assert set(listed) == set(oids)
    assert listed == sorted(listed, key=lambda o: o.sort_key())
    # pagination resumes after cursor
    first = store.collection_list(CID, max_count=4)
    rest = store.collection_list(CID, start=first[-1])
    assert first + rest == listed
    with pytest.raises(NoSuchCollection):
        store.collection_list(CollectionId.pg(9, 9))


def test_store_callbacks_order(store):
    _mkcoll(store)
    events = []
    store.queue_transactions(
        [Transaction().write(CID, OID, 0, b"x")],
        on_applied=lambda: events.append("applied"),
        on_commit=lambda: events.append("commit"))
    # applied fires inline (state readable immediately); commit may ride
    # the group-commit thread — sync() drains it (and with no event loop
    # captured the callback runs on the commit thread before sync returns)
    assert events[0] == "applied"
    store.sync()
    assert events == ["applied", "commit"]


# ------------------------------------------------------- filestore replay

def test_filestore_crash_replay(tmp_path):
    path = str(tmp_path / "fs")
    s = FileStore(path)
    s.mkfs()
    s.mount()
    _mkcoll(s)
    s.apply_transaction(Transaction().write(CID, OID, 0, b"durable")
                        .omap_setkeys(CID, OID, {b"k": b"v"}))
    # crash: no umount/checkpoint
    s._wal.close()

    s2 = FileStore(path)
    s2.mount()
    assert s2.read(CID, OID) == b"durable"
    assert s2.omap_get(CID, OID)[1] == {b"k": b"v"}
    s2.apply_transaction(Transaction().write(CID, OID, 0, b"DURABLE"))
    s2.umount()  # clean: checkpoint + truncate wal

    s3 = FileStore(path)
    s3.mount()
    assert s3.read(CID, OID) == b"DURABLE"
    assert os.path.getsize(os.path.join(path, "wal")) == 0
    s3.umount()


def test_filestore_checkpoint_midstream(tmp_path):
    path = str(tmp_path / "fs")
    s = FileStore(path)
    s.mkfs()
    s.mount()
    _mkcoll(s)
    for i in range(5):
        s.apply_transaction(
            Transaction().write(CID, ObjectId(f"o{i}", pool=1), 0,
                                bytes([i]) * 100))
    s.checkpoint()
    s.apply_transaction(Transaction().write(CID, ObjectId("after", pool=1),
                                            0, b"post-ckpt"))
    s._wal.close()  # crash after checkpoint + one more txn
    s2 = FileStore(path)
    s2.mount()
    assert s2.read(CID, ObjectId("o3", pool=1)) == b"\x03" * 100
    assert s2.read(CID, ObjectId("after", pool=1)) == b"post-ckpt"
    s2.umount()


def test_store_apply_is_total(store):
    # regression: destructive ops on missing targets are no-ops; a journaled
    # transaction can never fail halfway through apply (poison WAL record)
    _mkcoll(store)
    missing = ObjectId("missing", pool=1)
    t = (Transaction().write(CID, OID, 0, b"x")
         .rmattr(CID, missing, "a").omap_rmkeys(CID, missing, [b"k"])
         .omap_clear(CID, missing).remove(CID, missing)
         .clone(CID, missing, ObjectId("c", pool=1))
         .remove(CollectionId.pg(9, 9), missing))
    store.apply_transaction(t)      # must not raise
    assert store.read(CID, OID) == b"x"
    assert not store.exists(CID, missing)


def test_filestore_no_poison_wal(tmp_path):
    # a txn containing destructive ops on missing targets must not prevent
    # future mounts (it is replayed from the WAL on mount)
    path = str(tmp_path / "fs")
    s = FileStore(path)
    s.mkfs()
    s.mount()
    _mkcoll(s)
    s.apply_transaction(Transaction().write(CID, OID, 0, b"ok")
                        .rmattr(CID, ObjectId("ghost", pool=1), "x"))
    s._wal.close()  # crash before checkpoint: WAL replays on mount
    s2 = FileStore(path)
    s2.mount()
    assert s2.read(CID, OID) == b"ok"
    s2.umount()


def test_filestore_commits_after_torn_tail_survive(tmp_path):
    path = str(tmp_path / "fs")
    s = FileStore(path)
    s.mkfs()
    s.mount()
    _mkcoll(s)
    s.apply_transaction(Transaction().write(CID, OID, 0, b"one"))
    s._wal.close()
    with open(os.path.join(path, "wal"), "ab") as f:
        f.write(b"torn-half-record\x00\x01")
    s2 = FileStore(path)
    s2.mount()
    assert s2.read(CID, OID) == b"one"
    s2.apply_transaction(Transaction().write(CID, OID, 0, b"two"))
    s2._wal.close()  # crash again
    s3 = FileStore(path)
    s3.mount()
    assert s3.read(CID, OID) == b"two"
    s3.umount()


def test_mkfs_required(tmp_path):
    s = FileStore(str(tmp_path / "nofs"))
    with pytest.raises(Exception):
        s.mount()


# ----------------------------------------------------------- blockstore

def test_blockstore_remount_preserves_data(tmp_path):
    from ceph_tpu.store.blockstore import BlockStore
    path = str(tmp_path / "bs")
    s = BlockStore(path)
    s.mkfs()
    s.mount()
    s.apply_transaction(Transaction().create_collection(CID)
                        .write(CID, OID, 0, b"A" * 10000)
                        .setattr(CID, OID, "x", b"v")
                        .omap_setkeys(CID, OID, {b"k": b"v"}))
    s.umount()
    s2 = BlockStore(path)
    s2.mount()
    assert s2.read(CID, OID) == b"A" * 10000
    assert s2.getattr(CID, OID, "x") == b"v"
    assert s2.omap_get(CID, OID)[1] == {b"k": b"v"}
    s2.umount()


def test_blockstore_crash_no_umount_recovers(tmp_path):
    """Abandon the store without umount (crash): the kv WAL replays and
    the allocator rebuild must reclaim any leaked COW blocks."""
    from ceph_tpu.store.blockstore import BlockStore
    path = str(tmp_path / "bs")
    s = BlockStore(path)
    s.mkfs()
    s.mount()
    s.apply_transaction(Transaction().create_collection(CID))
    for i in range(10):
        s.apply_transaction(
            Transaction().write(CID, ObjectId(f"o{i}", pool=1), 0,
                                bytes([i]) * 5000))
    # overwrite churn creates freed+reallocated extents
    for i in range(10):
        s.apply_transaction(
            Transaction().write(CID, ObjectId(f"o{i}", pool=1), 100,
                                bytes([0xF0 | (i & 0xF)]) * 1000))
    # NO umount — reopen like after a crash
    s2 = BlockStore(path)
    s2.mount()
    for i in range(10):
        got = s2.read(CID, ObjectId(f"o{i}", pool=1))
        want = bytearray(bytes([i]) * 5000)
        want[100:1100] = bytes([0xF0 | (i & 0xF)]) * 1000
        assert got == bytes(want), i
    # allocator accounting is consistent: used <= device, free+used=total
    fs = s2.statfs()
    assert fs["used"] + fs["free"] == fs["total"]
    s2.umount()


def test_blockstore_detects_bit_rot(tmp_path):
    """Flip one bit in the raw block file: the per-extent crc must turn
    the read into an error instead of returning rot (bluestore csum)."""
    import os as _os
    from ceph_tpu.store.blockstore import BlockStore, StoreError
    path = str(tmp_path / "bs")
    s = BlockStore(path)
    s.mkfs()
    s.mount()
    s.apply_transaction(Transaction().create_collection(CID)
                        .write(CID, OID, 0, b"precious-bytes" * 100))
    ext = s._get_onode(CID, OID).extents[0]
    s.umount()
    with open(_os.path.join(path, "block"), "r+b") as f:
        f.seek(ext.disk + 7)
        b = f.read(1)
        f.seek(ext.disk + 7)
        f.write(bytes([b[0] ^ 0x40]))
    s2 = BlockStore(path)
    s2.mount()
    with pytest.raises(StoreError, match="csum"):
        s2.read(CID, OID)
    s2.umount()


def test_blockstore_cow_overwrite_moves_blocks(tmp_path):
    """Overwrites land in fresh blocks (COW) and the old ones return to
    the allocator after commit."""
    from ceph_tpu.store.blockstore import BlockStore
    path = str(tmp_path / "bs")
    s = BlockStore(path)
    s.mkfs()
    s.mount()
    s.apply_transaction(Transaction().create_collection(CID)
                        .write(CID, OID, 0, b"1" * 8192))
    before = {(e.disk, e.length) for e in s._get_onode(CID, OID).extents}
    s.apply_transaction(Transaction().write(CID, OID, 0, b"2" * 8192))
    after = {(e.disk, e.length) for e in s._get_onode(CID, OID).extents}
    assert before.isdisjoint(after)
    assert s.read(CID, OID) == b"2" * 8192
    # freed space is reusable: total device should not balloon
    for _ in range(20):
        s.apply_transaction(Transaction().write(CID, OID, 0, b"x" * 8192))
    assert s.statfs()["total"] <= 8192 * 4 + 4 * 4096
    s.umount()


# --------------------------------------------------- objectstore tool

def test_objectstore_tool_list_info_export_import(tmp_path, capsys):
    from ceph_tpu.store.blockstore import BlockStore
    from ceph_tpu.tools import objectstore_tool as ost
    src = str(tmp_path / "src")
    s = BlockStore(src)
    s.mkfs()
    s.mount()
    cid = CollectionId.pg(1, 4)
    s.apply_transaction(Transaction().create_collection(cid))
    for i in range(3):
        o = ObjectId(f"obj{i}", pool=1)
        s.apply_transaction(Transaction().write(cid, o, 0, b"D" * 100)
                            .setattr(cid, o, "_", b"m")
                            .omap_setkeys(cid, o, {b"k": bytes([i])}))
    s.umount()

    assert ost.main(["--data-path", src, "--op", "list-pgs"]) == 0
    assert "1.4" in capsys.readouterr().out
    assert ost.main(["--data-path", src, "--op", "list",
                     "--pgid", "1.4"]) == 0
    assert capsys.readouterr().out.count("obj") == 3
    assert ost.main(["--data-path", src, "--op", "info", "--pgid", "1.4",
                     "--object", "obj1"]) == 0
    import json as _json
    info = _json.loads(capsys.readouterr().out)
    assert info["size"] == 100 and info["omap_keys"] == 1

    exp = str(tmp_path / "pg.export")
    assert ost.main(["--data-path", src, "--op", "export",
                     "--pgid", "1.4", "--file", exp]) == 0
    capsys.readouterr()

    # import into a DIFFERENT backend (filestore)
    dst = str(tmp_path / "dst")
    d = ObjectStore.create("filestore", dst)
    d.mkfs()
    assert ost.main(["--data-path", dst, "--type", "filestore",
                     "--op", "import", "--file", exp]) == 0
    capsys.readouterr()
    d2 = ObjectStore.create("filestore", dst)
    d2.mount()
    oids = d2.collection_list(cid)
    assert {o.name for o in oids} == {"obj0", "obj1", "obj2"}
    for o in oids:
        assert d2.read(cid, o) == b"D" * 100
        assert d2.getattr(cid, o, "_") == b"m"
    d2.umount()

    # surgical remove
    assert ost.main(["--data-path", src, "--op", "remove",
                     "--pgid", "1.4", "--object", "obj0"]) == 0
    capsys.readouterr()
    assert ost.main(["--data-path", src, "--op", "list",
                     "--pgid", "1.4"]) == 0
    assert capsys.readouterr().out.count("obj") == 2


# ----------------------------------------------------------- compressor

def test_compressor_plugins_roundtrip():
    from ceph_tpu.compressor import CompressorError, create, plugin_names
    data = b"compressible " * 1000 + bytes(range(256))
    for name in ("zlib", "bz2", "lzma"):
        c = create(name)
        z = c.compress(data)
        assert len(z) < len(data)
        assert c.decompress(z) == data
    with pytest.raises(CompressorError):
        create("snappy")            # gated: native lib absent
    with pytest.raises(CompressorError):
        create("nope")
    assert "zlib" in plugin_names()
    with pytest.raises(CompressorError):
        create("zlib").decompress(b"not compressed data")


def test_blockstore_compression_roundtrip_and_savings(tmp_path):
    from ceph_tpu.store.blockstore import BlockStore
    path = str(tmp_path / "bsz")
    s = BlockStore(path, compression="zlib")
    s.mkfs()
    s.mount()
    payload = b"squeeze me please " * 4096           # ~72 KiB, redundant
    s.apply_transaction(Transaction().create_collection(CID)
                        .write(CID, OID, 0, payload))
    on = s._get_onode(CID, OID)
    assert any(e.alg == "zlib" for e in on.extents)
    assert sum(e.disk_len for e in on.extents) < len(payload) // 4
    assert s.read(CID, OID) == payload
    # incompressible data stays raw
    import os as _os
    rnd = _os.urandom(32768)
    OID2 = ObjectId("rand", pool=1)
    s.apply_transaction(Transaction().write(CID, OID2, 0, rnd))
    assert all(e.alg == "" for e in s._get_onode(CID, OID2).extents)
    assert s.read(CID, OID2) == rnd
    s.umount()
    # remount without compression configured still reads both (per-
    # extent alg tags), and mixed writes compose
    s2 = BlockStore(path)
    s2.mount()
    assert s2.read(CID, OID) == payload
    s2.apply_transaction(Transaction().write(CID, OID, 100, b"RAW"))
    want = bytearray(payload)
    want[100:103] = b"RAW"
    assert s2.read(CID, OID) == bytes(want)
    s2.umount()


# -------------------------------------------------------------- kstore

def test_kstore_remount_preserves_everything(tmp_path):
    """All state (data stripes, xattrs, omap) lives in the KV WAL and
    survives umount/mount (os/kstore/KStore.cc role)."""
    from ceph_tpu.store.kstore import KStore, STRIPE
    p = str(tmp_path / "ks")
    s = KStore(p)
    s.mkfs(); s.mount()
    t = Transaction()
    t.create_collection(CID)
    big = bytes(range(256)) * ((STRIPE * 2 + 777) // 256 + 1)
    t.write(CID, OID, 0, big)
    t.setattr(CID, OID, "_", b"oi-bytes")
    t.omap_setkeys(CID, OID, {b"a": b"1", b"b": b"2"})
    t.omap_setheader(CID, OID, b"HDR")
    s.apply_transaction(t)
    s.umount()

    s2 = KStore(p)
    s2.mount()
    assert s2.read(CID, OID) == big
    # partial read across a stripe boundary
    assert s2.read(CID, OID, STRIPE - 100, 200) == big[STRIPE - 100:
                                                       STRIPE + 100]
    assert s2.getattr(CID, OID, "_") == b"oi-bytes"
    hdr, omap = s2.omap_get(CID, OID)
    assert hdr == b"HDR" and omap == {b"a": b"1", b"b": b"2"}
    assert s2.collection_list(CID) == [OID]
    s2.umount()


def test_kstore_small_overwrite_wals_only_touched_stripes(tmp_path):
    """A 100-byte overwrite inside a multi-stripe object must not
    rewrite every stripe record (the store's reason to stripe)."""
    from ceph_tpu.store.kstore import KStore, STRIPE, P_DATA
    s = KStore("")
    s.mount()
    t = Transaction()
    t.create_collection(CID)
    t.write(CID, OID, 0, b"x" * (STRIPE * 4))
    s.apply_transaction(t)

    seen = []
    orig = s.db.submit
    def spy(kvt, sync=True):
        seen.extend(k for kind, k, _ in kvt.ops
                    if kind == 0 and k.startswith(b"D"))
        return orig(kvt, sync=sync)
    s.db.submit = spy
    t2 = Transaction()
    t2.write(CID, OID, STRIPE + 5, b"y" * 100)
    s.apply_transaction(t2)
    assert len(seen) == 1            # exactly one stripe rewritten
    got = s.read(CID, OID, STRIPE, 200)
    assert got[5:105] == b"y" * 100
    s.umount()


def test_kstore_clone_and_rename_carry_omap(tmp_path):
    from ceph_tpu.store.kstore import KStore
    s = KStore("")
    s.mount()
    o2 = ObjectId("obj2", pool=1)
    t = Transaction()
    t.create_collection(CID)
    t.write(CID, OID, 0, b"payload")
    t.omap_setkeys(CID, OID, {b"k": b"v"})
    t.clone(CID, OID, o2)
    s.apply_transaction(t)
    assert s.read(CID, o2) == b"payload"
    assert s.omap_get(CID, o2)[1] == {b"k": b"v"}
    # rename within the collection
    o3 = ObjectId("obj3", pool=1)
    t2 = Transaction()
    t2.try_rename(CID, o2, o3)
    s.apply_transaction(t2)
    assert s.read(CID, o3) == b"payload"
    assert not s.exists(CID, o2)
    s.umount()


def test_kstore_rename_replaces_existing_destination():
    """try_rename onto an existing object must REPLACE it wholesale —
    stale destination omap/data must not merge in (review finding)."""
    from ceph_tpu.store.kstore import KStore, STRIPE
    s = KStore("")
    s.mount()
    a = ObjectId("a", pool=1)
    b = ObjectId("b", pool=1)
    t = Transaction()
    t.create_collection(CID)
    t.write(CID, b, 0, b"Z" * (STRIPE * 2))
    t.omap_setkeys(CID, b, {b"old": b"1"})
    t.write(CID, a, 0, b"payload")
    t.omap_setkeys(CID, a, {b"k": b"v"})
    s.apply_transaction(t)
    t2 = Transaction()
    t2.try_rename(CID, a, b)
    s.apply_transaction(t2)
    assert s.omap_get(CID, b)[1] == {b"k": b"v"}
    assert s.read(CID, b) == b"payload"
    # extend past the first stripe: old b's bytes must not resurface
    t3 = Transaction()
    t3.write(CID, b, STRIPE + 5, b"!")
    s.apply_transaction(t3)
    assert s.read(CID, b, STRIPE, 5) == b"\x00" * 5
    s.umount()


def _random_txn(rng):
    """One seeded transaction touching data/xattr/omap."""
    from ceph_tpu.store.objectstore import Transaction
    from ceph_tpu.store.types import CollectionId, ObjectId
    cid = CollectionId("seq")
    oid = ObjectId(f"o{rng.integers(0, 6)}")
    t = Transaction()
    kind = int(rng.integers(0, 4))
    if kind == 0:
        t.write(cid, oid, int(rng.integers(0, 512)),
                bytes(rng.integers(0, 256, int(rng.integers(1, 2048)),
                                   dtype=np.uint8)))
    elif kind == 1:
        t.setattr(cid, oid, f"a{int(rng.integers(0, 3))}",
                  bytes(rng.integers(0, 256, 16, dtype=np.uint8)))
    elif kind == 2:
        t.omap_setkeys(cid, oid, {
            f"k{int(rng.integers(0, 4))}".encode():
            bytes(rng.integers(0, 256, 32, dtype=np.uint8))})
    else:
        t.truncate(cid, oid, int(rng.integers(0, 256)))
    return t


def _store_fingerprint(s):
    """Canonical digest of every object's data/xattrs/omap."""
    out = {}
    for cid in s.list_collections():
        for oid in s.collection_list(cid):
            o = (bytes(s.read(cid, oid, 0, -1)),
                 tuple(sorted(s.getattrs(cid, oid).items())),
                 tuple(sorted(s.omap_get(cid, oid)[1].items())))
            out[(cid.name, oid.name)] = o
    return out


def test_deterministic_crash_replay_sweep(tmp_path):
    """DeterministicOpSequence / filestore_kill_at role
    (test/objectstore/DeterministicOpSequence.cc, run_seed_to.sh):
    a seeded transaction sequence is killed at EVERY injection point
    — before-journal and after-journal-before-apply of each batch —
    and the remounted store must equal a clean replay of the exact
    transaction-boundary prefix: after-journal kills recover the txn,
    before-journal kills lose it, never anything in between."""
    from ceph_tpu.store.filestore import FileStore, KilledAt
    from ceph_tpu.store.objectstore import Transaction
    from ceph_tpu.store.types import CollectionId

    SEQ = 12
    seed = 1234

    def build_txns():
        rng = np.random.default_rng(seed)
        txns = [Transaction()]
        txns[0].create_collection(CollectionId("seq"))
        txns += [_random_txn(rng) for _ in range(SEQ)]
        return txns

    _fp_cache = {}

    def clean_prefix_fingerprint(m):
        """Fingerprint after applying the first m txns cleanly
        (cached: each prefix replays exactly once, in a FRESH dir —
        the oracle must not depend on op idempotence)."""
        if m not in _fp_cache:
            d = tmp_path / f"clean{m}"
            s = FileStore(str(d))
            s.mkfs(); s.mount()
            for t in build_txns()[:m]:
                s.queue_transactions([t])
            _fp_cache[m] = _store_fingerprint(s)
            s.umount()
        return _fp_cache[m]

    for n in range(1, SEQ + 2):
        for mode, survivors in (("after", n), ("before", n - 1)):
            d = tmp_path / f"kill_{mode}_{n}"
            s = FileStore(str(d))
            s.mkfs(); s.mount()
            s.kill_at = n if mode == "after" else -n
            died = False
            try:
                for t in build_txns():
                    s.queue_transactions([t])
            except KilledAt:
                died = True
            assert died, (mode, n)
            # crash: no umount/checkpoint — remount replays the WAL
            s2 = FileStore(str(d))
            s2.mount()
            assert _store_fingerprint(s2) == \
                clean_prefix_fingerprint(survivors), (mode, n)
            s2.umount()


# ------------------------------------------------- group-commit pipeline

def test_group_commit_callbacks_fire_in_submission_order(store):
    """on_commit callbacks fire in submission order even when the commit
    thread drains many queued batches in one group (ISSUE 1 invariant:
    repop acks / pglog last_complete ride these callbacks)."""
    import threading
    _mkcoll(store)
    committer = getattr(store, "_committer", None)
    if committer is not None:
        # hold the thread so every batch below lands in ONE group
        committer.gate = threading.Event()
    order = []
    n = 24
    for i in range(n):
        store.queue_transactions(
            [Transaction().write(CID, ObjectId(f"seq{i}", pool=1), 0,
                                 bytes([i]) * 128)],
            on_commit=lambda i=i: order.append(i))
    if committer is not None:
        committer.gate.set()
    store.sync()
    assert order == list(range(n))


def test_blockstore_group_commit_shares_fsyncs(tmp_path):
    """N concurrent transaction batches commit with fewer than N fsyncs:
    the kv-sync thread issues ONE data barrier + ONE atomic kv submit
    per group (BlueStore kv_sync_thread recipe)."""
    import threading
    from ceph_tpu.store.blockstore import BlockStore
    s = BlockStore(str(tmp_path / "bs"))
    s.mkfs()
    s.mount()
    _mkcoll(s)
    base = s.commit_counters()
    s._committer.gate = threading.Event()
    n = 16
    done = []
    for i in range(n):
        s.queue_transactions(
            [Transaction().write(CID, ObjectId(f"grp{i}", pool=1), 0,
                                 bytes([i]) * 4096)],
            on_commit=lambda i=i: done.append(i))
    s._committer.gate.set()
    s.sync()
    c = s.commit_counters()
    txns = c["txns"] - base["txns"]
    fsyncs = c["fsyncs"] - base["fsyncs"]
    batches = c["commit_batches"] - base["commit_batches"]
    assert txns == n and done == list(range(n))
    assert batches < n                # grouping engaged
    assert 1 <= fsyncs < n            # shared barriers, not per-txn
    assert c["fsyncs_saved"] > base["fsyncs_saved"]
    # group-committed state is really durable: crash-reopen sees it all
    s2 = BlockStore(str(tmp_path / "bs"))   # no umount (power cut)
    s2.mount()
    for i in range(n):
        assert s2.read(CID, ObjectId(f"grp{i}", pool=1)) == \
            bytes([i]) * 4096
    s2.umount()
    s.umount()


@pytest.mark.parametrize("point", ["before_data_sync", "before_kv"])
def test_blockstore_crash_ordering_data_before_metadata(tmp_path, point):
    """Fault-inject a power cut on the commit thread: a kv batch must
    never be visible (replayable) before its data blocks are fsync'd.
    The trace hook proves the data barrier strictly precedes the kv
    submit; a crash at either point leaves the object invisible on
    replay and fires NO commit callback."""
    from ceph_tpu.store.blockstore import BlockStore, StoreError
    path = str(tmp_path / "bs")
    s = BlockStore(path)
    s.mkfs()
    s.mount()
    s.apply_transaction(Transaction().create_collection(CID))  # durable
    stages = []
    s._committer.trace = lambda pt, n: stages.append(pt)
    s._committer.crash_at = point
    done = []
    s.queue_transactions([Transaction().write(CID, OID, 0, b"doomed")],
                         on_commit=lambda: done.append(1))
    # sync fails LOUDLY: durability can no longer be promised
    with pytest.raises(StoreError):
        s.sync()
    assert s._committer.dead
    assert done == []                 # never committed, never acked
    # and so do new writes (no silent phantom acceptance)
    with pytest.raises(StoreError):
        s.queue_transactions([Transaction().write(
            CID, ObjectId("after", pool=1), 0, b"x")])
    # applied state WAS readable in memory (apply/commit split) ...
    assert s.read(CID, OID) == b"doomed"
    if point == "before_kv":
        # ... and the data barrier ran strictly before the kv submit
        assert stages == ["before_data_sync", "before_kv"]
    else:
        assert stages == ["before_data_sync"]
    # power cut: abandon without umount (umount would flush), reopen
    s2 = BlockStore(path)
    s2.mount()
    assert s2._coll_exists(CID)       # the durable prefix survives
    with pytest.raises(NoSuchObject):
        s2.read(CID, OID)             # the un-fsync'd batch never lands
    s2.umount()
