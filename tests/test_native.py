"""Native library tests: crc32c check vectors, rjenkins parity with the
python hash, GF(2^8) apply parity with gf256.host_apply."""

import numpy as np
import pytest

from ceph_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib unavailable")


def test_crc32c_check_vectors():
    # standard castagnoli check value
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(b"") == 0
    # incremental == one-shot
    whole = native.crc32c(b"hello world")
    part = native.crc32c(b" world", native.crc32c(b"hello"))
    assert whole == part
    # unaligned head loop: crc of an offset numpy view must equal crc of a
    # fresh (aligned) copy of the same bytes
    raw = np.frombuffer(bytes(range(256)) * 3, np.uint8)
    for off in range(1, 9):
        view = raw[off:]
        aligned = view.copy()
        assert native.crc32c(view.tobytes()) == \
            native.crc32c(aligned.tobytes())
        # drive the C pointer-alignment path directly via an offset view
        import ctypes
        lib = native._load()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        got = lib.ceph_crc32c(0, view.ctypes.data_as(u8p), view.size)
        assert got == native.crc32c(aligned.tobytes())


def test_rjenkins_matches_python():
    from ceph_tpu.crush.hashfn import hash32_3
    rng = np.random.default_rng(0)
    for _ in range(100):
        a, b, c = (int(x) for x in rng.integers(0, 2**32, 3))
        assert native.rjenkins3(a, b, c) == hash32_3(a, b, c)


def test_rjenkins_batch_matches_scalar():
    from ceph_tpu.crush.hashfn import hash32_3
    rng = np.random.default_rng(3)
    a = rng.integers(0, 2**32, 64, dtype=np.uint32)
    out = native.rjenkins3_batch(a, 7, 123456)
    for i in range(a.size):
        assert out[i] == hash32_3(int(a[i]), 7, 123456)


def test_gf_matrix_apply_matches_host():
    from ceph_tpu.ec import gf256
    rng = np.random.default_rng(1)
    for (r, k, L) in [(1, 2, 64), (4, 8, 1000), (2, 3, 7)]:
        mat = rng.integers(0, 256, (r, k)).astype(np.uint8)
        chunks = rng.integers(0, 256, (k, L)).astype(np.uint8)
        assert np.array_equal(native.gf_matrix_apply(mat, chunks),
                              gf256.host_apply(mat, chunks))


def test_gf_simd_matches_scalar():
    # GFNI/AVX-512 kernel (when the host has it) vs the table sweep —
    # including the non-multiple-of-64 scalar tail path
    from ceph_tpu.ec import gf256
    if not native.gf_simd_available():
        import pytest
        pytest.skip("no GFNI/AVX-512 on this host")
    rng = np.random.default_rng(2)
    for (r, k, L) in [(4, 8, 1 << 16), (2, 8, 100001), (3, 5, 63)]:
        mat = rng.integers(0, 256, (r, k)).astype(np.uint8)
        chunks = rng.integers(0, 256, (k, L)).astype(np.uint8)
        got = native.gf_matrix_apply(mat, chunks)
        want = native.gf_matrix_apply(mat, chunks, force_scalar=True)
        assert np.array_equal(got, want), (r, k, L)
        assert np.array_equal(got, gf256.host_apply(mat, chunks))


def test_region_xor():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 256, 1000).astype(np.uint8)
    b = rng.integers(0, 256, 1000).astype(np.uint8)
    assert np.array_equal(native.region_xor(a, b), a ^ b)
