"""Past-intervals / PriorSet peering: the interval walk, blocked-on-down
semantics, `osd lost`, and stray-copy rescue.

Mirrors the reference's PG::generate_past_intervals / PriorSet logic
(osd/PG.cc:3300 region) and its qa thrash invariants: a PG whose only
possibly-written copies are down must NOT serve (it blocks) until an
operator declares the osds lost; a stray copy holding the newest data
must be found and adopted even when no current member has it.
"""

import asyncio
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.client import ObjectOperationError  # noqa: E402
from ceph_tpu.osd.pglog import PastInterval  # noqa: E402


def test_past_interval_roundtrip():
    iv = PastInterval(5, 9, [1, 2], [2, 1], 2, True)
    iv2 = PastInterval.from_bytes(iv.to_bytes())
    assert iv2 == iv and iv2.maybe_went_rw


def _pg_of(admin, pool, oid):
    m = admin.monc.osdmap
    from ceph_tpu.osd.types import ObjectLocator
    pid = m.lookup_pool(pool)
    raw = m.object_locator_to_pg(oid, ObjectLocator(pid))
    pgid = m.pools[pid].raw_pg_to_pg(raw)   # masked: matches PG instances
    up, _, acting, primary = m.pg_to_up_acting_osds(pgid)
    return pgid, acting, primary


def test_blocked_when_rw_interval_all_down_then_osd_lost():
    """Kill BOTH holders of a 2-replica PG: the remapped PG must refuse
    to serve (down+peering, PriorSet blocked) until `osd lost`."""
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("p", pg_num=8, size=2)
        io = admin.open_ioctx("p")
        # find an object and its acting pair
        oid = None
        for i in range(64):
            cand = f"obj{i}"
            _, acting, _ = _pg_of(admin, "p", cand)
            if len(acting) == 2:
                oid = cand
                break
        assert oid is not None
        await io.write_full(oid, b"precious")
        pgid, acting, _ = _pg_of(admin, "p", oid)
        a, b = acting

        # kill both holders and mark them out so crush remaps the pg to
        # survivors with no data
        await cl.kill_osd(a)
        await cl.mark_down_and_wait(admin, a)
        await cl.kill_osd(b)
        await cl.mark_down_and_wait(admin, b)
        for o in (a, b):
            await admin.mon_command({"prefix": "osd out", "id": o})
        deadline = asyncio.get_running_loop().time() + 15
        while True:
            _, new_acting, new_primary = _pg_of(admin, "p", oid)
            if new_acting and not (set(new_acting) & {a, b}):
                break
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)

        # some survivor must now be primary and BLOCKED
        _, new_acting, new_primary = _pg_of(admin, "p", oid)
        assert new_primary not in (a, b) and new_primary >= 0
        pg = None
        deadline = asyncio.get_running_loop().time() + 10
        while pg is None or not pg.peering_blocked_by:
            for osd in cl.osds.values():
                for p in osd.pgs.values():
                    if p.pgid.without_shard() == pgid.without_shard() \
                            and p.is_primary():
                        pg = p
            assert asyncio.get_running_loop().time() < deadline, \
                "pg never blocked on the downed rw interval"
            await asyncio.sleep(0.1)
        assert set(pg.peering_blocked_by) <= {a, b}

        # reads must NOT be served from the empty survivors
        with pytest.raises(asyncio.TimeoutError):
            await io.read(oid, timeout=2.0)

        # operator declares the osds lost -> pg unblocks (data is gone,
        # an honest ENOENT instead of a hang)
        for o in (a, b):
            await admin.mon_command({"prefix": "osd lost", "id": o,
                                     "yes_i_really_mean_it": True})
        deadline = asyncio.get_running_loop().time() + 15
        while True:
            try:
                await io.read(oid, timeout=2.0)
                break   # served (empty object would also be a serve)
            except ObjectOperationError:
                break   # -ENOENT: pg active, object honestly gone
            except asyncio.TimeoutError:
                assert asyncio.get_running_loop().time() < deadline, \
                    "pg stayed blocked after osd lost"
        await cl.stop()
    asyncio.run(run())


def test_stray_copy_rescues_writes_after_full_remap():
    """Move a PG entirely off its acting set (reweight both members to
    0): the new members hold nothing, but peering must find the STRAY
    copies via past intervals and adopt their data."""
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("p", pg_num=8, size=2)
        io = admin.open_ioctx("p")
        oid = None
        for i in range(64):
            cand = f"obj{i}"
            _, acting, _ = _pg_of(admin, "p", cand)
            if len(acting) == 2:
                oid = cand
                break
        await io.write_full(oid, b"survives the remap")
        pgid, acting, _ = _pg_of(admin, "p", oid)
        a, b = acting

        # push both members out (osds stay UP as strays)
        for o in (a, b):
            await admin.mon_command({"prefix": "osd out", "id": o})
        deadline = asyncio.get_running_loop().time() + 15
        while True:
            _, new_acting, _ = _pg_of(admin, "p", oid)
            if new_acting and not (set(new_acting) & {a, b}):
                break
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)

        # the data must be served by the NEW acting set (pulled from the
        # strays during peering)
        deadline = asyncio.get_running_loop().time() + 20
        while True:
            try:
                got = await io.read(oid, timeout=3.0)
                assert got == b"survives the remap"
                break
            except (asyncio.TimeoutError, ObjectOperationError):
                assert asyncio.get_running_loop().time() < deadline, \
                    "data lost after full remap: stray never consulted"
                await asyncio.sleep(0.2)

        # once clean, the primary tells the strays to drop their copies
        deadline = asyncio.get_running_loop().time() + 20
        while True:
            stray_live = [
                1 for o in (a, b) if o in cl.osds
                for p in cl.osds[o].pgs.values()
                if p.pgid.without_shard() == pgid.without_shard()]
            if not stray_live:
                break
            if asyncio.get_running_loop().time() > deadline:
                break   # removal is best-effort cleanup; don't hard-fail
            await asyncio.sleep(0.2)
        await cl.stop()
    asyncio.run(run())


def test_restart_survivor_unblocks_without_lost():
    """The good path: when one member of the rw interval comes BACK, the
    pg unblocks by itself and serves the old data (no operator action)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("p", pg_num=8, size=2)
        io = admin.open_ioctx("p")
        oid = None
        for i in range(64):
            cand = f"obj{i}"
            _, acting, _ = _pg_of(admin, "p", cand)
            if len(acting) == 2:
                oid = cand
                break
        await io.write_full(oid, b"come back to me")
        pgid, acting, _ = _pg_of(admin, "p", oid)
        a, b = acting
        store_a = await cl.kill_osd(a)
        await cl.mark_down_and_wait(admin, a)
        store_b = await cl.kill_osd(b)
        await cl.mark_down_and_wait(admin, b)
        await asyncio.sleep(1.5)
        # restart one with its data: peering should find it and serve
        await cl.start_osd(a, store=store_a)
        deadline = asyncio.get_running_loop().time() + 25
        while True:
            try:
                got = await io.read(oid, timeout=3.0)
                assert got == b"come back to me"
                break
            except (asyncio.TimeoutError, ObjectOperationError):
                assert asyncio.get_running_loop().time() < deadline, \
                    "pg never recovered after a member returned"
                await asyncio.sleep(0.2)
        await cl.stop()
    asyncio.run(run())


def test_stale_survivor_cascade_blocks_until_newest_interval_heard():
    """The cascade the reference's build_prior guards against
    (/root/reference/src/osd/PG.cc build_prior): interval I1 = {A,B}
    writes v1; I2 = {C,D} (A,B down) writes v2; then C,D die and A,B
    come BACK with stale v1.  The PG must NOT serve v1 — it blocks on
    {C,D} (the newest maybe-rw interval) until one returns, then serves
    v2."""
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("p", pg_num=8, size=2)
        io = admin.open_ioctx("p")
        oid = None
        for i in range(64):
            cand = f"obj{i}"
            _, acting, _ = _pg_of(admin, "p", cand)
            if len(acting) == 2:
                oid = cand
                break
        assert oid is not None
        await io.write_full(oid, b"v1")
        pgid, acting, _ = _pg_of(admin, "p", oid)
        a, b = acting
        cd = [o for o in cl.osds if o not in (a, b)]
        assert len(cd) == 2
        c, d = cd

        # ---- interval 2: {a,b} down+out -> pg remaps to {c,d} ----
        store_a = await cl.kill_osd(a)
        await cl.mark_down_and_wait(admin, a)
        store_b = await cl.kill_osd(b)
        await cl.mark_down_and_wait(admin, b)
        for o in (a, b):
            await admin.mon_command({"prefix": "osd out", "id": o})
        deadline = asyncio.get_running_loop().time() + 20
        while True:
            _, new_acting, _ = _pg_of(admin, "p", oid)
            if new_acting and not (set(new_acting) & {a, b}):
                break
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        # {a,b} were killed, not lost: {c,d} block first, then unblock
        # via `osd lost` (their data is in our hands as store_a/store_b,
        # which the cluster will never see again)
        for o in (a, b):
            await admin.mon_command({"prefix": "osd lost", "id": o,
                                     "yes_i_really_mean_it": True})
        # v2 lands on the NEW interval {c,d}
        deadline = asyncio.get_running_loop().time() + 25
        while True:
            try:
                await asyncio.wait_for(io.write_full(oid, b"v2"), 3.0)
                break
            except (asyncio.TimeoutError, ObjectOperationError):
                assert asyncio.get_running_loop().time() < deadline, \
                    "write never succeeded on the new interval"
                await asyncio.sleep(0.2)

        # ---- the cascade: {c,d} die; stale {a,b} come back ----
        store_c = await cl.kill_osd(c)
        await cl.mark_down_and_wait(admin, c)
        store_d = await cl.kill_osd(d)
        await cl.mark_down_and_wait(admin, d)
        for o in (c, d):
            await admin.mon_command({"prefix": "osd out", "id": o})
        # revive a,b with their STALE stores; mark them in again
        await cl.start_osd(a, store=store_a)
        await cl.start_osd(b, store=store_b)
        for o in (a, b):
            await admin.mon_command({"prefix": "osd in", "id": o})
        # the pg must map to live members again
        deadline = asyncio.get_running_loop().time() + 20
        while True:
            _, new_acting, np_ = _pg_of(admin, "p", oid)
            if new_acting and not (set(new_acting) & {c, d}) \
                    and np_ >= 0:
                break
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)

        # a stale read MUST NOT be served: v1 would be silent data loss
        try:
            got = await io.read(oid, timeout=3.0)
            assert got == b"v2", \
                f"STALE DATA SERVED: read {got!r}, newest was b'v2'"
            served_early = True
        except asyncio.TimeoutError:
            served_early = False     # blocked, as required
        if not served_early:
            # bring one member of the newest interval back: the pg must
            # unblock and serve v2
            await cl.start_osd(c, store=store_c)
            deadline = asyncio.get_running_loop().time() + 30
            while True:
                try:
                    got = await io.read(oid, timeout=3.0)
                    assert got == b"v2", f"read {got!r} != v2"
                    break
                except (asyncio.TimeoutError, ObjectOperationError):
                    assert asyncio.get_running_loop().time() < deadline, \
                        "pg never served v2 after C returned"
                    await asyncio.sleep(0.2)
        del store_d
        await cl.stop()
    asyncio.run(run())
