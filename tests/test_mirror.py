"""Journal library + rbd-mirror async replication.

Mirrors the reference coverage: journal append/replay/commit/trim
(test/journal/*.cc) and ImageReplayer bootstrap + incremental replay +
failover (test/rbd_mirror/test_ImageReplayer.cc).
"""

import asyncio
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.journal import Journaler  # noqa: E402
from ceph_tpu.services.rbd import RBD, Image  # noqa: E402
from ceph_tpu.services.rbd_mirror import ImageReplayer  # noqa: E402


def test_journal_append_replay_commit_trim():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("j", pg_num=8)
        io = admin.open_ioctx("j")
        jr = Journaler(io, "img1", object_size=256)  # tiny: forces rotation
        assert not await jr.exists()
        await jr.create()
        assert await jr.exists()
        seqs = [await jr.append(f"event-{i}".encode()) for i in range(20)]
        assert seqs == list(range(1, 21))
        got = [e async for e in jr.replay(0)]
        assert [e.seq for e in got] == seqs
        assert got[3].payload == b"event-3"
        # replay resumes mid-stream
        got = [e.seq async for e in jr.replay(15)]
        assert got == [16, 17, 18, 19, 20]
        # a new Journaler handle recovers the append position
        jr2 = Journaler(io, "img1", object_size=256)
        assert await jr2.append(b"after-reopen") == 21
        # trim respects the slowest registered client
        await jr.register_client("a")
        await jr.register_client("b")
        await jr.commit("a", 21)
        assert await jr.trim() == 0          # b still at 0
        await jr.commit("b", 15)
        removed = await jr.trim()
        assert removed > 0
        # everything at or below the slowest commit may be gone, nothing
        # above it may be
        remaining = [e.seq async for e in jr.replay(15)]
        assert remaining == list(range(16, 22))
        await jr.remove()
        assert not await jr.exists()
        await cl.stop()
    asyncio.run(run())


def test_rbd_mirror_bootstrap_and_incremental_replay():
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("site-a", pg_num=8)
        await admin.pool_create("site-b", pg_num=8)
        src_io = admin.open_ioctx("site-a")
        dst_io = admin.open_ioctx("site-b")
        await RBD(src_io).create("vol", 4 << 20, order=16)
        img = await Image.open(src_io, "vol", journaling=True)
        await img.write(0, b"A" * 100000)
        await img.write(200000, b"B" * 50000)

        rep = ImageReplayer(src_io, dst_io, "vol")
        await rep.bootstrap()
        await rep.replay_once()
        dst = await Image.open(dst_io, "vol")
        assert await dst.read(0, 100000) == b"A" * 100000
        assert await dst.read(200000, 50000) == b"B" * 50000

        # incremental: new primary writes flow on the next replay
        await img.write(50, b"CHANGED")
        await img.discard(200000, 50000)
        applied = await rep.replay_once()
        assert applied >= 2
        dst = await Image.open(dst_io, "vol")
        assert (await dst.read(50, 7)) == b"CHANGED"
        assert await dst.read(200000, 50000) == b"\x00" * 50000

        # resize replicates too
        await img.resize(2 << 20)
        await rep.replay_once()
        dst = await Image.open(dst_io, "vol")
        assert dst.size == 2 << 20

        # failover: the secondary is a fully usable image
        await dst.write(0, b"promoted")
        assert (await dst.read(0, 8)) == b"promoted"

        # journal trimmed up to the mirror's commit position
        jr = Journaler(src_io, "vol")
        pos = await jr.get_commit("rbd-mirror")
        assert pos >= applied
        await cl.stop()
    asyncio.run(run())


def test_mirror_requires_journaling():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("a", pg_num=4)
        await admin.pool_create("b", pg_num=4)
        src_io = admin.open_ioctx("a")
        await RBD(src_io).create("nojournal", 1 << 20, order=16)
        rep = ImageReplayer(src_io, admin.open_ioctx("b"), "nojournal")
        with pytest.raises(RuntimeError, match="journal"):
            await rep.bootstrap()
        await cl.stop()
    asyncio.run(run())
