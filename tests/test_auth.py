"""cephx auth: keyring, ticket protocol, and secured-cluster e2e.

Mirrors the reference test strategy for auth (test/mon/moncap.cc role +
qa cephx coverage): protocol-level unit tests of seal/ticket/authorizer
invariants, then a live cluster with auth_supported=cephx proving that
unauthenticated or wrong-key clients are rejected while keyed clients do
real I/O (VERDICT r2 ask #10).
"""

import asyncio
import os
import sys
import time

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster, make_ctx  # noqa: E402

from ceph_tpu.auth import cephx  # noqa: E402
from ceph_tpu.auth.keyring import Keyring, generate_key  # noqa: E402
from ceph_tpu.client import Rados  # noqa: E402
from ceph_tpu.mon.client import CommandError  # noqa: E402


# ------------------------------------------------------------------ keyring

def test_keyring_text_roundtrip(tmp_path):
    kr = Keyring()
    k1 = kr.add("client.admin", caps={"mon": "allow *", "osd": "allow *"})
    k2 = kr.add("osd.0", caps={"mon": "allow profile osd"})
    path = str(tmp_path / "keyring")
    kr.save(path)
    kr2 = Keyring.load(path)
    assert kr2.get_key("client.admin") == k1
    assert kr2.get_key("osd.0") == k2
    assert kr2.get_caps("client.admin") == {"mon": "allow *",
                                            "osd": "allow *"}
    assert "osd.9" not in kr2


# ----------------------------------------------------------------- protocol

def test_seal_unseal_and_tamper():
    key = generate_key()
    blob = cephx.seal(key, b"secret payload")
    assert cephx.unseal(key, blob) == b"secret payload"
    with pytest.raises(cephx.AuthError):
        cephx.unseal(generate_key(), blob)           # wrong key
    bad = bytearray(blob)
    bad[20] ^= 1
    with pytest.raises(cephx.AuthError):
        cephx.unseal(key, bytes(bad))                # tampered

def test_ticket_issue_open_expiry():
    master = generate_key()
    svc = cephx.service_secret(master, "osd")
    blob, skey = cephx.issue_ticket(svc, "client.admin", "osd",
                                    {"osd": "allow *"}, ttl=100.0)
    t = cephx.open_ticket(svc, blob)
    assert (t.entity, t.service) == ("client.admin", "osd")
    assert t.session_key == skey
    with pytest.raises(cephx.AuthError):
        cephx.open_ticket(svc, blob, now=time.time() + 200)   # expired
    with pytest.raises(cephx.AuthError):
        cephx.open_ticket(cephx.service_secret(master, "mds"), blob)


def test_authorizer_mutual_proof():
    svc = cephx.service_secret(generate_key(), "osd")
    blob, skey = cephx.issue_ticket(svc, "client.x", "osd", {}, 100.0)
    authorizer, nonce = cephx.make_authorizer(blob, skey)
    ticket, proof = cephx.verify_authorizer(svc, authorizer)
    assert ticket.entity == "client.x"
    assert cephx.hmac_eq(proof,
                         cephx.authorizer_reply_proof(skey, nonce))
    # an authorizer built on a FORGED session key fails the nonce proof
    forged, _ = cephx.make_authorizer(blob, generate_key())
    with pytest.raises(cephx.AuthError):
        cephx.verify_authorizer(svc, forged)


def test_message_signature():
    skey = generate_key()
    sig = cephx.sign_payload(skey, b"payload bytes")
    assert cephx.hmac_eq(sig, cephx.sign_payload(skey, b"payload bytes"))
    assert not cephx.hmac_eq(sig, cephx.sign_payload(skey, b"payload bytez"))


# -------------------------------------------------------------- secured e2e

class SecureCluster(Cluster):
    """In-process cluster with auth_supported=cephx and a shared keyring."""

    def __init__(self, tmpdir: str):
        super().__init__(
            ctx_factory=lambda name: self._secure(make_ctx(name)))
        self.keyring_path = os.path.join(tmpdir, "keyring")
        kr = Keyring()
        kr.add("mon.")
        kr.add("client.admin", caps={"mon": "allow *", "osd": "allow *"})
        kr.add("client.readonly", caps={"mon": "allow r",
                                        "osd": "allow *"})
        for i in range(16):
            kr.add(f"osd.{i}", caps={"mon": "allow profile osd",
                                     "osd": "allow *"})
        kr.save(self.keyring_path)

    def _secure(self, ctx):
        ctx.config.set("auth_supported", "cephx")
        ctx.config.set("keyring", self.keyring_path)
        return ctx


def test_secured_cluster_end_to_end(tmp_path):
    async def run():
        cl = SecureCluster(str(tmp_path))
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=8)
        io = admin.open_ioctx("p")
        await io.write_full("obj", b"under cephx")
        assert await io.read("obj") == b"under cephx"

        # 1. client with a WRONG key: auth handshake denied
        wrong_ctx = cl._secure(make_ctx("client.admin"))
        bad_kr = Keyring()
        bad_kr.add("mon.")
        bad_kr.add("client.admin", caps={"mon": "allow *"})
        bad_path = str(tmp_path / "bad_keyring")
        bad_kr.save(bad_path)
        wrong_ctx.config.set("keyring", bad_path)
        with pytest.raises(CommandError) as ei:
            await Rados(wrong_ctx, cl.monmap).connect()
        assert ei.value.retcode == -13           # EACCES

        # 2. entity not in the mon's db: denied
        ghost_ctx = cl._secure(make_ctx("client.ghost"))
        ghost_kr = Keyring()
        ghost_kr.add("mon.")
        ghost_kr.add("client.ghost", caps={"mon": "allow *"})
        ghost_path = str(tmp_path / "ghost_keyring")
        ghost_kr.save(ghost_path)
        ghost_ctx.config.set("keyring", ghost_path)
        with pytest.raises(CommandError):
            await Rados(ghost_ctx, cl.monmap).connect()

        # 3. auth runtime commands
        ack = await admin.mon_command({"prefix": "auth ls"})
        assert "osd.0" in ack.outs and "client.admin" in ack.outs
        ack = await admin.mon_command({"prefix": "auth get-or-create",
                                       "entity": "client.newguy",
                                       "caps": {"mon": "allow r"}})
        assert "client.newguy" in ack.outs
        await cl.stop()
    asyncio.run(run())


def test_unauthenticated_client_rejected(tmp_path):
    """A client that skips the cephx handshake gets nothing: the mon
    denies its commands and the OSD refuses its data-path sockets."""
    async def run():
        cl = SecureCluster(str(tmp_path))
        admin = await cl.start(3)
        await admin.pool_create("p", pg_num=8)
        io = admin.open_ioctx("p")
        await io.write_full("x", b"protected")

        from ceph_tpu.client.objecter import Objecter
        from ceph_tpu.mon.client import MonClient
        from ceph_tpu.msg.messenger import Messenger
        from ceph_tpu.msg.types import EntityName
        from ceph_tpu.osd.messages import OP_READ, OSDOp
        from ceph_tpu.osd.types import ObjectLocator
        sneak_ctx = make_ctx("client.sneak")   # auth_supported stays none
        msgr = Messenger(sneak_ctx, EntityName("client", "sneak"))
        await msgr.bind()
        monc = MonClient(sneak_ctx, msgr, cl.monmap)
        objecter = Objecter(sneak_ctx, msgr, monc)

        # mon side: command denied outright
        with pytest.raises(CommandError) as ei:
            await monc.command({"prefix": "status"}, timeout=3.0)
        assert ei.value.retcode in (-13, -110)   # EACCES (or starved out)

        # osd side: even with a stolen osdmap, the data socket is refused
        monc.osdmap = admin.monc.osdmap
        pool_id = admin.monc.osdmap.lookup_pool("p")
        with pytest.raises(asyncio.TimeoutError):
            await objecter.op_submit(
                "x", ObjectLocator(pool_id),
                [OSDOp(OP_READ, 0, 100)], timeout=3.0)
        # the keyed admin still works fine alongside
        assert await io.read("x") == b"protected"
        await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_caps_enforced_and_tickets_renew(tmp_path):
    """MonCap checks: a read-only entity can look but not touch; and the
    client renews tickets before expiry (CephXTicketHandler renew role)."""
    async def run():
        cl = SecureCluster(str(tmp_path))
        admin = await cl.start(3)

        ro_ctx = cl._secure(make_ctx("client.readonly"))
        ro = Rados(ro_ctx, cl.monmap)
        await ro.connect()
        ack = await ro.mon_command({"prefix": "status"})      # r: ok
        assert "HEALTH" in ack.outs
        with pytest.raises(CommandError) as ei:               # w: denied
            await ro.pool_create("nope", pg_num=8)
        assert ei.value.retcode == -13
        with pytest.raises(CommandError) as ei:               # x: denied
            await ro.mon_command({"prefix": "auth ls"})
        assert ei.value.retcode == -13

        # renewal: with a tiny ttl the renew task must refresh expiry
        admin2_ctx = cl._secure(make_ctx("client.admin"))
        admin2_ctx.config.set("auth_ticket_ttl", 2.0)
        # the mon's ttl governs issue; shrink it there too
        cl.mons[0].cfg.set("auth_ticket_ttl", 2.0)
        admin2 = Rados(admin2_ctx, cl.monmap)
        await admin2.connect()
        first_expiry = min(t[2] for t in admin2.monc.tickets.values())
        await asyncio.sleep(3.0)
        renewed = min(t[2] for t in admin2.monc.tickets.values())
        assert renewed > first_expiry, "tickets were not renewed"
        ack = await admin2.mon_command({"prefix": "status"})   # still live
        assert "HEALTH" in ack.outs
        await ro.shutdown()
        await admin2.shutdown()
        await cl.stop()
    asyncio.run(run())
