"""Sharded RGW bucket index: key-hash spread across N shard objects,
merged listings, two-phase crash reconciliation per shard, and live
reshard (old-layout reads during the copy window, 503 write gate).

Mirrors the reference's rgw_reshard.cc + cls_rgw shard contract: the
index never lies about committed entries, no matter how many objects
hold it or which generation is live.
"""

import asyncio
import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.cls.rgw import index_shard_oid, shard_of_key  # noqa: E402
from ceph_tpu.services.rgw import (S3Gateway, _index_oid,  # noqa: E402
                                   _owning_oid, _shard_oids)


def _j(d) -> bytes:
    return json.dumps(d).encode()


async def _gw(index_shards=None):
    cl = Cluster()
    admin = await cl.start(3)
    await admin.pool_create(".rgw", pg_num=8)
    gw = S3Gateway(admin, require_auth=False,
                   index_shards=index_shards)
    return cl, gw


def test_shard_layout_helpers():
    # routing is pure + stable: every writer/reader agrees on the
    # owning shard with no coordination
    assert shard_of_key("k", 1) == 0
    assert all(0 <= shard_of_key(f"key-{i}", 7) < 7 for i in range(50))
    assert shard_of_key("same", 4) == shard_of_key("same", 4)
    assert index_shard_oid("b", 2, 3) == ".bucket.index.b.g2.3"
    # legacy layout (no "index" in the rec) keeps the pre-shard oid
    assert _shard_oids("b", None) == [".bucket.index.b"]
    assert _owning_oid("b", "k", None) == _index_oid("b")
    lay = {"shards": 4, "gen": 1}
    assert _shard_oids("b", lay) == [
        f".bucket.index.b.g1.{s}" for s in range(4)]
    assert _owning_oid("b", "k", lay) == \
        index_shard_oid("b", 1, shard_of_key("k", 4))


def test_sharded_put_list_delete_spread():
    """Objects spread across shard objects; usage is the sum of shard
    headers; listings stay globally ordered; delete_bucket sweeps
    every shard object."""
    async def run():
        cl, gw = await _gw(index_shards=4)
        st, _, _ = await gw._put_bucket("b")
        assert st == 200
        keys = [f"obj-{i:02d}" for i in range(20)]
        for i, k in enumerate(keys):
            st, _, _ = await gw._put_object("b", k, b"x" * (i + 1), {})
            assert st == 200

        rep = await gw.bucket_shard_stats("b")
        assert rep["shards"] == 4 and rep["gen"] == 0
        assert rep["entries"] == 20
        assert rep["bytes"] == sum(range(1, 21))
        populated = [s for s in rep["per_shard"] if s["entries"]]
        assert len(populated) >= 2        # the spread actually spreads
        # each shard holds exactly its crc32-owned keys
        for s, row in enumerate(rep["per_shard"]):
            assert row["entries"] == sum(
                1 for k in keys if shard_of_key(k, 4) == s)

        # merged listing: globally ordered despite 4 sorted sources
        got = [k async for k, _ in gw._iter_index("b")]
        assert got == sorted(keys)
        # pagination across the merge: max-keys + NextMarker walk
        walked, marker = [], ""
        for _ in range(10):
            q = "max-keys=7" + (f"&marker={marker}" if marker else "")
            st, _, body = await gw._list_objects("b", q)
            assert st == 200
            page = [seg.split(b"</Key>")[0].decode()
                    for seg in body.split(b"<Key>")[1:]]
            walked += page
            if b"<IsTruncated>true</IsTruncated>" not in body:
                break
            marker = body.split(b"<NextMarker>")[1] \
                .split(b"</NextMarker>")[0].decode()
        assert walked == sorted(keys)

        # reads route to the owning shard
        st, _, data = await gw._get_object("b", "obj-07", {})
        assert st == 200 and data == b"x" * 8
        for k in keys:
            st, _, _ = await gw._delete_object("b", k)
            assert st == 204
        rep = await gw.bucket_shard_stats("b")
        assert rep["entries"] == 0 and rep["bytes"] == 0
        st, _, _ = await gw._delete_bucket("b")
        assert st == 204
        # every shard object is gone with the bucket
        for oid in _shard_oids("b", {"shards": 4, "gen": 0}):
            with pytest.raises(Exception):
                await gw.io.omap_get(oid)
        await cl.stop()
    asyncio.run(run())


def test_sharded_delimiter_fold_across_shards():
    """CommonPrefixes folding runs over the MERGED stream: a folded
    group whose keys live on different shards still collapses to one
    row, and the fold-restart seek works against the merge."""
    async def run():
        cl, gw = await _gw(index_shards=4)
        await gw._put_bucket("b")
        keys = [f"a/{i}" for i in range(6)] + \
               [f"b/{i}" for i in range(6)] + ["top1", "top2"]
        # sanity: the folded groups genuinely straddle shards
        assert len({shard_of_key(k, 4) for k in keys}) >= 2
        for k in keys:
            await gw._put_object("b", k, b"d", {})
        st, _, body = await gw._list_objects("b", "delimiter=/")
        assert st == 200
        assert body.count(b"<CommonPrefixes>") == 2
        assert b"<Prefix>a/</Prefix>" in body
        assert b"<Prefix>b/</Prefix>" in body
        assert b"<Key>top1</Key>" in body and b"<Key>top2</Key>" in body
        assert b"<Key>a/0</Key>" not in body
        # tiny pages force the fold-restart seek through the merge
        seen, marker = [], ""
        for _ in range(10):
            q = "delimiter=/&max-keys=1" + (
                f"&marker={marker}" if marker else "")
            st, _, body = await gw._list_objects("b", q)
            for seg in body.split(b"<Key>")[1:]:
                seen.append(seg.split(b"</Key>")[0].decode())
            for seg in body.split(b"<Prefix>")[1:]:
                seen.append(seg.split(b"</Prefix>")[0].decode())
            if b"<IsTruncated>true</IsTruncated>" not in body:
                break
            tok = body.split(b"<NextMarker>")[1]
            marker = tok.split(b"</NextMarker>")[0].decode()
        assert seen == ["a/", "b/", "top1", "top2"]
        await cl.stop()
    asyncio.run(run())


def test_sharded_crash_reconciliation():
    """A 'gateway crash' between prepare and complete leaves the
    pending marker on the OWNING shard only; check --fix expires it
    there, and a dangling entry heals via dir_suggest on its shard."""
    async def run():
        cl, gw = await _gw(index_shards=4)
        await gw._put_bucket("b")
        lay = {"shards": 4, "gen": 0}
        # simulate the crash: prepare lands, complete never does
        oid = _owning_oid("b", "crashed", lay)
        await gw.io.exec(oid, "rgw", "bucket_prepare_op",
                         _j({"tag": "dead", "op": "put",
                             "key": "crashed", "ts": 1.0}))
        rep = await gw.bucket_check("b")
        assert [p["tag"] for p in rep["pending"]] == ["dead"]
        # the marker sits on exactly the owning shard
        chk = json.loads(await gw.io.exec(oid, "rgw", "bucket_check"))
        assert [p["tag"] for p in chk["pending"]] == ["dead"]
        # an in-flight marker blocks bucket deletion (phantom entry
        # resurrection guard) until reconciled
        st, _, _ = await gw._delete_bucket("b")
        assert st == 409
        rep = await gw.bucket_check("b", fix=True, min_age=0.0)
        assert rep["fixed"]["expired_tags"] == ["dead"]
        assert rep["pending"] == []

        # dangling entry (data object lost): GET 404s AND suggests the
        # removal back to the owning shard
        await gw.io.exec(_owning_oid("b", "ghost", lay), "rgw",
                         "bucket_complete_op",
                         _j({"op": "put", "key": "ghost",
                             "entry": {"size": 5, "etag": "", "mtime": 0,
                                       "soid": "b//ghost.nope"}}))
        st, _, _ = await gw._get_object("b", "ghost", {})
        assert st == 404
        rep = await gw.bucket_shard_stats("b")
        assert rep["entries"] == 0
        st, _, _ = await gw._delete_bucket("b")
        assert st == 204
        await cl.stop()
    asyncio.run(run())


def test_live_reshard():
    """Legacy 1-object index -> 4 shards: reads keep working against
    the old layout during the copy window while writes 503 (SlowDown),
    the flip is atomic, and the old index object is dropped."""
    async def run():
        cl, gw = await _gw()          # default: legacy unsharded
        await gw._put_bucket("b")
        keys = [f"k-{i:02d}" for i in range(12)]
        for i, k in enumerate(keys):
            await gw._put_object("b", k, b"z" * (i + 1), {})
        rep = await gw.bucket_shard_stats("b")
        assert rep["shards"] == 1 and rep["gen"] == -1    # legacy

        # copy window semantics: flag the rec like reshard does and
        # observe the gate before running the real thing
        rec = await gw._bucket_rec("b")
        rec["resharding"] = {"shards": 4, "gen": 0}
        await gw._save_bucket_rec("b", rec)
        st, _, _ = await gw._put_object("b", "new", b"x", {})
        assert st == 503
        st, _, _ = await gw._delete_object("b", keys[0])
        assert st == 503
        st, _, data = await gw._get_object("b", keys[3], {})
        assert st == 200 and data == b"z" * 4   # reads ride old layout
        assert await gw.reshard_bucket("b", 4) is None   # no re-enter
        rec.pop("resharding")
        await gw._save_bucket_rec("b", rec)

        out = await gw.reshard_bucket("b", 4)
        assert out == {"shards": 4, "gen": 0, "entries": 12}
        rep = await gw.bucket_shard_stats("b")
        assert rep["shards"] == 4 and rep["entries"] == 12
        assert rep["bytes"] == sum(range(1, 13))
        assert sum(1 for s in rep["per_shard"] if s["entries"]) >= 2
        # the legacy index object is gone; reads + listing re-route
        with pytest.raises(Exception):
            await gw.io.omap_get(_index_oid("b"))
        assert [k async for k, _ in gw._iter_index("b")] == keys
        st, _, data = await gw._get_object("b", keys[5], {})
        assert st == 200 and data == b"z" * 6
        # writes flow again, routed by the new hash
        st, _, _ = await gw._put_object("b", "after", b"q" * 3, {})
        assert st == 200
        st, _, _ = await gw._delete_object("b", keys[0])
        assert st == 204
        rep = await gw.bucket_shard_stats("b")
        assert rep["entries"] == 12               # -1 del, +1 put

        # second reshard bumps the generation (4 -> 2)
        out = await gw.reshard_bucket("b", 2)
        assert out["gen"] == 1 and out["entries"] == 12
        assert [k async for k, _ in gw._iter_index("b")] == \
            sorted(keys[1:] + ["after"])
        # a FRESH gateway (cold cache) sees the new layout via the rec
        gw2 = S3Gateway(gw.rados, require_auth=False)
        st, _, data = await gw2._get_object("b", "after", {})
        assert st == 200 and data == b"qqq"
        await cl.stop()
    asyncio.run(run())
