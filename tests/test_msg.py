"""Messenger tests: delivery, ordering, typed codec, lossless replay under
fault injection, lossy reset, peer-restart detection.

Models the reference's messenger test strategy (test/msgr/test_msgr.cc:
client/server dispatchers exchanging counted messages under
ms_inject_socket_failures).
"""

import asyncio

import pytest

from ceph_tpu.common.context import Context
from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.msg import (
    Dispatcher, EntityAddr, EntityName, Message, MPing, Messenger, Policy,
    register_message,
)


@register_message
class MTestEcho(Message):
    TYPE = 9001

    def __init__(self, n: int = 0, blob: bytes = b""):
        super().__init__()
        self.n = n
        self.blob = blob

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.n).bytes_(self.blob)

    @classmethod
    def decode_payload(cls, dec: Decoder, struct_v: int) -> "MTestEcho":
        return cls(dec.u64(), dec.bytes_())


class Collector(Dispatcher):
    def __init__(self):
        self.msgs = []
        self.resets = []
        self.remote_resets = []
        self.event = asyncio.Event()

    def ms_dispatch(self, msg) -> bool:
        self.msgs.append(msg)
        self.event.set()
        return True

    def ms_handle_reset(self, addr) -> None:
        self.resets.append(addr)
        self.event.set()

    def ms_handle_remote_reset(self, addr) -> None:
        self.remote_resets.append(addr)

    async def wait_for(self, pred, timeout=10.0):
        async def _loop():
            while True:
                self.event.clear()
                if pred(self):       # check AFTER clear: no lost wakeup
                    return
                await self.event.wait()
        await asyncio.wait_for(_loop(), timeout)


def make_messenger(name, **cfg):
    ctx = Context(name)
    for k, v in cfg.items():
        ctx.config.set(k, v)
    return Messenger(ctx, EntityName.parse(name))


async def _pair(**cfg):
    a = make_messenger("osd.1", **cfg)
    b = make_messenger("osd.2", **cfg)
    ca, cb = Collector(), Collector()
    a.add_dispatcher(ca)
    b.add_dispatcher(cb)
    await a.bind()
    await b.bind()
    return a, b, ca, cb


def test_send_receive_typed():
    async def run():
        a, b, ca, cb = await _pair()
        a.send_message(MTestEcho(7, b"payload"), b.addr)
        a.send_message(MPing("hi"), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 2)
        assert isinstance(cb.msgs[0], MTestEcho)
        assert cb.msgs[0].n == 7 and cb.msgs[0].blob == b"payload"
        assert str(cb.msgs[0].src_name) == "osd.1"
        assert isinstance(cb.msgs[1], MPing) and cb.msgs[1].note == "hi"
        # reply path: b -> a using the source addr
        b.send_message(MTestEcho(8), cb.msgs[0].src_addr)
        await ca.wait_for(lambda c: len(c.msgs) >= 1)
        assert ca.msgs[0].n == 8
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_ordering_and_volume():
    async def run():
        a, b, _, cb = await _pair()
        n = 500
        for i in range(n):
            a.send_message(MTestEcho(i, bytes([i % 251]) * (i % 4096)), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= n, timeout=30)
        assert [m.n for m in cb.msgs] == list(range(n))
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_lossless_replay_under_fault_injection():
    """With 1-in-20 injected socket failures, every message still arrives
    exactly once and in order (sender replay + receiver dedupe)."""
    async def run():
        a, b, _, cb = await _pair(ms_inject_socket_failures=20,
                                  ms_initial_backoff=0.01)
        n = 200
        for i in range(n):
            a.send_message(MTestEcho(i), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= n, timeout=60)
        assert [m.n for m in cb.msgs] == list(range(n))
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_lossy_client_reset():
    async def run():
        client = make_messenger("client.1")
        client.set_policy("client", Policy.lossy_client())
        cc = Collector()
        client.add_dispatcher(cc)
        # no bind for the client; target address has no listener
        dead = EntityAddr("127.0.0.1", 1, 0)
        client.send_message(MPing("x"), dead)
        await cc.wait_for(lambda c: len(c.resets) >= 1)
        assert cc.resets[0].without_nonce() == ("127.0.0.1", 1)
        assert client.get_connection(dead) is None  # conn dropped
        await client.shutdown()
    asyncio.run(run())


def test_lossless_survives_receiver_restart():
    """Messages queued while the peer is down are delivered after it comes
    back on the same port; the receiver sees a remote reset of the sender?
    No — the RECEIVER restarted, so the sender just reconnects and replays."""
    async def run():
        a = make_messenger("osd.1", ms_initial_backoff=0.01)
        b = make_messenger("osd.2")
        cb = Collector()
        b.add_dispatcher(cb)
        await a.bind()
        addr_b = await b.bind()
        port = addr_b.port
        a.send_message(MTestEcho(1), addr_b)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        await b.shutdown()
        # queue while down
        a.send_message(MTestEcho(2), addr_b)
        await asyncio.sleep(0.05)
        # restart receiver on same port (new messenger instance)
        b2 = make_messenger("osd.2")
        cb2 = Collector()
        b2.add_dispatcher(cb2)
        await b2.bind(port=port)
        await cb2.wait_for(lambda c: len(c.msgs) >= 1, timeout=20)
        assert cb2.msgs[0].n == 2
        await a.shutdown()
        await b2.shutdown()
    asyncio.run(run())


def test_remote_reset_detection():
    """Receiver notices a restarted sender (new nonce, same ip:port space)."""
    async def run():
        b = make_messenger("osd.2")
        cb = Collector()
        b.add_dispatcher(cb)
        await b.bind()

        a1 = make_messenger("osd.1", ms_initial_backoff=0.01)
        await a1.bind(port=0)
        host, port = a1.addr.host, a1.addr.port
        a1.send_message(MTestEcho(1), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        await a1.shutdown()

        a2 = make_messenger("osd.1", ms_initial_backoff=0.01)
        # same bind address as a1 -> same (host, port) key, new nonce
        await a2.bind(port=port)
        assert a2.addr.without_nonce() == (host, port)
        a2.send_message(MTestEcho(2), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 2)
        assert len(cb.remote_resets) == 1
        await a2.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_mark_down():
    async def run():
        a, b, _, cb = await _pair()
        a.send_message(MTestEcho(1), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        a.mark_down(b.addr)
        assert a.get_connection(b.addr) is None or \
            a.get_connection(b.addr).closed
        # a fresh send creates a new connection transparently
        a.send_message(MTestEcho(2), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 2)
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_dispatcher_chain():
    class Picky(Dispatcher):
        def __init__(self, want):
            self.want = want
            self.got = []

        def ms_dispatch(self, msg) -> bool:
            if isinstance(msg, self.want):
                self.got.append(msg)
                return True
            return False

    async def run():
        a = make_messenger("client.1")
        b = make_messenger("osd.1")
        pings, echos = Picky(MPing), Picky(MTestEcho)
        b.add_dispatcher(pings)
        b.add_dispatcher(echos)
        await b.bind()
        a.send_message(MPing("p"), b.addr)
        a.send_message(MTestEcho(3), b.addr)

        async def until():
            while not (pings.got and echos.got):
                await asyncio.sleep(0.01)
        await asyncio.wait_for(until(), 10)
        assert pings.got[0].note == "p" and echos.got[0].n == 3
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_perf_msgr_harness():
    """perf_msgr_client/server role (src/test/msgr/): the throughput
    harness round-trips real typed messages over TCP and reports
    sane numbers."""
    from ceph_tpu.tools.perf_msgr import run as perf_run

    out = asyncio.run(perf_run(count=100, size=1024, inflight=16))
    assert out["count"] == 100
    assert out["msgs_per_sec"] > 0
    assert out["p99_us"] >= out["p50_us"] > 0


def test_corked_pump_coalesces_burst():
    """A burst of messages queued in one event-loop tick drains as ONE
    corked socket write (msgs/write > 1), while per-connection ordering
    and the ack/replay protocol stay intact."""
    async def run():
        a, b, _, cb = await _pair()
        n = 64
        # queue the whole burst before yielding: the pump corks it
        for i in range(n):
            a.send_message(MTestEcho(i, bytes([i % 251]) * 512), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= n, timeout=30)
        # ordering preserved through the cork
        assert [m.n for m in cb.msgs] == list(range(n))
        # coalesced: far fewer socket writes than messages
        assert a._sock_write_msgs == n
        assert a._sock_writes < n
        assert a._sock_write_msgs / a._sock_writes > 1.0
        # ack semantics intact: the peer's acks drain the replay buffer
        conn = a.conns[b.addr.without_nonce()]

        async def drained():
            while conn.unacked:
                await asyncio.sleep(0.005)
        await asyncio.wait_for(drained(), 10)
        assert conn.acked_seq == conn.out_seq
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_local_delivery_fast_path():
    """Co-located messengers with ms_local_delivery skip the socket
    entirely: messages arrive typed, ordered, and decoded from their own
    serialized copy (object isolation), with zero corked socket writes
    and the local counter accounting for every frame."""
    async def run():
        a, b, ca, cb = await _pair(ms_local_delivery=True)
        n = 32
        for i in range(n):
            a.send_message(MTestEcho(i, bytes([i % 251]) * 256), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= n)
        assert [m.n for m in cb.msgs] == list(range(n))
        assert str(cb.msgs[0].src_name) == "osd.1"
        # isolation: mutating the received blob can't touch the sender
        assert cb.msgs[0].blob == bytes([0]) * 256
        assert a._local_msgs == n
        assert a._sock_writes == 0
        # reply path rides local too (src_addr is b's registry key)
        b.send_message(MTestEcho(99), cb.msgs[0].src_addr)
        await ca.wait_for(lambda c: len(c.msgs) >= 1)
        assert ca.msgs[0].n == 99 and b._local_msgs == 1
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_local_delivery_requires_both_ends_and_no_injection():
    """The fast path only engages when BOTH ends opted in and nothing
    requires real wire semantics — otherwise it falls back to TCP with
    identical delivery behavior."""
    async def run():
        # receiver did not opt in -> TCP
        a = make_messenger("osd.1", ms_local_delivery=True)
        b = make_messenger("osd.2")
        cb = Collector()
        b.add_dispatcher(cb)
        await a.bind()
        await b.bind()
        a.send_message(MTestEcho(1, b"x"), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        assert a._local_msgs == 0 and a._sock_writes > 0
        await a.shutdown()
        await b.shutdown()
        # fault injection armed -> TCP (thrash semantics preserved)
        c = make_messenger("osd.3", ms_local_delivery=True,
                           ms_inject_socket_failures=10**9)
        d = make_messenger("osd.4", ms_local_delivery=True)
        cd = Collector()
        d.add_dispatcher(cd)
        await c.bind()
        await d.bind()
        c.send_message(MTestEcho(2, b"y"), d.addr)
        await cd.wait_for(lambda c_: len(c_.msgs) >= 1)
        assert c._local_msgs == 0
        await c.shutdown()
        await d.shutdown()
    asyncio.run(run())


def test_local_delivery_bounded_intake_backpressures_sender():
    """The local intake queue is bounded by a bytes budget tied to
    ms_dispatch_throttle_bytes: a flood from a co-located sender parks
    on the async producer gate (messages stay in the SENDER'S queue)
    instead of growing receiver intake RAM; once the receiver drains,
    everything arrives in order with nothing lost."""

    @register_message
    class MTestThrottled(Message):
        TYPE = 9002
        THROTTLE_DISPATCH = True

        def __init__(self, n: int = 0, blob: bytes = b""):
            super().__init__()
            self.n = n
            self.blob = blob

        def encode_payload(self, enc: Encoder) -> None:
            enc.u64(self.n).bytes_(self.blob)

        @classmethod
        def decode_payload(cls, dec: Decoder, struct_v: int):
            return cls(dec.u64(), dec.bytes_())

        def local_cost(self) -> int:
            return len(self.blob)

    class Releasing(Collector):
        """Dispatcher that completes each op instantly (releases its
        dispatch-throttle budget), like the OSD does at op finish."""

        def __init__(self, msgr):
            super().__init__()
            self.msgr = msgr

        def ms_dispatch(self, msg) -> bool:
            super().ms_dispatch(msg)
            self.msgr.put_dispatch_throttle(msg)
            return True

    async def run():
        from ceph_tpu.common.throttle import AsyncThrottle
        a = make_messenger("osd.1", ms_local_delivery=True,
                           ms_dispatch_throttle_bytes=4096)
        b = make_messenger("osd.2", ms_local_delivery=True,
                           ms_dispatch_throttle_bytes=4096)
        cb = Releasing(b)
        b.add_dispatcher(cb)
        await a.bind()
        await b.bind()
        # receiver's op budget: exhausted, so its local worker blocks on
        # dispatch WHILE HOLDING intake budget — the TCP-equivalent of
        # a reader stalled over a full throttle
        b.dispatch_throttle = AsyncThrottle("t", 8192)
        await b.dispatch_throttle.get(8192)
        n, blob = 24, bytes(1024)
        for i in range(n):
            a.send_message(MTestThrottled(i, blob), b.addr)
        await asyncio.sleep(0.1)
        conn = a.conns[b.addr.without_nonce()]
        # intake admitted at most the bytes budget; the rest is parked
        # at the sender behind the async gate
        gate = b._local_intake_gate(conn.conn_id)
        assert gate.cur <= 4096 + 1024
        assert len(conn.out_q) >= n - 6
        assert len(cb.msgs) == 0          # nothing dispatched yet
        # drain: release the receiver's op budget
        b.dispatch_throttle.put(8192)
        await cb.wait_for(lambda c: len(c.msgs) >= n, timeout=20)
        assert [m.n for m in cb.msgs] == list(range(n))
        assert a._local_msgs == n and a._sock_writes == 0
        await a.shutdown()
        await b.shutdown()
    asyncio.run(run())


def test_local_delivery_peer_shutdown_resets():
    """A local session to a messenger that shut down behaves like a
    torn-down lossy TCP session: the sender's dispatcher sees a reset
    and the connection is dropped (higher layers own resend)."""
    async def run():
        a = make_messenger("client.1", ms_local_delivery=True)
        b = make_messenger("osd.2", ms_local_delivery=True)
        ca, cb = Collector(), Collector()
        a.add_dispatcher(ca)
        b.add_dispatcher(cb)
        await a.bind()
        await b.bind()
        a.send_message(MTestEcho(1), b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        await b.shutdown()
        a.send_message(MTestEcho(2), b.addr)
        await ca.wait_for(lambda c: len(c.resets) >= 1)
        assert a.get_connection(b.addr) is None
        await a.shutdown()
    asyncio.run(run())
