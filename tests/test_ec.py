"""Erasure-code engine tests.

Mirrors the reference test strategy: per-plugin k/m/technique matrices
(test/erasure-code/TestErasureCodeJerasure.cc, TestErasureCodeIsa.cc,
TestErasureCodeLrc.cc, TestErasureCodeShec.cc) plus kernel-vs-host
bit-exactness, which stands in for the reference's SIMD-vs-scalar parity.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import ErasureCodeError, factory, plugin_names
from ceph_tpu.ec import gf256


def rand_bytes(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


# -- gf256 field/matrix math -------------------------------------------------

def test_field_axioms():
    rng = np.random.default_rng(1)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(1, 256, 3))
        assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
        assert gf256.gf_mul(a, gf256.gf_mul(b, c)) == \
            gf256.gf_mul(gf256.gf_mul(a, b), c)
        assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1
        # distributivity over xor
        assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)


def test_mul_table_matches_scalar():
    t = gf256.mul_table()
    for a in (0, 1, 2, 3, 97, 255):
        for b in (0, 1, 5, 128, 255):
            assert t[a, b] == gf256.gf_mul(a, b)


def test_mat_inv_roundtrip():
    rng = np.random.default_rng(2)
    for n in (1, 2, 5, 8):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf256.mat_inv(m)
                break
            except ValueError:
                continue
        assert np.array_equal(gf256.mat_mul(m, inv), gf256.identity(n))


@pytest.mark.parametrize("maker", [gf256.rs_vandermonde_matrix,
                                   gf256.cauchy_matrix])
def test_generator_any_k_rows_invertible(maker):
    k, m = 4, 3
    g = maker(k, m)
    assert np.array_equal(g[:k], gf256.identity(k))
    for rows in itertools.combinations(range(k + m), k):
        gf256.mat_inv(g[list(rows)])  # must not raise


def test_bitmatrix_expansion_semantics():
    rng = np.random.default_rng(3)
    for _ in range(20):
        c = int(rng.integers(0, 256))
        x = int(rng.integers(0, 256))
        m = gf256.expand_to_bitmatrix(np.array([[c]], np.uint8))
        bits = np.array([(x >> i) & 1 for i in range(8)], np.uint8)
        y_bits = (m @ bits) % 2
        y = sum(int(b) << i for i, b in enumerate(y_bits))
        assert y == gf256.gf_mul(c, x)


def test_express_rows_consistency():
    g = gf256.cauchy_matrix(4, 2)
    # chunk 5 from chunks [0,1,2,3] must equal direct encode row
    m = gf256.express_rows(g[[0, 1, 2, 3]], g[[5]])
    assert np.array_equal(gf256.mat_mul(m, g[[0, 1, 2, 3]]), g[[5]])
    with pytest.raises(ValueError):
        gf256.express_rows(g[[0, 1]], g[[5]])


# -- kernel vs host ground truth --------------------------------------------

def test_kernel_matches_host_apply():
    from ceph_tpu.ec.kernel import matrix_apply
    rng = np.random.default_rng(4)
    for (r, k, L) in [(1, 2, 64), (4, 8, 1024), (3, 5, 333)]:
        mat = rng.integers(0, 256, (r, k)).astype(np.uint8)
        chunks = rng.integers(0, 256, (k, L)).astype(np.uint8)
        want = gf256.host_apply(mat, chunks)
        got = matrix_apply(mat)(chunks)
        assert np.array_equal(want, got)


def test_pallas_fused_kernel_matches_host_apply():
    # The fused unpack->matmul->mod2->pack kernel (the TPU production
    # path) validated here via the pallas interpreter; the same code
    # runs compiled on the chip in bench.py with a bit-exact assert.
    import jax.numpy as jnp
    from ceph_tpu.ec.gf256 import expand_to_bitmatrix
    from ceph_tpu.ec.kernel import _apply_bitmatrix_pallas
    rng = np.random.default_rng(5)
    for (r, k, L) in [(4, 8, 8192), (2, 8, 16384), (3, 5, 9000)]:
        mat = rng.integers(0, 256, (r, k)).astype(np.uint8)
        chunks = rng.integers(0, 256, (k, L)).astype(np.uint8)
        want = gf256.host_apply(mat, chunks)
        bm = jnp.asarray(expand_to_bitmatrix(mat), jnp.int8)
        got = np.asarray(_apply_bitmatrix_pallas(bm, jnp.asarray(chunks),
                                                 interpret=True))
        assert np.array_equal(want, got), (r, k, L)


# -- codec matrices (reference-style per-plugin parameter sweeps) ------------

PROFILES = [
    ("rs", {"k": "2", "m": "1"}),
    ("rs", {"k": "4", "m": "2"}),
    ("rs", {"k": "8", "m": "4"}),
    ("jerasure", {"k": "3", "m": "2", "technique": "reed_sol_van"}),
    ("jerasure", {"k": "4", "m": "2", "technique": "cauchy_good"}),
    ("isa", {"k": "4", "m": "2", "technique": "cauchy"}),
    ("isa", {"k": "6", "m": "3"}),
]


@pytest.mark.parametrize("plugin,profile", PROFILES)
def test_encode_decode_roundtrip(plugin, profile):
    ec = factory(plugin, profile)
    k, m = ec.k, ec.m
    data = rand_bytes(k * 700 + 13, seed=k * 31 + m)
    chunks = ec.encode(set(range(k + m)), data)
    assert len(chunks) == k + m
    # every erasure pattern of up to m chunks decodes
    for n_lost in range(1, m + 1):
        for lost in itertools.combinations(range(k + m), n_lost):
            have = {i: c for i, c in chunks.items() if i not in lost}
            dec = ec.decode(set(lost), have)
            for i in lost:
                assert np.array_equal(dec[i], chunks[i]), \
                    f"chunk {i} mismatch losing {lost}"
    assert ec.decode_concat(
        {i: chunks[i] for i in range(k + m) if i >= m})[:len(data)] == data


def test_chunk_size_alignment():
    ec = factory("rs", {"k": "3", "m": "2"})
    assert ec.get_chunk_size(1) == 128
    assert ec.get_chunk_size(3 * 128) == 128
    assert ec.get_chunk_size(3 * 128 + 1) == 256
    assert ec.get_chunk_count() == 5
    assert ec.get_data_chunk_count() == 3


def test_minimum_to_decode_greedy():
    ec = factory("rs", {"k": "4", "m": "2"})
    # all wanted available -> wanted
    assert ec.minimum_to_decode({0, 1}, {0, 1, 2, 3}) == {0, 1}
    # missing chunk -> k sources
    got = ec.minimum_to_decode({0}, {1, 2, 3, 4, 5})
    assert len(got) == 4 and got <= {1, 2, 3, 4, 5}
    with pytest.raises(ErasureCodeError):
        ec.minimum_to_decode({0}, {1, 2, 3})


def test_registry_errors():
    with pytest.raises(ErasureCodeError, match="known plugins"):
        factory("nope", {})
    with pytest.raises(ErasureCodeError):
        factory("rs", {"k": "0", "m": "1"})
    with pytest.raises(ErasureCodeError):
        factory("rs", {"k": "2", "m": "1", "technique": "bogus"})
    assert {"rs", "jerasure", "isa", "lrc", "shec"} <= set(plugin_names())


def test_host_backend_matches_tpu_backend():
    data = rand_bytes(4096, seed=9)
    tpu = factory("rs", {"k": "4", "m": "2"})
    host = factory("rs", {"k": "4", "m": "2", "backend": "host"})
    a = tpu.encode(set(range(6)), data)
    b = host.encode(set(range(6)), data)
    for i in range(6):
        assert np.array_equal(a[i], b[i])


# -- LRC ---------------------------------------------------------------------

def test_lrc_kml_roundtrip():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    assert ec.k == 4 and ec.get_chunk_count() == 8  # 4+2 global + 2 local
    data = rand_bytes(4 * 300, seed=11)
    chunks = ec.encode(set(range(8)), data)
    for lost in range(8):
        have = {i: c for i, c in chunks.items() if i != lost}
        dec = ec.decode({lost}, have)
        assert np.array_equal(dec[lost], chunks[lost])


def test_lrc_local_repair_reads_fewer_chunks():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    # single lost chunk: plan should use one l-wide group, not k-wide global
    plan = ec.minimum_to_decode({0}, set(range(1, 8)))
    assert len(plan) <= 3, f"local repair should read <= l=3, got {plan}"


def test_lrc_layers_profile():
    ec = factory("lrc", {
        "mapping": "DD_DD_",
        "layers": [["DDc___", {}], ["___DDc", {}]],
    })
    assert ec.k == 4 and ec.m == 2
    data = rand_bytes(4 * 256, seed=12)
    chunks = ec.encode(set(range(6)), data)
    # chunk ids: data 0..3, coding 4..5; lose one data chunk per group
    for lost in (0, 2):
        have = {i: c for i, c in chunks.items() if i != lost}
        dec = ec.decode({lost}, have)
        assert np.array_equal(dec[lost], chunks[lost])


def test_lrc_bad_profiles():
    with pytest.raises(ErasureCodeError):
        factory("lrc", {"k": "4", "m": "2", "l": "4"})  # (k+m) % l != 0
    with pytest.raises(ErasureCodeError):
        factory("lrc", {"layers": [["Dc", {}]]})  # no mapping


# -- SHEC --------------------------------------------------------------------

def test_shec_roundtrip_single_failures():
    ec = factory("shec", {"k": "4", "m": "3", "c": "2"})
    data = rand_bytes(4 * 500, seed=13)
    chunks = ec.encode(set(range(7)), data)
    for lost in range(7):
        have = {i: c for i, c in chunks.items() if i != lost}
        dec = ec.decode({lost}, have)
        assert np.array_equal(dec[lost], chunks[lost])


def test_shec_c_failures_always_recoverable():
    k, m, c = 4, 3, 2
    ec = factory("shec", {"k": str(k), "m": str(m), "c": str(c)})
    data = rand_bytes(k * 200, seed=14)
    chunks = ec.encode(set(range(k + m)), data)
    for lost in itertools.combinations(range(k + m), c):
        have = {i: ch for i, ch in chunks.items() if i not in lost}
        dec = ec.decode(set(lost), have)
        for i in lost:
            assert np.array_equal(dec[i], chunks[i])


def test_shec_partial_read_recovery():
    # one lost data chunk should not require reading all k chunks when a
    # covering shingle is narrower
    ec = factory("shec", {"k": "6", "m": "3", "c": "1"})
    plan = ec.minimum_to_decode({0}, set(range(1, 9)))
    assert len(plan) < 6, f"shec partial read should beat k=6, got {plan}"


def test_shec_minimum_with_cost_needs_specific_chunks():
    # regression: cheapest-k prefix may be rank-deficient for sparse codes;
    # the planner must widen until a decodable set exists
    ec = factory("shec", {"k": "4", "m": "3", "c": "2"})
    cost = {1: 1, 2: 1, 3: 1, 5: 1, 6: 9}
    plan = ec.minimum_to_decode_with_cost({0}, cost)
    # must actually decode with the planned chunks
    data = rand_bytes(4 * 128, seed=16)
    chunks = ec.encode(set(range(7)), data)
    dec = ec.decode({0}, {i: chunks[i] for i in plan})
    assert np.array_equal(dec[0], chunks[0])


def test_shec_minimum_wanted_only_set_decodable():
    # regression: want includes both present and missing chunks, and the
    # present ones alone suffice
    ec = factory("shec", {"k": "2", "m": "1", "c": "1"})
    plan = ec.minimum_to_decode({0, 1, 2}, {1, 2})
    assert plan == {1, 2}


def test_rs_undecodable_raises_ec_error():
    ec = factory("rs", {"k": "4", "m": "2"})
    data = rand_bytes(4 * 128, seed=17)
    chunks = ec.encode(set(range(6)), data)
    with pytest.raises(ErasureCodeError):
        ec.decode({0}, {1: chunks[1], 2: chunks[2]})


def test_preload_all_builtin():
    from ceph_tpu.ec.registry import preload
    preload(plugin_names())


def test_lrc_kml_propagates_backend():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3", "backend": "host"})
    for layer in ec.layers:
        assert layer.codec._use_tpu is False


def test_shec_c_equals_m_is_mds():
    ec = factory("shec", {"k": "4", "m": "2", "c": "2"})
    data = rand_bytes(4 * 128, seed=15)
    chunks = ec.encode(set(range(6)), data)
    for lost in itertools.combinations(range(6), 2):
        have = {i: ch for i, ch in chunks.items() if i not in lost}
        dec = ec.decode(set(lost), have)
        for i in lost:
            assert np.array_equal(dec[i], chunks[i])


# -- bit-matrix RAID-6 techniques: liberation / blaum_roth -------------------
# (reference ErasureCodeJerasureLiberation/BlaumRoth parameter semantics,
#  ErasureCodeJerasure.cc:305-483; constructions per the published papers —
#  see ceph_tpu/ec/bitmatrix.py)

@pytest.mark.parametrize("tech,kw", [
    ("liberation", [(2, 3), (5, 7), (7, 7), (10, 11)]),
    ("blaum_roth", [(2, 4), (6, 6), (10, 10)]),
])
def test_bitmatrix_roundtrip_all_erasure_pairs(tech, kw):
    for k, w in kw:
        ec = factory("jerasure", {"k": str(k), "m": "2", "technique": tech,
                                  "w": str(w), "packetsize": "8"})
        data = rand_bytes(137 * k + 13, seed=k * w)
        enc = ec.encode(set(range(k + 2)), data)
        assert ec.decode_concat(enc)[:len(data)] == data
        for gone in itertools.combinations(range(k + 2), 2):
            have = {i: v for i, v in enc.items() if i not in gone}
            out = ec.decode(set(gone), have)
            for i in gone:
                assert np.array_equal(out[i], enc[i]), (tech, k, w, gone)


def test_bitmatrix_chunk_size_is_packet_aligned():
    ec = factory("jerasure", {"k": "5", "m": "2", "technique": "liberation",
                              "w": "7", "packetsize": "2048"})
    cs = ec.get_chunk_size(1 << 20)
    assert cs % (7 * 2048) == 0 and cs % 128 == 0
    assert cs * 5 >= (1 << 20)


def test_bitmatrix_parity_differs_from_cauchy_alias():
    """Regression for VERDICT r2 weak #7: these techniques must not silently
    produce GF(2^8) Cauchy parity."""
    prof = {"k": "4", "m": "2", "w": "5", "packetsize": "4"}
    lib = factory("jerasure", dict(prof, technique="liberation"))
    cau = factory("jerasure", dict(prof, technique="cauchy_good"))
    data = rand_bytes(4 * 5 * 4 * 8)
    pl = lib.encode({4, 5}, data)
    pc = cau.encode({4, 5}, data)
    assert not (np.array_equal(pl[4], pc[4]) and np.array_equal(pl[5], pc[5]))


def test_bitmatrix_rejections():
    bad = [
        dict(k="3", m="2", technique="liberation", w="8"),    # w not prime
        dict(k="3", m="2", technique="blaum_roth", w="7"),    # w+1 not prime
        dict(k="3", m="3", technique="liberation", w="5"),    # m != 2
        dict(k="8", m="2", technique="liberation", w="7"),    # k > w
        dict(k="3", m="2", technique="liberation", w="5", packetsize="6"),
        dict(k="5", m="2", technique="liber8tion"),           # searched table
    ]
    for prof in bad:
        with pytest.raises(ErasureCodeError):
            factory("jerasure", prof)


def test_bitmatrix_liberation_q_block_weight():
    """Each liberation X_j (j>0) has exactly w+1 ones, X_0 = I (the paper's
    minimal-density property) and the P row is all identities."""
    from ceph_tpu.ec.bitmatrix import liberation_bitmatrix
    k, w = 6, 7
    B = liberation_bitmatrix(k, w)
    for j in range(k):
        P = B[:w, j * w:(j + 1) * w]
        Q = B[w:, j * w:(j + 1) * w]
        assert np.array_equal(P, np.eye(w, dtype=np.uint8))
        assert Q.sum() == (w if j == 0 else w + 1)


def test_pallas_variant_space_bit_exact():
    """Every autotune variant (layout x pack) must produce identical
    bytes — the tuner may install any of them."""
    import jax.numpy as jnp
    from ceph_tpu.ec import gf256
    from ceph_tpu.ec.kernel import _apply_bitmatrix_pallas
    gen = gf256.rs_vandermonde_matrix(4, 3)
    bm = jnp.asarray(gf256.expand_to_bitmatrix(gen[4:]), jnp.int8)
    rng = np.random.default_rng(13)
    chunks = rng.integers(0, 256, (4, 1024), dtype=np.uint8)
    want = gf256.host_apply(gen[4:], chunks)
    for layout in ("cb", "bc"):
        for pack in ("vpu", "mxu", "or"):
            got = np.asarray(_apply_bitmatrix_pallas(
                bm, jnp.asarray(chunks), interpret=True, tile=512,
                layout=layout, pack=pack))
            assert np.array_equal(got, want), (layout, pack)
