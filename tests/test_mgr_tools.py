"""mgr-lite, lockdep, psim, kvstore tool, reweight-by-utilization.

The §2/§5 tail components: manager module host over a live cluster,
lock-order race detection, placement simulation, offline kv surgery,
and utilization-driven reweighting through the mon.
"""

import asyncio
import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402


# ------------------------------------------------------------------ lockdep

def test_lockdep_detects_cycle_and_allows_consistent_order():
    from ceph_tpu.common.lockdep import (DepLock, LockOrderViolation,
                                         reset)

    async def run():
        reset()
        a, b = DepLock("a"), DepLock("b")
        # consistent order is fine, repeatedly
        for _ in range(3):
            async with a:
                async with b:
                    pass
        # reverse order closes the cycle
        with pytest.raises(LockOrderViolation) as ei:
            async with b:
                async with a:
                    pass
        assert "a" in str(ei.value) and "b" in str(ei.value)
        reset()
    asyncio.run(run())


def test_lockdep_three_lock_cycle():
    from ceph_tpu.common.lockdep import (DepLock, LockOrderViolation,
                                         reset)

    async def run():
        reset()
        a, b, c = DepLock("A"), DepLock("B"), DepLock("C")
        async with a:
            async with b:
                pass
        async with b:
            async with c:
                pass
        with pytest.raises(LockOrderViolation):
            async with c:
                async with a:
                    pass
        reset()
    asyncio.run(run())


def test_lockdep_factory_gated_by_config():
    from ceph_tpu.common.context import Context
    from ceph_tpu.common.lockdep import DepLock, make_lock
    ctx = Context("client.test")
    assert isinstance(make_lock(ctx, "x"), asyncio.Lock)
    ctx.config.set("lockdep", True)
    assert isinstance(make_lock(ctx, "x"), DepLock)


# --------------------------------------------------------------------- psim

def test_psim_distribution(capsys):
    from ceph_tpu.tools import psim
    assert psim.main(["--osds", "12", "--hosts", "4", "--pgs", "128",
                      "--engine", "host"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["osds"] == 12 and out["pgs"] == 128
    # every osd carries pgs and the spread is sane for straw2
    assert out["pg_per_osd"]["min"] > 0
    assert out["spread_ratio"] < 2.0


# ------------------------------------------------------------- kvstore tool

def test_kvstore_tool_surgery(tmp_path, capsys):
    from ceph_tpu.store.kv import FileDB
    from ceph_tpu.tools import kvstore_tool
    path = str(tmp_path / "kv")
    db = FileDB(path)
    db.submit(db.create_transaction().set("osdmap", b"full_1", b"\x01\x02")
              .set("auth", b"client.admin", b"key"))
    db.close()
    assert kvstore_tool.main([path, "list"]) == 0
    out = capsys.readouterr().out
    assert "osdmap" in out and "auth" in out
    assert kvstore_tool.main([path, "get", "osdmap", "full_1"]) == 0
    assert capsys.readouterr().out.strip() == "0102"
    assert kvstore_tool.main([path, "stats"]) == 0
    assert json.loads(capsys.readouterr().out)["keys"] == 2
    assert kvstore_tool.main([path, "rm", "auth", "client.admin"]) == 0
    capsys.readouterr()
    assert kvstore_tool.main([path, "stats"]) == 0
    assert json.loads(capsys.readouterr().out)["keys"] == 1


# ----------------------------------------------------- mgr + reweighting

def test_mgr_dashboard_and_balancer_over_cluster():
    from ceph_tpu.services.mgr import (BalancerModule, DashboardModule,
                                       Mgr)

    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("p", pg_num=16)
        io = admin.open_ioctx("p")
        for i in range(10):
            await io.write_full(f"o{i}", b"x" * 1000)
        # wait out the MPGStats report interval (2s default)
        for _ in range(100):
            if cl.mons[0].pgmon.pg_stats:
                break
            await asyncio.sleep(0.1)
        mgr = Mgr(admin)
        await mgr.start()
        dash: DashboardModule = mgr.get_module("dashboard")
        for _ in range(50):
            if dash.port:
                break
            await asyncio.sleep(0.05)
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       dash.port)
        writer.write(b"GET /health HTTP/1.1\r\n\r\n")
        await writer.drain()
        raw = await reader.read(65536)
        writer.close()
        body = json.loads(raw.split(b"\r\n\r\n", 1)[1])
        assert body["status"].startswith("HEALTH")

        bal: BalancerModule = mgr.get_module("balancer")
        ev = await bal.evaluate()
        assert ev["per_osd"] and ev["avg"] > 0
        await mgr.stop()
        await cl.stop()
    asyncio.run(run())


def test_reweight_by_utilization_moves_weight():
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("p", pg_num=32)
        await asyncio.sleep(1.2)       # stats tick
        # manual reweight surface
        await admin.mon_command({"prefix": "osd reweight", "id": 0,
                                 "weight": 0.5})
        while admin.monc.osdmap.osd_weight[0] != 0x8000:
            await asyncio.sleep(0.05)
        await admin.mon_command({"prefix": "osd reweight", "id": 0,
                                 "weight": 1.0})
        # utilization-driven: with an aggressive threshold SOME osd is
        # above 101% of mean and gets nudged down
        out = {"avg_pgs": 0}
        for _ in range(40):            # wait out the stats tick
            ack = await admin.mon_command(
                {"prefix": "osd reweight-by-utilization", "oload": 101})
            out = json.loads(ack.outs)
            if out["avg_pgs"] > 0:
                break
            await asyncio.sleep(0.3)
        assert out["avg_pgs"] > 0
        if out["reweighted"]:
            osd = int(next(iter(out["reweighted"])))
            while admin.monc.osdmap.osd_weight[osd] >= 0x10000:
                await asyncio.sleep(0.05)
        await cl.stop()
    asyncio.run(run())


# ------------------------------------------------- fsmap + config-key

def test_fsmap_registration_and_config_key():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        ack = await admin.mon_command({"prefix": "mds boot",
                                       "name": "mds.a",
                                       "addr": "127.0.0.1:1234:99"})
        assert ack.retcode == 0
        ack = await admin.mon_command({"prefix": "mds dump"})
        dump = json.loads(ack.outs)
        assert dump["mds.a"]["addr"] == "127.0.0.1:1234:99"

        await admin.mon_command({"prefix": "config-key set",
                                 "key": "rgw/zone", "val": "us-east"})
        ack = await admin.mon_command({"prefix": "config-key get",
                                       "key": "rgw/zone"})
        assert ack.outs == "us-east"
        ack = await admin.mon_command({"prefix": "config-key ls"})
        assert "rgw/zone" in json.loads(ack.outs)
        await admin.mon_command({"prefix": "config-key rm",
                                 "key": "rgw/zone"})
        import pytest as _pytest
        from ceph_tpu.mon.client import CommandError
        with _pytest.raises(CommandError):
            await admin.mon_command({"prefix": "config-key get",
                                     "key": "rgw/zone"})
        await cl.stop()
    asyncio.run(run())
