"""Health + introspection: PGMap/ceph -s, admin socket, OpTracker,
cluster log.

VERDICT r2 ask #7 done-criterion: `ceph -s` tracks a kill/recover cycle
correctly (HEALTH_OK -> WARN on kill -> OK after down-out + re-peer).
"""

import asyncio
import json
import sys
import tempfile

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster, FAST_CFG, make_ctx  # noqa: E402

from ceph_tpu.common.admin_socket import (AdminSocket,  # noqa: E402
                                          admin_command)
from ceph_tpu.common.op_tracker import OpTracker  # noqa: E402


# ----------------------------------------------------------- op tracker

def test_op_tracker_inflight_and_history():
    t = OpTracker(history_size=2)
    a = t.create("op-a")
    b = t.create("op-b")
    a.mark("reached_pg")
    d = t.dump_in_flight()
    assert d["num_ops"] == 2
    assert d["ops"][0]["description"] == "op-a"
    assert [e["event"] for e in d["ops"][0]["events"]] == \
        ["initiated", "reached_pg"]
    t.finish(a)
    assert t.dump_in_flight()["num_ops"] == 1
    assert t.dump_historic()["num_ops"] == 1
    t.finish(b)
    c = t.create("op-c")
    t.finish(c)
    d2 = t.dump_historic()          # ring bounded at 2
    assert d2["num_ops"] == 2
    assert [o["description"] for o in d2["ops"]] == ["op-b", "op-c"]


# --------------------------------------------------------- admin socket

def test_admin_socket_commands():
    async def run():
        ctx = make_ctx("osd.9")
        with tempfile.TemporaryDirectory() as td:
            path = f"{td}/osd.9.asok"
            sock = AdminSocket(ctx, path)
            sock.register("whoami", lambda cmd: {"id": 9}, "test cmd")
            await sock.start()
            loop = asyncio.get_running_loop()

            def cmd(c):
                return admin_command(path, c)
            out = await loop.run_in_executor(None, cmd, "whoami")
            assert out == {"id": 9}
            out = await loop.run_in_executor(None, cmd, "perf dump")
            assert isinstance(out, dict)
            out = await loop.run_in_executor(None, cmd, "config show")
            assert out["osd_heartbeat_interval"] == 0.3
            out = await loop.run_in_executor(
                None, cmd, "config set log_level 3")
            assert "success" in out
            assert ctx.config["log_level"] == 3
            out = await loop.run_in_executor(None, cmd, "help")
            assert "perf dump" in out
            out = await loop.run_in_executor(None, cmd, "no-such")
            assert "error" in out
            await sock.stop()
    asyncio.run(run())


# -------------------------------------------------------------- health

async def wait_health(admin, want_status, timeout=30.0, forbid=None):
    deadline = asyncio.get_event_loop().time() + timeout
    last = None
    while asyncio.get_event_loop().time() < deadline:
        ack = await admin.mon_command({"prefix": "health"})
        last = json.loads(ack.outs)
        if last["status"] == want_status and (
                forbid is None or
                not any(forbid in c for c in last["checks"])):
            return last
        await asyncio.sleep(0.2)
    raise AssertionError(f"health never became {want_status}: {last}")


def test_ceph_status_tracks_kill_and_recover_cycle():
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("data", pg_num=8)
        io = admin.open_ioctx("data")
        for i in range(4):
            await io.write_full(f"o{i}", b"h" * 2048)
        # stats flow in; everything active+clean -> HEALTH_OK
        h = await wait_health(admin, "HEALTH_OK")
        ack = await admin.mon_command({"prefix": "status"})
        st = json.loads(ack.outs)
        assert st["pgmap"]["num_pgs"] == 8
        assert st["pgmap"]["num_objects"] == 4
        assert set(st["pgmap"]["by_state"]) == {"active+clean"}
        # kill an osd: health degrades to WARN (osd down)
        await cl.kill_osd(3)
        h = await wait_health(admin, "HEALTH_WARN")
        assert any("osds down" in c for c in h["checks"])
        # after down-out + re-peer + recovery the cluster heals itself
        h = await wait_health(admin, "HEALTH_OK", timeout=60.0)
        for i in range(4):
            assert await io.read(f"o{i}") == b"h" * 2048
        await cl.stop()
    asyncio.run(run())


def test_pg_stat_and_dump_commands():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("x", b"y" * 100)
        await wait_health(admin, "HEALTH_OK")
        ack = await admin.mon_command({"prefix": "pg stat"})
        st = json.loads(ack.outs)
        assert st["num_pgs"] == 4 and st["num_objects"] == 1
        assert st["num_bytes"] == 100
        ack = await admin.mon_command({"prefix": "pg dump"})
        dump = json.loads(ack.outs)
        assert len(dump["pg_stats"]) == 4
        assert all(r["state"] == "active+clean"
                   for r in dump["pg_stats"].values())
        await cl.stop()
    asyncio.run(run())


def test_cluster_log_reaches_mon():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        # boot messages arrive via MLog -> LogMonitor
        deadline = asyncio.get_event_loop().time() + 10
        while asyncio.get_event_loop().time() < deadline:
            ack = await admin.mon_command({"prefix": "log last",
                                           "num": 50})
            entries = json.loads(ack.outs)
            boots = [e for e in entries
                     if "boot" in e.get("message", "")]
            if len(boots) >= 3:
                break
            await asyncio.sleep(0.2)
        assert len(boots) >= 3, entries
        await cl.stop()
    asyncio.run(run())


def test_osd_op_tracking_via_client_io():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("tracked", b"z" * 512)
        await io.read("tracked")
        hist = [o for osd in cl.osds.values()
                for o in osd.op_tracker.dump_historic()["ops"]]
        assert any("tracked" in o["description"] for o in hist)
        done = [o for o in hist if "tracked" in o["description"]][0]
        events = [e["event"] for e in done["events"]]
        assert events[0] == "initiated" and "reached_pg" in events
        assert all(osd.op_tracker.dump_in_flight()["num_ops"] == 0
                   for osd in cl.osds.values())
        await cl.stop()
    asyncio.run(run())


def test_ceph_df_reports_pool_usage():
    """`ceph df` (PGMonitor dump_pool_stats role): per-pool logical
    bytes/objects from pg stats + raw usage implied by redundancy
    (size x for replicated, (k+m)/k x for EC)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("rep", pg_num=4, size=3)
        await admin.pool_create("ec", pg_num=4, pool_type="erasure",
                                k=2, m=2)
        rio = admin.open_ioctx("rep")
        eio = admin.open_ioctx("ec")
        await rio.write_full("a", b"x" * 1000)
        await eio.write_full("b", b"y" * 4000)
        await wait_health(admin, "HEALTH_OK")
        ack = await admin.mon_command({"prefix": "df"})
        df = json.loads(ack.outs)
        rows = {p["name"]: p for p in df["pools"]}
        assert rows["rep"]["objects"] == 1
        assert rows["rep"]["bytes_used"] == 1000
        assert rows["rep"]["raw_bytes_used"] == 3000      # size 3
        assert rows["ec"]["bytes_used"] == 4000
        assert rows["ec"]["raw_bytes_used"] == 8000       # (2+2)/2
        assert df["stats"]["total_objects"] == 2
        assert df["stats"]["total_bytes_used"] == 5000
        await cl.stop()
    asyncio.run(run())


def test_osd_bench_admin_command():
    """`ceph tell osd.N bench` role (osd/OSD.cc:5583): timed writes
    straight at the ObjectStore via the admin socket; the bench
    collection is cleaned up."""
    async def run():
        cl = Cluster()
        admin = await cl.start(1)
        osd = list(cl.osds.values())[0]
        out = await osd._store_bench(count=8, size=64 * 1024)
        assert out["bytes_written"] == 8 * 64 * 1024
        assert out["bytes_per_sec"] > 0
        from ceph_tpu.store.types import CollectionId
        assert not osd.store.collection_exists(
            CollectionId(f"bench.{osd.whoami}"))
        # count/size clamp
        out2 = await osd._store_bench(count=0, size=0)
        assert out2["bytes_written"] == 1
        await cl.stop()
    asyncio.run(run())


def test_osd_df_reports_capacity():
    """`ceph osd df` (PGMap osd_df role): per-osd store usage + pg
    counts from the reported statfs."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("x", b"y" * 5000)
        await wait_health(admin, "HEALTH_OK")
        ack = await admin.mon_command({"prefix": "osd df"})
        out = json.loads(ack.outs)
        assert len(out["nodes"]) == 3
        assert all(n["up"] and n["in"] for n in out["nodes"])
        assert sum(n["num_pgs"] for n in out["nodes"]) >= 4
        # memstore: total unknown (0) but used counts stored bytes
        assert out["summary"]["used"] >= 5000 * 3   # replicated x3
        await cl.stop()
    asyncio.run(run())
