"""Shared-memory ring + process-lane seam (ISSUE 13): osd/laneipc.py.

Coverage map:
  * frame round-trip — FIFO order, wrap-around at the capacity
    boundary, byte-exact payloads across sizes;
  * backpressure — a full ring refuses frames (no overwrite, no drop)
    and drains make room again; an over-capacity frame is a hard
    error;
  * wakeup handshake — the waiting flag halves compose so a producer
    burst against a parked consumer yields a wake signal and a burst
    against a busy one yields none;
  * envelope codecs — a message crossing a ring keeps its transport
    stamps and wire-identical payload;
  * worker crash — a dead lane turns posts into LOUD LaneDead
    failures and in-flight ops error instead of phantom-acking.
"""

import asyncio
import os

import pytest

from ceph_tpu.osd.laneipc import (FRAME_MSG, LaneDead, ShmRing,
                                  pack_frame, unpack_frame)


# ---------------------------------------------------------- ring basics

def test_ring_fifo_roundtrip_and_wraparound():
    ring = ShmRing(capacity=256, create=True)
    peer = ShmRing(name=ring.name)
    try:
        # many pushes of varying size force several wraps of a 256B
        # ring; every frame must come out byte-exact, in order
        sent = []
        i = 0
        for round_ in range(40):
            payload = bytes([i & 0xFF]) * (1 + (i * 7) % 90)
            assert ring.try_push(payload)
            sent.append(payload)
            i += 1
            if i % 3 == 0:
                for exp in sent:
                    assert peer.try_pop() == exp
                sent = []
        for exp in sent:
            assert peer.try_pop() == exp
        assert peer.try_pop() is None
    finally:
        peer.close()
        ring.close()
        ring.unlink()


def test_ring_backpressure_refuses_and_recovers():
    ring = ShmRing(capacity=64, create=True)
    peer = ShmRing(name=ring.name)
    try:
        assert ring.try_push(b"x" * 40)
        # 40+4 used of 64: a 30B frame (34 with header) cannot fit
        assert not ring.try_push(b"y" * 30)
        assert ring.full_stalls == 1
        assert peer.try_pop() == b"x" * 40
        assert ring.try_push(b"y" * 30)         # room again
        assert peer.try_pop() == b"y" * 30
        # an over-capacity frame could NEVER fit: hard error, not spin
        with pytest.raises(ValueError):
            ring.try_push(b"z" * 100)
    finally:
        peer.close()
        ring.close()
        ring.unlink()


def test_ring_wakeup_handshake_flag_halves():
    ring = ShmRing(capacity=256, create=True)
    peer = ShmRing(name=ring.name)
    try:
        # consumer not parked: producer burst sees waiting=0
        assert not ring.peer_waiting()
        ring.try_push(b"a")
        assert not ring.peer_waiting()
        # consumer parks: advertise, then re-check (the drain)
        peer.advertise_waiting(True)
        assert peer.try_pop() == b"a"
        assert ring.peer_waiting()          # producer now sends a byte
        peer.advertise_waiting(False)
        assert not ring.peer_waiting()
    finally:
        peer.close()
        ring.close()
        ring.unlink()


def test_frame_kind_tagging():
    f = pack_frame(FRAME_MSG, b"body")
    kind, body = unpack_frame(f)
    assert kind == FRAME_MSG and body == b"body"


# ----------------------------------------------------- envelope codecs

def test_msg_envelope_roundtrip_keeps_stamps_and_payload():
    from ceph_tpu.msg.types import EntityAddr, EntityName
    from ceph_tpu.osd.lanes import (decode_msg_envelope,
                                    encode_msg_envelope)
    from ceph_tpu.osd.messages import MOSDOp, OSDOp, OP_WRITEFULL
    from ceph_tpu.osd.types import PGId
    m = MOSDOp(pgid=PGId(3, 2), oid="obj-a", tid=7,
               ops=[OSDOp(OP_WRITEFULL, data=b"payload-bytes")])
    m.src_name = EntityName("client", "4711")
    m.src_addr = EntityAddr("127.0.0.1", 6801, nonce=99)
    m.recv_stamp = 123.5
    m.transport_id = 17
    m.throttle_cost = 256
    got = decode_msg_envelope(encode_msg_envelope(m))
    assert type(got) is MOSDOp
    assert got.tid == 7 and got.oid == "obj-a"
    assert got.pgid.without_shard() == PGId(3, 2)
    assert str(got.src_name) == str(m.src_name)
    assert got.src_addr.port == 6801 and got.src_addr.nonce == 99
    assert got.recv_stamp == 123.5 and got.transport_id == 17
    assert got.throttle_cost == 256
    assert got.ops[0].data == b"payload-bytes"


def test_out_frame_roundtrip():
    from ceph_tpu.msg.types import EntityAddr
    from ceph_tpu.osd.lanes import decode_out_frame, encode_out_frame
    from ceph_tpu.osd.messages import MOSDOpReply
    reply = MOSDOpReply(9, 0, map_epoch=5)
    addr = EntityAddr("127.0.0.1", 6805, nonce=3)
    m, got_addr, peer_type, t_send = decode_out_frame(
        encode_out_frame(reply, addr, "client"))
    assert type(m) is MOSDOpReply and m.tid == 9
    assert got_addr.port == 6805 and peer_type == "client"
    # the reply-leg anchor: stamped at encode, in the lane's
    # monotonic clock (the parent converts via the PING/PONG offset)
    assert t_send > 0.0


# ------------------------------------------------------- crash = LOUD

def test_dead_lane_posts_raise_loudly_no_phantom_acks():
    """A ProcessLane whose worker died must raise LaneDead on post and
    fail its pending id-keyed calls — never quietly accept work."""
    from ceph_tpu.osd.lanes import ProcessLane

    class _Plane:
        num_shards = 2

        class osd:      # the slice ProcessLane.__init__ touches
            class cfg:
                @staticmethod
                def __getitem__(k):
                    raise KeyError

    async def run():
        plane = _Plane()
        plane.osd = type("O", (), {})()
        plane.osd.cfg = {"osd_lane_ring_bytes": 1 << 16}
        plane.osd.whoami = 0
        lane = ProcessLane.__new__(ProcessLane)
        lane.plane = plane
        lane.idx = 0
        lane.osd = plane.osd
        lane.to_lane = ShmRing(capacity=1 << 16, create=True)
        lane.from_lane = ShmRing(capacity=1 << 16, create=True)
        lane.proc = None
        lane.dead = False
        lane._stopping = False
        lane._loop = asyncio.get_running_loop()
        lane._pending = {}
        lane._next_id = 1
        lane._overflow = []
        lane._retry_handle = None
        # a pending id-keyed call, then the worker "dies"
        fut = asyncio.get_running_loop().create_future()
        lane._pending[1] = fut
        lane._on_exit()
        assert lane.dead
        with pytest.raises(LaneDead):
            lane._push(b"\x01frame")
        with pytest.raises(LaneDead):
            await fut                      # pending call failed LOUDLY
        lane.to_lane.close()
        lane.to_lane.unlink()
        lane.from_lane.close()
        lane.from_lane.unlink()

    asyncio.run(run())


def test_cross_process_ring_smoke():
    """One real child process echoes frames back: proves the shm
    segment + cursors work across a process boundary (not just across
    two attachments in one process)."""
    import multiprocessing

    ring_in = ShmRing(capacity=1 << 14, create=True)
    ring_out = ShmRing(capacity=1 << 14, create=True)
    ctx = multiprocessing.get_context("spawn")
    p = ctx.Process(target=_ring_echo_child,
                    args=(ring_in.name, ring_out.name))
    p.start()
    try:
        import time
        for i in range(5):
            assert ring_in.try_push(b"frame-%d" % i)
        got = []
        deadline = time.monotonic() + 20
        while len(got) < 5 and time.monotonic() < deadline:
            f = ring_out.try_pop()
            if f is None:
                time.sleep(0.002)
                continue
            got.append(f)
        assert got == [(b"frame-%d" % i)[::-1] for i in range(5)]
    finally:
        p.join(timeout=10)
        assert not p.is_alive()
        ring_in.close()
        ring_in.unlink()
        ring_out.close()
        ring_out.unlink()


def _ring_echo_child(a: str, b: str) -> None:
    import time
    rin = ShmRing(name=a)
    rout = ShmRing(name=b)
    deadline = time.monotonic() + 20
    echoed = 0
    while echoed < 5 and time.monotonic() < deadline:
        got = rin.try_pop()
        if got is None:
            time.sleep(0.002)
            continue
        rout.try_push(got[::-1])
        echoed += 1
    rin.close()
    rout.close()
