"""Direct engine="jax" bit-exactness tests (VERDICT r2 weak #1a).

These call batch_do_rule(engine="jax") explicitly — no auto-routing — so
the jitted descent itself is validated, on whatever backend the test
host has (CPU under the conftest virtual mesh; the identical code path
runs on TPU in bench.py).  Weight grids include degraded and fractional
vectors where the retry paths fire, and a FAST_TRIES=1 variant forces
lanes through the straggler FULL (while_loop) path.
"""

import numpy as np
import pytest

from ceph_tpu.crush.builder import (build_hierarchy, make_erasure_rule,
                                    make_replicated_rule)
from ceph_tpu.crush.mapper import do_rule
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.ops import crush_kernel
from ceph_tpu.ops.crush_kernel import (JaxEngine, batch_do_rule,
                                       compile_rule, engine_is_warm,
                                       warmup)

N_X = 300   # deliberately NOT a chunk size: exercises padding


def build(n_osds, per_host, ec_size=6):
    m = CrushMap()
    m.max_devices = n_osds
    build_hierarchy(m, n_osds, per_host)
    rep = make_replicated_rule(m, "rep")
    ec = make_erasure_rule(m, "ec", size=ec_size)
    return m, rep, ec


def assert_jax_match(m, rule, numrep, weights, xs=None):
    xs = xs if xs is not None else list(range(N_X))
    got = batch_do_rule(m, rule, xs, numrep, weights, engine="jax")
    want = [do_rule(m, rule, x, numrep, weights) for x in xs]
    mism = [(x, w, g) for x, w, g in zip(xs, want, got) if w != g]
    assert not mism, f"{len(mism)} mismatches, first: {mism[:3]}"


WEIGHT_CASES = [
    ("uniform", lambda n: [0x10000] * n),
    ("degraded", lambda n: [0 if i % 4 == 0 else 0x10000
                            for i in range(n)]),
    ("fractional", lambda n: [(0x3000 + 0x1800 * (i % 7)) & 0xFFFF or
                              0x10000 for i in range(n)]),
    ("mixed", lambda n: [0 if i % 5 == 0 else
                         (0x8000 if i % 3 == 0 else 0x10000)
                         for i in range(n)]),
]


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
def test_jax_firstn_bit_exact(wname, wfn):
    m, rep, _ = build(12, 2)
    for numrep in (1, 3):
        assert_jax_match(m, rep, numrep, wfn(12))


@pytest.mark.parametrize("wname,wfn", WEIGHT_CASES)
def test_jax_indep_bit_exact(wname, wfn):
    m, _, ec = build(12, 2, ec_size=6)
    assert_jax_match(m, ec, 6, wfn(12))


def test_jax_straggler_full_path(monkeypatch):
    # FAST_TRIES=1 leaves every lane that needs a second try unresolved,
    # forcing the compacted straggler batch through the FULL while_loop
    # descent — results must still be bit-exact.
    monkeypatch.setattr(JaxEngine, "FAST_TRIES", 1)
    crush_kernel._engine_cache.clear()
    m, rep, ec = build(12, 2, ec_size=6)
    w = [0 if i % 3 == 0 else 0x10000 for i in range(12)]  # heavy outs
    assert_jax_match(m, rep, 3, w)
    assert_jax_match(m, ec, 6, w)
    crush_kernel._engine_cache.clear()


def test_jax_fuzz_weights_and_xs():
    rng = np.random.default_rng(11)
    m, rep, ec = build(16, 2, ec_size=6)
    for _ in range(3):
        w = rng.choice([0, 0x3000, 0x8000, 0xC000, 0x10000],
                       size=16).tolist()
        xs = rng.integers(0, 2**31, 200).tolist()
        assert_jax_match(m, rep, 3, w, xs)
        assert_jax_match(m, ec, 6, w, xs)


def test_auto_routes_host_until_warm():
    # engine="auto" must NEVER pay a cold jit compile: it stays on the
    # host engine until warmup() has been called for the topology.
    crush_kernel._engine_cache.clear()
    m, rep, _ = build(8, 2)
    w = [0x10000] * 8
    cr = compile_rule(m, rep)
    assert cr is not None
    assert not engine_is_warm(cr, w, 3)
    # auto on a big batch: host path (cache stays cold)
    batch_do_rule(m, rep, list(range(5000)), 3, w, engine="auto")
    assert not engine_is_warm(cr, w, 3)
    assert warmup(m, rep, 3, w)
    assert engine_is_warm(cr, w, 3)
    got = batch_do_rule(m, rep, list(range(512)), 3, w, engine="jax")
    want = [do_rule(m, rep, x, 3, w) for x in range(512)]
    assert got == want


def test_jax_reweight_reuses_compiled_fn():
    # weights are traced args: a reweight must not grow the jit cache
    m, rep, _ = build(12, 2)
    eng = crush_kernel._jax_engine(compile_rule(m, rep), [0x10000] * 12)
    assert_jax_match(m, rep, 3, [0x10000] * 12)
    n_compiled = len(eng._fns)
    assert_jax_match(m, rep, 3, [0x8000] * 12)     # reweighted
    assert len(eng._fns) == n_compiled


def test_jax_more_reps_than_hosts():
    # impossible placements: firstn short sets, indep holes — the FULL
    # path runs to try exhaustion without hanging
    m, rep, ec = build(6, 2, ec_size=6)   # only 3 hosts
    assert_jax_match(m, rep, 5, [0x10000] * 6)
    assert_jax_match(m, ec, 6, [0x10000] * 6)


def build3(n_racks=3, hosts_per_rack=3, per_host=2, ec_size=4):
    """Three-level map: root -> rack -> host -> osd."""
    n = n_racks * hosts_per_rack * per_host
    m = CrushMap()
    m.max_devices = n
    build_hierarchy(m, n, per_host, hosts_per_rack=hosts_per_rack)
    rep = make_replicated_rule(m, "rep")               # chooseleaf host
    ec = make_erasure_rule(m, "ec", size=ec_size)
    rep_rack = make_replicated_rule(m, "rep_rack",
                                    failure_domain="rack")
    return m, rep, ec, rep_rack


def test_jax_three_level_bit_exact():
    m, rep, ec, rep_rack = build3()
    n = m.max_devices
    for wname, wfn in WEIGHT_CASES:
        w = wfn(n)
        assert_jax_match(m, rep, 3, w)
        assert_jax_match(m, ec, 4, w)
        assert_jax_match(m, rep_rack, 3, w)     # 2-level leaf descent


def test_jax_multi_take_bit_exact():
    from ceph_tpu.crush.builder import make_bucket
    from ceph_tpu.crush.constants import (BUCKET_STRAW2,
                                          RULE_CHOOSELEAF_FIRSTN,
                                          RULE_EMIT, RULE_TAKE)
    from ceph_tpu.crush.types import Rule, RuleStep
    m = CrushMap()
    m.max_devices = 12
    roots = []
    osd = 0
    for _ in range(2):
        hosts = []
        for _h in range(3):
            items = [osd, osd + 1]
            osd += 2
            hosts.append(make_bucket(m, BUCKET_STRAW2, 1, items,
                                     [0x10000] * 2))
        roots.append(make_bucket(m, BUCKET_STRAW2, 10,
                                 [h.id for h in hosts],
                                 [h.weight for h in hosts]))
    rid = m.add_rule(Rule(0, 1, 1, 10, [
        RuleStep(RULE_TAKE, roots[0].id),
        RuleStep(RULE_CHOOSELEAF_FIRSTN, 2, 1),
        RuleStep(RULE_EMIT),
        RuleStep(RULE_TAKE, roots[1].id),
        RuleStep(RULE_CHOOSELEAF_FIRSTN, 2, 1),
        RuleStep(RULE_EMIT)]))
    assert compile_rule(m, rid) is not None
    for wname, wfn in WEIGHT_CASES:
        assert_jax_match(m, rid, 4, wfn(12))


def test_fallback_is_counted_and_logged(caplog):
    import logging
    m, rep, _ = build(8, 2)
    m.tunables.chooseleaf_stable = 0          # unsupported shape
    assert compile_rule(m, rep) is None
    before = crush_kernel.fallback_count()
    with caplog.at_level(logging.WARNING, logger="ceph_tpu.crush"):
        got = batch_do_rule(m, rep, list(range(16)), 3, [0x10000] * 8)
    want = [do_rule(m, rep, x, 3, [0x10000] * 8) for x in range(16)]
    assert got == want
    assert crush_kernel.fallback_count() == before + 1
    assert any("not vectorizable" in r.message for r in caplog.records)
