"""dmClock QoS scheduler (common/qos.py): tag math, two-phase dequeue,
reservation floors, limits, the WPQ-seam contract, and the scheduler
live in a cluster — including recovery riding the background class and
class tags surviving the process-lane ring.

Mirrors the reference's mClockScheduler.cc unit surface
(src/test/osd/TestMClockScheduler.cc) plus the dmClock paper's
delta/rho envelope semantics.
"""

import asyncio
import sys
from types import SimpleNamespace

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster, make_ctx  # noqa: E402

from ceph_tpu.common.qos import (CLASS_ALIASES, DEFAULT_SPECS,  # noqa: E402
                                 PHASE_PROPORTIONAL, PHASE_RESERVATION,
                                 QOS_CLASS, DmClockQueue, QosFeedback,
                                 QosSpec, parse_specs)
from ceph_tpu.common.wpq import WeightedPriorityQueue  # noqa: E402


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ------------------------------------------------------------ spec parsing

def test_parse_specs():
    specs = parse_specs("client:r=40,w=60,l=0;bulk:r=2,w=1,l=50")
    assert specs["client"] == QosSpec(40.0, 60.0, 0.0)
    assert specs["bulk"] == QosSpec(2.0, 1.0, 50.0)
    # absent classes keep defaults; malformed groups are ignored
    assert specs["background"] == DEFAULT_SPECS["background"]
    assert parse_specs("garbage;;x=;a:r=oops")["client"] == \
        DEFAULT_SPECS["client"]
    assert parse_specs("")["default"] == DEFAULT_SPECS["default"]
    # partial override inherits the rest of the class's default
    s = parse_specs("client:w=10")["client"]
    assert s.weight == 10.0
    assert s.reservation == DEFAULT_SPECS["client"].reservation


# --------------------------------------------------------------- tag queue

def test_reservation_served_before_weight():
    """With every tag due, reservation-phase serves drain first: the
    guaranteed class cannot sit behind a heavier-weighted backlog."""
    async def run():
        clk = FakeClock(0.0)
        q = DmClockQueue({"bulk": QosSpec(0.0, 9.0, 0.0),
                          "interactive": QosSpec(1.0, 1.0, 0.0),
                          "default": QosSpec()}, clock=clk)
        for i in range(20):
            q.put_nowait(("b", i), "bulk")
        for i in range(6):
            q.put_nowait(("i", i), "interactive")
        clk.t = 100.0
        first6 = [await q.get() for _ in range(6)]
        assert first6 == [("i", i) for i in range(6)]
        rest = [await q.get() for _ in range(20)]
        assert rest == [("b", i) for i in range(20)]
        c = q.counters()
        assert c["interactive"]["reservation"] == 6
        assert c["bulk"]["proportional"] == 20
        assert q.empty() and q.qsize() == 0
    asyncio.run(run())


def test_proportional_share_follows_weights():
    """Two reservation-less classes split throughput by weight (P-tag
    spacing 1/w): ~3:1 over the first half of a mixed backlog."""
    async def run():
        clk = FakeClock(0.0)
        q = DmClockQueue({"a": QosSpec(0.0, 3.0, 0.0),
                          "b": QosSpec(0.0, 1.0, 0.0),
                          "default": QosSpec()}, clock=clk)
        for i in range(40):
            q.put_nowait(("a", i), "a")
            q.put_nowait(("b", i), "b")
        clk.t = 1000.0
        first = [await q.get() for _ in range(40)]
        n_a = sum(1 for x in first if x[0] == "a")
        assert 28 <= n_a <= 32, n_a
        # within a class, strict FIFO
        assert [x[1] for x in first if x[0] == "a"] == \
            list(range(n_a))
    asyncio.run(run())


def test_limit_gates_even_on_idle_server():
    """limit=2/s: only the heads whose L tags are due may serve, no
    matter how idle the queue is — the paper's hard ceiling."""
    async def run():
        clk = FakeClock(0.0)
        q = DmClockQueue({"capped": QosSpec(0.0, 1.0, 2.0),
                          "default": QosSpec()}, clock=clk)
        for i in range(5):
            q.put_nowait(i, "capped")     # L tags 0, .5, 1, 1.5, 2
        clk.t = 1.0
        got = [await q.get() for _ in range(3)]
        assert got == [0, 1, 2]
        # the 4th head is future-dated: _select reports its wake time
        assert q._select(1.0) == pytest.approx(1.5)
        clk.t = 2.0
        assert [await q.get() for _ in range(2)] == [3, 4]
    asyncio.run(run())


def test_background_aliases_fold_to_one_stream():
    clk = FakeClock(0.0)
    q = DmClockQueue(clock=clk)
    q.put_nowait("s", "scrub")
    q.put_nowait("r", "recovery")
    q.put_nowait("a", "agent")
    c = q.counters()
    assert set(c) == {"background"} and c["background"]["queued"] == 3
    assert CLASS_ALIASES["recovery"] == "background"


def test_unknown_class_rides_default_spec():
    clk = FakeClock(0.0)
    q = DmClockQueue(clock=clk)
    q.put_nowait("x", "tenant-42")
    rec = q._classes["tenant-42"]
    assert rec.spec == DEFAULT_SPECS["default"]
    assert q.get_nowait() == "x"


def test_forced_drain_and_phase_stamp():
    async def run():
        clk = FakeClock(0.0)
        q = DmClockQueue({"client": QosSpec(10.0, 5.0, 0.0),
                          "bulk": QosSpec(0.0, 1.0, 0.0),
                          "default": QosSpec()}, clock=clk)
        ops = [SimpleNamespace(qos_delta=1, qos_rho=1) for _ in range(3)]
        q.put_nowait(ops[0], "client")
        q.put_nowait(ops[1], "bulk")
        q.put_nowait(ops[2], "client")
        clk.t = 50.0
        a = await q.get()
        assert a is ops[0] and a._qos_phase == PHASE_RESERVATION
        # forced drain (teardown path): tag order, rate ignored,
        # QueueEmpty at the end like asyncio.Queue
        drained = []
        try:
            while True:
                drained.append(q.get_nowait())
        except asyncio.QueueEmpty:
            pass
        assert len(drained) == 2 and q.empty()
        c = q.counters()
        assert c["client"]["reservation"] == 1
        assert c["client"]["forced"] + c["bulk"]["forced"] == 2
    asyncio.run(run())


def test_delta_rho_advance_tag_spacing():
    """An op carrying delta=5 advances the P tag five quanta: ops
    completed at OTHER servers count against this class's share."""
    clk = FakeClock(0.0)
    q = DmClockQueue({"c": QosSpec(0.0, 1.0, 0.0),
                      "default": QosSpec()}, clock=clk)
    q.put_nowait(SimpleNamespace(qos_delta=1, qos_rho=1), "c")
    q.put_nowait(SimpleNamespace(qos_delta=5, qos_rho=1), "c")
    tags = [t for _i, _r, t, _l in q._classes["c"].items]
    assert tags == [0.0, 5.0]


def test_proportional_serve_discounts_reservation():
    """mClock Algorithm 1: a weight-phase serve shifts the class's
    outstanding R tags back one reservation quantum so throughput
    already delivered is not double-claimed by the floor."""
    async def run():
        clk = FakeClock(0.0)
        q = DmClockQueue({"c": QosSpec(2.0, 1.0, 0.0),
                          "default": QosSpec()}, clock=clk)
        q.put_nowait(1, "c")
        q.put_nowait(2, "c")        # R tags 0, 0.5
        clk.t = 10.0
        await q.get()
        rec = q._classes["c"]
        assert rec.served_res == 1 and rec.r_shift == 0.0
        # force a proportional serve by pushing R into the future
        q.put_nowait(3, "c")
        rec.items[0] = (rec.items[0][0], 1e9, rec.items[0][2],
                        rec.items[0][3])
        rec.items[1] = (rec.items[1][0], 1e9, rec.items[1][2],
                        rec.items[1][3])
        await q.get()
        assert rec.served_prop == 1
        assert rec.r_shift == pytest.approx(0.5)   # 1/reservation
    asyncio.run(run())


def test_queue_wakes_on_put_and_on_tag_horizon():
    """get() parked on an empty queue wakes on a put; parked on a
    future-dated limit tag it wakes when the tag comes due (real
    clock: the asyncio sleep path)."""
    async def run():
        q = DmClockQueue({"capped": QosSpec(0.0, 1.0, 50.0),
                          "default": QosSpec()})

        async def producer():
            await asyncio.sleep(0.03)
            for i in range(3):
                q.put_nowait(i, "capped")

        asyncio.get_running_loop().create_task(producer())
        got = [await asyncio.wait_for(q.get(), 2.0) for _ in range(3)]
        assert got == [0, 1, 2]
    asyncio.run(run())


def test_qos_feedback_counts_since_last_send():
    fb = QosFeedback()
    assert fb.note_sent("c", 0) == (1, 1)       # nothing done yet
    fb.note_done("c", PHASE_RESERVATION)
    fb.note_done("c", PHASE_PROPORTIONAL)
    fb.note_done("c", PHASE_RESERVATION)
    # 3 completed anywhere (2 by reservation) since last send to osd.0
    assert fb.note_sent("c", 0) == (4, 3)
    # a server never sent to starts fresh — no back-credit for history
    assert fb.note_sent("c", 1) == (1, 1)
    # immediately after, nothing new
    assert fb.note_sent("c", 0) == (1, 1)
    # classes are independent
    assert fb.note_sent("other", 0) == (1, 1)


# ----------------------------------------------------------- the WPQ seam

def test_queue_seam_flags_and_defaults():
    """qos=off (osd_op_queue=wpq, the config default) keeps the old
    scheduler bit-for-bit: the QOS flag is the queue_op gate that
    stops class-tag rewrites from ever reaching wpq."""
    assert WeightedPriorityQueue.QOS is False
    assert DmClockQueue.QOS is True
    from ceph_tpu.common.context import Context
    cfg = Context("client.test").config
    assert cfg["osd_op_queue"] == "wpq"
    specs = parse_specs(cfg["osd_qos_specs"])
    assert specs["client"] == QosSpec(40.0, 60.0, 0.0)
    assert specs["background"] == QosSpec(8.0, 4.0, 0.0)


# --------------------------------------------------------- cluster (live)

def _mclock_ctx(name):
    c = make_ctx(name)
    c.config.set("osd_op_queue", "mclock")
    return c


def test_mclock_cluster_classes_and_recovery_background():
    """mclock in vivo: tagged client classes ride the MOSDOp envelope
    into per-PG DmClock queues (contextvar multi-tenancy), and after a
    kill/rewrite/restart cycle recovery pushes are served through the
    queue's background class — not around it."""
    async def run():
        from ceph_tpu.common.qos import DmClockQueue as DQ
        cl = Cluster(ctx_factory=_mclock_ctx)
        admin = await cl.start(3)
        await admin.pool_create("q", pg_num=8)
        io = admin.open_ioctx("q")

        async def bulk_writes():
            QOS_CLASS.set("bulk")
            for i in range(24):
                await io.write(f"bulk-{i}", b"B" * 2048)

        async def interactive_writes():
            for i in range(8):
                await io.write(f"int-{i}", b"i" * 64)

        await asyncio.gather(bulk_writes(), interactive_writes())
        for i in range(8):
            assert await io.read(f"int-{i}") == b"i" * 64

        def merged_counters():
            out = {}
            for osd in cl.osds.values():
                for pg in osd.pgs.values():
                    assert isinstance(pg._op_queue, DQ)
                    for k, c in pg._op_queue.counters().items():
                        tot = out.setdefault(k, 0)
                        out[k] = tot + c["reservation"] + \
                            c["proportional"] + c["forced"]
            return out

        served = merged_counters()
        # both tagged classes reached the OSD queues under their names
        assert served.get("bulk", 0) >= 24
        assert served.get("client", 0) >= 8

        # recovery as background: kill, write degraded, restart
        store = await cl.kill_osd(2)
        await cl.mark_down_and_wait(admin, 2)
        for i in range(6):
            await io.write(f"deg-{i}", b"D" * 1024)
        await cl.start_osd(2, store=store)
        for _ in range(200):
            if merged_counters().get("background", 0) > 0:
                break
            await asyncio.sleep(0.05)
        assert merged_counters().get("background", 0) > 0, \
            "recovery pushes bypassed the QoS queue"
        for i in range(6):
            assert await io.read(f"deg-{i}") == b"D" * 1024
        await cl.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_mclock_process_lanes_tags_survive_ring():
    """Lane-mode acceptance: with osd_shard_lanes=process every PG
    lives in a worker process and ops cross the shm ring as encoded
    MOSDOp v4 frames — the class tag and the qos_phase reply echo must
    survive the trip (the client-side QosFeedback only ever counts
    phases echoed back on MOSDOpReply)."""
    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("osd_op_num_shards", 2)
        c.config.set("osd_shard_lanes", "process")
        c.config.set("ms_local_delivery", True)
        c.config.set("osd_op_queue", "mclock")
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(3)
        for osd in cl.osds.values():
            assert osd.shards.active_backend == "process"
        await admin.pool_create("lq", pg_num=4)
        io = admin.open_ioctx("lq")

        async def tenant(tag, n):
            QOS_CLASS.set(tag)
            for i in range(n):
                await io.write(f"{tag}-{i}", b"L" * 512)

        await asyncio.gather(tenant("bulk", 12), tenant("client", 6))
        for i in range(6):
            assert await io.read(f"client-{i}") == b"L" * 512
        fb = admin.objecter._qos
        # phase echoes crossed the ring: completions were tallied per
        # class, and the reserved class saw reservation-phase serves
        assert fb._total.get("bulk", 0) >= 12
        assert fb._total.get("client", 0) >= 6
        assert fb._res.get("client", 0) > 0
        await cl.stop()
    asyncio.run(run())


def test_schedule_explorer_green_with_mclock():
    """Deterministic-sim acceptance: the explorer's virtual clock
    drives the dmClock tags (loop.time() seam), so schedules stay
    replayable with the QoS queue in the dequeue path."""
    from ceph_tpu.devtools.schedule import explore, run_ec_mini
    rep = explore(6, with_crashes=False,
                  cfg={"osd_op_queue": "mclock"})
    assert len(rep.schedules) >= 6
    assert not rep.failures, rep.render_failures()
    # and replayable: same seed, same trace, dmClock tags included
    r1 = run_ec_mini(seed=3, cfg={"osd_op_queue": "mclock"})
    r2 = run_ec_mini(seed=3, cfg={"osd_op_queue": "mclock"})
    assert r1.ok and r2.ok, r1.render() + r2.render()
    assert r1.trace_hash == r2.trace_hash
