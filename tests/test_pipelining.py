"""Per-PG op pipelining invariants (ISSUE 5).

The dependency-tracked in-flight window (osd/sequencer.py) replaced the
serial one-op-per-PG worker; these tests pin the invariants that make
that safe:
  * same-object ops serialize in admission (client) order even at
    window depth 16 — last write wins, reads see the chain;
  * pglog versions stay DENSE and ordered under concurrency (version
    assignment is atomic with the log append);
  * barrier-class work drains the window and runs alone;
  * a replica failure mid-window re-peers cleanly: every in-flight
    write either completes or is retried by the client, nothing is
    lost, the cluster serves consistent reads after;
  * the store commit thread's gather window auto-tunes from observed
    barrier cost, clamped to [0, 4x] of the static value.
"""

import asyncio
import time

import pytest

from ceph_tpu.osd.sequencer import OpSequencer
from ceph_tpu.qa.cluster import Cluster, make_ctx
from ceph_tpu.store.commit import KVSyncThread


# ------------------------------------------------------ sequencer (unit)

def test_sequencer_same_object_writes_chain_in_admission_order():
    async def run():
        seq = OpSequencer(16)
        order = []

        async def op(slot, name, delay):
            await slot.wait()
            # later admissions must not overtake even when faster
            await asyncio.sleep(delay)
            order.append(name)
            seq.release(slot)

        s1 = seq.admit("obj", True)
        s2 = seq.admit("obj", True)
        s3 = seq.admit("obj", True)
        await asyncio.gather(op(s1, "a", 0.03), op(s2, "b", 0.02),
                             op(s3, "c", 0.0))
        assert order == ["a", "b", "c"]
        assert seq.active == 0

    asyncio.run(run())


def test_sequencer_disjoint_objects_run_concurrently():
    async def run():
        seq = OpSequencer(16)
        running = set()
        peak = []

        async def op(slot, name):
            await slot.wait()
            running.add(name)
            await asyncio.sleep(0.02)
            peak.append(len(running))
            running.discard(name)
            seq.release(slot)

        slots = [(seq.admit(f"o{i}", True), f"o{i}") for i in range(8)]
        await asyncio.gather(*[op(s, n) for s, n in slots])
        assert max(peak) == 8     # all disjoint writes overlapped

    asyncio.run(run())


def test_sequencer_readers_share_writers_exclude():
    async def run():
        seq = OpSequencer(16)
        trace = []

        async def op(slot, name, delay=0.01):
            await slot.wait()
            trace.append(("start", name))
            await asyncio.sleep(delay)
            trace.append(("end", name))
            seq.release(slot)

        w1 = seq.admit("obj", True)
        r1 = seq.admit("obj", False)
        r2 = seq.admit("obj", False)
        w2 = seq.admit("obj", True)
        await asyncio.gather(op(w1, "w1"), op(r1, "r1"),
                             op(r2, "r2"), op(w2, "w2"))
        idx = {(ev, n): i for i, (ev, n) in enumerate(trace)}
        # readers start only after w1 ends, and overlap each other
        assert idx[("end", "w1")] < idx[("start", "r1")]
        assert idx[("end", "w1")] < idx[("start", "r2")]
        assert idx[("start", "r2")] < idx[("end", "r1")] \
            or idx[("start", "r1")] < idx[("end", "r2")]
        # w2 waits for BOTH readers
        assert idx[("end", "r1")] < idx[("start", "w2")]
        assert idx[("end", "r2")] < idx[("start", "w2")]

    asyncio.run(run())


def test_sequencer_failed_op_never_wedges_successors():
    async def run():
        seq = OpSequencer(16)

        async def fail(slot):
            await slot.wait()
            try:
                raise RuntimeError("boom")
            finally:
                seq.release(slot)     # the _run_windowed contract

        async def ok(slot):
            await slot.wait()
            seq.release(slot)
            return "ran"

        s1 = seq.admit("obj", True)
        s2 = seq.admit("obj", True)
        t1 = asyncio.ensure_future(fail(s1))
        t2 = asyncio.ensure_future(ok(s2))
        with pytest.raises(RuntimeError):
            await t1
        assert await asyncio.wait_for(t2, 2.0) == "ran"

    asyncio.run(run())


def test_sequencer_drain_barriers_the_window():
    async def run():
        seq = OpSequencer(16)
        done = []

        async def op(slot, name):
            await slot.wait()
            await asyncio.sleep(0.02)
            done.append(name)
            seq.release(slot)

        slots = [(seq.admit(f"o{i}", True), f"o{i}") for i in range(4)]
        tasks = [asyncio.ensure_future(op(s, n)) for s, n in slots]
        assert seq.active == 4
        await seq.drain()
        # every in-flight op finished before the barrier proceeded
        assert seq.active == 0 and len(done) == 4
        await asyncio.gather(*tasks)
        # window is reusable after a drain
        s = seq.admit("o0", True)
        await s.wait()
        seq.release(s)

    asyncio.run(run())


def test_sequencer_window_slot_backpressure():
    async def run():
        seq = OpSequencer(2)
        s1 = seq.admit("a", True)
        s2 = seq.admit("b", True)

        async def admit_third():
            await seq.wait_slot()
            return seq.admit("c", True)

        t = asyncio.ensure_future(admit_third())
        await asyncio.sleep(0.01)
        assert not t.done()           # window full: admitter parked
        seq.release(s1)
        s3 = await asyncio.wait_for(t, 2.0)
        seq.release(s2)
        seq.release(s3)

    asyncio.run(run())


# --------------------------------------------- e2e ordering + density

def test_same_object_write_ordering_and_dense_versions():
    """16 concurrent writes to ONE object land in client-issue order
    (last write wins) while 32 disjoint-object writes interleave; the
    primary's pglog versions stay dense and strictly ordered."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("ord", pg_num=1)
        io = admin.open_ioctx("ord")
        # warm the pg (activation) so the burst measures the window
        await io.write_full("hot", b"seed")

        async def hot(i):
            await io.write_full("hot", bytes([i]) * 2048)

        async def cold(i):
            await io.write_full(f"cold{i:03d}", bytes([i]) * 512)

        await asyncio.gather(*[hot(i) for i in range(16)],
                             *[cold(i) for i in range(32)])
        assert await io.read("hot") == bytes([15]) * 2048
        for i in range(32):
            assert await io.read(f"cold{i:03d}") == bytes([i]) * 512
        # dense/ordered pglog on every copy that hosts the pg
        checked = 0
        for osd in cl.osds.values():
            for pg in osd.pgs.values():
                if pg.pool_id != io.pool_id or not pg.log.entries:
                    continue
                vs = [e.version.version for e in pg.log.entries]
                assert vs == list(range(vs[0], vs[0] + len(vs))), vs
                checked += 1
        assert checked >= 1
        win = cl.window_counters()
        await cl.stop()
        return win

    win = asyncio.run(run())
    assert win["mean_inflight_depth"] > 1.0, win


def test_scrub_barrier_drains_window_under_load():
    """A scrub issued mid-burst drains the window (runs alone) and the
    cluster stays consistent: all writes land, scrub reports clean."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("sb", pg_num=1)
        io = admin.open_ioctx("sb")
        await io.write_full("warm", b"x")
        burst = asyncio.ensure_future(cl.write_burst(
            io, {f"s{i:03d}": bytes([i]) * 4096 for i in range(24)},
            iodepth=24))
        await asyncio.sleep(0.01)     # let the window fill
        pgid = next(pg.pgid.without_shard()
                    for osd in cl.osds.values()
                    for pg in osd.pgs.values()
                    if pg.pool_id == io.pool_id)
        await admin.mon_command({"prefix": "pg scrub",
                                 "pgid": str(pgid)})
        await burst
        # scrub completed (stamp advanced / result recorded) and found
        # nothing inconsistent despite the concurrent burst
        deadline = time.monotonic() + 20.0
        result = None
        while time.monotonic() < deadline:
            for osd in cl.osds.values():
                for pg in osd.pgs.values():
                    if pg.pool_id == io.pool_id and pg.is_primary() \
                            and pg.last_scrub_result is not None:
                        result = pg.last_scrub_result
            if result is not None:
                break
            await asyncio.sleep(0.1)
        assert result is not None, "scrub never ran"
        assert result.get("errors", 0) == 0, result
        win = cl.window_counters()
        assert win["window_drains"] >= 1, win
        for i in range(24):
            assert await io.read(f"s{i:03d}") == bytes([i]) * 4096
        await cl.stop()

    asyncio.run(run())


def test_replica_failure_mid_window_repeers_cleanly():
    """Kill an OSD while an EC pool has a full window of writes in
    flight: aborted ops surface as EAGAIN to the objecter (which
    resends), peering drains the window before adopting the new
    interval, and every write is durable and readable after."""
    async def run():
        cl = Cluster()
        admin = await cl.start(5)
        await admin.pool_create("fi", pg_num=4,
                                pool_type="erasure", k=2, m=2)
        io = admin.open_ioctx("fi")
        await io.write_full("warm", b"x")
        blobs = {f"f{i:03d}": bytes([i % 251]) * 8192 for i in range(32)}
        burst = asyncio.ensure_future(
            cl.write_burst(io, blobs, iodepth=16))
        await asyncio.sleep(0.05)     # mid-window
        victim = 4
        await cl.kill_osd(victim)
        await cl.mark_down_and_wait(admin, victim)
        await asyncio.wait_for(burst, 90.0)
        for k, v in blobs.items():
            assert await io.read(k) == v
        await cl.stop()

    asyncio.run(run())


# --------------------------------------------- commit window auto-tune

def test_gather_window_autotune_tracks_barrier_cost():
    ewma_sleep = 0.004
    th = KVSyncThread("t_auto",
                      data_sync=lambda: time.sleep(ewma_sleep),
                      kv_sync=lambda s: None,
                      gather_window=0.002)
    th.start()
    try:
        for i in range(6):
            th.submit(seq=i, wrote_data=True)
            th.flush()
        assert th._barrier_ewma is not None
        eff = th._effective_window()
        # tracks the ~4ms barrier but clamps at 4x the 2ms static
        assert 0.0 < eff <= 4 * 0.002 + 1e-9
        assert eff > 0.002, eff       # grew beyond the static guess
        c = th.counters()
        assert c["gather_window_ms"] == round(eff * 1e3, 4)
        assert c["gather_window_static_ms"] == 2.0
        assert c["commit_inflight"] >= 0.0
    finally:
        th.stop()


def test_gather_window_autotune_clamps_and_gates():
    # clamp: a pathological 1s barrier must not stretch the window
    # beyond 4x static
    th = KVSyncThread("t_clamp", data_sync=lambda: None,
                      kv_sync=lambda s: None, gather_window=0.001)
    th._barrier_ewma = 1.0
    assert th._effective_window() == pytest.approx(0.004)
    # no auto-tune signal (RAM store: no barrier hooks, ewma stays
    # None) -> the static window keeps ruling
    th2 = KVSyncThread("t_ram", gather_window=0.0003)
    assert th2._effective_window() == pytest.approx(0.0003)
    assert th2._barrier_ewma is None   # nothing to learn from
    # disabled: static wins even with a signal
    th3 = KVSyncThread("t_off", data_sync=lambda: None,
                       gather_window=0.008, auto_tune=False)
    th3._barrier_ewma = 0.001
    assert th3._effective_window() == pytest.approx(0.008)
