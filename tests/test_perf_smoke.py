"""Perf smoke: the group-committed write path must actually engage.

A miniature in-process cluster takes a 32-way concurrent write burst on
OSDs backed by file-backed BlockStores whose data barrier costs ~1ms
and whose commit thread gathers for 8ms (emulating a real device — a
tmpfs fsync is free, so without the simulated cost the commit thread
drains groups of one and the test proves nothing).  The store commit
counters over the burst must show group commit working: strictly fewer
fsyncs than transactions and more than one transaction per commit
batch.  This is the tier-1 regression guard for ISSUE 1's async commit
pipeline — a reversion to per-txn synchronous fsync fails here instead
of only showing up in bench runs.
"""

import asyncio
import time

from ceph_tpu.osd.pg import STATE_ACTIVE
from ceph_tpu.qa.cluster import Cluster
from ceph_tpu.store.blockstore import BlockStore

N_OBJS = 64
OBJ_SIZE = 8 * 1024
CONC = 32
N_PGS = 16


class SlowBarrierBlockStore(BlockStore):
    """BlockStore with ~1ms data barriers and an 8ms commit gather
    window — the shape of a real disk, where the barrier dominates and
    batching behind it is what group commit exists for."""

    def mount(self):
        super().mount()
        self._committer.gather_window = 0.008
        # pin the window: this store EMULATES a device with a fixed
        # gather; the auto-tuner (tracks real barrier cost) would
        # shrink it toward the 1ms fake barrier and the test would
        # measure the tuner, not the group-commit machinery
        self._committer.auto_tune = False

    def _data_barrier(self):
        time.sleep(0.001)
        super()._data_barrier()


def _counters(cl):
    txns = fsyncs = batches = 0
    for osd in cl.osds.values():
        c = osd.store.commit_counters()
        txns += int(c.get("txns", 0))
        fsyncs += int(c.get("fsyncs", 0))
        batches += int(c.get("commit_batches", 0))
    return txns, fsyncs, batches


async def _settle(cl, n_pg_instances):
    """Wait for every PG instance to reach active so peering meta txns
    (sequential, batches-of-one by nature) stay out of the burst
    window."""
    for _ in range(300):
        pgs = [pg for osd in cl.osds.values() for pg in osd.pgs.values()]
        active = {pg.pgid for pg in pgs if pg.state == STATE_ACTIVE}
        if len(pgs) >= n_pg_instances and \
                len(active) == len({pg.pgid for pg in pgs}):
            break
        await asyncio.sleep(0.05)
    await asyncio.sleep(0.3)


def test_cluster_write_burst_engages_group_commit(tmp_path):
    async def run():
        cl = Cluster(store_factory=lambda i: SlowBarrierBlockStore(
            str(tmp_path / f"osd{i}")))
        admin = await cl.start(3)
        await admin.pool_create("smoke", pg_num=N_PGS)
        await _settle(cl, N_PGS * 3)
        io = admin.open_ioctx("smoke")
        data = bytes(range(256)) * (OBJ_SIZE // 256)
        sem = asyncio.Semaphore(CONC)

        async def one(i):
            async with sem:
                await io.write_full(f"smoke{i:04d}", data)

        t0, f0, b0 = _counters(cl)
        await asyncio.gather(*[one(i) for i in range(N_OBJS)])
        t1, f1, b1 = _counters(cl)   # read BEFORE stop: umount drops thread
        # spot-check durability through the async path
        assert await io.read("smoke0000") == data
        await cl.stop()
        return t1 - t0, f1 - f0, b1 - b0

    txns, fsyncs, batches = asyncio.run(run())
    # every replica write is a transaction (one per OSD per object); the
    # burst must share commit batches instead of one fsync pair each
    assert txns >= N_OBJS, txns
    assert fsyncs < txns, (fsyncs, txns)
    assert batches < txns and txns / batches > 1.0, (batches, txns)


def test_pg_op_window_depth_engages():
    """Regression guard for ISSUE 5's per-PG op pipelining (the twin
    of the zero-encode guard): a concurrent write burst against a
    single-PG pool must reach a counter-proven mean in-flight depth
    > 1 — a reversion to the serial one-op-per-PG worker pins the
    sampled depth at exactly 1.0 and fails here instead of only
    showing up as flat bench numbers."""
    from ceph_tpu.qa.cluster import Cluster

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        # ONE pg: every write lands in the same window, so the client
        # iodepth (24) translates directly into window depth
        await admin.pool_create("winpool", pg_num=1)
        io = admin.open_ioctx("winpool")
        blobs = {f"w{i:03d}": bytes([i]) * 4096 for i in range(24)}
        await cl.write_burst(io, blobs, iodepth=24)
        win = cl.window_counters()
        for k, v in blobs.items():
            assert await io.read(k) == v
        await cl.stop()
        return win

    win = asyncio.run(run())
    assert win["ops_admitted"] >= 24, win
    assert win["mean_inflight_depth"] > 1.0, win
    assert win["max_inflight_depth"] > 1, win


def test_tracing_stage_coverage_and_zero_encode():
    """ISSUE 6 regression guard for the op tracer, twin of the
    zero-encode guard: on an EC mini-cluster with op_tracing on,
    (a) the chain stages must attribute >= 90% of the independently
    measured e2e op latency — a dropped cut or broken span propagation
    silently un-names the write path and fails here, and (b) tracing
    must add ZERO message-body encodes on the local path (the live
    span rides local_view; the trace header only encodes on TCP)."""
    import time as _time

    from ceph_tpu.msg import payload as payload_mod
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("ms_local_delivery", True)
        c.config.set("op_tracing", True)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(4)
        await admin.pool_create("trpool", pg_num=4,
                                pool_type="erasure", k=2, m=2)
        await _settle(cl, 4 * 4)
        io = admin.open_ioctx("trpool")
        payload_mod.reset_counters()
        blobs = {f"tr{i:03d}": bytes([i]) * 8192 for i in range(24)}
        lats = []
        sem = asyncio.Semaphore(8)

        async def one(name, data):
            async with sem:
                t0 = _time.perf_counter()
                await io.write_full(name, data)
                lats.append(_time.perf_counter() - t0)

        await asyncio.gather(*[one(n, d) for n, d in blobs.items()])
        bd = cl.stage_breakdown(measured_e2e_s=sum(lats))
        # the metrics plane on the same run (ISSUE 15): a full
        # cluster-wide scrape must be pure counter arithmetic —
        # zero message encodes at inline lanes
        scrape = cl.cluster_perf_dump()
        enc = payload_mod.counters()
        merged = cl.stage_histograms()
        for k, v in blobs.items():
            assert await io.read(k) == v
        await cl.stop()
        return bd, enc, merged, scrape

    bd, enc, merged, scrape = asyncio.run(run())
    # (b) tracing AND the metrics-plane scrape must not reintroduce
    # encodes on the pure-local path
    assert enc["msg_encode_calls"] == 0, enc
    assert enc["msg_encode_bytes"] == 0, enc
    assert "op_stages" in scrape["groups"] and scrape["sources"]
    # every write produced a finished span
    assert merged["op_total"].count >= 24, merged["op_total"].count
    # the EC write path stages all recorded samples
    for stage in ("client_submit", "prepare", "ec_encode", "store_apply",
                  "submit", "replica_rtt", "ack_delivery", "repl_apply"):
        assert stage in merged and merged[stage].count > 0, stage
    # (a) no silent unattributed gap: named stages cover >= 90% of the
    # measured e2e latency
    assert bd["measured_s"] > 0
    assert bd["attributed_s"] >= 0.9 * bd["measured_s"], bd
    assert bd["unattributed_frac"] < 0.10, bd


def test_cluster_rw_over_local_delivery(tmp_path):
    """E2E guard for the messenger's same-process fast path: a cluster
    with ms_local_delivery on serves writes+reads correctly (EC pool,
    so sub-op fan-out and acks all ride local), with the client's data
    ops actually taking the local path — and, since ISSUE 4's lazy
    payloads, performing ZERO message body encodes: every hop hands
    over the live object graph, so any encode call on this path is a
    regression (the counter is the guard that keeps the encode->decode
    round trip removed)."""
    from ceph_tpu.msg import payload as payload_mod
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("ms_local_delivery", True)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(4)
        await admin.pool_create("lp", pg_num=4,
                                pool_type="erasure", k=2, m=2)
        io = admin.open_ioctx("lp")
        payload_mod.reset_counters()
        blobs = {f"lo{i:03d}": bytes([i]) * (4096 + i) for i in range(24)}
        await asyncio.gather(*[io.write_full(k, v)
                               for k, v in blobs.items()])
        for k, v in blobs.items():
            assert await io.read(k) == v
        local = sum(o.messenger._local_msgs for o in cl.osds.values())
        local += admin.messenger._local_msgs
        enc = payload_mod.counters()
        assert local > 0, "fast path never engaged"
        # lazy-payload invariant: the pure-local I/O burst (client ops,
        # EC sub-op fan-out, acks, replies) encoded NOTHING
        assert enc["msg_encode_calls"] == 0, enc
        assert enc["msg_encode_bytes"] == 0, enc
        await cl.stop()

    asyncio.run(run())


def test_sharded_plane_perf_guards():
    """ISSUE 10 regression guards for the sharded data plane, with a
    shards=1 run in the same test pinning backward compatibility:

      * shards=4 on the local path keeps ``msg_encode_calls`` at 0
        (the classify seam hands over live object graphs, never
        bytes);
      * per-PG window depth still engages (> 1) through the shard
        rings;
      * the ``osd_shard_handoff`` counters prove cross-shard handoffs
        are BATCHED: pump wakeups < handed-off ops under burst, and
        replica write sub-ops apply inline off the ring;
      * shards=1 (the FAST_CFG default the whole suite runs under)
        leaves the plane disabled — no shard router, no handoff
        group, the commit thread intact — i.e. today's path."""
    from ceph_tpu.msg import payload as payload_mod
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_f(shards):
        def f(name):
            c = make_ctx(name)
            c.config.set("osd_op_num_shards", shards)
            c.config.set("osd_shard_threads", False)
            c.config.set("ms_local_delivery", True)
            return c
        return f

    async def run(shards):
        cl = Cluster(ctx_factory=ctx_f(shards))
        admin = await cl.start(4)
        await admin.pool_create("shsm", pg_num=2,
                                pool_type="erasure", k=2, m=2)
        io = admin.open_ioctx("shsm")
        payload_mod.reset_counters()
        blobs = {f"g{i:03d}": bytes([i]) * 8192 for i in range(32)}
        await cl.write_burst(io, blobs, iodepth=16)
        win = cl.window_counters()
        enc = payload_mod.counters()
        sc = {}
        for osd in cl.osds.values():
            for k, v in osd.shards.counters().items():
                if isinstance(v, (int, float)):
                    sc[k] = sc.get(k, 0) + v
        routers = [osd.messenger.shard_router
                   for osd in cl.osds.values()]
        for k, v in blobs.items():
            assert await io.read(k) == v
        await cl.stop()
        return win, enc, sc, routers

    win, enc, sc, routers = asyncio.run(run(4))
    assert enc["msg_encode_calls"] == 0, enc
    assert win["mean_inflight_depth"] > 1.0, win
    assert sc["handoff_ops"] > 0, sc
    assert sc["handoff_wakeups"] < sc["handoff_ops"], sc
    assert sc["subop_inline"] > 0, sc
    assert all(r is not None for r in routers)

    # shards=1 compat pin: plane fully off, zero-encode still holds
    win1, enc1, sc1, routers1 = asyncio.run(run(1))
    assert enc1["msg_encode_calls"] == 0, enc1
    assert win1["mean_inflight_depth"] > 1.0, win1
    assert sc1["handoff_ops"] == 0, sc1
    assert all(r is None for r in routers1)


def test_sanitizer_fully_off_path_when_disabled():
    """ISSUE 7 off-path guard: with lockdep=false the invariant
    sanitizer must leave ZERO footprint on the write path — the
    commit-thread and payload-path locks are plain stdlib locks (no
    wrapper allocation), the order graph stays empty, nothing is
    recorded — while the pipelining/zero-encode evidence counters look
    exactly as they do with the sanitizer on (the suite's other
    perf-smoke tests run under FAST_CFG's lockdep=true, so the two
    configurations are both continuously proven)."""
    from ceph_tpu.common import lockdep
    from ceph_tpu.msg import payload as payload_mod
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_off(name):
        c = make_ctx(name)
        c.config.set("lockdep", False)
        c.config.set("ms_local_delivery", True)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_off)
        admin = await cl.start(3)
        assert not lockdep.is_enabled()
        await admin.pool_create("offpool", pg_num=1)
        io = admin.open_ioctx("offpool")
        payload_mod.reset_counters()
        blobs = {f"o{i:03d}": bytes([i]) * 4096 for i in range(24)}
        await cl.write_burst(io, blobs, iodepth=24)
        win = cl.window_counters()
        enc = payload_mod.counters()
        # no lockdep allocations anywhere on this cluster's stores
        for osd in cl.osds.values():
            committer = getattr(osd.store, "_committer", None)
            if committer is not None:
                assert not isinstance(committer._lock,
                                      lockdep.DepThreadLock)
        assert lockdep.GRAPH.edges == {}
        assert lockdep.report() == []
        await cl.stop()
        return win, enc

    win, enc = asyncio.run(run())
    # the same evidence the lockdep=true twin tests assert: window
    # pipelining engages and the local path encodes nothing
    assert win["mean_inflight_depth"] > 1.0, win
    assert enc["msg_encode_calls"] == 0, enc


def test_save_meta_bytes_per_write_are_o1_in_log_length():
    """ISSUE 13 guard: the write path's meta persistence must stay
    O(1) in log length.  save_meta_log at a ~100-entry log and at a
    ~1200-entry log must encode about the same number of omap bytes
    (one cached entry frame + info + loghead) — the old full-blob
    save grew linearly and profiled as the biggest per-op CPU slice.
    The full snapshot (peering-time save_meta) is the contrast: it
    MUST still grow with the log."""
    from ceph_tpu.osd.messages import EVersion
    from ceph_tpu.osd.pglog import LogEntry
    from ceph_tpu.store.objectstore import Transaction

    async def run():
        cl = Cluster()
        admin = await cl.start(2)
        await admin.pool_create("o1", pg_num=1, size=2)
        io = admin.open_ioctx("o1")
        await io.write_full("seed", b"x")
        pg = next(pg for osd in cl.osds.values()
                  for pg in osd.pgs.values() if pg.is_primary())

        def one_append_bytes():
            v = EVersion(pg.info.last_update.epoch or 1,
                         pg.info.last_update.version + 1)
            e = LogEntry(oid="guard", version=v,
                         prior_version=pg.info.last_update)
            txn = Transaction()
            pg.append_log(txn, e)
            return sum(len(k) + len(val)
                       for op in txn.ops
                       if getattr(op, "kv", None)
                       for k, val in op.kv.items())

        def grow_to(n):
            while len(pg.log.entries) < n:
                one_append_bytes()

        grow_to(100)
        small = one_append_bytes()
        grow_to(1200)
        large = one_append_bytes()
        assert large <= small * 1.5, (small, large)

        # contrast: the full snapshot is O(len(log)) by design
        txn = Transaction()
        pg.save_meta(txn)
        full = sum(len(k) + len(val)
                   for op in txn.ops
                   if getattr(op, "kv", None)
                   for k, val in op.kv.items())
        assert full > 10 * large, (full, large)
        await cl.stop()

    asyncio.run(run())


def test_device_kernel_compile_count_plateaus():
    """ISSUE 14 guard (runtime half of the device-seam pass): a
    steady-state EC workload through the cross-PG device queue must
    PLATEAU at a fixed jit compile count — the lane-bucket padding
    (osd/ec_queue.py LANE_BUCKETS) means every round after the first
    replays already-compiled signatures, so kernel launches keep
    growing while compiles (distinct signatures per common/devstats)
    stay flat.  A per-op retrace — the regression JIT16 can't see
    statically (unhashable statics, shape-per-call drift) — fails
    here, in tier-1, not in a bench review.  msg_encode_calls stays
    pinned at 0 throughout: the device path must never touch the
    message codec."""
    import numpy as np

    from ceph_tpu.common import devstats
    from ceph_tpu.common.context import Context
    from ceph_tpu.ec import gf256
    from ceph_tpu.msg import payload
    from ceph_tpu.osd.ec_queue import ECBatchQueue

    enc0 = payload.counters()["msg_encode_calls"]
    devstats.reset()

    async def run():
        q = ECBatchQueue(Context("osd.0"), mode="force",
                         window_ms=2.0, min_device_bytes=256)
        mat = gf256.rs_vandermonde_matrix(4, 2)[4:]
        rng = np.random.default_rng(7)
        snaps = []
        for _round in range(3):
            # varied per-request lengths, same folded lane bucket:
            # the steady-state shape of a running cluster
            ins = [rng.integers(0, 256, (4, 900 + 128 * i),
                                dtype=np.uint8) for i in range(6)]
            outs = await asyncio.gather(
                *[q.apply(mat, c) for c in ins])
            for c, o in zip(ins, outs):
                assert np.array_equal(o, gf256.host_apply(mat, c)), \
                    "device bytes diverged from the host kernel"
            snaps.append(devstats.counters())
        await q.stop()
        return snaps

    snaps = asyncio.run(run())
    compiles = [s["compiles"].get("ec_apply", 0) for s in snaps]
    launches = [s["launches"].get("ec_apply", 0) for s in snaps]
    assert launches[0] >= 1 and launches[2] > launches[1] > \
        launches[0], launches               # work kept flowing
    assert compiles[0] >= 1, compiles       # ...through the device
    assert compiles[2] == compiles[1] == compiles[0], \
        (f"jit compile count kept growing across steady-state rounds "
         f"{compiles}: a per-op retrace slipped into the kernel path")
    assert payload.counters()["msg_encode_calls"] == enc0, \
        "device-queue workload bumped the message codec"


def test_degraded_read_decode_plateaus_and_zero_encode():
    """ISSUE 17 guard (recovery under fire): with one EC shard-holder
    dead and UNREPLACEABLE (pool width == cluster size, so recovery
    keeps retrying but can never remap the hole), every read of an
    object whose data shard died must reconstruct it through the
    device decode queue — and the decode signatures must PLATEAU: the
    first round of degraded reads pays the jit compiles, every later
    round replays them while launches keep growing.  A per-read
    retrace in the decode path (shape drift, unhashable matrix key)
    fails here in tier-1 instead of in a bench review.  The whole
    degraded window — client reads, shard gathers, recovery retries —
    rides the local path with ZERO message-body encodes."""
    from ceph_tpu.common import devstats
    from ceph_tpu.msg import payload as payload_mod
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("ms_local_delivery", True)
        c.config.set("osd_ec_batch_device", "force")
        c.config.set("osd_ec_batch_min_bytes", 1)
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(4)
        # width k+m == n_osds: killing any osd leaves a hole no
        # backfill target can fill — the degraded window stays open
        await admin.pool_create("degpool", pg_num=4,
                                pool_type="erasure", k=2, m=2)
        await _settle(cl, 4 * 4)
        io = admin.open_ioctx("degpool")
        blobs = {f"dg{i:03d}": bytes([i + 1]) * 8192 for i in range(16)}
        for k, v in blobs.items():
            await io.write_full(k, v)
        # kill an osd that holds a DATA shard (shard < k) somewhere:
        # reads of those objects must decode, not just re-route
        victim = next(o.whoami for o in cl.osds.values()
                      if any(pg.pgid.shard < 2 for pg in o.pgs.values()))
        await cl.kill_osd(victim)
        await cl.mark_down_and_wait(admin, victim)
        devstats.reset()
        payload_mod.reset_counters()
        snaps = []
        for _round in range(3):
            got = await asyncio.gather(*[io.read(k) for k in blobs])
            assert list(got) == list(blobs.values())
            snaps.append(devstats.counters())
        enc = payload_mod.counters()
        await cl.stop()
        return snaps, enc

    snaps, enc = asyncio.run(run())
    compiles = [s["compiles"].get("ec_apply", 0) for s in snaps]
    launches = [s["launches"].get("ec_apply", 0) for s in snaps]
    assert compiles[0] >= 1, (compiles, launches)   # decode engaged
    assert launches[2] > launches[1] >= 1, launches  # and kept flowing
    assert compiles[2] == compiles[1] == compiles[0], \
        (f"degraded-read decode compiles kept growing {compiles}: "
         f"a per-read retrace slipped into the decode path")
    # the degraded window (including recovery retrying in the
    # background) never touched the message codec on the local path
    assert enc["msg_encode_calls"] == 0, enc
    assert enc["msg_encode_bytes"] == 0, enc


def test_objecter_cork_is_one_placement_kernel_launch():
    """ISSUE 16 guard (batched CRUSH in the data path): ONE corked
    Objecter flush computes placement for the whole burst in exactly
    ONE batched placement-kernel launch (devstats "crush_place"), not
    one scalar descent per op; steady-state bursts replay the same
    launch signature (compile plateau), and map churn recompiles the
    rule exactly once (guarded per-map compile cache)."""
    from ceph_tpu.client.objecter import Objecter, _InFlight
    from ceph_tpu.common import devstats
    from ceph_tpu.common.context import Context
    from ceph_tpu.crush.builder import (build_hierarchy,
                                        make_replicated_rule)
    from ceph_tpu.crush.types import CrushMap
    from ceph_tpu.msg.types import EntityAddr
    from ceph_tpu.osd.messages import (MOSDOp, MOSDOpBatch, OP_WRITEFULL,
                                       OSDOp)
    from ceph_tpu.osd.osdmap import Incremental, OSDMap
    from ceph_tpu.osd.types import (OSD_IN_WEIGHT, ObjectLocator,
                                    POOL_TYPE_REPLICATED, PGPool)

    def build_map():
        m = OSDMap()
        m.fsid = "cork-fsid"
        crush = CrushMap()
        crush.max_devices = 8
        build_hierarchy(crush, 8, 2)
        rep = make_replicated_rule(crush, "replicated_rule")
        m.crush = crush
        m.set_max_osd(8)
        inc = Incremental(1)
        for o in range(8):
            inc.new_up[o] = EntityAddr("127.0.0.1", 6800 + o, o + 1)
            inc.new_weight[o] = OSD_IN_WEIGHT
        m.apply_incremental(inc)
        m.pools[1] = PGPool(POOL_TYPE_REPLICATED, size=3,
                            crush_ruleset=rep, pg_num=32)
        m.pool_names[1] = "rbd"
        return m

    class FakeMessenger:
        nonce = 1

        def __init__(self):
            self.sent = []

        def add_dispatcher(self, d):
            pass

        def send_message(self, msg, addr, peer_type=None):
            self.sent.append(msg)

    class FakeMonc:
        def __init__(self, m):
            self.osdmap = m

        def on_osdmap(self, cb):
            pass

        def sub_want(self, *a, **k):
            pass

    async def run():
        m = build_map()
        msgr = FakeMessenger()
        obj = Objecter(Context("client"), msgr, FakeMonc(m))
        assert obj._batching
        devstats.reset()
        loop = asyncio.get_running_loop()

        async def burst(tag, n=16):
            before = len(msgr.sent)
            for i in range(n):
                obj._tid += 1
                op = _InFlight(obj._tid, f"{tag}-{i:03d}",
                               ObjectLocator(1),
                               [OSDOp(OP_WRITEFULL, data=b"x")],
                               loop.create_future())
                obj._inflight[op.tid] = op
                obj._send(op)
            assert len(msgr.sent) == before, \
                "corked ops must not ship before the flush"
            await asyncio.sleep(0)      # run the call_soon flush
            frames = msgr.sent[before:]
            shipped = sum(len(f.msgs) if isinstance(f, MOSDOpBatch)
                          else 1 for f in frames)
            assert shipped == n, (shipped, n)
            assert all(isinstance(f, (MOSDOp, MOSDOpBatch))
                       for f in frames)
            # grouped per target OSD: far fewer frames than ops
            assert len(frames) <= 8 < n

        def stats(domain):
            c = devstats.counters()
            return (c["launches"].get(domain, 0),
                    c["compiles"].get(domain, 0))

        await burst("a")
        # ONE cork = ONE placement-kernel launch for all 16 ops, which
        # cost exactly one guarded rule compile
        assert stats("crush_place") == (1, 1), stats("crush_place")
        assert stats("crush_compile")[1] == 1, stats("crush_compile")

        # steady state: new names, same map — the acting cache and the
        # repeated (pool, rule, chunk) launch signature keep the
        # compile counts FLAT (any extra launch replays a seen sig)
        await burst("b")
        await burst("c")
        assert stats("crush_place")[1] == 1, stats("crush_place")
        assert stats("crush_compile")[1] == 1, stats("crush_compile")

        # map churn: a NEW crush object recompiles the rule exactly
        # once, and the next cork is again one launch (cache cleared)
        inc = Incremental(m.epoch + 1)
        inc.new_crush = CrushMap.from_bytes(m.crush.to_bytes())
        m.apply_incremental(inc)
        place_launches = stats("crush_place")[0]
        await burst("d")
        assert stats("crush_place") == (place_launches + 1, 1), \
            stats("crush_place")
        assert stats("crush_compile")[1] == 2, stats("crush_compile")
        await burst("e")
        assert stats("crush_compile")[1] == 2, stats("crush_compile")

    asyncio.run(run())
