"""Distributed op tracing (common/tracer.py): histogram bucket/quantile
math, span cut-chain tiling, trace-header propagation (byte-identity
across local vs forced-TCP delivery, old-version decode tolerance),
op_tracker monotonic clocks + slow-op complaints, and the new
admin-socket commands (perf histogram dump / dump_op_stages /
dump_historic_slow_ops) on a live mini-cluster.
"""

import asyncio
import sys
import tempfile
import time

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from ceph_tpu.common.config import Config
from ceph_tpu.common.context import Context
from ceph_tpu.common.op_tracker import OpTracker
from ceph_tpu.common.perf_counters import PerfCounters, PerfHistogram
from ceph_tpu.common import tracer as tracer_mod
from ceph_tpu.common.tracer import CHAIN_STAGES, Span, Tracer
from ceph_tpu.osd.messages import MOSDOp, MOSDOpReply, MOSDRepOp, OSDOp
from ceph_tpu.osd.messages import OP_WRITE
from ceph_tpu.osd.types import PGId


# ---------------------------------------------------------- histograms

def test_histogram_buckets_and_quantiles():
    h = PerfHistogram()
    # 100 samples at ~1ms, 10 at ~16ms, 1 at ~1s
    for _ in range(100):
        h.add(0.001)
    for _ in range(10):
        h.add(0.016)
    h.add(1.0)
    assert h.count == 111
    assert abs(h.sum - (0.1 + 0.16 + 1.0)) < 1e-9
    # p50 must land in 1000us's bucket [512us, 1024us)
    assert 512e-6 <= h.quantile(0.5) < 1024e-6
    # p99 in 16000us's bucket [8192us, 16384us)
    assert 8192e-6 <= h.quantile(0.99) < 16384e-6
    # the max sample dominates the extreme tail
    assert h.quantile(0.9999) >= 0.5
    d = h.dump()
    assert d["count"] == 111 and d["p50_ms"] < d["p99_ms"]


def test_histogram_bucket_edges():
    h = PerfHistogram()
    # sub-microsecond -> bucket 0; exact powers land in their own bucket
    assert h._bucket_of(0.0) == 0
    assert h._bucket_of(0.5e-6) == 0
    assert h._bucket_of(1e-6) == 0
    assert h._bucket_of(2e-6) == 1
    assert h._bucket_of(1024e-6) == 10
    # huge samples clamp into the last (open-ended) bucket
    assert h._bucket_of(1e9) == PerfHistogram.N_BUCKETS - 1


def test_histogram_merge_and_dump_roundtrip():
    a, b = PerfHistogram(), PerfHistogram()
    for _ in range(5):
        a.add(0.002)
    for _ in range(7):
        b.add(0.050)
    merged = PerfHistogram().merge(a).merge(b)
    assert merged.count == 12
    assert abs(merged.sum - (0.010 + 0.350)) < 1e-9
    # per-PG/per-daemon merging = bucket-wise addition
    assert merged.buckets[a._bucket_of(0.002)] == 5
    assert merged.buckets[a._bucket_of(0.050)] == 7
    # full dumps round-trip for cross-process merging
    rt = PerfHistogram.from_dump(merged.dump_full())
    assert rt.buckets == merged.buckets
    assert rt.count == merged.count
    assert rt.dump() == merged.dump()


def test_perfcounters_hist_auto_register_and_dump():
    pc = PerfCounters("t")
    pc.hinc("stage_x", 0.004)
    pc.hinc("stage_x", 0.004)
    d = pc.dump()
    assert d["stage_x"]["count"] == 2
    full = pc.dump_histograms()
    assert sum(full["stage_x"]["buckets"]) == 2


# ---------------------------------------------------------------- spans

def test_span_cut_chain_tiles_total():
    pc = PerfCounters("op_stages")
    sp = Span(1, 2, "op")
    time.sleep(0.002)
    sp.cut("client_submit", pc)
    time.sleep(0.004)
    sp.cut("replica_rtt", pc)
    total = sp.finish(pc)
    # the chain cuts tile t0 -> finish with no gap and no double count
    chain = sum(dt for s, dt in sp.stages if s != "op_total")
    assert abs(chain - total) < 2e-3
    assert [s for s, _ in sp.stages] == ["client_submit", "replica_rtt",
                                         "op_total"]
    # post-finish cuts are inert (late replies must not corrupt stats)
    assert sp.cut("ack_delivery", pc) == 0.0
    assert pc.dump()["op_total"]["count"] == 1


def test_tracer_disabled_by_default_and_off_path():
    assert Config()["op_tracing"] is False
    ctx = Context("client.test")
    assert ctx.tracer.enabled is False
    assert ctx.tracer.start() is None          # no span allocation
    # runtime enable via config observer (injectargs path)
    ctx.config.set("op_tracing", True)
    sp = ctx.tracer.start()
    assert sp is not None and sp.trace_id and sp.span_id
    ctx.config.set("op_tracing", False)
    assert ctx.tracer.start() is None


# --------------------------------------------------- wire propagation

def test_mosdop_trace_header_roundtrip_and_old_version_decode():
    ops = [OSDOp(OP_WRITE, 0, 4, data=b"data")]
    m = MOSDOp(PGId(1, 2), "obj", None, ops, tid=7, map_epoch=3,
               reqid="c.7")
    m.trace_id, m.span_id = 0xabc123, 0xdef456
    rt = MOSDOp.from_bytes(m.to_bytes())
    assert (rt.trace_id, rt.span_id) == (0xabc123, 0xdef456)
    # an untraced op encodes zeros and decodes as untraced
    m2 = MOSDOp(PGId(1, 2), "obj", None, ops, tid=8)
    rt2 = MOSDOp.from_bytes(m2.to_bytes())
    assert rt2.trace_id == 0 and rt2.span_id == 0
    # OLD (v2) bytes — the trace ids are the trailing 16 payload bytes;
    # strip them and rewrite the struct header the way a v2 encoder
    # would have: the new decoder must accept and read "untraced"
    blob = bytearray(m.to_bytes())
    body_len = int.from_bytes(blob[2:6], "little")
    blob[0] = 2                                  # struct_v = 2
    blob[2:6] = (body_len - 16).to_bytes(4, "little")
    old = bytes(blob[:-16])
    rt3 = MOSDOp.from_bytes(old)
    assert rt3.oid == "obj" and rt3.tid == 7
    assert rt3.trace_id == 0 and rt3.span_id == 0
    # replies mirror the header the same way
    r = MOSDOpReply(7, 0, ops, 3)
    r.trace_id, r.span_id = 5, 6
    rr = MOSDOpReply.from_bytes(r.to_bytes())
    assert (rr.trace_id, rr.span_id) == (5, 6)


def test_span_propagation_local_vs_tcp_byte_identity():
    """The same traced op delivered locally hands the receiver the LIVE
    span object; forced over TCP the ids survive decode, the receiver
    adopts a span with the same identity, and the wire frame is
    byte-identical to an eagerly built untraced-constructor message
    with the same fields."""
    import test_msg as tm

    async def run():
        # --- local: live span rides local_view
        a, b, _, cb = await tm._pair(ms_local_delivery=True,
                                     op_tracing=True)
        sp = Tracer(a.ctx).start("osd_op")
        m = MOSDOp(PGId(1, 0), "o1", None,
                   [OSDOp(OP_WRITE, 0, 2, data=b"hi")], tid=1)
        m.trace_id, m.span_id = sp.trace_id, sp.span_id
        m._span = sp
        a.send_message(m, b.addr)
        await cb.wait_for(lambda c: len(c.msgs) >= 1)
        got = cb.msgs[0]
        assert got._span is sp                 # the live span itself
        assert (got.trace_id, got.span_id) == (sp.trace_id, sp.span_id)
        await a.shutdown()
        await b.shutdown()

        # --- TCP (armed fault injection disables the local path)
        c_, d, _, cd = await tm._pair(ms_local_delivery=True,
                                      op_tracing=True,
                                      ms_inject_socket_failures=10**9)
        sp2 = Tracer(c_.ctx).start("osd_op")
        m2 = MOSDOp(PGId(1, 0), "o1", None,
                    [OSDOp(OP_WRITE, 0, 2, data=b"hi")], tid=1)
        m2.trace_id, m2.span_id = sp2.trace_id, sp2.span_id
        m2._span = sp2
        c_.send_message(m2, d.addr)
        await cd.wait_for(lambda col: len(col.msgs) >= 1)
        got2 = cd.msgs[0]
        assert (got2.trace_id, got2.span_id) == (sp2.trace_id,
                                                 sp2.span_id)
        assert got2._span is not None          # adopted remote handle
        assert got2._span is not sp2
        assert got2._span.trace_id == sp2.trace_id
        # wire bytes: identical to a fresh message with the same fields
        eager = MOSDOp(PGId(1, 0), "o1", None,
                       [OSDOp(OP_WRITE, 0, 2, data=b"hi")], tid=1)
        eager.trace_id, eager.span_id = sp2.trace_id, sp2.span_id
        assert m2.wire_bytes() == eager.to_bytes()
        await c_.shutdown()
        await d.shutdown()

    asyncio.run(run())


def test_subop_trace_header_propagates():
    m = MOSDRepOp(PGId(2, 1), 9)
    m.trace_id, m.span_id = 11, 22
    rt = MOSDRepOp.from_bytes(m.to_bytes())
    assert (rt.trace_id, rt.span_id) == (11, 22)


# ------------------------------------------------- op tracker satellites

def test_op_tracker_uses_monotonic_and_wall_only_in_dump():
    t = OpTracker()
    op = t.create("op-a")
    # measuring clock is monotonic: start must sit on the monotonic
    # timeline, never the wall clock epoch
    now_m = time.monotonic()
    assert abs(op.start - now_m) < 5.0
    assert op.age() >= 0.0
    d = op.dump()
    # dump output shows WALL time (human-readable), reconstructed from
    # the anchor — initiated_at must sit on the wall timeline
    assert abs(d["initiated_at"] - time.time()) < 5.0
    assert abs(d["events"][0]["time"] - d["initiated_at"]) < 0.5


def test_op_tracker_slow_op_complaints():
    class _Log:
        def __init__(self):
            self.lines = []

        def warning(self, msg):
            self.lines.append(msg)

    pc = PerfCounters("osd")
    pc.add_u64("slow_ops")
    log = _Log()
    t = OpTracker(complaint_time=0.01, perf=pc, logger=log)
    op = t.create("slow-op")
    fast = t.create("fast-op")
    assert t.check_slow() == 0                 # not old enough yet
    time.sleep(0.02)
    t.finish(fast)                             # finished before scan
    assert t.check_slow() == 1
    assert t.check_slow() == 0                 # complains ONCE per op
    assert pc.dump()["slow_ops"] == 1
    assert len(log.lines) == 1 and "slow request" in log.lines[0]
    assert t.slow_op_count == 1
    # lands in the slow history ring on completion
    t.finish(op)
    d = t.dump_historic_slow_ops()
    assert d["num_ops"] == 1
    assert d["total_slow_ops"] == 1
    assert d["ops"][0]["description"] == "slow-op"
    assert any(e["event"] == "slow_op_complaint"
               for e in d["ops"][0]["events"])


def test_tracked_op_marks_become_span_events():
    t = OpTracker()
    op = t.create("traced")
    sp = Span(1, 2, "op")
    op.span = sp
    op.mark("queued_for_pg")
    assert [e for _, e in sp.events] == ["queued_for_pg"]
    assert "trace" in op.dump()


# --------------------------------------------- admin socket (live OSD)

def test_admin_socket_tracer_commands():
    """End to end on a mini-cluster with tracing on: the OSD admin
    socket serves `perf histogram dump`, `dump_op_stages` and
    `dump_historic_slow_ops`, and the stage table carries real write
    path samples."""
    from ceph_tpu.common.admin_socket import admin_command
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    td = tempfile.mkdtemp()

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("ms_local_delivery", True)
        c.config.set("op_tracing", True)
        if name.startswith("osd"):
            c.config.set("admin_socket", f"{td}/$name.asok")
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(3)
        await admin.pool_create("tp", pg_num=4)
        io = admin.open_ioctx("tp")
        for i in range(6):
            await io.write_full(f"t{i}", bytes([i]) * 1024)
        loop = asyncio.get_running_loop()

        def cmd(osd_id, c):
            return admin_command(f"{td}/osd.{osd_id}.asok", c)

        # not every OSD is primary for the written objects — at least
        # one must expose chain-stage samples; every OSD serves the
        # commands with a well-formed shape
        chain_seen = False
        for osd_id in cl.osds:
            hist = await loop.run_in_executor(
                None, cmd, osd_id, "perf histogram dump")
            stages = await loop.run_in_executor(
                None, cmd, osd_id, "dump_op_stages")
            assert stages["op_tracing"] is True
            for d in stages["stages"].values():
                assert d["count"] > 0
            if any(s in stages["stages"] for s in CHAIN_STAGES):
                chain_seen = True
                assert "op_stages" in hist, hist.keys()
            slow = await loop.run_in_executor(
                None, cmd, osd_id, "dump_historic_slow_ops")
            assert slow["num_ops"] == 0        # nothing slow in a burst
            assert slow["complaint_time"] > 0
        assert chain_seen
        # cluster-wide merge sees client + every OSD's share; the chain
        # must include the client-side and osd-side stages
        merged = cl.stage_histograms()
        assert merged["op_total"].count >= 6
        assert "client_submit" in merged and "ack_delivery" in merged
        await cl.stop()

    asyncio.run(run())


def test_per_daemon_disable_drops_foreign_spans():
    """A daemon with op_tracing=false must stay fully off-path even
    when the CLIENT traced the op: the span riding local delivery is
    dropped at OSD intake, no OSD-side stage histograms appear, and
    the client books the server gap into ack_delivery."""
    from ceph_tpu.common.tracer import STAGE_GROUP
    from ceph_tpu.qa.cluster import Cluster, make_ctx

    def ctx_f(name):
        c = make_ctx(name)
        c.config.set("ms_local_delivery", True)
        if name.startswith("client"):
            c.config.set("op_tracing", True)   # OSDs/mon stay off
        return c

    async def run():
        cl = Cluster(ctx_factory=ctx_f)
        admin = await cl.start(3)
        await admin.pool_create("mx", pg_num=2)
        io = admin.open_ioctx("mx")
        for i in range(4):
            await io.write_full(f"m{i}", b"x" * 512)
        for osd in cl.osds.values():
            assert STAGE_GROUP not in osd.ctx.perf._groups, osd.whoami
        merged = cl.stage_histograms()
        assert merged["op_total"].count >= 4    # client side still traces
        assert "ack_delivery" in merged
        assert "prepare" not in merged          # no OSD-side cuts
        await cl.stop()

    asyncio.run(run())


def test_stage_table_and_breakdown_helpers():
    ctx = Context("osd.5")
    ctx.config.set("op_tracing", True)
    tr = ctx.tracer
    tr.hist.hinc("prepare", 0.002)
    tr.hist.hinc("replica_rtt", 0.010)
    tr.hist.hinc("repl_apply", 0.001)          # aux
    tr.hist.hinc("op_total", 0.014)
    table = tracer_mod.stage_table(ctx.perf)
    assert set(table["stages"]) == {"prepare", "replica_rtt",
                                    "repl_apply", "op_total"}
    assert table["stages"]["repl_apply"].get("aux") is True
    assert abs(table["chain_s"] - 0.012) < 1e-9
    merged = tracer_mod.merge_stage_histograms([ctx])
    bd = tracer_mod.breakdown(merged)
    # chain sum vs the aux op_total: 12ms attributed of 14ms measured
    assert abs(bd["attributed_s"] - 0.012) < 1e-9
    assert abs(bd["measured_s"] - 0.014) < 1e-9
    assert abs(bd["unattributed_frac"] - (1 - 12 / 14)) < 1e-3
    # aux stages never count into the attributed sum
    assert "repl_apply" in bd["stages"]
