/*
 * Golden-vector generator for CRUSH bit-exactness tests.
 *
 * This harness is ORIGINAL code that links against the *reference* Ceph
 * CRUSH C sources (mapper.c/builder.c/hash.c) at generation time only —
 * the reference tree is NOT part of this repository; only the JSON vectors
 * it emits are committed (tests/golden/*.json).  Regenerate with
 * tests/golden/generate.py, which compiles this file with
 *   gcc gen_golden.c <ref>/src/crush/{mapper,builder,hash,crush}.c
 *
 * Output: one JSON object on stdout with
 *   - hash vectors for crush_hash32_{1..5}
 *   - crush_ln samples (full 64K range checksummed + first/last 512 raw)
 *   - per-scenario crush_do_rule results over many inputs x
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "crush/crush.h"
#include "crush/builder.h"
#include "crush/hash.h"
#include "crush/mapper.h"

/* crush_ln is static in mapper.c; re-derive it through straw2 is awkward,
 * so we compile mapper.c with -Dcrush_ln_static= via generate.py instead.
 * Simpler: declare the straw2 path exercised by do_rule only, and dump
 * crush_ln indirectly via a tiny two-item straw2 duel is lossy.  We instead
 * include mapper.c directly so statics are visible. */
#define dprintk(args...) /* nothing */
#include MAPPER_C_PATH

static struct crush_bucket *mk(struct crush_map *m, int alg, int type,
                               int n, int *items, int *weights, int *idout) {
  struct crush_bucket *b =
      crush_make_bucket(m, alg, CRUSH_HASH_RJENKINS1, type, n, items, weights);
  crush_add_bucket(m, 0, b, idout);
  return b;
}

static void emit_rule_results(struct crush_map *map, int ruleno,
                              int result_max, const __u32 *weight,
                              int weight_max, int nx, int first) {
  int result[64], scratch[64 * 3];
  if (!first) printf(",");
  printf("[");
  for (int x = 0; x < nx; x++) {
    int len = crush_do_rule(map, ruleno, x, result, result_max, weight,
                            weight_max, scratch);
    if (x) printf(",");
    printf("[");
    for (int i = 0; i < len; i++)
      printf(i ? ",%d" : "%d", result[i]);
    printf("]");
  }
  printf("]");
}

static void set_tunables(struct crush_map *map, int profile) {
  if (profile == 0) { /* legacy */
    map->choose_local_tries = 2;
    map->choose_local_fallback_tries = 5;
    map->choose_total_tries = 19;
    map->chooseleaf_descend_once = 0;
    map->chooseleaf_vary_r = 0;
    map->chooseleaf_stable = 0;
    map->straw_calc_version = 0;
  } else { /* jewel/optimal */
    map->choose_local_tries = 0;
    map->choose_local_fallback_tries = 0;
    map->choose_total_tries = 50;
    map->chooseleaf_descend_once = 1;
    map->chooseleaf_vary_r = 1;
    map->chooseleaf_stable = 1;
    map->straw_calc_version = 1;
  }
}

/* deterministic LCG so weights are reproducible in python */
static unsigned lcg_state = 12345;
static unsigned lcg(void) {
  lcg_state = lcg_state * 1103515245u + 12345u;
  return (lcg_state >> 16) & 0x7fff;
}

int main(void) {
  printf("{");

  /* ---- hash vectors ---- */
  printf("\"hash\":[");
  for (int i = 0; i < 64; i++) {
    unsigned a = i * 2654435761u, b = i * 40503u + 7, c = i + 0xdeadbeefu,
             d = i * 97u, e = i * 1000003u;
    if (i) printf(",");
    printf("[%u,%u,%u,%u,%u]", crush_hash32(CRUSH_HASH_RJENKINS1, a),
           crush_hash32_2(CRUSH_HASH_RJENKINS1, a, b),
           crush_hash32_3(CRUSH_HASH_RJENKINS1, a, b, c),
           crush_hash32_4(CRUSH_HASH_RJENKINS1, a, b, c, d),
           crush_hash32_5(CRUSH_HASH_RJENKINS1, a, b, c, d, e));
  }
  printf("],");

  /* ---- crush_ln: full-range FNV checksum + sparse raw samples ---- */
  unsigned long long fnv = 1469598103934665603ull;
  printf("\"ln_samples\":[");
  for (unsigned u = 0; u < 0x10000; u++) {
    unsigned long long v = (unsigned long long)crush_ln(u);
    fnv = (fnv ^ v) * 1099511628211ull;
    if (u % 509 == 0) printf(u ? ",%llu" : "%llu", v);
  }
  printf("],\"ln_fnv\":%llu,", fnv);

  /* ---- scenario A: flat straw2 root over 12 osds, varied weights ---- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 1);
    int items[12], w[12], id;
    for (int i = 0; i < 12; i++) { items[i] = i; w[i] = (i + 1) * 0x8000; }
    mk(m, CRUSH_BUCKET_STRAW2, 10, 12, items, w, &id);
    struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, id, 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSE_FIRSTN, 0, 0);
    crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    crush_finalize(m);
    __u32 weight[12];
    for (int i = 0; i < 12; i++) weight[i] = 0x10000;
    weight[3] = 0;           /* out */
    weight[5] = 0x8000;      /* half in */
    printf("\"scenarios\":[");
    emit_rule_results(m, 0, 3, weight, 12, 256, 1);
  }

  /* ---- scenario B: two-level straw2, chooseleaf firstn, jewel ---- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 1);
    int hostids[5];
    int osd = 0;
    for (int h = 0; h < 5; h++) {
      int items[4], w[4];
      int n = 2 + (h % 3); /* sizes 2,3,4,2,3 */
      for (int i = 0; i < n; i++) {
        items[i] = osd++;
        w[i] = 0x10000 + (int)(lcg() % 0x10000);
      }
      struct crush_bucket *hb =
          mk(m, CRUSH_BUCKET_STRAW2, 1, n, items, w, &hostids[h]);
      (void)hb;
    }
    int hw[5];
    for (int h = 0; h < 5; h++) {
      struct crush_bucket *hb = m->buckets[-1 - hostids[h]];
      hw[h] = hb->weight;
    }
    int rootid;
    mk(m, CRUSH_BUCKET_STRAW2, 10, 5, hostids, hw, &rootid);
    struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
    crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    crush_finalize(m);
    __u32 weight[16];
    for (int i = 0; i < 14; i++) weight[i] = 0x10000;
    weight[2] = 0; weight[7] = 0xc000;
    emit_rule_results(m, 0, 3, weight, 14, 256, 0);

    /* scenario C: same map, chooseleaf INDEP (EC-style), result_max 4 */
    struct crush_rule *r2 = crush_make_rule(3, 1, 3, 1, 10);
    crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
    crush_rule_set_step(r2, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r2, 1);
    emit_rule_results(m, 1, 4, weight, 14, 256, 0);
  }

  /* ---- scenario D: every bucket alg as a host, choose firstn via types --- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 1);
    int algs[5] = {CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE,
                   CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2};
    int hostids[5], hw[5];
    int osd = 0;
    for (int h = 0; h < 5; h++) {
      int items[5], w[5];
      int n = 3 + (h % 2);
      for (int i = 0; i < n; i++) {
        items[i] = osd++;
        /* uniform buckets need equal weights */
        w[i] = (algs[h] == CRUSH_BUCKET_UNIFORM)
                   ? 0x10000
                   : 0x8000 + (int)(lcg() % 0x18000);
      }
      mk(m, algs[h], 1, n, items, w, &hostids[h]);
      hw[h] = m->buckets[-1 - hostids[h]]->weight;
    }
    int rootid;
    mk(m, CRUSH_BUCKET_STRAW2, 10, 5, hostids, hw, &rootid);
    struct crush_rule *r = crush_make_rule(4, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSE_FIRSTN, 0, 1); /* hosts */
    crush_rule_set_step(r, 2, CRUSH_RULE_CHOOSE_FIRSTN, 1, 0); /* 1 osd each */
    crush_rule_set_step(r, 3, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    crush_finalize(m);
    __u32 weight[32];
    for (int i = 0; i < osd; i++) weight[i] = 0x10000;
    weight[1] = 0x4000;
    emit_rule_results(m, 0, 4, weight, osd, 256, 0);
  }

  /* ---- scenario E: legacy tunables, straw1 two-level chooseleaf ---- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 0);
    int hostids[4], hw[4];
    int osd = 0;
    for (int h = 0; h < 4; h++) {
      int items[3], w[3];
      for (int i = 0; i < 3; i++) {
        items[i] = osd++;
        w[i] = 0x10000 + (int)(lcg() % 0x20000);
      }
      mk(m, CRUSH_BUCKET_STRAW, 1, 3, items, w, &hostids[h]);
      hw[h] = m->buckets[-1 - hostids[h]]->weight;
    }
    int rootid;
    mk(m, CRUSH_BUCKET_STRAW, 10, 4, hostids, hw, &rootid);
    struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
    crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    crush_finalize(m);
    __u32 weight[12];
    for (int i = 0; i < 12; i++) weight[i] = 0x10000;
    weight[4] = 0;
    emit_rule_results(m, 0, 3, weight, 12, 256, 0);
  }

  /* ---- scenario F: bigger cluster, 32 hosts x 4 osds, jewel, repl 3 --- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 1);
    int hostids[32], hw[32];
    int osd = 0;
    for (int h = 0; h < 32; h++) {
      int items[4], w[4];
      for (int i = 0; i < 4; i++) {
        items[i] = osd++;
        w[i] = 0x10000;
      }
      mk(m, CRUSH_BUCKET_STRAW2, 1, 4, items, w, &hostids[h]);
      hw[h] = m->buckets[-1 - hostids[h]]->weight;
    }
    int rootid;
    mk(m, CRUSH_BUCKET_STRAW2, 10, 32, hostids, hw, &rootid);
    struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
    crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    /* EC 8+4 indep rule */
    struct crush_rule *r2 = crush_make_rule(3, 1, 3, 1, 16);
    crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
    crush_rule_set_step(r2, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r2, 1);
    crush_finalize(m);
    __u32 weight[128];
    for (int i = 0; i < osd; i++) weight[i] = 0x10000;
    weight[10] = 0; weight[50] = 0; weight[77] = 0x8000;
    emit_rule_results(m, 0, 3, weight, osd, 512, 0);
    emit_rule_results(m, 1, 12, weight, osd, 512, 0);
  }

  /* ---- scenario G: THREE-level straw2 (root->rack->host->osd), jewel;
   *      chooseleaf firstn to host, chooseleaf indep to host, and
   *      chooseleaf firstn to RACK (leaf descent through 2 levels) --- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 1);
    int rackids[4], rw[4];
    int osd = 0;
    for (int rk = 0; rk < 4; rk++) {
      int hostids[3], hw[3];
      for (int h = 0; h < 3; h++) {
        int items[2], w[2];
        for (int i = 0; i < 2; i++) {
          items[i] = osd++;
          w[i] = 0x10000 + (int)(lcg() % 0x10000);
        }
        mk(m, CRUSH_BUCKET_STRAW2, 1, 2, items, w, &hostids[h]);
        hw[h] = m->buckets[-1 - hostids[h]]->weight;
      }
      mk(m, CRUSH_BUCKET_STRAW2, 2, 3, hostids, hw, &rackids[rk]);
      rw[rk] = m->buckets[-1 - rackids[rk]]->weight;
    }
    int rootid;
    mk(m, CRUSH_BUCKET_STRAW2, 10, 4, rackids, rw, &rootid);
    struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
    crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    struct crush_rule *r2 = crush_make_rule(3, 1, 3, 1, 10);
    crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
    crush_rule_set_step(r2, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r2, 1);
    struct crush_rule *r3 = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(r3, 0, CRUSH_RULE_TAKE, rootid, 0);
    crush_rule_set_step(r3, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 2);
    crush_rule_set_step(r3, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r3, 2);
    crush_finalize(m);
    __u32 weight[32];
    for (int i = 0; i < osd; i++) weight[i] = 0x10000;
    weight[3] = 0; weight[11] = 0x9000; weight[17] = 0;
    emit_rule_results(m, 0, 3, weight, osd, 512, 0);
    emit_rule_results(m, 1, 5, weight, osd, 512, 0);
    emit_rule_results(m, 2, 3, weight, osd, 512, 0);
  }

  /* ---- scenario H: MULTI-TAKE rule over two roots (primary pool +
   *      secondary pool pattern): take A chooseleaf 2, emit,
   *      take B chooseleaf 2, emit; plus an indep variant ---- */
  {
    struct crush_map *m = crush_create();
    set_tunables(m, 1);
    int rootids[2];
    int osd = 0;
    for (int rt = 0; rt < 2; rt++) {
      int hostids[3], hw[3];
      for (int h = 0; h < 3; h++) {
        int items[3], w[3];
        for (int i = 0; i < 3; i++) {
          items[i] = osd++;
          w[i] = 0x10000 + (int)(lcg() % 0x8000);
        }
        mk(m, CRUSH_BUCKET_STRAW2, 1, 3, items, w, &hostids[h]);
        hw[h] = m->buckets[-1 - hostids[h]]->weight;
      }
      mk(m, CRUSH_BUCKET_STRAW2, 10, 3, hostids, hw, &rootids[rt]);
    }
    struct crush_rule *r = crush_make_rule(6, 0, 1, 1, 10);
    crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, rootids[0], 0);
    crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 1);
    crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_rule_set_step(r, 3, CRUSH_RULE_TAKE, rootids[1], 0);
    crush_rule_set_step(r, 4, CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 1);
    crush_rule_set_step(r, 5, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r, 0);
    struct crush_rule *r2 = crush_make_rule(6, 1, 3, 1, 10);
    crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, rootids[0], 0);
    crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 2, 1);
    crush_rule_set_step(r2, 2, CRUSH_RULE_EMIT, 0, 0);
    crush_rule_set_step(r2, 3, CRUSH_RULE_TAKE, rootids[1], 0);
    crush_rule_set_step(r2, 4, CRUSH_RULE_CHOOSELEAF_INDEP, 2, 1);
    crush_rule_set_step(r2, 5, CRUSH_RULE_EMIT, 0, 0);
    crush_add_rule(m, r2, 1);
    crush_finalize(m);
    __u32 weight[32];
    for (int i = 0; i < osd; i++) weight[i] = 0x10000;
    weight[2] = 0; weight[12] = 0xa000;
    emit_rule_results(m, 0, 4, weight, osd, 512, 0);
    emit_rule_results(m, 1, 4, weight, osd, 512, 0);
  }

  printf("]}\n");
  return 0;
}
