#!/usr/bin/env python3
"""Regenerate CRUSH golden vectors from the reference C implementation.

Requires the reference tree (default /root/reference).  Compiles
gen_golden.c against the reference's crush sources in a temp dir and writes
crush_golden.json next to this script; also re-extracts the crush_ln lookup
constants into ceph_tpu/crush/_ln_tables.json.  The committed JSON is what
the test suite / package consume; this script only needs to run when
scenarios change.  The python side rebuilds identical maps in
tests/test_crush_golden.py (mirroring gen_golden.c's LCG weight streams).
"""

import json
import pathlib
import re
import subprocess
import sys
import tempfile

HERE = pathlib.Path(__file__).resolve().parent
REF = pathlib.Path(sys.argv[1] if len(sys.argv) > 1 else "/root/reference")


def extract_ln_tables():
    """Pull the 514 crush_ln constants out of crush_ln_table.h as data."""
    txt = (REF / "src/crush/crush_ln_table.h").read_text()
    m = re.search(r"__RH_LH_tbl\[128\*2\+2\] = \{(.*?)\};", txt, re.S)
    vals = [int(v, 16) for v in re.findall(r"0x([0-9a-fA-F]+)ll", m.group(1))]
    m2 = re.search(r"__LL_tbl\[256\] = \{(.*?)\};", txt, re.S)
    ll = [int(v, 16) for v in re.findall(r"0x([0-9a-fA-F]+)ull?", m2.group(1))]
    assert len(vals) == 258 and len(ll) == 256
    out = HERE.parent.parent / "ceph_tpu/crush/_ln_tables.json"
    out.write_text(json.dumps({"rh": vals[0::2], "lh": vals[1::2], "ll": ll}))
    print(f"wrote {out}")


def main():
    extract_ln_tables()
    src = REF / "src"
    assert (src / "crush/mapper.c").exists(), f"reference not at {REF}"
    with tempfile.TemporaryDirectory() as td:
        exe = pathlib.Path(td) / "gen_golden"
        # reference expects a configure-generated acconfig.h
        (pathlib.Path(td) / "acconfig.h").write_text(
            "#define HAVE_INTTYPES_H 1\n"
            "#define HAVE_STDINT_H 1\n"
            "#define HAVE_LINUX_TYPES_H 1\n")
        cmd = [
            "gcc", "-O1", "-o", str(exe), "-I", td,
            str(HERE / "gen_golden.c"),
            str(src / "crush/builder.c"),
            str(src / "crush/crush.c"),
            str(src / "crush/hash.c"),
            "-I", str(src),
            "-I", str(src / "crush"),
            f"-DMAPPER_C_PATH=\"{src}/crush/mapper.c\"",
            "-lm",
        ]
        subprocess.run(cmd, check=True)
        out = subprocess.run([str(exe)], check=True, capture_output=True)
        data = json.loads(out.stdout)
    path = HERE / "crush_golden.json"
    path.write_text(json.dumps(data))
    print(f"wrote {path} ({path.stat().st_size} bytes, "
          f"{len(data['scenarios'])} scenarios)")


if __name__ == "__main__":
    main()
