/*
 * Reference-CRUSH throughput harness (BASELINE.md row 4).
 *
 * ORIGINAL benchmark code that links against the *reference* Ceph CRUSH C
 * sources at bench time only (same arrangement as gen_golden.c): the
 * reference tree is NOT part of this repository.  bench.py compiles this
 * with
 *   gcc -O3 -march=native bench_ref_crush.c <ref>/src/crush/{builder,crush,hash}.c
 * and runs it to measure the single-core crush_do_rule rate the TPU engine
 * is compared against (topology: 128 hosts x 8 osds = 1024 OSDs, jewel
 * tunables, firstn x3 and indep x6 rules — mirroring
 * /root/reference/src/tools/osdmaptool.cc:328 --test-map-pgs).
 *
 * Output: one JSON line {"firstn_per_sec": N, "indep_per_sec": N}.
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#include "crush/crush.h"
#include "crush/builder.h"
#include "crush/hash.h"

#define dprintk(args...) /* nothing */
#include MAPPER_C_PATH

enum { HOSTS = 128, PER_HOST = 8, NOSD = HOSTS * PER_HOST };

static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

int main(int argc, char **argv) {
  int n_x = argc > 1 ? atoi(argv[1]) : 200000;
  struct crush_map *map = crush_create();
  map->choose_local_tries = 0;
  map->choose_local_fallback_tries = 0;
  map->choose_total_tries = 50;
  map->chooseleaf_descend_once = 1;
  map->chooseleaf_vary_r = 1;
  map->chooseleaf_stable = 1;
  map->straw_calc_version = 1;

  int host_ids[HOSTS];
  for (int h = 0; h < HOSTS; h++) {
    int items[PER_HOST], weights[PER_HOST];
    for (int i = 0; i < PER_HOST; i++) {
      items[i] = h * PER_HOST + i;
      weights[i] = 0x10000;
    }
    struct crush_bucket *b = crush_make_bucket(
        map, CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1, 1 /*host*/,
        PER_HOST, items, weights);
    crush_add_bucket(map, 0, b, &host_ids[h]);
  }
  int hw[HOSTS];
  for (int h = 0; h < HOSTS; h++) hw[h] = PER_HOST * 0x10000;
  struct crush_bucket *root = crush_make_bucket(
      map, CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1, 10 /*root*/,
      HOSTS, host_ids, hw);
  int root_id;
  crush_add_bucket(map, 0, root, &root_id);

  /* rule 0: replicated chooseleaf firstn; rule 1: ec chooseleaf indep */
  struct crush_rule *r0 = crush_make_rule(3, 0, 1, 1, 10);
  crush_rule_set_step(r0, 0, CRUSH_RULE_TAKE, root_id, 0);
  crush_rule_set_step(r0, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
  crush_rule_set_step(r0, 2, CRUSH_RULE_EMIT, 0, 0);
  crush_add_rule(map, r0, 0);
  struct crush_rule *r1 = crush_make_rule(3, 1, 3, 1, 10);
  crush_rule_set_step(r1, 0, CRUSH_RULE_TAKE, root_id, 0);
  crush_rule_set_step(r1, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
  crush_rule_set_step(r1, 2, CRUSH_RULE_EMIT, 0, 0);
  crush_add_rule(map, r1, 1);
  crush_finalize(map);

  /* 3-level variant: 16 racks x 8 hosts x 8 osds (same 1024 devices),
   * rule 2 = chooseleaf firstn to host THROUGH the rack level —
   * mapper.c's intervening-bucket descent (mapper.c:490-501) */
  enum { RACKS = 16, HPR = HOSTS / RACKS };
  int rack_ids[RACKS];
  for (int rk = 0; rk < RACKS; rk++) {
    int rh[HPR], rhw[HPR];
    for (int i = 0; i < HPR; i++) {
      rh[i] = host_ids[rk * HPR + i];
      rhw[i] = PER_HOST * 0x10000;
    }
    struct crush_bucket *rb = crush_make_bucket(
        map, CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1, 2 /*rack*/,
        HPR, rh, rhw);
    crush_add_bucket(map, 0, rb, &rack_ids[rk]);
  }
  int rw[RACKS];
  for (int rk = 0; rk < RACKS; rk++) rw[rk] = HPR * PER_HOST * 0x10000;
  struct crush_bucket *root3 = crush_make_bucket(
      map, CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1, 10 /*root*/,
      RACKS, rack_ids, rw);
  int root3_id;
  crush_add_bucket(map, 0, root3, &root3_id);
  struct crush_rule *r2 = crush_make_rule(3, 0, 1, 1, 10);
  crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, root3_id, 0);
  crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
  crush_rule_set_step(r2, 2, CRUSH_RULE_EMIT, 0, 0);
  crush_add_rule(map, r2, 2);
  crush_finalize(map);

  __u32 weight[NOSD];
  for (int i = 0; i < NOSD; i++) weight[i] = 0x10000;
  int result[8];
  int scratch[8 * 3];
  long acc = 0;

  double t0 = now_s();
  for (int x = 0; x < n_x; x++) {
    int len = crush_do_rule(map, 0, x, result, 3, weight, NOSD, scratch);
    acc += len ? result[0] : 0;
  }
  double firstn_rate = n_x / (now_s() - t0);

  t0 = now_s();
  for (int x = 0; x < n_x; x++) {
    int len = crush_do_rule(map, 1, x, result, 6, weight, NOSD, scratch);
    acc += len ? result[0] : 0;
  }
  double indep_rate = n_x / (now_s() - t0);

  t0 = now_s();
  for (int x = 0; x < n_x; x++) {
    int len = crush_do_rule(map, 2, x, result, 3, weight, NOSD, scratch);
    acc += len ? result[0] : 0;
  }
  double firstn3l_rate = n_x / (now_s() - t0);

  fprintf(stderr, "acc=%ld\n", acc); /* defeat dead-code elimination */
  printf("{\"firstn_per_sec\": %.0f, \"indep_per_sec\": %.0f, "
         "\"firstn3l_per_sec\": %.0f}\n",
         firstn_rate, indep_rate, firstn3l_rate);
  return 0;
}
