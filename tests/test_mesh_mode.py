"""OSD device-mesh execution mode (SURVEY §2.4 TPU-native data plane).

Boots a co-located cluster with osd_mesh_mode=on on the 8-device
virtual CPU mesh: EC writes encode as ONE sharded device program
(all_gather over the mesh's shard axis replaces the messenger chunk
fan-out; each device computes its own shard), sub-ops deliver in
process, and reads come back through the normal client path.  Verifies
VERDICT r3 ask #4's done-criteria: librados write -> per-shard
placement + parity bytes checked against the codec ground truth.
"""

import asyncio
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.parallel import mesh_exec  # noqa: E402
from ceph_tpu.qa.cluster import make_ctx  # noqa: E402


def _mesh_ctx(name):
    c = make_ctx(name)
    c.config.set("osd_mesh_mode", "on")
    return c


def test_mesh_mode_ec_write_placement_and_parity():
    async def run():
        mesh_exec.disable()
        cl = Cluster(ctx_factory=_mesh_ctx)
        admin = await cl.start(5)
        ex = mesh_exec.current()
        assert ex is not None and len(ex.osds) == 5, \
            "all co-located osds must register on the executor"
        await admin.pool_create("ecm", pg_num=4, pool_type="erasure",
                                k=2, m=2)
        io = admin.open_ioctx("ecm")
        payloads = {f"mobj{i}": bytes([i + 1]) * (4096 + 512 * i)
                    for i in range(6)}
        for oid, data in payloads.items():
            await io.write_full(oid, data)
        # the sharded program ran and sub-ops skipped the messenger
        assert ex.launches >= len(payloads), \
            f"mesh encode launches: {ex.launches}"
        assert ex.inproc_subops > 0
        # reads come back through the normal client path
        for oid, data in payloads.items():
            assert await io.read(oid) == data

        # per-shard placement + parity ground truth: find each object's
        # pg, locate every shard osd's store copy, compare with the
        # codec's own split/parity
        from ceph_tpu.ec.registry import factory
        from ceph_tpu.ec import gf256
        from ceph_tpu.client.objecter import ObjectLocator
        from ceph_tpu.store.types import CollectionId, ObjectId
        m = admin.monc.osdmap
        pool_id = m.lookup_pool("ecm")
        pool = m.pools[pool_id]
        profile = dict(m.ec_profiles[pool.ec_profile])
        profile.setdefault("k", "2")
        profile.setdefault("m", "2")
        profile.pop("plugin", None)
        codec = factory("rs", profile)
        k, n = 2, 4
        checked_parity = 0
        for oid, data in payloads.items():
            pgid = pool.raw_pg_to_pg(
                m.object_locator_to_pg(oid, ObjectLocator(pool_id)))
            up, _, acting, _ = m.pg_to_up_acting_osds(pgid)
            chunks = codec.split_data(data)
            gen = codec.generator
            parity = gf256.host_apply(gen[k:], chunks)
            want = {i: (chunks[i] if i < k else parity[i - k])
                    for i in range(n)}
            for i, osd_id in enumerate(acting):
                osd = cl.osds[osd_id]
                cid = CollectionId.pg(pool_id, pgid.seed, i)
                raw = osd.store.read(cid, ObjectId(oid, pool=pool_id))
                got = np.frombuffer(raw, np.uint8)
                assert np.array_equal(got, want[i]), \
                    f"{oid} shard {i} on osd.{osd_id} mismatch"
                if i >= k:
                    checked_parity += 1
        assert checked_parity >= len(payloads) * 2
        await cl.stop()
        mesh_exec.disable()
    asyncio.run(run())
