"""Scrub: integrity detection + repair (osd/scrub.py).

Reference strategy analog: test/osd/osd-scrub-repair.sh — corrupt a
stored copy behind the cluster's back, scrub, prove detection and
repair for replicated and EC pools.
"""

import asyncio
import sys

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.osd.messages import MPGScrub  # noqa: E402
from ceph_tpu.store.objectstore import Transaction  # noqa: E402


def find_copies(cl, name):
    """[(osd, cid, soid)] for every stored copy/shard of object `name`."""
    out = []
    for osd in cl.osds.values():
        for cid in osd.store.list_collections():
            for soid in osd.store.collection_list(cid):
                if soid.name == name:
                    out.append((osd, cid, soid))
    return out


def corrupt(osd, cid, soid, flip=0):
    """Flip one bit of the stored bytes WITHOUT touching xattrs —
    simulated silent media bit-rot."""
    data = bytearray(osd.store.read(cid, soid))
    data[flip] ^= 0x40
    osd.store.apply_transaction(
        Transaction().write(cid, soid, 0, bytes(data)))


def primary_pg(cl, pool_name, name):
    """(pg-on-primary, primary-osd) for the PG holding `name`."""
    for osd in cl.osds.values():
        for pg in osd.pgs.values():
            if not pg.is_primary():
                continue
            for soid in osd.store.collection_list(pg.cid):
                if soid.name == name:
                    return pg, osd
    raise AssertionError(f"no primary pg holds {name}")


async def run_scrub(pg, deep):
    pg.last_scrub_result = None
    pg.queue_op(MPGScrub(pg.pgid, deep=deep))
    for _ in range(400):
        if pg.last_scrub_result is not None:
            return pg.last_scrub_result
        await asyncio.sleep(0.05)
    raise AssertionError("scrub did not complete")


def test_deep_scrub_repairs_replica_bitrot():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        payload = bytes(range(256)) * 32
        await io.write_full("obj", payload)
        pg, posd = primary_pg(cl, "data", "obj")
        # rot a NON-primary copy
        victims = [(o, c, s) for (o, c, s) in find_copies(cl, "obj")
                   if o is not posd]
        assert victims
        vosd, vcid, vsoid = victims[0]
        corrupt(vosd, vcid, vsoid)
        assert vosd.store.read(vcid, vsoid) != payload
        res = await run_scrub(pg, deep=True)
        assert res["errors"] >= 1 and res["repaired"] >= 1
        assert vosd.store.read(vcid, vsoid) == payload   # healed
        # second scrub: clean
        res = await run_scrub(pg, deep=True)
        assert res["errors"] == 0
        assert await io.read("obj") == payload
        await cl.stop()
    asyncio.run(run())


def test_deep_scrub_repairs_primary_bitrot():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        payload = b"primary-rot" * 500
        await io.write_full("obj", payload)
        pg, posd = primary_pg(cl, "data", "obj")
        mine = [(o, c, s) for (o, c, s) in find_copies(cl, "obj")
                if o is posd]
        corrupt(*mine[0])
        res = await run_scrub(pg, deep=True)
        assert res["errors"] >= 1
        assert posd.store.read(mine[0][1], mine[0][2]) == payload
        assert await io.read("obj") == payload
        await cl.stop()
    asyncio.run(run())


def test_light_scrub_repairs_missing_replica_object():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"x" * 4096)
        pg, posd = primary_pg(cl, "data", "obj")
        victims = [(o, c, s) for (o, c, s) in find_copies(cl, "obj")
                   if o is not posd]
        vosd, vcid, vsoid = victims[0]
        vosd.store.apply_transaction(Transaction().remove(vcid, vsoid))
        res = await run_scrub(pg, deep=False)     # light finds absence
        assert res["errors"] >= 1 and res["repaired"] >= 1
        assert vosd.store.read(vcid, vsoid) == b"x" * 4096
        await cl.stop()
    asyncio.run(run())


def test_deep_scrub_rebuilds_ec_shard():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ecpool", pg_num=4, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ecpool")
        rng = np.random.default_rng(5)
        payload = rng.integers(0, 256, 16384, dtype=np.uint8).tobytes()
        await io.write_full("obj", payload)
        pg, posd = primary_pg(cl, "ecpool", "obj")
        victims = [(o, c, s) for (o, c, s) in find_copies(cl, "obj")
                   if o is not posd]
        vosd, vcid, vsoid = victims[0]
        before = vosd.store.read(vcid, vsoid)
        corrupt(vosd, vcid, vsoid, flip=7)
        res = await run_scrub(pg, deep=True)
        assert res["errors"] >= 1 and res["repaired"] >= 1
        assert vosd.store.read(vcid, vsoid) == before    # shard rebuilt
        assert await io.read("obj") == payload
        res = await run_scrub(pg, deep=True)
        assert res["errors"] == 0
        await cl.stop()
    asyncio.run(run())


def test_deep_scrub_rebuilds_primary_own_ec_shard():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ecpool", pg_num=4, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ecpool")
        payload = bytes(range(256)) * 64
        await io.write_full("obj", payload)
        pg, posd = primary_pg(cl, "ecpool", "obj")
        mine = [(o, c, s) for (o, c, s) in find_copies(cl, "obj")
                if o is posd]
        before = posd.store.read(mine[0][1], mine[0][2])
        corrupt(*mine[0], flip=3)
        res = await run_scrub(pg, deep=True)
        assert res["errors"] >= 1 and res["repaired"] >= 1
        assert posd.store.read(mine[0][1], mine[0][2]) == before
        assert await io.read("obj") == payload
        await cl.stop()
    asyncio.run(run())


def test_pg_scrub_mon_command_path():
    """Operator path: `ceph pg deep-scrub <pgid>` routed mon -> primary."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"cmd-path" * 512)
        pg, posd = primary_pg(cl, "data", "obj")
        victims = [(o, c, s) for (o, c, s) in find_copies(cl, "obj")
                   if o is not posd]
        corrupt(*victims[0])
        pg.last_scrub_result = None
        ackm = await admin.mon_command(
            {"prefix": "pg deep-scrub",
             "pgid": str(pg.pgid.without_shard())})
        assert ackm.retcode == 0, ackm.outs
        for _ in range(400):
            if pg.last_scrub_result is not None:
                break
            await asyncio.sleep(0.05)
        assert pg.last_scrub_result is not None, "scrub never ran"
        assert pg.last_scrub_result["repaired"] >= 1
        assert victims[0][0].store.read(victims[0][1], victims[0][2]) \
            == b"cmd-path" * 512
        await cl.stop()
    asyncio.run(run())


def test_scrub_updates_info_stamps_and_perf():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"stamps")
        pg, posd = primary_pg(cl, "data", "obj")
        assert pg.info.last_deep_scrub_stamp == 0
        await run_scrub(pg, deep=True)
        assert pg.info.last_deep_scrub_stamp > 0
        assert pg.info.last_scrub_stamp > 0
        assert posd.perf_scrub.dump()["scrubs_deep"] >= 1
        await cl.stop()
    asyncio.run(run())


def test_deep_scrub_repairs_clone_bitrot():
    """Snapshot clones scrub + repair like heads (keyed name\\x00snap):
    bit-rot in a replica's CLONE is detected by deep scrub and healed
    by re-pushing the base object (head + SnapSet + clones)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"frozen" * 500)
        await io.snap_create("s1")
        sid = io.snap_lookup("s1")
        await io.write_full("obj", b"newer!" * 700)   # clones v1

        clones = [(o, c, s) for o, c, s in find_copies(cl, "obj")
                  if not s.is_head()]
        assert len(clones) == 3
        vosd, vcid, vsoid = clones[0]
        corrupt(vosd, vcid, vsoid)

        pg, posd = primary_pg(cl, "data", "obj")
        res = await run_scrub(pg, deep=True)
        assert res["errors"] >= 1, res
        assert res["repaired"] >= 1, res
        assert any("\x00" in i for i in res["inconsistent"]), res

        # the corrupted clone is bit-exact again on every copy...
        for o, c, s in find_copies(cl, "obj"):
            if not s.is_head():
                assert o.store.read(c, s) == b"frozen" * 500
        # ...and a re-scrub is clean
        res = await run_scrub(pg, deep=True)
        assert res["errors"] == 0, res
        # snapshot read serves the healed bytes
        sio = io.dup()
        sio.set_snap_read(sid)
        assert await sio.read("obj") == b"frozen" * 500
        await cl.stop()
    asyncio.run(run())


def test_deep_scrub_rebuilds_ec_clone_chunk():
    """EC clone chunks scrub + rebuild: bit-rot in one shard's CLONE
    chunk is detected and reconstructed by decoding over the peers'
    clone chunks (the erasure relation holds per clone)."""
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("ec", pg_num=4, pool_type="erasure",
                                k=2, m=2)
        io = admin.open_ioctx("ec")
        await io.write_full("obj", b"frozen" * 600)
        await io.snap_create("s1")
        sid = io.snap_lookup("s1")
        await io.write_full("obj", b"newer!" * 400)   # clones chunks

        clones = [(o, c, s) for o, c, s in find_copies(cl, "obj")
                  if not s.is_head()]
        assert len(clones) == 4            # one clone chunk per shard
        vosd, vcid, vsoid = clones[0]
        want = vosd.store.read(vcid, vsoid)
        corrupt(vosd, vcid, vsoid)

        pg, posd = primary_pg(cl, "ec", "obj")
        res = await run_scrub(pg, deep=True)
        assert res["errors"] >= 1, res
        assert res["repaired"] >= 1, res

        # the corrupted clone chunk is bit-exact again
        assert vosd.store.read(vcid, vsoid) == want
        res = await run_scrub(pg, deep=True)
        assert res["errors"] == 0, res
        # and the snapshot read decodes the healed stripe
        sio = io.dup()
        sio.set_snap_read(sid)
        assert await sio.read("obj") == b"frozen" * 600
        await cl.stop()
    asyncio.run(run())
