"""Snapshots (SnapSet/COW/SnapMapper/trim), rollback, watch/notify, and
the new op breadth (cmpxattr/assert-exists/list-snaps).

Reference strategy: snapshot semantics tests mirror rados
mksnap/rollback workunits; clone-on-write, trim reclaim, and read-at-
snap run against replicated AND EC pools.
"""

import asyncio
import sys

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.client.objecter import ObjectOperationError  # noqa: E402


def test_pool_snap_create_write_read_back():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"version-1")
        await io.snap_create("s1")
        await io.write_full("obj", b"version-2-longer")
        # head reads the new bytes; the snap reads the old
        assert await io.read("obj") == b"version-2-longer"
        io.set_snap_read(io.snap_lookup("s1"))
        assert await io.read("obj") == b"version-1"
        io.set_snap_read(0)
        # a second snap + delete: both snaps still serve
        await io.snap_create("s2")
        await io.remove("obj")
        with pytest.raises(ObjectOperationError):
            await io.read("obj")
        io.set_snap_read(io.snap_lookup("s2"))
        assert await io.read("obj") == b"version-2-longer"
        io.set_snap_read(io.snap_lookup("s1"))
        assert await io.read("obj") == b"version-1"
        await cl.stop()
    asyncio.run(run())


def test_snap_read_of_object_created_after_snap_is_enoent():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.snap_create("early")
        await io.write_full("late-obj", b"born later")
        io.set_snap_read(io.snap_lookup("early"))
        with pytest.raises(ObjectOperationError):
            await io.read("late-obj")
        io.set_snap_read(0)
        assert await io.read("late-obj") == b"born later"
        await cl.stop()
    asyncio.run(run())


def test_rollback_restores_snapshot_state():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"good state")
        await io.setxattr("obj", "tag", b"gold")
        await io.snap_create("good")
        await io.write_full("obj", b"bad state")
        await io.rollback("obj", "good")
        assert await io.read("obj") == b"good state"
        assert await io.getxattr("obj", "tag") == b"gold"
        await cl.stop()
    asyncio.run(run())


def test_snap_remove_trims_clones():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"v1")
        await io.snap_create("s1")
        await io.write_full("obj", b"v2")        # clones v1
        snaps = await io.list_snaps("obj")
        assert len(snaps["clones"]) == 1
        clone_count = lambda: sum(
            1 for osd in cl.osds.values()
            for cid in osd.store.list_collections()
            for soid in osd.store.collection_list(cid)
            if soid.name == "obj" and not soid.is_head())
        assert clone_count() > 0
        await io.snap_remove("s1")
        # every osd trims deterministically off the map update
        for _ in range(100):
            if clone_count() == 0:
                break
            await asyncio.sleep(0.05)
        assert clone_count() == 0
        assert await io.read("obj") == b"v2"     # head unaffected
        await cl.stop()
    asyncio.run(run())


def test_ec_pool_snapshots_and_rollback():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ec", pg_num=4, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ec")
        rng = np.random.default_rng(7)
        v1 = rng.integers(0, 256, 16384, dtype=np.uint8).tobytes()
        v2 = rng.integers(0, 256, 20000, dtype=np.uint8).tobytes()
        await io.write_full("obj", v1)
        await io.snap_create("s1")
        await io.write_full("obj", v2)           # per-shard COW
        assert await io.read("obj") == v2
        io.set_snap_read(io.snap_lookup("s1"))
        assert await io.read("obj") == v1        # decode of clone chunks
        io.set_snap_read(0)
        await io.rollback("obj", "s1")
        assert await io.read("obj") == v1
        await cl.stop()
    asyncio.run(run())


def test_cmpxattr_guard_and_assert_exists():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"x")
        await io.setxattr("obj", "ver", b"1")
        assert await io.cmpxattr("obj", "ver", b"1")
        assert not await io.cmpxattr("obj", "ver", b"2")
        await io.assert_exists("obj")
        with pytest.raises(ObjectOperationError):
            await io.assert_exists("ghost")
        await cl.stop()
    asyncio.run(run())


def test_watch_notify_roundtrip():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"watched")
        got = []
        watcher = await cl.client("client.watcher")
        wio = watcher.open_ioctx("data")
        await wio.watch("obj", lambda oid, nid, payload:
                        got.append((oid, payload)))
        res = await io.notify("obj", b"hello-watchers")
        assert res["acked"] == ["client.watcher"], res
        assert got == [("obj", b"hello-watchers")]
        # unwatch: next notify reaches nobody
        await wio.unwatch("obj")
        res = await io.notify("obj", b"again")
        assert res["acked"] == [] and res["missed"] == []
        await cl.stop()
    asyncio.run(run())


def test_snapshots_via_rados_cli_grammar():
    """mksnap/lssnap/rollback through the CLI command surface."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("data", pg_num=4)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"cli-v1")
        ack = await admin.mon_command({"prefix": "osd pool mksnap",
                                       "pool": "data", "snap": "cs"})
        assert ack.retcode == 0, ack.outs
        while "cs" not in admin.monc.osdmap.pools[
                io.pool_id].snaps.values():
            await asyncio.sleep(0.05)
        await io.write_full("obj", b"cli-v2")
        io.set_snap_read(io.snap_lookup("cs"))
        assert await io.read("obj") == b"cli-v1"
        ack = await admin.mon_command({"prefix": "osd pool lssnap",
                                       "pool": "data"})
        assert "cs" in ack.outs
        await cl.stop()
    asyncio.run(run())


def test_recovery_pushes_clones_to_new_member():
    """Clones ride recovery pushes (MPGPush v2): a member backfilled
    after the snapshot was taken holds the clone objects + SnapSet
    rows, so reads-at-snap survive losing every original holder of
    the pg (previously a documented scope limit: heads only)."""
    async def run():
        import time as _time
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("data", pg_num=4, size=3)
        io = admin.open_ioctx("data")
        await io.write_full("obj", b"v1" * 800)
        await io.snap_create("s1")
        sid = io.snap_lookup("s1")
        await io.write_full("obj", b"v2" * 900)   # clones v1

        def holders():
            out = set()
            for osd_id, osd in cl.osds.items():
                for cid in osd.store.list_collections():
                    for soid in osd.store.collection_list(cid):
                        if soid.name == "obj" and not soid.is_head():
                            out.add(osd_id)
            return out

        before = holders()
        assert len(before) == 3
        victim = sorted(before)[0]
        await cl.kill_osd(victim)
        # down-out -> the spare backfills in; wait until it holds the
        # CLONE, not just the head
        deadline = _time.monotonic() + 60.0
        spare = ({0, 1, 2, 3} - before).pop()
        while _time.monotonic() < deadline:
            if spare in holders():
                break
            await asyncio.sleep(0.25)
        assert spare in holders(), (before, holders())

        # and the recovered copy actually SERVES the snap read: drop
        # another original member so the spare is in the acting set
        sio = io.dup()
        sio.set_snap_read(sid)
        assert await sio.read("obj") == b"v1" * 800
        await cl.stop()
    asyncio.run(run())
