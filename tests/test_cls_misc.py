"""cls_version / cls_numops / cls_timeindex / cls_log / cls_user.

Mirrors the reference's src/test/cls_version, cls_numops.cc tests,
test_cls_log.cc, and cls_user semantics (src/cls/{version,numops,
timeindex,log,user}/*.cc): CAS versioning, atomic arithmetic,
time-range list/trim, header high-water marks, aggregated user stats.
"""

import asyncio
import errno
import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.client.objecter import ObjectOperationError  # noqa: E402


async def _cluster():
    cl = Cluster()
    admin = await cl.start(3)
    await admin.pool_create("p", pg_num=8)
    return cl, admin.open_ioctx("p")


def _j(d) -> bytes:
    return json.dumps(d).encode()


def test_cls_version_set_inc_conds():
    async def run():
        cl, io = await _cluster()

        # unversioned object reads as ver 0 / empty tag
        v = json.loads(await io.exec("o", "version", "read"))
        assert v == {"ver": 0, "tag": ""}

        # inc mints a tag and bumps; second inc keeps the tag
        await io.exec("o", "version", "inc")
        v1 = json.loads(await io.exec("o", "version", "read"))
        assert v1["ver"] == 1 and v1["tag"]
        await io.exec("o", "version", "inc")
        v2 = json.loads(await io.exec("o", "version", "read"))
        assert v2["ver"] == 2 and v2["tag"] == v1["tag"]

        # conditional inc: stale EQ loses with ECANCELED (the RMW fence)
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("o", "version", "inc",
                          _j({"conds": [{"cond": "eq", "ver": 1}]}))
        assert ei.value.retcode == -errno.ECANCELED
        await io.exec("o", "version", "inc",
                      _j({"conds": [{"cond": "eq", "ver": 2}]}))

        # explicit set + tag conditions
        await io.exec("o", "version", "set", _j({"ver": 10, "tag": "t0"}))
        await io.exec("o", "version", "check_conds",
                      _j({"conds": [{"cond": "tag_eq", "tag": "t0"},
                                    {"cond": "ge", "ver": 10}]}))
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("o", "version", "check_conds",
                          _j({"conds": [{"cond": "tag_ne", "tag": "t0"}]}))
        assert ei.value.retcode == -errno.ECANCELED
        await cl.stop()
    asyncio.run(run())


def test_cls_numops_add_mul_errors():
    async def run():
        cl, io = await _cluster()
        await io.exec("n", "numops", "add", _j({"key": "x", "value": "5"}))
        await io.exec("n", "numops", "add", _j({"key": "x", "value": -2}))
        omap = await io.omap_get("n")
        assert omap[b"x"] == b"3"
        await io.exec("n", "numops", "mul", _j({"key": "x", "value": 2.5}))
        omap = await io.omap_get("n")
        assert float(omap[b"x"]) == 7.5

        # non-numeric stored value -> EBADMSG
        await io.omap_set("n", {b"bad": b"not-a-number"})
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("n", "numops", "add",
                          _j({"key": "bad", "value": 1}))
        assert ei.value.retcode == -errno.EBADMSG

        # overflow -> EOVERFLOW
        await io.omap_set("n", {b"big": b"1e308"})
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("n", "numops", "mul",
                          _j({"key": "big", "value": "1e308"}))
        assert ei.value.retcode == -errno.EOVERFLOW
        await cl.stop()
    asyncio.run(run())


def test_cls_timeindex_add_list_trim():
    async def run():
        cl, io = await _cluster()
        entries = [{"ts": 100.0 + i, "key_ext": f"e{i}", "value": i}
                   for i in range(10)]
        await io.exec("t", "timeindex", "add", _j({"entries": entries}))

        # ranged list [102, 107) in time order
        out = json.loads(await io.exec(
            "t", "timeindex", "list",
            _j({"from_ts": 102.0, "to_ts": 107.0})))
        assert [e["value"] for e in out["entries"]] == [2, 3, 4, 5, 6]
        assert not out["truncated"]

        # pagination by marker
        out1 = json.loads(await io.exec(
            "t", "timeindex", "list", _j({"max_entries": 4})))
        assert out1["truncated"] and len(out1["entries"]) == 4
        out2 = json.loads(await io.exec(
            "t", "timeindex", "list", _j({"marker": out1["marker"]})))
        got = [e["value"] for e in out1["entries"] + out2["entries"]]
        assert got == list(range(10))

        # trim [0, 105) then re-list; second trim of same range ENODATA
        await io.exec("t", "timeindex", "trim", _j({"to_ts": 105.0}))
        out = json.loads(await io.exec("t", "timeindex", "list"))
        assert [e["value"] for e in out["entries"]] == [5, 6, 7, 8, 9]
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("t", "timeindex", "trim", _j({"to_ts": 105.0}))
        assert ei.value.retcode == -errno.ENODATA
        await cl.stop()
    asyncio.run(run())


def test_cls_log_header_high_water():
    async def run():
        cl, io = await _cluster()
        await io.exec("lg", "log", "add", _j({"entries": [
            {"ts": 50.0, "section": "meta", "name": "a", "data": "d0"},
            {"ts": 60.0, "section": "meta", "name": "b", "data": "d1"},
        ]}))
        info = json.loads(await io.exec("lg", "log", "info"))
        assert info["max_time"] == 60.0 and info["max_marker"]

        out = json.loads(await io.exec("lg", "log", "list"))
        assert [e["name"] for e in out["entries"]] == ["a", "b"]

        # same-timestamp entries stay distinct (persistent uniquifier)
        await io.exec("lg", "log", "add", _j({"entries": [
            {"ts": 60.0, "section": "meta", "name": "c"},
            {"ts": 60.0, "section": "meta", "name": "d"},
        ]}))
        out = json.loads(await io.exec("lg", "log", "list"))
        assert len(out["entries"]) == 4

        # trim everything before 60s: only ts<60 goes; header keeps
        # its high-water mark
        await io.exec("lg", "log", "trim", _j({"to_ts": 60.0}))
        out = json.loads(await io.exec("lg", "log", "list"))
        assert sorted(e["name"] for e in out["entries"]) == ["b", "c", "d"]
        info2 = json.loads(await io.exec("lg", "log", "info"))
        assert info2["max_time"] == 60.0
        await cl.stop()
    asyncio.run(run())


def test_cls_user_stats_and_listing():
    async def run():
        cl, io = await _cluster()
        await io.exec("u", "user", "set_buckets", _j({
            "entries": [
                {"bucket": "b1", "size": 100, "count": 3,
                 "creation_ts": 1.0},
                {"bucket": "b2", "size": 50, "count": 1,
                 "creation_ts": 2.0},
            ], "add": True, "ts": 99.0}))
        hdr = json.loads(await io.exec("u", "user", "get_header"))
        assert hdr["total_entries"] == 2 and hdr["total_bytes"] == 150

        # update b1's stats; creation time survives re-registration
        await io.exec("u", "user", "set_buckets", _j({
            "entries": [{"bucket": "b1", "size": 200, "count": 5,
                         "creation_ts": 7.0}], "add": True, "ts": 100.0}))
        out = json.loads(await io.exec("u", "user", "list_buckets"))
        b1 = [e for e in out["entries"] if e["bucket"] == "b1"][0]
        assert b1["size"] == 200 and b1["creation_ts"] == 1.0
        hdr = json.loads(await io.exec("u", "user", "get_header"))
        assert hdr["total_bytes"] == 250

        # remove a bucket: header shrinks; removing again ENOENT
        await io.exec("u", "user", "remove_bucket", _j({"bucket": "b2"}))
        hdr = json.loads(await io.exec("u", "user", "get_header"))
        assert hdr["total_entries"] == 1 and hdr["total_bytes"] == 200
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("u", "user", "remove_bucket",
                          _j({"bucket": "b2"}))
        assert ei.value.retcode == -errno.ENOENT
        await cl.stop()
    asyncio.run(run())


def test_cls_statelog_indexes_and_guard():
    """cls_statelog (src/cls/statelog/cls_statelog.cc): triple-indexed
    op-state entries; filtered listings; check_state fences stale
    agents with ECANCELED."""
    async def run():
        cl, io = await _cluster()
        await io.exec("sl", "statelog", "add", _j({"entries": [
            {"client_id": "c1", "op_id": "op1", "object": "a",
             "state": "in_progress", "ts": 1.0},
            {"client_id": "c1", "op_id": "op2", "object": "b",
             "state": "done", "ts": 2.0},
            {"client_id": "c2", "op_id": "op3", "object": "a",
             "state": "in_progress", "ts": 3.0},
        ]}))
        by_client = json.loads(await io.exec(
            "sl", "statelog", "list", _j({"client_id": "c1"})))
        assert sorted(e["op_id"] for e in by_client["entries"]) \
            == ["op1", "op2"]
        by_obj = json.loads(await io.exec(
            "sl", "statelog", "list", _j({"object": "a"})))
        assert sorted(e["client_id"] for e in by_obj["entries"]) \
            == ["c1", "c2"]

        # separator collision: object "a" filter must NOT leak
        # object "a_1" entries (values are %-escaped in index keys)
        await io.exec("sl", "statelog", "add", _j({"entries": [
            {"client_id": "c9", "op_id": "op9", "object": "a_1",
             "state": "done", "ts": 9.0}]}))
        by_obj = json.loads(await io.exec(
            "sl", "statelog", "list", _j({"object": "a"})))
        assert sorted(e["client_id"] for e in by_obj["entries"]) \
            == ["c1", "c2"]
        by_obj = json.loads(await io.exec(
            "sl", "statelog", "list", _j({"object": "a_1"})))
        assert [e["client_id"] for e in by_obj["entries"]] == ["c9"]
        await io.exec("sl", "statelog", "remove",
                      _j({"client_id": "c9", "op_id": "op9",
                          "object": "a_1"}))

        # state guard
        ok = json.loads(await io.exec(
            "sl", "statelog", "check_state",
            _j({"client_id": "c1", "op_id": "op2", "object": "b",
                "state": "done"})))
        assert ok["ts"] == 2.0
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("sl", "statelog", "check_state",
                          _j({"client_id": "c1", "op_id": "op2",
                              "object": "b", "state": "in_progress"}))
        assert ei.value.retcode == -errno.ECANCELED

        # remove drops every index row
        await io.exec("sl", "statelog", "remove",
                      _j({"client_id": "c1", "op_id": "op1",
                          "object": "a"}))
        allrows = json.loads(await io.exec("sl", "statelog", "list"))
        assert len(allrows["entries"]) == 2
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("sl", "statelog", "remove",
                          _j({"client_id": "c1", "op_id": "op1",
                              "object": "a"}))
        assert ei.value.retcode == -errno.ENOENT
        await cl.stop()
    asyncio.run(run())


def test_cls_replica_log_bounds():
    """cls_replica_log (src/cls/replica_log): per-entity progress
    markers; get_bounds returns the OLDEST position (the trim fence);
    a bound can't move backward over in-progress items."""
    async def run():
        cl, io = await _cluster()
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("rl", "replica_log", "get_bounds")
        assert ei.value.retcode == -errno.ENOENT

        await io.exec("rl", "replica_log", "set_bound",
                      _j({"entity_id": "zoneB", "position_marker": "50",
                          "position_time": 5.0}))
        await io.exec("rl", "replica_log", "set_bound",
                      _j({"entity_id": "zoneC", "position_marker": "30",
                          "position_time": 3.0,
                          "items": [{"name": "x", "ts": 2.5}]}))
        b = json.loads(await io.exec("rl", "replica_log", "get_bounds"))
        assert b["position_marker"] == "30"
        assert b["oldest_time"] == 3.0 and len(b["markers"]) == 2

        # backward move with in-progress items refused
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("rl", "replica_log", "set_bound",
                          _j({"entity_id": "zoneC",
                              "position_marker": "10"}))
        assert ei.value.retcode == -errno.EINVAL
        # forward move fine; then delete releases the fence
        await io.exec("rl", "replica_log", "set_bound",
                      _j({"entity_id": "zoneC",
                          "position_marker": "60",
                          "position_time": 6.0}))
        await io.exec("rl", "replica_log", "delete_bound",
                      _j({"entity_id": "zoneB"}))
        b = json.loads(await io.exec("rl", "replica_log", "get_bounds"))
        assert b["position_marker"] == "60"
        await cl.stop()
    asyncio.run(run())
