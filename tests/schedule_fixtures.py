"""Seeded-bug fixtures for the deterministic schedule explorer.

Each context manager re-introduces one HISTORICAL write-path hazard so
tests/test_schedule.py can assert the explorer actually detects the
class of bug it exists for (a checker that has never caught its target
bug is a no-op with good marketing):

  * ``out_of_order_version_assignment`` — the pre-PR-5 structure:
    pglog version assigned BEFORE a suspension point, log appended
    after it.  Two concurrent ops on disjoint objects can then append
    out of assignment order, leaving the pglog non-dense (a gap the
    in-order group-commit callbacks silently mis-account).  PR 5 fixed
    this by assigning versions inside the await-free submit section
    (rule AF01 guards the structure; the explorer guards the BEHAVIOR).

  * ``commit_callbacks_before_durability`` — a commit thread that runs
    its completion callbacks before the group's durability barrier.
    Acks (client replies, repop acks, last_complete) then vouch for
    writes a crash at the PR-1 fault-injection points would lose —
    the phantom-ack class the data-before-metadata discipline exists
    to prevent.

Both patch at class level and restore on exit; apply them INSIDE the
test, around the run_ec_mini/explore call.
"""

from __future__ import annotations

import asyncio
import contextlib


@contextlib.contextmanager
def out_of_order_version_assignment():
    """Reintroduce the pre-PR-5 hazard on ReplicatedBackend: a private
    version counter advances at op ARRIVAL, then the op yields once
    before entering the (otherwise unchanged) submit path, which is
    forced to use the early-assigned version.  Any schedule that wakes
    two ops out of assignment order appends a gapped/misordered pglog
    — exactly what dense-version checking must catch."""
    from ceph_tpu.osd.backend import ReplicatedBackend
    from ceph_tpu.osd.messages import EVersion

    orig_submit = ReplicatedBackend.submit_client_write

    async def buggy(self, m):
        pg = self.pg
        cnt = pg.__dict__.get("_fx_version_counter")
        if cnt is None:
            cnt = pg.info.last_update.version
        cnt += 1
        pg.__dict__["_fx_version_counter"] = cnt
        forced = EVersion(pg.osd.osdmap.epoch, cnt)
        # the bug: a suspension point between version assignment and
        # the log append — another op can interleave here
        await asyncio.sleep(0)
        # force the original submit path to use the stale version.
        # The instance attribute shadows the class method and is
        # consumed synchronously (no await precedes next_version in
        # the replicated submit path), so concurrent ops cannot read
        # each other's forced version.
        pg.__dict__["next_version"] = lambda: forced
        try:
            return await orig_submit(self, m)
        finally:
            pg.__dict__.pop("next_version", None)

    ReplicatedBackend.submit_client_write = buggy
    try:
        yield
    finally:
        ReplicatedBackend.submit_client_write = orig_submit


@contextlib.contextmanager
def commit_callbacks_before_durability():
    """Reintroduce the phantom-ack hazard on KVSyncThread: completion
    callbacks fire BEFORE the group's data/kv barrier instead of
    after.  The commit-order observer flags every group ("ack before
    durability"); with a crash armed at before_data_sync the acks have
    already escaped for a group that never became durable."""
    from ceph_tpu.store.commit import KVSyncThread

    orig_commit = KVSyncThread._commit
    orig_complete = KVSyncThread._complete

    def buggy(self, group):
        orig_complete(self, group)          # BUG: acks first
        # suppress the in-order completion the real path runs after
        # durability — the callbacks must not fire twice
        self._complete = lambda g: None
        try:
            orig_commit(self, group)
        finally:
            del self._complete

    KVSyncThread._commit = buggy
    try:
        yield
    finally:
        KVSyncThread._commit = orig_commit


@contextlib.contextmanager
def boolean_backfill_marker():
    """Reintroduce the pre-PR-17 boolean-marker bug on ECBackend: a
    backfilling shard has no per-object cursor, only an all-or-nothing
    "complete" flag, so the sub-read path trusts the LOCAL object set
    over its whole namespace — an absent name inside the unfinished
    copy answers ENOENT (a data statement: "deleted") instead of
    EAGAIN (a topology statement: "ask elsewhere"), and a half-copied
    versionless blob is served as authoritative.  This is the
    historical ~1/6-seed EC model-checker phantom-deletion window the
    per-object last_backfill cursor closed; the explorer's
    watch_backfill_cursors canaries must flag any schedule that
    exercises it."""
    from ceph_tpu.osd.backend import ECBackend
    from ceph_tpu.osd.pglog import LB_MAX

    orig_read = ECBackend._handle_ec_sub_read
    orig_stale = ECBackend._stale_shards

    def buggy_read(self, m):
        pg = self.pg
        real = pg.info.last_backfill
        # the bug, replica half: reads see "backfilled or not" as a
        # boolean — a mid-copy shard claims cursor-complete authority.
        # The read handler is synchronous (no suspension point), so
        # the flip cannot leak into a concurrent op.
        pg.info.last_backfill = LB_MAX
        try:
            return orig_read(self, m)
        finally:
            pg.info.last_backfill = real

    def buggy_stale(self, oid):
        # the bug, primary half: with only a boolean marker the
        # primary has no per-object view of a backfill target — it
        # either drops the shard for the WHOLE copy or trusts it
        # wholesale.  The buggy replica claims completion, so the
        # boolean-era primary trusts it: skip both the cursor clause
        # AND the backfill-tracking missing set for targets mid-copy
        # (log-recovery peers keep their missing-set gate — that
        # plumbing predates the cursor)
        pg = self.pg
        out = set()
        for i, osd_id in enumerate(pg.acting):
            if osd_id in getattr(pg, "_backfilling", ()):
                continue
            pm = pg.peer_missing.get(osd_id)
            if pm is not None and oid in pm:
                out.add(i)
        return out

    ECBackend._handle_ec_sub_read = buggy_read
    ECBackend._stale_shards = buggy_stale
    try:
        yield
    finally:
        ECBackend._handle_ec_sub_read = orig_read
        ECBackend._stale_shards = orig_stale
