"""Test bootstrap: force an 8-device virtual CPU mesh before jax imports.

All kernel tests run on CPU devices so they are hermetic; the same code paths
run on real TPU when available (bench.py / driver).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def ctx():
    from ceph_tpu.common.context import Context
    return Context("client.test")
