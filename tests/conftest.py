"""Test bootstrap: force an 8-device virtual CPU mesh before jax imports.

All kernel tests run on CPU devices so they are hermetic; the same code paths
run on real TPU when available (bench.py / driver).
"""

import os

# The ambient env routes jax at the real TPU (JAX_PLATFORMS=axon via the
# sitecustomize in /root/.axon_site, which overrides jax_platforms at the
# CONFIG level, beating any env var).  Tests must be hermetic on a virtual
# 8-device CPU mesh, so force both the flag and the config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ctx():
    from ceph_tpu.common.context import Context
    return Context("client.test")
