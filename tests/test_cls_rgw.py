"""cls_rgw bucket index: two-phase prepare/complete, header stats,
pending-marker reconciliation, and the gateway riding it.

Mirrors the reference's src/test/cls_rgw/test_cls_rgw.cc (prepare/
complete/list/check_index/suggest) plus the rgw_rados.cc contract that
the index never exposes half-applied ops to listings.
"""

import asyncio
import errno
import json
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.client.objecter import ObjectOperationError  # noqa: E402


def _j(d) -> bytes:
    return json.dumps(d).encode()


async def _cluster():
    cl = Cluster()
    admin = await cl.start(3)
    await admin.pool_create("p", pg_num=8)
    return cl, admin.open_ioctx("p")


def test_prepare_complete_and_header_stats():
    async def run():
        cl, io = await _cluster()
        await io.exec("idx", "rgw", "bucket_init")
        # re-init of a live index is refused
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("idx", "rgw", "bucket_init")
        assert ei.value.retcode == -errno.EEXIST

        # put: prepare -> (data elsewhere) -> complete
        await io.exec("idx", "rgw", "bucket_prepare_op",
                      _j({"tag": "t1", "op": "put", "key": "a", "ts": 1.0}))
        # in-flight op is invisible to list but visible to check
        out = json.loads(await io.exec("idx", "rgw", "bucket_list"))
        assert out["entries"] == []
        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert [p["tag"] for p in chk["pending"]] == ["t1"]

        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"tag": "t1", "op": "put", "key": "a",
                          "entry": {"size": 100, "etag": "e1",
                                    "mtime": 1.0}}))
        hdr = json.loads(await io.exec("idx", "rgw", "bucket_read_header"))
        assert hdr == {"entries": 1, "bytes": 100}
        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert chk["pending"] == [] and chk["actual"] == hdr

        # overwrite adjusts bytes, not entries; delete removes both
        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"op": "put", "key": "a",
                          "entry": {"size": 40, "etag": "e2",
                                    "mtime": 2.0}}))
        hdr = json.loads(await io.exec("idx", "rgw", "bucket_read_header"))
        assert hdr == {"entries": 1, "bytes": 40}
        out = json.loads(await io.exec("idx", "rgw", "bucket_complete_op",
                                       _j({"op": "del", "key": "a"})))
        assert out["removed"]
        hdr = json.loads(await io.exec("idx", "rgw", "bucket_read_header"))
        assert hdr == {"entries": 0, "bytes": 0}
        # del of a ghost still SUCCEEDS (it must clear the pending
        # marker even when a concurrent delete won) but reports it
        await io.exec("idx", "rgw", "bucket_prepare_op",
                      _j({"tag": "t9", "op": "del", "key": "ghost",
                          "ts": 2.0}))
        out = json.loads(await io.exec("idx", "rgw", "bucket_complete_op",
                                       _j({"tag": "t9", "op": "del",
                                           "key": "ghost"})))
        assert not out["removed"]
        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert chk["pending"] == []      # marker gone despite the miss

        # object keys can't enter the \x01 marker namespace
        with pytest.raises(ObjectOperationError) as ei:
            await io.exec("idx", "rgw", "bucket_complete_op",
                          _j({"op": "put", "key": "\x01pfake",
                              "entry": {"size": 1}}))
        assert ei.value.retcode == -errno.EINVAL

        # cancel: a live gateway whose data write failed clears its
        # own marker and touches nothing else
        await io.exec("idx", "rgw", "bucket_prepare_op",
                      _j({"tag": "tc", "op": "put", "key": "c",
                          "ts": 3.0}))
        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"tag": "tc", "op": "cancel", "key": "c"}))
        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert chk["pending"] == [] and chk["actual"]["entries"] == 0

        # observed-pinned del: an overwrite that raced in since the
        # deleter's read keeps its entry (removed=false)
        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"op": "put", "key": "r",
                          "entry": {"size": 5, "etag": "new",
                                    "mtime": 9.0}}))
        out = json.loads(await io.exec(
            "idx", "rgw", "bucket_complete_op",
            _j({"op": "del", "key": "r",
                "observed": {"etag": "old", "mtime": 1.0}})))
        assert not out["removed"]
        hdr = json.loads(await io.exec("idx", "rgw",
                                       "bucket_read_header"))
        assert hdr == {"entries": 1, "bytes": 5}
        await cl.stop()
    asyncio.run(run())


def test_list_pagination_and_prefix():
    async def run():
        cl, io = await _cluster()
        await io.exec("idx", "rgw", "bucket_init")
        for i in range(6):
            await io.exec("idx", "rgw", "bucket_complete_op",
                          _j({"op": "put", "key": f"d/{i}",
                              "entry": {"size": i, "etag": "", "mtime": 0}}))
        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"op": "put", "key": "other",
                          "entry": {"size": 9, "etag": "", "mtime": 0}}))
        p1 = json.loads(await io.exec(
            "idx", "rgw", "bucket_list",
            _j({"prefix": "d/", "max_keys": 4})))
        assert p1["truncated"] and len(p1["entries"]) == 4
        p2 = json.loads(await io.exec(
            "idx", "rgw", "bucket_list",
            _j({"prefix": "d/", "marker": p1["marker"]})))
        keys = [e["key"] for e in p1["entries"] + p2["entries"]]
        assert keys == [f"d/{i}" for i in range(6)]
        await cl.stop()
    asyncio.run(run())


def test_crash_repair_suggest_and_rebuild():
    """A 'gateway crash' between prepare and complete leaves a marker;
    check --fix semantics (expire tags + rebuild header) and
    dir_suggest removal of a dangling entry reconcile the index."""
    async def run():
        cl, io = await _cluster()
        await io.exec("idx", "rgw", "bucket_init")
        await io.exec("idx", "rgw", "bucket_prepare_op",
                      _j({"tag": "dead", "op": "put", "key": "x",
                          "ts": 1.0}))
        # entry whose data object vanished
        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"op": "put", "key": "dangling",
                          "entry": {"size": 7, "etag": "", "mtime": 0}}))

        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert [p["tag"] for p in chk["pending"]] == ["dead"]

        # a STALE suggestion (observed meta no longer matches) is
        # skipped — a concurrent overwrite must not lose its entry
        await io.exec("idx", "rgw", "dir_suggest_changes",
                      _j({"changes": [{"op": "remove", "key": "dangling",
                                       "observed": {"etag": "other"}}]}))
        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert chk["actual"]["entries"] == 1

        await io.exec("idx", "rgw", "dir_suggest_changes",
                      _j({"changes": [{"op": "remove", "key": "dangling",
                                       "observed": {"etag": ""}}],
                          "expire_tags": ["dead"]}))
        chk = json.loads(await io.exec("idx", "rgw", "bucket_check"))
        assert chk["pending"] == []
        assert chk["actual"] == {"entries": 0, "bytes": 0}

        # rebuild resets a (deliberately corrupted) header to truth
        await io.exec("idx", "rgw", "bucket_complete_op",
                      _j({"op": "put", "key": "y",
                          "entry": {"size": 3, "etag": "", "mtime": 0}}))
        hdr = json.loads(await io.exec(
            "idx", "rgw", "bucket_rebuild_index"))
        assert hdr == {"entries": 1, "bytes": 3}
        await cl.stop()
    asyncio.run(run())


def test_gateway_rides_cls_index():
    """End-to-end: S3 puts/deletes through the gateway maintain the
    cls-held header stats, and a dangling entry self-heals on GET."""
    async def run():
        from ceph_tpu.services.rgw import S3Gateway, _index_oid
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create(".rgw", pg_num=8)
        r = cl.clients[-1] if hasattr(cl, "clients") else admin
        gw = S3Gateway(admin, pool=".rgw", require_auth=False)
        io = gw.io

        st, _, _ = await gw._put_bucket("b")
        assert st == 200
        st, _, _ = await gw._put_object("b", "k1", b"x" * 100, {})
        assert st == 200
        st, _, _ = await gw._put_object("b", "k2", b"y" * 50, {})
        assert st == 200
        hdr = json.loads(await io.exec(_index_oid("b"), "rgw",
                                       "bucket_read_header"))
        assert hdr == {"entries": 2, "bytes": 150}

        st, _, _ = await gw._delete_object("b", "k1")
        assert st == 204
        hdr = json.loads(await io.exec(_index_oid("b"), "rgw",
                                       "bucket_read_header"))
        assert hdr == {"entries": 1, "bytes": 50}
        # no pending markers left behind by the happy path
        chk = json.loads(await io.exec(_index_oid("b"), "rgw",
                                       "bucket_check"))
        assert chk["pending"] == []

        # dangling index entry (data object lost): GET 404s AND heals
        # the index via dir_suggest
        await io.exec(_index_oid("b"), "rgw", "bucket_complete_op",
                      _j({"op": "put", "key": "ghost",
                          "entry": {"size": 5, "etag": "", "mtime": 0,
                                    "soid": "b//ghost.nope"}}))
        st, _, _ = await gw._get_object("b", "ghost", {})
        assert st == 404
        out = json.loads(await io.exec(_index_oid("b"), "rgw",
                                       "bucket_list"))
        assert [e["key"] for e in out["entries"]] == ["k2"]
        await cl.stop()
    asyncio.run(run())
