"""Cache tiering: bloom HitSets, overlay redirection, promote on miss,
agent flush/evict (reference osd/HitSet.h, ReplicatedPG.cc:12008
agent_work, maybe_handle_cache; pool linkage osd_types.h:1230-1234).
"""

import asyncio
import sys

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.osd.hitset import BloomHitSet, HitSetTracker  # noqa: E402


# ------------------------------------------------------------- unit: bloom

def test_bloom_no_false_negatives_and_low_fp():
    hs = BloomHitSet(target_size=512, fpp=0.01)
    ins = [f"obj{i}" for i in range(512)]
    hs.insert_many(ins)
    assert hs.contains_many(ins).all()          # zero false negatives
    others = [f"other{i}" for i in range(2000)]
    fp = hs.contains_many(others).mean()
    assert fp < 0.05, f"false-positive rate {fp:.3f}"


def test_bloom_roundtrip_encoding():
    hs = BloomHitSet(target_size=64)
    hs.insert_many(["a", "b", "c"])
    blob = hs.to_bytes()
    hs2 = BloomHitSet.from_bytes(blob)
    assert hs2.contains("a") and hs2.contains("b")
    assert hs2.nbits == hs.nbits and hs2.k == hs.k


def test_hitset_tracker_window():
    tr = HitSetTracker(count=2, target_size=64)
    tr.insert("hot1")
    tr.rotate()
    tr.insert("hot2")
    assert tr.contains("hot1") and tr.contains("hot2")
    tr.rotate()            # hot1's set falls out of the 2-set window
    tr.rotate()
    assert not tr.contains("hot1")


# ------------------------------------------------------- e2e: live cluster

def _base_pool_heads(cl, pool_id):
    """Objects present in any OSD's store for the given pool."""
    names = set()
    for osd in cl.osds.values():
        for cid in osd.store.list_collections():
            if cid.name.startswith(f"{pool_id}."):
                for o in osd.store.collection_list(cid):
                    if o.is_head() and not o.name.startswith("_"):
                        names.add(o.name)
    return names


async def _setup_tiered(cl, base_type="replicated", n=3):
    admin = await cl.start(n)
    if base_type == "erasure":
        await admin.pool_create("base", pg_num=4, pool_type="erasure",
                                k=2, m=1)
    else:
        await admin.pool_create("base", pg_num=4)
    await admin.pool_create("cache", pg_num=4)
    await admin.mon_command({"prefix": "osd tier add", "pool": "base",
                             "tierpool": "cache"})
    await admin.mon_command({"prefix": "osd tier cache-mode",
                             "pool": "cache", "mode": "writeback"})
    await admin.mon_command({"prefix": "osd tier set-overlay",
                             "pool": "base", "overlaypool": "cache"})
    # wait for the overlay to land in the client's map
    base_id = admin.monc.osdmap.lookup_pool("base")
    while admin.monc.osdmap.pools[base_id].read_tier < 0:
        await asyncio.sleep(0.05)
    return admin


def test_overlay_redirects_writes_to_cache_pool():
    async def run():
        cl = Cluster()
        admin = await _setup_tiered(cl)
        base_id = admin.monc.osdmap.lookup_pool("base")
        cache_id = admin.monc.osdmap.lookup_pool("cache")
        io = admin.open_ioctx("base")
        rng = np.random.default_rng(1)
        payloads = {f"o{i}": rng.integers(0, 256, 4096,
                                          dtype=np.uint8).tobytes()
                    for i in range(8)}
        for k, v in payloads.items():
            await io.write_full(k, v)
        for k, v in payloads.items():
            assert await io.read(k) == v
        # bytes landed in the CACHE pool, not the base pool
        assert _base_pool_heads(cl, cache_id) >= set(payloads)
        assert not (_base_pool_heads(cl, base_id) & set(payloads))
        await cl.stop()
    asyncio.run(run())


def test_agent_flushes_and_evicts_then_promote_serves_reads():
    async def run():
        cl = Cluster()
        admin = await _setup_tiered(cl)
        base_id = admin.monc.osdmap.lookup_pool("base")
        cache_id = admin.monc.osdmap.lookup_pool("cache")
        # tiny budget so the agent must flush+evict almost everything
        await admin.mon_command({"prefix": "osd pool set",
                                 "pool": "cache",
                                 "var": "target_max_objects",
                                 "val": "4"})
        io = admin.open_ioctx("base")
        rng = np.random.default_rng(2)
        payloads = {f"o{i:02d}": rng.integers(0, 256, 8192,
                                              dtype=np.uint8).tobytes()
                    for i in range(16)}
        for k, v in payloads.items():
            await io.write_full(k, v)
        # agent passes run every osd_tier_agent_interval: wait until the
        # base pool holds flushed copies and the cache shrank
        for _ in range(200):
            flushed = _base_pool_heads(cl, base_id) & set(payloads)
            cached = _base_pool_heads(cl, cache_id) & set(payloads)
            if len(flushed) >= 12 and len(cached) <= 8:
                break
            await asyncio.sleep(0.25)
        else:
            raise AssertionError(
                f"agent never converged: flushed={len(flushed)} "
                f"cached={len(cached)}")
        # every object still reads back bit-exact: evicted ones
        # re-promote from the base pool on miss
        for k, v in payloads.items():
            assert await io.read(k) == v, f"{k} corrupted by tiering"
        promotes = 0
        for osd in cl.osds.values():
            for pg in osd.pgs.values():
                if pg.pool_id == cache_id and pg._perf_tier is not None:
                    promotes += pg._perf_tier.dump().get("promotes", 0)
        assert promotes > 0, "no promote ever ran"
        await cl.stop()
    asyncio.run(run())


def test_tiering_over_ec_base_pool():
    """The flagship layout: replicated cache in front of an EC base."""
    async def run():
        cl = Cluster()
        admin = await _setup_tiered(cl, base_type="erasure", n=4)
        base_id = admin.monc.osdmap.lookup_pool("base")
        await admin.mon_command({"prefix": "osd pool set",
                                 "pool": "cache",
                                 "var": "target_max_objects",
                                 "val": "4"})
        io = admin.open_ioctx("base")
        rng = np.random.default_rng(3)
        payloads = {f"e{i:02d}": rng.integers(0, 256, 16384,
                                              dtype=np.uint8).tobytes()
                    for i in range(12)}
        for k, v in payloads.items():
            await io.write_full(k, v)
        for _ in range(200):
            if len(_base_pool_heads(cl, base_id)
                   & set(payloads)) >= 8:
                break
            await asyncio.sleep(0.25)
        else:
            raise AssertionError("no flushes to the EC base pool")
        for k, v in payloads.items():
            assert await io.read(k) == v
        await cl.stop()
    asyncio.run(run())


def test_tier_commands_validate():
    async def run():
        cl = Cluster()
        admin = await cl.start(4)
        await admin.pool_create("base", pg_num=4)
        await admin.pool_create("ecache", pg_num=4,
                                pool_type="erasure", k=2, m=1)
        from ceph_tpu.mon.client import CommandError
        # EC pools can't be cache tiers
        with pytest.raises(CommandError):
            await admin.mon_command({"prefix": "osd tier add",
                                     "pool": "base",
                                     "tierpool": "ecache"})
        await admin.pool_create("cache", pg_num=4)
        await admin.mon_command({"prefix": "osd tier add",
                                 "pool": "base", "tierpool": "cache"})
        # cache-mode on a non-tier pool refuses
        with pytest.raises(CommandError):
            await admin.mon_command({"prefix": "osd tier cache-mode",
                                     "pool": "base",
                                     "mode": "writeback"})
        await admin.mon_command({"prefix": "osd tier set-overlay",
                                 "pool": "base", "overlaypool": "cache"})
        # removing a tier under an overlay refuses
        with pytest.raises(CommandError):
            await admin.mon_command({"prefix": "osd tier remove",
                                     "pool": "base",
                                     "tierpool": "cache"})
        await admin.mon_command({"prefix": "osd tier remove-overlay",
                                 "pool": "base"})
        await admin.mon_command({"prefix": "osd tier remove",
                                 "pool": "base", "tierpool": "cache"})
        base_id = admin.monc.osdmap.lookup_pool("base")
        while admin.monc.osdmap.pools[base_id].tiers:
            await asyncio.sleep(0.05)
        await cl.stop()
    asyncio.run(run())


def test_hitset_window_survives_primary_failover():
    """Persisted hit sets (_hitset_<n> replicated objects): a new
    primary inherits the recency window instead of starting cold
    (ReplicatedPG::hit_set_persist/hit_set_setup)."""
    async def run():
        cl = Cluster()
        admin = await _setup_tiered(cl, n=4)
        cache_id = admin.monc.osdmap.lookup_pool("cache")
        io = admin.open_ioctx("base")
        # tiny period so rotation (and persistence) actually happens
        for osd in cl.osds.values():
            for pg in osd.pgs.values():
                if pg.pool_id == cache_id:
                    pg.pool.hit_set_period = 0.2
        rng = np.random.default_rng(4)
        data = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        for i in range(6):
            await io.write_full(f"h{i}", data)
            await asyncio.sleep(0.08)
        for i in range(6):
            assert await io.read(f"h{i}") == data
        await asyncio.sleep(0.5)
        await io.write_full("kick", data)   # forces a rotate+persist
        persisted = 0
        for osd in cl.osds.values():
            for cid in osd.store.list_collections():
                if cid.name.startswith(f"{cache_id}."):
                    persisted += sum(
                        1 for o in osd.store.collection_list(cid)
                        if o.name.startswith("_hitset_"))
        assert persisted > 0, "no hit set was ever persisted"
        # fresh PG object on another OSD loads the window
        src = next(pg for osd in cl.osds.values()
                   for pg in osd.pgs.values()
                   if pg.pool_id == cache_id and pg.is_primary()
                   and pg._hitset_seq > 0)
        await src._load_hitsets()
        assert src.hitset.archive, "persisted window not loaded"
        await cl.stop()
    asyncio.run(run())
